"""Benchmark harness: prints ONE JSON line for the driver.

Primary metric (BASELINE.json north star): mnist_distributed steps/sec/chip
against the 100 steps/sec 4xV100 proxy recorded in BASELINE.md. The same
line carries the flagship-transformer numbers VERDICT r1 asked for in
``extras``: train-step tokens/sec/chip with computed MFU, and a
flash-attention (Pallas) vs blockwise-XLA microbench at seq 2k/8k.

Steady-state measurement everywhere: donated state, on-device loop, host
sync only at the timer edges. The sync is a HOST READBACK (float()), not
block_until_ready: on the tunneled "axon" platform block_until_ready is not
a reliable execution fence (measured 40k "TFLOP/s" with it; 95 real
TFLOP/s with a readback), so every timer edge forces a device->host copy.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np

BASELINE_STEPS_PER_SEC_PER_CHIP = 100.0  # see BASELINE.md proxy table
BATCH = 512
MEASURE = 200

# Jit sanitizer ON for every bench run (opt-out with =0): each workload
# records the retraces its dispatches incurred (`retraces_total` in its
# extras), and those feed the BASELINE.json gate — a steady-state step
# that starts recompiling fails `bench --check` even when its wall time
# hides it. setdefault BEFORE any tony_tpu import, matching the tier-1
# conftest arming.
os.environ.setdefault("TONY_JIT_SANITIZER", "1")

# Peak dense bf16 throughput per chip, for MFU — the SAME table the
# live step anatomy uses (observability/stepstats.py), so a bench MFU
# and a production job's tony_mfu gauge are one definition, one table.
from tony_tpu.observability.stepstats import peak_flops_per_chip  # noqa: E402


def _peak_flops() -> float:
    return peak_flops_per_chip(jax.devices()[0])


def best_of_windows(fn, windows: int = 3) -> float:
    """One shared measurement protocol: run ``fn`` once to warm
    (compile), then best-of-``windows`` wall seconds. ``fn`` must END
    with a host readback — the fence contract from the module docstring
    (block_until_ready is not a fence on the tunneled platform)."""
    fn()
    best = float("inf")
    for _ in range(windows):
        t0 = time.perf_counter()
        fn()
        best = min(best, time.perf_counter() - t0)
    return best


def bench_mnist() -> float:
    """Steps/sec/chip with the training loop ON DEVICE: steps_per_call
    batches one lax.scan of optimizer steps per dispatch, so the number
    measures chip throughput, not host/tunnel round-trips (per-call
    dispatch swings 80-700 steps/s with tunnel health; the fused loop is
    stable). Distinct per-step batches — this is a real training loop,
    not one batch replayed inside the scan."""
    from tony_tpu.models import MnistConfig
    from tony_tpu.models.train import make_classifier_step
    from tony_tpu.parallel.mesh import MeshSpec, build_mesh

    n_chips = len(jax.devices())
    mesh = build_mesh(MeshSpec.auto(n_chips), devices=jax.devices())
    cfg = MnistConfig(arch="cnn", dtype="bfloat16")
    per_call = 50
    init_fn, step_fn = make_classifier_step(
        cfg, mesh, learning_rate=1e-3, steps_per_call=per_call
    )

    rng = np.random.default_rng(0)
    images = jnp.asarray(
        rng.normal(size=(per_call, BATCH, 28, 28, 1)), jnp.float32
    )
    labels = jnp.asarray(
        rng.integers(0, 10, (per_call, BATCH)), jnp.int32
    )

    with jax.sharding.set_mesh(mesh):
        state = init_fn(jax.random.key(0))
        state, metrics = step_fn(state, images, labels)  # compile + warm
        float(metrics["loss"])  # host readback = real fence

        calls = max(1, MEASURE // per_call)
        best_dt = float("inf")
        # Best-of-5 (not 3): this is the headline vs_baseline number and
        # the tunnel's health swings individual windows by 20-30%; extra
        # windows cost ~a second each and tighten the recorded best.
        for _ in range(5):
            t0 = time.perf_counter()
            for _ in range(calls):
                state, metrics = step_fn(state, images, labels)
            float(metrics["loss"])  # tony: noqa[TONY-X002] — intended per-window timing fence
            best_dt = min(best_dt, time.perf_counter() - t0)
    return calls * per_call / best_dt / n_chips


def _bench_lm_train(cfg, batch: int, seq: int, measure: int,
                    optimizer=None, warmup: int = 3):
    """Shared LM train-step measurement: warmup + fence, best-of-2
    windows (the tunneled chip sees transient contention that can halve
    a single window), analytic model flops (6·N·T PaLM counting + the
    causal attention term; remat recompute NOT counted — MFU is model
    flops, not hardware flops)."""
    from tony_tpu.models import make_train_step
    from tony_tpu.parallel.mesh import MeshSpec, build_mesh

    mesh = build_mesh(MeshSpec(), devices=jax.devices()[:1])
    init_fn, step_fn = make_train_step(cfg, mesh, optimizer=optimizer)
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, cfg.vocab_size, (batch, seq)),
        jnp.int32,
    )
    with jax.sharding.set_mesh(mesh):
        state = init_fn(jax.random.key(0))
        metrics = None
        for _ in range(warmup):
            state, metrics = step_fn(state, tokens)
        float(metrics["loss"])  # host readback = real fence

        dt = float("inf")
        for _ in range(2):
            t0 = time.perf_counter()
            for _ in range(measure):
                state, metrics = step_fn(state, tokens)
            float(metrics["loss"])  # tony: noqa[TONY-X002] — intended per-window timing fence
            dt = min(dt, time.perf_counter() - t0)
    n_params = sum(x.size for x in jax.tree.leaves(state.params))
    flops_per_step = (
        6.0 * n_params * batch * seq
        + 6.0 * cfg.n_layers * batch * seq * seq * cfg.n_heads * cfg.head_dim
    )
    out = {
        "tokens_per_sec_per_chip": round(batch * seq * measure / dt),
        "params_m": round(n_params / 1e6, 1),
        "batch": batch,
        "seq": seq,
        "step_ms": round(dt / measure * 1000, 2),
    }
    peak = _peak_flops()
    if peak:  # unknown accelerator generation: no MFU, not a wrong one
        out["mfu"] = round(flops_per_step * measure / dt / peak, 4)
    return out


def bench_transformer(batch: int = 8, seq: int = 2048, measure: int = 20,
                      n_heads: int = 16, head_dim: int = 64):
    """Flagship LM full train step (fwd+loss+grad+adamw) on one chip:
    tokens/sec/chip and analytic MFU. Remat only when the activations
    need it: flash attention keeps activations O(T·block), so at 200M
    both bench shapes fit HBM without remat and its recompute is pure
    MFU loss (measured: 47.0% -> 51.5% at 2k/b8, 36.2% -> 41.6% at
    8k/b2); more total tokens than that force it back on (the fit is a
    batch*seq property: b=16 @ 2k already blows memory without it).

    ``head_dim``: 64 is the r1-r4 comparability shape; 128 (same d_model,
    same params) is the TPU-FIRST flagship shape — d=64 fills only half
    the MXU's 128-deep contraction/output width, structurally capping
    every attention matmul at 50% of peak, and the r5 device-trace
    analysis showed the flash kernels already run at ~72% of that capped
    ceiling. head_dim 128 is what one designs for this hardware (the 1B
    row always did): measured 42.1% -> 59.2% MFU at 8k/b2."""
    from tony_tpu.models import TransformerConfig

    cfg = TransformerConfig(
        vocab_size=32_000, d_model=1024, n_layers=8, n_heads=n_heads,
        head_dim=head_dim, d_ff=4096, max_seq=seq, dtype="bfloat16",
        remat=batch * seq > 16384,
        remat_policy="dots", layer_scan_unroll=8,
    )
    return _bench_lm_train(cfg, batch, seq, measure)


def bench_transformer_1b(batch: int = 4, seq: int = 2048, measure: int = 8):
    """1.0B-parameter LM full train step on ONE v5e chip — the
    realistic-size MFU row (MFU should RISE with model size; a 200M-only
    story undersells the stack, VERDICT r3 weak #4). Fits 16 GB HBM with
    adafactor (factored second moments — the standard memory-lean
    optimizer at this scale; adamw's 12 bytes/param of fp32 state does
    not fit), NO remat (flash keeps activations O(T·block); recompute
    was pure MFU loss: dots 0.558 -> none 0.643), head_dim 128 (fills
    the 128-deep MXU contraction), and the fully-unrolled layer loop.
    Measured sweep (BASELINE.md): b=1 0.362 -> b=4 no-remat 0.643."""
    import optax

    from tony_tpu.models import TransformerConfig

    cfg = TransformerConfig(
        vocab_size=32_000, d_model=2048, n_layers=13, n_heads=16,
        head_dim=128, d_ff=8192, max_seq=seq, dtype="bfloat16", remat=False,
        layer_scan_unroll=13,
    )
    out = _bench_lm_train(
        cfg, batch, seq, measure, optimizer=optax.adafactor(1e-3), warmup=2
    )
    out["optimizer"] = "adafactor"
    return out


def bench_decode(batch: int = 8, prompt_len: int = 128, new_tokens: int = 128,
                 n_kv_heads: int = 4, windows: int = 3):
    """KV-cache greedy decode on the flagship LM with GQA (the decode
    bandwidth lever — the cache holds n_kv_heads of the 16 query heads),
    through a persistent DecodeSession — weights fuse once, each call
    dispatches only the compiled loop (the serving shape; per-call
    re-fusion cost BENCH_r03 113 ms of a 186 ms wall). Wall tok/s is
    best-of-N calls (tunnel variance); see BASELINE.md."""
    from tony_tpu.models import DecodeSession, TransformerConfig, init_params

    cfg = TransformerConfig(
        vocab_size=32_000, d_model=1024, n_layers=8, n_heads=16, head_dim=64,
        d_ff=4096, max_seq=2048, dtype="bfloat16", remat=False,
        n_kv_heads=n_kv_heads,
    )
    params = jax.jit(lambda k: init_params(k, cfg))(jax.random.key(0))  # tony: noqa[TONY-X001] — one-shot init compile, not a step path
    prompt = jnp.asarray(
        np.random.default_rng(0).integers(0, cfg.vocab_size,
                                          (batch, prompt_len)),
        jnp.int32,
    )
    session = DecodeSession(params, cfg)

    def timed(n: int) -> float:
        return best_of_windows(
            lambda: float(jnp.sum(session.generate(prompt, max_new_tokens=n))),
            windows,
        )

    # Two horizons; the difference isolates the marginal decode step from
    # the prefill + dispatch cost that a single-horizon wall divide would
    # smear into "step_ms". The horizons are LONG (2x and 4x new_tokens,
    # i.e. steps averaged over a prompt+512 context) because the tunnel
    # adds +/-15 ms of wall noise per call: a 96-step difference gave
    # step_ms anywhere in 0.2-1.0 on the same chip (BENCH_r03's 0.567
    # came from such short horizons); 256 steps bound the error to
    # ~0.06 ms.
    short_n, long_n = new_tokens * 2, new_tokens * 4
    dt_wall = timed(new_tokens)
    dt_short = timed(short_n)
    dt_long = timed(long_n)
    step_s = max(dt_long - dt_short, 1e-9) / (long_n - short_n)
    return {
        "tokens_per_sec_per_chip": round(batch / step_s),
        "step_ms": round(step_s * 1000, 3),
        "generate_wall_tokens_per_sec": round(batch * new_tokens / dt_wall),
        "prefill_plus_overhead_ms": round(
            (dt_wall - new_tokens * step_s) * 1000, 2
        ),
        "batch": batch,
        "prompt_len": prompt_len,
        "new_tokens": new_tokens,
        "n_kv_heads": n_kv_heads,
    }


def bench_moe(batch: int = 4, seq: int = 2048, measure: int = 8):
    """MoE trunk train step on one chip (4 experts, top-2, with the Switch
    balance + router z losses active): tokens/sec/chip."""
    from tony_tpu.models import TransformerConfig, make_train_step
    from tony_tpu.parallel.mesh import MeshSpec, build_mesh

    cfg = TransformerConfig(
        vocab_size=32_000, d_model=1024, n_layers=8, n_heads=16, head_dim=64,
        d_ff=4096, max_seq=seq, dtype="bfloat16", remat=True,
        remat_policy="dots", n_experts=4, expert_top_k=2,
        layer_scan_unroll=8,
    )
    mesh = build_mesh(MeshSpec(), devices=jax.devices()[:1])
    init_fn, step_fn = make_train_step(cfg, mesh)
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, cfg.vocab_size, (batch, seq)),
        jnp.int32,
    )
    with jax.sharding.set_mesh(mesh):
        state = init_fn(jax.random.key(0))
        for _ in range(2):
            state, metrics = step_fn(state, tokens)
        float(metrics["loss"])
        dt = float("inf")
        for _ in range(2):
            t0 = time.perf_counter()
            for _ in range(measure):
                state, metrics = step_fn(state, tokens)
            float(metrics["loss"])  # tony: noqa[TONY-X002] — intended per-window timing fence
            dt = min(dt, time.perf_counter() - t0)
    n_params = sum(x.size for x in jax.tree.leaves(state.params))
    return {
        "tokens_per_sec_per_chip": round(batch * seq * measure / dt),
        "params_m": round(n_params / 1e6, 1),
        "batch": batch,
        "seq": seq,
        "moe_entropy": round(float(metrics["moe_entropy"]), 3),
        "moe_drop_rate": round(float(metrics["moe_drop_rate"]), 4),
    }


def bench_moe_decode(batch: int = 8, windows: int = 3):
    """MoE decode at E=4 vs E=16: routed (top-k gather) step times plus
    the dense-mixture comparison at E=16. The measured verdict on v5e is
    that DENSE wins (XLA streams stacked expert weights near roofline;
    per-token gathers do not) — these numbers are the evidence for why
    moe_decode_mode=auto resolves to dense. Long differencing horizons
    (256 vs 896 steps) because the tunnel adds +/-15 ms of wall noise
    per call."""
    from tony_tpu.models import DecodeSession, TransformerConfig, init_params

    out = {"batch": batch, "top_k": 2}
    steps = {}
    for n_experts, mode in ((4, "routed"), (16, "routed"), (16, "dense")):
        cfg = TransformerConfig(
            vocab_size=32_000, d_model=512, n_layers=4, n_heads=8,
            head_dim=64, d_ff=1024, max_seq=1024, dtype="bfloat16",
            remat=False, n_experts=n_experts, expert_top_k=2,
            moe_decode_mode=mode,
        )
        params = jax.jit(lambda k, c=cfg: init_params(k, c))(  # tony: noqa[TONY-X001] — one-shot init compile, not a step path
            jax.random.key(0)
        )
        prompt = jnp.asarray(
            np.random.default_rng(0).integers(0, cfg.vocab_size, (batch, 16)),
            jnp.int32,
        )
        session = DecodeSession(params, cfg)

        def timed(n, s=session, p=prompt):
            return best_of_windows(
                lambda: float(jnp.sum(s.generate(p, max_new_tokens=n))),
                windows,
            )

        step_s = max(timed(896) - timed(256), 1e-9) / 640
        steps[(n_experts, mode)] = step_s
        key = f"step_ms_e{n_experts}" + ("_dense" if mode == "dense" else "")
        out[key] = round(step_s * 1000, 3)
    out["e16_over_e4_step_ratio"] = round(
        steps[(16, "routed")] / steps[(4, "routed")], 2
    )
    out["dense_over_routed_e16"] = round(
        steps[(16, "dense")] / steps[(16, "routed")], 2
    )
    return out


def bench_serving(
    slots: int = 16,
    n_requests: int = 64,
    prefill_chunk: int = 32,
    # 32 on the TPU defaults: the tunneled platform adds ~15 ms of wall
    # noise per dispatch, so a window must carry enough ~0.65 ms decode
    # steps to amortize it; the CPU micro uses 8 (its step is ~5 ms).
    decode_window: int = 32,
    prefill_batch: int = 4,
    d_model: int = 1024,
    n_layers: int = 8,
    n_heads: int = 16,
    head_dim: int = 64,
    n_kv_heads: int = 4,
    vocab: int = 32_000,
    max_seq: int = 2048,
    prompt_rng: tuple = (16, 96),
    out_mean: float = 48.0,
    out_clip: tuple = (8, 192),
    bucket: int = 32,
    arrival_mean_ms: float = 3.0,
    seed: int = 0,
):
    """Continuous-batching serving wall throughput vs the single-shot
    ``generate`` server on the SAME mixed workload and hardware — the
    number that closes the 12.4k-marginal vs 5.5k-wall gap ROADMAP calls
    out. Workload: ``n_requests`` with uniform prompt lengths and
    exponential (heavy-tail-ish, the realistic shape) output budgets,
    Poisson-ish arrivals.

    The single-shot comparator is the BEST static server one can build
    from ``DecodeSession.generate``: requests batched ``slots`` at a
    time in arrival order, prompts padded to one width (one prefill
    executable), horizons bucketed to multiples of ``bucket`` (how real
    static servers bound their compile count), weights pre-fused, every
    signature pre-warmed so neither side's wall contains compile time.
    Its structural tax is padding: every row pays its group's bucketed
    MAX output budget while the engine retires each stream at its own
    budget and refills the slot — that, not kernel speed, is the gap
    being measured. Both sides count the same useful tokens
    (sum of per-request budgets) over their wall.

    Two comparators come back: ``single_shot_*`` (the strict same-slots
    static server above) and ``generate_wall_*`` — the decode_gqa-shaped
    figure (batch 8, uniform prompt/new lengths) that BASELINE.json's
    5,512 tok/s records; ``generate_wall_speedup`` is the acceptance
    ratio the serving issue names (≥ 2×)."""
    from tony_tpu.models import DecodeSession, TransformerConfig, init_params
    from tony_tpu.serving import ServingEngine

    cfg = TransformerConfig(
        vocab_size=vocab, d_model=d_model, n_layers=n_layers,
        n_heads=n_heads, head_dim=head_dim, d_ff=4 * d_model,
        max_seq=max_seq, dtype="bfloat16", remat=False,
        n_kv_heads=n_kv_heads,
    )
    params = jax.jit(lambda k: init_params(k, cfg))(jax.random.key(0))  # tony: noqa[TONY-X001] — one-shot init compile, not a step path
    rng = np.random.default_rng(seed)
    prompts = [
        rng.integers(0, vocab, rng.integers(prompt_rng[0],
                                            prompt_rng[1] + 1)).astype(
            np.int32
        )
        for _ in range(n_requests)
    ]
    outs = np.clip(
        np.round(rng.exponential(out_mean, n_requests)).astype(int),
        out_clip[0], out_clip[1],
    )
    arrivals_s = np.cumsum(
        rng.exponential(arrival_mean_ms / 1000.0, n_requests)
    )
    useful = int(outs.sum())

    # -- the decode_gqa-shaped generate_wall figure -----------------------
    # One batch-8 uniform-length generate call on the same weights — the
    # shape behind BASELINE.json's decode_gqa.generate_wall_tokens_per_sec
    # (prompt 128 / new 128 there; scaled by max_seq for micro configs).
    session = DecodeSession(params, cfg)
    ref_len = min(128, max_seq // 4)
    ref_prompt = jnp.asarray(
        rng.integers(0, vocab, (8, ref_len)), jnp.int32
    )
    gw = best_of_windows(lambda: float(jnp.sum(
        session.generate(ref_prompt, max_new_tokens=ref_len)
    )))
    generate_wall_rate = 8 * ref_len / gw

    # -- single-shot comparator -------------------------------------------
    width = max(p.size for p in prompts)

    def batch_of(group):
        rows = [np.concatenate([np.zeros(width - p.size, np.int32), p])
                for p in group]
        while len(rows) < slots:  # fixed batch: a static server pads
            rows.append(rows[0])
        return jnp.asarray(np.stack(rows), jnp.int32)

    groups = [
        (batch_of(prompts[i:i + slots]),
         int(-(-int(outs[i:i + slots].max()) // bucket) * bucket))
        for i in range(0, n_requests, slots)
    ]
    for batch, horizon in groups:  # warm every signature out of the wall
        float(jnp.sum(session.generate(batch, max_new_tokens=horizon)))
    t0 = time.perf_counter()
    for batch, horizon in groups:
        float(jnp.sum(session.generate(batch, max_new_tokens=horizon)))
    single_wall = time.perf_counter() - t0
    single_rate = useful / single_wall

    # -- continuous batching ----------------------------------------------
    # Right-size the slot KV rows to the workload's admission bound
    # (prompt + budget + one chunk of slack) instead of cfg.max_seq —
    # every decode step's attention reads scale with the row length.
    max_len = min(max_seq, prompt_rng[1] + out_clip[1] + prefill_chunk)
    engine = ServingEngine(
        session.params, cfg, slots=slots, max_len=max_len,
        prefill_chunk=prefill_chunk, decode_window=decode_window,
        prefill_batch=prefill_batch, seed=seed,
    )
    # Warm both engine executables before the clock starts.
    engine.submit(prompts[0], max_new_tokens=2)
    while engine.stats()["retired"] < 1:
        engine.step()
    engine.inter_token_ms_samples.clear()
    engine.ttft_ms_samples.clear()
    # Drive the loop on THIS thread (submitting arrivals as their
    # Poisson clock comes due) — the threaded serve_forever path
    # measured ~15% slower here from GIL contention with the submitting
    # thread, and a bench should report the engine, not the bench.
    reqs = []
    due = iter(zip(prompts, outs, arrivals_s))
    nxt = next(due)
    sustained_tokens = 0
    sustained_wall = 0.0
    t0 = time.perf_counter()
    while nxt is not None or not all(r.done() for r in reqs):
        while nxt is not None and time.perf_counter() - t0 >= nxt[2]:
            reqs.append(engine.submit(nxt[0], max_new_tokens=int(nxt[1])))
            nxt = next(due, None)
        # Saturated-window accounting: iterations that START with a
        # non-empty queue are the steady state a deployed engine lives
        # in; the ramp/drain boundary of a FINITE workload (arrivals
        # stop, slots empty out) is a bench artifact, so it is reported
        # separately (wall_tokens_per_sec) rather than averaged in.
        saturated = engine.stats()["queue_depth"] > 0
        tok_before = engine.tokens_generated
        it_t0 = time.perf_counter()
        did = engine.step()
        if saturated:
            sustained_wall += time.perf_counter() - it_t0
            sustained_tokens += engine.tokens_generated - tok_before
        if not did and nxt is not None:
            time.sleep(0.0005)
    serving_wall = time.perf_counter() - t0
    engine.close()
    serving_rate = useful / serving_wall
    sustained_rate = (sustained_tokens / sustained_wall
                      if sustained_wall > 0 else serving_rate)
    inter = np.asarray(engine.inter_token_ms_samples, float)
    ttft = np.asarray(engine.ttft_ms_samples, float)
    return {
        "wall_tokens_per_sec": round(serving_rate),
        "sustained_tokens_per_sec": round(sustained_rate),
        "generate_wall_tokens_per_sec": round(generate_wall_rate),
        "generate_wall_speedup": round(
            sustained_rate / generate_wall_rate, 2
        ),
        "single_shot_wall_tokens_per_sec": round(single_rate),
        "single_shot_speedup": round(sustained_rate / single_rate, 2),
        "inter_token_p50_ms": round(float(np.percentile(inter, 50)), 2),
        "inter_token_p95_ms": round(float(np.percentile(inter, 95)), 2),
        "ttft_p50_ms": round(float(np.percentile(ttft, 50)), 2),
        "ttft_p95_ms": round(float(np.percentile(ttft, 95)), 2),
        "generated_tokens": useful,
        "slots": slots,
        "n_requests": n_requests,
        "prefill_chunk": prefill_chunk,
        "decode_window": decode_window,
        "out_mean": float(out_mean),
        "d_model": d_model,
    }


# CPU smoke variant: same engine, same comparator, a model small enough
# that the whole section stays under about a minute — seeds the portable
# (ratio) serving gate for non-TPU runs.
SERVING_CPU_MICRO = dict(
    slots=16, n_requests=128, prefill_chunk=32, decode_window=8,
    prefill_batch=4, d_model=128, n_layers=2, n_heads=4, head_dim=32,
    n_kv_heads=2, vocab=1024, max_seq=256, prompt_rng=(8, 48),
    out_mean=32.0, out_clip=(8, 96), bucket=32, arrival_mean_ms=2.0,
)


def bench_serving_fleet(
    max_replicas: int = 3,
    slots: int = 4,
    prefill_chunk: int = 16,
    decode_window: int = 4,
    d_model: int = 128,
    n_layers: int = 2,
    n_heads: int = 4,
    head_dim: int = 32,
    n_kv_heads: int = 2,
    vocab: int = 512,
    max_seq: int = 128,
    prompt_rng: tuple = (8, 24),
    out_tokens: int = 16,
    # Stepped + bursty arrivals: (n_requests, arrival_mean_ms) phases.
    # Phase 1 cruises on one replica; phase 2 steps the rate up ~12x
    # (the autoscale trigger); phase 3 falls back to cruise.
    phases: tuple = ((12, 25.0), (56, 2.0), (12, 25.0)),
    tick_ms: float = 25.0,
    scale_up_queue_depth: int = 2,
    hysteresis_ticks: int = 2,
    cooldown_ms: int = 400,
    seed: int = 0,
):
    """Autoscaled serving fleet under a stepped/bursty arrival process:
    ``max_replicas`` engine replicas (each a real ``ServingEngine``
    behind a real ``ServingServer``) fronted by the ``FleetRouter``,
    with the ``Autoscaler`` ticking on the router's aggregated signals
    and actuating 1→N as the burst lands.

    What the numbers mean:

    * ``fleet_sustained_tokens_per_sec`` — useful tokens retired during
      the burst window over that window's wall: the figure that should
      SCALE with replicas (a 1-replica fleet saturates at roughly the
      engine's micro rate / slots ratio).
    * ``ttft_p95_ms`` — engine-reported submit→first-token p95 across
      every request, queue wait included (what a client feels during
      the burst before capacity arrives).
    * ``autoscale_reaction_ms`` — burst onset to the first scale-up
      ACTUATION (replica in rotation). Replicas are pre-warmed, so
      this isolates the control loop (poll → hysteresis → cooldown →
      add), not XLA compile or checkpoint restore; the fleet e2e test
      covers the cold path.

    The actuation here swaps a pre-built warm replica into the router —
    the daemon's launch path (WAL, slice placement, addr discovery) is
    benched by ``bench_scheduler`` and tested in tests/test_fleet.py;
    this bench isolates serving-plane behavior under load."""
    from tony_tpu.fleet.autoscale import AutoscalePolicy, Autoscaler
    from tony_tpu.fleet.router import FleetRouter
    from tony_tpu.models import DecodeSession, TransformerConfig, init_params
    from tony_tpu.observability.metrics import MetricsRegistry
    from tony_tpu.serving import ServingEngine
    from tony_tpu.serving.http import ServingServer

    cfg = TransformerConfig(
        vocab_size=vocab, d_model=d_model, n_layers=n_layers,
        n_heads=n_heads, head_dim=head_dim, d_ff=4 * d_model,
        max_seq=max_seq, dtype="float32", remat=False,
        n_kv_heads=n_kv_heads,
    )
    params = jax.jit(lambda k: init_params(k, cfg))(jax.random.key(0))  # tony: noqa[TONY-X001] — one-shot init compile, not a step path
    session = DecodeSession(params, cfg)
    rng = np.random.default_rng(seed)

    # Pre-build and WARM every replica the autoscaler may bring into
    # rotation (compile out of the wall; reaction measures control).
    replicas = []
    for i in range(max_replicas):
        eng = ServingEngine(
            session.params, cfg, slots=slots,
            prefill_chunk=prefill_chunk, decode_window=decode_window,
            registry=MetricsRegistry(), seed=seed,
        ).start()
        warm = eng.submit(
            rng.integers(0, vocab, prompt_rng[1]).astype(np.int32), 2
        )
        warm.result(timeout=300)
        eng.ttft_ms_samples.clear()
        eng.inter_token_ms_samples.clear()
        srv = ServingServer(eng, port=0, host="127.0.0.1")
        port = srv.start()
        replicas.append((eng, srv, f"127.0.0.1:{port}"))

    router = FleetRouter(health_interval_s=3600.0, retries=2,
                         wake_timeout_s=5.0)
    scaler = Autoscaler(AutoscalePolicy(
        min_replicas=1, max_replicas=max_replicas,
        scale_up_queue_depth=scale_up_queue_depth,
        hysteresis_ticks=hysteresis_ticks, cooldown_ms=cooldown_ms,
        scale_down_idle_ms=10 ** 9,  # bounded wall: no down-phase here
    ))
    router.add_replica("r0", replicas[0][2])
    desired = [1]
    scale_events: list = []

    # Arrival schedule (relative seconds) + the burst-onset timestamp.
    arrivals: list = []
    t_acc = 0.0
    for n_req, mean_ms in phases:
        for _ in range(n_req):
            t_acc += float(rng.exponential(mean_ms / 1000.0))
            arrivals.append(t_acc)
    burst_rel = arrivals[phases[0][0]]

    stop = threading.Event()
    lock = threading.Lock()
    results: list = []
    t0 = time.perf_counter()

    def control_loop():
        while not stop.wait(tick_ms / 1000.0):
            router.poll_once()
            decision = scaler.tick(router.signals(), desired[0])
            if decision is None or decision.target == desired[0]:
                continue
            now_rel = time.perf_counter() - t0
            for i in range(desired[0], decision.target):
                router.add_replica(f"r{i}", replicas[i][2])
            for i in range(decision.target, desired[0]):
                router.drain_replica(f"r{i}")
            desired[0] = decision.target
            scale_events.append(
                (now_rel, decision.target, decision.reason)
            )

    def client(prompt, rid):
        code, raw, _ = router.route_generate({
            "prompt": [int(x) for x in prompt],
            "max_new_tokens": out_tokens, "request_id": rid,
        })
        done_rel = time.perf_counter() - t0
        out = json.loads(raw) if code == 200 else {}
        with lock:
            results.append({
                "code": code, "done_rel": done_rel,
                "tokens": int(out.get("length", 0)),
                "ttft_ms": float(out.get("ttft_ms", 0.0)),
            })

    ctrl = threading.Thread(target=control_loop, daemon=True)
    ctrl.start()
    workers = []
    for idx, due in enumerate(arrivals):
        delay = due - (time.perf_counter() - t0)
        if delay > 0:
            time.sleep(delay)
        prompt = rng.integers(
            0, vocab, int(rng.integers(prompt_rng[0], prompt_rng[1] + 1))
        )
        w = threading.Thread(target=client,
                             args=(prompt, f"fleet-{idx}"), daemon=True)
        w.start()
        workers.append(w)
    for w in workers:
        w.join(timeout=120)
    stop.set()
    ctrl.join(timeout=10)
    wall = time.perf_counter() - t0

    router.stop()
    for eng, srv, _ in replicas:
        srv.stop()
        eng.close()

    ok = [r for r in results if r["code"] == 200]
    total_tokens = sum(r["tokens"] for r in ok)
    burst_n = phases[0][0] + phases[1][0]
    burst_done = [r["done_rel"] for r in ok
                  if burst_rel <= r["done_rel"]]
    burst_done = sorted(burst_done)[:max(1, burst_n - phases[0][0])]
    burst_wall = (burst_done[-1] - burst_rel) if burst_done else wall
    burst_tokens = out_tokens * len(burst_done)
    ttft = np.asarray([r["ttft_ms"] for r in ok], float)
    up_events = [e for e in scale_events if e[1] > 1]
    # Clamped at 0: a scale-up actuated DURING burst ramp-up (cruise
    # load already tripping hysteresis as the burst lands) reacted
    # early, not slowly. The gated failures are "slow" and "never"
    # (the 9e9 sentinel fails the lower-is-better gate loudly).
    reaction_ms = (
        round(max(0.0, (up_events[0][0] - burst_rel) * 1000.0), 1)
        if up_events else 9e9
    )
    return {
        "fleet_wall_tokens_per_sec": round(total_tokens / wall),
        "fleet_sustained_tokens_per_sec": round(
            burst_tokens / max(burst_wall, 1e-6)
        ),
        "ttft_p50_ms": round(float(np.percentile(ttft, 50)), 2),
        "ttft_p95_ms": round(float(np.percentile(ttft, 95)), 2),
        "autoscale_reaction_ms": reaction_ms,
        "replicas_peak": max([e[1] for e in scale_events],
                             default=desired[0]),
        "scale_ups": len(up_events),
        "requests_ok": len(ok),
        "requests_failed": len(results) - len(ok),
        "generated_tokens": total_tokens,
        "slots": slots,
        "max_replicas": max_replicas,
        "d_model": d_model,
    }


def bench_resnet50(batch: int = 32, size: int = 224, measure: int = 20):
    """ResNet-50 full train step (fwd+loss+grad+adam), images/sec/chip —
    the BASELINE config-5 workload."""
    from tony_tpu.models import (
        ResNetConfig,
        make_image_classifier_step,
        resnet_apply,
        resnet_init,
    )
    from tony_tpu.parallel.mesh import MeshSpec, build_mesh

    cfg = ResNetConfig(depth=50, width=64, n_classes=1000, dtype="bfloat16")
    mesh = build_mesh(MeshSpec(), devices=jax.devices()[:1])
    init_fn, step_fn = make_image_classifier_step(
        lambda key: resnet_init(key, cfg),
        lambda params, images: resnet_apply(params, images, cfg),
        mesh,
        config=cfg,
    )
    rng = np.random.default_rng(0)
    images = jnp.asarray(rng.normal(size=(batch, size, size, 3)), jnp.float32)
    labels = jnp.asarray(rng.integers(0, 1000, (batch,)), jnp.int32)
    with jax.sharding.set_mesh(mesh):
        state = init_fn(jax.random.key(0))
        for _ in range(3):
            state, metrics = step_fn(state, images, labels)
        float(metrics["loss"])  # host readback = real fence
        dt = float("inf")  # best of 2 (see bench_transformer)
        for _ in range(2):
            t0 = time.perf_counter()
            for _ in range(measure):
                state, metrics = step_fn(state, images, labels)
            float(metrics["loss"])  # tony: noqa[TONY-X002] — intended per-window timing fence
            dt = min(dt, time.perf_counter() - t0)
    return {
        "images_per_sec_per_chip": round(batch * measure / dt, 1),
        "batch": batch,
        "image_size": size,
        "step_ms": round(dt / measure * 1000, 2),
    }


def _step_stats(walls_s: list[float]) -> dict:
    """Per-step wall stats: the mean hides a bimodal pipeline (fast
    overlapped steps + periodic stalls when the prefetch queue drains),
    so the JSON line carries p50/p95 too — a data-plane regression shows
    up in the tail before it moves the average."""
    arr = np.asarray(walls_s) * 1000.0
    return {
        "mean_ms": round(float(arr.mean()), 2),
        "p50_ms": round(float(np.percentile(arr, 50)), 2),
        "p95_ms": round(float(np.percentile(arr, 95)), 2),
    }


def _io_rates(snap0: dict, snap1: dict) -> dict:
    """Data-plane sub-rates from two observability-registry snapshots
    bracketing the streamed window: sustained read and H2D throughput
    (bytes over the time actually spent in reads/puts — the overlapped
    rates, not wall-clock divides) plus the mean consumer stall per
    batch. These attribute a regression to its layer without a rerun."""
    def dc(name):
        return (snap1["counters"].get(name, 0.0)
                - snap0["counters"].get(name, 0.0))

    def dh(name):
        a = snap1["histograms"].get(name, {"count": 0, "sum": 0.0})
        b = snap0["histograms"].get(name, {"count": 0, "sum": 0.0})
        return a["count"] - b["count"], a["sum"] - b["sum"]

    from tony_tpu.io.reader import (
        IO_BYTES_READ_COUNTER,
        IO_H2D_BYTES_COUNTER,
        IO_H2D_MS_HISTOGRAM,
        IO_QUEUE_WAIT_MS_HISTOGRAM,
        IO_READ_MS_HISTOGRAM,
    )

    _, read_ms = dh(IO_READ_MS_HISTOGRAM)
    _, h2d_ms = dh(IO_H2D_MS_HISTOGRAM)
    n_wait, wait_ms = dh(IO_QUEUE_WAIT_MS_HISTOGRAM)
    return {
        "read_mb_per_sec": round(
            dc(IO_BYTES_READ_COUNTER) / 1e3 / read_ms, 1
        ) if read_ms > 0 else 0.0,
        "h2d_mb_per_sec": round(
            dc(IO_H2D_BYTES_COUNTER) / 1e3 / h2d_ms, 1
        ) if h2d_ms > 0 else 0.0,
        "queue_wait_ms_mean": round(wait_ms / n_wait, 2) if n_wait else 0.0,
    }


def bench_input_pipeline(lm_measure: int = 16, resnet_measure: int = 20,
                         workloads: tuple = ("lm", "resnet")):
    """VERDICT r4 weak #2: prove the data plane can FEED the chip. Writes
    a real on-disk tokens corpus, streams it through ShardedRecordReader
    (parallel span reads) → ``device_prefetch`` (background-thread H2D,
    depth 4) into the same train steps the synthetic benches run, and
    reports streamed vs synthetic per-step stats (the gap is the input
    pipeline's uncovered cost). Second point at ResNet scale: raw uint8
    image records (150,528 B each, the shape where bytes — not tokens —
    are the constraint) transferred as uint8 and decoded ON DEVICE
    (resnet_apply's cast+scale), with the sustained disk→HBM byte rate
    and the registry-attributed io sub-rates. Every step is fenced by a
    loss readback so the per-step distribution (p50/p95) is real.

    ``workloads`` selects the sections — the post-PR-4 streamed-ResNet
    re-measurement runs ``("resnet",)`` alone (the 200M LM section is
    pointless on hosts where that model cannot hit steady state)."""
    from tony_tpu import observability
    from tony_tpu.parallel.mesh import MeshSpec, build_mesh

    mesh = build_mesh(MeshSpec(), devices=jax.devices()[:1])
    registry = observability.default_registry()
    rng = np.random.default_rng(0)
    out = {}
    warm = 3

    def timed_steps(n, one_step):
        walls = []
        for _ in range(n):
            t0 = time.perf_counter()
            one_step()  # must end with a host readback (module fence rule)
            walls.append(time.perf_counter() - t0)
        return walls

    # -- LM: 200M flagship config, same shape as bench_transformer --------
    if "lm" in workloads:
        out.update(_bench_input_lm(mesh, registry, rng, lm_measure, warm,
                                   timed_steps))
    if "resnet" in workloads:
        out.update(_bench_input_resnet(mesh, registry, rng, resnet_measure,
                                       warm, timed_steps))
    return out


def _bench_input_lm(mesh, registry, rng, lm_measure, warm, timed_steps):
    import os as _os
    import tempfile

    from tony_tpu.io import ShardedRecordReader, sharded_batches
    from tony_tpu.models import TransformerConfig, make_train_step

    out = {}
    batch, seq = 8, 2048
    cfg = TransformerConfig(
        vocab_size=32_000, d_model=1024, n_layers=8, n_heads=16,
        head_dim=64, d_ff=4096, max_seq=seq, dtype="bfloat16",
        remat=False, layer_scan_unroll=8,
    )
    init_fn, step_fn = make_train_step(cfg, mesh)
    rows = (lm_measure + warm) * batch
    corpus = rng.integers(0, cfg.vocab_size, (rows, seq), dtype=np.uint16)
    with tempfile.NamedTemporaryFile(suffix=".tokens", delete=False) as f:
        f.write(corpus.tobytes())
        lm_path = f.name
    try:
        with jax.sharding.set_mesh(mesh):
            state_box = [init_fn(jax.random.key(0))]
            synth = jnp.asarray(corpus[:batch], jnp.uint16)

            def synth_step():
                state_box[0], m = step_fn(state_box[0], synth)
                float(m["loss"])

            timed_steps(warm, synth_step)
            synth_walls = timed_steps(lm_measure, synth_step)

            reader = ShardedRecordReader(
                [lm_path], fmt="tokens", dtype=np.uint16, record_len=seq,
                batch_size=batch,
            )
            with reader:
                it = sharded_batches(reader, mesh)

                def stream_step():
                    state_box[0], m = step_fn(state_box[0], next(it))
                    float(m["loss"])

                io0 = registry.snapshot()  # pre-warm: rates cover
                timed_steps(warm, stream_step)  # the whole stream session
                stream_walls = timed_steps(lm_measure, stream_step)
                io1 = registry.snapshot()
        synth_dt, stream_dt = sum(synth_walls), sum(stream_walls)
        out["lm_200m"] = {
            "synthetic_step_ms": round(synth_dt / lm_measure * 1000, 2),
            "streamed_step_ms": round(stream_dt / lm_measure * 1000, 2),
            "overhead_pct": round((stream_dt / synth_dt - 1) * 100, 1),
            "synthetic": _step_stats(synth_walls),
            "streamed": _step_stats(stream_walls),
            "io": _io_rates(io0, io1),
            "batch": batch, "seq": seq,
        }
    finally:
        _os.unlink(lm_path)
    return out


def _bench_input_resnet(mesh, registry, rng, resnet_measure, warm,
                        timed_steps):
    import os as _os
    import tempfile

    from tony_tpu.io import ShardedRecordReader, device_prefetch
    from tony_tpu.models import (
        ResNetConfig,
        make_image_classifier_step,
        resnet_apply,
        resnet_init,
    )

    out = {}
    # -- ResNet-50: uint8 image records, bytes are the constraint ---------
    ibatch, size = 32, 224
    rec = size * size * 3
    rcfg = ResNetConfig(depth=50, width=64, n_classes=1000, dtype="bfloat16")
    rinit, rstep = make_image_classifier_step(
        lambda key: resnet_init(key, rcfg),
        lambda params, images: resnet_apply(params, images, rcfg),
        mesh,
        config=rcfg,
    )
    rows = (resnet_measure + warm) * ibatch
    images = rng.integers(0, 256, (rows, rec), dtype=np.uint8)
    with tempfile.NamedTemporaryFile(suffix=".tokens", delete=False) as f:
        f.write(images.tobytes())
        img_path = f.name
    try:
        from jax.sharding import NamedSharding, PartitionSpec as P

        labels = jnp.asarray(rng.integers(0, 1000, (ibatch,)), jnp.int32)
        sharding = NamedSharding(mesh, P(("dp", "ep")))
        with jax.sharding.set_mesh(mesh):
            state_box = [rinit(jax.random.key(0))]
            # Synthetic feeds the SAME uint8 contract the streamed path
            # uses (decode happens on device in resnet_apply), pre-placed
            # so its step time is pure compute.
            synth = jax.device_put(
                images[:ibatch].reshape(ibatch, size, size, 3), sharding
            )

            def synth_step():
                state_box[0], m = rstep(state_box[0], synth, labels)
                float(m["loss"])

            timed_steps(warm, synth_step)
            synth_walls = timed_steps(resnet_measure, synth_step)

            reader = ShardedRecordReader(
                [img_path], fmt="tokens", dtype=np.uint8, record_len=rec,
                batch_size=ibatch,
            )
            with reader:
                def img_batches():
                    for b in reader:
                        if b.shape[0] == ibatch:
                            # reshape is metadata-only; bytes stay uint8
                            # until the on-device decode inside the step
                            yield b.reshape(ibatch, size, size, 3)

                # Deep pipeline, wide transfer pool: at ~4.8 MB/batch the
                # put dominates the 18 ms step on slow transports, so up
                # to 6 transfers proceed concurrently while the consumer
                # steps (~38 MB of host batches in flight — noise next to
                # the model). On fast PCIe the extra workers just idle.
                with device_prefetch(
                    img_batches(), sharding, depth=8, transfer_workers=6,
                ) as it:
                    def stream_step():
                        state_box[0], m = rstep(
                            state_box[0], next(it), labels
                        )
                        float(m["loss"])

                    io0 = registry.snapshot()  # pre-warm (see LM)
                    timed_steps(warm, stream_step)
                    stream_walls = timed_steps(resnet_measure, stream_step)
                    io1 = registry.snapshot()
        synth_dt, stream_dt = sum(synth_walls), sum(stream_walls)
        # Attribution microbenches: where does a streamed-vs-synthetic gap
        # come from? Host-side reader throughput vs a bare device_put of
        # one batch. On the tunneled axon platform a blocking H2D put
        # measures ~12-16 MB/s (the tunnel relay serializes transfers)
        # while the reader sustains GB/s — the background transfer thread
        # plus deep prefetch is what hides that latency behind the step.
        reader2 = ShardedRecordReader(
            [img_path], fmt="tokens", dtype=np.uint8, record_len=rec,
            batch_size=ibatch,
        )
        with reader2:
            t0 = time.perf_counter()
            nbytes = sum(b.nbytes for b in reader2)
            host_rate = nbytes / (time.perf_counter() - t0) / 1e6
        one = jnp.asarray(images[:ibatch].reshape(ibatch, size, size, 3))
        np.asarray(one.reshape(-1)[0])
        t0 = time.perf_counter()
        for _ in range(4):
            one = jax.device_put(
                images[:ibatch].reshape(ibatch, size, size, 3)
            )
        np.asarray(one.reshape(-1)[0])
        h2d_rate = 4 * ibatch * rec / (time.perf_counter() - t0) / 1e6
        out["resnet50"] = {
            "synthetic_step_ms": round(synth_dt / resnet_measure * 1000, 2),
            "streamed_step_ms": round(stream_dt / resnet_measure * 1000, 2),
            "overhead_pct": round((stream_dt / synth_dt - 1) * 100, 1),
            "disk_to_hbm_mb_per_sec": round(
                ibatch * rec * resnet_measure / stream_dt / 1e6, 1
            ),
            "host_reader_mb_per_sec": round(host_rate, 1),
            "h2d_device_put_mb_per_sec": round(h2d_rate, 1),
            "synthetic": _step_stats(synth_walls),
            "streamed": _step_stats(stream_walls),
            "io": _io_rates(io0, io1),
            "prefetch_depth": 8,
            "transfer_workers": 6,
            "batch": ibatch,
        }
    finally:
        _os.unlink(img_path)
    return out


def bench_flash_attention(seq: int, batch: int, heads: int = 8,
                          head_dim: int = 64, measure: int = 30):
    """Pallas flash kernel vs the blockwise-XLA fallback (force_jax=True),
    forward pass, causal self-attention."""
    from tony_tpu.ops import flash_attention

    rng = np.random.default_rng(0)
    shape = (batch, seq, heads, head_dim)
    q, k, v = (
        jnp.asarray(rng.normal(size=shape), jnp.bfloat16) for _ in range(3)
    )

    def timed(force_jax: bool) -> float:
        fn = jax.jit(
            # fold a reduction in so the timed fence is one scalar readback
            lambda q, k, v: jnp.sum(
                flash_attention(q, k, v, force_jax=force_jax)
                .astype(jnp.float32)
            )
        )
        out = fn(q, k, v)
        float(out)  # host readback = real fence
        t0 = time.perf_counter()
        for _ in range(measure):
            out = fn(q, k, v)
        float(out)
        return (time.perf_counter() - t0) / measure * 1000

    pallas_ms = timed(False)
    xla_ms = timed(True)
    return {
        "seq": seq,
        "batch": batch,
        "pallas_ms": round(pallas_ms, 3),
        "blockwise_xla_ms": round(xla_ms, 3),
        "speedup": round(xla_ms / pallas_ms, 2),
    }


# The per-job script bench_scheduler submits: compile one instrumented
# classifier step (the plan-keyed compile the warm pool's cache serves)
# and stamp the first-step completion time for submit-to-first-step.
_SCHED_JOB_SCRIPT = """\
import os, time
import tony_tpu.runtime as rt
ctx = rt.initialize()
import jax
import jax.numpy as jnp
import numpy as np
from tony_tpu.models import MnistConfig
from tony_tpu.models.train import make_classifier_step
from tony_tpu.parallel.mesh import MeshSpec, build_mesh

mesh = build_mesh(MeshSpec(), devices=jax.devices()[:1])
init_fn, step_fn = make_classifier_step(
    MnistConfig(arch="cnn", dtype="float32"), mesh)
rng = np.random.default_rng(0)
images = jnp.asarray(rng.normal(size=(8, 28, 28, 1)), jnp.float32)
labels = jnp.asarray(rng.integers(0, 10, (8,)), jnp.int32)
state = init_fn(jax.random.key(0))
state, metrics = step_fn(state, images, labels)
float(metrics["loss"])
with open(os.environ["FIRST_STEP_OUT"], "w") as f:
    f.write(str(time.time()))
"""


def bench_scheduler(jobs: int = 3, provision_ms: int = 4000):
    """Multi-tenant scheduler warm-pool amortization: N identical jobs
    through one ``SchedulerDaemon`` on a 1-slice pool. Job 1 pays the
    full cold path (slice provisioning — modeled at ``provision_ms``,
    far below the minutes a real queued-resource create takes — plus a
    cold XLA compile); jobs 2..N lease the slice warm: provisioning
    skipped, compiles served from the slice's pool-owned cache. The
    headline is warm vs cold submit-to-first-step and jobs/hour over
    the drained batch."""
    import sys as _sys
    import tempfile as _tempfile
    from pathlib import Path as _Path

    if jobs < 2:
        raise ValueError("bench_scheduler needs >= 2 jobs: the warm "
                         "figure is jobs 2..N")

    from tony_tpu.conf import keys as _keys
    from tony_tpu.conf.configuration import TonyConfiguration
    from tony_tpu.scheduler import SchedulerDaemon
    from tony_tpu.scheduler.pool import (
        COLD_PROVISIONS_COUNTER, WARM_HITS_COUNTER,
    )

    with _tempfile.TemporaryDirectory(prefix="tony-bench-sched-") as root:
        d = _Path(root)
        script = d / "first_step.py"
        script.write_text(_SCHED_JOB_SCRIPT)
        conf = TonyConfiguration()
        conf.set(_keys.K_SCHED_TICK_MS, 50)
        conf.set(_keys.K_SCHED_MAX_SLICES, 1)
        conf.set(_keys.K_SCHED_LOCAL_PROVISION_MS, provision_ms)
        daemon = SchedulerDaemon(d / "sched", conf=conf).start(
            serve_http=False
        )
        # Executor children ALWAYS run on CPU: this bench measures the
        # orchestration layer (provision/staging/compile-cache
        # amortization), and on a TPU host the parent bench process
        # already holds the chip — libtpu is exclusive per host, so a
        # TPU child could never initialize anyway.
        platform = "cpu"
        lat_ms: list[float] = []
        t_batch0 = time.perf_counter()
        try:
            for i in range(jobs):
                c = TonyConfiguration()
                c.set(_keys.K_EXECUTES, str(script))
                c.set(_keys.K_PYTHON_BINARY, _sys.executable)
                c.set(_keys.instances_key("worker"), 1)
                c.set(_keys.instances_key("ps"), 0)
                # Children must land on the same backend the bench runs
                # on (a CPU bench box must not have executors probe TPUs).
                c.set(_keys.K_SHELL_ENV,
                      f"FIRST_STEP_OUT={d}/step-{i}.ts,"
                      f"JAX_PLATFORMS={platform}")
                t0 = time.time()
                job_id = daemon.submit(c)
                state = daemon.wait_job(job_id, timeout_s=600)
                ts_file = d / f"step-{i}.ts"
                if state.value != "SUCCEEDED" or not ts_file.is_file():
                    raise RuntimeError(
                        f"scheduler bench job {i} ended {state.value} "
                        f"without a first step"
                    )
                lat_ms.append((float(ts_file.read_text()) - t0) * 1000)
            wall_s = time.perf_counter() - t_batch0
            counters = daemon.registry.snapshot()["counters"]
        finally:
            daemon.shutdown()
    cold = lat_ms[0]
    warm = sum(lat_ms[1:]) / (len(lat_ms) - 1)
    warm_hits = counters.get(WARM_HITS_COUNTER, 0)
    provisions = counters.get(COLD_PROVISIONS_COUNTER, 0)
    return {
        "jobs": jobs,
        # A config parameter of the bench, not a measurement — named
        # WITHOUT the _ms suffix so the gate's direction heuristic
        # leaves it ungated (raising the model must not read as a
        # latency regression). Unit is milliseconds.
        "provision_model": provision_ms,
        "cold_submit_to_step_ms": round(cold, 1),
        "warm_submit_to_step_ms": round(warm, 1),
        "warm_cold_speedup": round(cold / warm, 3),
        "jobs_per_hour": round(jobs / (wall_s / 3600.0), 1),
        "warm_hit_rate": round(warm_hits / max(warm_hits + provisions, 1),
                               3),
        **_bench_scheduler_ha(),
    }


def _bench_scheduler_ha(queued_jobs: int = 8):
    """Control-plane HA sub-metrics for ``bench_scheduler``:

    * ``recovery_ms`` — a dead leader's base dir (journal seeded with
      ``queued_jobs`` queued submissions: exactly the bytes a SIGKILL
      leaves behind) to a fresh daemon's ``start()`` returning with the
      queue rebuilt and the first snapshot published. Recovery runs
      synchronously inside ``start()``, so the wall around it IS the
      SIGKILL-to-first-post-recovery-tick window.
    * ``failover_ms`` — an active/standby pair on one base dir; the
      leader dies the way SIGKILL kills it (loop stopped dead, flock
      dropped, heartbeat left to go stale un-renewed) to the standby
      holding the seat with recovery done.
    """
    import tempfile as _tempfile
    from pathlib import Path as _Path

    from tony_tpu.conf import keys as _keys
    from tony_tpu.conf.configuration import TonyConfiguration
    from tony_tpu.scheduler import SchedulerDaemon
    from tony_tpu.scheduler import journal as _wal
    from tony_tpu.scheduler.journal import SchedulerJournal

    out: dict[str, float] = {}
    with _tempfile.TemporaryDirectory(prefix="tony-bench-ha-") as root:
        base = _Path(root) / "sched"
        base.mkdir()
        j = SchedulerJournal(base / _wal.JOURNAL_FILE)
        now = int(time.time() * 1000)
        for i in range(queued_jobs):
            j.append(_wal.J_JOB_QUEUED, ts_ms=now,
                     job_id=f"job_{i + 1:04d}_bench",
                     app_dir=str(base / f"app-{i}"), priority=0,
                     tenant="default", submit_ms=now, seq_no=i + 1)
        conf = TonyConfiguration()
        conf.set(_keys.K_SCHED_TICK_MS, 50)
        # Zero slots: the recovered queue must REBUILD, not launch —
        # this measures the control plane, not executor spawn time.
        conf.set(_keys.K_SCHED_MAX_SLICES, 0)
        t0 = time.perf_counter()
        daemon = SchedulerDaemon(base, conf=conf).start(serve_http=False)
        recovery_ms = (time.perf_counter() - t0) * 1000
        restored = len(daemon._jobs)
        daemon.shutdown()
        if daemon.recovered_ms is None or restored != queued_jobs:
            raise RuntimeError(
                f"recovery bench restored {restored}/{queued_jobs} jobs"
            )
        out["recovery_ms"] = round(recovery_ms, 1)

        pair = _Path(root) / "pair"
        pair.mkdir()

        def _pair_conf(node: str) -> TonyConfiguration:
            c = TonyConfiguration()
            c.set(_keys.K_SCHED_TICK_MS, 50)
            c.set(_keys.K_SCHED_MAX_SLICES, 0)
            c.set(_keys.K_SCHED_HA_LEASE_MS, 600)
            c.set(_keys.K_SCHED_HA_NODE_ID, node)
            return c

        a = SchedulerDaemon(pair, conf=_pair_conf("bench-a")).start(
            serve_http=False
        )
        b = SchedulerDaemon(pair, conf=_pair_conf("bench-b")).start(
            serve_http=False
        )
        if not a.election.is_leader or b.election.is_leader:
            raise RuntimeError("failover bench pair did not settle "
                               "into active/standby")
        # Crash the leader the way SIGKILL does: loop stopped dead (no
        # clean release — the heartbeat goes stale un-renewed), then
        # the kernel drops the flock.
        a._stop.set()
        a._wake.set()
        a._thread.join(timeout=30)
        t1 = time.perf_counter()
        a.election.abandon()
        deadline = t1 + 30
        while time.perf_counter() < deadline:
            if b.election.is_leader and b.recovered_ms is not None:
                break
            time.sleep(0.005)
        failover_ms = (time.perf_counter() - t1) * 1000
        took_over = b.election.is_leader
        b.shutdown()
        if not took_over:
            raise RuntimeError("standby never took the seat")
        out["failover_ms"] = round(failover_ms, 1)
    return out


def bench_checkpoint(saves: int = 6, store_ms: int = 20,
                     train_gap_ms: int = 80):
    """Checkpoint pipeline amortization on the REAL lm_train optimizer
    tree (``make_train_step``'s TrainState: step + params + adamw
    moments, a couple of real steps run so the moments are populated).

    Three claims, each a gated sub-metric:

    * **save wall off the step path** — mean ``save(blocking=True)``
      wall vs the pipelined ``save()`` CALL wall against a store whose
      per-PUT latency is modeled at ``store_ms`` (a remote-object-store
      RTT; local-fs puts are too fast to show the effect the pipeline
      exists for). Between saves both arms "train" for a modeled
      ``train_gap_ms`` (the checkpoint-interval wall a real loop has —
      the window the pipeline persists inside; back-to-back saves
      would measure pure backpressure instead of the steady state).
      ``save_offpath_speedup`` is the ratio.
    * **differential bytes** — per-save shard bytes, full rewrites vs
      differential saves under a frozen-fine-tune update pattern (one
      third of the leaves mutated per save; the rest — frozen layers /
      untouched adam moments — byte-identical). ``full_over_diff_speedup``
      is the bytes ratio.
    * **commit lag** — ``commit_lag_ms``: last ``save()`` return → every
      submitted step committed (markers down), the window a crash can
      cost beyond the last marker.
    """
    import tempfile as _tempfile
    from pathlib import Path as _Path

    from tony_tpu.checkpoint import CheckpointManager
    from tony_tpu.models import TransformerConfig, make_train_step
    from tony_tpu.parallel.mesh import MeshSpec, build_mesh

    cfg = TransformerConfig(
        vocab_size=256, d_model=64, n_layers=2, n_heads=4, head_dim=16,
        d_ff=256, max_seq=64, dtype="float32", remat=False,
    )
    mesh = build_mesh(MeshSpec(dp=1), devices=jax.devices()[:1])
    init_fn, step_fn = make_train_step(cfg, mesh)
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, 256, (4, 65)), jnp.int32
    )
    with jax.sharding.set_mesh(mesh):
        state = init_fn(jax.random.key(0))
        for _ in range(2):  # populate the adam moments with real values
            state, _ = step_fn(state, tokens)

    leaves, treedef = jax.tree_util.tree_flatten(state)
    state_bytes = sum(
        np.asarray(leaf).nbytes for leaf in leaves
    )

    def mutate(tree, salt: float):
        """Frozen-fine-tune shape: every third leaf changes, the rest
        stay byte-identical (what a diff save may skip)."""
        flat, td = jax.tree_util.tree_flatten(tree)
        out = []
        for i, leaf in enumerate(flat):
            if i % 3 == 0 and jnp.issubdtype(leaf.dtype, jnp.floating):
                out.append(leaf + jnp.asarray(salt, leaf.dtype))
            else:
                out.append(leaf)
        return jax.tree_util.tree_unflatten(td, out)

    class _ModeledStore:
        """A store whose every PUT pays a modeled remote RTT."""

        def __init__(self, inner):
            self._inner = inner

        def put_file(self, step, name, data):
            time.sleep(store_ms / 1000.0)
            return self._inner.put_file(step, name, data)

        def __getattr__(self, attr):
            return getattr(self._inner, attr)

    def shard_bytes(root: _Path, step: int) -> int:
        return (root / f"step_{step}" / "process_0.npz").stat().st_size

    with _tempfile.TemporaryDirectory(prefix="tony-bench-ckpt-") as root:
        d = _Path(root)
        # Arm 1: full rewrites, blocking — the pre-pipeline step-path
        # cost (snapshot + encode + 3 modeled PUTs on the caller).
        full_dir = d / "full"
        mgr_full = CheckpointManager(full_dir, differential=False,
                                     max_to_keep=saves + 2)
        mgr_full._store = _ModeledStore(mgr_full._store)
        cur = state
        blocking_ms = []
        for i in range(1, saves + 1):
            cur = mutate(cur, float(i))
            t0 = time.perf_counter()
            mgr_full.save(i, cur, blocking=True)
            blocking_ms.append((time.perf_counter() - t0) * 1000.0)
            time.sleep(train_gap_ms / 1000.0)
        bytes_full = shard_bytes(full_dir, saves)
        # Arm 2: differential saves through the pipeline — the call wall
        # is what the train loop pays; commit runs behind it.
        diff_dir = d / "diff"
        mgr_diff = CheckpointManager(diff_dir, differential=True,
                                     full_every=10**6, pipeline_depth=2,
                                     max_to_keep=saves + 2)
        mgr_diff._store = _ModeledStore(mgr_diff._store)
        cur = state
        call_ms = []
        for i in range(1, saves + 1):
            cur = mutate(cur, float(i))
            t0 = time.perf_counter()
            mgr_diff.save(i, cur)
            call_ms.append((time.perf_counter() - t0) * 1000.0)
            time.sleep(train_gap_ms / 1000.0)
        t_drain = time.perf_counter()
        while mgr_diff.last_committed_step != saves:
            if time.perf_counter() - t_drain > 120:
                raise RuntimeError("checkpoint pipeline never drained")
            time.sleep(0.001)
        commit_lag_ms = (time.perf_counter() - t_drain) * 1000.0
        mgr_diff.wait()
        bytes_diff = shard_bytes(diff_dir, saves)

    blocking = sum(blocking_ms) / len(blocking_ms)
    # Backpressured calls (depth exceeded) are real step-path cost and
    # stay in the mean on purpose.
    call = sum(call_ms) / len(call_ms)
    return {
        "saves": saves,
        # Modeled per-PUT store latency and per-interval training wall
        # — bench parameters, named WITHOUT unit suffixes so the gate's
        # direction heuristic leaves them ungated. Unit: milliseconds.
        "store_model": store_ms,
        "train_gap_model": train_gap_ms,
        "state_mb": round(state_bytes / 1e6, 3),
        "blocking_save_ms": round(blocking, 2),
        "pipeline_save_call_ms": round(call, 2),
        "save_offpath_speedup": round(blocking / max(call, 1e-6), 2),
        "full_save_kb": round(bytes_full / 1024.0, 1),
        "diff_save_kb": round(bytes_diff / 1024.0, 1),
        "full_over_diff_speedup": round(bytes_full / max(bytes_diff, 1),
                                        2),
        "commit_lag_ms": round(commit_lag_ms, 1),
    }


def bench_autotune(trial_budget: int = 4, n_requests: int = 8,
                   max_new_tokens: int = 24):
    """The measured autotuner's loop, closed and gated (three claims):

    - ``tuned_over_default_speedup``: a COLD ``tune_train_step`` search
      over the remat candidates of a tiny lm config. The ratio is >= 1.0
      by construction (the default is ``candidates[0]`` and the winner
      is the min over all trials including it), so the 1.0 baseline
      gates the search *machinery* — a broken ranking, a default that
      stopped being measured, or a record whose winner loses to its own
      default all read as a regression.
    - ``search_trials_warm``: the SAME call again must be answered from
      the persisted record with ZERO new measurements — the warm-reuse
      analog of the compile-cache hits==2/misses==0 gate.
    - ``int8_kv_decode_tok_per_sec`` (with its float comparator): the
      serving engine draining a fixed greedy workload from a quantized
      KV cache — the serving-side tuning axis; decode is bandwidth-
      bound, so halving KV bytes is the lever, and the gate keeps the
      quantized path from silently rotting.
    """
    import tempfile as _tempfile

    from tony_tpu.models import TransformerConfig, init_params
    from tony_tpu.parallel import autotune
    from tony_tpu.parallel.mesh import MeshSpec, build_mesh
    from tony_tpu.serving import ServingEngine

    cfg = TransformerConfig(
        vocab_size=256, d_model=64, n_layers=2, n_heads=2, head_dim=32,
        d_ff=256, max_seq=128, dtype="float32", remat=False,
    )
    mesh = build_mesh(MeshSpec(dp=1), devices=jax.devices()[:1])
    with _tempfile.TemporaryDirectory(prefix="tony-bench-tune-") as td:
        rec_cold = autotune.tune_train_step(
            cfg, mesh, global_batch=4, seq=64,
            trial_budget=trial_budget, cache_dir=td,
        )
        rec_warm = autotune.tune_train_step(
            cfg, mesh, global_batch=4, seq=64,
            trial_budget=trial_budget, cache_dir=td,
        )
    speedup = (
        rec_cold["default_ms"] / rec_cold["best_ms"]
        if rec_cold.get("best_ms") and rec_cold.get("default_ms")
        else float("nan")
    )

    # -- int8 KV decode ---------------------------------------------------
    scfg = TransformerConfig(
        vocab_size=512, d_model=128, n_layers=2, n_heads=4, head_dim=32,
        d_ff=512, max_seq=256, dtype="float32", remat=False, n_kv_heads=2,
    )
    params = jax.jit(lambda k: init_params(k, scfg))(jax.random.key(0))  # tony: noqa[TONY-X001] — one-shot init compile, not a step path
    rng = np.random.default_rng(0)
    prompts = [rng.integers(0, scfg.vocab_size, 16).astype(np.int32)
               for _ in range(n_requests)]

    def drain(kv_quant: str) -> float:
        eng = ServingEngine(
            params, scfg, slots=4, max_len=64, prefill_chunk=16,
            decode_window=8, kv_quant=kv_quant,
        )
        # Warm the executables out of the wall.
        warm = eng.submit(prompts[0], max_new_tokens=2)
        while not warm.done():
            eng.step()
        t0 = time.perf_counter()
        reqs = [eng.submit(p, max_new_tokens=max_new_tokens)
                for p in prompts]
        while not all(r.done() for r in reqs):
            eng.step()
        wall = time.perf_counter() - t0
        eng.close()
        return sum(len(r.tokens) for r in reqs) / wall

    int8_rate = drain("int8")
    float_rate = drain("none")

    return {
        "tuned_over_default_speedup": round(speedup, 3),
        "search_trials_warm": rec_warm["trials_this_run"],
        # Bench parameters / context, named without unit suffixes so the
        # direction heuristic leaves them ungated.
        "search_trials_cold": rec_cold["trials_this_run"],
        "int8_kv_decode_tok_per_sec": round(int8_rate),
        "float_kv_decode_tok_per_sec": round(float_rate),
        "int8_over_float_ratio": round(int8_rate / float_rate, 3),
    }


def bench_rollup(targets: int = 24, tasks_per_target: int = 8,
                 ticks: int = 12, queries: int = 200):
    """The fleet rollup's control-plane costs at synthetic-fleet scale,
    hermetic (injected scrape documents, no HTTP, no jobs):

    - ``scrape_fan_in_ms``: one full scrape pass over every target's
      /api/metrics document (parse + normalize, the per-tick fan-in);
    - ``rollup_tick_ms``: mean full tick — scrape, fold (counter deltas,
      gauge folds, histogram merges across all scopes), TSDB record,
      SLO evaluation;
    - ``query_p95_ms``: p95 of range reads against the populated store
      (the /api/query path a dashboard hammers);
    - ``series_bytes_on_disk`` / ``series``: store shape after a
      checkpoint, ungated context numbers.

    Counters advance and gauges wobble per tick so the fold exercises
    the delta path, not the first-sight shortcut."""
    import tempfile as _tempfile

    from tony_tpu.observability.events import EventLog
    from tony_tpu.observability.goodput import GOODPUT_RATIO_GAUGE
    from tony_tpu.observability.rollup import FleetRollup, SloObjective, Target
    from tony_tpu.observability.stepstats import MFU_GAUGE
    from tony_tpu.observability.tsdb import TimeSeriesStore
    from tony_tpu.serving.scheduler import SERVING_TTFT_MS_HISTOGRAM

    bounds = [float(2 ** i) for i in range(16)]
    tick_state = {"n": 0}

    def doc_for(idx: int) -> dict:
        n = tick_state["n"]
        hist = {
            "count": 100 * (n + 1),
            "sum": 2500.0 * (n + 1),
            "buckets": [[b, min(100 * (n + 1), int(b) * (n + 1))]
                        for b in bounds],
        }
        tasks = {
            f"worker:{t}": {
                "counters": {"train_steps_total": 50.0 * n + t},
                "gauges": {"loss": 1.0 / (n + 1), MFU_GAUGE: 0.5,
                           "tokens_per_sec": 900.0 + t},
                "histograms": {},
            }
            for t in range(tasks_per_target)
        }
        return {
            "coordinator": {
                "counters": {"train_steps_total": 50.0 * n * tasks_per_target},
                "gauges": {GOODPUT_RATIO_GAUGE: 0.8 + 0.01 * (idx % 10)},
                "histograms": {SERVING_TTFT_MS_HISTOGRAM: hist},
            },
            "heartbeats": {f"worker:{t}": float(n + 1)
                           for t in range(tasks_per_target)},
            "heartbeat_age_s": {f"worker:{t}": 0.5
                                for t in range(tasks_per_target)},
            "tasks": tasks,
        }

    fleet = [Target(f"job{i}", "job", f"host:{i}",
                    tenant=f"tenant{i % 4}") for i in range(targets)]

    def fetch(url: str, timeout_s: float) -> dict:
        idx = int(url.split("host:")[1].split("/")[0])
        return doc_for(idx)

    base_ms = 1_700_000_400_000
    with _tempfile.TemporaryDirectory(prefix="tony-bench-rollup-") as td:
        rollup = FleetRollup(
            None,
            tsdb=TimeSeriesStore(td),
            events=EventLog(),
            objectives=[SloObjective(
                "goodput", "tony_goodput_ratio|fleet", "min", 0.9
            )],
            fast_window_s=60, slow_window_s=300,
            fetch_json=fetch,
        )
        rollup.discover_targets = lambda: list(fleet)

        t0 = time.perf_counter()
        scraped = [rollup._scrape(t) for t in fleet]
        fan_in_ms = (time.perf_counter() - t0) * 1e3
        assert all(s is not None for s in scraped)

        walls = []
        for n in range(ticks):
            tick_state["n"] = n
            t0 = time.perf_counter()
            rollup.tick(now_ms=base_ms + n * 15_000)
            walls.append((time.perf_counter() - t0) * 1e3)

        names = rollup.tsdb.names()
        q_walls = []
        for i in range(queries):
            series = names[i % len(names)]
            name, _, scope = series.rpartition("|")
            t0 = time.perf_counter()
            rollup.query_series(name, agg="avg", scope=scope,
                                since_s=3600, step_s=60)
            q_walls.append((time.perf_counter() - t0) * 1e3)
        q_walls.sort()

        rollup.tsdb.checkpoint()
        stats = rollup.tsdb.stats()

    return {
        "scrape_fan_in_ms": round(fan_in_ms, 2),
        "rollup_tick_ms": round(sum(walls) / len(walls), 2),
        "query_p95_ms": round(q_walls[int(len(q_walls) * 0.95)], 3),
        # Shape / context, named without direction suffixes (ungated).
        "targets": targets,
        "series": stats["series"],
        "series_bytes_on_disk": stats["disk_bytes"],
    }


# ---------------------------------------------------------------------------
# Regression gate (`bench.py --check`)
# ---------------------------------------------------------------------------
# BENCH r01–r05 showed real regressions sailing through because only the
# headline mnist number was eyeballed: mnist 3548 → 750 → 2401
# steps/sec/chip, resnet50 2036 → 1786 img/s, flash 2k speedup
# 2.19× → 1.56×. The gate makes every SUB-metric first-class: a baseline
# per metric per platform persists in BASELINE.json, and any >10% drop
# exits nonzero.

BASELINE_FILE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "BASELINE.json")
BASELINE_KEY = "bench_baselines"  # platform -> {metric path -> value}
DEFAULT_THRESHOLD = 0.10

# Direction by name suffix. Anything matching neither list is a shape /
# config parameter (batch, seq, params_m, ...) and is not gated.
_HIGHER_SUFFIXES = ("per_sec", "per_sec_per_chip", "mfu", "speedup",
                    "mb_per_sec", "vs_baseline", "per_hour", "hit_rate")
_LOWER_SUFFIXES = ("_ms", "_pct", "ms_mean", "step_ms", "p50_ms", "p95_ms",
                   "retraces_total", "trials_warm")


def metric_direction(name: str) -> str | None:
    """'higher' / 'lower' / None (ungated parameter)."""
    leaf = name.rsplit(".", 1)[-1]
    if leaf == "mfu" or leaf.endswith(_HIGHER_SUFFIXES):
        return "higher"
    if leaf.endswith(_LOWER_SUFFIXES):
        return "lower"
    return None


def collect_submetrics(line: dict) -> dict[str, float]:
    """Flatten one bench JSON line into {dotted.path: value} for every
    gated (direction-carrying, numeric, finite) sub-metric. Errored
    extras (`{"error": ...}` from _safe) contribute nothing — their
    metrics go MISSING, which --check reports as a failure rather than
    silently shrinking the gate."""
    out: dict[str, float] = {}

    def walk(node, path: str) -> None:
        if isinstance(node, dict):
            if "error" in node:
                return
            for k, v in node.items():
                walk(v, f"{path}.{k}" if path else str(k))
            return
        if isinstance(node, bool) or not isinstance(node, (int, float)):
            return
        if metric_direction(path) and np.isfinite(node):
            out[path] = float(node)

    if isinstance(line.get("value"), (int, float)):
        out["mnist_train_steps_per_sec_per_chip"] = float(line["value"])
    walk(line.get("extras", {}), "")
    return out


def check_regressions(
    current: dict[str, float],
    baseline: dict[str, float],
    threshold: float = DEFAULT_THRESHOLD,
) -> list[str]:
    """Every baseline metric that regressed past ``threshold`` (or went
    missing), as human-readable complaints. Empty list = gate passes.
    Metrics present only in ``current`` are new and pass free — run
    --update-baseline to start gating them."""
    problems: list[str] = []
    for name in sorted(baseline):
        base = baseline[name]
        if name not in current:
            problems.append(f"{name}: missing from this run "
                            f"(baseline {base:g})")
            continue
        cur = current[name]
        direction = metric_direction(name) or "higher"
        if base == 0:
            # A zero baseline on a lower-is-better COUNT is absolute:
            # "the steady-state step never re-traces" — any non-zero
            # current is a regression, no threshold to scale against.
            if direction == "lower" and cur > 0:
                problems.append(
                    f"{name}: {cur:g} regressed from a zero baseline "
                    f"(was clean, now is not)"
                )
            continue  # ratio gates need a non-zero base to scale against
        if direction == "higher" and cur < base * (1 - threshold):
            problems.append(
                f"{name}: {cur:g} is {(1 - cur / base) * 100:.1f}% below "
                f"baseline {base:g}"
            )
        elif direction == "lower" and cur > base * (1 + threshold):
            # Percent-point metrics near zero (a 1.3% io overhead) would
            # otherwise gate on fractions of a point — pure noise. They
            # get 5 points of absolute slack on top of the ratio.
            if name.endswith("_pct") and cur - base <= 5.0:
                continue
            problems.append(
                f"{name}: {cur:g} is {(cur / base - 1) * 100:.1f}% above "
                f"baseline {base:g}"
            )
    return problems


def load_baselines(path: str = BASELINE_FILE) -> dict:
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return {}
    table = doc.get(BASELINE_KEY, {})
    return table if isinstance(table, dict) else {}


def save_baselines(platform: str, metrics: dict[str, float],
                   path: str = BASELINE_FILE) -> None:
    """Merge this platform's baselines into BASELINE.json — per METRIC,
    not per platform: a partial-workload run (`--update-baseline` after
    a resnet-only re-measure) must refresh only the metrics it produced,
    never silently drop the transformer/decode/flash gates it didn't run
    (that would reopen exactly the silent-regression window the gate
    closes). Other keys in the file — north star, configs — pass through
    untouched. Retire a truly dead metric by hand-editing the file."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        doc = {}
    table = doc.setdefault(BASELINE_KEY, {}).setdefault(platform, {})
    table.update(metrics)
    doc[BASELINE_KEY][platform] = {k: table[k] for k in sorted(table)}
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=2, sort_keys=False)
        f.write("\n")
    os.replace(tmp, path)


def _bench_platform() -> str:
    d = jax.devices()[0]
    return d.device_kind or d.platform


def _safe(fn, *args, **kwargs):
    """One extra must not sink the whole bench line: the driver records
    exactly one JSON object per round, so a transient failure (tunnel
    hiccup, compile-helper 500, full /tmp) in a single extra degrades to
    an inline error string instead of losing every other number.

    With the jit sanitizer armed (the bench default), each workload's
    extras additionally carry ``retraces_total`` — the re-traces its
    instrumented dispatches incurred, measured as a tracker delta around
    the workload. Gated as a lower-is-better metric: a steady-state
    workload's baseline is 0, so ONE silent recompile fails --check."""
    from tony_tpu.analysis import jit_sanitizer

    armed = jit_sanitizer.enabled()
    before = jit_sanitizer.tracker().retraces() if armed else 0
    try:
        out = fn(*args, **kwargs)
    except Exception as exc:  # recorded, never raised
        return {"error": f"{type(exc).__name__}: {exc}"[:300]}
    if armed and isinstance(out, dict) and "error" not in out:
        out.setdefault(
            "retraces_total", jit_sanitizer.tracker().retraces() - before
        )
    return out


def run_benches() -> dict:
    steps_per_sec_per_chip = bench_mnist()
    if jax.devices()[0].platform in ("tpu", "axon"):
        extras = {
            "transformer": _safe(bench_transformer),
            "transformer_long_context": _safe(
                bench_transformer, batch=2, seq=8192, measure=6
            ),
            # TPU-first flagship long-context shape: head_dim 128 (same
            # d_model/params) fills the 128-deep MXU contraction the d=64
            # rows leave half-empty — see bench_transformer's docstring.
            # The d=64 rows above stay for r1-r4 comparability.
            "transformer_hd128": _safe(
                bench_transformer, measure=12, n_heads=8, head_dim=128
            ),
            "transformer_long_context_hd128": _safe(
                bench_transformer, batch=2, seq=8192, measure=6,
                n_heads=8, head_dim=128,
            ),
            "transformer_16k_hd128": _safe(
                bench_transformer, batch=1, seq=16384, measure=5,
                n_heads=8, head_dim=128,
            ),
            "transformer_1b": _safe(bench_transformer_1b),
            "resnet50": _safe(bench_resnet50),
            "decode_gqa": _safe(bench_decode),
            "serving": _safe(bench_serving),
            "serving_fleet": _safe(bench_serving_fleet),
            "moe": _safe(bench_moe),
            "moe_decode_routed": _safe(bench_moe_decode),
            "input_pipeline": _safe(bench_input_pipeline),
            "scheduler": _safe(bench_scheduler),
            "checkpoint": _safe(bench_checkpoint),
            "autotune": _safe(bench_autotune),
            "rollup": _safe(bench_rollup),
            "flash_attention_2k": _safe(
                bench_flash_attention, seq=2048, batch=4
            ),
            "flash_attention_8k": _safe(
                bench_flash_attention, seq=8192, batch=1
            ),
            "device": jax.devices()[0].device_kind,
        }
        # The default-config vs hd128 MFU gap (ROADMAP: 0.53 vs 0.65 —
        # the half-filled MXU tax): a derived, GATED sub-metric so
        # closing (or reopening) the gap moves --check, instead of
        # hiding in a side-by-side read of two rows.
        t = extras.get("transformer")
        t128 = extras.get("transformer_hd128")
        if (isinstance(t, dict) and isinstance(t128, dict)
                and t.get("mfu") and t128.get("mfu")):
            extras["mfu_gap"] = {
                "default_over_hd128_mfu": round(t["mfu"] / t128["mfu"], 4)
            }
    else:
        # CPU smoke stays seconds, not hours: the 200M transformer and the
        # 8k attention sweeps are TPU-only. The serving engine's micro
        # variant DOES run here — its acceptance figure (continuous
        # batching vs single-shot) is a ratio, portable across hosts.
        extras = {"skipped": "transformer/flash extras are TPU-only",
                  "serving": _safe(bench_serving, **SERVING_CPU_MICRO),
                  "serving_fleet": _safe(bench_serving_fleet),
                  "scheduler": _safe(bench_scheduler),
                  "checkpoint": _safe(bench_checkpoint),
                  "autotune": _safe(bench_autotune),
                  "rollup": _safe(bench_rollup),
                  "device": jax.devices()[0].device_kind}
    # Final aggregated telemetry snapshot (observability.metrics): the
    # instrumented train steps populate the default registry while the
    # benches above run, so the perf trajectory picks up the
    # dispatch-count/step-time series for free alongside the headline
    # numbers.
    from tony_tpu import observability

    return {
        "metric": "mnist_train_steps_per_sec_per_chip",
        "value": round(steps_per_sec_per_chip, 2),
        "unit": f"steps/sec/chip (batch={BATCH}, cnn, adam)",
        "vs_baseline": round(
            steps_per_sec_per_chip / BASELINE_STEPS_PER_SEC_PER_CHIP, 3
        ),
        "extras": extras,
        "metrics": observability.default_registry().summary(),
    }


def _load_line(path: str) -> dict:
    """A bench line from a file: either a bare JSON object or the last
    JSON-parseable line of a log (the driver's record format)."""
    with open(path) as f:
        text = f.read()
    try:
        return json.loads(text)
    except ValueError:
        pass
    for raw in reversed(text.splitlines()):
        raw = raw.strip()
        if raw.startswith("{"):
            try:
                return json.loads(raw)
            except ValueError:
                continue
    raise ValueError(f"no JSON bench line found in {path}")


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        description="tony_tpu benchmark harness / perf-regression gate"
    )
    p.add_argument("--check", action="store_true",
                   help="compare sub-metrics against the persisted "
                        "baseline; exit 1 on any >threshold drop")
    p.add_argument("--update-baseline", action="store_true",
                   help="persist this run's sub-metrics as the new "
                        "baseline for this platform")
    p.add_argument("--input", metavar="PATH",
                   help="use an existing bench JSON line instead of "
                        "running the benches")
    p.add_argument("--baseline", default=BASELINE_FILE,
                   help=f"baseline file (default {BASELINE_FILE})")
    p.add_argument("--threshold", type=float, default=DEFAULT_THRESHOLD,
                   help="fractional regression tolerance (default 0.10)")
    args = p.parse_args(argv)

    if args.input:
        line = _load_line(args.input)
    else:
        # Warm persistent compile cache: repeat bench invocations (the
        # per-PR driver rounds) skip every XLA compile that the model
        # zoo's plan-instrumented steps share with a prior round.
        from tony_tpu.parallel.plan import configure_compile_cache

        configure_compile_cache()
        line = run_benches()
        print(json.dumps(line))

    if not (args.check or args.update_baseline):
        return 0

    platform = (line.get("extras") or {}).get("device") or _bench_platform()
    current = collect_submetrics(line)
    rc = 0
    # Check BEFORE update: `--check --update-baseline` must gate against
    # the PRIOR baseline (update-first would make the check vacuous and
    # bless the very regression it was asked to catch).
    if args.check:
        baseline = load_baselines(args.baseline).get(platform, {})
        if not baseline:
            print(f"bench --check: no baseline for platform {platform!r} "
                  f"in {args.baseline}; run --update-baseline first",
                  file=sys.stderr)
        else:
            problems = check_regressions(current, baseline, args.threshold)
            for prob in problems:
                print(f"bench --check: REGRESSION {prob}", file=sys.stderr)
            if problems:
                rc = 1
            else:
                print(f"bench --check: {len(baseline)} gated metrics "
                      f"within {args.threshold * 100:.0f}% of baseline "
                      f"({platform})", file=sys.stderr)
    if args.update_baseline:
        save_baselines(platform, current, args.baseline)
        print(f"bench: baseline for {platform!r} updated "
              f"({len(current)} metrics) in {args.baseline}",
              file=sys.stderr)
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
