"""Client/CLI e2e: the full submission path — stage, spawn coordinator
subprocess, RPC monitor, finish signal — against fixture scripts, mirroring
the reference's client-driven e2e tier (TestTonyE2E.java runs TonyClient
against the mini-cluster, not the AM directly)."""

import socket
import sys
import threading
import time
from pathlib import Path

import pytest

from tony_tpu.client.cli import cluster_submit, local_submit
from tony_tpu.conf import keys
from tony_tpu.client.client import TonyClient
from tony_tpu.proxy import ProxyServer

FIXTURES = Path(__file__).parent / "fixtures"


def _base_argv(tmp_path, fixture, extra=()):
    return [
        "--executes", str(FIXTURES / fixture),
        "--framework", "jax",
        "--conf", f"{keys.K_STAGING_LOCATION}={tmp_path}/staging",
        "--conf", f"{keys.K_HISTORY_LOCATION}={tmp_path}/history",
        "--conf", "tony.application.python-binary-path=" + sys.executable,
        "--conf", "tony.am.stop-grace=0",
        *extra,
    ]


class TestClientE2E:
    def test_submit_succeeds_exit_0(self, tmp_path):
        rc = TonyClient().init(_base_argv(tmp_path, "exit_0.py")).run()
        assert rc == 0
        # History written through the client path too.
        hist = list((tmp_path / "history").rglob("*.jhist"))
        assert hist and "SUCCEEDED" in hist[0].name

    def test_submit_fails_exit_1(self, tmp_path):
        rc = TonyClient().init(_base_argv(tmp_path, "exit_1.py")).run()
        assert rc == 1

    def test_src_dir_packaging_relative_executes(self, tmp_path):
        # Job sources are zipped, shipped, unpacked by the coordinator, and
        # a *relative* entry point resolves in the unpacked workdir.
        src = tmp_path / "src"
        src.mkdir()
        (src / "main.py").write_text("import helper; helper.go()\n")
        (src / "helper.py").write_text(
            "def go():\n    print('packaged module ran')\n"
        )
        argv = [
            "--executes", "main.py",
            "--src_dir", str(src),
            "--conf", f"{keys.K_STAGING_LOCATION}={tmp_path}/staging",
            "--conf", "tony.application.python-binary-path=" + sys.executable,
            "--conf", "tony.am.stop-grace=0",
        ]
        rc = TonyClient().init(argv).run()
        assert rc == 0

    def test_multi_worker_via_cli_local(self, tmp_path):
        rc = local_submit(
            _base_argv(tmp_path, "check_jax_env.py",
                       extra=["--conf", "tony.worker.instances=2"])
        )
        assert rc == 0

    def test_cluster_submit_stages_and_cleans_framework(self, tmp_path):
        # The fixture exits nonzero unless tony_tpu resolved from a staged
        # lib-<uuid> dir, so rc==0 proves staging actually happened.
        rc = cluster_submit(_base_argv(tmp_path, "check_staged_framework.py"))
        assert rc == 0
        # Per-submission lib-<uuid> dir is owned and removed by this
        # submission only (ClusterSubmitter.java:74-80 cleanup analogue).
        assert not list((tmp_path / "staging").glob("lib-*"))

    def test_am_crash_fails_job(self, tmp_path, monkeypatch):
        """TEST_AM_CRASH makes the coordinator subprocess die mid-session;
        the client must observe the death and return nonzero — the analogue
        of TestTonyE2E.testAMCrashTonyShouldFail (:178-192). Runs through
        the client path because an in-process coordinator would os._exit
        the test runner."""
        from tony_tpu import constants

        monkeypatch.setenv(constants.TEST_AM_CRASH, "1")
        rc = TonyClient().init(_base_argv(tmp_path, "exit_0.py")).run()
        assert rc == 1

    def test_client_timeout_kills_job(self, tmp_path):
        argv = [
            "--executes", "-c 'import time; time.sleep(600)'",
            "--conf", f"{keys.K_STAGING_LOCATION}={tmp_path}/staging",
            "--conf", "tony.application.python-binary-path=" + sys.executable,
            "--conf", "tony.application.timeout=3000",
            "--conf", "tony.am.stop-grace=0",
        ]
        rc = TonyClient().init(argv).run()
        assert rc == 1


class TestProxy:
    def test_bidirectional_tunnel(self):
        # Echo server as the "notebook".
        server = socket.create_server(("127.0.0.1", 0))
        port = server.getsockname()[1]

        def echo_once():
            conn, _ = server.accept()
            data = conn.recv(1024)
            conn.sendall(b"echo:" + data)
            conn.close()

        t = threading.Thread(target=echo_once, daemon=True)
        t.start()

        proxy = ProxyServer("127.0.0.1", port, 0)
        lport = proxy.start()
        try:
            with socket.create_connection(("127.0.0.1", lport), timeout=5) as c:
                c.sendall(b"ping")
                assert c.recv(1024) == b"echo:ping"
        finally:
            proxy.stop()
            server.close()


class TestNotebookFlow:
    def test_notebook_tunnel_end_to_end(self, tmp_path):
        """Full notebook flow: submit -> executor reserves TB_PORT ->
        notebook fixture serves on it -> registered URL -> client proxy
        tunnel -> HTTP through the tunnel."""
        import logging
        import re as _re
        import urllib.request

        from tony_tpu.client import cli as cli_mod

        records = []

        class Capture(logging.Handler):
            def emit(self, record):
                records.append(record.getMessage())

        handler = Capture()
        old_level = cli_mod.log.level
        cli_mod.log.setLevel(logging.INFO)  # default effective level is
        cli_mod.log.addHandler(handler)     # WARNING under pytest
        results = []
        argv = _base_argv(tmp_path, "notebook_server.py",
                          extra=["--conf", "tony.application.timeout=90000"])
        t = threading.Thread(
            target=lambda: results.append(cli_mod.notebook_submit(argv))
        )
        t.start()
        try:
            deadline = time.time() + 60
            port = None
            while time.time() < deadline and port is None:
                for msg in records:
                    m = _re.search(r"notebook tunnel: http://localhost:(\d+)", msg)
                    if m:
                        port = int(m.group(1))
                time.sleep(0.2)
            assert port is not None, f"tunnel never appeared; logs: {records}"
            body = urllib.request.urlopen(
                f"http://localhost:{port}/", timeout=10
            ).read()
            assert body == b"notebook-alive"
            t.join(timeout=60)
            assert results == [0]
        finally:
            cli_mod.log.removeHandler(handler)
            cli_mod.log.setLevel(old_level)


class TestClusterNotebookUrl:
    """Cluster-notebook discovery (VERDICT r4 missing #3): the tunnel
    must target the notebook TASK's registered http URL — on a TPU-VM
    backend that is the REMOTE executor's host:port — with the
    coordinator-status tensorboard_url only as fallback."""

    def test_prefers_registered_task_url(self):
        from tony_tpu.client.cli import _notebook_url
        from tony_tpu.rpc import TaskUrl

        class Rpc:
            def get_task_urls(self):
                return [
                    TaskUrl("worker", 0, "file:///log"),
                    TaskUrl("notebook", 0, "http://tpu-vm-7:41213"),
                ]

            def get_application_status(self):
                raise AssertionError("fallback must not be consulted")

        assert _notebook_url(Rpc()) == "http://tpu-vm-7:41213"

    def test_falls_back_to_status_and_skips_log_urls(self):
        from tony_tpu.client.cli import _notebook_url
        from tony_tpu.rpc import TaskUrl

        class Rpc:
            def get_task_urls(self):
                # local backend: the notebook task carries its LOG url
                return [TaskUrl("notebook", 0, "file:///notebook-0.log")]

            def get_application_status(self):
                return {"tensorboard_url": "http://127.0.0.1:9999"}

        assert _notebook_url(Rpc()) == "http://127.0.0.1:9999"

    def test_transient_rpc_failure_returns_none(self):
        from tony_tpu.client.cli import _notebook_url

        class Rpc:
            def get_task_urls(self):
                raise ConnectionError("AM not up yet")

        assert _notebook_url(Rpc()) is None

    def test_register_tensorboard_pins_urlless_task(self, tmp_path):
        """Coordinator handler: a remote (url-less) task that registers
        its service URL becomes visible through get_task_urls; a local
        task keeps its log URL (history links)."""
        from tony_tpu.conf.configuration import TonyConfiguration
        from tony_tpu.coordinator.app_master import _RpcForClient
        from tony_tpu.coordinator.session import TonySession

        conf = TonyConfiguration()
        conf.set("tony.notebook.instances", 1)
        conf.set("tony.worker.instances", 1)
        conf.set("tony.ps.instances", 0)
        session = TonySession(conf, session_id=1)

        class Coord:
            pass

        from tony_tpu.observability.events import EventLog

        coord = Coord()
        coord.session = session
        coord.tensorboard_url = None
        coord.events = EventLog()
        handlers = _RpcForClient(coord)
        local = session.get_task("worker", 0)
        local.url = "file:///worker-0.log"
        handlers.register_tensorboard_url(
            "notebook:0", "http://tpu-vm-3:40001"
        )
        handlers.register_tensorboard_url(
            "worker:0", "http://should-not-clobber:1"
        )
        urls = {(u.name, u.index): u.url for u in session.task_urls()}
        assert urls[("notebook", 0)] == "http://tpu-vm-3:40001"
        assert urls[("worker", 0)] == "file:///worker-0.log"
