"""History read path + web server tests — the analogue of the reference's
history-server tier (TestParserUtils/TestHdfsUtils fixture-folder scans and
the WithBrowser smoke test, tony-history-server/test/**)."""

import json
import time
import urllib.error
import urllib.request

from tony_tpu.conf.configuration import TonyConfiguration
from tony_tpu.history import JobMetadata, setup_job_dir
from tony_tpu.history.reader import TtlCache, job_config, list_jobs
from tony_tpu.history.server import HistoryServer
from tony_tpu.history.writer import create_history_file, write_config_file


def _make_job(hist, app_id, started_ms, status="SUCCEEDED"):
    job_dir = setup_job_dir(str(hist), app_id, started_ms)
    conf = TonyConfiguration()
    conf.set("tony.application.name", f"name-of-{app_id}")
    write_config_file(job_dir, conf)
    create_history_file(job_dir, JobMetadata.new(app_id, started_ms, status))
    return job_dir


class TestReadPath:
    def test_list_jobs_newest_first_and_malformed_skipped(self, tmp_path):
        now = int(time.time() * 1000)
        _make_job(tmp_path, "application_1_0001", now - 60_000)
        _make_job(tmp_path, "application_1_0002", now, status="FAILED")
        # Malformed entries must be skipped, not crash the listing.
        bad = tmp_path / "2020" / "01" / "01" / "application_bad_x"
        bad.mkdir(parents=True)
        (bad / "nonsense.jhist").write_text("")
        (tmp_path / "2020" / "01" / "01" / "not-an-app").mkdir()

        jobs = list_jobs(tmp_path)
        assert [j.app_id for j in jobs] == [
            "application_1_0002", "application_1_0001",
        ]
        assert jobs[0].status == "FAILED"

    def test_job_config_roundtrip(self, tmp_path):
        now = int(time.time() * 1000)
        _make_job(tmp_path, "application_1_0003", now)
        cfg = job_config(tmp_path, "application_1_0003")
        assert cfg["tony.application.name"] == "name-of-application_1_0003"
        assert job_config(tmp_path, "application_9_9999") is None

    def test_malformed_jhist_variants_skipped(self, tmp_path):
        """Satellite coverage: every malformed-.jhist shape seen in the
        wild must be skipped, never raise — non-int timestamps, too few
        fields, empty stems, a .jhist that is a directory."""
        now = int(time.time() * 1000)
        _make_job(tmp_path, "application_1_0001", now)
        day = tmp_path / "2021" / "02" / "03"
        bad = day / "application_2_0001"
        bad.mkdir(parents=True)
        (bad / "application_2_0001-notanint-0-u-FAILED.jhist").write_text("")
        (bad / "too-few.jhist").write_text("")
        (bad / ".jhist").write_text("")
        (bad / "application_2_0001-1-2-u-OK.jhist.d").mkdir()
        jobs = list_jobs(tmp_path)
        assert [j.app_id for j in jobs] == ["application_1_0001"]

    def test_empty_day_directories_listed_clean(self, tmp_path):
        """Empty year/month/day trees (history locations are pre-created
        by provisioning) must list as zero jobs."""
        (tmp_path / "2024" / "01" / "01").mkdir(parents=True)
        (tmp_path / "2024" / "01" / "02").mkdir(parents=True)
        assert list_jobs(tmp_path) == []
        # an empty JOB dir (crashed before any write) is also clean
        (tmp_path / "2024" / "01" / "02" / "application_7_0001").mkdir()
        assert list_jobs(tmp_path) == []

    def test_config_without_final_status_lists_and_serves(self, tmp_path):
        """A job with config.json + .jhist but no final-status (crashed
        coordinator, or pre-observability writer) must list, serve its
        config, and 404 — not 500 — on the run-report views."""
        now = int(time.time() * 1000)
        _make_job(tmp_path, "application_5_0001", now, status="RUNNING")
        jobs = list_jobs(tmp_path)
        assert [j.app_id for j in jobs] == ["application_5_0001"]
        assert job_config(tmp_path, "application_5_0001") is not None
        from tony_tpu.history.reader import (
            job_events,
            job_final_status,
            job_trace,
        )

        assert job_final_status(tmp_path, "application_5_0001") is None
        assert job_events(tmp_path, "application_5_0001") is None
        assert job_trace(tmp_path, "application_5_0001") is None
        server = HistoryServer(str(tmp_path), port=0)
        port = server.serve_background()
        try:
            try:
                urllib.request.urlopen(
                    f"http://localhost:{port}/job/application_5_0001"
                )
                assert False, "expected 404"
            except urllib.error.HTTPError as e:
                assert e.code == 404
        finally:
            server.stop()

    def test_ttl_cache(self):
        clock = [0.0]
        cache = TtlCache(ttl_s=10.0, clock=lambda: clock[0])
        calls = []
        load = lambda: calls.append(1) or len(calls)
        assert cache.get_or_load("k", load) == 1
        assert cache.get_or_load("k", load) == 1  # cached
        clock[0] = 11.0
        assert cache.get_or_load("k", load) == 2  # expired


class TestHistoryServer:
    def test_pages_and_api(self, tmp_path):
        now = int(time.time() * 1000)
        _make_job(tmp_path, "application_2_0001", now)
        server = HistoryServer(str(tmp_path), port=0)
        port = server.serve_background()
        try:
            base = f"http://localhost:{port}"
            index = urllib.request.urlopen(f"{base}/").read().decode()
            assert "application_2_0001" in index and "SUCCEEDED" in index

            page = urllib.request.urlopen(
                f"{base}/config/application_2_0001"
            ).read().decode()
            assert "name-of-application_2_0001" in page

            jobs = json.loads(
                urllib.request.urlopen(f"{base}/api/jobs").read()
            )
            assert jobs[0]["app_id"] == "application_2_0001"

            cfg = json.loads(urllib.request.urlopen(
                f"{base}/api/config/application_2_0001"
            ).read())
            assert cfg["tony.application.name"] == "name-of-application_2_0001"

            try:
                urllib.request.urlopen(f"{base}/config/application_9_9")
                assert False, "expected 404"
            except urllib.error.HTTPError as e:
                assert e.code == 404
        finally:
            server.stop()

    def test_per_job_run_stats_page(self, tmp_path):
        """The /job/<id> page renders the coordinator's terminal record:
        state, run stats, slice plans, per-task exits — the VERDICT r2
        item 7 page; /api/job/<id> serves the raw record."""
        from tony_tpu.history.writer import write_final_status

        now = int(time.time() * 1000)
        job_dir = _make_job(tmp_path, "application_3_0001", now,
                            status="FAILED")
        write_final_status(job_dir, {
            "state": "FAILED",
            "stats": {
                "sessions_run": 2,
                "tasks_failed": 1,
                "heartbeat_missed_tasks": ["worker:1"],
                "wall_ms": 61_500,
            },
            "slices": {"worker": {
                "accelerator_type": "v5litepod-16", "num_slices": 2,
                "hosts_per_slice": 4, "chips_per_slice": 16,
            }},
            "tasks": [
                {"id": "worker:0", "exit_code": 0},
                {"id": "worker:1", "exit_code": 1},
            ],
        })
        server = HistoryServer(str(tmp_path), port=0)
        port = server.serve_background()
        try:
            base = f"http://localhost:{port}"
            page = urllib.request.urlopen(
                f"{base}/job/application_3_0001"
            ).read().decode()
            for needle in ("FAILED", "sessions run", ">2<", "tasks failed",
                           "worker:1", "61.5 s", "v5litepod-16",
                           "worker:0"):
                assert needle in page, needle
            # jobs table links to the per-job page
            index = urllib.request.urlopen(f"{base}/").read().decode()
            assert "/job/application_3_0001" in index

            api = json.loads(urllib.request.urlopen(
                f"{base}/api/job/application_3_0001"
            ).read())
            assert api["stats"]["sessions_run"] == 2
            try:
                urllib.request.urlopen(f"{base}/job/application_9_9")
                assert False, "expected 404"
            except urllib.error.HTTPError as e:
                assert e.code == 404
        finally:
            server.stop()

    def test_job_page_timeline_metrics_and_tensorboard(self, tmp_path):
        """The observability additions to the per-job page: the lifecycle
        timeline from events.jsonl, the final aggregated metric summary,
        and the persisted TensorBoard link (previously the URL lived only
        in coordinator memory); /api/events serves the raw timeline."""
        from tony_tpu.history.writer import (
            write_events_file,
            write_final_status,
        )

        now = int(time.time() * 1000)
        job_dir = _make_job(tmp_path, "application_6_0001", now)
        write_final_status(job_dir, {
            "state": "SUCCEEDED",
            "stats": {"sessions_run": 1, "tasks_failed": 0, "wall_ms": 100},
            "tensorboard_url": "http://tb-host:6006",
            "metrics": {
                "heartbeats": {"worker:0": 9},
                "tasks": {"worker:0": {
                    "counters": {"train_steps_total": 5},
                    "gauges": {"loss": 0.25},
                }},
            },
        })
        write_events_file(job_dir, [
            {"ts_ms": now, "kind": "task_registered", "task": "worker:0"},
            {"ts_ms": now + 10, "kind": "rendezvous_released", "tasks": 1},
            {"ts_ms": now + 20, "kind": "final_status",
             "state": "SUCCEEDED"},
        ])
        server = HistoryServer(str(tmp_path), port=0)
        port = server.serve_background()
        try:
            base = f"http://localhost:{port}"
            page = urllib.request.urlopen(
                f"{base}/job/application_6_0001"
            ).read().decode()
            for needle in ("Timeline", "rendezvous_released",
                           "Final metrics", "train_steps_total",
                           "http://tb-host:6006"):
                assert needle in page, needle
            api = json.loads(urllib.request.urlopen(
                f"{base}/api/events/application_6_0001"
            ).read())
            assert [e["kind"] for e in api] == [
                "task_registered", "rendezvous_released", "final_status",
            ]
            try:
                urllib.request.urlopen(f"{base}/api/events/application_9_9")
                assert False, "expected 404"
            except urllib.error.HTTPError as e:
                assert e.code == 404
        finally:
            server.stop()

    def test_job_supplied_tensorboard_url_scheme_gated(self, tmp_path):
        """register_tensorboard_url is job-controlled: a javascript: URL
        must render as text, never as a clickable link in the history
        server's origin."""
        from tony_tpu.history.writer import write_final_status

        now = int(time.time() * 1000)
        job_dir = _make_job(tmp_path, "application_6_0002", now)
        write_final_status(job_dir, {
            "state": "SUCCEEDED",
            "tensorboard_url": "javascript:alert(1)",
        })
        server = HistoryServer(str(tmp_path), port=0)
        port = server.serve_background()
        try:
            page = urllib.request.urlopen(
                f"http://localhost:{port}/job/application_6_0002"
            ).read().decode()
            assert "javascript:alert(1)" in page  # visible as text
            assert "href='javascript" not in page and \
                   'href="javascript' not in page
        finally:
            server.stop()

    def test_secrets_redacted_in_history_and_responses(self, tmp_path):
        """ADVICE r1 (medium): the history path must never expose
        tony.secret.key — anyone reading it could authenticate to a live
        job's RPC. Redacted at write time AND at serve time."""
        now = int(time.time() * 1000)
        job_dir = setup_job_dir(str(tmp_path), "application_3_0001", now)
        conf = TonyConfiguration()
        conf.set("tony.secret.key", "hunter2")
        write_config_file(job_dir, conf)
        create_history_file(
            job_dir, JobMetadata.new("application_3_0001", now, "SUCCEEDED")
        )
        on_disk = (job_dir / "config.json").read_text()
        assert "hunter2" not in on_disk

        # serve-time defense in depth: plant an unredacted legacy config
        legacy = json.loads(on_disk)
        legacy["tony.secret.key"] = "hunter2"
        (job_dir / "config.json").write_text(json.dumps(legacy))
        server = HistoryServer(str(tmp_path), port=0)
        port = server.serve_background()
        try:
            body = urllib.request.urlopen(
                f"http://localhost:{port}/api/config/application_3_0001"
            ).read().decode()
            assert "hunter2" not in body and "<redacted>" in body
        finally:
            server.stop()

    def test_shell_env_values_redacted_names_kept(self):
        """--shell_env values routinely carry tokens the key-name heuristic
        can't see (HF_TOKEN=...); names stay browsable, values do not."""
        from tony_tpu.history.writer import redact_config

        out = redact_config({
            "tony.application.shell-env": "HF_TOKEN=supersecret,MODE=fast",
            "tony.worker.env": "API_KEY=abc",
            "tony.application.name": "keepme",
        })
        assert "supersecret" not in str(out) and "abc" not in str(out)
        assert out["tony.application.shell-env"].startswith("HF_TOKEN=<redacted>")
        assert out["tony.application.name"] == "keepme"

    def test_binds_localhost_by_default(self, tmp_path):
        server = HistoryServer(str(tmp_path), port=0)
        assert server.httpd.server_address[0] == "127.0.0.1"
        server.stop()

    def test_from_conf_port_selection(self, tmp_path):
        from tony_tpu.conf import keys
        import pytest

        conf = TonyConfiguration()
        conf.set(keys.K_HISTORY_LOCATION, str(tmp_path))
        with pytest.raises(ValueError, match="disabled"):
            HistoryServer.from_conf(conf)  # default http.port=disabled
        conf.set(keys.K_HTTP_PORT, "0")
        server = HistoryServer.from_conf(conf)
        assert server.scheme == "http"
        server.stop()

    def test_https_with_pem_pair(self, tmp_path):
        """tony.https.cert/key serve TLS (keystore analogue,
        TonyConfigurationKeys.java:41-63)."""
        import ssl
        import subprocess

        from tony_tpu.conf import keys

        cert, key = tmp_path / "c.pem", tmp_path / "k.pem"
        subprocess.run(
            ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
             "-keyout", str(key), "-out", str(cert), "-days", "1",
             "-subj", "/CN=localhost"],
            check=True, capture_output=True,
        )
        now = int(time.time() * 1000)
        _make_job(tmp_path / "hist", "application_4_0001", now)
        conf = TonyConfiguration()
        conf.set(keys.K_HISTORY_LOCATION, str(tmp_path / "hist"))
        conf.set(keys.K_HTTPS_PORT, 0)
        conf.set(keys.K_HTTPS_CERT, str(cert))
        conf.set(keys.K_HTTPS_KEY, str(key))
        server = HistoryServer.from_conf(conf)
        assert server.scheme == "https"
        port = server.serve_background()
        try:
            ctx = ssl.create_default_context()
            ctx.check_hostname = False
            ctx.verify_mode = ssl.CERT_NONE
            body = urllib.request.urlopen(
                f"https://localhost:{port}/api/jobs", context=ctx
            ).read()
            assert b"application_4_0001" in body
        finally:
            server.stop()
