"""Checkpoint/resume: unit tests for the async per-process-sharded
CheckpointManager and the restore-on-retry e2e the reference's AM-retry
resume path implies (SURVEY §5.4; session retry is
TonyApplicationMaster.reset:526-542)."""

import sys
import time
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tony_tpu.checkpoint import CheckpointManager
from tony_tpu.conf import keys
from tony_tpu.coordinator.session import SessionStatus
from tony_tpu.mini import MiniTonyCluster

FIXTURES = Path(__file__).resolve().parent / "fixtures"


def _state(val: float):
    return {
        "step": jnp.asarray(int(val), jnp.int32),
        "params": {"w": jnp.full((8, 4), val), "b": jnp.zeros(4)},
    }


def test_roundtrip(tmp_path):
    mgr = CheckpointManager(tmp_path)
    mgr.save(3, _state(3.0), blocking=True)
    out = mgr.restore(_state(0.0))
    assert int(out["step"]) == 3
    np.testing.assert_array_equal(np.asarray(out["params"]["w"]), 3.0)


def test_async_save_is_durable_after_wait(tmp_path):
    mgr = CheckpointManager(tmp_path)
    mgr.save(1, _state(1.0))  # async
    mgr.wait()
    assert mgr.latest_step() == 1


def test_latest_complete_wins_and_torn_writes_ignored(tmp_path):
    mgr = CheckpointManager(tmp_path)
    mgr.save(1, _state(1.0), blocking=True)
    mgr.save(2, _state(2.0), blocking=True)
    # a torn/incomplete step: dir without metadata must be invisible
    (tmp_path / "step_9").mkdir()
    (tmp_path / "step_9" / ".tmp_process_0.npz").write_bytes(b"garbage")
    assert mgr.latest_step() == 2
    assert int(mgr.restore(_state(0.0))["step"]) == 2


def test_multiprocess_checkpoint_incomplete_until_all_written(tmp_path):
    p0 = CheckpointManager(tmp_path, process_id=0, num_processes=2)
    p1 = CheckpointManager(tmp_path, process_id=1, num_processes=2)
    p0.save(1, _state(1.0), blocking=True)
    assert p0.latest_step() is None  # process 1 hasn't written
    p1.save(1, _state(1.5), blocking=True)
    assert p0.latest_step() == 1
    # each process restores its own shard file
    assert float(p1.restore(_state(0.0))["params"]["w"][0, 0]) == 1.5


def test_gc_keeps_newest(tmp_path):
    mgr = CheckpointManager(tmp_path, max_to_keep=2)
    for s in (1, 2, 3, 4):
        mgr.save(s, _state(float(s)), blocking=True)
    assert mgr._complete_steps() == [3, 4]


def test_bfloat16_roundtrips_exactly(tmp_path):
    """np.savez corrupts ml_dtypes (bf16 -> void); the byte+manifest
    encoding must restore the exact dtype and values."""
    state = {"w": jnp.asarray([1.5, -2.25, 3.0], jnp.bfloat16),
             "step": jnp.asarray(4, jnp.int32)}
    mgr = CheckpointManager(tmp_path)
    mgr.save(1, state, blocking=True)
    out = mgr.restore(state)
    assert out["w"].dtype == jnp.bfloat16
    np.testing.assert_array_equal(
        np.asarray(out["w"], np.float32), [1.5, -2.25, 3.0]
    )


def test_async_writer_failure_raises_on_wait(tmp_path, monkeypatch):
    """A failed background write must surface, not silently drop the
    checkpoint."""
    import tony_tpu.checkpoint as ckpt

    def boom(path, tmp, data):
        raise OSError("disk full")

    monkeypatch.setattr(ckpt, "_fsync_write", boom)
    mgr = CheckpointManager(tmp_path)
    mgr.save(1, _state(1.0))  # async
    with pytest.raises(RuntimeError, match="checkpoint write failed"):
        mgr.wait()
    # the failure is consumed; the manager is usable again
    monkeypatch.undo()
    mgr.save(2, _state(2.0), blocking=True)
    assert mgr.latest_step() == 2


def test_explicit_step_missing_or_torn_returns_none(tmp_path):
    mgr = CheckpointManager(tmp_path)
    mgr.save(1, _state(1.0), blocking=True)
    assert mgr.restore(_state(0.0), step=7) is None
    # torn: dir exists but no metadata
    (tmp_path / "step_7").mkdir()
    assert mgr.restore(_state(0.0), step=7) is None


def test_gc_reclaims_old_torn_dirs(tmp_path):
    mgr = CheckpointManager(tmp_path, max_to_keep=2, torn_gc_grace_s=0.0)
    mgr.save(1, _state(1.0), blocking=True)
    # a crash leftover older than the kept window
    (tmp_path / "step_0").mkdir()
    (tmp_path / "step_0" / ".tmp_process_0.npz").write_bytes(b"torn")
    time.sleep(0.01)  # let the leftover age past the (zero) grace window
    for s in (2, 3):
        mgr.save(s, _state(float(s)), blocking=True)
    assert mgr._complete_steps() == [2, 3]
    assert not (tmp_path / "step_0").exists()


def test_gc_spares_recently_written_torn_dirs(tmp_path):
    """A torn dir still being written (recent mtime) survives GC: process 0
    must not rmtree a straggler's in-flight older-step write."""
    mgr = CheckpointManager(tmp_path, max_to_keep=2, torn_gc_grace_s=3600.0)
    mgr.save(1, _state(1.0), blocking=True)
    (tmp_path / "step_0").mkdir()
    (tmp_path / "step_0" / ".tmp_process_1.npz").write_bytes(b"in flight")
    for s in (2, 3):
        mgr.save(s, _state(float(s)), blocking=True)
    assert (tmp_path / "step_0").exists()


def test_cross_topology_restore_raises_not_truncates(tmp_path):
    """A 1-process restore of a checkpoint whose leaves are per-process
    SHARDS (different topology) must raise, not silently hand back
    wrong-shaped arrays (found live: a standalone serving job restoring a
    2-process training checkpoint got half of every sharded leaf)."""
    # Simulate a shard file: the saved piece is half the template leaf.
    half = {"w": jnp.ones((4, 2))}
    CheckpointManager(tmp_path, process_id=0, num_processes=1).save(
        1, half, blocking=True
    )
    full_template = {"w": jnp.zeros((8, 2))}
    with pytest.raises(ValueError, match="topology"):
        CheckpointManager(tmp_path).restore(full_template)


def test_structure_mismatch_raises(tmp_path):
    mgr = CheckpointManager(tmp_path)
    mgr.save(1, _state(1.0), blocking=True)
    with pytest.raises(ValueError, match="structure changed"):
        mgr.restore({"totally": jnp.zeros(2)})


def test_restore_preserves_sharding(tmp_path):
    """Restored leaves land with the template's NamedSharding — the
    per-process sharded restore the multi-chip path needs."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from tony_tpu.parallel.mesh import MeshSpec, build_mesh

    mesh = build_mesh(MeshSpec(dp=8), devices=jax.devices()[:8])
    sharding = NamedSharding(mesh, P("dp"))
    state = {"w": jax.device_put(jnp.arange(16.0), sharding)}
    mgr = CheckpointManager(tmp_path)
    mgr.save(1, state, blocking=True)
    out = mgr.restore(state)
    assert out["w"].sharding == sharding
    np.testing.assert_array_equal(np.asarray(out["w"]), np.arange(16.0))


def test_trainstate_roundtrip_on_mesh(tmp_path):
    """The real thing: a make_train_step TrainState (step + params +
    adamw opt_state, sharded over a dp×tp mesh) survives save→restore with
    values and shardings intact, mid-training."""
    from tony_tpu.models import TransformerConfig, make_train_step
    from tony_tpu.parallel.mesh import MeshSpec, build_mesh

    cfg = TransformerConfig(
        vocab_size=64, d_model=32, n_layers=2, n_heads=2, head_dim=16,
        d_ff=64, max_seq=32, dtype="float32", remat=False,
    )
    mesh = build_mesh(MeshSpec(dp=2, tp=2), devices=jax.devices()[:4])
    init_fn, step_fn = make_train_step(cfg, mesh)
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, 64, (4, 17)), jnp.int32
    )
    with jax.sharding.set_mesh(mesh):
        state = init_fn(jax.random.key(0))
        state, _ = step_fn(state, tokens)
        mgr = CheckpointManager(tmp_path)
        mgr.save(int(state.step), state, blocking=True)
        restored = mgr.restore(state)
        assert int(restored.step) == int(state.step) == 1
        for a, b in zip(jax.tree.leaves(state), jax.tree.leaves(restored)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b))
            assert a.sharding == b.sharding
        # training continues from the restored state
        resumed, metrics = step_fn(restored, tokens)
        assert int(resumed.step) == 2 and np.isfinite(float(metrics["loss"]))


def test_sharded_save_restore_across_processes_e2e(tmp_path):
    """2 executor processes checkpoint a global array neither fully owns:
    per-process shard files, completeness gating, and
    make_array_from_single_device_arrays reassembly on restore."""
    cluster = MiniTonyCluster(tmp_path / "cluster")
    conf = cluster.base_conf()
    conf.set(keys.K_FRAMEWORK, "jax")
    conf.set(keys.K_EXECUTES, str(FIXTURES / "ckpt_sharded.py"))
    conf.set(keys.K_PYTHON_BINARY, sys.executable)
    conf.set(keys.instances_key("worker"), 2)
    conf.set(keys.instances_key("ps"), 0)
    conf.set(keys.K_SHELL_ENV, f"CKPT_DIR={tmp_path}/ckpt")
    status, coord = cluster.run_job(conf, timeout_s=300)
    assert status is SessionStatus.SUCCEEDED, coord.session.diagnostics


def test_resnet_gang_fault_restart_e2e(tmp_path):
    """BASELINE config 5 (CI-scaled): 2 gang-scheduled workers train the
    in-framework ResNet; worker 0 crashes mid-run, the whole session
    restarts, both workers resume from checkpoints and finish."""
    cluster = MiniTonyCluster(tmp_path / "cluster")
    conf = cluster.base_conf()
    conf.set(keys.K_FRAMEWORK, "jax")
    conf.set(keys.K_EXECUTES, str(FIXTURES / "resnet_train.py"))
    conf.set(keys.K_PYTHON_BINARY, sys.executable)
    conf.set(keys.instances_key("worker"), 2)
    conf.set(keys.instances_key("ps"), 0)
    conf.set(keys.K_AM_RETRY_COUNT, 1)
    conf.set(keys.K_SHELL_ENV, f"CKPT_DIR={tmp_path}/ckpt")
    status, coord = cluster.run_job(conf, timeout_s=600)
    assert status is SessionStatus.SUCCEEDED, coord.session.diagnostics
    assert coord.session.session_id == 2  # fault-restarted once


# ---------------------------------------------------------------------------
# Object-store (gs://) checkpointing — VERDICT r3 missing #2: per-object
# PUTs are atomic, metadata.json is the commit marker, completeness is
# reader-side. Runs over FileObjectStorage (the MiniDFS analogue).
# ---------------------------------------------------------------------------

@pytest.fixture
def gcs_emulator(tmp_path):
    from tony_tpu.cloud import set_default_storage
    from tony_tpu.cloud.gcs import FileObjectStorage

    store = FileObjectStorage(tmp_path / "objects")
    set_default_storage(store)
    yield store
    set_default_storage(None)


def test_gs_roundtrip_and_bf16(gcs_emulator):
    state = {"w": jnp.asarray([1.5, -2.25, 3.0], jnp.bfloat16),
             "step": jnp.asarray(7, jnp.int32)}
    mgr = CheckpointManager("gs://ckpts/job1")
    mgr.save(7, state, blocking=True)
    out = mgr.restore(state)
    assert out["w"].dtype == jnp.bfloat16 and int(out["step"]) == 7
    np.testing.assert_array_equal(
        np.asarray(out["w"], np.float32), [1.5, -2.25, 3.0]
    )
    # no tmp objects: atomic PUTs need no rename dance
    keys_ = gcs_emulator.list_prefix("gs://ckpts/job1/")
    assert sorted(keys_) == ["job1/step_7/metadata.json",
                             "job1/step_7/process_0.npz"]


def test_gs_commit_marker_gates_completeness(gcs_emulator):
    p0 = CheckpointManager("gs://ckpts/j", process_id=0, num_processes=2)
    p1 = CheckpointManager("gs://ckpts/j", process_id=1, num_processes=2)
    p0.save(1, _state(1.0), blocking=True)
    assert p0.latest_step() is None  # marker present, shard 1 missing
    p1.save(1, _state(1.5), blocking=True)
    assert p0.latest_step() == 1
    assert float(p1.restore(_state(0.0))["params"]["w"][0, 0]) == 1.5


def test_gs_gc_reclaims_torn_prefixes(gcs_emulator):
    mgr = CheckpointManager("gs://ckpts/g", max_to_keep=2,
                            torn_gc_grace_s=0.0)
    mgr.save(1, _state(1.0), blocking=True)
    # a crash leftover: shard object without its commit marker
    gcs_emulator.put_bytes("gs://ckpts/g/step_0/process_0.npz", b"torn")
    time.sleep(0.01)
    for s in (2, 3):
        mgr.save(s, _state(float(s)), blocking=True)
    assert mgr._complete_steps() == [2, 3]
    assert not gcs_emulator.exists("gs://ckpts/g/step_0/process_0.npz")
    # max_to_keep pruned step 1's objects too
    assert not gcs_emulator.exists("gs://ckpts/g/step_1/metadata.json")


def test_gs_recent_torn_prefix_survives_gc(gcs_emulator):
    mgr = CheckpointManager("gs://ckpts/r", max_to_keep=2,
                            torn_gc_grace_s=3600.0)
    mgr.save(1, _state(1.0), blocking=True)
    gcs_emulator.put_bytes("gs://ckpts/r/step_0/process_0.npz", b"inflight")
    for s in (2, 3):
        mgr.save(s, _state(float(s)), blocking=True)
    assert gcs_emulator.exists("gs://ckpts/r/step_0/process_0.npz")


def test_gs_restore_on_session_retry_e2e(tmp_path):
    """Resume-on-retry against the object store: session 1 checkpoints to
    gs:// and crashes at step 5; the retried session restores from the
    bucket and finishes — no filesystem anywhere in the checkpoint path."""
    cluster = MiniTonyCluster(tmp_path / "cluster")
    conf = cluster.base_conf()
    conf.set(keys.K_FRAMEWORK, "jax")
    conf.set(keys.K_EXECUTES, str(FIXTURES / "ckpt_train.py"))
    conf.set(keys.K_PYTHON_BINARY, sys.executable)
    conf.set(keys.instances_key("worker"), 1)
    conf.set(keys.instances_key("ps"), 0)
    conf.set(keys.K_AM_RETRY_COUNT, 1)
    conf.set(
        keys.K_SHELL_ENV,
        "CKPT_DIR=gs://ckpts/retry,"
        f"TONY_GCS_EMULATOR_DIR={tmp_path / 'objects'}",
    )
    status, coord = cluster.run_job(conf, timeout_s=180)
    assert status is SessionStatus.SUCCEEDED, coord.session.diagnostics
    assert coord.session.session_id == 2
    import os

    os.environ["TONY_GCS_EMULATOR_DIR"] = str(tmp_path / "objects")
    try:
        from tony_tpu.cloud import set_default_storage

        set_default_storage(None)  # rebuild from the env var
        assert CheckpointManager("gs://ckpts/retry").latest_step() == 10
    finally:
        del os.environ["TONY_GCS_EMULATOR_DIR"]
        set_default_storage(None)


def test_restore_on_session_retry_e2e(tmp_path):
    """Full-stack resume: session 1 checkpoints every step and crashes at
    step 5; the retried session restores from step 5 and finishes — the
    orchestrator-restart + checkpoint contract of SURVEY §5.4."""
    cluster = MiniTonyCluster(tmp_path / "cluster")
    conf = cluster.base_conf()
    conf.set(keys.K_FRAMEWORK, "jax")
    conf.set(keys.K_EXECUTES, str(FIXTURES / "ckpt_train.py"))
    conf.set(keys.K_PYTHON_BINARY, sys.executable)
    conf.set(keys.instances_key("worker"), 1)
    conf.set(keys.instances_key("ps"), 0)
    conf.set(keys.K_AM_RETRY_COUNT, 1)
    conf.set(keys.K_SHELL_ENV, f"CKPT_DIR={tmp_path}/ckpt")
    status, coord = cluster.run_job(conf, timeout_s=180)
    assert status is SessionStatus.SUCCEEDED, coord.session.diagnostics
    assert coord.session.session_id == 2  # second session finished the job
    # checkpoints survive: step 10 is the newest complete one
    assert CheckpointManager(tmp_path / "ckpt").latest_step() == 10
