"""Checkpoint/resume: unit tests for the async per-process-sharded
CheckpointManager and the restore-on-retry e2e the reference's AM-retry
resume path implies (SURVEY §5.4; session retry is
TonyApplicationMaster.reset:526-542)."""

import sys
import time
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tony_tpu.checkpoint import CheckpointManager
from tony_tpu.conf import keys
from tony_tpu.coordinator.session import SessionStatus
from tony_tpu.mini import MiniTonyCluster

FIXTURES = Path(__file__).resolve().parent / "fixtures"


def _state(val: float):
    # Every leaf varies with ``val`` on purpose: consecutive saves of
    # _state(s) then share no unchanged bytes, so the differential
    # planner writes them full and the legacy GC/completeness tests keep
    # their exact step sets. Partially-static trees (where diffs and
    # donor protection engage) get their own tests below.
    return {
        "step": jnp.asarray(int(val), jnp.int32),
        "params": {"w": jnp.full((8, 4), val),
                   "b": jnp.full(4, val / 2.0)},
    }


def test_roundtrip(tmp_path):
    mgr = CheckpointManager(tmp_path)
    mgr.save(3, _state(3.0), blocking=True)
    out = mgr.restore(_state(0.0))
    assert int(out["step"]) == 3
    np.testing.assert_array_equal(np.asarray(out["params"]["w"]), 3.0)


def test_save_records_snapshot_stall_metric(tmp_path):
    """Every save observes its synchronous D2H snapshot phase into
    ``tony_ckpt_snapshot_ms`` (the save-stall the train loop pays — the
    batched-transfer satellite's observable)."""
    from tony_tpu.checkpoint import CKPT_SNAPSHOT_HISTOGRAM
    from tony_tpu.observability.metrics import default_registry

    def count():
        h = default_registry().snapshot()["histograms"].get(
            CKPT_SNAPSHOT_HISTOGRAM
        )
        return 0 if h is None else h["count"]

    before = count()
    mgr = CheckpointManager(tmp_path)
    mgr.save(1, _state(1.0), blocking=True)
    mgr.save(2, _state(2.0))
    mgr.wait()
    assert count() == before + 2


def test_saved_num_processes_tolerates_corrupt_metadata(tmp_path):
    """A corrupt metadata.json (unparseable, or parsing to a non-dict,
    or carrying a non-numeric num_processes) must fall back to the
    ambient process count, not abort the restore."""
    mgr = CheckpointManager(tmp_path, num_processes=3)
    for corrupt in (
        b"{not json",            # unparseable
        b"[1, 2]",               # parses to a list
        b'"just a string"',      # parses to a string
        b"17",                   # parses to a number
        b'{"num_processes": "x"}',   # non-numeric value
        b'{"num_processes": null}',  # null value
    ):
        mgr._store.put_file(7, "metadata.json", corrupt)
        assert mgr._saved_num_processes(7) == 3, corrupt
    # And an honest file still wins.
    mgr._store.put_file(7, "metadata.json", b'{"num_processes": 5}')
    assert mgr._saved_num_processes(7) == 5


def test_async_save_is_durable_after_wait(tmp_path):
    mgr = CheckpointManager(tmp_path)
    mgr.save(1, _state(1.0))  # async
    mgr.wait()
    assert mgr.latest_step() == 1


def test_latest_complete_wins_and_torn_writes_ignored(tmp_path):
    mgr = CheckpointManager(tmp_path)
    mgr.save(1, _state(1.0), blocking=True)
    mgr.save(2, _state(2.0), blocking=True)
    # a torn/incomplete step: dir without metadata must be invisible
    (tmp_path / "step_9").mkdir()
    (tmp_path / "step_9" / ".tmp_process_0.npz").write_bytes(b"garbage")
    assert mgr.latest_step() == 2
    assert int(mgr.restore(_state(0.0))["step"]) == 2


def test_multiprocess_checkpoint_incomplete_until_all_written(tmp_path):
    p0 = CheckpointManager(tmp_path, process_id=0, num_processes=2)
    p1 = CheckpointManager(tmp_path, process_id=1, num_processes=2)
    p0.save(1, _state(1.0), blocking=True)
    assert p0.latest_step() is None  # process 1 hasn't written
    p1.save(1, _state(1.5), blocking=True)
    assert p0.latest_step() == 1
    # each process restores its own shard file
    assert float(p1.restore(_state(0.0))["params"]["w"][0, 0]) == 1.5


def test_gc_keeps_newest(tmp_path):
    mgr = CheckpointManager(tmp_path, max_to_keep=2)
    for s in (1, 2, 3, 4):
        mgr.save(s, _state(float(s)), blocking=True)
    assert mgr._complete_steps() == [3, 4]


def test_bfloat16_roundtrips_exactly(tmp_path):
    """np.savez corrupts ml_dtypes (bf16 -> void); the byte+manifest
    encoding must restore the exact dtype and values."""
    state = {"w": jnp.asarray([1.5, -2.25, 3.0], jnp.bfloat16),
             "step": jnp.asarray(4, jnp.int32)}
    mgr = CheckpointManager(tmp_path)
    mgr.save(1, state, blocking=True)
    out = mgr.restore(state)
    assert out["w"].dtype == jnp.bfloat16
    np.testing.assert_array_equal(
        np.asarray(out["w"], np.float32), [1.5, -2.25, 3.0]
    )


def test_async_writer_failure_raises_on_wait(tmp_path, monkeypatch):
    """A failed background write must surface, not silently drop the
    checkpoint."""
    import tony_tpu.checkpoint.stores as ckpt_stores

    def boom(path, tmp, data):
        raise OSError("disk full")

    monkeypatch.setattr(ckpt_stores, "_fsync_write", boom)
    mgr = CheckpointManager(tmp_path)
    mgr.save(1, _state(1.0))  # async
    with pytest.raises(RuntimeError, match="checkpoint write failed"):
        mgr.wait()
    # the failure is consumed; the manager is usable again
    monkeypatch.undo()
    mgr.save(2, _state(2.0), blocking=True)
    assert mgr.latest_step() == 2


def test_explicit_step_missing_or_torn_returns_none(tmp_path):
    mgr = CheckpointManager(tmp_path)
    mgr.save(1, _state(1.0), blocking=True)
    assert mgr.restore(_state(0.0), step=7) is None
    # torn: dir exists but no metadata
    (tmp_path / "step_7").mkdir()
    assert mgr.restore(_state(0.0), step=7) is None


def test_gc_reclaims_old_torn_dirs(tmp_path):
    mgr = CheckpointManager(tmp_path, max_to_keep=2, torn_gc_grace_s=0.0)
    mgr.save(1, _state(1.0), blocking=True)
    # a crash leftover older than the kept window
    (tmp_path / "step_0").mkdir()
    (tmp_path / "step_0" / ".tmp_process_0.npz").write_bytes(b"torn")
    time.sleep(0.01)  # let the leftover age past the (zero) grace window
    for s in (2, 3):
        mgr.save(s, _state(float(s)), blocking=True)
    assert mgr._complete_steps() == [2, 3]
    assert not (tmp_path / "step_0").exists()


def test_gc_spares_recently_written_torn_dirs(tmp_path):
    """A torn dir still being written (recent mtime) survives GC: process 0
    must not rmtree a straggler's in-flight older-step write."""
    mgr = CheckpointManager(tmp_path, max_to_keep=2, torn_gc_grace_s=3600.0)
    mgr.save(1, _state(1.0), blocking=True)
    (tmp_path / "step_0").mkdir()
    (tmp_path / "step_0" / ".tmp_process_1.npz").write_bytes(b"in flight")
    for s in (2, 3):
        mgr.save(s, _state(float(s)), blocking=True)
    assert (tmp_path / "step_0").exists()


def test_global_shape_mismatch_raises_not_truncates(tmp_path):
    """Restoring into a template whose GLOBAL leaf shape differs from the
    checkpoint's must raise, not silently hand back wrong-shaped arrays
    (found live pre-r5: a serving job restoring a sharded training
    checkpoint got half of every leaf; now topology differences reassemble
    and only genuine model-definition changes raise)."""
    half = {"w": jnp.ones((4, 2))}
    CheckpointManager(tmp_path, process_id=0, num_processes=1).save(
        1, half, blocking=True
    )
    full_template = {"w": jnp.zeros((8, 2))}
    with pytest.raises(ValueError, match="does not match the template"):
        CheckpointManager(tmp_path).restore(full_template)


def _write_slab_checkpoint(directory, step, slabs, *, extra_leaf=None,
                           store=None):
    """Hand-craft a multi-process slab checkpoint in the manager's on-disk
    format — a format-contract pin that lets single-process tests exercise
    the cross-topology reassembly path (a real cross-process array cannot
    exist in one test process; the mini-cluster e2e covers the real one).
    ``slabs``: list per process of {key: (piece, [[start, stop], ...],
    global_shape)}. ``extra_leaf``: (key, full_array) replicated full-span
    in every process file (the way replicated params are saved).
    ``store``: optional step store (e.g. _ObjectCheckpointStore for the
    gs:// twin); default is the filesystem store over ``directory``."""
    import io as _io
    import json as _json

    from tony_tpu.checkpoint import _MANIFEST, _FsCheckpointStore, _encode

    store = store or _FsCheckpointStore(directory)
    n = len(slabs)
    for pid, leaves in enumerate(slabs):
        leaves = dict(leaves)
        if extra_leaf is not None:
            k, arr = extra_leaf
            leaves[k] = (arr, [[0, d] for d in arr.shape], arr.shape)
        manifest, blobs = {}, {}
        for key, (piece, index, gshape) in leaves.items():
            piece = np.asarray(piece)
            manifest[key] = {
                "dtype": str(piece.dtype),
                "shape": list(gshape),
                "num_shards": 1,
                "shard_shapes": [list(piece.shape)],
                "shard_indices": [index],
            }
            blobs[f"{key}#s0"] = _encode(piece)
        buf = _io.BytesIO()
        np.savez(buf, **blobs, **{_MANIFEST: np.frombuffer(
            _json.dumps(manifest).encode(), dtype=np.uint8)})
        store.put_file(step, f"process_{pid}.npz", buf.getvalue())
    store.put_file(step, "metadata.json", _json.dumps(
        {"step": step, "num_processes": n}).encode())


def test_cross_topology_restore_to_single_process(tmp_path):
    """The train-on-a-slice / serve-on-one-host lifecycle: a 2-process
    slab checkpoint restores into a 1-process full template, every leaf
    reassembled exactly from all shard files (VERDICT r4 missing #1 — the
    reference got this from TF full-tensor checkpoints,
    tony-examples/mnist-tensorflow/mnist_distributed.py:46-48)."""
    w = np.arange(16.0, dtype=np.float32).reshape(8, 2)
    b = np.asarray([9.0, -3.0], np.float32)
    _write_slab_checkpoint(
        tmp_path, 4,
        [{"['w']": (w[:4], [[0, 4], [0, 2]], (8, 2))},
         {"['w']": (w[4:], [[4, 8], [0, 2]], (8, 2))}],
        extra_leaf=("['b']", b),
    )
    out = CheckpointManager(tmp_path).restore(
        {"w": jnp.zeros((8, 2)), "b": jnp.zeros(2)}
    )
    np.testing.assert_array_equal(np.asarray(out["w"]), w)
    np.testing.assert_array_equal(np.asarray(out["b"]), b)


def test_cross_topology_restore_onto_different_mesh(tmp_path):
    """The same 2-process slab checkpoint re-shards onto a DIFFERENT mesh
    template (4-way dp) — reassemble global, then place under the
    template's NamedSharding."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from tony_tpu.parallel.mesh import MeshSpec, build_mesh

    w = np.arange(16.0, dtype=np.float32).reshape(8, 2)
    _write_slab_checkpoint(
        tmp_path, 1,
        [{"['w']": (w[:4], [[0, 4], [0, 2]], (8, 2))},
         {"['w']": (w[4:], [[4, 8], [0, 2]], (8, 2))}],
    )
    mesh = build_mesh(MeshSpec(dp=4), devices=jax.devices()[:4])
    sharding = NamedSharding(mesh, P("dp"))
    template = {"w": jax.device_put(jnp.zeros((8, 2)), sharding)}
    out = CheckpointManager(tmp_path).restore(template)
    assert out["w"].sharding == sharding
    np.testing.assert_array_equal(np.asarray(out["w"]), w)


def test_restore_onto_more_processes_than_saved(tmp_path):
    """The fewer-to-more direction: a 1-process checkpoint restored by a
    2-process gang. Rank 1 has no shard file of its own — it must
    reassemble from the donor files (process 0's manifest), not silently
    return None while rank 0 restores (a diverged gang deadlocks at the
    first collective)."""
    state = {"w": jnp.arange(8.0), "step": jnp.asarray(3, jnp.int32)}
    CheckpointManager(tmp_path).save(3, state, blocking=True)
    for pid in (0, 1):
        mgr = CheckpointManager(tmp_path, process_id=pid, num_processes=2)
        out = mgr.restore(
            {"w": jnp.zeros(8), "step": jnp.zeros((), jnp.int32)}
        )
        assert out is not None, f"rank {pid} restore returned None"
        np.testing.assert_array_equal(np.asarray(out["w"]), np.arange(8.0))
        assert int(out["step"]) == 3


def test_cross_topology_incomplete_coverage_raises(tmp_path):
    """Shard files whose union does not tile the global array are a torn
    or inconsistent checkpoint — restore must refuse, not zero-fill."""
    w = np.arange(16.0, dtype=np.float32).reshape(8, 2)
    _write_slab_checkpoint(
        tmp_path, 1,
        [{"['w']": (w[:4], [[0, 4], [0, 2]], (8, 2))},
         {"['w']": (w[:2], [[0, 2], [0, 2]], (8, 2))}],  # rows 4-8 nowhere
    )
    with pytest.raises(ValueError, match="does not cover"):
        CheckpointManager(tmp_path).restore({"w": jnp.zeros((8, 2))})


def test_structure_mismatch_raises(tmp_path):
    mgr = CheckpointManager(tmp_path)
    mgr.save(1, _state(1.0), blocking=True)
    with pytest.raises(ValueError, match="structure changed"):
        mgr.restore({"totally": jnp.zeros(2)})


def test_restore_preserves_sharding(tmp_path):
    """Restored leaves land with the template's NamedSharding — the
    per-process sharded restore the multi-chip path needs."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from tony_tpu.parallel.mesh import MeshSpec, build_mesh

    mesh = build_mesh(MeshSpec(dp=8), devices=jax.devices()[:8])
    sharding = NamedSharding(mesh, P("dp"))
    state = {"w": jax.device_put(jnp.arange(16.0), sharding)}
    mgr = CheckpointManager(tmp_path)
    mgr.save(1, state, blocking=True)
    out = mgr.restore(state)
    assert out["w"].sharding == sharding
    np.testing.assert_array_equal(np.asarray(out["w"]), np.arange(16.0))


def test_trainstate_roundtrip_on_mesh(tmp_path):
    """The real thing: a make_train_step TrainState (step + params +
    adamw opt_state, sharded over a dp×tp mesh) survives save→restore with
    values and shardings intact, mid-training."""
    from tony_tpu.models import TransformerConfig, make_train_step
    from tony_tpu.parallel.mesh import MeshSpec, build_mesh

    cfg = TransformerConfig(
        vocab_size=64, d_model=32, n_layers=2, n_heads=2, head_dim=16,
        d_ff=64, max_seq=32, dtype="float32", remat=False,
    )
    mesh = build_mesh(MeshSpec(dp=2, tp=2), devices=jax.devices()[:4])
    init_fn, step_fn = make_train_step(cfg, mesh)
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, 64, (4, 17)), jnp.int32
    )
    with jax.sharding.set_mesh(mesh):
        state = init_fn(jax.random.key(0))
        state, _ = step_fn(state, tokens)
        mgr = CheckpointManager(tmp_path)
        mgr.save(int(state.step), state, blocking=True)
        restored = mgr.restore(state)
        assert int(restored.step) == int(state.step) == 1
        for a, b in zip(jax.tree.leaves(state), jax.tree.leaves(restored)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b))
            assert a.sharding == b.sharding
        # training continues from the restored state
        resumed, metrics = step_fn(restored, tokens)
        assert int(resumed.step) == 2 and np.isfinite(float(metrics["loss"]))


def test_sharded_save_restore_across_processes_e2e(tmp_path):
    """2 executor processes checkpoint a global array neither fully owns:
    per-process shard files, completeness gating, and
    make_array_from_single_device_arrays reassembly on restore."""
    cluster = MiniTonyCluster(tmp_path / "cluster")
    conf = cluster.base_conf()
    conf.set(keys.K_FRAMEWORK, "jax")
    conf.set(keys.K_EXECUTES, str(FIXTURES / "ckpt_sharded.py"))
    conf.set(keys.K_PYTHON_BINARY, sys.executable)
    conf.set(keys.instances_key("worker"), 2)
    conf.set(keys.instances_key("ps"), 0)
    conf.set(keys.K_SHELL_ENV, f"CKPT_DIR={tmp_path}/ckpt")
    status, coord = cluster.run_job(conf, timeout_s=300)
    assert status is SessionStatus.SUCCEEDED, coord.session.diagnostics
    # Cross-topology epilogue on REAL 2-process shard files: this test
    # process (1 process) reassembles the global array the cluster saved
    # sharded — the serve-after-train path — and re-shards it onto a
    # local mesh.
    from jax.sharding import NamedSharding, PartitionSpec as P

    from tony_tpu.parallel.mesh import MeshSpec, build_mesh

    mgr = CheckpointManager(tmp_path / "ckpt")  # process 0 of 1
    meta = mgr._saved_num_processes(1)
    assert meta == 2, "fixture should have saved from 2 processes"
    # global length from the manifest (device count inside the cluster
    # executors is an executor-env detail this test must not hardcode)
    (n,) = mgr._read_shard_file(1, 0)[0]["['x']"]["shape"]
    out = mgr.restore({"x": jnp.zeros(n)})
    np.testing.assert_array_equal(
        np.asarray(out["x"]), np.arange(n, dtype=np.float32)
    )
    mesh = build_mesh(MeshSpec(dp=2), devices=jax.devices()[:2])
    sharded = jax.device_put(jnp.zeros(n), NamedSharding(mesh, P("dp")))
    out2 = mgr.restore({"x": sharded})
    assert out2["x"].sharding == sharded.sharding
    np.testing.assert_array_equal(
        np.asarray(out2["x"]), np.arange(n, dtype=np.float32)
    )


@pytest.mark.slow
def test_resnet_gang_fault_restart_e2e(tmp_path):
    """BASELINE config 5 (CI-scaled): 2 gang-scheduled workers train the
    in-framework ResNet; worker 0 crashes mid-run, the whole session
    restarts, both workers resume from checkpoints and finish."""
    cluster = MiniTonyCluster(tmp_path / "cluster")
    conf = cluster.base_conf()
    conf.set(keys.K_FRAMEWORK, "jax")
    conf.set(keys.K_EXECUTES, str(FIXTURES / "resnet_train.py"))
    conf.set(keys.K_PYTHON_BINARY, sys.executable)
    conf.set(keys.instances_key("worker"), 2)
    conf.set(keys.instances_key("ps"), 0)
    conf.set(keys.K_AM_RETRY_COUNT, 1)
    conf.set(keys.K_SHELL_ENV, f"CKPT_DIR={tmp_path}/ckpt")
    status, coord = cluster.run_job(conf, timeout_s=600)
    assert status is SessionStatus.SUCCEEDED, coord.session.diagnostics
    assert coord.session.session_id == 2  # fault-restarted once


# ---------------------------------------------------------------------------
# Object-store (gs://) checkpointing — VERDICT r3 missing #2: per-object
# PUTs are atomic, metadata.json is the commit marker, completeness is
# reader-side. Runs over FileObjectStorage (the MiniDFS analogue).
# ---------------------------------------------------------------------------

@pytest.fixture
def gcs_emulator(tmp_path):
    from tony_tpu.cloud import set_default_storage
    from tony_tpu.cloud.gcs import FileObjectStorage

    store = FileObjectStorage(tmp_path / "objects")
    set_default_storage(store)
    yield store
    set_default_storage(None)


def test_gs_roundtrip_and_bf16(gcs_emulator):
    state = {"w": jnp.asarray([1.5, -2.25, 3.0], jnp.bfloat16),
             "step": jnp.asarray(7, jnp.int32)}
    mgr = CheckpointManager("gs://ckpts/job1")
    mgr.save(7, state, blocking=True)
    out = mgr.restore(state)
    assert out["w"].dtype == jnp.bfloat16 and int(out["step"]) == 7
    np.testing.assert_array_equal(
        np.asarray(out["w"], np.float32), [1.5, -2.25, 3.0]
    )
    # no tmp objects: atomic PUTs need no rename dance (the .json
    # sidecar is the per-process commit record, not a tmp file)
    keys_ = gcs_emulator.list_prefix("gs://ckpts/job1/")
    assert sorted(keys_) == ["job1/step_7/metadata.json",
                             "job1/step_7/process_0.json",
                             "job1/step_7/process_0.npz"]


def test_gs_commit_marker_gates_completeness(gcs_emulator):
    p0 = CheckpointManager("gs://ckpts/j", process_id=0, num_processes=2)
    p1 = CheckpointManager("gs://ckpts/j", process_id=1, num_processes=2)
    p0.save(1, _state(1.0), blocking=True)
    assert p0.latest_step() is None  # marker present, shard 1 missing
    p1.save(1, _state(1.5), blocking=True)
    assert p0.latest_step() == 1
    assert float(p1.restore(_state(0.0))["params"]["w"][0, 0]) == 1.5


def test_gs_gc_reclaims_torn_prefixes(gcs_emulator):
    mgr = CheckpointManager("gs://ckpts/g", max_to_keep=2,
                            torn_gc_grace_s=0.0)
    mgr.save(1, _state(1.0), blocking=True)
    # a crash leftover: shard object without its commit marker
    gcs_emulator.put_bytes("gs://ckpts/g/step_0/process_0.npz", b"torn")
    time.sleep(0.01)
    for s in (2, 3):
        mgr.save(s, _state(float(s)), blocking=True)
    assert mgr._complete_steps() == [2, 3]
    assert not gcs_emulator.exists("gs://ckpts/g/step_0/process_0.npz")
    # max_to_keep pruned step 1's objects too
    assert not gcs_emulator.exists("gs://ckpts/g/step_1/metadata.json")


def test_gs_recent_torn_prefix_survives_gc(gcs_emulator):
    mgr = CheckpointManager("gs://ckpts/r", max_to_keep=2,
                            torn_gc_grace_s=3600.0)
    mgr.save(1, _state(1.0), blocking=True)
    gcs_emulator.put_bytes("gs://ckpts/r/step_0/process_0.npz", b"inflight")
    for s in (2, 3):
        mgr.save(s, _state(float(s)), blocking=True)
    assert gcs_emulator.exists("gs://ckpts/r/step_0/process_0.npz")


def test_gs_cross_topology_restore(gcs_emulator):
    """The topology-portable reassembly path over the OBJECT store: a
    2-process slab checkpoint under gs:// restores into a 1-process full
    template — donor shard files fetched as objects, values exact."""
    w = np.arange(16.0, dtype=np.float32).reshape(8, 2)
    from tony_tpu.checkpoint import _ObjectCheckpointStore

    _write_slab_checkpoint(
        None, 2,
        [{"['w']": (w[:4], [[0, 4], [0, 2]], (8, 2))},
         {"['w']": (w[4:], [[4, 8], [0, 2]], (8, 2))}],
        store=_ObjectCheckpointStore("gs://ckpts/xtopo"),
    )
    out = CheckpointManager("gs://ckpts/xtopo").restore(
        {"w": jnp.zeros((8, 2))}
    )
    np.testing.assert_array_equal(np.asarray(out["w"]), w)


def test_gs_restore_on_session_retry_e2e(tmp_path):
    """Resume-on-retry against the object store: session 1 checkpoints to
    gs:// and crashes at step 5; the retried session restores from the
    bucket and finishes — no filesystem anywhere in the checkpoint path."""
    cluster = MiniTonyCluster(tmp_path / "cluster")
    conf = cluster.base_conf()
    conf.set(keys.K_FRAMEWORK, "jax")
    conf.set(keys.K_EXECUTES, str(FIXTURES / "ckpt_train.py"))
    conf.set(keys.K_PYTHON_BINARY, sys.executable)
    conf.set(keys.instances_key("worker"), 1)
    conf.set(keys.instances_key("ps"), 0)
    conf.set(keys.K_AM_RETRY_COUNT, 1)
    conf.set(
        keys.K_SHELL_ENV,
        "CKPT_DIR=gs://ckpts/retry,"
        f"TONY_GCS_EMULATOR_DIR={tmp_path / 'objects'}",
    )
    status, coord = cluster.run_job(conf, timeout_s=180)
    assert status is SessionStatus.SUCCEEDED, coord.session.diagnostics
    assert coord.session.session_id == 2
    import os

    os.environ["TONY_GCS_EMULATOR_DIR"] = str(tmp_path / "objects")
    try:
        from tony_tpu.cloud import set_default_storage

        set_default_storage(None)  # rebuild from the env var
        assert CheckpointManager("gs://ckpts/retry").latest_step() == 10
    finally:
        del os.environ["TONY_GCS_EMULATOR_DIR"]
        set_default_storage(None)


def test_restore_on_session_retry_e2e(tmp_path):
    """Full-stack resume: session 1 checkpoints every step and crashes at
    step 5; the retried session restores from step 5 and finishes — the
    orchestrator-restart + checkpoint contract of SURVEY §5.4."""
    cluster = MiniTonyCluster(tmp_path / "cluster")
    conf = cluster.base_conf()
    conf.set(keys.K_FRAMEWORK, "jax")
    conf.set(keys.K_EXECUTES, str(FIXTURES / "ckpt_train.py"))
    conf.set(keys.K_PYTHON_BINARY, sys.executable)
    conf.set(keys.instances_key("worker"), 1)
    conf.set(keys.instances_key("ps"), 0)
    conf.set(keys.K_AM_RETRY_COUNT, 1)
    conf.set(keys.K_SHELL_ENV, f"CKPT_DIR={tmp_path}/ckpt")
    status, coord = cluster.run_job(conf, timeout_s=180)
    assert status is SessionStatus.SUCCEEDED, coord.session.diagnostics
    assert coord.session.session_id == 2  # second session finished the job
    # checkpoints survive: step 10 is the newest complete one
    assert CheckpointManager(tmp_path / "ckpt").latest_step() == 10


# ---------------------------------------------------------------------------
# Staged pipeline, differential saves, commit sidecars, live migration
# (checkpoint/ package). The fallback contract under test everywhere: a
# torn/corrupt/chain-broken step costs one interval of progress, never
# the job.
# ---------------------------------------------------------------------------
import json
import os
import signal
import subprocess
import threading

from tony_tpu import constants
from tony_tpu.checkpoint import FlushSignal
from tony_tpu.resilience import latest_complete_step


def _diff_state(val: float, static: float = 1.0):
    """A tree with a large STATIC leaf (the differential win) plus small
    hot leaves that change every save."""
    return {
        "hot": jnp.full((16, 4), float(val)),
        "frozen": jnp.full((512, 8), float(static)),
        "step": jnp.asarray(int(val), jnp.int32),
    }


def _arm_fault_plan(monkeypatch, plan: dict) -> None:
    """Point the user-process fault singletons at a fresh TONY_FAULT_PLAN."""
    from tony_tpu.resilience import faults as faults_mod

    monkeypatch.setenv(constants.TONY_FAULT_PLAN, json.dumps(plan))
    monkeypatch.setattr(faults_mod, "_env_plan", None)
    monkeypatch.setattr(faults_mod, "_ckpt_faults", False)


class _GatedStore:
    """Store wrapper that parks shard uploads on an Event — the
    controllable slow store for pipeline-overlap tests."""

    def __init__(self, inner, gate: threading.Event) -> None:
        self._inner = inner
        self._gate = gate
        self.shard_puts = 0

    def put_file(self, step, name, data):
        if name.endswith(".npz"):
            self.shard_puts += 1
            assert self._gate.wait(timeout=30.0), "gate never opened"
        return self._inner.put_file(step, name, data)

    def __getattr__(self, attr):
        return getattr(self._inner, attr)


def test_pipeline_overlaps_saves_and_save_call_does_not_block(tmp_path):
    """With depth 2, two saves ride the pipeline concurrently while the
    store is wedged, and the save() calls themselves return immediately
    — the persist wall is off the step path."""
    gate = threading.Event()
    mgr = CheckpointManager(tmp_path, pipeline_depth=2)
    mgr._store = _GatedStore(mgr._store, gate)
    t0 = time.monotonic()
    mgr.save(1, _state(1.0))
    mgr.save(2, _state(2.0))
    call_wall = time.monotonic() - t0
    assert call_wall < 5.0  # snapshot only; the store is parked
    assert mgr._pipeline.inflight() == 2
    assert mgr.latest_step() is None  # nothing committed yet
    gate.set()
    mgr.wait()
    assert mgr._pipeline.inflight() == 0
    assert mgr.latest_step() == 2
    assert mgr.last_committed_step == 2


def test_pipeline_depth_backpressures_the_caller(tmp_path):
    """Depth 1 + a wedged store: the second save must BLOCK (bounded
    host memory beats an unbounded snapshot queue) until the first
    commits."""
    gate = threading.Event()
    mgr = CheckpointManager(tmp_path, pipeline_depth=1)
    mgr._store = _GatedStore(mgr._store, gate)
    mgr.save(1, _state(1.0))
    entered = threading.Event()
    done = threading.Event()

    def second():
        entered.set()
        mgr.save(2, _state(2.0))
        done.set()

    t = threading.Thread(target=second, daemon=True)
    t.start()
    assert entered.wait(5.0)
    assert not done.wait(0.3), "save #2 should block at depth 1"
    gate.set()
    assert done.wait(30.0), "save #2 never unblocked"
    mgr.wait()
    assert mgr.latest_step() == 2


def test_differential_save_skips_unchanged_leaves_and_restores(tmp_path):
    """Steps 2..3 reference the frozen leaf's bytes in step 1 instead of
    rewriting them: measurably fewer bytes on disk, exact values on
    restore (newest AND an explicit mid-chain step)."""
    mgr = CheckpointManager(tmp_path, full_every=100)
    for s in (1, 2, 3):
        mgr.save(s, _diff_state(s), blocking=True)
    sc1 = json.loads((tmp_path / "step_1/process_0.json").read_text())
    sc3 = json.loads((tmp_path / "step_3/process_0.json").read_text())
    assert sc1["kind"] == "full" and sc1["base_steps"] == []
    assert sc3["kind"] == "diff" and sc3["base_steps"] == [1]
    full_bytes = (tmp_path / "step_1/process_0.npz").stat().st_size
    diff_bytes = (tmp_path / "step_3/process_0.npz").stat().st_size
    assert diff_bytes < full_bytes * 0.5, (full_bytes, diff_bytes)
    out = mgr.restore(_diff_state(0))
    assert int(out["step"]) == 3
    assert float(out["hot"][0, 0]) == 3.0
    assert float(out["frozen"][0, 0]) == 1.0  # resolved from step 1
    out2 = mgr.restore(_diff_state(0), step=2)
    assert int(out2["step"]) == 2 and float(out2["hot"][0, 0]) == 2.0
    # A fresh manager (no in-memory hash state) restores too.
    out3 = CheckpointManager(tmp_path).restore(_diff_state(0))
    assert int(out3["step"]) == 3


def test_full_every_compaction_and_donor_gc(tmp_path):
    """Every full_every-th save rewrites everything; GC keeps a donor
    step alive exactly as long as a kept diff references it."""
    mgr = CheckpointManager(tmp_path, max_to_keep=2, full_every=3)
    for s in range(1, 8):
        mgr.save(s, _diff_state(s), blocking=True)
    # Pattern: 1 full, 2-3 diff(base 1), 4 full, 5-6 diff(base 4), 7 full.
    kinds = {
        s: json.loads((tmp_path / f"step_{s}/process_0.json").read_text())
        for s in (4, 6, 7)
        if (tmp_path / f"step_{s}/process_0.json").exists()
    }
    assert kinds[4]["kind"] == "full"
    assert kinds[6]["kind"] == "diff" and kinds[6]["base_steps"] == [4]
    assert kinds[7]["kind"] == "full"
    present = {
        int(p.name.split("_")[1])
        for p in tmp_path.iterdir() if p.name.startswith("step_")
    }
    # kept {6, 7} + donor {4}; everything else pruned.
    assert present == {4, 6, 7}
    out = mgr.restore(_diff_state(0), step=6)
    assert int(out["step"]) == 6 and float(out["frozen"][0, 0]) == 1.0


def test_torn_differential_chain_falls_back(tmp_path):
    """A diff step whose base bytes vanished is invisible to BOTH the
    manager and the jax-free probe; readers fall back to the newest
    intact step instead of raising."""
    mgr = CheckpointManager(tmp_path, max_to_keep=10, full_every=3)
    for s in (1, 2, 3, 4):  # 1 full, 2-3 diff(base 1), 4 full
        mgr.save(s, _diff_state(s), blocking=True)
    (tmp_path / "step_1" / "process_0.npz").unlink()
    assert mgr._complete_steps() == [4]
    assert mgr.latest_step() == 4
    assert latest_complete_step(tmp_path) == 4  # probe agrees
    assert mgr.restore(_diff_state(0), step=3) is None
    out = mgr.restore(_diff_state(0))
    assert int(out["step"]) == 4


def test_corrupt_shard_checksum_falls_back(tmp_path):
    """Bit rot the listing cannot see: the newest step's shard fails its
    commit-sidecar sha256 at decode time — restore falls back to the
    previous complete step; the explicit step returns None."""
    mgr = CheckpointManager(tmp_path)
    for s in (1, 2):
        mgr.save(s, _state(float(s)), blocking=True)
    shard = tmp_path / "step_2" / "process_0.npz"
    raw = bytearray(shard.read_bytes())
    raw[-1] ^= 0xFF
    shard.write_bytes(bytes(raw))
    assert mgr.latest_step() == 2  # completeness listing can't see rot
    assert mgr.restore(_state(0.0), step=2) is None
    out = mgr.restore(_state(0.0))
    assert int(out["step"]) == 1
    # restore_resumable pinned at the rotten step falls back too.
    os.environ["TONY_RESUME_STEP"] = "2"
    try:
        assert int(mgr.restore_resumable(_state(0.0))["step"]) == 1
    finally:
        del os.environ["TONY_RESUME_STEP"]


def test_partial_write_fault_withholds_commit(tmp_path, monkeypatch):
    """fail_checkpoint_write mode=partial: the shard lands, the commit
    sidecar + marker are withheld — no reader (manager or probe) ever
    surfaces the torn step."""
    _arm_fault_plan(monkeypatch, {"faults": [
        {"action": "fail_checkpoint_write", "step": 2, "mode": "partial"},
    ]})
    mgr = CheckpointManager(tmp_path)
    mgr.save(1, _state(1.0), blocking=True)
    mgr.save(2, _state(2.0), blocking=True)  # no error raised
    assert (tmp_path / "step_2" / "process_0.npz").exists()
    assert not (tmp_path / "step_2" / "process_0.json").exists()
    assert not (tmp_path / "step_2" / "metadata.json").exists()
    assert mgr.latest_step() == 1
    assert latest_complete_step(tmp_path) == 1
    assert int(mgr.restore(_state(0.0))["step"]) == 1


def test_delay_checkpoint_write_stays_off_step_path(tmp_path, monkeypatch):
    """delay_checkpoint_write slows the PERSIST stage only: the save()
    call returns fast while wait() pays the injected delay — the
    off-step-path proof in miniature."""
    _arm_fault_plan(monkeypatch, {"faults": [
        {"action": "delay_checkpoint_write", "ms": 500, "count": 1},
    ]})
    mgr = CheckpointManager(tmp_path)
    t0 = time.monotonic()
    mgr.save(1, _state(1.0))
    call_s = time.monotonic() - t0
    t1 = time.monotonic()
    mgr.wait()
    drain_s = time.monotonic() - t1
    assert call_s < 0.4, call_s
    assert call_s + drain_s >= 0.5
    assert mgr.latest_step() == 1


def test_flush_signal_fires_once_per_order_at_target(tmp_path, monkeypatch):
    f = tmp_path / "flush.json"
    monkeypatch.setenv(constants.TONY_CKPT_FLUSH_FILE, str(f))
    sig = FlushSignal()
    assert not sig.requested(5)  # no order yet
    f.write_text(json.dumps({"req_id": "r1", "step": 7}))
    assert not sig.requested(6)  # before the target step
    assert sig.requested(7)
    assert not sig.requested(8)  # once per order
    f.write_text(json.dumps({"req_id": "r2"}))  # targetless re-order
    assert sig.requested(1)
    assert not sig.requested(2)
    # Garbage never fires (a torn write is retried by the executor).
    f.write_text("{not json")
    assert not sig.requested(3)


def test_manager_without_flush_env_never_flushes(tmp_path, monkeypatch):
    monkeypatch.delenv(constants.TONY_CKPT_FLUSH_FILE, raising=False)
    mgr = CheckpointManager(tmp_path)
    assert not mgr.flush_requested(1)


@pytest.mark.parametrize("stage", ["shard", "sidecar", "marker"])
def test_sigkill_mid_persist_never_surfaces_torn_step(tmp_path, stage):
    """The satellite's kill-during-persist contract: SIGKILL the saving
    process at each commit boundary of the pipeline; readers only ever
    see complete steps and resume lands on the last committed one."""
    ckpt = tmp_path / "ckpt"
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=str(Path(__file__).resolve().parent.parent))
    proc = subprocess.run(
        [sys.executable, str(FIXTURES / "ckpt_kill_stage.py"),
         str(ckpt), stage],
        capture_output=True, timeout=240, env=env,
    )
    assert proc.returncode == -signal.SIGKILL, (
        proc.returncode, proc.stderr.decode()[-500:],
    )
    mgr = CheckpointManager(ckpt)
    assert mgr.latest_step() == 3
    assert latest_complete_step(ckpt) == 3
    template = {"step": jnp.zeros((), jnp.int32), "w": jnp.zeros(8)}
    # The coordinator would seed the victim's last REPORTED step (4);
    # the reader must fall back to the last COMMITTED one (3).
    os.environ["TONY_RESUME_STEP"] = "4"
    try:
        out = mgr.restore_resumable(template)
    finally:
        del os.environ["TONY_RESUME_STEP"]
    assert int(out["step"]) == 3
    assert float(out["w"][0]) == 3.0


@pytest.mark.slow
def test_preemption_live_migration_e2e(tmp_path):
    """The tentpole acceptance: scheduler preemption of a running,
    checkpointing job becomes live migration — the coordinator orders a
    gang-wide flush over the heartbeat replies, waits for the commit
    marker, and the relaunch resumes within ~one step-interval of the
    victim's last executed step (vs one whole checkpoint interval for
    the non-migrating baseline), with wasted_by_failure bounded
    accordingly in the fleet ledger."""
    from tony_tpu.scheduler.queue import JobState

    with MiniTonyCluster(tmp_path / "cluster") as cluster:
        sched_conf = cluster.base_conf()
        sched_conf.set(keys.K_SCHED_TICK_MS, 50)
        sched_conf.set(keys.K_SCHED_MAX_SLICES, 1)
        daemon = cluster.start_scheduler(sched_conf, serve_http=False)
        ckpt = tmp_path / "ckpt"
        last_step = tmp_path / "last_step.txt"
        conf = cluster.base_conf()
        conf.set(keys.K_EXECUTES, str(FIXTURES / "migrate_train.py"))
        conf.set(keys.K_PYTHON_BINARY, sys.executable)
        conf.set(keys.instances_key("worker"), 1)
        conf.set(keys.instances_key("ps"), 0)
        conf.set(keys.K_CHECKPOINT_LOCATION, str(ckpt))
        conf.set(keys.K_SCHED_PRIORITY, 0)
        conf.set(keys.K_SHELL_ENV,
                 f"LAST_STEP_OUT={last_step},TARGET_STEPS=500,"
                 f"CKPT_EVERY=10,STEP_S=0.15,JAX_PLATFORMS=cpu")
        low = daemon.submit(conf)
        # Let it train past the first periodic checkpoint and INTO the
        # next interval, so migration has something to win.
        deadline = time.monotonic() + 120
        while latest_complete_step(ckpt) is None:
            assert time.monotonic() < deadline, "no first checkpoint"
            time.sleep(0.2)
        while (not last_step.exists()
               or int(last_step.read_text() or 0) < 13):
            assert time.monotonic() < deadline, "job made no progress"
            time.sleep(0.2)
        hi_conf = cluster.base_conf()
        hi_conf.set(keys.K_EXECUTES, str(FIXTURES / "exit_0.py"))
        hi_conf.set(keys.K_PYTHON_BINARY, sys.executable)
        hi_conf.set(keys.instances_key("worker"), 1)
        hi_conf.set(keys.instances_key("ps"), 0)
        hi_conf.set(keys.K_SCHED_PRIORITY, 10)
        hi = daemon.submit(hi_conf)
        assert daemon.wait_job(hi, 180) is JobState.SUCCEEDED
        assert daemon.wait_job(low, 180) is JobState.SUCCEEDED
        job = daemon.job(low)
        assert job.preemptions == 1
        # The flush order must actually have fired (a broken command
        # channel + the 20s migrate-timeout fallback could otherwise
        # land close enough by luck): attempt 1's coordinator stamped
        # it into the job's events.jsonl.
        events_log = Path(job.app_dir) / "events.jsonl"
        kinds = [
            json.loads(line).get("kind")
            for line in events_log.read_text().splitlines() if line
        ]
        assert "checkpoint_flush_requested" in kinds
        assert "checkpoint_progress" in kinds  # the live commit mark
        victim_last = int(last_step.read_text())
        resume = job.resume_step
        assert resume is not None
        # THE migration claim (ISSUE 14 acceptance): the relaunch's
        # resume step is within one SAVE interval (CKPT_EVERY=10) of
        # the victim's last executed step — the flush targets one past
        # the furthest reported step (heartbeat-lagged by up to one
        # ping) and the victim executes a few more while the order
        # lands and teardown drains.
        assert victim_last - resume <= 10, (victim_last, resume)
        # And never worse than the periodic-save baseline; with the
        # flush committed (events asserted above) it is the flushed
        # step, not the last multiple of 10.
        baseline_resume = (victim_last // 10) * 10
        assert resume >= baseline_resume, (resume, baseline_resume)
        # Ledger: the migrated job's recomputation debt is bounded by
        # the resume gap (~seconds), not the whole interval since the
        # last periodic save.
        fleet = daemon.goodput.to_json()["fleet_chip_seconds"]
        assert fleet["productive"] > 0.0
        assert fleet["wasted_by_failure"] <= 10.0, fleet


def test_resave_of_same_step_never_self_references(tmp_path):
    """Regression (found by a live lm_train run): the train loop's
    in-loop save and the final blocking save can hit the SAME step —
    the second save's unchanged leaves must be rewritten, not
    referenced to their own step (a self-ref diff overwrites the very
    shard file its bytes live in, and the step becomes unreadable)."""
    mgr = CheckpointManager(tmp_path, full_every=100)
    mgr.save(1, _diff_state(1), blocking=True)
    mgr.save(2, _diff_state(2), blocking=True)
    mgr.save(2, _diff_state(2), blocking=True)  # the re-save
    sc = json.loads((tmp_path / "step_2/process_0.json").read_text())
    assert 2 not in sc["base_steps"]
    out = CheckpointManager(tmp_path).restore(_diff_state(0))
    assert int(out["step"]) == 2
    assert float(out["hot"][0, 0]) == 2.0
    assert float(out["frozen"][0, 0]) == 1.0


def test_committed_gauge_is_global_not_per_process(tmp_path):
    """Review finding: the tony_ckpt_committed_step gauge feeds the
    goodput checkpoint mark, so it must reflect READER-SIDE (global)
    completeness — process 0 publishes it from the completeness rule;
    a peer's local commit publishes nothing, and process 0's own commit
    of a step whose peer shard is missing must not advance it."""
    from tony_tpu.checkpoint import CKPT_COMMITTED_GAUGE
    from tony_tpu.observability.metrics import default_registry

    def gauge():
        return default_registry().snapshot()["gauges"].get(
            CKPT_COMMITTED_GAUGE
        )

    p0 = CheckpointManager(tmp_path, process_id=0, num_processes=2)
    p1 = CheckpointManager(tmp_path, process_id=1, num_processes=2)
    before = gauge()
    p1.save(41, _state(1.5), blocking=True)  # peer commits FIRST
    assert gauge() == before  # non-marker processes publish nothing
    p0.save(41, _state(1.0), blocking=True)  # completes step 41
    assert gauge() == 41.0
    p0.save(42, _state(2.0), blocking=True)  # p1's shard still missing
    assert gauge() == 41.0  # own commit of an incomplete step: no move
    p1.save(42, _state(2.5), blocking=True)
    assert gauge() == 41.0  # conservative: advances at p0's next save
    p0.save(43, _state(3.0), blocking=True)
    assert gauge() == 42.0
