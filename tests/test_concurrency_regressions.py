"""Targeted regressions for the races the TONY-T pass and the sync
sanitizer surfaced in existing modules: metrics-registry step/publish
state, labeled-child creation, events.jsonl append ordering, and
aggregator render during a heartbeat-thread ingest storm.

These hammer the real concurrency (threads, not mocks): post-fix the
assertions are deterministic; pre-fix they were the races reviewers
kept hand-catching.
"""

import json
import threading

import pytest

from tony_tpu.observability.aggregator import MetricsAggregator
from tony_tpu.observability.events import (
    TASK_REGISTERED,
    EventLog,
    jsonl_file_sink,
    parse_jsonl,
)
from tony_tpu.observability.metrics import MetricsRegistry


def _spawn(n, fn):
    threads = [
        threading.Thread(target=fn, args=(i,), daemon=True)
        for i in range(n)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
        assert not t.is_alive(), "worker thread wedged"


class TestMetricsRegistry:
    def test_labeled_child_creation_race(self):
        """16 threads racing the first registration of the same labeled
        child must all get ONE object — a lost-update here would shard
        increments across ghost children and undercount the series."""
        registry = MetricsRegistry()
        barrier = threading.Barrier(16)
        got = [None] * 16

        def worker(i):
            barrier.wait(timeout=10)
            child = registry.counter(
                "widgets_total", labels={"kind": str(i % 2)}
            )
            got[i] = child
            for _ in range(100):
                child.inc()

        _spawn(16, worker)
        assert all(c is not None for c in got)
        # One object per label value, shared by every racing thread.
        assert len({id(c) for c in got}) == 2
        counters = registry.snapshot()["counters"]
        assert counters['widgets_total{kind="0"}'] == 800
        assert counters['widgets_total{kind="1"}'] == 800

    def test_publish_throttle_single_flush_under_race(self, tmp_path):
        """Concurrent report() calls inside one throttle window must
        publish exactly once — the _last_publish check-then-act is
        under the report lock now (the flush itself stays outside)."""
        registry = MetricsRegistry(
            publish_path=tmp_path / "snap.json",
            publish_min_interval_s=60.0,
        )
        flushes = []
        real_flush = registry.flush
        registry.flush = lambda: flushes.append(1) or real_flush()
        barrier = threading.Barrier(8)

        def worker(i):
            barrier.wait(timeout=10)
            registry.report(loss=float(i))

        _spawn(8, worker)
        assert len(flushes) == 1

    def test_report_step_state_is_serialized(self):
        """Concurrent report(step=...) calls keep internal state
        consistent: the steps counter is finite, positive, and the
        registry snapshot stays parseable mid-storm."""
        registry = MetricsRegistry()

        def worker(i):
            for step in range(1, 101):
                registry.report(step=step, loss=0.1 * i)
                registry.snapshot()

        _spawn(4, worker)
        counters = registry.snapshot()["counters"]
        assert counters["train_steps_total"] >= 100


class TestEventLog:
    def test_file_order_matches_memory_order(self, tmp_path):
        """events.jsonl and the in-memory timeline must agree exactly
        under concurrent emitters (liveness expiry vs monitor thread):
        the sink runs inside the log's lock, so the two sequences can
        never contradict each other."""
        path = tmp_path / "events.jsonl"
        log = EventLog(sink=jsonl_file_sink(path))

        def worker(i):
            for n in range(50):
                log.emit(TASK_REGISTERED, task=f"w:{i}", n=n)

        _spawn(8, worker)
        in_memory = log.to_dicts()
        on_disk = parse_jsonl(path.read_text())
        assert len(in_memory) == 400
        assert on_disk == in_memory

    def test_raising_sink_never_breaks_emitters(self, tmp_path):
        hits = []

        def sink(event):
            hits.append(event)
            raise OSError("disk gone")

        log = EventLog(sink=sink)
        log.emit(TASK_REGISTERED, task="w:0")
        assert len(log.to_dicts()) == 1 and len(hits) == 1


class TestAggregator:
    def _snapshot(self, step):
        return {
            "ts_ms": 1_000_000 + step,
            "counters": {"train_steps_total": float(step)},
            "gauges": {"loss": 1.0 / (step + 1), "step_time_ms": 12.0},
            "histograms": {},
        }

    def test_render_during_ingest_storm(self):
        """Every render view stays consistent while heartbeat threads
        mutate the per-task series underneath — the series copies are
        taken under the aggregator lock, so no RuntimeError('dict
        changed size') and no torn series."""
        agg = MetricsAggregator()
        stop = threading.Event()
        errors = []

        def ingester(i):
            for step in range(200):
                agg.ingest(f"worker:{i}", self._snapshot(step))

        def renderer():
            while not stop.is_set():
                try:
                    agg.prometheus_text()
                    doc = agg.to_json()
                    json.dumps(doc)
                    agg.stepstats_json()
                    agg.summary()
                    agg.heartbeat_ages()
                except Exception as exc:  # noqa: BLE001 — the assertion
                    errors.append(exc)
                    return

        render_thread = threading.Thread(target=renderer, daemon=True)
        render_thread.start()
        _spawn(4, ingester)
        stop.set()
        render_thread.join(timeout=30)
        assert errors == []
        doc = agg.to_json()
        assert set(doc["heartbeats"]) == {f"worker:{i}" for i in range(4)}
        # Per-task gauge series stay strictly monotonic in time.
        for key, points in doc["series"].items():
            ts = [p[0] for p in points]
            assert ts == sorted(ts), f"series {key} out of order"
            assert len(ts) == len(set(ts)), f"series {key} duplicated"

    def test_reset_task_during_render(self):
        """Healing's reset_task (evict-and-replace) racing a render
        must neither crash nor resurrect the evicted series."""
        agg = MetricsAggregator()
        for step in range(10):
            agg.ingest("worker:0", self._snapshot(step))

        def resetter(i):
            for _ in range(50):
                agg.ingest("worker:0", self._snapshot(i))
                agg.reset_task("worker:0")

        def renderer(i):
            for _ in range(50):
                agg.prometheus_text()
                agg.to_json()

        threads = [
            threading.Thread(target=resetter, args=(0,), daemon=True),
            threading.Thread(target=renderer, args=(1,), daemon=True),
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
            assert not t.is_alive()


class TestSanitizerCoversControlPlane:
    def test_suite_runs_with_sanitizer_armed(self):
        """The conftest bootstrap arms the sanitizer for tier-1 (every
        e2e doubles as a race probe); pin that the flag is actually on
        and the control-plane locks above registered under it."""
        import os

        from tony_tpu.analysis import sync_sanitizer as _sync

        if os.environ.get(_sync.ENV_FLAG) != "1":
            pytest.skip("sanitizer disabled for this run")
        locks = _sync.tracker().report()["locks"]
        assert "metrics.MetricsRegistry._lock" in locks
        assert "events.EventLog._lock" in locks
        assert "aggregator.MetricsAggregator._lock" in locks
