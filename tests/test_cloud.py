"""Cloud control-plane tests — recorded-response (fixture-transport) tests
for the concrete GCP clients, the analogue of the reference's client really
talking to its cluster (`TonyClient.createAMContainerSpec` uploads to HDFS
and submits through a live `YarnClient`, TonyClient.java:369-424, 568-621;
`ClusterSubmitter.java:48-82` stages the framework jar). No egress exists
in this environment, so the transports are the seam: every test drives the
real request-building / response-parsing code against scripted responses
and asserts the exact wire traffic.
"""

from __future__ import annotations

import json
import re
from pathlib import Path

import pytest

from tony_tpu.cloud import (
    GcpQueuedResourceApi,
    GcsStorage,
    is_gs_uri,
    set_default_storage,
    split_gs_uri,
)
from tony_tpu.cloud.gcs import GcsError
from tony_tpu.coordinator.backend import SlicePlan, TpuVmBackend


class FakeTransport:
    """Scripted HTTP transport: responses matched by (method, url regex),
    each consumed in order; every request is recorded for assertions."""

    def __init__(self) -> None:
        self.scripts: list[tuple[str, str, int, bytes]] = []
        self.requests: list[tuple[str, str, bytes | None]] = []

    def expect(self, method: str, url_re: str, status: int,
               body: object = b"") -> "FakeTransport":
        if isinstance(body, (dict, list)):
            body = json.dumps(body).encode()
        elif isinstance(body, str):
            body = body.encode()
        self.scripts.append((method, url_re, status, body))
        return self

    def request(self, method, url, body, headers):
        if hasattr(body, "read"):
            body = body.read()  # streamed upload: record the real payload
        self.requests.append((method, url, body))
        for i, (m, url_re, status, resp) in enumerate(self.scripts):
            if m == method and re.search(url_re, url):
                self.scripts.pop(i)
                return status, resp
        raise AssertionError(f"unexpected request: {method} {url}")


class FakeRunner:
    """CommandRunner fake: records started commands, lets tests finish
    them."""

    def __init__(self) -> None:
        self.started: list[tuple[str, int, str]] = []
        self.stdins: list[bytes | None] = []
        self._codes: dict[int, int | None] = {}
        self.killed: list[int] = []

    def start(self, node, worker, command, stdin_data=None):
        handle = len(self.started)
        self.started.append((node, worker, command))
        self.stdins.append(stdin_data)
        self._codes[handle] = None
        return handle

    def finish(self, handle: int, code: int) -> None:
        self._codes[handle] = code

    def poll(self, handle):
        return self._codes[handle]

    def kill(self, handle):
        self.killed.append(handle)
        self._codes[handle] = -9


class FakeStorage:
    """In-memory object store with GcsStorage's surface, for code that
    takes a storage client (staging, bootstrap, history)."""

    def __init__(self) -> None:
        self.objects: dict[str, bytes] = {}

    def put_bytes(self, uri, data):
        self.objects[uri] = bytes(data)

    def get_bytes(self, uri):
        return self.objects[uri]

    def upload_file(self, local, uri):
        self.put_bytes(uri, Path(local).read_bytes())

    def download_file(self, uri, local):
        Path(local).parent.mkdir(parents=True, exist_ok=True)
        Path(local).write_bytes(self.get_bytes(uri))

    def exists(self, uri):
        return uri in self.objects

    def list_prefix(self, uri):
        bucket, prefix = split_gs_uri(uri)
        return [
            split_gs_uri(u)[1]
            for u in sorted(self.objects)
            if u.startswith(f"gs://{bucket}/{prefix}")
        ]

    def delete(self, uri):
        self.objects.pop(uri, None)


@pytest.fixture
def fake_storage():
    store = FakeStorage()
    set_default_storage(store)  # type: ignore[arg-type]
    yield store
    set_default_storage(None)


# ---------------------------------------------------------------------------
# GCS client over recorded responses
# ---------------------------------------------------------------------------

class TestGcsStorage:
    def test_uri_helpers(self):
        assert is_gs_uri("gs://b/k") and not is_gs_uri("/tmp/x")
        assert split_gs_uri("gs://bucket/a/b.json") == ("bucket", "a/b.json")
        with pytest.raises(ValueError):
            split_gs_uri("s3://nope/x")

    def test_put_get_roundtrip_wire_shape(self):
        t = FakeTransport()
        t.expect("POST", r"upload/storage/v1/b/bkt/o\?uploadType=media"
                         r"&name=app%2Fconf\.json", 200, {"name": "app/conf.json"})
        t.expect("GET", r"storage/v1/b/bkt/o/app%2Fconf\.json\?alt=media",
                 200, b"hello")
        store = GcsStorage(t)
        store.put_bytes("gs://bkt/app/conf.json", b"hello")
        assert store.get_bytes("gs://bkt/app/conf.json") == b"hello"
        method, url, body = t.requests[0]
        assert body == b"hello"

    def test_list_prefix_follows_pages(self):
        t = FakeTransport()
        t.expect("GET", r"/o\?prefix=app%2F$", 200,
                 {"items": [{"name": "app/a"}], "nextPageToken": "p2"})
        t.expect("GET", r"pageToken=p2", 200, {"items": [{"name": "app/b"}]})
        assert GcsStorage(t).list_prefix("gs://bkt/app/") == ["app/a", "app/b"]

    def test_get_range_sends_range_header(self):
        t = FakeTransport()
        t.expect("GET", r"/o/corpus%2Fshard\.bin\?alt=media", 206, b"cdef")
        store = GcsStorage(t)
        assert store.get_range("gs://bkt/corpus/shard.bin", 2, 4) == b"cdef"
        # The Range request-header is how GCS serves ranged object reads;
        # FakeTransport drops headers, so assert via a header-capturing
        # transport.
        caught = {}

        class HdrTransport:
            def request(self, method, url, body, headers):
                caught.update(headers)
                return 206, b"cd"

        GcsStorage(HdrTransport()).get_range("gs://b/k", 2, 2)
        assert caught["Range"] == "bytes=2-3"

    def test_get_range_tolerates_full_body_200(self):
        # Proxies/tiny objects may ignore Range and return 200 + whole body.
        t = FakeTransport()
        t.expect("GET", r"alt=media", 200, b"0123456789")
        assert GcsStorage(t).get_range("gs://b/k", 3, 4) == b"3456"

    def test_size_reads_metadata(self):
        t = FakeTransport()
        t.expect("GET", r"/o/k$", 200, {"name": "k", "size": "1048576"})
        assert GcsStorage(t).size("gs://b/k") == 1048576

    def test_exists_and_error_paths(self):
        t = FakeTransport()
        t.expect("GET", r"/o/x$", 200, {"name": "x"})
        t.expect("GET", r"/o/y$", 404, b"not found")
        t.expect("GET", r"/o/z$", 403, b"denied")
        store = GcsStorage(t)
        assert store.exists("gs://b/x") is True
        assert store.exists("gs://b/y") is False
        with pytest.raises(GcsError, match="403"):
            store.exists("gs://b/z")


# ---------------------------------------------------------------------------
# UrllibTransport auth lifecycle (ADVICE r3: honor expires_in; retry on 401)
# ---------------------------------------------------------------------------

class TestUrllibTransportAuth:
    def _urlopen_script(self, monkeypatch, responses):
        """Patch urllib.request.urlopen with a scripted response list;
        entries are bytes (200 body) or int (HTTPError status)."""
        import io
        import urllib.error
        import urllib.request

        calls = []

        class FakeResp:
            def __init__(self, data):
                self.status = 200
                self._data = data

            def read(self):
                return self._data

            def __enter__(self):
                return self

            def __exit__(self, *a):
                return False

        def fake_urlopen(req, timeout=None):
            calls.append(req)
            r = responses.pop(0)
            if isinstance(r, int):
                raise urllib.error.HTTPError(
                    req.full_url, r, "err", {}, io.BytesIO(b"denied")
                )
            return FakeResp(r)

        monkeypatch.setattr(urllib.request, "urlopen", fake_urlopen)
        return calls

    def test_token_cached_until_expires_in_minus_margin(self, monkeypatch):
        from tony_tpu.cloud.gcp import UrllibTransport

        fetches = []

        def provider():
            fetches.append(1)
            return f"tok{len(fetches)}", 600.0  # 10-minute token

        tr = UrllibTransport(token_provider=provider)
        clock = [1000.0]
        monkeypatch.setattr("tony_tpu.cloud.gcp.time.monotonic",
                            lambda: clock[0])
        assert tr._bearer() == "tok1"
        clock[0] += 299.0  # inside 600 - 300 margin
        assert tr._bearer() == "tok1" and len(fetches) == 1
        clock[0] += 2.0  # past the margin-adjusted deadline
        assert tr._bearer() == "tok2" and len(fetches) == 2

    def test_short_lived_token_not_cached_a_fixed_hour(self, monkeypatch):
        """The metadata server returns its CACHED token until shortly
        before expiry — a fetch can see expires_in of a few minutes. The
        old fixed 3000 s cache would serve it long past death."""
        from tony_tpu.cloud.gcp import UrllibTransport

        fetches = []

        def provider():
            fetches.append(1)
            return f"tok{len(fetches)}", 120.0  # 2 minutes of life left

        tr = UrllibTransport(token_provider=provider)
        clock = [0.0]
        monkeypatch.setattr("tony_tpu.cloud.gcp.time.monotonic",
                            lambda: clock[0])
        assert tr._bearer() == "tok1"
        clock[0] += 45.0  # past life-margin floor (30 s), well before 3000
        assert tr._bearer() == "tok2"

    def test_401_drops_token_and_retries_once(self, monkeypatch):
        from tony_tpu.cloud.gcp import UrllibTransport

        tokens = iter(["stale", "fresh"])
        tr = UrllibTransport(token_provider=lambda: (next(tokens), 3600.0))
        calls = self._urlopen_script(monkeypatch, [401, b"ok"])
        status, body = tr.request("GET", "https://x/y", None, {})
        assert (status, body) == (200, b"ok")
        assert [c.get_header("Authorization") for c in calls] == [
            "Bearer stale", "Bearer fresh"
        ]

    def test_persistent_403_is_returned_not_looped(self, monkeypatch):
        from tony_tpu.cloud.gcp import UrllibTransport

        tr = UrllibTransport(token_provider=lambda: ("t", 3600.0))
        calls = self._urlopen_script(monkeypatch, [403, 403])
        status, _ = tr.request("GET", "https://x/y", None, {})
        assert status == 403 and len(calls) == 2  # one retry, then surface


# ---------------------------------------------------------------------------
# Queued-resources API lifecycle (VERDICT r2 item 1's "Done" list)
# ---------------------------------------------------------------------------

def _qr_state(state: str) -> dict:
    return {"state": {"state": state}}


class TestGcpQueuedResourceApi:
    def _api(self, transport, runner=None):
        return GcpQueuedResourceApi(
            "proj", "us-central1-a", transport=transport,
            runner=runner or FakeRunner(),
        )

    def test_create_ready_start_delete_lifecycle(self):
        t = FakeTransport()
        runner = FakeRunner()
        api = self._api(t, runner)
        # create: one queued resource, two nodes (multi-slice is atomic)
        t.expect("POST",
                 r"projects/proj/locations/us-central1-a/queuedResources"
                 r"\?queued_resource_id=app1-worker", 200, {"name": "op1"})
        api.create_slice("app1-worker", "v5litepod-16", 2)
        method, url, body = t.requests[-1]
        spec = json.loads(body)
        # Canonical proto-JSON camelCase on the wire — the same spelling
        # the API emits in responses, so writes diff cleanly against
        # recorded GET bodies.
        nodes = spec["tpu"]["nodeSpec"]
        assert [n["nodeId"] for n in nodes] == [
            "app1-worker-s0", "app1-worker-s1"
        ]
        assert nodes[0]["node"]["acceleratorType"] == "v5litepod-16"
        assert nodes[0]["node"]["runtimeVersion"] == "v2-alpha-tpuv5-lite"
        assert nodes[0]["parent"] == "projects/proj/locations/us-central1-a"

        # poll: CREATING (ACCEPTED) -> READY (ACTIVE)
        t.expect("GET", r"queuedResources/app1-worker$", 200,
                 _qr_state("ACCEPTED"))
        t.expect("GET", r"queuedResources/app1-worker$", 200,
                 _qr_state("ACTIVE"))
        assert api.slice_state("app1-worker") == "CREATING"
        assert api.slice_state("app1-worker") == "READY"

        # start: host 5 of 4-host v5litepod-16 slices -> slice 1, worker 1;
        # env exported, stage-0 loader fetches the staged app dir
        h = api.start_executor(
            "app1-worker", 5,
            {"JOB_NAME": "worker", "TONY_STAGED_URI": "gs://bkt/app1"},
        )
        node, worker, command = runner.started[-1]
        assert node == "app1-worker-s1" and worker == 1
        assert "export JOB_NAME=worker;" in command
        assert "gs://bkt/app1" in command
        assert "metadata.google.internal" in command  # stage-0 loader inlined
        assert api.executor_status(h) is None
        runner.finish(h, 0)
        assert api.executor_status(h) == 0

        # delete: force, 404 tolerated on retry
        t.expect("DELETE", r"queuedResources/app1-worker\?force=true", 200)
        api.delete_slice("app1-worker")
        t.expect("DELETE", r"queuedResources/app1-worker\?force=true", 404,
                 b"gone")
        api.delete_slice("app1-worker")

    def test_multihost_placement_map_v5litepod16_two_slices(self):
        """The exact (node, worker) placement for a 2-slice v5litepod-16
        job: 8 host indexes -> 2 nodes x 4 ssh workers. Real multihost v5e
        is tiled from 4-chip host VMs (ct5lp-hightpu-4t), so a v5litepod-16
        has 4 workers — an 8-chip-host model would launch half the
        executors onto a truncated worker list (VERDICT r3 weak #1)."""
        t = FakeTransport()
        runner = FakeRunner()
        api = self._api(t, runner)
        t.expect("POST", r"queued_resource_id=app2-worker", 200, {})
        api.create_slice("app2-worker", "v5litepod-16", 2)
        for host_index in range(8):
            api.start_executor("app2-worker", host_index, {})
        placements = [(node, worker) for node, worker, _ in runner.started]
        assert placements == [
            ("app2-worker-s0", 0), ("app2-worker-s0", 1),
            ("app2-worker-s0", 2), ("app2-worker-s0", 3),
            ("app2-worker-s1", 0), ("app2-worker-s1", 1),
            ("app2-worker-s1", 2), ("app2-worker-s1", 3),
        ]

    def test_runtime_version_resolves_per_generation(self):
        """An unset runtime version must resolve to the provisioned
        accelerator's family image — a fixed v5e image would make every
        other generation unprovisionable with defaults."""
        t = FakeTransport()
        api = self._api(t)
        for accel, want in (
            ("v5litepod-16", "v2-alpha-tpuv5-lite"),
            ("v6e-16", "v2-alpha-tpuv6e"),
            ("v5p-32", "v2-alpha-tpuv5"),
            ("v4-32", "tpu-ubuntu2204-base"),
        ):
            t.expect("POST", r"queued_resource_id=", 200, {})
            api.create_slice(f"j-{accel}", accel, 1)
            spec = json.loads(t.requests[-1][2])
            got = spec["tpu"]["nodeSpec"][0]["node"]["runtimeVersion"]
            assert got == want, (accel, got)
        # explicit override still wins
        api2 = GcpQueuedResourceApi(
            "proj", "z", transport=t, runner=FakeRunner(),
            runtime_version="my-custom-image",
        )
        t.expect("POST", r"queued_resource_id=", 200, {})
        api2.create_slice("j-x", "v6e-16", 1)
        spec = json.loads(t.requests[-1][2])
        assert (spec["tpu"]["nodeSpec"][0]["node"]["runtimeVersion"]
                == "my-custom-image")

    def test_unknown_accelerator_runtime_raises_with_guidance(self):
        from tony_tpu.cloud.gcp import default_runtime_version

        with pytest.raises(ValueError, match="tony.gcp.runtime-version"):
            default_runtime_version("v99-frobnicator-8")

    def test_restart_relearns_shape_from_response_fixture(self):
        """A coordinator restarted mid-flight has an empty _groups map and
        must re-learn the slice shape from a GET — the fixture mirrors the
        queuedResources RESOURCE shape (proto-JSON camelCase: state.state,
        tpu.nodeSpec[].node.acceleratorType), which is also the spelling
        create_slice now writes."""
        t = FakeTransport()
        runner = FakeRunner()
        api = self._api(t, runner)
        t.expect("GET", r"queuedResources/lost-worker$", 200, {
            "name": ("projects/proj/locations/us-central1-a/"
                     "queuedResources/lost-worker"),
            "state": {"state": "ACTIVE"},
            "tpu": {"nodeSpec": [
                {"parent": "projects/proj/locations/us-central1-a",
                 "nodeId": "lost-worker-s0",
                 "node": {"acceleratorType": "v5litepod-16",
                          "runtimeVersion": "v2-alpha-tpuv5-lite"}},
                {"parent": "projects/proj/locations/us-central1-a",
                 "nodeId": "lost-worker-s1",
                 "node": {"acceleratorType": "v5litepod-16",
                          "runtimeVersion": "v2-alpha-tpuv5-lite"}},
            ]},
        })
        api.start_executor("lost-worker", 6, {})
        node, worker, _ = runner.started[-1]
        assert (node, worker) == ("lost-worker-s1", 2)

    def test_secrets_ride_stdin_not_argv(self):
        """Credential env (TONY_EXECUTOR_TOKEN etc.) must not appear in the
        ssh command — argv is visible in process listings on the client
        host and the TPU VM, and the command prefix is logged at INFO
        (ADVICE r3). Values travel via the remote shell's stdin; only the
        variable NAMES may appear in the command."""
        t = FakeTransport()
        runner = FakeRunner()
        api = self._api(t, runner)
        t.expect("POST", r"queued_resource_id=app3-w", 200, {})
        api.create_slice("app3-w", "v5litepod-8", 1)
        api.start_executor("app3-w", 0, {
            "JOB_NAME": "worker",
            "TONY_EXECUTOR_TOKEN": "deadbeefcafe",
            "TONY_JOB_SECRET": "s3cr3t",
        })
        node, worker, command = runner.started[-1]
        assert "deadbeefcafe" not in command and "s3cr3t" not in command
        assert "export JOB_NAME=worker;" in command  # plain env still argv
        # stdin carries one value per line in sorted key order, read into
        # the matching variable before exec
        assert runner.stdins[-1] == b"deadbeefcafe\ns3cr3t\n"
        assert "IFS= read -r TONY_EXECUTOR_TOKEN; export TONY_EXECUTOR_TOKEN;" in command
        assert "IFS= read -r TONY_JOB_SECRET; export TONY_JOB_SECRET;" in command

    def test_newline_in_secret_is_rejected(self):
        """A secret value with an embedded newline would shift every later
        line-oriented stdin binding — refuse loudly instead."""
        t = FakeTransport()
        api = self._api(t, FakeRunner())
        t.expect("POST", r"queued_resource_id=app4-w", 200, {})
        api.create_slice("app4-w", "v5litepod-8", 1)
        with pytest.raises(ValueError, match="newline"):
            api.start_executor(
                "app4-w", 0, {"TONY_EXECUTOR_TOKEN": "bad\nvalue"}
            )

    def test_failed_provision_maps_to_failed(self):
        t = FakeTransport()
        api = self._api(t)
        for raw, want in [("FAILED", "FAILED"), ("SUSPENDED", "FAILED"),
                          ("WAITING_FOR_RESOURCES", "CREATING"),
                          ("PROVISIONING", "CREATING")]:
            t.expect("GET", r"queuedResources/g$", 200, _qr_state(raw))
            assert api.slice_state("g") == want

    def test_api_error_raises_with_status(self):
        t = FakeTransport()
        t.expect("POST", r"queuedResources", 409, b"already exists")
        with pytest.raises(Exception, match="409"):
            self._api(t).create_slice("dup", "v5litepod-8", 1)

    def test_backend_drives_full_lifecycle_through_api(self):
        """TpuVmBackend + GcpQueuedResourceApi end to end: launch while
        CREATING, executor starts on READY, exit propagates, stop_all
        deletes the queued resource — the reference's async
        allocate->launch->complete flow on the real control-plane client."""
        from tony_tpu.coordinator.session import TonyTask

        t = FakeTransport()
        runner = FakeRunner()
        api = self._api(t, runner)
        backend = TpuVmBackend(api, "app9")
        backend.prepare_slices(
            {"worker": SlicePlan("v5litepod-8", 1, 1, 8)}
        )
        t.expect("POST", r"queued_resource_id=app9-worker", 200, {})
        task = TonyTask(job_name="worker", index=0, session_id=1)
        h = backend.launch(task, {"TONY_STAGED_URI": "gs://b/app9"})

        t.expect("GET", r"queuedResources/app9-worker$", 200,
                 _qr_state("CREATING"))
        assert backend.poll(h) is None          # still provisioning
        backend._state_cache.clear()
        t.expect("GET", r"queuedResources/app9-worker$", 200,
                 _qr_state("ACTIVE"))
        assert backend.poll(h) is None          # READY -> executor started
        assert runner.started[-1][0] == "app9-worker-s0"
        runner.finish(h.remote, 0)
        assert backend.poll(h) == 0

        t.expect("DELETE", r"queuedResources/app9-worker\?force", 200)
        backend.stop_all()
        assert not backend._created

    def test_backend_failed_provision_fails_task(self):
        t = FakeTransport()
        api = self._api(t)
        from tony_tpu.coordinator.session import TonyTask

        backend = TpuVmBackend(api, "app9")
        backend.prepare_slices({"worker": SlicePlan("v5litepod-8", 1, 1, 8)})
        t.expect("POST", r"queued_resource_id=app9-worker", 200, {})
        h = backend.launch(
            TonyTask(job_name="worker", index=0, session_id=1), {}
        )
        t.expect("GET", r"queuedResources/app9-worker$", 200,
                 _qr_state("FAILED"))
        assert backend.poll(h) == 1  # fails the session -> retry machinery


# ---------------------------------------------------------------------------
# gs:// staging + localization
# ---------------------------------------------------------------------------

class TestGsStaging:
    def test_client_stages_to_gs(self, fake_storage, tmp_path, monkeypatch):
        """_stage with a gs:// staging location mirrors every artifact
        (archive, venv, lib.zip, frozen conf) under gs://.../<app_id>/ and
        rewrites the venv to a bare name remote bootstraps can resolve."""
        from tony_tpu.client.client import TonyClient
        from tony_tpu.conf import keys

        src = tmp_path / "src"
        src.mkdir()
        (src / "train.py").write_text("print('hi')\n")
        venv = tmp_path / "venv.zip"
        venv.write_bytes(b"fake venv zip")
        lib = tmp_path / "lib"
        (lib / "tony_tpu").mkdir(parents=True)
        (lib / "tony_tpu" / "__init__.py").write_text("")

        client = TonyClient().init([
            "--src_dir", str(src), "--executes", "train.py",
            "--python_venv", str(venv),
            "--conf", "tony.staging.location=gs://bkt/staging",
        ])
        client.conf.set(keys.K_LIB_PATH, str(lib))
        client._gcs_store = fake_storage
        app_dir = client._stage()
        prefix = f"gs://bkt/staging/{client.app_id}"
        names = {
            u[len(prefix) + 1:] for u in fake_storage.objects
            if u.startswith(prefix)
        }
        assert {"tony.zip", "venv.zip", "lib.zip",
                "tony-final.json"} <= names
        # venv key rewritten to the bare localized name
        frozen = json.loads(
            fake_storage.get_bytes(f"{prefix}/tony-final.json")
        )
        assert frozen[keys.K_PYTHON_VENV] == "venv.zip"
        assert (app_dir / "tony-final.json").is_file()  # local copy stays

    def test_bootstrap_localizes_and_runs_executor(
        self, fake_storage, tmp_path, monkeypatch
    ):
        """Stage 2 of the TPU-VM bootstrap: downloads every staged object,
        unzips the archive, points TONY_CONF_PATH at the local conf, and
        hands off to the task executor in the workdir."""
        from tony_tpu import constants, utils
        from tony_tpu.cloud import bootstrap

        src = tmp_path / "src"
        src.mkdir()
        (src / "train.py").write_text("ok\n")
        archive = tmp_path / "tony.zip"
        utils.zip_dir(src, archive)
        fake_storage.put_bytes("gs://b/app/tony.zip", archive.read_bytes())
        fake_storage.put_bytes("gs://b/app/tony-final.json", b"{}")
        fake_storage.put_bytes("gs://b/app/lib.zip", b"skipped")

        ran = {}

        def fake_executor_main():
            ran["cwd"] = Path.cwd()
            ran["conf"] = os.environ[constants.TONY_CONF_PATH]
            return 0

        import os

        import tony_tpu.executor.task_executor as te

        monkeypatch.setattr(te, "main", fake_executor_main)
        monkeypatch.chdir(tmp_path)
        rc = bootstrap.main("gs://b/app")
        assert rc == 0
        workdir = tmp_path / "tony-workdir"
        assert ran["cwd"] == workdir
        assert ran["conf"] == str(workdir / "tony-final.json")
        assert (workdir / "train.py").is_file()       # archive unzipped
        assert not (workdir / "lib.zip").exists()     # loader's job, skipped

    def test_history_writer_gs(self, fake_storage):
        from tony_tpu.conf.configuration import TonyConfiguration
        from tony_tpu.history.writer import (
            JobMetadata,
            create_history_file,
            setup_job_dir,
            write_config_file,
        )

        job_dir = setup_job_dir("gs://b/hist", "application_1_a", 0)
        assert job_dir.startswith("gs://b/hist/1970/")
        write_config_file(job_dir, TonyConfiguration())
        meta = JobMetadata.new("application_1_a", 0, "SUCCEEDED", user="u")
        uri = create_history_file(job_dir, meta)
        assert f"{job_dir}/config.json" in fake_storage.objects
        assert uri.endswith("-SUCCEEDED.jhist")
        assert uri in fake_storage.objects


class TestReviewFixes:
    def test_upload_file_streams_from_disk(self, tmp_path):
        """upload_file hands the transport an open file (not a bytes blob)
        with Content-Length — multi-GB artifacts never land in RAM."""
        t = FakeTransport()
        t.expect("POST", r"name=big\.bin", 200, {})
        big = tmp_path / "big.bin"
        big.write_bytes(b"x" * 1024)
        GcsStorage(t).upload_file(big, "gs://b/big.bin")
        method, url, body = t.requests[0]
        assert body == b"x" * 1024  # FakeTransport read it from the file

    def test_download_file_uses_stream_when_available(self, tmp_path):
        import io

        class StreamTransport(FakeTransport):
            def request_stream(self, method, url):
                return 200, io.BytesIO(b"streamed!")

        target = tmp_path / "out.bin"
        GcsStorage(StreamTransport()).download_file("gs://b/k", target)
        assert target.read_bytes() == b"streamed!"

    def test_bootstrap_exports_pythonpath_for_user_subprocess(
        self, fake_storage, tmp_path, monkeypatch
    ):
        """The user script is a SUBPROCESS of the executor; bootstrap must
        export PYTHONPATH so `import tony_tpu` works there too (locally
        LocalProcessBackend does this; the remote path must as well)."""
        import os

        import tony_tpu
        import tony_tpu.executor.task_executor as te
        from tony_tpu.cloud import bootstrap

        fake_storage.put_bytes("gs://b/app/tony-final.json", b"{}")
        seen = {}
        monkeypatch.setattr(
            te, "main", lambda: seen.update(pp=os.environ.get("PYTHONPATH"))
            or 0,
        )
        monkeypatch.delenv("PYTHONPATH", raising=False)
        monkeypatch.chdir(tmp_path)
        assert bootstrap.main("gs://b/app") == 0
        pkg_root = str(Path(tony_tpu.__file__).resolve().parent.parent)
        assert pkg_root in seen["pp"].split(os.pathsep)

    def test_relearn_without_node_specs_raises_clearly(self):
        t = FakeTransport()
        t.expect("GET", r"queuedResources/ghost$", 200, {})
        api = GcpQueuedResourceApi(
            "proj", "z", transport=t, runner=FakeRunner()
        )
        with pytest.raises(RuntimeError, match="no node specs"):
            api.start_executor("ghost", 0, {})

    def test_gs_history_read_path(self, fake_storage):
        """Writers gained gs://; the readers must see the same jobs —
        list/jhist/config/final all through the object listing."""
        from tony_tpu.conf.configuration import TonyConfiguration
        from tony_tpu.history.reader import (
            job_config,
            job_final_status,
            list_jobs,
        )
        from tony_tpu.history.writer import (
            JobMetadata,
            create_history_file,
            setup_job_dir,
            write_config_file,
            write_final_status,
        )

        loc = "gs://b/hist"
        for app, ms, status in [
            ("application_1_a", 1_000, "SUCCEEDED"),
            ("application_1_b", 2_000, "FAILED"),
        ]:
            job_dir = setup_job_dir(loc, app, ms)
            conf = TonyConfiguration()
            conf.set("tony.application.name", f"name-{app}")
            write_config_file(job_dir, conf)
            write_final_status(job_dir, {"state": status, "stats": {}})
            create_history_file(
                job_dir, JobMetadata.new(app, ms, status, user="u")
            )
        jobs = list_jobs(loc)
        assert [j.app_id for j in jobs] == [
            "application_1_b", "application_1_a"
        ]
        assert job_config(loc, "application_1_a")[
            "tony.application.name"] == "name-application_1_a"
        assert job_final_status(loc, "application_1_b")["state"] == "FAILED"
        assert job_config(loc, "application_9_x") is None

    def test_cluster_submit_gs_staging_uses_tempdir(self, tmp_path,
                                                    monkeypatch):
        """A gs:// staging location must not be treated as a local path
        for the framework lib dir (no literal 'gs:/...' dirs in cwd)."""
        from tony_tpu.client import cli
        from tony_tpu.conf import keys as _keys

        captured = {}

        class FakeClient:
            def __init__(self):
                from tony_tpu.conf.configuration import TonyConfiguration

                self.conf = TonyConfiguration()
                self.conf.set(_keys.K_STAGING_LOCATION, "gs://bkt/stage")

            def init(self, argv):
                return self

            def run(self):
                captured["lib"] = self.conf.get_str(_keys.K_LIB_PATH)
                assert Path(captured["lib"]).is_dir()
                return 0

        monkeypatch.setattr(cli, "TonyClient", FakeClient)
        monkeypatch.chdir(tmp_path)
        assert cli.cluster_submit([]) == 0
        assert not captured["lib"].startswith(str(tmp_path))
        assert "gs:" not in captured["lib"]
        assert not list(tmp_path.iterdir())  # nothing littered in cwd


class TestBackendSelection:
    def test_gcp_project_requires_gs_staging(self, tmp_path):
        """Coordinator main() refuses a GCP backend without gs:// staging —
        remote bootstraps could never localize the job."""
        import subprocess
        import sys

        from tony_tpu import constants
        from tony_tpu.conf.configuration import TonyConfiguration

        conf = TonyConfiguration()
        conf.set("tony.gcp.project", "proj")
        conf.set("tony.worker.instances", 1)
        conf.write_final(tmp_path / constants.TONY_FINAL_CONF)
        out = subprocess.run(
            [sys.executable, "-m", "tony_tpu.coordinator.app_master",
             "--app-dir", str(tmp_path), "--app-id", "app_x"],
            capture_output=True, text=True, timeout=120,
        )
        assert out.returncode != 0
        assert "gs://" in out.stderr


class TestJanitor:
    """Cloud-resource janitor (VERDICT r4 weak #5): a coordinator that
    dies uncleanly after create_slice leaks ACTIVE queued resources; a
    SECOND process must be able to find them by the deterministic
    {app}-{job} prefix and free them — the TPU-VM stand-in for YARN's RM
    reaping an expired AM's containers."""

    def _listing(self, *names_states):
        return {
            "queuedResources": [
                {
                    "name": f"projects/p/locations/z/queuedResources/{n}",
                    "state": {"state": s},
                    "tpu": {"nodeSpec": [{"node": {}}]},
                }
                for n, s in names_states
            ]
        }

    def test_list_queued_resources_filters_and_pages(self):
        t = FakeTransport()
        t.expect(
            "GET", r"/queuedResources$", 200,
            {**self._listing(("app1-worker", "ACTIVE")),
             "nextPageToken": "p2"},
        )
        t.expect(
            "GET", r"/queuedResources\?pageToken=p2$", 200,
            self._listing(("app1-ps", "CREATING"), ("other-worker", "ACTIVE")),
        )
        api = GcpQueuedResourceApi("p", "z", transport=t)
        got = api.list_queued_resources("app1")
        assert [(r["name"], r["state"], r["nodes"]) for r in got] == [
            ("app1-worker", "ACTIVE", 1), ("app1-ps", "CREATING", 1),
        ]

    def test_second_process_frees_crashed_coordinators_slices(self, capsys):
        """The crash story end to end at the CLI: coordinator process A
        creates a slice group and dies without stop_all; process B runs
        ``cli cleanup --prefix <app>`` and the leaked group is deleted —
        and only it (another app's resources survive)."""
        from tony_tpu.client.cli import cleanup_resources

        # Process A: create, then "crash" (no delete ever issued).
        ta = FakeTransport()
        ta.expect("POST", r"queued_resource_id=app9-worker", 200, {})
        apia = GcpQueuedResourceApi("p", "z", transport=ta)
        apia.create_slice("app9-worker", "v5litepod-8", 1)
        del apia  # OOM / preemption / kill -9

        # Process B: fresh api (no in-memory _groups), finds by prefix.
        tb = FakeTransport()
        tb.expect(
            "GET", r"/queuedResources$", 200,
            self._listing(("app9-worker", "ACTIVE"),
                          ("other-app", "ACTIVE")),
        )
        tb.expect("DELETE", r"/queuedResources/app9-worker\?force=true",
                  200, {})
        apib = GcpQueuedResourceApi("p", "z", transport=tb)
        rc = cleanup_resources(
            ["--project", "p", "--zone", "z", "--prefix", "app9"], api=apib
        )
        assert rc == 0
        assert "deleted app9-worker" in capsys.readouterr().out
        deletes = [u for (m, u, _) in tb.requests if m == "DELETE"]
        assert len(deletes) == 1 and "app9-worker" in deletes[0]

    def test_cleanup_dry_run_deletes_nothing(self, capsys):
        from tony_tpu.client.cli import cleanup_resources

        t = FakeTransport()
        t.expect("GET", r"/queuedResources$", 200,
                 self._listing(("app2-worker", "SUSPENDED")))
        api = GcpQueuedResourceApi("p", "z", transport=t)
        rc = cleanup_resources(
            ["--project", "p", "--zone", "z", "--prefix", "app2",
             "--dry-run"], api=api,
        )
        assert rc == 0
        assert "would delete app2-worker" in capsys.readouterr().out
        assert not [m for (m, _, _) in t.requests if m == "DELETE"]

    def test_cleanup_refuses_empty_prefix(self):
        from tony_tpu.client.cli import cleanup_resources

        rc = cleanup_resources(
            ["--project", "p", "--zone", "z"], api=object()
        )
        assert rc == 2

    def test_cli_list_prints_states(self, capsys):
        from tony_tpu.client.cli import list_resources

        t = FakeTransport()
        t.expect("GET", r"/queuedResources$", 200,
                 self._listing(("app3-worker", "ACTIVE")))
        api = GcpQueuedResourceApi("p", "z", transport=t)
        rc = list_resources(
            ["--project", "p", "--zone", "z", "--prefix", "app3"], api=api
        )
        assert rc == 0
        out = capsys.readouterr().out
        assert "app3-worker" in out and "ACTIVE" in out
