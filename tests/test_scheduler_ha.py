"""Control-plane HA (tony_tpu/scheduler/{journal,election}.py + the
daemon's recover/fencing paths): journal append/rotate/replay units,
loader hardening against torn bytes, leader-election + epoch-fence
units, kill-at-every-transition recovery, standby takeover, zombie
double-tick fencing, thin-client retry backoff, and the slow failover
chaos acceptance e2e (SIGKILL the daemon mid-run; nothing is lost,
nothing runs twice, goodput folds exactly once)."""

import json
import os
import signal
import subprocess
import sys
import time
from pathlib import Path

import pytest

from tony_tpu.conf import keys
from tony_tpu.conf.configuration import TonyConfiguration
from tony_tpu.mini import MiniTonyCluster
from tony_tpu.resilience.faults import (
    FaultPlan,
    FaultPlanError,
    SCHEDULER_PHASES,
    SchedulerFaults,
)
from tony_tpu.scheduler import (
    FileElectionBackend,
    JobState,
    LeaseElection,
    MemoryElectionBackend,
    SchedulerDaemon,
    SchedulerJournal,
)
from tony_tpu.scheduler import journal as wal
from tony_tpu.scheduler.http import scheduler_request

FIXTURES = Path(__file__).resolve().parent / "fixtures"
REPO_ROOT = Path(__file__).resolve().parent.parent


def _fixture_env() -> dict[str, str]:
    """Env for fixture daemons run as subprocesses: the source tree on
    PYTHONPATH (the repo may not be pip-installed) and CPU-only jax."""
    env = dict(os.environ)
    existing = env.get("PYTHONPATH", "")
    env["PYTHONPATH"] = str(REPO_ROOT) + (
        os.pathsep + existing if existing else ""
    )
    env.setdefault("JAX_PLATFORMS", "cpu")
    return env


# ---------------------------------------------------------------------------
# Journal units
# ---------------------------------------------------------------------------
class TestJournal:
    def test_append_is_monotonic_and_loads_in_order(self, tmp_path):
        j = SchedulerJournal(tmp_path / "j.jsonl")
        s1 = j.append(wal.J_JOB_QUEUED, ts_ms=1, job_id="a")
        s2 = j.append(wal.J_JOB_LAUNCHED, ts_ms=2, job_id="a")
        assert (s1, s2) == (1, 2)
        assert j.last_seq == 2
        kinds = [r["kind"] for r in SchedulerJournal.load(tmp_path / "j.jsonl")]
        assert kinds == [wal.J_JOB_QUEUED, wal.J_JOB_LAUNCHED]

    def test_torn_tail_is_skipped(self, tmp_path):
        """A SIGKILL mid-append leaves half a line; the loader must
        keep every complete record and drop only the torn tail."""
        path = tmp_path / "j.jsonl"
        j = SchedulerJournal(path)
        j.append(wal.J_JOB_QUEUED, ts_ms=1, job_id="a")
        j.append(wal.J_JOB_QUEUED, ts_ms=2, job_id="b")
        with open(path, "ab") as f:
            f.write(b'{"seq": 3, "ts_ms": 3, "kind": "job_laun')
        records = SchedulerJournal.load(path)
        assert [r["job_id"] for r in records] == ["a", "b"]
        # And a journal reopened over the torn file continues PAST the
        # highest parseable seq — never reuses one.
        assert SchedulerJournal(path).append(
            wal.J_JOB_QUEUED, ts_ms=4, job_id="c"
        ) == 3

    def test_garbage_lines_are_skipped(self, tmp_path):
        path = tmp_path / "j.jsonl"
        path.write_bytes(
            b'\x00\xffgarbage\n'
            b'{"seq": 1, "ts_ms": 1, "kind": "job_queued", "job_id": "a"}\n'
            b'[1, 2, 3]\n'
            b'{"seq": "not-an-int", "kind": "job_queued"}\n'
            b'{"no": "kind", "seq": 9}\n'
        )
        records = SchedulerJournal.load(path)
        assert len(records) == 1 and records[0]["job_id"] == "a"

    def test_rotate_drops_folded_prefix(self, tmp_path):
        path = tmp_path / "j.jsonl"
        j = SchedulerJournal(path)
        for i in range(5):
            j.append(wal.J_JOB_QUEUED, ts_ms=i, job_id=f"j{i}")
        assert j.rotate(up_to_seq=3) == 2
        seqs = [r["seq"] for r in SchedulerJournal.load(path)]
        assert seqs == [4, 5]
        # seq keeps counting from where it was, not from the survivors.
        assert j.append(wal.J_JOB_QUEUED, ts_ms=9, job_id="x") == 6
        assert j.records_since_rotate == 3

    def test_resync_continues_past_foreign_records(self, tmp_path):
        """A standby taking over a shared journal must continue the seq
        sequence past the dead leader's records, not collide."""
        path = tmp_path / "j.jsonl"
        leader = SchedulerJournal(path)
        standby = SchedulerJournal(path)  # opened when journal was empty
        leader.append(wal.J_JOB_QUEUED, ts_ms=1, job_id="a")
        leader.append(wal.J_JOB_LAUNCHED, ts_ms=2, job_id="a")
        assert standby.resync() == 2
        assert standby.append(wal.J_JOB_FINISHED, ts_ms=3, job_id="a",
                              state="SUCCEEDED") == 3

    def test_snapshot_loader_degrades_to_none(self, tmp_path):
        assert wal.load_snapshot(tmp_path / "missing.json") is None
        torn = tmp_path / "torn.json"
        torn.write_bytes(b'{"journal_seq": 12, "jobs": [')
        assert wal.load_snapshot(torn) is None
        not_a_dict = tmp_path / "list.json"
        not_a_dict.write_text("[1, 2]")
        assert wal.load_snapshot(not_a_dict) is None


# ---------------------------------------------------------------------------
# Replay units
# ---------------------------------------------------------------------------
def _rec(seq, kind, **fields):
    return {"seq": seq, "ts_ms": seq, "kind": kind, **fields}


class TestReplay:
    def test_job_lifecycle_folds(self):
        out = wal.replay(None, [
            _rec(1, wal.J_JOB_QUEUED, job_id="a", app_dir="/x",
                 priority=2, tenant="t", submit_ms=1, seq_no=1),
            _rec(2, wal.J_SLICE_LEASED, slice_id="s1", job_id="a",
                 profile="local", workspace="/w", expires_ms=99),
            _rec(3, wal.J_JOB_LAUNCHED, job_id="a", app_id="app1",
                 slice_id="s1", attempt=1),
            _rec(4, wal.J_JOB_FINISHED, job_id="a", state="SUCCEEDED"),
            _rec(5, wal.J_SLICE_RELEASED, slice_id="s1", job_id="a",
                 healthy=True),
        ])
        assert out["journal_seq"] == 5
        job = out["jobs"]["a"]
        assert job["state"] == "SUCCEEDED"
        assert job["app_ids"] == ["app1"]
        assert out["slices"]["s1"]["state"] == "FREE"

    def test_watermark_skips_snapshotted_records(self):
        snapshot = {"journal_seq": 2,
                    "jobs": [{"job_id": "a", "state": "RUNNING",
                              "seq": 1}]}
        out = wal.replay(snapshot, [
            _rec(1, wal.J_JOB_QUEUED, job_id="a"),       # folded already
            _rec(2, wal.J_JOB_LAUNCHED, job_id="a"),     # folded already
            _rec(3, wal.J_JOB_FINISHED, job_id="a", state="FAILED"),
        ])
        assert out["jobs"]["a"]["state"] == "FAILED"
        assert out["journal_seq"] == 3

    def test_goodput_folds_exactly_once(self):
        """The idempotence contract: an attempt id in the snapshot's
        folded list (or seen twice in the tail) must not double-count."""
        snapshot = {"journal_seq": 0, "folded": ["app-old"],
                    "goodput": {"tenants": {"t": {"productive": 10.0}}}}
        out = wal.replay(snapshot, [
            _rec(1, wal.J_GOODPUT_FOLDED, app_id="app-old", tenant="t",
                 chip_seconds={"productive": 10.0}),    # replayed fold
            _rec(2, wal.J_GOODPUT_FOLDED, app_id="app-new", tenant="t",
                 chip_seconds={"productive": 5.0}, queued_chip_s=1.0),
            _rec(3, wal.J_GOODPUT_FOLDED, app_id="app-new", tenant="t",
                 chip_seconds={"productive": 5.0}),     # duplicate
        ])
        assert out["tenants"]["t"]["productive"] == 15.0
        assert out["tenants"]["t"]["queued"] == 1.0
        assert sorted(out["folded"]) == ["app-new", "app-old"]

    def test_queued_jobs_preserve_priority_band_order(self):
        out = wal.replay(None, [
            _rec(1, wal.J_JOB_QUEUED, job_id="lo1", priority=0, seq_no=1),
            _rec(2, wal.J_JOB_QUEUED, job_id="hi1", priority=5, seq_no=2),
            _rec(3, wal.J_JOB_QUEUED, job_id="hi2", priority=5, seq_no=3),
        ])
        assert [j["job_id"] for j in wal.queued_jobs(out)] == \
            ["hi1", "hi2", "lo1"]

    def test_unhealthy_release_and_retire_drop_slice(self):
        out = wal.replay(None, [
            _rec(1, wal.J_SLICE_LEASED, slice_id="s1", job_id="a"),
            _rec(2, wal.J_SLICE_RELEASED, slice_id="s1", healthy=False),
            _rec(3, wal.J_SLICE_LEASED, slice_id="s2", job_id="b"),
            _rec(4, wal.J_SLICE_RETIRED, slice_id="s2",
                 reason="lease_expired"),
        ])
        assert out["slices"] == {}


# ---------------------------------------------------------------------------
# Election units
# ---------------------------------------------------------------------------
class TestElection:
    def test_second_daemon_blocks_while_leader_lives(self, tmp_path):
        a = LeaseElection(FileElectionBackend(tmp_path, node_id="a"))
        b = LeaseElection(FileElectionBackend(tmp_path, node_id="b"))
        assert a.try_acquire() and a.epoch == 1
        assert not b.try_acquire()
        a.release()
        assert b.try_acquire() and b.epoch == 2

    def test_sigkill_flock_drop_means_instant_takeover(self, tmp_path):
        """abandon() leaves exactly what SIGKILL leaves: a fresh
        heartbeat but a free flock — the standby takes over on the
        fast path without waiting out the lease."""
        a = LeaseElection(FileElectionBackend(tmp_path, node_id="a"))
        assert a.try_acquire()
        a.abandon()
        b = LeaseElection(FileElectionBackend(tmp_path, node_id="b"))
        assert b.try_acquire() and b.epoch == 2

    def test_stale_heartbeat_is_stolen(self, tmp_path):
        """The wedged-alive leader: flock held, heartbeat stale — a
        standby must steal by bumping the epoch past it."""
        clock = [1000]
        a = LeaseElection(
            FileElectionBackend(tmp_path, node_id="a",
                                clock_ms=lambda: clock[0]),
            lease_ms=500, clock_ms=lambda: clock[0],
        )
        assert a.try_acquire()
        b = LeaseElection(
            FileElectionBackend(tmp_path, node_id="b",
                                clock_ms=lambda: clock[0]),
            lease_ms=500, clock_ms=lambda: clock[0],
        )
        assert not b.try_acquire()  # fresh heartbeat, flock held
        clock[0] += 10_000          # a's heartbeat goes stale un-renewed
        assert b.try_acquire() and b.epoch == 2
        # The deposed holder's next heartbeat fails — stop actuating.
        clock[0] += 1000
        assert not a.heartbeat()
        assert not a.is_leader

    def test_check_fence_catches_deposition(self, tmp_path):
        backend = MemoryElectionBackend(node_id="a")
        a = LeaseElection(backend, lease_ms=10 ** 9)
        assert a.try_acquire()
        assert a.check_fence()
        backend.depose("usurper")
        assert not a.check_fence()
        assert not a.is_leader


# ---------------------------------------------------------------------------
# Scheduler fault-plan validation + windows
# ---------------------------------------------------------------------------
class TestSchedulerFaults:
    def test_crash_phase_is_validated(self):
        with pytest.raises(FaultPlanError, match="at must be one of"):
            FaultPlan.parse(json.dumps({"faults": [
                {"action": "crash_scheduler", "at": "somewhere"},
            ]}))
        plan = FaultPlan.parse(json.dumps({"faults": [
            {"action": "crash_scheduler", "at": phase}
            for phase in SCHEDULER_PHASES
        ]}))
        assert len(plan.specs) == 3

    def test_partition_requires_window(self):
        with pytest.raises(FaultPlanError, match="ms must be nonzero"):
            FaultPlan.parse(json.dumps({"faults": [
                {"action": "partition_scheduler"},
            ]}))

    def test_partition_window_opens_and_closes(self):
        plan = FaultPlan.parse(json.dumps({"faults": [
            {"action": "partition_scheduler", "after_ms": 1000, "ms": 500},
        ]}))
        clock = [0.0]
        faults = SchedulerFaults(plan, clock=lambda: clock[0])
        assert not faults.rpc_partitioned()
        clock[0] = 1.2   # 1200 ms after daemon birth: inside the window
        assert faults.rpc_partitioned()
        clock[0] = 1.6   # window over
        assert not faults.rpc_partitioned()


# ---------------------------------------------------------------------------
# Thin-client retry backoff
# ---------------------------------------------------------------------------
class TestClientRetries:
    def test_backoff_is_bounded_exponential(self):
        """Against a dead port every attempt refuses; the sleeps
        between them must double from backoff_ms and stay bounded."""
        delays = []
        with pytest.raises(OSError):
            scheduler_request(
                "127.0.0.1:1", "/api/state", timeout_s=0.5,
                retries=6, backoff_ms=100, sleep=delays.append,
            )
        assert delays == [0.1, 0.2, 0.4, 0.8, 0.8]  # capped at 8x

    def test_single_retry_never_sleeps(self):
        delays = []
        with pytest.raises(OSError):
            scheduler_request(
                "127.0.0.1:1", "/api/state", timeout_s=0.5,
                retries=1, backoff_ms=100, sleep=delays.append,
            )
        assert delays == []


# ---------------------------------------------------------------------------
# Daemon-level recovery (mini-cluster, jax-free fixtures)
# ---------------------------------------------------------------------------
@pytest.fixture()
def cluster(tmp_path):
    with MiniTonyCluster(tmp_path) as c:
        yield c


def _sched_conf(cluster, **kv):
    conf = cluster.base_conf()
    conf.set(keys.K_SCHED_TICK_MS, 50)
    for k, v in kv.items():
        conf.set(k, v)
    return conf


def _job_conf(cluster, fixture, **kv):
    conf = cluster.base_conf()
    conf.set(keys.K_EXECUTES, str(FIXTURES / fixture))
    conf.set(keys.K_PYTHON_BINARY, sys.executable)
    conf.set(keys.instances_key("worker"), 1)
    conf.set(keys.instances_key("ps"), 0)
    for k, v in kv.items():
        conf.set(k, v)
    return conf


def _events(daemon, kind):
    return [e for e in daemon.events.to_dicts() if e["kind"] == kind]


def _crash(daemon):
    """Kill an in-process daemon the way SIGKILL would: loop stopped
    dead, flock dropped, heartbeat left to go stale, no clean release,
    no final state publish."""
    daemon._stop.set()
    daemon._wake.set()
    if daemon._thread is not None:
        daemon._thread.join(timeout=30)
    daemon.election.abandon()


def test_recovery_restores_queue_in_priority_band_order(cluster):
    """Queued jobs survive a daemon crash and relaunch in exactly the
    order the dead daemon would have served (priority DESC, arrival
    ASC) — with zero slots the first daemon can only queue."""
    base = cluster.base_dir / "sched"
    d1 = SchedulerDaemon(base, conf=_sched_conf(
        cluster, **{keys.K_SCHED_MAX_SLICES: 0},
    )).start(serve_http=False)
    lo = d1.submit(_job_conf(cluster, "exit_0.py",
                             **{keys.K_SCHED_PRIORITY: 0}))
    hi1 = d1.submit(_job_conf(cluster, "exit_0.py",
                              **{keys.K_SCHED_PRIORITY: 5}))
    hi2 = d1.submit(_job_conf(cluster, "exit_0.py",
                              **{keys.K_SCHED_PRIORITY: 5}))
    _crash(d1)

    d2 = SchedulerDaemon(base, conf=_sched_conf(
        cluster, **{keys.K_SCHED_MAX_SLICES: 1},
    )).start(serve_http=False)
    try:
        for job_id in (hi1, hi2, lo):
            assert d2.wait_job(job_id, 90) is JobState.SUCCEEDED
        recovered = _events(d2, "scheduler_recovered")
        assert len(recovered) == 1
        assert recovered[0]["resubmitted"] == 3
        launches = [e["job_id"] for e in _events(d2, "job_launched")]
        assert launches == [hi1, hi2, lo]
        # Fresh ids keep counting past recovered ones — no collision.
        fresh = d2.submit(_job_conf(cluster, "exit_0.py"))
        assert fresh not in (lo, hi1, hi2)
        assert d2.wait_job(fresh, 90) is JobState.SUCCEEDED
    finally:
        d2.shutdown()


def test_daemon_boots_on_torn_journal_and_garbage_snapshot(cluster):
    """Loader hardening end-to-end: a torn journal tail plus a garbage
    snapshot must degrade to journal-replay recovery, not a boot
    crash."""
    base = cluster.base_dir / "sched"
    d1 = SchedulerDaemon(base, conf=_sched_conf(
        cluster, **{keys.K_SCHED_MAX_SLICES: 0},
    )).start(serve_http=False)
    job_id = d1.submit(_job_conf(cluster, "exit_0.py"))
    _crash(d1)
    with open(base / wal.JOURNAL_FILE, "ab") as f:
        f.write(b'{"seq": 999, "kind": "job_qu')       # torn tail
    (base / "scheduler-state.json").write_bytes(b"\x00\xffnot json")

    d2 = SchedulerDaemon(base, conf=_sched_conf(
        cluster, **{keys.K_SCHED_MAX_SLICES: 1},
    )).start(serve_http=False)
    try:
        assert d2.wait_job(job_id, 90) is JobState.SUCCEEDED
    finally:
        d2.shutdown()


@pytest.mark.parametrize("phase", SCHEDULER_PHASES)
def test_kill_at_every_transition_recovers(cluster, phase):
    """The kill-at-every-transition contract: a daemon SIGKILLed
    (os._exit via the fault plan) at each journal/actuation boundary
    leaves a base dir a fresh daemon recovers — the job is not lost,
    not launched twice, and finishes."""
    base = cluster.base_dir
    proc = subprocess.Popen(
        [sys.executable, str(FIXTURES / "sched_kill_stage.py"),
         str(base), phase, str(FIXTURES / "exit_0.py")],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        env=_fixture_env(),
    )
    job_id = proc.stdout.readline().strip()
    rc = proc.wait(timeout=120)
    assert rc == 1, f"daemon did not crash at {phase} (exit {rc})"
    assert job_id.startswith("job_")

    d2 = SchedulerDaemon(base / "sched", conf=_sched_conf(
        cluster, **{keys.K_SCHED_MAX_SLICES: 1},
    )).start(serve_http=False)
    try:
        assert d2.wait_job(job_id, 120) is JobState.SUCCEEDED
        # Exactly one post-recovery launch — never a duplicate.
        launches = _events(d2, "job_launched")
        assert [e["job_id"] for e in launches] == [job_id]
        job = d2.job(job_id)
        if phase == "post-journal":
            # The journaled-but-never-created attempt was classified
            # dead and requeued: the successful run is attempt 2.
            assert job.attempts == 2
        elif phase == "mid-tick":
            assert job.attempts == 1
        # Goodput folded exactly once for the one real attempt.
        state = d2.state_json()
        assert len(state["folded"]) == len(set(state["folded"])) == 1
    finally:
        d2.shutdown()


def test_standby_refuses_submit_then_takes_over(cluster):
    """Active/standby pair on one base dir: the standby rejects
    submissions while the leader lives, then wins the seat at a higher
    epoch once the leader dies and serves the same queue."""
    base = cluster.base_dir / "sched"
    conf_a = _sched_conf(cluster, **{keys.K_SCHED_MAX_SLICES: 1,
                                     keys.K_SCHED_HA_LEASE_MS: 500,
                                     keys.K_SCHED_HA_NODE_ID: "a"})
    conf_b = _sched_conf(cluster, **{keys.K_SCHED_MAX_SLICES: 1,
                                     keys.K_SCHED_HA_LEASE_MS: 500,
                                     keys.K_SCHED_HA_NODE_ID: "b"})
    a = SchedulerDaemon(base, conf=conf_a).start(serve_http=False)
    b = SchedulerDaemon(base, conf=conf_b).start(serve_http=False)
    try:
        assert a.election.is_leader and not b.election.is_leader
        with pytest.raises(RuntimeError, match="not the leader"):
            b.submit(_job_conf(cluster, "exit_0.py"))
        epoch_a = a.election.epoch
        _crash(a)
        deadline = time.monotonic() + 30
        while not b.election.is_leader:
            assert time.monotonic() < deadline, "standby never took over"
            time.sleep(0.05)
        assert b.election.epoch > epoch_a
        job_id = b.submit(_job_conf(cluster, "exit_0.py"))
        assert b.wait_job(job_id, 90) is JobState.SUCCEEDED
        assert len(_events(b, "leader_elected")) == 1
    finally:
        b.shutdown()


def test_deposed_zombie_leader_cannot_double_actuate(cluster):
    """The epoch-fence acceptance: a leader whose lease was stolen
    mid-tick (heartbeat still inside its throttle window, so only the
    fence can catch it) must abdicate instead of launching — across
    TWO ticks nothing lands in the journal past the deposition."""
    base = cluster.base_dir / "sched"
    backend = MemoryElectionBackend(node_id="a")
    daemon = SchedulerDaemon(
        base,
        conf=_sched_conf(cluster, **{keys.K_SCHED_MAX_SLICES: 1}),
        election=LeaseElection(backend, lease_ms=10 ** 9),
    )
    job_id = daemon.submit(_job_conf(cluster, "exit_0.py"))
    seq_before = daemon.journal.last_seq

    backend.depose("usurper")
    daemon._tick()  # zombie tick 1: pop → fence check → abdicate
    deadline = time.monotonic() + 10
    while not daemon._stop.is_set():
        assert time.monotonic() < deadline, "zombie never abdicated"
        time.sleep(0.02)
    daemon._tick()  # zombie tick 2: heartbeat fails outright

    records = SchedulerJournal.load(base / wal.JOURNAL_FILE)
    post = [r for r in records if r["seq"] > seq_before]
    assert not any(r["kind"] in (wal.J_JOB_LAUNCHED, wal.J_SLICE_LEASED)
                   for r in post), post
    job = daemon.job(job_id)
    assert job is not None and not job.state.terminal
    assert job.attempts == 0


def test_partition_window_rides_out_on_client_retries(cluster):
    """partition_scheduler drops every RPC inside its window; a thin
    client with retry backoff must ride it out and read state."""
    import urllib.request

    plan = json.dumps({"faults": [
        {"action": "partition_scheduler", "after_ms": 0, "ms": 700},
    ]})
    daemon = SchedulerDaemon(
        cluster.base_dir / "sched",
        conf=_sched_conf(cluster, **{keys.K_FAULT_PLAN: plan}),
    ).start(serve_http=True)
    try:
        addr = f"127.0.0.1:{daemon.http_server.port}"
        # Inside the window a bare request dies...
        with pytest.raises((OSError, ValueError)):
            urllib.request.urlopen(f"http://{addr}/api/state", timeout=5)
        # ...but the retrying client path lands once it closes.
        state = scheduler_request(addr, "/api/state", timeout_s=5,
                                  retries=8, backoff_ms=200)
        assert state["ha"]["epoch"] >= 1
    finally:
        daemon.shutdown()


def test_detached_attempt_runs_and_journals(cluster):
    """Detached mode smoke (tier-1): the coordinator runs as its own
    session-leader subprocess, the daemon tracks it via
    coordinator.pid + final-status.json, and the journal says so."""
    daemon = SchedulerDaemon(
        cluster.base_dir / "sched",
        conf=_sched_conf(cluster, **{keys.K_SCHED_MAX_SLICES: 1,
                                     keys.K_SCHED_DETACHED: True}),
    ).start(serve_http=False)
    try:
        job_id = daemon.submit(_job_conf(cluster, "exit_0.py"))
        assert daemon.wait_job(job_id, 120) is JobState.SUCCEEDED
        job = daemon.job(job_id)
        app_dir = Path(job.app_dir)
        assert (app_dir / "final-status.json").is_file()
        assert (app_dir / "coordinator.pid").is_file()
        launched = [r for r in SchedulerJournal.load(
            daemon.base_dir / wal.JOURNAL_FILE
        ) if r["kind"] == wal.J_JOB_LAUNCHED]
        assert len(launched) == 1 and launched[0]["detached"] is True
    finally:
        daemon.shutdown()


# ---------------------------------------------------------------------------
# Failover chaos acceptance (slow): SIGKILL mid-run, nothing lost,
# nothing twice
# ---------------------------------------------------------------------------
@pytest.mark.slow
def test_failover_chaos_sigkill_daemon_mid_run(tmp_path):
    """The acceptance shape: SIGKILL the daemon with one RUNNING
    detached job, one quota-blocked QUEUED job, and one warm-idle
    slice. The restarted daemon re-attaches the live attempt WITHOUT
    restarting it, relaunches the queued job in order on the re-adopted
    warm slice, both SUCCEED with exactly one attempt record each, and
    tenant goodput folds exactly once per attempt."""
    base = tmp_path
    marker = base / "marker.txt"
    proc = subprocess.Popen(
        [sys.executable, str(FIXTURES / "sched_ha_chaos.py"),
         str(base), str(marker), "15"],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        env=_fixture_env(),
    )
    warm_id, run_id, queued_id = proc.stdout.readline().split()

    state_file = base / "sched" / "scheduler-state.json"

    def shape_reached() -> bool:
        if not marker.exists() or not state_file.is_file():
            return False
        try:
            state = json.loads(state_file.read_text())
        except ValueError:
            return False  # racing the atomic replace
        jobs = {j["job_id"]: j["state"] for j in state.get("jobs", [])}
        slices = [s["state"] for s in state.get("pool", [])]
        return (jobs.get(warm_id) == "SUCCEEDED"
                and jobs.get(run_id) == "RUNNING"
                and jobs.get(queued_id) == "QUEUED"
                and "FREE" in slices)
    deadline = time.monotonic() + 120
    while not shape_reached():
        assert proc.poll() is None, "chaos daemon died before the kill"
        assert time.monotonic() < deadline, "acceptance shape never formed"
        time.sleep(0.1)
    os.kill(proc.pid, signal.SIGKILL)
    proc.wait(timeout=30)

    conf = TonyConfiguration()
    conf.set(keys.K_STAGING_LOCATION, str(base / "staging"))
    conf.set(keys.K_HISTORY_LOCATION, str(base / "history"))
    conf.set(keys.K_AM_STOP_GRACE_MS, 0)
    conf.set(keys.K_SCHED_TICK_MS, 50)
    conf.set(keys.K_SCHED_MAX_SLICES, 2)
    conf.set(keys.K_SCHED_DETACHED, True)
    conf.set(keys.K_SCHED_TENANT_QUOTA, 1)
    d2 = SchedulerDaemon(base / "sched", conf=conf).start(serve_http=False)
    try:
        # The live attempt was ADOPTED, not restarted.
        adopted = _events(d2, "attempt_adopted")
        assert [e["job_id"] for e in adopted] == [run_id]
        assert d2.wait_job(run_id, 120) is JobState.SUCCEEDED
        assert d2.wait_job(queued_id, 120) is JobState.SUCCEEDED

        # Exactly one attempt record each — no restart, no duplicate.
        assert d2.job(run_id).attempts == 1
        assert d2.job(queued_id).attempts == 1
        assert len(d2.job(run_id).app_ids) == 1
        # The adopted job's worker ran exactly once (one marker line).
        assert marker.read_text().splitlines() == ["resume=None"]
        # The queued job relaunched on the re-adopted WARM slice.
        launches = _events(d2, "job_launched")
        assert [e["job_id"] for e in launches] == [queued_id]
        assert launches[0]["warm"] is True

        # Goodput folded exactly once per attempt, across both lives:
        # every goodput_folded record in the whole journal names a
        # distinct attempt, and the recovered daemon's folded set
        # matches.
        records = SchedulerJournal.load(base / "sched" / wal.JOURNAL_FILE)
        folds = [r["app_id"] for r in records
                 if r["kind"] == wal.J_GOODPUT_FOLDED]
        assert len(folds) == len(set(folds)) == 3
        state = d2.state_json()
        assert sorted(state["folded"]) == sorted(folds)
        assert state["ha"]["epoch"] >= 2  # takeover bumped the epoch
    finally:
        d2.shutdown()
