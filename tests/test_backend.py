"""TPU resource model: slice topology planning (plan_slices), conf-driven
planning (plan_slices_from_conf), and the TpuVmBackend's async
provision-then-execute lifecycle against a fake TpuApi — the analogue of the
reference turning tony.<job>.gpus into YARN GPU capabilities and launching
containers through async RM callbacks (Utils.setCapabilityGPU:146-152,
TonyApplicationMaster.java:876-885, :980-989)."""

import json

import pytest

from tony_tpu import constants
from tony_tpu.conf import keys
from tony_tpu.conf.configuration import TonyConfiguration
from tony_tpu.coordinator.backend import (
    SlicePlan,
    TpuVmBackend,
    plan_slices,
    plan_slices_from_conf,
)
from tony_tpu.coordinator.session import SessionStatus
from tony_tpu.mini import MiniTonyCluster


# ---------------------------------------------------------------------------
# plan_slices
# ---------------------------------------------------------------------------
def test_single_host_exact_fit():
    plan = plan_slices(1, 8, "v5e")
    assert plan == SlicePlan("v5litepod-8", 1, 1, 8)


def test_multi_host_single_slice():
    # 4 hosts x 4 chips -> v5litepod-16 (multihost v5e = 4-chip hosts)
    plan = plan_slices(4, 4, "v5e")
    assert plan == SlicePlan("v5litepod-16", 1, 4, 16)


def test_eight_chip_hosts_cannot_tile_multihost_v5e():
    # 2 hosts x 8 chips: no 2-host v5e slice exists (multihost hosts carry
    # 4 chips), so the planner falls back to 2 DCN-connected v5litepod-8s
    # rather than inventing an impossible 16-chip 2-host slice.
    plan = plan_slices(2, 8, "v5e")
    assert plan == SlicePlan("v5litepod-8", 2, 1, 8)


def test_every_plan_has_one_host_per_instance():
    # The invariant the scheduler depends on: one executor per host. 3 hosts
    # x 4 chips has no 3-host slice, so it becomes 3 DCN-connected
    # single-host slices — never a slice with host indexes the coordinator
    # would not launch.
    plan = plan_slices(3, 4, "v5e")
    assert plan == SlicePlan("v5litepod-4", 3, 1, 4)
    assert plan.total_hosts == 3


def test_strict_rejects_chip_overshoot():
    # no 3-chip shape exists; strict refuses to round 3 up to 4
    with pytest.raises(ValueError, match="strict"):
        plan_slices(1, 3, "v5e", strict=True)
    assert plan_slices(1, 3, "v5e").accelerator_type == "v5litepod-4"


def test_strict_accepts_exact_tiling():
    plan = plan_slices(4, 4, "v5e", strict=True)
    assert plan == SlicePlan("v5litepod-16", 1, 4, 16)


def test_strict_accepts_exact_multislice_tiling():
    # 128 hosts x 4 chips = 512 chips = 2 x v5litepod-256 (64 hosts each)
    plan = plan_slices(128, 4, "v5e", strict=True)
    assert plan == SlicePlan("v5litepod-256", 2, 64, 256)


def test_multislice_fallback_beyond_largest_shape():
    # 128 hosts x 4 chips = 512 chips > v5litepod-256 -> 2 DCN-connected
    # slices
    plan = plan_slices(128, 4, "v5e")
    assert plan.num_slices == 2 and plan.chips_per_slice == 256


def test_accelerator_type_pinning():
    # pin v5litepod-8 (1 host/slice): 4 hosts x 8 chips -> 4 slices
    plan = plan_slices(4, 8, "v5e", accelerator_type="v5litepod-8")
    assert plan == SlicePlan("v5litepod-8", 4, 1, 8)


def test_accelerator_type_strict_mismatch():
    with pytest.raises(ValueError, match="strict"):
        plan_slices(1, 4, "v5e", strict=True, accelerator_type="v5litepod-8")


def test_unknown_generation_and_accelerator():
    with pytest.raises(ValueError, match="generation"):
        plan_slices(1, 8, "v9z")
    with pytest.raises(ValueError, match="accelerator"):
        plan_slices(1, 8, "v5e", accelerator_type="v5litepod-7")


def test_v4_shapes():
    # v4-8 = 4 chips (the name counts TensorCores), one 4-chip host.
    assert plan_slices(1, 4, "v4").accelerator_type == "v4-8"
    # Multihost v4: 4 chips per host VM.
    assert plan_slices(4, 4, "v4") == SlicePlan("v4-32", 1, 4, 16)


def test_v5p_shapes_count_tensorcores():
    # v5p names count TensorCores like v4: v5p-32 = 16 chips on 4 hosts.
    assert plan_slices(1, 4, "v5p") == SlicePlan("v5p-8", 1, 1, 4)
    assert plan_slices(4, 4, "v5p") == SlicePlan("v5p-32", 1, 4, 16)
    # Topology strings that are accelerator names resolve by NAME.
    conf = _conf(**{
        keys.instances_key("worker"): 4,
        keys.tpus_key("worker"): 4,
        keys.K_TPU_TOPOLOGY: "v5p-32",
        keys.instances_key("ps"): 0,
    })
    assert plan_slices_from_conf(conf)["worker"] == SlicePlan(
        "v5p-32", 1, 4, 16
    )


def test_v6e_shapes_follow_v5e_pattern():
    # Trillium: names count chips, 8-chip single host, 4-chip multihost.
    assert plan_slices(1, 8, "v6e") == SlicePlan("v6e-8", 1, 1, 8)
    assert plan_slices(4, 4, "v6e") == SlicePlan("v6e-16", 1, 4, 16)
    assert plan_slices(128, 4, "v6e", strict=True) == SlicePlan(
        "v6e-256", 2, 64, 256
    )


# ---------------------------------------------------------------------------
# plan_slices_from_conf
# ---------------------------------------------------------------------------
def _conf(**kv):
    conf = TonyConfiguration()
    for k, v in kv.items():
        conf.set(k, v)
    return conf


def test_conf_planning_per_job_type():
    conf = _conf(**{
        keys.instances_key("worker"): 4,
        keys.tpus_key("worker"): 4,
        keys.instances_key("ps"): 1,  # no tpus -> no plan
    })
    plans = plan_slices_from_conf(conf)
    assert set(plans) == {"worker"}
    assert plans["worker"].chips_per_slice == 16


def test_conf_topology_key_selects_shape():
    conf = _conf(**{
        keys.instances_key("worker"): 4,
        keys.tpus_key("worker"): 8,
        keys.K_TPU_TOPOLOGY: "v5e-8",
        keys.instances_key("ps"): 0,
    })
    plans = plan_slices_from_conf(conf)
    assert plans["worker"] == SlicePlan("v5litepod-8", 4, 1, 8)


def test_conf_accelerator_type_alone_selects_generation():
    # tony.tpu.accelerator-type=v4-32 must find the v4 family without a
    # redundant tony.tpu.topology key.
    conf = _conf(**{
        keys.instances_key("worker"): 4,
        keys.tpus_key("worker"): 4,
        keys.K_TPU_ACCELERATOR_TYPE: "v4-32",
        keys.instances_key("ps"): 0,
    })
    plans = plan_slices_from_conf(conf)
    assert plans["worker"] == SlicePlan("v4-32", 1, 4, 16)


def test_conf_v4_topology_number_means_the_accelerator_name():
    # "v4-16" is a GCP accelerator name (16 TensorCores = 8 chips, 2
    # hosts) — the name reading must win over treating 16 as a chip count
    # (which would silently provision a v4-32).
    conf = _conf(**{
        keys.instances_key("worker"): 2,
        keys.tpus_key("worker"): 4,
        keys.K_TPU_TOPOLOGY: "v4-16",
        keys.instances_key("ps"): 0,
    })
    plans = plan_slices_from_conf(conf)
    assert plans["worker"] == SlicePlan("v4-16", 1, 2, 8)


def test_conf_bad_topology_raises():
    conf = _conf(**{
        keys.instances_key("worker"): 1,
        keys.tpus_key("worker"): 8,
        keys.K_TPU_TOPOLOGY: "v5e-7",
    })
    with pytest.raises(ValueError, match="legal"):
        plan_slices_from_conf(conf)


# ---------------------------------------------------------------------------
# TpuVmBackend against a fake TpuApi
# ---------------------------------------------------------------------------
class FakeTpuApi:
    """Slices become READY after `ready_after` polls; executors exit with
    `exit_code` after `run_polls` status checks."""

    def __init__(self, ready_after=2, run_polls=1, exit_code=0,
                 fail_slice=False):
        self.ready_after = ready_after
        self.run_polls = run_polls
        self.exit_code = exit_code
        self.fail_slice = fail_slice
        self.created: dict[str, tuple[str, int]] = {}
        self.deleted: list[str] = []
        self.started: list[tuple[str, int]] = []
        self.envs: list[dict] = []
        self.killed: list[object] = []
        self._state_polls: dict[str, int] = {}

    def create_slice(self, name, accelerator_type, num_slices):
        self.created[name] = (accelerator_type, num_slices)

    def slice_state(self, name):
        if self.fail_slice:
            return "FAILED"
        n = self._state_polls.get(name, 0) + 1
        self._state_polls[name] = n
        return "READY" if n >= self.ready_after else "CREATING"

    def start_executor(self, name, host_index, env):
        self.started.append((name, host_index))
        self.envs.append(dict(env))
        return {"polls": 0, "env": env}

    def executor_status(self, handle):
        handle["polls"] += 1
        return self.exit_code if handle["polls"] >= self.run_polls else None

    def kill_executor(self, handle):
        self.killed.append(handle)

    def delete_slice(self, name):
        self.deleted.append(name)


def _tpu_session(tmp_path, api, **conf_kv):
    cluster = MiniTonyCluster(tmp_path)
    conf = cluster.base_conf()
    # 4 hosts x 4 chips -> one v5litepod-16 (4-chip multihost v5e hosts).
    conf.set(keys.instances_key("worker"), 4)
    conf.set(keys.tpus_key("worker"), 4)
    conf.set(keys.instances_key("ps"), 0)
    conf.set(keys.K_EXECUTES, "unused_on_tpu_backend.py")
    for k, v in conf_kv.items():
        conf.set(k, v)

    from tony_tpu.coordinator.app_master import TonyCoordinator

    app_dir = tmp_path / "app"
    coordinator = TonyCoordinator(
        conf, app_dir, app_id="application_tpu_1",
        backend=TpuVmBackend(api, "application_tpu_1"),
    )
    status = coordinator.run()
    return status, coordinator, app_dir


def test_tpu_backend_full_session(tmp_path):
    api = FakeTpuApi()
    status, coordinator, app_dir = _tpu_session(tmp_path, api)
    assert status is SessionStatus.SUCCEEDED
    # one slice group created for the worker job, then deleted on teardown
    assert api.created == {"application_tpu_1-worker": ("v5litepod-16", 1)}
    assert api.deleted == ["application_tpu_1-worker"]
    # all four hosts got an executor only after the slice went READY
    assert sorted(api.started) == [
        ("application_tpu_1-worker", i) for i in range(4)
    ]
    assert coordinator.slice_plans["worker"].chips_per_slice == 16
    # final-status.json records the planned slice
    final = json.loads((app_dir / "final-status.json").read_text())
    assert final["slices"]["worker"]["accelerator_type"] == "v5litepod-16"


def test_tpu_backend_slice_failure_fails_session(tmp_path):
    api = FakeTpuApi(fail_slice=True)
    status, coordinator, _ = _tpu_session(tmp_path, api)
    assert status is SessionStatus.FAILED


def test_tpu_backend_env_carries_topology(tmp_path):
    api = FakeTpuApi()
    _tpu_session(tmp_path, api)
    assert api.envs, "no executor env captured"
    for env in api.envs:
        plan = json.loads(env[constants.TONY_SLICE_TOPOLOGY])
        assert plan["accelerator_type"] == "v5litepod-16"


def test_mixed_tpu_cpu_job_fails_gracefully(tmp_path):
    """A job type without a tpus ask on the TPU-only backend must fail the
    session through stop() (terminal status + history), not crash the
    coordinator."""
    api = FakeTpuApi()
    status, coordinator, app_dir = _tpu_session(
        tmp_path, api, **{keys.instances_key("ps"): 1}
    )
    assert status is SessionStatus.FAILED
    assert "scheduling failed" in coordinator.session.diagnostics
    assert (app_dir / "final-status.json").is_file()


def test_planning_failure_is_not_retried(tmp_path):
    """A conf-derived planning error is deterministic: with retries
    configured, the coordinator must fail once, not re-plan K times."""
    cluster = MiniTonyCluster(tmp_path)
    conf = cluster.base_conf()
    conf.set(keys.instances_key("worker"), 1)
    conf.set(keys.tpus_key("worker"), 3)
    conf.set(keys.instances_key("ps"), 0)
    conf.set(keys.K_TPU_SLICE_STRICT, True)
    conf.set(keys.K_AM_RETRY_COUNT, 3)
    conf.set(keys.K_EXECUTES, "unused.py")
    status, coordinator = cluster.run_job(conf, timeout_s=30)
    assert status is SessionStatus.FAILED
    assert coordinator.session.session_id == 1  # one session, no retries


def test_strict_illegal_topology_fails_session(tmp_path):
    cluster = MiniTonyCluster(tmp_path)
    conf = cluster.base_conf()
    conf.set(keys.instances_key("worker"), 3)
    conf.set(keys.tpus_key("worker"), 3)
    conf.set(keys.instances_key("ps"), 0)
    conf.set(keys.K_TPU_SLICE_STRICT, True)
    conf.set(keys.K_EXECUTES, "unused.py")
    status, coordinator = cluster.run_job(conf, timeout_s=30)
    assert status is SessionStatus.FAILED
    assert "slice planning failed" in coordinator.session.diagnostics
