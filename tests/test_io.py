"""Data-plane tests, modeled on the reference's TestReader
(tony-core/src/test/.../TestReader.java:41-80): exhaustive split-coverage
property check plus multi-file, multi-reader exactly-once reads on the
local filesystem."""

import json

import numpy as np
import pytest

from tony_tpu.io import (
    ShardedRecordReader,
    compute_read_split,
    create_read_info,
    sharded_batches,
)


class TestSplits:
    def test_property_full_non_overlapping_coverage(self):
        # TestReader.java:41-60: 1000 random totals; splits must tile the
        # range exactly.
        rng = np.random.default_rng(0)
        for _ in range(1000):
            total = int(rng.integers(0, 10_000))
            n = int(rng.integers(1, 20))
            pos = 0
            for i in range(n):
                start, length = compute_read_split(total, i, n)
                assert start == pos
                pos = start + length
            assert pos == total

    def test_read_info_maps_ranges_to_files(self):
        files = [("a", 10), ("b", 0), ("c", 25)]
        segs = [create_read_info(files, i, 3) for i in range(3)]
        # 35 bytes over 3 tasks: 12, 12, 11.
        flat = [(s.path, s.offset, s.length) for task in segs for s in task]
        assert flat == [
            ("a", 0, 10), ("c", 0, 2),       # task 0: 12
            ("c", 2, 12),                    # task 1: 12
            ("c", 14, 11),                   # task 2: 11
        ]

    def test_bad_args(self):
        with pytest.raises(ValueError):
            compute_read_split(10, 0, 0)
        with pytest.raises(ValueError):
            compute_read_split(10, 3, 3)


def _write_jsonl(path, ids):
    with open(path, "w") as f:
        for i in ids:
            f.write(json.dumps({"id": i, "pad": "x" * (i % 7)}) + "\n")


class TestJsonlReader:
    @pytest.mark.parametrize("num_tasks", [1, 2, 3, 5])
    def test_exactly_once_across_readers(self, tmp_path, num_tasks):
        files = []
        n = 0
        for fi, count in enumerate([57, 1, 0, 113]):
            p = tmp_path / f"part-{fi}.jsonl"
            _write_jsonl(p, range(n, n + count))
            files.append(str(p))
            n += count
        seen = []
        for t in range(num_tasks):
            with ShardedRecordReader(
                files, t, num_tasks, fmt="jsonl", batch_size=16
            ) as r:
                for batch in r:
                    seen.extend(rec["id"] for rec in batch)
        assert sorted(seen) == list(range(n))  # every record exactly once

    def test_shuffle_changes_order_not_content(self, tmp_path):
        p = tmp_path / "d.jsonl"
        _write_jsonl(p, range(200))
        with ShardedRecordReader(
            [str(p)], fmt="jsonl", batch_size=200, shuffle=True,
            shuffle_pool=64, seed=1,
        ) as r:
            got = [rec["id"] for rec in r.next_batch()]
        assert got != list(range(200))
        assert sorted(got) == list(range(200))


class TestTokenReader:
    def test_batches_and_alignment(self, tmp_path):
        rl, n_rec = 8, 103
        data = np.arange(rl * n_rec, dtype=np.uint16).reshape(n_rec, rl)
        p = tmp_path / "tokens.bin"
        data.tofile(p)
        seen = []
        for t in range(4):
            with ShardedRecordReader(
                [str(p)], t, 4, fmt="tokens", record_len=rl,
                dtype=np.uint16, batch_size=10,
            ) as r:
                for batch in r:
                    assert batch.shape[1] == rl
                    seen.extend(batch[:, 0].tolist())
        # exactly once: first token of each record identifies it
        assert sorted(seen) == [i * rl for i in range(n_rec)]

    def test_sharded_batches_places_on_mesh(self, tmp_path):
        import jax
        from tony_tpu.parallel.mesh import MeshSpec, build_mesh

        rl = 4
        data = np.arange(rl * 64, dtype=np.uint16).reshape(64, rl)
        p = tmp_path / "t.bin"
        data.tofile(p)
        mesh = build_mesh(MeshSpec(dp=8))
        with ShardedRecordReader(
            [str(p)], fmt="tokens", record_len=rl, batch_size=16
        ) as r:
            batches = list(sharded_batches(r, mesh))
        assert len(batches) == 4
        for b in batches:
            assert b.shape == (16, rl)
            assert len(b.sharding.device_set) == 8

    def test_device_prefetch_preserves_order_and_content(self):
        from tony_tpu.io import device_prefetch

        src = [np.full((4,), i, np.int32) for i in range(7)]
        out = list(device_prefetch(iter(src), depth=2))
        assert len(out) == 7
        for i, b in enumerate(out):
            np.testing.assert_array_equal(np.asarray(b), src[i])

    def test_device_prefetch_keeps_transfers_in_flight(self):
        """The pipeline must ISSUE batch N+1's device_put before batch N
        is consumed — observed through a tracking iterator: after pulling
        batch 0, the background transfer thread advances the source past
        batch 1 (depth=2 lookahead: the yielded batch plus one in
        flight), which is what overlaps H2D with the running step — and
        advances NO further until the consumer asks again (depth bounds
        total in-flight batches)."""
        import time

        from tony_tpu.io import device_prefetch

        pulled = []

        def src():
            for i in range(5):
                pulled.append(i)
                yield np.full((2,), i, np.int32)

        it = device_prefetch(src(), depth=2)
        first = next(it)
        np.testing.assert_array_equal(np.asarray(first), [0, 0])
        deadline = time.monotonic() + 5
        while len(pulled) < 2 and time.monotonic() < deadline:
            time.sleep(0.005)  # the transfer thread races ahead async
        assert pulled == [0, 1], pulled  # one batch already in flight
        time.sleep(0.05)
        assert pulled == [0, 1], pulled  # ...and the depth bound holds
        rest = list(it)
        assert len(rest) == 4
        assert pulled == [0, 1, 2, 3, 4]
        with pytest.raises(ValueError, match="depth"):
            next(device_prefetch(iter([np.zeros(1)]), depth=0))

    def test_sharded_batches_stream_trains_identically(self, tmp_path):
        """Streamed (double-buffered) batches are byte-identical, in
        order, to the underlying records — the bench's streamed-vs-
        synthetic comparison depends on this."""
        import jax
        from tony_tpu.io import device_prefetch  # noqa: F401
        from tony_tpu.parallel.mesh import MeshSpec, build_mesh

        rl = 8
        data = np.arange(rl * 32, dtype=np.uint16).reshape(32, rl)
        p = tmp_path / "t.bin"
        data.tofile(p)
        mesh = build_mesh(MeshSpec(dp=8))
        with ShardedRecordReader(
            [str(p)], fmt="tokens", record_len=rl, batch_size=8
        ) as r:
            got = np.concatenate(
                [np.asarray(b) for b in sharded_batches(r, mesh)]
            )
        np.testing.assert_array_equal(got, data)


class TestConsumerApis:
    """Schema introspection + spill-to-file (HdfsAvroFileSplitReader
    getSchemaJson:446-463, nextBatchFile/LocalSpill:503-542 analogues)."""

    def _jsonl(self, tmp_path, n=10):
        p = tmp_path / "d.jsonl"
        p.write_text("".join(
            json.dumps({"id": i, "text": f"t{i}"}) + "\n" for i in range(n)
        ))
        return str(p)

    def test_schema_json_jsonl(self, tmp_path):
        with ShardedRecordReader([self._jsonl(tmp_path)]) as r:
            schema = json.loads(r.schema_json())
        assert schema == {
            "format": "jsonl", "fields": {"id": "int", "text": "str"}
        }

    def test_schema_json_does_not_consume_records(self, tmp_path):
        with ShardedRecordReader(
            [self._jsonl(tmp_path, 6)], batch_size=100
        ) as r:
            r.schema_json()
            batch = r.next_batch()
        assert [rec["id"] for rec in batch] == list(range(6))

    def test_schema_json_tokens(self, tmp_path):
        p = tmp_path / "t.bin"
        np.arange(32, dtype=np.uint16).tofile(p)
        with ShardedRecordReader(
            [str(p)], fmt="tokens", record_len=8, dtype=np.uint16
        ) as r:
            schema = json.loads(r.schema_json())
        assert schema == {"format": "tokens", "dtype": "uint16",
                          "record_len": 8}

    def test_next_batch_file_tokens_mmap_ready(self, tmp_path):
        p = tmp_path / "t.bin"
        np.arange(64, dtype=np.uint16).tofile(p)
        with ShardedRecordReader(
            [str(p)], fmt="tokens", record_len=8, dtype=np.uint16,
            batch_size=4,
        ) as r:
            path = r.next_batch_file(tmp_path)
        arr = np.load(path, mmap_mode="r")
        assert arr.shape == (4, 8) and arr[0, 0] == 0

    def test_next_batch_file_jsonl_and_eof(self, tmp_path):
        with ShardedRecordReader(
            [self._jsonl(tmp_path, 3)], batch_size=10
        ) as r:
            path = r.next_batch_file(tmp_path)
            lines = open(path).read().splitlines()
            assert [json.loads(l)["id"] for l in lines] == [0, 1, 2]
            assert r.next_batch_file(tmp_path) is None


class TestNativeDecoder:
    """Native C++ data-plane kernels (native/tony_io.cc) pinned to the
    pure-Python paths; all tests skip when the library isn't built
    (`make -C native`)."""

    @pytest.fixture(autouse=True)
    def _need_native(self):
        from tony_tpu.io import native

        if not native.available():
            pytest.skip("libtony_io.so not built")

    def test_scan_record_starts_matches_python(self):
        from tony_tpu.io import native

        chunk = b'{"a":1}\n{"b":2}\n{"c":3}\npartial'
        got = native.scan_record_starts(chunk)
        want = [m + 1 for m in range(len(chunk) - 1) if chunk[m:m + 1] == b"\n"]
        assert got == want == [8, 16, 24]
        assert native.count_records(chunk) == 3
        assert native.scan_record_starts(b"") == []
        assert native.scan_record_starts(b"no newline") == []
        # trailing newline: no successor byte, so no start offset
        assert native.scan_record_starts(b"x\n") == []

    def test_token_read_matches_python_fallback(self, tmp_path, monkeypatch):
        p = tmp_path / "t.bin"
        rng = np.random.default_rng(0)
        data = rng.integers(0, 2**16, size=(67, 8)).astype(np.uint16)
        data.tofile(p)

        def read_all(force_fallback):
            from tony_tpu.io import native

            if force_fallback:
                monkeypatch.setattr(native, "available", lambda: False)
            r = ShardedRecordReader(
                [str(p)], fmt="tokens", record_len=8, dtype=np.uint16,
                batch_size=67,
            )
            try:
                return r.next_batch()
            finally:
                r.close()
                monkeypatch.undo()

        native_batch = read_all(False)
        python_batch = read_all(True)
        np.testing.assert_array_equal(native_batch, python_batch)
        np.testing.assert_array_equal(native_batch, data)

    def test_native_read_chunking_boundaries(self, tmp_path):
        # more records than one native chunk -> multiple preads
        p = tmp_path / "big.bin"
        n = ShardedRecordReader._CHUNK_RECORDS * 2 + 7
        data = np.arange(n * 4, dtype=np.uint16).reshape(n, 4)
        data.tofile(p)
        with ShardedRecordReader(
            [str(p)], fmt="tokens", record_len=4, dtype=np.uint16,
            batch_size=n,
        ) as r:
            batch = r.next_batch()
        np.testing.assert_array_equal(batch, data)

    def test_exactly_once_with_native_path(self, tmp_path):
        p = tmp_path / "s.bin"
        np.arange(40 * 4, dtype=np.uint16).tofile(p)
        seen = []
        for idx in range(3):
            with ShardedRecordReader(
                [str(p)], task_index=idx, num_tasks=3, fmt="tokens",
                record_len=4, dtype=np.uint16, batch_size=100,
            ) as r:
                b = r.next_batch()
                if b is not None:
                    seen.extend(int(row[0]) for row in b)
        assert sorted(seen) == [i * 4 for i in range(40)]

    def test_batches_are_writable_both_paths(self, tmp_path, monkeypatch):
        from tony_tpu.io import native

        p = tmp_path / "w.bin"
        np.arange(32, dtype=np.uint16).tofile(p)
        for force_py in (False, True):
            if force_py:
                monkeypatch.setattr(native, "available", lambda: False)
            with ShardedRecordReader(
                [str(p)], fmt="tokens", record_len=8, dtype=np.uint16,
                batch_size=2,
            ) as r:
                b = r.next_batch()
                b *= 2  # consumers mutate in place (e.g. masking)
            monkeypatch.undo()


# ---------------------------------------------------------------------------
# gs:// data plane (VERDICT r3 missing #1): the reader opens remote corpora
# directly, the way the reference's reader opens HDFS
# (HdfsAvroFileSplitReader.java:347-416) — no manual staging.
# ---------------------------------------------------------------------------

@pytest.fixture
def gcs_emulator(tmp_path):
    from tony_tpu.cloud import set_default_storage
    from tony_tpu.cloud.gcs import FileObjectStorage

    store = FileObjectStorage(tmp_path / "objects")
    set_default_storage(store)
    yield store
    set_default_storage(None)


class TestGsReader:
    @pytest.mark.parametrize("num_tasks", [1, 2, 3])
    def test_jsonl_exactly_once_over_gs(self, gcs_emulator, num_tasks):
        """Two/three readers over gs:// shards: every record exactly once,
        including records straddling the byte-range boundaries (the
        split-brain rule must hold over ranged fetches too)."""
        uris, n = [], 0
        for fi, count in enumerate([41, 0, 87]):
            body = "".join(
                json.dumps({"id": i, "pad": "y" * (i % 11)}) + "\n"
                for i in range(n, n + count)
            ).encode()
            uri = f"gs://corpus/part-{fi}.jsonl"
            gcs_emulator.put_bytes(uri, body)
            uris.append(uri)
            n += count
        seen = []
        for t in range(num_tasks):
            with ShardedRecordReader(
                uris, t, num_tasks, fmt="jsonl", batch_size=16
            ) as r:
                for batch in r:
                    seen.extend(rec["id"] for rec in batch)
        assert sorted(seen) == list(range(n))

    def test_tokens_over_gs_match_local(self, gcs_emulator, tmp_path):
        rl, n_rec = 8, 103
        data = np.arange(rl * n_rec, dtype=np.uint16).reshape(n_rec, rl)
        local = tmp_path / "tokens.bin"
        data.tofile(local)
        gcs_emulator.put_bytes("gs://corpus/tokens.bin", local.read_bytes())
        for t in range(3):
            with ShardedRecordReader(
                [str(local)], t, 3, fmt="tokens", record_len=rl,
                dtype=np.uint16, batch_size=10,
            ) as lr, ShardedRecordReader(
                ["gs://corpus/tokens.bin"], t, 3, fmt="tokens",
                record_len=rl, dtype=np.uint16, batch_size=10,
            ) as gr:
                while True:
                    lb, gb = lr.next_batch(), gr.next_batch()
                    if lb is None:
                        assert gb is None
                        break
                    np.testing.assert_array_equal(lb, gb)

    def test_gs_token_batches_are_writable(self, gcs_emulator):
        gcs_emulator.put_bytes(
            "gs://corpus/w.bin", np.arange(32, dtype=np.uint16).tobytes()
        )
        with ShardedRecordReader(
            ["gs://corpus/w.bin"], fmt="tokens", record_len=8,
            dtype=np.uint16, batch_size=2,
        ) as r:
            b = r.next_batch()
            b *= 2

    def test_mixed_local_and_gs_paths(self, gcs_emulator, tmp_path):
        local = tmp_path / "a.jsonl"
        _write_jsonl(local, range(10))
        gcs_emulator.put_bytes("gs://corpus/b.jsonl", "".join(
            json.dumps({"id": i, "pad": ""}) + "\n" for i in range(10, 25)
        ).encode())
        seen = []
        for t in range(2):
            with ShardedRecordReader(
                [str(local), "gs://corpus/b.jsonl"], t, 2, fmt="jsonl",
                batch_size=7,
            ) as r:
                for batch in r:
                    seen.extend(rec["id"] for rec in batch)
        assert sorted(seen) == list(range(25))


class TestRangeLineStream:
    def test_lines_across_chunk_boundaries(self, gcs_emulator, monkeypatch):
        from tony_tpu.io.storage import RangeLineStream

        lines = [f"record-{i:04d}-" + "z" * (i % 13) for i in range(300)]
        body = ("\n".join(lines) + "\n").encode()
        gcs_emulator.put_bytes("gs://corpus/lines.txt", body)
        monkeypatch.setattr(RangeLineStream, "CHUNK", 37)  # force many fetches
        s = RangeLineStream("gs://corpus/lines.txt")
        got = []
        while True:
            line = s.readline()
            if not line:
                break
            got.append(line.decode().rstrip("\n"))
        assert got == lines
        assert s.tell() == len(body)

    def test_seek_one_byte_back_boundary_rule(self, gcs_emulator):
        from tony_tpu.io.storage import RangeLineStream

        body = b"aaaa\nbbbb\ncccc\n"
        gcs_emulator.put_bytes("gs://corpus/b.txt", body)
        s = RangeLineStream("gs://corpus/b.txt")
        # offset 5 is exactly the start of "bbbb": seeking one back and
        # reading a line must consume only the newline, keeping "bbbb"
        s.seek(4)
        assert s.readline() == b"\n"
        assert s.readline() == b"bbbb\n"
        assert s.tell() == 10


class TestJsonlBlocks:
    """Block-compressed jsonl container (io/blocks.py) — the Avro-
    container analogue (HdfsAvroFileSplitReader.java:190-240 sync-marker
    splits, :446-463 schema negotiation): compressed corpora must still
    split by byte range, read exactly once, and surface their schema."""

    def _write(self, path, n=100, codec="gzip", schema=None, block=16):
        from tony_tpu.io import write_jsonl_blocks

        recs = [{"id": i, "text": f"record-{i}" * 3} for i in range(n)]
        if codec == "zstd":
            pytest.importorskip("zstandard")
        wrote = write_jsonl_blocks(
            str(path), recs, codec=codec, block_records=block,
            schema=schema,
        )
        assert wrote == n
        return recs

    @pytest.mark.parametrize("codec", ["none", "gzip", "zstd"])
    def test_roundtrip_all_codecs(self, tmp_path, codec):
        p = tmp_path / f"c.{codec}.jblk"
        recs = self._write(p, codec=codec)
        with ShardedRecordReader(
            [str(p)], fmt="jsonl-blocks", batch_size=32
        ) as r:
            got = [rec for batch in r for rec in batch]
        assert got == recs

    def test_compression_actually_shrinks(self, tmp_path):
        pn, pz = tmp_path / "a", tmp_path / "b"
        self._write(pn, n=500, codec="none")
        self._write(pz, n=500, codec="zstd")
        assert pz.stat().st_size < pn.stat().st_size / 2

    @pytest.mark.parametrize("codec", ["gzip", "zstd"])
    def test_split_readers_each_record_exactly_once(self, tmp_path, codec):
        """4 byte-range readers over one compressed container: the sync-
        marker owner rule hands every block to exactly one reader even
        though ranges land mid-block."""
        p = tmp_path / "c.jblk"
        recs = self._write(p, n=200, codec=codec, block=8)
        seen = []
        for t in range(4):
            with ShardedRecordReader(
                [str(p)], t, 4, fmt="jsonl-blocks", batch_size=16
            ) as r:
                seen.extend(rec["id"] for b in r for rec in b)
        assert sorted(seen) == list(range(200))

    def test_schema_negotiated_from_header_without_data_read(self, tmp_path):
        import json as _json

        p = tmp_path / "s.jblk"
        self._write(p, schema={"id": "long", "text": "string"})
        with ShardedRecordReader(
            [str(p)], fmt="jsonl-blocks", batch_size=8
        ) as r:
            doc = _json.loads(r.schema_json())
        assert doc["codec"] == "gzip"
        assert doc["schema"] == {"id": "long", "text": "string"}

    def test_schema_falls_back_to_introspection(self, tmp_path):
        import json as _json

        p = tmp_path / "s2.jblk"
        self._write(p)  # no embedded schema
        with ShardedRecordReader(
            [str(p)], fmt="jsonl-blocks", batch_size=8
        ) as r:
            doc = _json.loads(r.schema_json())
        assert doc["fields"] == {"id": "int", "text": "str"}

    def test_schema_found_in_later_container(self, tmp_path):
        """Schema negotiation must consult EVERY container backing the
        reader, not just the first: here the first header is empty and
        only the second embeds a schema."""
        import json as _json

        p1 = tmp_path / "a.jblk"
        p2 = tmp_path / "b.jblk"
        self._write(p1)  # no embedded schema
        self._write(p2, schema={"id": "long", "text": "string"})
        with ShardedRecordReader(
            [str(p1), str(p2)], fmt="jsonl-blocks", batch_size=8
        ) as r:
            doc = _json.loads(r.schema_json())
        assert doc["schema"] == {"id": "long", "text": "string"}

    def test_corrupt_sync_candidate_skipped_by_crc(self, tmp_path):
        """Garbage bytes containing a fake SYNC marker (with junk lengths
        and CRC) between two real blocks must be skipped — the CRC +
        length guard is what makes marker collisions harmless."""
        from tony_tpu.io.blocks import SYNC, write_jsonl_blocks

        p = tmp_path / "k.jblk"
        write_jsonl_blocks(str(p), [{"id": 0}], block_records=1)
        tail_recs = [{"id": 1}]
        p2 = tmp_path / "tail.jblk"
        write_jsonl_blocks(str(p2), tail_recs, block_records=1)
        # splice: file = (whole first container) + fake sync + junk +
        # (second container's first block, stripped of its header)
        from tony_tpu.io.blocks import read_header

        _, _, data_start = read_header(str(p2))
        blob = (
            p.read_bytes()
            + SYNC + b"\xff" * 24          # implausible lengths + junk
            + p2.read_bytes()[data_start:]
        )
        p.write_bytes(blob)
        with ShardedRecordReader(
            [str(p)], fmt="jsonl-blocks", batch_size=8
        ) as r:
            got = [rec["id"] for b in r for rec in b]
        assert got == [0, 1]

    def test_non_container_file_fails_loudly(self, tmp_path):
        p = tmp_path / "plain.jsonl"
        p.write_text('{"id": 1}\n')
        with ShardedRecordReader(
            [str(p)], fmt="jsonl-blocks", batch_size=8
        ) as r:
            with pytest.raises(ValueError, match="bad magic"):
                r.schema_json()
        # and CONSUMING must raise too — a fetcher-thread failure must
        # never read as a clean (empty) end of shard
        with ShardedRecordReader(
            [str(p)], fmt="jsonl-blocks", batch_size=8
        ) as r:
            with pytest.raises(RuntimeError, match="NOT exhausted"):
                r.next_batch()
            # a caller that catches and retries must KEEP failing loudly,
            # not read the requeued sentinel as a clean end of shard
            with pytest.raises(RuntimeError, match="NOT exhausted"):
                r.next_batch()

    def test_gs_container_roundtrip(self, tmp_path, monkeypatch):
        """A gs:// container through the FileObjectStorage emulator: the
        writer PUTs the whole container, split readers range-read it."""
        import os

        from tony_tpu.cloud import set_default_storage
        from tony_tpu.cloud.gcs import FileObjectStorage

        set_default_storage(FileObjectStorage(tmp_path / "obj"))
        try:
            uri = "gs://corpus/train.jblk"
            recs = self._write(uri, n=60, codec="gzip", block=7)
            seen = []
            for t in range(2):
                with ShardedRecordReader(
                    [uri], t, 2, fmt="jsonl-blocks", batch_size=16
                ) as r:
                    seen.extend(rec["id"] for b in r for rec in b)
            assert sorted(seen) == list(range(60))
        finally:
            set_default_storage(None)


class TestJsonlBlocksEdges:
    def test_empty_container_reads_cleanly(self, tmp_path):
        from tony_tpu.io import write_jsonl_blocks

        p = tmp_path / "empty.jblk"
        assert write_jsonl_blocks(str(p), []) == 0
        with ShardedRecordReader(
            [str(p)], fmt="jsonl-blocks", batch_size=4
        ) as r:
            assert r.next_batch() is None

    def test_single_record_container(self, tmp_path):
        from tony_tpu.io import write_jsonl_blocks

        p = tmp_path / "one.jblk"
        write_jsonl_blocks(str(p), [{"id": 42}])
        with ShardedRecordReader(
            [str(p)], fmt="jsonl-blocks", batch_size=4
        ) as r:
            assert [rec["id"] for rec in r.next_batch()] == [42]

    def test_reader_more_tasks_than_blocks(self, tmp_path):
        """8 split readers over a 2-block container: most shards own no
        block and must come up empty instead of duplicating reads."""
        from tony_tpu.io import write_jsonl_blocks

        p = tmp_path / "few.jblk"
        write_jsonl_blocks(
            str(p), [{"id": i} for i in range(8)], block_records=4
        )
        seen = []
        for t in range(8):
            with ShardedRecordReader(
                [str(p)], t, 8, fmt="jsonl-blocks", batch_size=8
            ) as r:
                seen.extend(rec["id"] for b in r for rec in b)
        assert sorted(seen) == list(range(8))
