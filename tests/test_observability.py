"""Observability subsystem tests: the metrics registry + Prometheus
rendering, the structured event log, trace spans + the per-job Chrome
trace merge, the coordinator-side aggregator, the heartbeat metrics
piggyback over real RPC, and the mini-cluster e2e that drives the whole
telemetry plane through a 2-task job (jax-free fixture)."""

import json
import sys
import threading
import time
import urllib.error
import urllib.request
from pathlib import Path

import pytest

from tony_tpu import constants
from tony_tpu.conf import keys
from tony_tpu.coordinator.app_master import TonyCoordinator
from tony_tpu.coordinator.backend import LocalProcessBackend
from tony_tpu.coordinator.session import SessionStatus
from tony_tpu.mini import MiniTonyCluster
from tony_tpu.observability import events as obs_events
from tony_tpu.observability import metrics as obs_metrics
from tony_tpu.observability import trace as obs_trace
from tony_tpu.observability.aggregator import (
    MetricsAggregator,
    ObservabilityHttpServer,
)

FIXTURES = Path(__file__).resolve().parent / "fixtures"


# ---------------------------------------------------------------------------
# metrics.py
# ---------------------------------------------------------------------------
class TestMetricsRegistry:
    def test_counter_gauge_histogram_roundtrip(self):
        reg = obs_metrics.MetricsRegistry()
        reg.counter("requests_total").inc()
        reg.counter("requests_total").inc(2)
        reg.gauge("loss").set(0.5)
        h = reg.histogram("step_seconds", buckets=(0.1, 1.0))
        h.observe(0.05)
        h.observe(0.5)
        h.observe(5.0)
        snap = reg.snapshot()
        assert snap["counters"]["requests_total"] == 3
        assert snap["gauges"]["loss"] == 0.5
        hist = snap["histograms"]["step_seconds"]
        assert hist["count"] == 3 and hist["sum"] == pytest.approx(5.55)
        assert hist["buckets"] == [[0.1, 1], [1.0, 2]]  # cumulative

    def test_name_validation(self):
        reg = obs_metrics.MetricsRegistry()
        with pytest.raises(ValueError, match="snake_case"):
            reg.counter("Bad-Name")
        with pytest.raises(ValueError, match="_total"):
            reg.counter("requests")
        with pytest.raises(ValueError, match="unit suffix"):
            reg.gauge("step_time")  # time without _ms/_seconds
        with pytest.raises(ValueError, match="unit suffix"):
            reg.gauge("memory_used")
        reg.gauge("step_time_ms")  # legal
        reg.counter("ticks_total")
        with pytest.raises(ValueError, match="already registered"):
            reg.gauge("ticks_total")  # kind conflict

    def test_counter_cannot_decrease(self):
        reg = obs_metrics.MetricsRegistry()
        with pytest.raises(ValueError, match="decrease"):
            reg.counter("ticks_total").inc(-1)

    def test_report_drives_step_counter_by_delta(self):
        reg = obs_metrics.MetricsRegistry()
        reg.report(step=3, loss=1.0)
        reg.report(step=5, loss=0.5)
        reg.report(step=5, loss=0.4)  # no progress: counter holds
        snap = reg.snapshot()
        assert snap["counters"]["train_steps_total"] == 5
        assert snap["gauges"]["train_step"] == 5
        assert snap["gauges"]["loss"] == 0.4

    def test_publish_and_load_snapshot(self, tmp_path):
        path = tmp_path / "m.json"
        reg = obs_metrics.MetricsRegistry(
            publish_path=path, publish_min_interval_s=0.0
        )
        reg.report(step=1, loss=2.0)
        snap = obs_metrics.load_snapshot_file(path)
        assert snap is not None and snap["gauges"]["loss"] == 2.0
        # corrupt file -> None, never raises (heartbeats must not fail)
        path.write_text("{not json")
        assert obs_metrics.load_snapshot_file(path) is None
        assert obs_metrics.load_snapshot_file(tmp_path / "nope") is None

    def test_prometheus_rendering(self):
        reg = obs_metrics.MetricsRegistry()
        reg.counter("requests_total").inc(7)
        reg.gauge("loss").set(1.5)
        text = reg.to_prometheus()
        assert "# TYPE requests_total counter" in text
        assert "requests_total 7" in text
        assert "loss 1.5" in text
        labeled = obs_metrics.render_prometheus(
            reg.snapshot(), labels={"task": 'work"er'}
        )
        assert 'requests_total{task="work\\"er"} 7' in labeled

    def test_sanitize_metric_name(self):
        assert obs_metrics.sanitize_metric_name("%fusion.1") == "fusion_1"
        assert obs_metrics.sanitize_metric_name("") == "unnamed"


# ---------------------------------------------------------------------------
# events.py
# ---------------------------------------------------------------------------
class TestEventLog:
    def test_emit_order_and_sink(self, tmp_path):
        path = tmp_path / "events.jsonl"
        log = obs_events.EventLog(sink=obs_events.jsonl_file_sink(path))
        log.emit(obs_events.TASK_REGISTERED, task="worker:0", session=1)
        log.emit(obs_events.RENDEZVOUS_RELEASED, session=1, tasks=2)
        assert log.kinds() == ["task_registered", "rendezvous_released"]
        lines = path.read_text().splitlines()
        assert len(lines) == 2
        assert json.loads(lines[0])["task"] == "worker:0"

    def test_sink_failure_never_raises(self):
        def explode(event):
            raise OSError("disk gone")

        log = obs_events.EventLog(sink=explode)
        log.emit("task_finished")  # must not raise
        assert log.kinds() == ["task_finished"]

    def test_parse_jsonl_skips_torn_lines(self):
        text = '{"kind": "a"}\n{"kind": "b"\nnot json\n{"kind": "c"}\n'
        events = obs_events.parse_jsonl(text)
        assert [e["kind"] for e in events] == ["a", "c"]


# ---------------------------------------------------------------------------
# trace.py
# ---------------------------------------------------------------------------
class TestTrace:
    def test_span_exports_chrome_events(self):
        tracer = obs_trace.Tracer(trace_id="abc123", proc="coordinator")
        with tracer.span("prepare", session=1):
            pass
        events = tracer.to_chrome_events()
        # metadata row + the span
        assert events[0]["ph"] == "M"
        span = events[-1]
        assert span["ph"] == "X" and span["name"] == "prepare"
        assert span["args"]["trace_id"] == "abc123"
        assert span["args"]["proc"] == "coordinator"
        assert span["dur"] >= 1

    def test_span_end_idempotent_and_attrs(self):
        tracer = obs_trace.Tracer()
        span = tracer.begin("monitor")
        span.set(status="SUCCEEDED")
        span.end()
        span.end()
        spans = [e for e in tracer.to_chrome_events() if e["ph"] == "X"]
        assert len(spans) == 1
        assert spans[0]["args"]["status"] == "SUCCEEDED"

    def test_merge_job_trace_includes_executor_files(self, tmp_path):
        coord = obs_trace.Tracer(trace_id="t1", proc="coordinator")
        with coord.span("session"):
            pass
        ex = obs_trace.Tracer(trace_id="t1", proc="executor:worker:0")
        with ex.span("user_process"):
            pass
        ex.write_jsonl(tmp_path / "trace-worker-0.jsonl")
        # a torn tail must not hide the rest
        (tmp_path / "trace-broken.jsonl").write_text('{"name": "x"\n')
        doc = obs_trace.merge_job_trace(coord, tmp_path)
        procs = {
            e["args"]["proc"] for e in doc["traceEvents"]
            if e.get("ph") == "X"
        }
        assert procs == {"coordinator", "executor:worker:0"}
        assert doc["otherData"]["trace_id"] == "t1"

    def test_ambient_trace_id_env(self, monkeypatch):
        monkeypatch.setenv(constants.TONY_TRACE_ID, "feedbeef")
        assert obs_trace.Tracer().trace_id == "feedbeef"
        monkeypatch.delenv(constants.TONY_TRACE_ID)
        assert obs_trace.Tracer().trace_id != ""


# ---------------------------------------------------------------------------
# aggregator.py
# ---------------------------------------------------------------------------
def _snap(loss, step=1, ts=None):
    return {
        "ts_ms": ts or int(time.time() * 1000),
        "counters": {"train_steps_total": step},
        "gauges": {"loss": loss},
        "histograms": {},
    }


class TestAggregator:
    def test_ingest_and_prometheus(self):
        agg = MetricsAggregator()
        agg.registry.counter("sessions_started_total").inc()
        agg.ingest("worker:0", _snap(0.5, ts=1))
        agg.ingest("worker:1", None)  # plain liveness ping
        text = agg.prometheus_text()
        assert "sessions_started_total 1" in text
        assert 'tony_task_heartbeats_total{task="worker:0"} 1' in text
        assert 'tony_task_heartbeats_total{task="worker:1"} 1' in text
        assert 'loss{task="worker:0"} 0.5' in text
        assert 'train_steps_total{task="worker:0"} 1' in text
        # TYPE headers are emitted once however many tasks share a name
        assert text.count("# TYPE tony_task_heartbeats_total counter") == 1

    def test_series_bounded_and_keyed(self):
        agg = MetricsAggregator(series_limit=3)
        for i in range(5):
            agg.ingest("worker:0", _snap(float(i), ts=i + 1))
        series = agg.to_json()["series"]["worker:0:loss"]
        assert [v for _, v in series] == [2.0, 3.0, 4.0]  # bounded

    def test_reset_tasks_keeps_heartbeat_totals(self):
        agg = MetricsAggregator()
        agg.ingest("worker:0", _snap(0.5))
        agg.reset_tasks()
        agg.ingest("worker:0", None)
        text = agg.prometheus_text()
        assert 'tony_task_heartbeats_total{task="worker:0"} 2' in text
        assert "loss{" not in text  # dead session's gauges dropped

    def test_summary_compact(self):
        agg = MetricsAggregator()
        agg.ingest("worker:0", _snap(0.25, step=4))
        summary = agg.summary()
        assert summary["tasks"]["worker:0"]["gauges"]["loss"] == 0.25
        assert summary["heartbeats"]["worker:0"] == 1

    def test_malformed_snapshot_families_normalized(self):
        """The snapshot crosses a trust boundary (user-writable file →
        executor → RPC): null/garbage families must not crash summary()
        in stop() (losing the terminal record) or the /metrics render."""
        agg = MetricsAggregator()
        agg.ingest("worker:0", {
            "ts_ms": "yesterday",
            "counters": None,
            "gauges": {"loss": "not-a-number", "ok_ratio": 0.5},
            "histograms": {"h_seconds": None,
                           "g_seconds": {"count": 1, "sum": 2.0,
                                         "buckets": [[1.0, 1], "junk"]}},
        })
        summary = agg.summary()
        assert summary["tasks"]["worker:0"]["counters"] == {}
        assert summary["tasks"]["worker:0"]["gauges"] == {"ok_ratio": 0.5}
        text = agg.prometheus_text()
        assert 'ok_ratio{task="worker:0"} 0.5' in text
        assert 'g_seconds_count{task="worker:0"} 1' in text

    def test_nan_loss_stays_valid_json(self):
        """A diverged loop reporting loss=nan is exactly when operators
        read these views: the JSON surfaces must stay strictly parseable
        (null, not the bare NaN token), while Prometheus keeps NaN."""
        agg = MetricsAggregator()
        agg.ingest("worker:0", _snap(float("nan")))
        summary = agg.summary()
        assert summary["tasks"]["worker:0"]["gauges"]["loss"] is None
        assert "NaN" not in json.dumps(summary)
        assert 'loss{task="worker:0"} NaN' in agg.prometheus_text()
        server = ObservabilityHttpServer(agg, host="127.0.0.1")
        port = server.serve_background()
        try:
            body = urllib.request.urlopen(
                f"http://127.0.0.1:{port}/api/metrics"
            ).read().decode()
            assert "NaN" not in body
            json.loads(body)  # strictly parseable
        finally:
            server.stop()

    def test_http_endpoints(self):
        agg = MetricsAggregator()
        agg.ingest("worker:0", _snap(0.5))
        events = obs_events.EventLog()
        events.emit(obs_events.TASK_REGISTERED, task="worker:0")
        tracer = obs_trace.Tracer(trace_id="t9", proc="coordinator")
        with tracer.span("prepare"):
            pass
        server = ObservabilityHttpServer(
            agg, events=events, tracer=tracer, host="127.0.0.1"
        )
        port = server.serve_background()
        base = f"http://127.0.0.1:{port}"
        try:
            text = urllib.request.urlopen(f"{base}/metrics").read().decode()
            assert 'loss{task="worker:0"} 0.5' in text
            api = json.loads(
                urllib.request.urlopen(f"{base}/api/metrics").read()
            )
            assert api["tasks"]["worker:0"]["gauges"]["loss"] == 0.5
            ev = json.loads(
                urllib.request.urlopen(f"{base}/api/events").read()
            )
            assert ev[0]["kind"] == "task_registered"
            tr = json.loads(
                urllib.request.urlopen(f"{base}/api/trace").read()
            )
            assert tr["otherData"]["trace_id"] == "t9"
            with pytest.raises(urllib.error.HTTPError):
                urllib.request.urlopen(f"{base}/nope")
        finally:
            server.stop()


# ---------------------------------------------------------------------------
# heartbeat piggyback over real RPC
# ---------------------------------------------------------------------------
class _HbApp:
    """Heartbeat-only impl mirroring the coordinator's optional-metrics
    signature."""

    def __init__(self):
        self.pings = []

    def task_executor_heartbeat(self, task_id, session_id, metrics=None):
        self.pings.append((task_id, session_id, metrics))


class TestHeartbeatMetricsRpc:
    @pytest.fixture()
    def served(self):
        from tony_tpu.rpc.server import ApplicationRpcServer

        app = _HbApp()
        server = ApplicationRpcServer(
            app, host="127.0.0.1", port_range=(20000, 25000)
        )
        server.start()
        yield app, server
        server.stop()

    def test_metrics_ride_the_heartbeat(self, served):
        from tony_tpu.rpc.client import ApplicationRpcClient

        app, server = served
        c = ApplicationRpcClient("127.0.0.1", server.port)
        c.task_executor_heartbeat("worker:0", "1")
        c.task_executor_heartbeat("worker:0", "1", metrics=_snap(0.5))
        assert app.pings[0][2] is None  # optional arg stays off the wire
        assert app.pings[1][2]["gauges"]["loss"] == 0.5

    def test_dispatch_accepts_omitted_optional_arg(self, served):
        _, server = served
        ok = server.dispatch({
            "method": "task_executor_heartbeat",
            "args": {"task_id": "w:0", "session_id": "1"},
        })
        assert ok["ok"] is True
        bad = server.dispatch({
            "method": "task_executor_heartbeat",
            "args": {"metrics": {}},  # required args missing
        })
        assert bad["ok"] is False and "expects args" in bad["error"]

    def test_trace_metadata_reaches_handler(self, served):
        from tony_tpu.rpc.client import ApplicationRpcClient

        app, server = served
        seen = []
        orig = app.task_executor_heartbeat

        def spy(task_id, session_id, metrics=None):
            seen.append(obs_trace.current_rpc_trace())
            return orig(task_id, session_id, metrics)

        app.task_executor_heartbeat = spy
        c = ApplicationRpcClient(
            "127.0.0.1", server.port, trace_id="cafe01"
        )
        c.task_executor_heartbeat("worker:0", "1")
        assert seen == ["cafe01"]


# ---------------------------------------------------------------------------
# mini-cluster e2e: the acceptance scenario
# ---------------------------------------------------------------------------
def test_mini_cluster_observability_e2e(tmp_path):
    """2-task jax-free job: the coordinator's /metrics endpoint serves
    Prometheus text with per-task heartbeat and step counters WHILE the
    job runs; events.jsonl lands in history with the ordered lifecycle
    sequence; and the exported Chrome trace contains spans from the
    coordinator, an executor, and the user process sharing one trace
    id."""
    cluster = MiniTonyCluster(tmp_path)
    conf = cluster.base_conf()
    conf.set(keys.K_EXECUTES, str(FIXTURES / "report_metrics.py"))
    conf.set(keys.K_PYTHON_BINARY, sys.executable)
    conf.set(keys.instances_key("worker"), 2)
    conf.set(keys.instances_key("ps"), 0)
    conf.set(keys.K_TASK_HEARTBEAT_INTERVAL_MS, 150)
    conf.set(keys.K_SHELL_ENV, "LINGER_S=4.0")

    app_id = "application_mini_obs1"
    app_dir = cluster.staging_dir / app_id
    app_dir.mkdir(parents=True)
    conf.write_final(app_dir / constants.TONY_FINAL_CONF)
    coordinator = TonyCoordinator(
        conf, app_dir, app_id=app_id,
        backend=LocalProcessBackend(app_dir / "logs"),
    )
    result: list[SessionStatus] = []
    t = threading.Thread(
        target=lambda: result.append(coordinator.run()), daemon=True
    )
    cluster._live.append(coordinator)
    t.start()
    try:
        # -- live: scrape /metrics while the workers linger ---------------
        deadline = time.monotonic() + 60
        addr_file = app_dir / "coordinator.http"
        while not addr_file.is_file() and time.monotonic() < deadline:
            time.sleep(0.05)
        assert addr_file.is_file(), "coordinator.http never advertised"
        addr = addr_file.read_text().strip()
        text = ""
        wanted = (
            'tony_task_heartbeats_total{task="worker:0"}',
            'tony_task_heartbeats_total{task="worker:1"}',
            'train_steps_total{task="worker:0"}',
            'train_steps_total{task="worker:1"}',
            'loss{task="worker:0"}',
        )
        while time.monotonic() < deadline:
            try:
                text = urllib.request.urlopen(
                    f"http://{addr}/metrics", timeout=5
                ).read().decode()
            except OSError:
                time.sleep(0.1)
                continue
            if all(n in text for n in wanted):
                break
            time.sleep(0.1)
        for needle in wanted:
            assert needle in text, f"{needle!r} never appeared in /metrics"
        assert "# TYPE train_steps_total counter" in text
    finally:
        t.join(timeout=120)
    assert result and result[0] is SessionStatus.SUCCEEDED, (
        coordinator.session.diagnostics if coordinator.session else "no run"
    )

    # -- events.jsonl in history: the ordered lifecycle sequence ----------
    event_files = list(cluster.history_dir.rglob("events.jsonl"))
    assert len(event_files) == 1
    events = obs_events.parse_jsonl(event_files[0].read_text())
    kinds = [e["kind"] for e in events]
    for kind in ("job_submitted", "session_started", "task_scheduled"):
        assert kind in kinds
    order = [
        kinds.index("task_registered"),
        kinds.index("rendezvous_released"),
        kinds.index("task_finished"),
        kinds.index("final_status"),
    ]
    assert order == sorted(order) and len(set(order)) == 4
    # RPC metadata propagation: the registration event carries the same
    # trace id the coordinator minted.
    reg_event = events[kinds.index("task_registered")]
    assert reg_event["trace_id"] == coordinator.tracer.trace_id

    # -- Chrome trace: coordinator + executor + user spans, one trace id --
    trace_files = list(cluster.history_dir.rglob("trace.json"))
    assert len(trace_files) == 1
    doc = json.loads(trace_files[0].read_text())
    spans = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
    trace_ids = {s["args"]["trace_id"] for s in spans}
    assert trace_ids == {coordinator.tracer.trace_id}
    procs = {s["args"]["proc"] for s in spans}
    assert "coordinator" in procs
    assert any(p.startswith("executor:worker:") for p in procs)
    assert any(p.startswith("user:worker:") for p in procs)
    names = {s["name"] for s in spans}
    for name in ("prepare", "schedule_tasks", "rendezvous_wait",
                 "rendezvous", "user_process", "fixture_train"):
        assert name in names, f"span {name!r} missing from job trace"

    # -- final-status carries the aggregated metric summary ---------------
    final = json.loads((app_dir / "final-status.json").read_text())
    assert final["trace_id"] == coordinator.tracer.trace_id
    tasks = final["metrics"]["tasks"]
    assert tasks["worker:0"]["gauges"]["loss"] == pytest.approx(0.2)
    assert final["metrics"]["heartbeats"]["worker:0"] >= 1

    # -- CLI: tony events / tony metrics over the same artifacts ----------
    from tony_tpu.client import cli

    rc = cli.main([
        "events", app_id, "--staging-location", str(cluster.staging_dir),
        "--history-location", str(cluster.history_dir),
    ])
    assert rc == 0
    rc = cli.main([
        "metrics", app_id, "--staging-location", str(cluster.staging_dir),
        "--history-location", str(cluster.history_dir),
    ])
    assert rc == 0


def test_observability_port_can_be_disabled(tmp_path):
    cluster = MiniTonyCluster(tmp_path)
    conf = cluster.base_conf()
    conf.set(keys.K_EXECUTES, str(FIXTURES / "exit_0.py"))
    conf.set(keys.K_PYTHON_BINARY, sys.executable)
    conf.set(keys.instances_key("worker"), 1)
    conf.set(keys.instances_key("ps"), 0)
    conf.set(keys.K_AM_HTTP_PORT, "disabled")
    status, coord = cluster.run_job(conf)
    assert status is SessionStatus.SUCCEEDED
    assert coord.http_server is None
    assert not (coord.app_dir / "coordinator.http").exists()
    # The rest of the telemetry plane still runs: events + trace persist.
    assert (coord.app_dir / "events.jsonl").is_file()
    assert (coord.app_dir / "trace.json").is_file()


# ---------------------------------------------------------------------------
# histogram_quantile edge cases (the single-sample clamp)
# ---------------------------------------------------------------------------
class TestHistogramQuantileEdgeCases:
    def test_empty_histogram_is_none(self):
        assert obs_metrics.histogram_quantile(
            {"count": 0, "buckets": []}, 0.95
        ) is None
        assert obs_metrics.histogram_quantile({}, 0.5) is None

    def test_single_sample_clamps_to_observed_max(self):
        h = obs_metrics.Histogram("x_ms", buckets=(5.0, 10.0))
        h.observe(3.0)
        snap = h.snapshot()
        assert snap["max"] == 3.0
        # without the clamp this reads as the 5.0 bucket bound — a p95
        # over one 3 ms sample must be 3 ms, not 5 ms
        assert obs_metrics.histogram_quantile(snap, 0.95) == 3.0
        assert obs_metrics.histogram_quantile(snap, 0.5) == 3.0

    def test_all_in_overflow_bucket_reads_max_not_mean(self):
        h = obs_metrics.Histogram("x_ms", buckets=(5.0, 10.0))
        h.observe(50.0)
        h.observe(70.0)
        snap = h.snapshot()
        # both samples are past the last bound: the readout is the
        # observed max (70), not the mean (60) and not infinite
        assert obs_metrics.histogram_quantile(snap, 0.95) == 70.0

    def test_snapshot_without_max_keeps_bucket_bound(self):
        # aggregated/legacy snapshots that carry no "max" keep the
        # upper-bound behavior (and the mean fallback past the end)
        snap = {"count": 1, "sum": 3.0, "buckets": [[5.0, 1], [10.0, 1]]}
        assert obs_metrics.histogram_quantile(snap, 0.95) == 5.0
        snap = {"count": 2, "sum": 120.0, "buckets": [[5.0, 0], [10.0, 0]]}
        assert obs_metrics.histogram_quantile(snap, 0.95) == 60.0

    def test_max_rides_through_aggregator_normalization(self):
        agg = MetricsAggregator()
        h = obs_metrics.Histogram("x_ms", buckets=(5.0,))
        h.observe(3.0)
        agg.ingest("w:0", {"histograms": {"x_ms": h.snapshot()}})
        norm = agg.to_json()["tasks"]["w:0"]["histograms"]["x_ms"]
        assert norm["max"] == 3.0
        assert obs_metrics.histogram_quantile(norm, 0.95) == 3.0


# ---------------------------------------------------------------------------
# stepstats.py — the per-step anatomy recorder
# ---------------------------------------------------------------------------
from tony_tpu.observability import stepstats as stepstats_mod  # noqa: E402


class _TinyCfg:
    """Transformer-shaped config for the analytic flops model."""
    d_model = 64
    n_layers = 2
    vocab_size = 512
    n_heads = 4
    head_dim = 16
    n_kv_heads = 2
    d_ff = 256
    dtype = "float32"


class _Clock:
    def __init__(self):
        self.t = 100.0

    def __call__(self):
        return self.t

    def advance(self, s):
        self.t += s


class TestStepStats:
    def _stats(self, reg, clock, **kw):
        kw.setdefault("cfg", _TinyCfg())
        kw.setdefault("peak_flops", 1e12)
        kw.setdefault("calibrate", False)
        kw.setdefault("enabled", True)
        return stepstats_mod.StepStats(
            registry=reg, clock=clock, **kw
        )

    def test_phases_are_exclusive_and_sum_to_wall(self):
        reg = obs_metrics.MetricsRegistry()
        clock = _Clock()
        stats = self._stats(reg, clock)
        stats.step_begin((4, 33))       # dispatch 1 = trace + compile
        clock.advance(5.0)              # a 5 s compile wall...
        stats.step_begin((4, 33))       # ...dropped, never published
        stats.step_end(0.002)
        clock.advance(0.1)              # one 100 ms step
        stats.step_begin((4, 33))
        g = reg.snapshot()["gauges"]
        phases = {
            p: g[f'tony_step_phase_ms{{phase="{p}"}}']
            for p in stepstats_mod.PHASES
        }
        assert sum(phases.values()) == pytest.approx(100.0, rel=1e-6)
        assert phases["host"] == pytest.approx(2.0)       # the dispatch
        assert phases["compute"] == pytest.approx(98.0)   # residual, no plan
        assert phases["data_wait"] == 0.0 and phases["h2d"] == 0.0
        # MFU: analytic flops over wall × 1 device × pinned peak
        flops = stepstats_mod.model_flops_per_step(_TinyCfg(), 4, 32)
        assert g["tony_mfu"] == pytest.approx(
            flops / (0.1 * 1e12), abs=1e-5  # gauge rounds to 5 decimals
        )
        assert g["tony_model_flops_per_step"] == flops
        # report() rode along: the straggler detector's gauge is fed
        assert g["step_time_ms"] == pytest.approx(100.0)
        assert stats.steps_observed == 1

    def test_wrap_batches_attributes_input_wait(self):
        reg = obs_metrics.MetricsRegistry()
        clock = _Clock()
        stats = self._stats(reg, clock)

        def slow_batches():
            while True:
                clock.advance(0.03)    # 30 ms blocked in next()
                yield (4, 33)

        it = stats.wrap_batches(slow_batches())
        shape = next(it)
        stats.step_begin(shape)        # dispatch 1 = compile
        stats.step_end(0.0)
        shape = next(it)
        clock.advance(0.07)
        stats.step_begin(shape)        # compile interval dropped
        shape = next(it)               # +30 ms data wait
        clock.advance(0.07)            # +70 ms "device" work
        stats.step_begin(shape)
        g = reg.snapshot()["gauges"]
        assert g['tony_step_phase_ms{phase="data_wait"}'] == \
            pytest.approx(30.0, rel=1e-6)
        assert g['tony_step_phase_ms{phase="compute"}'] == \
            pytest.approx(70.0, rel=1e-6)

    def test_disabled_recorder_is_inert(self):
        reg = obs_metrics.MetricsRegistry()
        clock = _Clock()
        stats = self._stats(reg, clock, enabled=False)
        batches = iter([(4, 33)])
        assert stats.wrap_batches(batches) is batches
        stats.step_begin((4, 33))
        clock.advance(0.1)
        stats.step_begin((4, 33))
        assert reg.snapshot()["gauges"] == {}

    def test_classifier_workload_gets_phases_but_no_mfu(self):
        reg = obs_metrics.MetricsRegistry()
        clock = _Clock()
        stats = self._stats(reg, clock, cfg=None, tokens_workload=False,
                            steps_per_call=2)
        stats.step_begin((8, 28, 28, 1))
        clock.advance(0.2)              # compile call — dropped
        stats.step_begin((8, 28, 28, 1))
        clock.advance(0.2)              # 200 ms call = 2 fused steps
        stats.step_begin((8, 28, 28, 1))
        g = reg.snapshot()["gauges"]
        assert g['tony_step_phase_ms{phase="compute"}'] == \
            pytest.approx(100.0)        # per-step, not per-call
        assert "tony_mfu" not in g
        assert stats.steps_observed == 2

    def test_deferred_sizing_uses_builder_global_shape(self):
        """size_from_shapes=False: the dispatch hook's (local) shape is
        ignored — the builder sizes with the assembled GLOBAL batch, the
        multi-process contract make_train_step relies on (hook sees one
        process's shard; MFU/calibration must use the global work)."""
        reg = obs_metrics.MetricsRegistry()
        clock = _Clock()
        stats = self._stats(reg, clock, size_from_shapes=False)
        stats.step_begin((4, 33))       # hook: local shard [4, 33]
        stats.set_workload(8, 32)       # builder: global batch is 8
        clock.advance(0.1)
        stats.step_begin((4, 33))       # compile interval dropped
        clock.advance(0.1)
        stats.step_begin((4, 33))
        g = reg.snapshot()["gauges"]
        flops = stepstats_mod.model_flops_per_step(_TinyCfg(), 8, 32)
        assert g["tony_model_flops_per_step"] == flops
        assert g["tony_mfu"] == pytest.approx(
            flops / (0.1 * 1e12), abs=1e-5
        )

    def test_live_calibration_records_and_publishes_residual(
        self, tmp_path, monkeypatch,
    ):
        from tony_tpu.models import TransformerConfig
        from tony_tpu.parallel import plan as plan_lib

        monkeypatch.setattr(
            plan_lib, "active_cache_dir", lambda: str(tmp_path)
        )
        cfg = TransformerConfig(
            vocab_size=64, d_model=32, n_layers=4, n_heads=4, head_dim=8,
            d_ff=64, max_seq=64, dtype="float32", n_kv_heads=2,
        )
        reg = obs_metrics.MetricsRegistry()
        clock = _Clock()
        stats = stepstats_mod.StepStats(
            cfg=cfg, plan=plan_lib.Plan(plan_lib.MeshSpec()),
            registry=reg, clock=clock, peak_flops=1e12,
            calibrate=True, window=2,
        )
        stats.step_begin((4, 17))       # compile
        for _ in range(4):
            clock.advance(0.05)
            stats.step_begin((4, 17))
        table = plan_lib.load_measurements(cache_dir=str(tmp_path))
        assert len(table) == 1
        (bucket,) = table.values()
        assert bucket == {"dp1.pp1.ep1.sp1.tp1":
            pytest.approx(50.0, rel=0.01)}
        g = reg.snapshot()["gauges"]
        assert g['tony_plan_residual{plan="dp1.pp1.ep1.sp1.tp1"}'] == pytest.approx(1.0)

    def test_calibration_failure_never_raises(self, monkeypatch):
        from tony_tpu.parallel import plan as plan_lib

        def boom(*a, **kw):
            raise OSError("cache dir gone")

        monkeypatch.setattr(plan_lib, "record_step_time", boom)
        reg = obs_metrics.MetricsRegistry()
        clock = _Clock()
        stats = stepstats_mod.StepStats(
            cfg=_TinyCfg(), plan=plan_lib.Plan(plan_lib.MeshSpec()),
            registry=reg, clock=clock, peak_flops=1e12,
            calibrate=True, window=1,
        )
        stats.step_begin((4, 33))
        for _ in range(5):
            clock.advance(0.05)
            stats.step_begin((4, 33))   # calibration is telemetry: no raise
        assert stats.steps_observed == 4

    def test_counter_rate_clamps_restart_resets(self):
        assert stepstats_mod.counter_rate(100.0, 110.0, 2.0) == 5.0
        # a task restart resets its process-local counters: the reset
        # must read as zero progress, never a negative rate
        assert stepstats_mod.counter_rate(100.0, 3.0, 2.0) == 0.0
        assert stepstats_mod.counter_rate(1.0, 2.0, 0.0) == 0.0

    def test_view_and_format_roundtrip(self):
        snap = {
            "counters": {
                "train_steps_total": 40,
                'tony_collective_bytes_total{axis="dp"}': 4096.0,
            },
            "gauges": {
                'tony_step_phase_ms{phase="data_wait"}': 60.0,
                'tony_step_phase_ms{phase="h2d"}': 5.0,
                'tony_step_phase_ms{phase="compute"}': 30.0,
                'tony_step_phase_ms{phase="collective"}': 4.0,
                'tony_step_phase_ms{phase="host"}': 1.0,
                "tony_mfu": 0.42,
                'tony_plan_residual{plan="dp2"}': 1.08,
            },
        }
        view = stepstats_mod.stepstats_view({"worker:0": snap,
                                             "worker:1": {"gauges": {}}})
        assert list(view["tasks"]) == ["worker:0"]
        t = view["tasks"]["worker:0"]
        assert t["dominant_phase"] == "data_wait"
        assert t["step_time_ms"] == pytest.approx(100.0)
        assert t["shares"]["data_wait"] == pytest.approx(0.6)
        assert t["mfu"] == 0.42
        assert t["collective_bytes"] == {"dp": 4096.0}
        assert t["residuals"] == {"dp2": 1.08}
        assert view["fleet"]["dominant_phase"] == "data_wait"
        assert view["fleet"]["mfu_median"] == pytest.approx(0.42)
        text = stepstats_mod.format_top("app_1", view, "final")
        assert "DATA_WAIT" in text and "worker:0" in text
        assert "0.4200" in text and "data_wait" in text


class TestAggregatorStepstats:
    def test_task_restart_resets_do_not_go_negative(self):
        """A task that restarts mid-session resets its process-local
        counters; the gauge series stays a monotonic-ts timeline and
        stepstats-derived rates clamp at zero instead of amplifying
        the drop."""
        agg = MetricsAggregator()
        agg.ingest("w:0", {"ts_ms": 1_000,
                           "counters": {"train_steps_total": 100},
                           "gauges": {"step_time_ms": 5.0}})
        # restart: counters reset, wall clock moved on
        agg.ingest("w:0", {"ts_ms": 3_000,
                           "counters": {"train_steps_total": 3},
                           "gauges": {"step_time_ms": 7.0}})
        doc = agg.to_json()
        series = doc["series"]["w:0:step_time_ms"]
        assert [ts for ts, _ in series] == sorted(
            ts for ts, _ in series
        )
        first = doc["tasks"]["w:0"]["counters"]["train_steps_total"]
        assert first == 3  # latest snapshot shows the reset plainly
        rate = stepstats_mod.counter_rate(100, 3, 2.0)
        assert rate == 0.0

    def test_stepstats_json_and_api_endpoint(self):
        agg = MetricsAggregator()
        agg.ingest("w:0", {"ts_ms": 1, "counters": {}, "gauges": {
            'tony_step_phase_ms{phase="data_wait"}': 1.0,
            'tony_step_phase_ms{phase="h2d"}': 0.0,
            'tony_step_phase_ms{phase="compute"}': 8.0,
            'tony_step_phase_ms{phase="collective"}': 0.5,
            'tony_step_phase_ms{phase="host"}': 0.5,
            "tony_mfu": 0.33,
        }})
        view = agg.stepstats_json()
        assert view["tasks"]["w:0"]["dominant_phase"] == "compute"
        assert view["fleet"]["mfu_median"] == pytest.approx(0.33)

        server = ObservabilityHttpServer(agg, port=0)
        server.serve_background()
        try:
            doc = json.loads(urllib.request.urlopen(
                f"http://127.0.0.1:{server.port}/api/stepstats", timeout=5
            ).read())
            assert doc["tasks"]["w:0"]["mfu"] == pytest.approx(0.33)
            assert doc["fleet"]["tasks"] == 1
        finally:
            server.stop()


# ---------------------------------------------------------------------------
# step-anatomy mini-cluster e2e (the PR-10 acceptance scenario)
# ---------------------------------------------------------------------------
def test_mini_cluster_stepstats_training_e2e(tmp_path, capsys):
    """A REAL training job (examples/lm_train.py through make_train_step)
    publishes its step anatomy end to end: tony_step_phase_ms{phase=}
    and a nonzero tony_mfu on the coordinator's live /metrics, phases
    summing to the step wall within 5% in the persisted snapshot, a
    plan-measurements.json entry recorded by the LIVE job (not bench),
    and `tony top` rendering the breakdown from job history after the
    job exits."""
    import re

    repo = FIXTURES.parent.parent
    cache_dir = tmp_path / "xla-cache"
    cluster = MiniTonyCluster(tmp_path)
    conf = cluster.base_conf()
    conf.set(keys.K_EXECUTES, str(repo / "examples" / "lm_train.py"))
    conf.set(keys.K_PYTHON_BINARY, sys.executable)
    conf.set(keys.instances_key("worker"), 1)
    conf.set(keys.instances_key("ps"), 0)
    conf.set(keys.K_TASK_HEARTBEAT_INTERVAL_MS, 150)
    conf.set(keys.K_COMPILE_CACHE_DIR, str(cache_dir))
    conf.set(
        keys.K_TASK_PARAMS,
        "--steps 160 --d-model 32 --n-layers 2 --n-heads 2 "
        "--n-kv-heads 1 --vocab 128 --batch 4 --seq 64 "
        "--checkpoint-every 100000",
    )

    app_id = "application_mini_anatomy1"
    app_dir = cluster.staging_dir / app_id
    app_dir.mkdir(parents=True)
    conf.write_final(app_dir / constants.TONY_FINAL_CONF)
    coordinator = TonyCoordinator(
        conf, app_dir, app_id=app_id,
        backend=LocalProcessBackend(app_dir / "logs"),
    )
    result = []
    t = threading.Thread(
        target=lambda: result.append(coordinator.run()), daemon=True
    )
    cluster._live.append(coordinator)
    t.start()
    live_mfu = None
    live_phases = False
    try:
        deadline = time.monotonic() + 180
        addr_file = app_dir / "coordinator.http"
        while not addr_file.is_file() and time.monotonic() < deadline:
            time.sleep(0.05)
        assert addr_file.is_file(), "coordinator.http never advertised"
        addr = addr_file.read_text().strip()
        # Scrape /metrics WHILE the job trains: the anatomy gauges ride
        # the heartbeat piggyback onto the live endpoint.
        while time.monotonic() < deadline and t.is_alive():
            try:
                text = urllib.request.urlopen(
                    f"http://{addr}/metrics", timeout=5
                ).read().decode()
            except OSError:
                time.sleep(0.05)
                continue
            if not live_phases:
                live_phases = "tony_step_phase_ms" in text
            m = re.search(r"tony_mfu\{[^}]*\} ([0-9.eE+-]+)", text)
            if m:
                live_mfu = float(m.group(1))
                if live_mfu > 0 and live_phases:
                    break
            time.sleep(0.05)
    finally:
        t.join(timeout=240)
    assert result and result[0] is SessionStatus.SUCCEEDED, (
        coordinator.session.diagnostics if coordinator.session else "no run"
    )
    assert live_phases, "tony_step_phase_ms never appeared on live /metrics"
    assert live_mfu is not None and live_mfu > 0, (
        f"nonzero tony_mfu never appeared on live /metrics ({live_mfu})"
    )

    # -- persisted snapshot: exclusive phases summing to the step wall ----
    from tony_tpu.observability import stepstats as ss

    final = json.loads((app_dir / "final-status.json").read_text())
    entry = ss.task_stepstats(final["metrics"]["tasks"]["worker:0"])
    assert entry is not None
    assert set(entry["phases"]) == set(ss.PHASES)
    gauges = final["metrics"]["tasks"]["worker:0"]["gauges"]
    assert sum(entry["phases"].values()) == pytest.approx(
        gauges["step_time_ms"], rel=0.05
    )
    assert gauges["tony_mfu"] > 0

    # -- live calibration: the JOB recorded a measurement, not bench ------
    from tony_tpu.parallel import plan as plan_lib

    table = plan_lib.load_measurements(cache_dir=str(cache_dir))
    assert table, "plan-measurements.json not written by the live job"
    (bucket,) = table.values()
    assert any(v > 0 for v in bucket.values())

    # -- `tony top` renders the breakdown from job history ----------------
    from tony_tpu.client import cli

    empty = tmp_path / "empty-staging"
    empty.mkdir()
    rc = cli.main([
        "top", app_id,
        "--staging-location", str(empty),  # force the history leg
        "--history-location", str(cluster.history_dir),
    ])
    out = capsys.readouterr().out
    assert rc == 0
    assert "(history)" in out and "worker:0" in out
    assert "DATA_WAIT" in out and "COLLECTIVE" in out


def test_mini_cluster_stepstats_chaos_io_throttle(tmp_path, capsys):
    """Seeded io-throttle chaos: a `throttle_io` fault-plan entry starves
    the input pipeline mid-run — the dominant phase flips to data_wait,
    the mfu_collapse detector fires a health_alert, and `tony doctor`
    surfaces the TONY-D012 step-anatomy finding."""
    cluster = MiniTonyCluster(tmp_path)
    conf = cluster.base_conf()
    conf.set(keys.K_EXECUTES, str(FIXTURES / "stepstats_train.py"))
    conf.set(keys.K_PYTHON_BINARY, sys.executable)
    conf.set(keys.instances_key("worker"), 1)
    conf.set(keys.instances_key("ps"), 0)
    conf.set(keys.K_TASK_HEARTBEAT_INTERVAL_MS, 100)
    conf.set(keys.K_SHELL_ENV,
             "FIXTURE_STEPS=82,FIXTURE_COMPUTE_S=0.012,LINGER_S=1.0")
    conf.set(keys.K_FAULT_PLAN, json.dumps({
        "seed": 3,
        "faults": [{"action": "throttle_io", "target": "worker:0",
                    "ms": 150, "after_batches": 68, "count": 100000}],
    }))
    status, coord = cluster.run_job(conf)
    assert status is SessionStatus.SUCCEEDED, (
        coord.session.diagnostics if coord.session else "no run"
    )

    # -- the throttle flipped the dominant phase to data_wait -------------
    from tony_tpu.observability import stepstats as ss

    final = json.loads((coord.app_dir / "final-status.json").read_text())
    entry = ss.task_stepstats(final["metrics"]["tasks"]["worker:0"])
    assert entry is not None
    assert entry["dominant_phase"] == "data_wait", entry

    # -- the detector fired into the lifecycle log ------------------------
    events = obs_events.parse_jsonl(
        (coord.app_dir / "events.jsonl").read_text()
    )
    alerts = [e for e in events if e["kind"] == "health_alert"]
    assert any(e.get("detector") == "mfu_collapse" for e in alerts), (
        [(e.get("detector"), e.get("reason")) for e in alerts]
    )

    # -- `tony doctor` surfaces the step-anatomy finding ------------------
    from tony_tpu.client import cli

    rc = cli.main([
        "doctor", coord.app_id,
        "--staging-location", str(cluster.staging_dir),
        "--history-location", str(cluster.history_dir),
    ])
    out = capsys.readouterr().out
    assert rc == 0
    assert "TONY-D012" in out and "MFU collapsed" in out
