"""Ops-layer tests: the Pallas kernels run in interpret mode on CPU so
kernel math is validated without TPU hardware; the blockwise-JAX paths are
checked against naive references and through grad."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tony_tpu.ops import (
    apply_rope,
    flash_attention,
    rms_norm,
    rope_frequencies,
    softmax_cross_entropy,
)
from tony_tpu.ops.attention import _blockwise_attention_jax, _flash_attention_pallas


def naive_attention(q, k, v, causal=True):
    scale = q.shape[-1] ** -0.5
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        tq, tk = q.shape[1], k.shape[1]
        mask = np.arange(tq)[:, None] >= np.arange(tk)[None, :]
        s = jnp.where(jnp.asarray(mask)[None, None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


@pytest.fixture
def qkv():
    rng = np.random.default_rng(0)
    b, t, h, d = 2, 64, 2, 16
    mk = lambda: jnp.asarray(rng.normal(size=(b, t, h, d)), dtype=jnp.float32)
    return mk(), mk(), mk()


class TestFlashAttention:
    @pytest.mark.parametrize("causal", [True, False])
    def test_jax_path_matches_naive(self, qkv, causal):
        q, k, v = qkv
        out = flash_attention(q, k, v, causal=causal, block_k=16, force_jax=True)
        ref = naive_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    @pytest.mark.parametrize("causal", [True, False])
    def test_pallas_kernel_interpret_matches_naive(self, qkv, causal):
        q, k, v = qkv
        b, t, h, d = q.shape
        qf = q.transpose(0, 2, 1, 3).reshape(b * h, t, d)
        kf = k.transpose(0, 2, 1, 3).reshape(b * h, t, d)
        vf = v.transpose(0, 2, 1, 3).reshape(b * h, t, d)
        out = _flash_attention_pallas(
            qf, kf, vf, causal=causal, scale=d**-0.5,
            block_q=16, block_k=16, interpret=True,
        )
        out = out.reshape(b, h, t, d).transpose(0, 2, 1, 3)
        ref = naive_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    def test_uneven_block_sizes(self, qkv):
        q, k, v = qkv
        out = flash_attention(q, k, v, block_q=48, block_k=48, force_jax=True)
        ref = naive_attention(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    def test_uneven_blocks_pallas_interpret(self, qkv):
        q, k, v = qkv
        b, t, h, d = q.shape
        qf = q.transpose(0, 2, 1, 3).reshape(b * h, t, d)
        kf = k.transpose(0, 2, 1, 3).reshape(b * h, t, d)
        vf = v.transpose(0, 2, 1, 3).reshape(b * h, t, d)
        out = _flash_attention_pallas(
            qf, kf, vf, causal=True, scale=d**-0.5,
            block_q=48, block_k=48, interpret=True,
        )
        out = out.reshape(b, h, t, d).transpose(0, 2, 1, 3)
        ref = naive_attention(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    def test_cross_attention_lengths(self):
        rng = np.random.default_rng(1)
        q = jnp.asarray(rng.normal(size=(1, 8, 2, 8)), dtype=jnp.float32)
        k = jnp.asarray(rng.normal(size=(1, 32, 2, 8)), dtype=jnp.float32)
        v = jnp.asarray(rng.normal(size=(1, 32, 2, 8)), dtype=jnp.float32)
        out = flash_attention(q, k, v, causal=False, block_k=8, force_jax=True)
        ref = naive_attention(q, k, v, causal=False)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    def naive_decode_attention(self, q, k, v):
        """Causal with the query block at the END of the key range."""
        scale = q.shape[-1] ** -0.5
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
        tq, tk = q.shape[1], k.shape[1]
        q_pos = (tk - tq) + np.arange(tq)
        mask = q_pos[:, None] >= np.arange(tk)[None, :]
        s = jnp.where(jnp.asarray(mask)[None, None], s, -1e30)
        p = jax.nn.softmax(s, axis=-1)
        return jnp.einsum("bhqk,bkhd->bqhd", p, v)

    def test_causal_decode_attends_full_prefix(self):
        """t_q=1 against a t_k=8 cache must attend to ALL 8 keys (decode
        convention), not just key 0."""
        rng = np.random.default_rng(7)
        q = jnp.asarray(rng.normal(size=(1, 1, 2, 8)), dtype=jnp.float32)
        k = jnp.asarray(rng.normal(size=(1, 8, 2, 8)), dtype=jnp.float32)
        v = jnp.asarray(rng.normal(size=(1, 8, 2, 8)), dtype=jnp.float32)
        out = flash_attention(q, k, v, causal=True, block_k=4, force_jax=True)
        ref = self.naive_decode_attention(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    def test_causal_decode_pallas_interpret(self):
        rng = np.random.default_rng(8)
        tq, tk, d = 4, 32, 8
        q = jnp.asarray(rng.normal(size=(2, tq, d)), dtype=jnp.float32)
        k = jnp.asarray(rng.normal(size=(2, tk, d)), dtype=jnp.float32)
        v = jnp.asarray(rng.normal(size=(2, tk, d)), dtype=jnp.float32)
        out = _flash_attention_pallas(
            q, k, v, causal=True, scale=d**-0.5,
            block_q=4, block_k=8, interpret=True,
        )
        ref = self.naive_decode_attention(
            q.reshape(2, tq, 1, d),
            k.reshape(2, tk, 1, d),
            v.reshape(2, tk, 1, d),
        ).reshape(2, tq, d)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    def test_grad_matches_naive(self, qkv):
        q, k, v = qkv

        def loss_flash(q, k, v):
            return flash_attention(q, k, v, block_k=16, force_jax=True).sum()

        def loss_naive(q, k, v):
            return naive_attention(q, k, v).sum()

        g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(loss_naive, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-5)

    def test_bf16_runs(self, qkv):
        q, k, v = (x.astype(jnp.bfloat16) for x in qkv)
        out = flash_attention(q, k, v, force_jax=True)
        assert out.dtype == jnp.bfloat16


class TestRmsNorm:
    def test_matches_reference(self):
        rng = np.random.default_rng(2)
        x = jnp.asarray(rng.normal(size=(4, 32)), dtype=jnp.float32)
        w = jnp.asarray(rng.normal(size=(32,)), dtype=jnp.float32)
        out = rms_norm(x, w, force_jax=True)
        ref = x / np.sqrt((np.asarray(x) ** 2).mean(-1, keepdims=True) + 1e-6) * w
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)

    def test_pallas_kernel_interpret_matches_jax(self):
        from tony_tpu.ops.norms import _rms_norm_jax, _rms_norm_pallas

        rng = np.random.default_rng(6)
        x = jnp.asarray(rng.normal(size=(300, 32)), dtype=jnp.float32)
        w = jnp.asarray(rng.normal(size=(32,)), dtype=jnp.float32)
        out = _rms_norm_pallas(x, w, 1e-6, block_rows=128, interpret=True)
        ref = _rms_norm_jax(x, w, 1e-6)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)

    def test_grad_finite(self):
        x = jnp.ones((2, 8))
        w = jnp.ones((8,))
        g = jax.grad(lambda x: rms_norm(x, w, force_jax=True).sum())(x)
        assert np.isfinite(np.asarray(g)).all()


class TestRope:
    def test_rotation_preserves_norm(self):
        rng = np.random.default_rng(3)
        x = jnp.asarray(rng.normal(size=(1, 16, 2, 8)), dtype=jnp.float32)
        cos, sin = rope_frequencies(8, 32)
        y = apply_rope(x, cos, sin)
        np.testing.assert_allclose(
            np.linalg.norm(np.asarray(x), axis=-1),
            np.linalg.norm(np.asarray(y), axis=-1),
            atol=1e-4,
        )

    def test_position_offset_matches_slicing(self):
        """Sharded application with explicit positions == slicing the full
        result (the sequence-parallel contract)."""
        rng = np.random.default_rng(4)
        x = jnp.asarray(rng.normal(size=(1, 16, 2, 8)), dtype=jnp.float32)
        cos, sin = rope_frequencies(8, 32)
        full = apply_rope(x, cos, sin)
        half = apply_rope(x[:, 8:], cos, sin, positions=jnp.arange(8, 16))
        np.testing.assert_allclose(
            np.asarray(full[:, 8:]), np.asarray(half), atol=1e-6
        )

    def test_position_zero_is_identity(self):
        x = jnp.ones((1, 1, 1, 8))
        cos, sin = rope_frequencies(8, 4)
        y = apply_rope(x, cos, sin)
        np.testing.assert_allclose(np.asarray(y), np.asarray(x), atol=1e-6)


class TestCrossEntropy:
    def test_matches_naive(self):
        rng = np.random.default_rng(5)
        logits = jnp.asarray(rng.normal(size=(4, 10)), dtype=jnp.float32)
        labels = jnp.asarray(rng.integers(0, 10, size=(4,)))
        out = softmax_cross_entropy(logits, labels)
        p = jax.nn.log_softmax(logits)
        ref = -p[jnp.arange(4), labels].mean()
        np.testing.assert_allclose(float(out), float(ref), atol=1e-6)

    def test_mask_excludes_entries(self):
        logits = jnp.zeros((4, 10))
        labels = jnp.zeros((4,), dtype=jnp.int32)
        where = jnp.asarray([True, True, False, False])
        out = softmax_cross_entropy(logits, labels, where=where)
        full = softmax_cross_entropy(logits[:2], labels[:2])
        np.testing.assert_allclose(float(out), float(full), atol=1e-6)

    def test_extreme_logits_stable(self):
        logits = jnp.asarray([[1e4, -1e4, 0.0]])
        labels = jnp.asarray([0])
        out = softmax_cross_entropy(logits, labels)
        assert np.isfinite(float(out))


class TestFlashBackwardKernels:
    """Pallas backward (dq + dkv kernels) in interpret mode, pinned to the
    blockwise-JAX vjp — the path the TPU takes for training."""

    @pytest.mark.parametrize("causal", [True, False])
    @pytest.mark.parametrize("t", [64, 40])  # exact and partial final blocks
    def test_bwd_kernels_match_blockwise_vjp(self, causal, t):
        from tony_tpu.ops.attention import (
            _blockwise_attention_jax,
            _flash_attention_pallas,
            _flash_attention_pallas_bwd,
        )

        rng = np.random.default_rng(0)
        bh, d = 4, 16
        q, k, v = (
            jnp.asarray(rng.normal(size=(bh, t, d)), jnp.float32)
            for _ in range(3)
        )
        g = jnp.asarray(rng.normal(size=(bh, t, d)), jnp.float32)
        scale = d ** -0.5

        out, lse = _flash_attention_pallas(
            q, k, v, causal=causal, scale=scale, block_q=16, block_k=16,
            interpret=True, return_lse=True,
        )
        dq, dk, dv = _flash_attention_pallas_bwd(
            q, k, v, out, lse, g, causal=causal, scale=scale,
            block_q=16, block_k=16, interpret=True,
        )
        ref_out, ref_vjp = jax.vjp(
            lambda q, k, v: _blockwise_attention_jax(
                q, k, v, causal=causal, scale=scale, block_k=16
            ),
            q, k, v,
        )
        rq, rk, rv = ref_vjp(g)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref_out),
                                   atol=2e-5)
        np.testing.assert_allclose(np.asarray(dq), np.asarray(rq), atol=3e-4)
        np.testing.assert_allclose(np.asarray(dk), np.asarray(rk), atol=3e-4)
        np.testing.assert_allclose(np.asarray(dv), np.asarray(rv), atol=3e-4)

    def test_bwd_cross_attention_lengths(self):
        from tony_tpu.ops.attention import (
            _blockwise_attention_jax,
            _flash_attention_pallas,
            _flash_attention_pallas_bwd,
        )

        rng = np.random.default_rng(1)
        bh, d, t_q, t_k = 2, 16, 16, 48  # decode convention
        q = jnp.asarray(rng.normal(size=(bh, t_q, d)), jnp.float32)
        k = jnp.asarray(rng.normal(size=(bh, t_k, d)), jnp.float32)
        v = jnp.asarray(rng.normal(size=(bh, t_k, d)), jnp.float32)
        g = jnp.asarray(rng.normal(size=(bh, t_q, d)), jnp.float32)
        scale = d ** -0.5
        out, lse = _flash_attention_pallas(
            q, k, v, causal=True, scale=scale, block_q=16, block_k=16,
            interpret=True, return_lse=True,
        )
        dq, dk, dv = _flash_attention_pallas_bwd(
            q, k, v, out, lse, g, causal=True, scale=scale,
            block_q=16, block_k=16, interpret=True,
        )
        _, ref_vjp = jax.vjp(
            lambda q, k, v: _blockwise_attention_jax(
                q, k, v, causal=True, scale=scale, block_k=16
            ),
            q, k, v,
        )
        for got, want in zip((dq, dk, dv), ref_vjp(g)):
            np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                       atol=3e-4)
