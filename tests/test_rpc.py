"""RPC layer tests: the 7-call protocol over real sockets, the rendezvous
barrier semantics, auth, error framing, and client reconnects."""

import threading
import time

import pytest

from tony_tpu.rpc import ApplicationRpc, ApplicationRpcClient, ApplicationRpcServer, RpcError, TaskUrl


class FakeApp(ApplicationRpc):
    """Minimal coordinator-side impl with a 2-task rendezvous barrier."""

    def __init__(self, expected=2):
        self.expected = expected
        self.registered = {}
        self.heartbeats = []
        self.results = []
        self.finished = threading.Event()
        self.tb_url = None

    def get_task_urls(self):
        return [TaskUrl("worker", 0, "http://logs/0"), TaskUrl("worker", 1, "http://logs/1")]

    def get_cluster_spec(self):
        if len(self.registered) < self.expected:
            return None
        return self._spec()

    def _spec(self):
        spec = {}
        for worker, addr in sorted(self.registered.items()):
            job = worker.split(":")[0]
            spec.setdefault(job, []).append(addr)
        return spec

    def register_worker_spec(self, worker, spec):
        self.registered[worker] = spec
        if len(self.registered) < self.expected:
            return None
        return self._spec()

    def register_tensorboard_url(self, spec, url):
        self.tb_url = (spec, url)
        return None

    def register_execution_result(self, exit_code, job_name, job_index, session_id):
        self.results.append((exit_code, job_name, job_index, session_id))
        return None

    def finish_application(self):
        self.finished.set()

    def task_executor_heartbeat(self, task_id, session_id, metrics=None,
                                profile=None):
        self.heartbeats.append(task_id)
        return None

    def request_profile(self, duration_ms):
        return {"req_id": f"prof-{duration_ms}"}

    def get_application_status(self):
        return {"state": "RUNNING", "diagnostics": ""}


@pytest.fixture()
def served():
    app = FakeApp()
    server = ApplicationRpcServer(app, host="127.0.0.1", port_range=(20000, 25000))
    server.start()
    yield app, server
    server.stop()


def _client(server, **kw):
    return ApplicationRpcClient("127.0.0.1", server.port, **kw)


def test_rendezvous_barrier(served):
    app, server = served
    c0 = _client(server)
    c1 = _client(server)
    assert c0.get_cluster_spec() is None
    assert c0.register_worker_spec("worker:0", "h0:1000") is None  # barrier holds
    spec = c1.register_worker_spec("worker:1", "h1:1001")
    assert spec == {"worker": ["h0:1000", "h1:1001"]}
    assert c0.get_cluster_spec() == spec  # late poll sees the released spec


def test_all_seven_calls(served):
    app, server = served
    c = _client(server)
    urls = c.get_task_urls()
    assert urls[0] == TaskUrl("worker", 0, "http://logs/0")
    c.register_worker_spec("worker:0", "h0:1")
    c.register_worker_spec("worker:1", "h1:2")
    c.register_tensorboard_url("worker:0", "http://tb:6006")
    assert app.tb_url == ("worker:0", "http://tb:6006")
    c.register_execution_result(0, "worker", "0", "s0")
    assert app.results == [(0, "worker", "0", "s0")]
    c.task_executor_heartbeat("worker:0", "1")
    assert app.heartbeats == ["worker:0"]
    c.finish_application()
    assert app.finished.is_set()


def test_auth_rejected():
    app = FakeApp()
    server = ApplicationRpcServer(
        app, host="127.0.0.1", port_range=(20000, 25000), secret="s3cr3t"
    )
    server.start()
    try:
        bad = ApplicationRpcClient("127.0.0.1", server.port, secret="wrong")
        with pytest.raises(RpcError, match="authentication"):
            bad.get_cluster_spec()
        good = ApplicationRpcClient("127.0.0.1", server.port, secret="s3cr3t")
        assert good.get_cluster_spec() is None
    finally:
        server.stop()


def test_remote_error_travels_framed(served):
    _, server = served

    class Exploding(FakeApp):
        def get_task_urls(self):
            raise RuntimeError("boom")

    server._impl = Exploding()
    c = _client(server)
    with pytest.raises(RpcError, match="RuntimeError: boom"):
        c.get_task_urls()
    # connection still usable after a remote error
    assert c.get_cluster_spec() is None


def test_unknown_method_and_bad_args(served):
    _, server = served
    assert server.dispatch({"method": "nope"})["ok"] is False
    r = server.dispatch({"method": "task_executor_heartbeat", "args": {"bad": 1}})
    assert r["ok"] is False and "expects args" in r["error"]
    assert server.dispatch("junk")["ok"] is False


def test_client_reconnects_after_drop(served):
    app, server = served
    c = _client(server, retry_interval_s=0.05)
    c.task_executor_heartbeat("worker:0", "1")
    # simulate a dropped connection under the client
    c._sock.close()
    c.task_executor_heartbeat("worker:0", "1")  # must transparently reconnect
    assert app.heartbeats == ["worker:0", "worker:0"]


def test_concurrent_heartbeaters(served):
    app, server = served

    def beat(i):
        c = _client(server)
        for _ in range(10):
            c.task_executor_heartbeat(f"w:{i}", "1")

    threads = [threading.Thread(target=beat, args=(i,)) for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(app.heartbeats) == 40


def test_raising_observer_is_swallowed_and_counted():
    """The dispatch observer's threading contract (see
    ApplicationRpcServer.__init__): an observer exception must never
    kill a dispatch — the RPC reply still goes out, the failure is
    counted, and subsequent dispatches keep observing."""
    app = FakeApp()
    seen = []

    def observer(method, ok, args):
        seen.append((method, ok))
        raise RuntimeError("observer boom")

    server = ApplicationRpcServer(
        app, host="127.0.0.1", port_range=(20000, 25000),
        observer=observer,
    )
    server.start()
    try:
        c = _client(server)
        # Over the real wire: the reply arrives despite the raise.
        assert c.get_task_urls()[0] == TaskUrl("worker", 0, "http://logs/0")
        c.task_executor_heartbeat("w:0", "1")
        # A direct (in-process) dispatch counts the same way; the
        # ok=False observer path is pinned by the next test.
        r = server.dispatch({"method": "task_executor_heartbeat",
                             "args": {"task_id": "w:0",
                                      "session_id": "1"}})
        assert r["ok"] is True
        assert server.observer_failures == 3
        assert [m for m, _ in seen] == [
            "get_task_urls", "task_executor_heartbeat",
            "task_executor_heartbeat",
        ]
        assert all(ok for _, ok in seen)
    finally:
        server.stop()


def test_observer_sees_handler_failures_too():
    """ok=False dispatches (impl raised) still reach the observer, and
    a raising observer there is swallowed the same way."""
    class Exploding(FakeApp):
        def finish_application(self):
            raise RuntimeError("impl failed")

    def observer(method, ok, args):
        raise RuntimeError("observer boom")

    server = ApplicationRpcServer(
        Exploding(), host="127.0.0.1", port_range=(20000, 25000),
        observer=observer,
    )
    server.start()
    try:
        r = server.dispatch({"method": "finish_application", "args": {}})
        assert r["ok"] is False and "impl failed" in r["error"]
        assert server.observer_failures == 1
    finally:
        server.stop()
