"""Goodput ledger + on-demand profiling (tony_tpu/observability/
goodput.py, profiling.py): ledger state-machine units (exclusive,
gap-free categories that survive torn/duplicated/out-of-order
events.jsonl replays), the recomputation-debt transfer, fleet/tenant
aggregation, the /api/events cursor `count` protocol, the render-time
heartbeat-age gauge, the scheduler queue-wait histogram, the profile
broker/executor round trip — and two mini-cluster e2e: a successful run
whose breakdown sums to wall clock within 1% with nonzero `productive`
(plus a live `tony profile` capture for every task, persisted to
history), and a chaos-retry run reporting nonzero `wasted_by_failure`.
"""

import json
import random
import sys
import threading
import time
import urllib.request
from pathlib import Path

import pytest

from tony_tpu import constants
from tony_tpu.conf import keys
from tony_tpu.coordinator.app_master import TonyCoordinator
from tony_tpu.coordinator.backend import LocalProcessBackend
from tony_tpu.coordinator.session import SessionStatus
from tony_tpu.mini import MiniTonyCluster
from tony_tpu.observability import events as obs_events
from tony_tpu.observability.aggregator import (
    HEARTBEAT_AGE_GAUGE,
    MetricsAggregator,
    ObservabilityHttpServer,
)
from tony_tpu.observability.goodput import (
    CATEGORIES,
    GOODPUT_RATIO_GAUGE,
    GOODPUT_SECONDS_GAUGE,
    FleetGoodput,
    GoodputLedger,
)
from tony_tpu.observability.metrics import (
    MetricsRegistry,
    histogram_quantile,
)
from tony_tpu.observability.profiling import (
    ExecutorProfiler,
    ProfileBroker,
    capture_snapshot,
    find_profiles,
    run_capture,
)
from tony_tpu.scheduler.queue import (
    QUEUE_WAIT_HISTOGRAM,
    JobQueue,
    SchedJob,
)

FIXTURES = Path(__file__).resolve().parent / "fixtures"


def _clean_run_events():
    """A canonical successful single-session timeline (ms timestamps)."""
    return [
        {"ts_ms": 0, "kind": "job_submitted"},
        {"ts_ms": 1_000, "kind": "job_staged"},
        {"ts_ms": 2_000, "kind": "session_started", "session": 1},
        {"ts_ms": 2_500, "kind": "task_scheduled", "task": "worker:0"},
        {"ts_ms": 3_000, "kind": "task_registered", "task": "worker:0"},
        {"ts_ms": 5_000, "kind": "rendezvous_released"},
        {"ts_ms": 6_000, "kind": "train_progress", "task": "worker:0",
         "steps": 1},
        {"ts_ms": 16_000, "kind": "session_finished", "session": 1,
         "status": "SUCCEEDED"},
        {"ts_ms": 17_000, "kind": "final_status", "state": "SUCCEEDED"},
    ]


class TestGoodputLedger:
    def test_exclusive_and_sums_to_wall(self):
        led = GoodputLedger.from_events(_clean_run_events(), chips=4)
        j = led.to_json()
        assert set(j["categories"]) == set(CATEGORIES)
        assert sum(j["categories"].values()) == pytest.approx(17.0)
        assert j["wall_s"] == pytest.approx(17.0)
        assert j["categories"]["staging"] == pytest.approx(1.0)
        assert j["categories"]["provisioning"] == pytest.approx(2.0)
        assert j["categories"]["rendezvous"] == pytest.approx(2.0)
        assert j["categories"]["compile"] == pytest.approx(1.0)
        assert j["categories"]["productive"] == pytest.approx(10.0)
        assert j["categories"]["teardown"] == pytest.approx(1.0)
        assert j["chip_seconds"]["productive"] == pytest.approx(40.0)
        assert j["ratio"] == pytest.approx(10.0 / 17.0, abs=1e-3)

    def test_torn_duplicated_out_of_order_replay(self):
        """The satellite acceptance: a shuffled, duplicated, torn-tail
        events.jsonl must replay to the same exclusive breakdown."""
        clean = _clean_run_events()
        expected = GoodputLedger.from_events(clean).to_json()

        text = "".join(json.dumps(e) + "\n" for e in clean)
        text += "this line is garbage\n"
        text += json.dumps(clean[3])[:17]  # torn tail
        parsed = obs_events.parse_jsonl(text)
        parsed = parsed + [dict(parsed[2]), dict(parsed[5])]  # duplicates
        rng = random.Random(42)
        rng.shuffle(parsed)

        replayed = GoodputLedger.from_events(parsed).to_json()
        assert sum(replayed["categories"].values()) == pytest.approx(
            sum(expected["categories"].values()), rel=1e-6
        )
        for cat in CATEGORIES:
            assert replayed["categories"][cat] == pytest.approx(
                expected["categories"][cat], abs=1e-6
            ), cat

    def test_failure_transfers_recompute_debt(self):
        evs = _clean_run_events()[:7] + [
            {"ts_ms": 16_000, "kind": "session_finished", "session": 1,
             "status": "FAILED"},
            {"ts_ms": 18_000, "kind": "session_started", "session": 2},
            {"ts_ms": 19_000, "kind": "task_registered", "task": "worker:0"},
            {"ts_ms": 20_000, "kind": "rendezvous_released"},
            {"ts_ms": 21_000, "kind": "train_progress"},
            {"ts_ms": 24_000, "kind": "session_finished", "session": 2,
             "status": "SUCCEEDED"},
            {"ts_ms": 24_500, "kind": "final_status"},
        ]
        j = GoodputLedger.from_events(evs).to_json()
        # Session 1's compile (1s) + productive (10s) become debt; the
        # inter-session backoff reads as provisioning.
        assert j["categories"]["wasted_by_failure"] == pytest.approx(11.0)
        assert j["categories"]["productive"] == pytest.approx(3.0)
        assert sum(j["categories"].values()) == pytest.approx(24.5)

    def test_checkpoint_mark_bounds_the_debt(self):
        evs = _clean_run_events()[:7] + [
            {"ts_ms": 12_000, "kind": "checkpoint_progress", "best_step": 5},
            {"ts_ms": 16_000, "kind": "session_finished", "session": 1,
             "status": "FAILED"},
            {"ts_ms": 16_500, "kind": "final_status"},
        ]
        j = GoodputLedger.from_events(evs).to_json()
        # Only the 4 s since the checkpoint mark are recomputation debt.
        assert j["categories"]["wasted_by_failure"] == pytest.approx(4.0)
        assert j["categories"]["productive"] == pytest.approx(6.0)
        assert sum(j["categories"].values()) == pytest.approx(16.5)

    def test_preemption_category_and_debt(self):
        evs = _clean_run_events()[:7] + [
            {"ts_ms": 10_000, "kind": "job_preempted"},
            {"ts_ms": 15_000, "kind": "job_launched", "warm": True},
            {"ts_ms": 16_000, "kind": "final_status"},
        ]
        j = GoodputLedger.from_events(evs).to_json()
        assert j["categories"]["preempted"] == pytest.approx(5.0)
        # Un-checkpointed work at preemption is debt too.
        assert j["categories"]["wasted_by_failure"] == pytest.approx(5.0)
        assert sum(j["categories"].values()) == pytest.approx(16.0)

    def test_stall_alert_and_recovery(self):
        from tony_tpu.observability.health import IO_STALL, PROGRESS_STALL

        # The ledger's defaults must match the REAL detector names the
        # health monitor emits, or 'stalled' silently stays zero.
        assert PROGRESS_STALL in GoodputLedger.STALL_DETECTORS
        assert IO_STALL in GoodputLedger.STALL_DETECTORS
        evs = _clean_run_events()[:7] + [
            {"ts_ms": 8_000, "kind": "health_alert",
             "detector": PROGRESS_STALL,
             "task": "worker:0", "reason": "no progress"},
            {"ts_ms": 11_000, "kind": "train_progress"},
            {"ts_ms": 14_000, "kind": "session_finished", "session": 1,
             "status": "SUCCEEDED"},
            {"ts_ms": 14_500, "kind": "final_status"},
        ]
        j = GoodputLedger.from_events(evs).to_json()
        assert j["categories"]["stalled"] == pytest.approx(3.0)
        assert j["categories"]["productive"] == pytest.approx(5.0)
        # A non-stall detector must NOT flip the phase.
        evs2 = _clean_run_events()[:7] + [
            {"ts_ms": 8_000, "kind": "health_alert", "detector":
             "straggler", "task": "worker:0", "reason": "slow"},
            {"ts_ms": 14_500, "kind": "final_status"},
        ]
        j2 = GoodputLedger.from_events(evs2).to_json()
        assert j2["categories"]["stalled"] == 0.0

    def test_observe_steps_drives_productive_and_throttles_events(self):
        led = GoodputLedger()
        led.observe_event({"ts_ms": 0, "kind": "session_started"})
        led.observe_event({"ts_ms": 1_000, "kind": "task_registered",
                           "task": "w:0"})
        led.observe_event({"ts_ms": 2_000, "kind": "rendezvous_released"})
        # First advance surfaces an event; the next within 10s does not.
        assert led.observe_steps("w:0", 1, ts_ms=3_000) is True
        assert led.observe_steps("w:0", 2, ts_ms=4_000) is False
        assert led.observe_steps("w:0", 3, ts_ms=14_000) is True
        # A non-advance is not progress.
        assert led.observe_steps("w:0", 3, ts_ms=15_000) is False
        b = led.breakdown(now_ms=15_000)
        assert b["compile"] == pytest.approx(1.0)
        assert b["productive"] == pytest.approx(12.0)

    def test_session_restart_resets_step_baselines(self):
        """A retried session's processes restart their step counters:
        the dead session's totals must not mask the re-run's advances
        (or the whole recompute window would misread as compile)."""
        led = GoodputLedger()
        led.observe_event({"ts_ms": 0, "kind": "session_started"})
        led.observe_event({"ts_ms": 100, "kind": "rendezvous_released"})
        assert led.observe_steps("w:0", 500, ts_ms=200) is True
        led.observe_event({"ts_ms": 300, "kind": "session_finished",
                           "status": "FAILED"})
        led.observe_event({"ts_ms": 400, "kind": "session_started"})
        led.observe_event({"ts_ms": 500, "kind": "rendezvous_released"})
        # Restarted from step 0: 1 <= stale 500, but it must still count
        # (productive reopens at 600 and runs to the 1000ms readout).
        assert led.observe_steps("w:0", 1, ts_ms=600) is True
        assert led.breakdown(now_ms=1_000)["productive"] \
            == pytest.approx(0.4)

    def test_finalize_freezes_and_seed_start_anchors(self):
        led = GoodputLedger()
        led.seed_start(500)
        led.observe_event({"ts_ms": 1_500, "kind": "job_submitted"})
        led.finalize(2_500)
        led.observe_event({"ts_ms": 9_000, "kind": "final_status"})
        j = led.to_json()
        assert j["wall_s"] == pytest.approx(2.0)  # 500 -> 2500, frozen
        assert j["categories"]["staging"] == pytest.approx(2.0)

    def test_publish_sets_gauges(self):
        reg = MetricsRegistry()
        led = GoodputLedger.from_events(_clean_run_events(), chips=2)
        led.publish(reg)
        snap = reg.snapshot()["gauges"]
        key = GOODPUT_SECONDS_GAUGE + '{category="productive"}'
        assert snap[key] == pytest.approx(20.0)
        assert snap[GOODPUT_RATIO_GAUGE] == pytest.approx(
            10.0 / 17.0, abs=1e-3
        )


class TestFleetGoodput:
    def test_per_tenant_accounts_and_ratio(self):
        fleet = FleetGoodput()
        fleet.add("alice", {"productive": 30.0, "compile": 10.0})
        fleet.add("bob", {"productive": 10.0}, queued_chip_s=10.0)
        fleet.add("alice", {"productive": 10.0})
        j = fleet.to_json()
        assert j["tenants"]["alice"]["productive"] == pytest.approx(40.0)
        assert j["tenants"]["bob"]["queued"] == pytest.approx(10.0)
        assert j["fleet_chip_seconds"]["productive"] == pytest.approx(50.0)
        assert j["ratio"] == pytest.approx(50.0 / 70.0, abs=1e-3)
        reg = MetricsRegistry()
        fleet.publish(reg)
        snap = reg.snapshot()["gauges"]
        assert snap[GOODPUT_SECONDS_GAUGE + '{category="queued"}'] \
            == pytest.approx(10.0)

    def test_malformed_breakdown_tolerated(self):
        fleet = FleetGoodput()
        fleet.add("t", {"productive": "garbage", "compile": 5.0})
        assert fleet.fleet()["compile"] == pytest.approx(5.0)
        fleet.add("t", None, queued_chip_s=1.0)
        assert fleet.fleet()["queued"] == pytest.approx(1.0)


class TestHistogramQuantile:
    def test_quantiles_and_empty(self):
        snap = {"count": 100, "sum": 5000.0,
                "buckets": [[10.0, 40], [50.0, 90], [100.0, 99]]}
        assert histogram_quantile(snap, 0.5) == pytest.approx(50.0)
        assert histogram_quantile(snap, 0.95) == pytest.approx(100.0)
        # Past the last bound: mean fallback keeps it finite.
        assert histogram_quantile(snap, 0.999) == pytest.approx(50.0)
        assert histogram_quantile({"count": 0, "buckets": []}, 0.5) is None


class TestQueueWait:
    def test_pop_records_wait_and_accumulates(self):
        from tony_tpu.conf.configuration import TonyConfiguration

        now = [1_000]
        reg = MetricsRegistry()
        q = JobQueue(registry=reg, clock_ms=lambda: now[0])
        job = SchedJob(job_id="j1", conf=TonyConfiguration(), app_dir="/x")
        q.submit(job)
        now[0] = 4_000
        popped = q.pop_next()
        assert popped is job
        assert job.queue_wait_total_ms == 3_000
        snap = reg.snapshot()["histograms"][QUEUE_WAIT_HISTOGRAM]
        assert snap["count"] == 1 and snap["sum"] == pytest.approx(3_000)
        # A requeue restarts the episode; the next pop adds only the
        # NEW wait.
        q.requeue(job)
        now[0] = 5_000
        q.pop_next()
        assert job.queue_wait_total_ms == 4_000
        assert reg.snapshot()["histograms"][QUEUE_WAIT_HISTOGRAM][
            "count"] == 2

    def test_preemption_and_kill_episodes_account_separately(self):
        from tony_tpu.conf.configuration import TonyConfiguration

        now = [1_000]
        reg = MetricsRegistry()
        q = JobQueue(registry=reg, clock_ms=lambda: now[0])
        job = SchedJob(job_id="j", conf=TonyConfiguration(), app_dir="/x")
        # Preemption-requeue episode: wait lands in the preempted
        # account, not queue latency.
        q.submit(job)
        job.requeued_by_preemption = True
        now[0] = 7_000
        q.pop_next()
        assert job.preempted_wait_total_ms == 6_000
        assert job.queue_wait_total_ms == 0
        # Kill-finalization pop: records nowhere (not a launch).
        q.requeue(job)
        job.kill_requested = True
        now[0] = 9_000
        q.pop_next()
        assert job.queue_wait_total_ms == 0
        assert job.preempted_wait_total_ms == 6_000
        # Histogram saw the preemption relaunch only.
        assert reg.snapshot()["histograms"][QUEUE_WAIT_HISTOGRAM][
            "count"] == 1

    def test_clamp_duration(self):
        from tony_tpu.observability.profiling import clamp_duration_ms

        assert clamp_duration_ms("abc") == 2000
        assert clamp_duration_ms(10**9) == 60_000
        assert clamp_duration_ms(None, default=500) == 500
        assert clamp_duration_ms(-5) == 1


class TestEventsCursorCount:
    def test_cursor_beyond_tail_reports_count(self):
        """The satellite fix: a consumer that outran the writer (or a
        coordinator that restarted with a shorter log) must be able to
        read the CURRENT count instead of conflating the empty suffix
        with 'no new events'."""
        events = obs_events.EventLog()
        for i in range(3):
            events.emit("job_submitted", idx=i)
        server = ObservabilityHttpServer(
            MetricsAggregator(), events=events, host="127.0.0.1"
        )
        server.serve_background()
        try:
            def get(path):
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{server.port}{path}", timeout=5
                ) as resp:
                    return json.loads(resp.read())

            tail = get("/api/events?cursor=10")
            assert tail["count"] == 3
            assert tail["cursor"] == 3
            assert tail["events"] == []
            ok = get("/api/events?cursor=1")
            assert ok["count"] == 3 and len(ok["events"]) == 2
        finally:
            server.stop()


class TestHeartbeatAge:
    def test_age_rendered_at_scrape_time(self):
        now = [100.0]
        agg = MetricsAggregator(clock=lambda: now[0])
        agg.ingest("worker:0", None)
        now[0] = 107.5
        text = agg.prometheus_text()
        assert (HEARTBEAT_AGE_GAUGE + '{task="worker:0"} 7.5') in text
        j = agg.to_json()
        assert j["heartbeat_age_s"]["worker:0"] == pytest.approx(7.5)


class TestProfileBrokerAndExecutor:
    def test_broker_delivers_once_and_fences_stale_results(self):
        broker = ProfileBroker(clock_ms=lambda: 1000)
        req = broker.start(["w:0", "w:1"], duration_ms=50)
        cmd = broker.command_for("w:0")
        assert cmd["profile"]["req_id"] == req
        assert broker.command_for("w:0") is None  # delivered once
        broker.record_result("w:0", {"req_id": "stale", "x": 1})
        assert broker.status()["tasks"]["w:0"]["state"] == "delivered"
        broker.record_result("w:0", {"req_id": req, "snapshot": {}})
        broker.record_result("w:1", {"req_id": req, "snapshot": {}})
        # w:1 never got the command but its result still lands.
        st = broker.status()
        assert st["done"] is True
        assert st["tasks"]["w:1"]["state"] == "captured"

    def test_failed_capture_reads_as_failed_not_success(self):
        broker = ProfileBroker(clock_ms=lambda: 1000)
        req = broker.start(["w:0"], duration_ms=10)
        assert broker.record_result(
            "w:0", {"req_id": req, "error": "capture failed"}
        ) == "failed"
        st = broker.status()
        # Terminal (the CLI's poll must not hang) but NOT a success.
        assert st["done"] is True
        assert st["tasks"]["w:0"]["state"] == "failed"
        # Stale results report None so no lifecycle event gets emitted.
        assert broker.record_result(
            "w:0", {"req_id": "bogus", "snapshot": {}}
        ) is None

    def test_same_millisecond_requests_get_distinct_ids(self):
        broker = ProfileBroker(clock_ms=lambda: 1000)
        a = broker.start(["w:0"], duration_ms=10)
        b = broker.start(["w:0"], duration_ms=10)
        assert a != b  # executors dedupe by req_id; a reuse would wedge

    def test_run_capture_writes_artifact_and_snapshot(self, tmp_path,
                                                      monkeypatch):
        # Pin the host path: whether jax happens to be loaded in the
        # test process must not change what this test exercises.
        from tony_tpu.observability import profiling as prof_mod

        monkeypatch.setattr(prof_mod, "_loaded_jax", lambda: None)
        summary = run_capture("req1", 1, tmp_path, "worker:0",
                              session_id="2")
        assert summary["snapshot"]["source"] in ("jax", "host")
        artifacts = find_profiles(tmp_path)
        assert len(artifacts) == 1
        assert artifacts[0].name == summary["artifact"]
        doc = json.loads(artifacts[0].read_text())
        assert doc["req_id"] == "req1" and doc["task"] == "worker:0"

    def test_executor_profiler_dedupes_and_one_shots(self, tmp_path,
                                                     monkeypatch):
        from tony_tpu.observability import profiling as prof_mod

        monkeypatch.setattr(prof_mod, "_loaded_jax", lambda: None)
        prof = ExecutorProfiler("w:0", tmp_path)
        cmd = {"profile": {"req_id": "r1", "duration_ms": 1}}
        assert prof.handle_command(cmd) is True
        assert prof.handle_command(cmd) is False  # deduped
        deadline = time.monotonic() + 10
        result = None
        while result is None and time.monotonic() < deadline:
            result = prof.take_result()
            time.sleep(0.02)
        assert result is not None and result["req_id"] == "r1"
        assert prof.take_result() is None  # one-shot
        assert prof.handle_command({"not": "a command"}) is False

    def test_capture_snapshot_always_returns_evidence(self):
        snap = capture_snapshot()
        assert snap["source"] in ("jax", "host")
        if snap["source"] == "host":
            assert snap["host"]["max_rss_bytes"] > 0


class TestGoodputFollow:
    def test_follow_tails_events_through_a_local_ledger(self, tmp_path,
                                                        capsys):
        """`tony goodput --follow` cursor-polls /api/events and folds
        the suffixes through a local ledger (restart detection rides
        the reply's `count` field)."""
        from tony_tpu.client import cli

        events = obs_events.EventLog()
        for e in _clean_run_events()[:6]:
            events.emit(e["kind"], **{k: v for k, v in e.items()
                                      if k not in ("kind", "ts_ms")})
        server = ObservabilityHttpServer(
            MetricsAggregator(), events=events, host="127.0.0.1"
        )
        server.serve_background()
        app_id = "application_follow_1"
        app_dir = tmp_path / "staging" / app_id
        app_dir.mkdir(parents=True)
        (app_dir / "coordinator.http").write_text(
            f"127.0.0.1:{server.port}\n"
        )
        try:
            rc = cli.main([
                "goodput", app_id, "--follow", "--max-polls", "2",
                "--poll-interval", "0.05",
                "--staging-location", str(tmp_path / "staging"),
            ])
        finally:
            server.stop()
        assert rc == 0
        out = capsys.readouterr().out
        assert "phase=" in out and "wall=" in out


# ---------------------------------------------------------------------------
# Mini-cluster e2e
# ---------------------------------------------------------------------------
@pytest.fixture()
def cluster(tmp_path):
    with MiniTonyCluster(tmp_path) as c:
        yield c


def _start_job(cluster, conf, app_id):
    app_dir = cluster.staging_dir / app_id
    app_dir.mkdir(parents=True)
    conf.write_final(app_dir / constants.TONY_FINAL_CONF)
    coordinator = TonyCoordinator(
        conf, app_dir, app_id=app_id,
        backend=LocalProcessBackend(app_dir / "logs"),
    )
    result = []
    t = threading.Thread(
        target=lambda: result.append(coordinator.run()), daemon=True
    )
    cluster._live.append(coordinator)
    t.start()
    return coordinator, t, result, app_dir


def test_goodput_and_profile_e2e(cluster, capsys):
    """THE acceptance run: a jax-free 2-worker job that reports train
    steps. Live: /api/goodput serves an exclusive breakdown and a
    `tony profile` round trip returns a device-memory snapshot for
    every task. Terminal: the breakdown sums to the job's wall clock
    within 1% with nonzero `productive`, the capture artifacts persist
    to history, and the CLI reads all of it back."""
    from tony_tpu.client import cli

    conf = cluster.base_conf()
    conf.set(keys.K_EXECUTES, str(FIXTURES / "report_metrics.py"))
    conf.set(keys.K_PYTHON_BINARY, sys.executable)
    conf.set(keys.instances_key("worker"), 2)
    conf.set(keys.instances_key("ps"), 0)
    conf.set(keys.K_TASK_HEARTBEAT_INTERVAL_MS, 150)
    conf.set(keys.K_SHELL_ENV, "LINGER_S=4.5")

    app_id = "application_mini_goodput1"
    coordinator, t, result, app_dir = _start_job(cluster, conf, app_id)
    try:
        deadline = time.monotonic() + 60
        addr_file = app_dir / "coordinator.http"
        while not addr_file.is_file() and time.monotonic() < deadline:
            time.sleep(0.05)
        assert addr_file.is_file(), "coordinator.http never advertised"
        addr = addr_file.read_text().strip()

        def get(path):
            with urllib.request.urlopen(
                f"http://{addr}{path}", timeout=5
            ) as resp:
                return json.loads(resp.read())

        # -- live goodput: wait for the steps to register as productive
        live = None
        while time.monotonic() < deadline:
            try:
                live = get("/api/goodput")
            except OSError:
                time.sleep(0.1)
                continue
            if (live.get("categories") or {}).get("productive", 0) > 0:
                break
            time.sleep(0.1)
        assert live and live["categories"]["productive"] > 0, live
        assert sum(live["categories"].values()) == pytest.approx(
            live["wall_s"], rel=1e-6
        )
        # The /metrics scrape serves the gauges, refreshed at scrape.
        text = urllib.request.urlopen(
            f"http://{addr}/metrics", timeout=5
        ).read().decode()
        assert GOODPUT_SECONDS_GAUGE + '{category="productive"}' in text
        assert GOODPUT_RATIO_GAUGE in text
        assert HEARTBEAT_AGE_GAUGE + '{task="worker:0"}' in text

        # -- live profile round trip via the CLI ---------------------------
        rc = cli.main([
            "profile", app_id,
            "--staging-location", str(cluster.staging_dir),
            "--history-location", str(cluster.history_dir),
            "--duration-ms", "30", "--timeout", "30",
        ])
        assert rc == 0
        status = get("/api/profile")
        assert status["done"] is True
        assert set(status["tasks"]) == {"worker:0", "worker:1"}
        for task, entry in status["tasks"].items():
            assert entry["state"] == "captured", (task, entry)
            snap = entry["summary"]["snapshot"]
            assert snap["source"] in ("jax", "host")
        # The cross-host arm path: POST /api/profile is loopback-only,
        # so remote CLIs fall back to the client-role RPC — prove it
        # arms a fresh request against the live coordinator.
        armed = cli._rpc_request_profile(
            cluster.staging_dir, app_id, None, 25
        )
        assert isinstance(armed, dict) and armed.get("req_id"), armed
    finally:
        t.join(timeout=120)
    assert result and result[0] is SessionStatus.SUCCEEDED, (
        coordinator.session.diagnostics if coordinator.session else "no run"
    )

    # -- terminal record: exclusive, sums to wall within 1% ---------------
    final = json.loads((app_dir / "final-status.json").read_text())
    g = final["goodput"]
    wall_s = final["stats"]["wall_ms"] / 1000.0
    assert sum(g["categories"].values()) == pytest.approx(
        wall_s, rel=0.01
    )
    assert g["categories"]["productive"] > 0
    assert g["chips"] == 2  # one chip-equivalent per local task
    assert g["ratio"] > 0
    # The timeline carries the throttled progress marker + the capture
    # round trip, so a replay attributes productive time too.
    kinds = [e["kind"] for e in obs_events.parse_jsonl(
        (app_dir / "events.jsonl").read_text()
    )]
    assert "train_progress" in kinds
    assert "profile_requested" in kinds
    assert "profile_captured" in kinds

    # -- history: profile artifacts persisted beside the trace (two
    # capture requests ran — the CLI round trip and the RPC re-arm —
    # each leaving one artifact per task) ---------------------------------
    persisted = list(cluster.history_dir.rglob("profile-*.json"))
    assert len(persisted) >= 2, persisted
    assert any("worker_0" in p.name for p in persisted)
    assert any("worker_1" in p.name for p in persisted)

    # -- CLI reads the terminal record (and the persisted captures) -------
    capsys.readouterr()
    rc = cli.main([
        "goodput", app_id,
        "--staging-location", str(cluster.staging_dir),
        "--history-location", str(cluster.history_dir),
    ])
    assert rc == 0
    out = capsys.readouterr().out
    assert "productive" in out and "goodput ratio" in out
    rc = cli.main([
        "profile", app_id,
        "--staging-location", str(cluster.staging_dir),
        "--history-location", str(cluster.history_dir),
    ])
    assert rc == 0
    assert "persisted captures" in capsys.readouterr().out

    # -- events replay through the ledger agrees on the big picture ------
    replay = GoodputLedger.from_events(
        obs_events.parse_jsonl((app_dir / "events.jsonl").read_text()),
        chips=2,
    ).to_json()
    assert replay["categories"]["productive"] > 0


def test_chaos_retry_reports_wasted_by_failure(cluster):
    """A post-rendezvous failure that retries must surface its
    recomputation debt: session 1's work lands in `wasted_by_failure`,
    and the categories still sum to wall clock."""
    conf = cluster.base_conf()
    conf.set(keys.K_EXECUTES, str(FIXTURES / "exit_1.py"))
    conf.set(keys.K_PYTHON_BINARY, sys.executable)
    conf.set(keys.instances_key("worker"), 1)
    conf.set(keys.instances_key("ps"), 0)
    conf.set(keys.K_AM_RETRY_COUNT, 1)
    conf.set(keys.K_AM_RETRY_BACKOFF_BASE_MS, 100)
    conf.set(keys.K_AM_RETRY_BACKOFF_MAX_MS, 300)
    status, coord = cluster.run_job(conf, timeout_s=90)
    assert status is SessionStatus.FAILED
    final = json.loads((coord.app_dir / "final-status.json").read_text())
    g = final["goodput"]
    assert g["categories"]["wasted_by_failure"] > 0, g
    assert sum(g["categories"].values()) == pytest.approx(
        final["stats"]["wall_ms"] / 1000.0, rel=0.01
    )
    assert g["categories"]["productive"] == 0.0


def test_goodput_disabled_by_conf(tmp_path):
    """tony.goodput.enabled=false: no ledger is constructed, so no
    events feed it and stop() writes no `goodput` record."""
    from tony_tpu.conf.configuration import TonyConfiguration

    conf = TonyConfiguration()
    conf.set(keys.K_GOODPUT_ENABLED, False)
    coordinator = TonyCoordinator(conf, tmp_path / "app")
    assert coordinator.goodput is None
    assert coordinator.goodput_json() == {"enabled": False}


def test_kill_queued_job_behind_full_pool(cluster):
    """The queue-wait admission gate must not strand a kill-requested
    queued job behind a full pool (it needs no slice, only
    finalization) — and the doomed job must never drive a preemption."""
    from tony_tpu.scheduler.queue import JobState
    from tony_tpu.scheduler.service import PREEMPTIONS_COUNTER

    sconf = cluster.base_conf()
    sconf.set(keys.K_SCHED_TICK_MS, 50)
    sconf.set(keys.K_SCHED_MAX_SLICES, 1)
    daemon = cluster.start_scheduler(sconf, serve_http=False)

    def job_conf(fixture, env=""):
        conf = cluster.base_conf()
        conf.set(keys.K_EXECUTES, str(FIXTURES / fixture))
        conf.set(keys.K_PYTHON_BINARY, sys.executable)
        conf.set(keys.instances_key("worker"), 1)
        conf.set(keys.instances_key("ps"), 0)
        if env:
            conf.set(keys.K_SHELL_ENV, env)
        return conf

    j1 = daemon.submit(job_conf("report_metrics.py", "LINGER_S=3.0"))
    deadline = time.monotonic() + 30
    while daemon.job(j1).state is not JobState.RUNNING:
        time.sleep(0.05)
        assert time.monotonic() < deadline
    # Pool full: j2 queues with kill_requested set — the state a kill
    # landing during a failed-provision requeue leaves behind. The next
    # tick must pop it past the headroom gate and finalize KILLED, and
    # its (high) priority must never drive a preemption of j1.
    j2 = daemon.submit(job_conf("exit_0.py"))
    job2 = daemon.job(j2)
    job2.priority = 99
    job2.kill_requested = True
    daemon._wake.set()
    assert daemon.wait_job(j2, 10) is JobState.KILLED
    assert daemon.job(j1).state is JobState.RUNNING
    assert daemon.registry.counter(PREEMPTIONS_COUNTER).value == 0
    assert daemon.wait_job(j1, 60) is JobState.SUCCEEDED


@pytest.mark.slow
def test_scheduler_fleet_goodput_and_warm_compile(cluster):
    """The scheduler half of the satellite acceptance: two jobs through
    a 1-slice pool — the daemon aggregates per-tenant chip-seconds,
    serves queue-wait p50/p95, and the WARM job's ledger shows a
    near-zero compile window (steps arrive immediately on the reused
    slice)."""
    from tony_tpu.scheduler.queue import JobState

    sconf = cluster.base_conf()
    sconf.set(keys.K_SCHED_TICK_MS, 50)
    sconf.set(keys.K_SCHED_MAX_SLICES, 1)
    daemon = cluster.start_scheduler(sconf, serve_http=False)

    def job_conf(tenant):
        conf = cluster.base_conf()
        conf.set(keys.K_EXECUTES, str(FIXTURES / "report_metrics.py"))
        conf.set(keys.K_PYTHON_BINARY, sys.executable)
        conf.set(keys.instances_key("worker"), 1)
        conf.set(keys.instances_key("ps"), 0)
        conf.set(keys.K_TASK_HEARTBEAT_INTERVAL_MS, 100)
        conf.set(keys.K_SHELL_ENV, "LINGER_S=2.0")
        conf.set(keys.K_SCHED_TENANT, tenant)
        return conf

    j1 = daemon.submit(job_conf("alice"))
    assert daemon.wait_job(j1, 90) is JobState.SUCCEEDED
    j2 = daemon.submit(job_conf("bob"))
    assert daemon.wait_job(j2, 90) is JobState.SUCCEEDED

    state = daemon.state_json()
    # Queue-wait stats: one observation per launch.
    assert state["queue_wait_ms"]["count"] == 2
    assert state["queue_wait_ms"]["p50_ms"] is not None
    # Per-tenant accounting: both tenants earned productive chip-time.
    tenants = state["goodput"]["tenants"]
    assert tenants["alice"]["productive"] > 0
    assert tenants["bob"]["productive"] > 0
    assert state["goodput"]["ratio"] > 0
    # Fleet gauges on the daemon registry.
    snap = daemon.registry.snapshot()["gauges"]
    assert snap[GOODPUT_SECONDS_GAUGE + '{category="productive"}'] > 0

    # The warm job's own ledger: compile ≈ 0 — the first step advance
    # closes the compile window, so it holds only the user-process
    # cold start (a couple of seconds on a loaded CI box), never the
    # bulk of the run. A broken progress feed would leave the WHOLE
    # post-rendezvous span in `compile` — that is what this catches.
    job2 = daemon.job(j2)
    final2 = json.loads(
        (Path(job2.app_dir) / "final-status.json").read_text()
    )
    g2 = final2["goodput"]
    assert g2["categories"]["productive"] > 0
    wall2 = sum(g2["categories"].values())
    assert g2["categories"]["compile"] < 0.5 * wall2, g2
    assert g2["categories"]["compile"] < 5.0, g2


class TestCommittedCheckpointMark:
    """ISSUE 14 satellite: the ledger's checkpoint mark advances only on
    COMMITTED steps (marker written), never on snapshot starts — with
    the async checkpoint pipeline a save's snapshot can be well ahead of
    its commit, and an in-flight save must not shrink
    ``wasted_by_failure`` it hasn't earned."""

    @staticmethod
    def _snap(ts_ms, gauges=None, counters=None, histograms=None):
        return {
            "ts_ms": ts_ms,
            "gauges": gauges or {},
            "counters": counters or {},
            "histograms": histograms or {},
        }

    def test_commit_hook_fires_on_min_across_tasks(self):
        agg = MetricsAggregator()
        fired = []
        agg.on_checkpoint_commit = fired.append
        agg.ingest("w0", self._snap(1, {"tony_ckpt_committed_step": 10}))
        assert fired == [10]
        # A later-joining reporter at a lower value does not retract.
        agg.ingest("w1", self._snap(2, {"tony_ckpt_committed_step": 5}))
        assert fired == [10]
        # The MIN must advance: one task alone at 20 is not a global
        # commit while the other sits at 5.
        agg.ingest("w0", self._snap(3, {"tony_ckpt_committed_step": 20}))
        assert fired == [10]
        agg.ingest("w1", self._snap(4, {"tony_ckpt_committed_step": 20}))
        assert fired == [10, 20]

    def test_snapshot_activity_never_fires_the_commit_hook(self):
        """A save IN FLIGHT is visible as snapshot-histogram and
        queue-depth telemetry — none of it may advance the mark."""
        agg = MetricsAggregator()
        fired = []
        agg.on_checkpoint_commit = fired.append
        agg.ingest("w0", self._snap(
            1,
            gauges={"tony_ckpt_queue_depth": 2.0},
            histograms={"tony_ckpt_snapshot_ms": {
                "count": 7, "sum": 70.0, "buckets": [[10.0, 7]],
            }},
        ))
        assert fired == []

    def test_inflight_save_does_not_shrink_wasted_by_failure(self):
        """Regression: 10s of productive work, a save whose snapshot
        started but whose marker never landed, then a session failure —
        ALL 10s are recomputation debt. The committed variant (the
        checkpoint_progress the commit hook emits) bounds the debt to
        the post-commit seconds."""
        def run(commit_at_ms):
            led = GoodputLedger(chips=1)
            led.seed_start(0)
            led.observe_event({"ts_ms": 0, "kind": "session_started"})
            led.observe_event({"ts_ms": 0, "kind": "task_registered",
                               "task": "w0"})
            led.observe_event({"ts_ms": 0, "kind": "rendezvous_released"})
            led.observe_steps("w0", 1, ts_ms=0)
            led.observe_steps("w0", 50, ts_ms=5_000)
            if commit_at_ms is not None:
                # What _on_checkpoint_commit stamps when the MARKER is
                # seen (heartbeat gauge min-advance, or the migration
                # wait's probe).
                led.observe_event({"ts_ms": commit_at_ms,
                                   "kind": "checkpoint_progress",
                                   "best_step": 50})
            led.observe_steps("w0", 100, ts_ms=10_000)
            led.observe_event({"ts_ms": 10_000, "kind": "session_finished",
                               "session": 1, "status": "FAILED"})
            led.finalize(10_000)
            return led.to_json()["categories"]

        no_commit = run(None)
        assert no_commit["wasted_by_failure"] == pytest.approx(10.0)
        assert no_commit["productive"] == pytest.approx(0.0)
        committed = run(5_000)
        assert committed["wasted_by_failure"] == pytest.approx(5.0)
        assert committed["productive"] == pytest.approx(5.0)

    def test_commit_watermark_survives_session_reset(self):
        """reset_tasks (session retry) drops per-task values but keeps
        the fired watermark: a restarted gang re-reporting the step it
        resumed FROM must not re-fire the hook (and re-clear debt that
        new work is accruing against)."""
        agg = MetricsAggregator()
        fired = []
        agg.on_checkpoint_commit = fired.append
        agg.ingest("w0", self._snap(1, {"tony_ckpt_committed_step": 10}))
        agg.reset_tasks()
        agg.ingest("w0", self._snap(2, {"tony_ckpt_committed_step": 10}))
        assert fired == [10]
        agg.ingest("w0", self._snap(3, {"tony_ckpt_committed_step": 11}))
        assert fired == [10, 11]
