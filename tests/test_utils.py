"""Utils tests — mirrors the reference's pure-unit tier
(tony-core/src/test/.../TestUtils.java:26-131): memory parse, polling, zip,
container-request parsing, TF_CONFIG construction, pytorch spec parse."""

import json
import zipfile

import pytest

from tony_tpu import utils
from tony_tpu.conf import TonyConfiguration, keys


def test_parse_memory_string_mb():
    assert utils.parse_memory_string_mb("2g") == 2048
    assert utils.parse_memory_string_mb("512m") == 512
    assert utils.parse_memory_string_mb("1024") == 1024
    assert utils.parse_memory_string_mb(256) == 256
    assert utils.parse_memory_string_mb("1.5g") == 1536
    with pytest.raises(ValueError):
        utils.parse_memory_string_mb("")


def test_poll_success_and_timeout():
    calls = []

    def eventually():
        calls.append(1)
        return len(calls) >= 3

    assert utils.poll(eventually, interval_s=0.01, timeout_s=5) is True
    assert utils.poll(lambda: False, interval_s=0.01, timeout_s=0.05) is False


def test_poll_till_non_null():
    calls = []

    def fn():
        calls.append(1)
        return "spec" if len(calls) >= 2 else None

    assert utils.poll_till_non_null(fn, interval_s=0.01, timeout_s=5) == "spec"


def test_zip_roundtrip(tmp_path):
    src = tmp_path / "src"
    (src / "sub").mkdir(parents=True)
    (src / "a.py").write_text("print('a')")
    (src / "sub" / "b.txt").write_text("b")
    z = tmp_path / "tony.zip"
    utils.zip_dir(src, z)
    assert sorted(zipfile.ZipFile(z).namelist()) == ["a.py", "sub/b.txt"]
    out = tmp_path / "out"
    utils.unzip(z, out)
    assert (out / "sub" / "b.txt").read_text() == "b"


def test_build_user_command_docker_passthrough():
    """tony.application.docker.* wraps the user process in the image with
    host networking (so the injected rendezvous env still works)."""
    conf = TonyConfiguration()
    conf.set(keys.K_EXECUTES, "train.py")
    conf.set(keys.K_DOCKER_ENABLED, True)
    conf.set(keys.K_DOCKER_IMAGE, "ghcr.io/acme/trainer:1")
    cmd, venv = utils.build_user_command(conf, "t")
    assert cmd.startswith("docker run --rm --network=host")
    assert "ghcr.io/acme/trainer:1 python train.py" in cmd
    assert venv is None

    conf.set(keys.K_DOCKER_IMAGE, "")
    with pytest.raises(ValueError, match="docker.image"):
        utils.build_user_command(conf, "t")

    # venv + docker rejected BEFORE extraction (the nonexistent zip would
    # raise OSError if the order were wrong, and nothing may leak on disk)
    conf.set(keys.K_DOCKER_IMAGE, "img")
    conf.set(keys.K_PYTHON_VENV, "does-not-exist.zip")
    with pytest.raises(ValueError, match="mutually exclusive"):
        utils.build_user_command(conf, "t")


def test_parse_container_requests():
    """Analogue of TestUtils.testParseContainerRequests (reference :55-78):
    arbitrary job types via the instances regex, with resources."""
    conf = TonyConfiguration()
    conf.set(keys.instances_key("worker"), 3)
    conf.set(keys.tpus_key("worker"), 8)
    conf.set(keys.memory_key("worker"), "4g")
    conf.set(keys.instances_key("evaluator"), 1)
    conf.set(keys.resources_key("evaluator"), "disk=10g,fpga=1")
    conf.set(keys.instances_key("ps"), 0)  # explicit zero → dropped
    reqs = utils.parse_container_requests(conf)
    assert set(reqs) == {"worker", "evaluator"}
    w = reqs["worker"]
    assert (w.num_instances, w.memory_mb, w.tpus) == (3, 4096, 8)
    assert reqs["evaluator"].extra_resources == {"disk": "10g", "fpga": "1"}
    # one distinct priority per job type (YARN-7631 workaround kept)
    assert len({r.priority for r in reqs.values()}) == len(reqs)


def test_construct_tf_config():
    spec = {"worker": ["h1:1", "h2:2"], "ps": ["h3:3"]}
    cfg = json.loads(utils.construct_tf_config(spec, "worker", 1))
    assert cfg["cluster"]["ps"] == ["h3:3"]
    assert cfg["task"] == {"type": "worker", "index": 1}


def test_parse_cluster_spec_for_pytorch():
    spec = {"worker": ["h1:29500", "h2:2"]}
    assert utils.parse_cluster_spec_for_pytorch(spec) == "tcp://h1:29500"
    with pytest.raises(ValueError):
        utils.parse_cluster_spec_for_pytorch({"ps": ["h:1"]})


def test_flatten_cluster_spec_chief_is_process_zero():
    # process 0 must be the chief job's task 0, because jax.distributed
    # starts the coordinator on process 0 and we advertise the chief's
    # address as coordinator_address — even when the chief job type sorts
    # after others alphabetically (e.g. ps < worker).
    spec = {"ps": ["p0"], "worker": ["w0", "w1"]}
    flat = utils.flatten_cluster_spec(spec, chief_name="worker")
    assert flat[0] == ("worker", 0, "w0")
    assert utils.coordinator_address_from_spec(spec, "worker") == "w0"
    assert flat == [("worker", 0, "w0"), ("worker", 1, "w1"), ("ps", 0, "p0")]


def test_execute_shell_env_and_timeout(tmp_path):
    marker = tmp_path / "env.txt"
    rc = utils.execute_shell(f'echo -n "$MY_VAR" > {marker}', extra_env={"MY_VAR": "x1"})
    assert rc == 0 and marker.read_text() == "x1"
    assert utils.execute_shell("exit 3") == 3
    assert utils.execute_shell("sleep 5", timeout_ms=200) == 124


def test_parse_key_values():
    assert utils.parse_key_values("a=1, b=2,,c=") == {"a": "1", "b": "2", "c": ""}
    assert utils.parse_key_values("") == {}
