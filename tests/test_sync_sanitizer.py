"""Runtime sync sanitizer: seeded inversions MUST be detected, clean
discipline MUST stay silent (no false positives on RLock re-entry,
ordered nesting, same-name instances, or Condition.wait), and the
violation report must be flight-recorder compatible.

Every test seeds a PRIVATE ``SyncTracker`` — the suite-wide gate in
conftest reads only the process-global tracker, so deliberate
inversions here can never fail another test.
"""

import json
import threading
import time

from tony_tpu.analysis import sync_sanitizer as ss


def tracked(tracker, *names, rlock=False):
    make = ss.make_rlock if rlock else ss.make_lock
    return [make(n, tracker_=tracker) for n in names]


class TestSeededDetection:
    def test_single_thread_inversion_detected(self):
        t = ss.SyncTracker()
        a, b = tracked(t, "a", "b")
        with a:
            with b:
                pass
        with b:
            with a:
                pass
        inv = t.violations(ss.LOCK_ORDER_INVERSION)
        assert len(inv) == 1
        assert inv[0]["locks"] == ["a", "b"]
        # Both acquisition stacks ride the violation.
        assert inv[0]["stack"] and inv[0]["reverse_stack"]
        assert "deadlock" in inv[0]["detail"]

    def test_cross_thread_inversion_detected(self):
        t = ss.SyncTracker()
        a, b = tracked(t, "cross.a", "cross.b")

        def forward():
            with a:
                with b:
                    pass

        th = threading.Thread(target=forward, daemon=True)
        th.start()
        th.join(timeout=5)
        with b:
            with a:
                pass
        assert len(t.violations(ss.LOCK_ORDER_INVERSION)) == 1

    def test_inversion_reported_once_per_pair(self):
        t = ss.SyncTracker()
        a, b = tracked(t, "a", "b")
        for _ in range(5):
            with a:
                with b:
                    pass
            with b:
                with a:
                    pass
        assert len(t.violations(ss.LOCK_ORDER_INVERSION)) == 1

    def test_long_hold_detected(self):
        t = ss.SyncTracker(long_hold_ms=10)
        (h,) = tracked(t, "slow")
        with h:
            time.sleep(0.05)
        holds = t.violations(ss.LONG_HOLD)
        assert len(holds) == 1
        assert holds[0]["locks"] == ["slow"]
        # Hold-time hygiene is telemetry, never an inversion.
        assert t.violations(ss.LOCK_ORDER_INVERSION) == []


class TestCleanRuns:
    def test_ordered_nesting_silent(self):
        t = ss.SyncTracker()
        a, b, c = tracked(t, "a", "b", "c")
        for _ in range(3):
            with a:
                with b:
                    with c:
                        pass
        assert t.violations() == []
        assert ("a", "b") in t.edges() and ("b", "c") in t.edges()

    def test_rlock_reentry_silent(self):
        t = ss.SyncTracker()
        (r,) = tracked(t, "r", rlock=True)
        (x,) = tracked(t, "x")
        with r:
            with r:
                with x:
                    pass
            with r:
                pass
        assert t.violations() == []

    def test_same_name_instances_no_edge(self):
        """Two EventLog-style instances share one graph node: nesting
        one inside the other is not an ordering fact."""
        t = ss.SyncTracker()
        log1 = ss.make_lock("events.EventLog._lock", tracker_=t)
        log2 = ss.make_lock("events.EventLog._lock", tracker_=t)
        with log1:
            with log2:
                pass
        with log2:
            with log1:
                pass
        assert t.violations() == []
        assert t.edges() == []

    def test_condition_wait_window_not_held(self):
        """A waiter parked in Condition.wait holds nothing — locks
        taken by other threads meanwhile add no edges against it, and
        notify/wakeup round-trips stay silent."""
        t = ss.SyncTracker()
        cond = ss.make_condition("c", tracker_=t)
        (other,) = tracked(t, "other")
        woke = threading.Event()

        def waiter():
            with cond:
                cond.wait(timeout=5)
                woke.set()

        th = threading.Thread(target=waiter, daemon=True)
        th.start()
        time.sleep(0.05)
        with other:
            pass
        with cond:
            cond.notify_all()
        th.join(timeout=5)
        assert woke.is_set()
        assert t.violations() == []

    def test_condition_on_rlock_reentrant_wait(self):
        """The scheduler idiom: Condition(RLock) waited on while the
        lock is held re-entrantly — _release_save must drop the whole
        hold and _acquire_restore must put it back."""
        t = ss.SyncTracker()
        lock = ss.make_rlock("svc", tracker_=t)
        cond = ss.make_condition("svc.cond", lock=lock, tracker_=t)
        done = threading.Event()

        def waiter():
            with lock:
                with cond:   # re-entrant: cond IS lock
                    cond.wait(timeout=5)
            done.set()

        th = threading.Thread(target=waiter, daemon=True)
        th.start()
        time.sleep(0.05)
        with cond:
            cond.notify_all()
        th.join(timeout=5)
        assert done.is_set()
        assert t.violations() == []


class TestReporting:
    def test_mark_and_violations_since(self):
        t = ss.SyncTracker()
        a, b = tracked(t, "a", "b")
        with a:
            with b:
                pass
        mark = t.mark()
        assert t.violations_since(mark) == []
        with b:
            with a:
                pass
        since = t.violations_since(mark, kind=ss.LOCK_ORDER_INVERSION)
        assert len(since) == 1

    def test_report_and_flight_compatible_dump(self, tmp_path):
        t = ss.SyncTracker(long_hold_ms=5)
        a, b = tracked(t, "a", "b")
        with a:
            with b:
                time.sleep(0.02)
        with b:
            with a:
                pass
        doc = t.report()
        assert doc["proc"] == "sync-sanitizer"
        assert set(doc["locks"]) == {"a", "b"}
        assert ["a", "b"] in doc["edges"]
        kinds = {e["kind"] for e in doc["events"]}
        assert kinds == {ss.LOCK_ORDER_INVERSION, ss.LONG_HOLD}

        path = t.dump(tmp_path, reason="test")
        assert path is not None
        on_disk = json.loads((tmp_path / path.split("/")[-1]).read_text())
        assert on_disk["reason"] == "test"
        # The blackbox reader treats it as any other flight dump.
        from tony_tpu.observability.flight import load_blackboxes

        boxes = load_blackboxes(tmp_path)
        assert len(boxes) == 1
        (name,) = boxes
        assert name.startswith("blackbox-sync-sanitizer-")

    def test_reset(self):
        t = ss.SyncTracker()
        a, b = tracked(t, "a", "b")
        with a:
            with b:
                pass
        with b:
            with a:
                pass
        t.reset()
        assert t.violations() == [] and t.edges() == []


class TestFactories:
    def test_disabled_returns_plain_primitives(self, monkeypatch):
        monkeypatch.setenv(ss.ENV_FLAG, "0")
        assert not ss.enabled()
        assert not isinstance(ss.make_lock("x"), ss.SanitizedLock)
        assert not isinstance(ss.make_rlock("x"), ss.SanitizedLock)
        cond = ss.make_condition("x")
        assert isinstance(cond, threading.Condition)
        with cond:
            pass

    def test_enabled_wraps(self, monkeypatch):
        monkeypatch.setenv(ss.ENV_FLAG, "1")
        lock = ss.make_lock("tests.enabled_wraps")
        assert isinstance(lock, ss.SanitizedLock)
        assert lock.acquire(timeout=1)
        assert lock.locked()
        lock.release()
        assert not lock.locked()

    def test_try_acquire_failure_not_tracked(self):
        t = ss.SyncTracker()
        (a,) = tracked(t, "a")
        a.acquire()
        got = []

        def contender():
            got.append(a.acquire(blocking=False))

        th = threading.Thread(target=contender, daemon=True)
        th.start()
        th.join(timeout=5)
        assert got == [False]
        a.release()
        assert t.violations() == []
