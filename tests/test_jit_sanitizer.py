"""Runtime jit sanitizer: cold/hit/retrace classification, the retrace
budget (report and strict modes), the step-region transfer guard, the
flight-recorder-compatible dump, and the ``instrument_jit`` accounting
split (compile-cache counters vs ``tony_retraces_total`` can never
double-count one dispatch).

Every test seeds a PRIVATE ``JitTracker`` for deliberate violations —
the suite-wide conftest gate reads only the process-global tracker."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from tony_tpu.analysis import jit_sanitizer
from tony_tpu.analysis.jit_sanitizer import (
    GUARDED_TRANSFER,
    RETRACE,
    JitTracker,
    RetraceBudgetExceeded,
    note_dispatch,
    step_region,
)


class TestTrackerClassification:
    def test_cold_then_hit_then_retrace(self):
        tr = JitTracker(budget=4)
        assert tr.note_call("k", "sig-a")[0] == "cold"
        assert tr.note_call("k", "sig-a")[0] == "hit"
        status, count, over = tr.note_call("k", "sig-b")
        assert (status, count, over) == ("retrace", 1, False)
        # Caught once per signature: replaying the retraced signature is
        # a cache hit, not a second violation.
        assert tr.note_call("k", "sig-b")[0] == "hit"
        assert tr.retraces("k") == 1
        assert len(tr.violations(RETRACE)) == 1

    def test_keys_are_independent(self):
        tr = JitTracker(budget=4)
        tr.note_call("a", "s1")
        assert tr.note_call("b", "s1")[0] == "cold"
        assert tr.retraces() == 0

    def test_budget_flags_over(self):
        tr = JitTracker(budget=2)
        tr.note_call("k", "s0")
        overs = [tr.note_call("k", f"s{i}")[2] for i in (1, 2, 3)]
        assert overs == [False, False, True]
        violations = tr.violations(RETRACE)
        assert [v["over_budget"] for v in violations] == overs
        assert all(v["stack"] for v in violations)

    def test_mark_and_violations_since(self):
        tr = JitTracker(budget=4)
        tr.note_call("k", "s0")
        tr.note_call("k", "s1")
        mark = tr.mark()
        assert tr.violations_since(mark) == []
        tr.note_call("k", "s2")
        since = tr.violations_since(mark)
        assert len(since) == 1 and since[0]["signature"] == "s2"


class TestNoteDispatch:
    def test_retrace_counts_metric_only_on_retrace(self):
        from tony_tpu import observability

        counter = observability.default_registry().counter(
            jit_sanitizer.RETRACES_COUNTER
        )
        tr = JitTracker(budget=4)
        base = counter.value
        assert note_dispatch("nd-key", "s0", tracker_=tr) == "cold"
        assert counter.value == base
        assert note_dispatch("nd-key", "s0", tracker_=tr) == "hit"
        assert counter.value == base
        assert note_dispatch("nd-key", "s1", tracker_=tr) == "retrace"
        assert counter.value == base + 1

    def test_strict_raises_past_budget(self, monkeypatch):
        monkeypatch.setenv(jit_sanitizer.ENV_FLAG, "strict")
        tr = JitTracker(budget=1)
        note_dispatch("strict-key", "s0", tracker_=tr)
        note_dispatch("strict-key", "s1", tracker_=tr)  # within budget
        with pytest.raises(RetraceBudgetExceeded, match="strict-key"):
            note_dispatch("strict-key", "s2", tracker_=tr)

    def test_report_mode_never_raises(self, monkeypatch):
        monkeypatch.setenv(jit_sanitizer.ENV_FLAG, "1")
        tr = JitTracker(budget=1)
        for i in range(5):
            note_dispatch("report-key", f"s{i}", tracker_=tr)
        assert tr.retraces("report-key") == 4


class TestStepRegion:
    def test_implicit_transfer_raises_and_records_stack(self):
        """The guard exception is recorded with a stack and re-raised.
        On the CPU backend arrays are host-resident, so jax's
        device-to-host guard never fires — the violation is seeded with
        the exact exception shape the guard raises on an accelerator."""
        tr = JitTracker()
        with pytest.raises(RuntimeError, match="[Tt]ransfer"):
            with step_region("guarded-key", tracker_=tr):
                raise RuntimeError(
                    "Disallowed device-to-host transfer: aval=f32[4]"
                )
        transfers = tr.violations(GUARDED_TRANSFER)
        assert len(transfers) == 1
        assert transfers[0]["key"] == "guarded-key"
        assert transfers[0]["stack"], "violation must carry a stack"
        assert tr.transfers() == 1

    def test_explicit_device_get_is_the_annotated_fence(self):
        tr = JitTracker()
        x = jnp.arange(4)
        with step_region("fence-key", tracker_=tr):
            host = np.asarray(jax.device_get(x))
        assert host.tolist() == [0, 1, 2, 3]
        assert tr.violations(GUARDED_TRANSFER) == []

    def test_disabled_is_a_noop(self, monkeypatch):
        monkeypatch.setenv(jit_sanitizer.ENV_FLAG, "0")
        tr = JitTracker()
        x = jnp.arange(3)
        with step_region("off-key", tracker_=tr):
            assert np.asarray(x).shape == (3,)  # no guard armed
        assert tr.violations() == []


class TestDump:
    def test_flight_recorder_compatible_envelope(self, tmp_path):
        import os

        from tony_tpu.observability import flight

        tr = JitTracker(budget=1)
        tr.note_call("dump-key", "s0")
        tr.note_call("dump-key", "s1")
        tr.note_transfer("disallowed device-to-host transfer", key="dump-key")
        path = tr.dump(tmp_path, reason="unit-test")
        assert path is not None
        assert path.endswith(f"blackbox-jit-sanitizer-{os.getpid()}.json")
        docs = flight.load_blackboxes(tmp_path)
        assert len(docs) == 1
        doc = next(iter(docs.values()))
        assert doc["proc"] == "jit-sanitizer"
        assert doc["reason"] == "unit-test"
        assert doc["retraces"] == {"dump-key": 1}
        assert doc["transfers"] == 1
        kinds = sorted(e["kind"] for e in doc["events"])
        assert kinds == [GUARDED_TRANSFER, RETRACE]
        # The flight-reader envelope fields the postmortem tooling walks.
        assert doc["reports"] == [] and doc["rpcs"] == []


class TestInstrumentJitAccounting:
    def test_cold_hit_retrace_never_double_count(self, tmp_path):
        """One dispatch lands in exactly one accounting bucket: the cold
        compile in ``tony_compile_cache_misses_total``, a retrace in
        ``tony_retraces_total`` — never both."""
        from tony_tpu import observability
        from tony_tpu.parallel import plan as plan_lib

        reg = observability.default_registry()
        misses = reg.counter("tony_compile_cache_misses_total")
        hits = reg.counter("tony_compile_cache_hits_total")
        retraces = reg.counter(jit_sanitizer.RETRACES_COUNTER)

        fn = plan_lib.instrument_jit(
            jax.jit(lambda x: x * 2), "acct-test-key",
            cache=plan_lib.CompileCache(str(tmp_path)),
        )
        m0, h0, r0 = misses.value, hits.value, retraces.value

        fn(jnp.zeros((4,)))          # cold: compile-cache miss only
        assert (misses.value, retraces.value) == (m0 + 1, r0)
        fn(jnp.ones((4,)))           # same shape/dtype: pure cache hit
        assert (misses.value, hits.value, retraces.value) == (
            m0 + 1, h0, r0
        )
        fn(jnp.zeros((8,)))          # new shape: retrace, NOT a miss
        assert (misses.value, retraces.value) == (m0 + 1, r0 + 1)
        assert jit_sanitizer.tracker().retraces("acct-test-key") == 1
