"""TONY-T concurrency-discipline lint: each rule against its bad/good
fixture pair, waiver syntax, docs drift, and the pass's own plumbing
(held-context propagation, the ``_locked``-helper exemption)."""

from pathlib import Path

from tony_tpu.analysis.concurrency import (
    ALL_RULES,
    RULE_BLOCKING,
    RULE_CHECK_ACT,
    RULE_DAEMON,
    RULE_JOIN,
    RULE_ORDER,
    RULE_UNGUARDED,
    check_concurrency,
    check_rule_docs,
)

REPO = Path(__file__).resolve().parents[1]
FIX = REPO / "tests" / "fixtures" / "lint"


def rule_ids(findings):
    return sorted({f.rule_id for f in findings})


def run(name):
    return check_concurrency([FIX / name])


class TestLockOrder:
    def test_cycle_detected(self):
        findings = [f for f in run("t001_bad.py") if f.rule_id == RULE_ORDER]
        assert findings, "lock-order cycle not detected"
        messages = " | ".join(f.message for f in findings)
        assert "cycle" in messages
        # The self-deadlock special case: helper re-acquiring the
        # non-reentrant lock its caller holds.
        assert "re-acquire" in messages or "re-acquired" in messages

    def test_consistent_order_and_rlock_reentry_clean(self):
        assert [f for f in run("t001_good.py")
                if f.rule_id == RULE_ORDER] == []


class TestBlockingUnderLock:
    def test_direct_and_transitive_blocking_flagged(self):
        findings = [f for f in run("t002_bad.py")
                    if f.rule_id == RULE_BLOCKING]
        # write_text under the lock, sleep under the lock, and the
        # sleep reached through the _slow() helper.
        assert len(findings) == 3
        joined = " | ".join(f.message for f in findings)
        assert "write_text" in joined
        assert "time.sleep" in joined
        assert "_slow" in joined

    def test_snapshot_then_write_outside_clean(self):
        assert [f for f in run("t002_good.py")
                if f.rule_id == RULE_BLOCKING] == []

    def test_with_item_expression_scanned(self, tmp_path):
        """``with open(...)`` nested inside a lock's with-block: the
        context expression itself is a blocking call under the lock."""
        (tmp_path / "w.py").write_text(
            "import threading\n\n\n"
            "class S:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n\n"
            "    def f(self, path, data):\n"
            "        with self._lock:\n"
            "            with open(path, 'w') as fh:\n"
            "                fh.write(data)\n"
        )
        findings = check_concurrency([tmp_path])
        assert [f.rule_id for f in findings] == [RULE_BLOCKING]
        assert "open" in findings[0].message


class TestSharedState:
    def test_two_entrypoints_unguarded_flagged(self):
        findings = [f for f in run("t003_bad.py")
                    if f.rule_id == RULE_UNGUARDED]
        assert len(findings) == 1
        assert "self.count" in findings[0].message
        assert "Worker._drain" in findings[0].message
        assert "Worker._run" in findings[0].message

    def test_common_lock_clean(self):
        assert [f for f in run("t003_good.py")
                if f.rule_id == RULE_UNGUARDED] == []


class TestCheckThenAct:
    def test_bare_test_and_set_flagged(self):
        findings = [f for f in run("t004_bad.py")
                    if f.rule_id == RULE_CHECK_ACT]
        assert len(findings) == 1
        assert "_value" in findings[0].message

    def test_locked_test_and_set_clean(self):
        assert [f for f in run("t004_good.py")
                if f.rule_id == RULE_CHECK_ACT] == []

    def test_locked_helper_idiom_exempt(self, tmp_path):
        """A helper whose every call site holds the lock runs in the
        caller's critical section — no TONY-T004."""
        (tmp_path / "helper.py").write_text(
            "import threading\n\n\n"
            "class S:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._v = None\n\n"
            "    def api(self):\n"
            "        with self._lock:\n"
            "            self._ensure_locked()\n\n"
            "    def _ensure_locked(self):\n"
            "        if self._v is None:\n"
            "            self._v = object()\n"
        )
        assert check_concurrency([tmp_path]) == []


class TestHygiene:
    def test_non_daemon_thread_flagged(self):
        findings = [f for f in run("t005_bad.py")
                    if f.rule_id == RULE_DAEMON]
        assert len(findings) == 1
        assert findings[0].severity == "warning"

    def test_daemon_kwarg_and_attr_clean(self):
        assert [f for f in run("t005_good.py")
                if f.rule_id == RULE_DAEMON] == []

    def test_join_without_timeout_flagged(self):
        findings = [f for f in run("t006_bad.py")
                    if f.rule_id == RULE_JOIN]
        assert len(findings) == 1

    def test_bounded_join_and_str_join_clean(self):
        assert [f for f in run("t006_good.py")
                if f.rule_id == RULE_JOIN] == []


class TestWaivers:
    def test_both_spellings_suppress(self):
        assert run("t_noqa_waived.py") == []

    def test_unrelated_rule_id_does_not_suppress(self, tmp_path):
        (tmp_path / "w.py").write_text(
            "import threading\nimport time\n\n\n"
            "class P:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n\n"
            "    def f(self):\n"
            "        with self._lock:\n"
            "            time.sleep(1)  # tony: noqa[T001]\n"
        )
        findings = check_concurrency([tmp_path])
        assert rule_ids(findings) == [RULE_BLOCKING]


class TestDocsDrift:
    def test_real_docs_have_every_rule(self):
        assert check_rule_docs(REPO / "docs" / "DEPLOY.md") == []

    def test_missing_rule_rows_flagged(self, tmp_path):
        partial = tmp_path / "DEPLOY.md"
        partial.write_text(" ".join(r for r in ALL_RULES
                                    if r != "TONY-T003"))
        findings = check_rule_docs(partial)
        assert len(findings) == 1
        assert findings[0].rule_id == "TONY-T003"
        # a missing doc flags every rule instead of crashing
        assert len(check_rule_docs(tmp_path / "nope.md")) == len(ALL_RULES)


class TestPlumbing:
    def test_condition_alias_shares_token(self, tmp_path):
        """``Condition(self._lock)`` is the SAME lock — nesting the
        condition inside the lock is re-entry, not an ordering edge."""
        (tmp_path / "cond.py").write_text(
            "import threading\n\n\n"
            "class S:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.RLock()\n"
            "        self._cond = threading.Condition(self._lock)\n\n"
            "    def f(self):\n"
            "        with self._lock:\n"
            "            with self._cond:\n"
            "                pass\n"
        )
        assert check_concurrency([tmp_path]) == []

    def test_sanitizer_factories_count_as_locks(self, tmp_path):
        """Locks created through sync_sanitizer factories carry the
        same static identity as stdlib ones."""
        (tmp_path / "f.py").write_text(
            "import time\n"
            "from tony_tpu.analysis import sync_sanitizer as _sync\n\n\n"
            "class S:\n"
            "    def __init__(self):\n"
            "        self._lock = _sync.make_lock('s')\n\n"
            "    def f(self):\n"
            "        with self._lock:\n"
            "            time.sleep(1)\n"
        )
        assert rule_ids(check_concurrency([tmp_path])) == [RULE_BLOCKING]

    def test_module_level_lock_tracked(self, tmp_path):
        (tmp_path / "m.py").write_text(
            "import threading\nimport time\n\n"
            "_mu = threading.Lock()\n\n\n"
            "def f():\n"
            "    with _mu:\n"
            "        time.sleep(1)\n"
        )
        assert rule_ids(check_concurrency([tmp_path])) == [RULE_BLOCKING]

    def test_unparseable_file_skipped(self, tmp_path):
        (tmp_path / "broken.py").write_text("def broken(:\n")
        assert check_concurrency([tmp_path]) == []
