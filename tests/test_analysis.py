"""Preflight static-analysis tests: the config check, the AST script
lint (against the hazard fixtures in tests/fixtures/lint/), the protocol
drift check, and the submit-path gate (tony.preflight.mode=strict must
refuse a typo'd submission before anything is staged)."""

from __future__ import annotations

import sys
from pathlib import Path

import pytest

from tony_tpu import constants
from tony_tpu.analysis import ERROR, WARNING, run_preflight
from tony_tpu.analysis.config_check import check_config
from tony_tpu.analysis.findings import Finding, format_findings, has_errors
from tony_tpu.analysis.protocol_check import check_protocol
from tony_tpu.analysis.script_lint import lint_script, lint_source
from tony_tpu.conf import keys
from tony_tpu.conf.configuration import TonyConfiguration

REPO = Path(__file__).resolve().parents[1]
LINT_FIXTURES = Path(__file__).parent / "fixtures" / "lint"
EXAMPLES = REPO / "examples"


# ---------------------------------------------------------------------------
# Script lint: every rule fires on its bad fixture at the right line and
# stays silent on the clean twin.
# ---------------------------------------------------------------------------
RULE_FIXTURES = [
    ("TONY-S101", "s101", 7, {}),
    ("TONY-S102", "s102", 8, {}),
    ("TONY-S103", "s103", 9, {}),
    ("TONY-S104", "s104", 8, {}),
    ("TONY-S105", "s105", 7, {}),
    ("TONY-S106", "s106", 4, {"multi_process": True}),
    ("TONY-S107", "s107", 6, {}),
    ("TONY-S108", "s108", 6, {}),
]


class TestScriptLint:
    @pytest.mark.parametrize(
        "rule_id,stem,line,ctx", RULE_FIXTURES,
        ids=[r[0] for r in RULE_FIXTURES],
    )
    def test_bad_fixture_flagged_at_line(self, rule_id, stem, line, ctx):
        findings = lint_script(str(LINT_FIXTURES / f"{stem}_bad.py"), **ctx)
        hits = [f for f in findings if f.rule_id == rule_id]
        assert hits, (
            f"{rule_id} did not fire on its fixture; got "
            f"{[f.rule_id for f in findings]}"
        )
        assert hits[0].line == line, format_findings(hits)

    @pytest.mark.parametrize(
        "rule_id,stem,line,ctx", RULE_FIXTURES,
        ids=[r[0] for r in RULE_FIXTURES],
    )
    def test_good_twin_clean(self, rule_id, stem, line, ctx):
        findings = lint_script(str(LINT_FIXTURES / f"{stem}_good.py"), **ctx)
        assert not [f for f in findings if f.rule_id == rule_id], (
            format_findings(findings)
        )

    def test_noqa_suppression(self):
        findings = lint_script(str(LINT_FIXTURES / "noqa_suppressed.py"))
        lines = sorted(f.line for f in findings
                       if f.rule_id == "TONY-S101")
        # line 8: suppressed by id; line 9: bare noqa; line 10: suppresses
        # a DIFFERENT rule id, so S101 must still fire there.
        assert lines == [10], format_findings(findings)

    def test_s103_skips_non_literal_mesh_axes(self):
        """A mesh whose axis names live in a variable recovers no literal
        axes — the rule must stay silent, not flag every PartitionSpec."""
        src = (
            "import jax\n"
            "from jax.sharding import Mesh, PartitionSpec\n"
            'AXES = ("data", "model")\n'
            "mesh = Mesh(jax.devices(), AXES)\n"
            'spec = PartitionSpec("data")\n'
        )
        findings = lint_source(src, "x.py")
        assert not [f for f in findings if f.rule_id == "TONY-S103"], (
            format_findings(findings)
        )

    def test_s107_set_does_not_sanction_order(self):
        """set() iteration order is hash-randomized per process — wrapping
        a glob in set() must still be flagged."""
        src = (
            "import glob\n"
            'for f in set(glob.glob("x/*.txt")):\n'
            "    pass\n"
        )
        findings = lint_source(src, "x.py")
        assert [f for f in findings if f.rule_id == "TONY-S107"]

    def test_entry_point_deduped_by_realpath(self):
        """The config's entry script already present in the explicit path
        list under a different spelling must not be linted twice."""
        bad = LINT_FIXTURES / "s101_bad.py"
        alias = f"{LINT_FIXTURES}/./s101_bad.py"
        conf = TonyConfiguration()
        conf.set(keys.K_EXECUTES, str(bad))
        findings = run_preflight(conf, [alias])
        assert len([f for f in findings if f.rule_id == "TONY-S101"]) == 1

    def test_syntax_error_reported_not_raised(self):
        findings = lint_source("def broken(:\n", "x.py")
        assert [f.rule_id for f in findings] == ["TONY-S100"]
        assert findings[0].severity == ERROR

    def test_single_process_skips_missing_init(self):
        findings = lint_script(
            str(LINT_FIXTURES / "s106_bad.py"), multi_process=False
        )
        assert not [f for f in findings if f.rule_id == "TONY-S106"]

    def test_examples_are_lint_clean(self):
        """Self-dogfooding: every shipped example passes its own lint."""
        scripts = sorted(EXAMPLES.glob("*.py"))
        assert len(scripts) == 6
        for script in scripts:
            findings = lint_script(str(script))
            assert not findings, (
                f"{script.name}:\n{format_findings(findings)}"
            )

    def test_lint_cli_on_examples_exits_zero(self, capsys):
        from tony_tpu.client.cli import lint

        assert lint([str(EXAMPLES)]) == 0
        out = capsys.readouterr().out
        assert "6 script(s), 0 error(s), 0 warning(s)" in out


# ---------------------------------------------------------------------------
# Config check
# ---------------------------------------------------------------------------
class TestConfigCheck:
    def _conf(self, **props):
        conf = TonyConfiguration()
        for k, v in props.items():
            conf.set(k, v)
        return conf

    def test_default_conf_is_clean(self):
        assert check_config(TonyConfiguration()) == []

    def test_unknown_key_suggests_static(self):
        conf = self._conf(**{"tony.aplication.framework": "jax"})
        (f,) = [x for x in check_config(conf) if x.rule_id == "TONY-C001"]
        assert f.severity == ERROR
        assert "tony.application.framework" in f.suggestion

    def test_unknown_key_suggests_dynamic_family(self):
        conf = self._conf(**{"tony.worker.instanses": 2})
        (f,) = [x for x in check_config(conf) if x.rule_id == "TONY-C001"]
        assert "tony.worker.instances" in f.suggestion

    def test_job_type_typo_warned(self):
        conf = self._conf(**{"tony.wroker.instances": 2})
        hits = [x for x in check_config(conf) if x.rule_id == "TONY-C009"]
        assert hits and "tony.worker.instances" in hits[0].suggestion

    def test_bad_bool_and_int(self):
        conf = self._conf(**{
            keys.K_SECURITY_ENABLED: "maybe",
            keys.K_TASK_HEARTBEAT_INTERVAL_MS: "soon",
        })
        ids = [x.rule_id for x in check_config(conf)]
        assert ids.count("TONY-C002") == 2

    def test_io_knobs_must_be_at_least_one(self):
        """tony.io.* pipeline knobs reject 0 (the generic int rule only
        floors at 0): a zero-depth prefetch or zero-record chunk is a
        stalled pipeline, not a configuration."""
        conf = self._conf(**{
            keys.K_IO_PREFETCH_DEPTH: 0,
            keys.K_IO_READ_WORKERS: 0,
            keys.K_IO_CHUNK_RECORDS: 0,
        })
        ids = [x.rule_id for x in check_config(conf)]
        assert ids.count("TONY-C002") == 3
        clean = self._conf(**{
            keys.K_IO_PREFETCH_DEPTH: 4,
            keys.K_IO_READ_WORKERS: 8,
            keys.K_IO_CHUNK_RECORDS: 128,
        })
        assert check_config(clean) == []

    def test_bad_port_range_and_enum(self):
        conf = self._conf(**{
            keys.K_AM_RPC_PORT_RANGE: "9000",
            keys.K_FRAMEWORK: "caffe",
        })
        ids = [x.rule_id for x in check_config(conf)]
        assert ids.count("TONY-C002") == 2

    def test_bad_memory_string(self):
        conf = self._conf(**{keys.memory_key("worker"): "lots"})
        assert any(
            x.rule_id == "TONY-C002" and "memory" in x.message
            for x in check_config(conf)
        )

    def test_chief_without_instances(self):
        conf = self._conf(**{
            keys.K_CHIEF_NAME: "chief",
            keys.instances_key("worker"): 2,
        })
        assert any(x.rule_id == "TONY-C003" for x in check_config(conf))

    def test_chief_index_out_of_range(self):
        conf = self._conf(**{
            keys.K_CHIEF_INDEX: "5",
            keys.instances_key("worker"): 2,
        })
        assert any(x.rule_id == "TONY-C003" for x in check_config(conf))

    def test_notebook_multi_instance(self):
        conf = self._conf(**{keys.instances_key("notebook"): 2})
        assert any(x.rule_id == "TONY-C004" for x in check_config(conf))

    def test_tpus_under_non_jax_runtime(self):
        conf = self._conf(**{
            keys.K_FRAMEWORK: "pytorch",
            keys.tpus_key("worker"): 8,
        })
        hits = [x for x in check_config(conf) if x.rule_id == "TONY-C005"]
        assert hits and hits[0].severity == WARNING

    def test_illegal_slice_shape(self):
        conf = self._conf(**{
            keys.instances_key("worker"): 3,
            # 3 hosts x 9 chips: single-host v5e shapes top out at 8
            # chips and no multi-host shape tiles 3 hosts.
            keys.tpus_key("worker"): 9,
        })
        assert any(x.rule_id == "TONY-C006" for x in check_config(conf))

    def test_illegal_topology_without_tpu_ask(self):
        conf = self._conf(**{keys.K_TPU_TOPOLOGY: "v5e-3"})
        assert any(x.rule_id == "TONY-C006" for x in check_config(conf))

    def test_legal_tpu_ask_is_clean(self):
        conf = self._conf(**{
            keys.instances_key("worker"): 4,
            keys.tpus_key("worker"): 4,
            keys.K_TPU_TOPOLOGY: "v5e-16",
        })
        assert check_config(conf) == []


# ---------------------------------------------------------------------------
# Protocol drift
# ---------------------------------------------------------------------------
class TestProtocolCheck:
    def test_live_tables_clean(self):
        assert check_protocol() == []

    def test_detects_missing_acl_and_extra_acl(self):
        from tony_tpu.rpc.protocol import RPC_METHODS

        acl = {m: frozenset({"client"}) for m in RPC_METHODS}
        acl.pop("finish_application")
        acl["shutdown_everything"] = frozenset({"client"})
        ids = [f.rule_id for f in check_protocol(acl=acl)]
        assert ids.count("TONY-P002") == 2

    def test_detects_registry_method_without_handler(self):
        from tony_tpu import security
        from tony_tpu.rpc.protocol import RPC_METHODS

        registry = dict(RPC_METHODS)
        registry["new_call"] = ("arg",)
        acl = dict(security.METHOD_ACL)
        acl["new_call"] = frozenset({"client"})
        findings = check_protocol(rpc_methods=registry, acl=acl)
        ids = {f.rule_id for f in findings}
        # missing on the interface, missing client stub, missing handler
        assert {"TONY-P001", "TONY-P003", "TONY-P004"} <= ids

    def test_detects_stub_arg_drift(self):
        from tony_tpu.rpc.protocol import RPC_METHODS

        registry = dict(RPC_METHODS)
        registry["task_executor_heartbeat"] = ("task_id", "extra")
        findings = check_protocol(rpc_methods=registry)
        assert any(
            f.rule_id == "TONY-P003" and "task_executor_heartbeat"
            in f.message
            for f in findings
        )

    def test_empty_role_set_flagged(self):
        from tony_tpu import security

        acl = dict(security.METHOD_ACL)
        acl["finish_application"] = frozenset()
        assert any(
            f.rule_id == "TONY-P002" and "no role" in f.message
            for f in check_protocol(acl=acl)
        )

    def test_optional_arg_must_be_trailing(self):
        """An optional arg that is not the trailing registry arg could
        never be omitted wire-side — flagged as P001."""
        from tony_tpu.rpc.protocol import RPC_METHODS

        optional = {"register_worker_spec": ("worker",)}  # 'worker' leads
        findings = check_protocol(optional_args=optional)
        assert any(
            f.rule_id == "TONY-P001" and "trailing" in f.message
            for f in findings
        )

    def test_optional_arg_without_default_flagged(self):
        """Declaring an arg optional in the registry but required on the
        interface/stub silently breaks omission — both sides flagged."""
        optional = {"register_worker_spec": ("spec",)}
        findings = check_protocol(optional_args=optional)
        assert any(
            f.rule_id == "TONY-P001" and "no default" in f.message
            for f in findings
        )
        assert any(
            f.rule_id == "TONY-P003" and "no default" in f.message
            for f in findings
        )

    def test_optional_entry_for_unknown_method_flagged(self):
        findings = check_protocol(optional_args={"no_such_call": ("x",)})
        assert any(
            f.rule_id == "TONY-P001" and "no_such_call" in f.message
            for f in findings
        )


# ---------------------------------------------------------------------------
# Metric-name lint (TONY-M001)
# ---------------------------------------------------------------------------
class TestMetricsLint:
    def _lint(self, tmp_path, source: str):
        from tony_tpu.analysis.metrics_lint import check_metric_names

        script = tmp_path / "script.py"
        script.write_text(source)
        return check_metric_names([script])

    def test_clean_registrations(self, tmp_path):
        findings = self._lint(tmp_path, (
            "reg.counter('requests_total')\n"
            "reg.gauge('loss')\n"
            "reg.histogram('step_seconds')\n"
            "observability.report(step=1, loss=0.5, step_time_ms=4.0)\n"
        ))
        assert findings == []

    def test_bad_names_flagged(self, tmp_path):
        findings = self._lint(tmp_path, (
            "reg.counter('CamelCase')\n"        # not snake_case
            "reg.counter('requests')\n"         # counter without _total
            "reg.gauge('step_time')\n"          # time without unit
            "reg.gauge('memory_used')\n"        # size without unit
        ))
        assert len(findings) == 4
        assert all(f.rule_id == "TONY-M001" for f in findings)
        assert findings[0].line == 1 and findings[3].line == 4

    def test_report_kwargs_linted_step_exempt(self, tmp_path):
        findings = self._lint(tmp_path, (
            "observability.report(step=3, queue_latency=1.0)\n"
        ))
        assert len(findings) == 1 and "queue_latency" in findings[0].message

    def test_kind_conflict_across_files(self, tmp_path):
        from tony_tpu.analysis.metrics_lint import check_metric_names

        (tmp_path / "a.py").write_text("reg.counter('widgets_total')\n")
        (tmp_path / "b.py").write_text("reg.gauge('widgets_total')\n")
        findings = check_metric_names([tmp_path])
        assert len(findings) == 1
        assert "one name, one kind" in findings[0].message

    def test_unparseable_file_skipped(self, tmp_path):
        findings = self._lint(tmp_path, "def broken(:\n")
        assert findings == []

    def test_repo_tree_is_clean(self):
        """The lint this PR ships must hold for the metrics this PR
        ships (also enforced via lint_self in tier-1)."""
        from tony_tpu.analysis.metrics_lint import check_metric_names

        findings = check_metric_names([
            REPO / "tony_tpu", REPO / "examples", REPO / "tools",
            REPO / "bench.py",
        ])
        assert findings == [], "\n".join(f.render() for f in findings)

    def test_observability_docs_catalogues(self, tmp_path):
        """TONY-M002 extension: every step-anatomy phase label value and
        every health detector name needs a DEPLOY.md row — an
        incomplete doc is flagged per missing value, the real doc is
        clean."""
        from tony_tpu.analysis.metrics_lint import check_observability_docs
        from tony_tpu.observability.health import DETECTORS
        from tony_tpu.observability.stepstats import PHASES

        assert check_observability_docs(REPO / "docs" / "DEPLOY.md") == []
        # a doc missing one phase and one detector gets exactly 2 flags
        partial = tmp_path / "DEPLOY.md"
        partial.write_text(" ".join(
            [f"`{p}`" for p in PHASES if p != "collective"]
            + [f"`{d}`" for d in DETECTORS if d != "comms_bound"]
        ))
        findings = check_observability_docs(partial)
        assert len(findings) == 2
        assert all(f.rule_id == "TONY-M002" for f in findings)
        assert {"collective", "comms_bound"} == {
            f.message.split("'")[1] for f in findings
        }
        # a missing doc flags everything instead of crashing
        missing = check_observability_docs(tmp_path / "nope.md")
        assert len(missing) == len(PHASES) + len(DETECTORS)


# ---------------------------------------------------------------------------
# Repo self-drift (tools/lint_self.py) — drift fails tier-1.
# ---------------------------------------------------------------------------
def test_repo_self_drift_clean(capsys):
    sys.path.insert(0, str(REPO / "tools"))
    try:
        import lint_self
    finally:
        sys.path.pop(0)
    assert lint_self.main() == 0


# ---------------------------------------------------------------------------
# Submission gate
# ---------------------------------------------------------------------------
class TestSubmissionGate:
    def test_strict_blocks_typo_and_suggests(self, tmp_path, caplog):
        """Acceptance: strict mode refuses a submission whose config has a
        typo'd key, names the intended key, and stages NOTHING."""
        from tony_tpu.client.client import TonyClient

        client = TonyClient().init([
            "--executes", str(LINT_FIXTURES / "s101_good.py"),
            "--conf", f"{keys.K_PREFLIGHT_MODE}=strict",
            "--conf", "tony.worker.instanses=2",
            "--conf", f"{keys.K_STAGING_LOCATION}={tmp_path}/staging",
        ])
        import logging

        with caplog.at_level(logging.ERROR):
            rc = client.run()
        assert rc == 1
        assert client.coordinator_proc is None, "nothing may launch"
        assert not (tmp_path / "staging").exists(), "nothing may stage"
        joined = "\n".join(r.message for r in caplog.records)
        assert "tony.worker.instanses" in joined
        assert "tony.worker.instances" in joined  # the suggestion

    def test_strict_passes_clean_config_preflight(self, tmp_path):
        from tony_tpu.analysis.preflight import run_for_submission

        conf = TonyConfiguration()
        conf.set(keys.K_PREFLIGHT_MODE, "strict")
        # The default conf schedules worker+ps (2 processes), so the
        # clean script must be one that initializes the distributed
        # runtime (s106_good) — s101_good would trip TONY-S106.
        conf.set(keys.K_EXECUTES, str(LINT_FIXTURES / "s106_good.py"))
        assert run_for_submission(conf) == 0

    def test_warn_mode_reports_but_proceeds(self, caplog):
        from tony_tpu.analysis.preflight import run_for_submission

        conf = TonyConfiguration()
        conf.set("tony.worker.instanses", 2)
        import logging

        with caplog.at_level(logging.WARNING):
            assert run_for_submission(conf) == 0
        assert any("TONY-C001" in r.message for r in caplog.records)

    def test_off_mode_runs_nothing(self, caplog):
        from tony_tpu.analysis.preflight import run_for_submission

        conf = TonyConfiguration()
        conf.set(keys.K_PREFLIGHT_MODE, "off")
        conf.set("tony.worker.instanses", 2)
        assert run_for_submission(conf) == 0
        assert not any("TONY-C001" in r.message for r in caplog.records)

    def test_unknown_mode_degrades_to_warn(self):
        from tony_tpu.analysis.preflight import preflight_mode

        conf = TonyConfiguration()
        conf.set(keys.K_PREFLIGHT_MODE, "paranoid")
        assert preflight_mode(conf) == constants.PREFLIGHT_WARN

    def test_strict_blocks_hazardous_script(self, tmp_path):
        """The script-lint layer participates in the strict gate: an
        error-severity hazard in the entry point refuses submission."""
        from tony_tpu.analysis.preflight import run_for_submission

        conf = TonyConfiguration()
        conf.set(keys.K_PREFLIGHT_MODE, "strict")
        conf.set(keys.K_EXECUTES, str(LINT_FIXTURES / "s101_bad.py"))
        assert run_for_submission(conf) == 1

    def test_preflight_resolves_entry_point_from_conf(self):
        conf = TonyConfiguration()
        conf.set(keys.K_EXECUTES, str(LINT_FIXTURES / "s108_bad.py"))
        findings = run_preflight(conf)
        assert any(f.rule_id == "TONY-S108" for f in findings)

    def test_multi_worker_conf_drives_s106(self):
        conf = TonyConfiguration()
        conf.set(keys.instances_key("worker"), 2)
        conf.set(keys.K_EXECUTES, str(LINT_FIXTURES / "s106_bad.py"))
        findings = run_preflight(conf)
        assert any(f.rule_id == "TONY-S106" for f in findings)


def test_findings_format_orders_errors_first():
    fs = [
        Finding("TONY-S107", WARNING, "w", file="a.py", line=3),
        Finding("TONY-S101", ERROR, "e", file="b.py", line=9),
    ]
    text = format_findings(fs)
    assert text.index("TONY-S101") < text.index("TONY-S107")
    assert has_errors(fs)
