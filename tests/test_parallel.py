"""Parallel-layer tests on the virtual 8-device CPU mesh (conftest.py sets
--xla_force_host_platform_device_count=8 — the mini-cluster idea applied to
devices, per SURVEY.md §4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from tony_tpu.parallel import (
    MeshSpec,
    all_gather_tp,
    all_to_all_ep,
    build_mesh,
    logical_sharding,
    logical_spec,
    pipeline_apply,
    pmean_gradients,
    reduce_scatter_tp,
    ring_attention,
    ring_halo_exchange,
)
from tony_tpu.parallel.mesh import round_up_to_slice


def reference_attention(q, k, v, causal=True):
    scale = q.shape[-1] ** -0.5
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        t = q.shape[1]
        mask = np.tril(np.ones((t, t), dtype=bool))
        s = jnp.where(mask[None, None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


class TestMeshSpec:
    def test_auto_factors_all_devices(self):
        spec = MeshSpec.auto(8)
        assert spec.num_devices == 8

    def test_auto_respects_fixed_axes(self):
        spec = MeshSpec.auto(8, tp=4)
        assert spec.tp == 4 and spec.num_devices == 8

    def test_auto_with_fixed_dp_absorbs_leftover(self):
        # Leftover factor must land on an unset axis, not be dropped.
        spec = MeshSpec.auto(16, dp=1)
        assert spec.dp == 1 and spec.num_devices == 16
        spec = MeshSpec.auto(16, dp=2)
        assert spec.dp == 2 and spec.num_devices == 16

    def test_auto_all_axes_fixed_wrong_product(self):
        with pytest.raises(ValueError):
            MeshSpec.auto(16, dp=1, pp=1, ep=1, sp=2, tp=2)

    def test_auto_rejects_non_dividing(self):
        with pytest.raises(ValueError):
            MeshSpec.auto(8, tp=3)

    def test_validate_rejects_wrong_count(self):
        with pytest.raises(ValueError):
            MeshSpec(dp=4).validate(8)

    def test_build_mesh_has_five_axes(self):
        mesh = build_mesh()
        assert set(mesh.axis_names) == {"dp", "pp", "ep", "sp", "tp"}
        assert mesh.devices.size == 8

    def test_round_up_to_slice(self):
        assert round_up_to_slice(3) == 4
        assert round_up_to_slice(8) == 8
        assert round_up_to_slice(9) == 16
        with pytest.raises(ValueError):
            round_up_to_slice(10_000)


class TestLogicalSharding:
    def test_spec_mapping(self):
        assert logical_spec("batch", "seq", "embed") == P(("dp", "ep"), "sp", None)

    def test_unknown_role_raises(self):
        with pytest.raises(KeyError):
            logical_spec("batch", "head")  # typo for "heads"

    def test_sharding_places_array(self):
        mesh = build_mesh(MeshSpec(dp=2, sp=2, tp=2))
        x = jnp.zeros((8, 16, 4))
        sh = logical_sharding(mesh, "batch", "seq", None)
        y = jax.device_put(x, sh)
        assert y.sharding.spec == P(("dp", "ep"), "sp", None)


class TestRingAttention:
    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_reference(self, causal):
        mesh = build_mesh(MeshSpec(sp=4, tp=2))
        rng = np.random.default_rng(0)
        b, t, h, d = 2, 32, 4, 8
        q = jnp.asarray(rng.normal(size=(b, t, h, d)), dtype=jnp.float32)
        k = jnp.asarray(rng.normal(size=(b, t, h, d)), dtype=jnp.float32)
        v = jnp.asarray(rng.normal(size=(b, t, h, d)), dtype=jnp.float32)
        out = ring_attention(q, k, v, mesh, causal=causal)
        ref = reference_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    def test_blockwise_inner_loop_matches_at_odd_block(self):
        """block_k smaller than (and not dividing) the shard: the inner
        flash accumulation + padding must stay exact."""
        mesh = build_mesh(MeshSpec(sp=4, dp=2))
        rng = np.random.default_rng(2)
        b, t, h, d = 2, 48, 2, 8  # t_local = 12, block_k 5 -> pad 3
        q, k, v = (
            jnp.asarray(rng.normal(size=(b, t, h, d)), dtype=jnp.float32)
            for _ in range(3)
        )
        out = ring_attention(q, k, v, mesh, causal=True, block_k=5)
        ref = reference_attention(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    def test_long_sequence_bounded_memory(self):
        """t_local >= 1k (VERDICT r1 item 8): the per-shard kv scan runs
        block_k keys at a time, so the [Tlocal, Tlocal] score matrix is
        never materialized; correctness is cross-checked against dense
        attention at seq 2048 over sp=2."""
        mesh = build_mesh(MeshSpec(sp=2, dp=2, tp=2))
        rng = np.random.default_rng(3)
        b, t, h, d = 2, 2048, 2, 16  # t_local = 1024
        q, k, v = (
            jnp.asarray(rng.normal(size=(b, t, h, d)), dtype=jnp.float32)
            for _ in range(3)
        )
        out = ring_attention(q, k, v, mesh, causal=True, block_k=256)
        ref = reference_attention(q, k, v, causal=True)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=5e-5
        )

    def test_matches_flash_attention_path(self):
        """Ring and the ops-layer flash fallback implement the same math in
        different decompositions; pinning them to each other catches a fix
        applied to one but not the other (the two share no code)."""
        from tony_tpu.ops import flash_attention

        mesh = build_mesh(MeshSpec(sp=4, dp=2))
        rng = np.random.default_rng(5)
        b, t, h, d = 2, 64, 2, 8
        q, k, v = (
            jnp.asarray(rng.normal(size=(b, t, h, d)), dtype=jnp.float32)
            for _ in range(3)
        )
        ring = ring_attention(q, k, v, mesh, causal=True, block_k=7)
        flash = flash_attention(q, k, v, causal=True, block_k=16,
                                force_jax=True)
        np.testing.assert_allclose(
            np.asarray(ring), np.asarray(flash), atol=2e-5
        )

    def test_grad_flows_long_sequence(self):
        """Backward at t_local=1k: the remat'd double scan must train, not
        OOM on stacked score residuals."""
        mesh = build_mesh(MeshSpec(sp=2, dp=2, tp=2))
        rng = np.random.default_rng(6)
        q = jnp.asarray(rng.normal(size=(2, 2048, 2, 8)), dtype=jnp.float32)

        def loss(q):
            return ring_attention(q, q, q, mesh, block_k=256).sum()

        g = jax.grad(loss)(q)
        assert np.isfinite(np.asarray(g)).all()

    def test_grad_flows(self):
        mesh = build_mesh(MeshSpec(sp=2, dp=2, tp=2))
        rng = np.random.default_rng(1)
        q = jnp.asarray(rng.normal(size=(2, 8, 2, 4)), dtype=jnp.float32)

        def loss(q):
            return ring_attention(q, q, q, mesh).sum()

        g = jax.grad(loss)(q)
        assert np.isfinite(np.asarray(g)).all()

    @pytest.mark.parametrize("causal", [True, False])
    def test_kernel_path_matches_jax_path(self, causal):
        """The Pallas-in-ring path (kernel="interpret" on CPU) must match
        the independent blockwise-JAX ring — forward AND gradients. This is
        the cross-check that lets "auto" pick the kernel on TPU."""
        mesh = build_mesh(MeshSpec(sp=4, dp=2))
        rng = np.random.default_rng(7)
        b, t, h, d = 2, 64, 2, 8
        q, k, v = (
            jnp.asarray(rng.normal(size=(b, t, h, d)), dtype=jnp.float32)
            for _ in range(3)
        )
        out_k = ring_attention(q, k, v, mesh, causal=causal,
                               kernel="interpret")
        out_j = ring_attention(q, k, v, mesh, causal=causal, kernel="jax")
        np.testing.assert_allclose(
            np.asarray(out_k), np.asarray(out_j), atol=2e-5
        )
        ref = reference_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(
            np.asarray(out_k), np.asarray(ref), atol=2e-5
        )

        def loss(fn_kernel):
            def inner(q, k, v):
                w = ring_attention(q, k, v, mesh, causal=causal,
                                   kernel=fn_kernel)
                # Non-uniform weighting so lse gradients matter.
                return (w * jnp.arange(1, d + 1, dtype=w.dtype)).sum()
            return inner

        gk = jax.grad(loss("interpret"), argnums=(0, 1, 2))(q, k, v)
        gj = jax.grad(loss("jax"), argnums=(0, 1, 2))(q, k, v)
        for a, b_ in zip(gk, gj):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b_), atol=3e-4
            )


class TestMultiSlice:
    """Multi-slice (DCN-spanning) mesh: dp rows tile slice-by-slice so
    inner-axis collectives never cross the slice boundary — the VERDICT r2
    item 2 contract."""

    def test_dp_outermost_tiles_slices(self):
        devices = jax.devices()[:8]
        mesh = build_mesh(
            MeshSpec(dp=2, sp=2, tp=2), devices=devices, num_slices=2
        )
        arr = mesh.devices  # [dp, pp, ep, sp, tp]
        # dp row 0 == slice 0 (devices 0..3), row 1 == slice 1 (4..7).
        assert {d.id for d in arr[0].flat} == {d.id for d in devices[:4]}
        assert {d.id for d in arr[1].flat} == {d.id for d in devices[4:]}

    def test_auto_spec_pins_dp_to_slices(self):
        mesh = build_mesh(devices=jax.devices()[:8], num_slices=2)
        assert mesh.shape["dp"] == 2

    def test_inner_axis_across_slices_rejected(self):
        with pytest.raises(ValueError, match="dp.*divisible by"):
            build_mesh(MeshSpec(dp=1, tp=8), devices=jax.devices()[:8],
                       num_slices=2)
        with pytest.raises(ValueError, match="equal slices"):
            build_mesh(MeshSpec(dp=3, tp=2), devices=jax.devices()[:6],
                       num_slices=4)

    def test_two_slice_training_dp_across_dcn(self):
        """The dryrun-style 2-slice case: 2 x 4-device groups, full train
        step with dp crossing the "DCN" boundary and tp/sp inside each
        slice — finite, descending loss."""
        import numpy as np

        from tony_tpu.models import TransformerConfig, make_train_step

        cfg = TransformerConfig(
            vocab_size=128, d_model=32, n_layers=2, n_heads=2, head_dim=16,
            d_ff=64, max_seq=64, dtype="float32", remat=False,
        )
        mesh = build_mesh(
            MeshSpec(dp=2, sp=2, tp=2), devices=jax.devices()[:8],
            num_slices=2,
        )
        init_fn, step_fn = make_train_step(cfg, mesh, learning_rate=1e-2)
        tokens = jnp.asarray(
            np.random.default_rng(0).integers(0, 128, (4, 33)), jnp.int32
        )
        with jax.sharding.set_mesh(mesh):
            state = init_fn(jax.random.key(0))
            losses = []
            for _ in range(3):
                state, metrics = step_fn(state, tokens)
                losses.append(float(metrics["loss"]))
        assert all(np.isfinite(l) for l in losses)
        assert losses[-1] < losses[0]

    def test_build_job_mesh_reads_topology_env(self, monkeypatch):
        import json as _json

        import tony_tpu.runtime as rt
        from tony_tpu import constants

        monkeypatch.setenv(
            constants.TONY_SLICE_TOPOLOGY,
            _json.dumps({
                "accelerator_type": "v5litepod-4", "num_slices": 2,
                "hosts_per_slice": 1, "chips_per_slice": 4,
            }),
        )
        mesh = rt.build_job_mesh(devices=jax.devices()[:8])
        assert mesh.shape["dp"] == 2


class TestCollectives:
    def _run(self, mesh, fn, in_specs, out_specs, *args):
        return jax.shard_map(
            fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_vma=False
        )(*args)

    def test_pmean_gradients(self):
        mesh = build_mesh(MeshSpec(dp=4, ep=2))
        x = jnp.arange(8.0).reshape(8, 1)

        def body(g):
            return pmean_gradients({"g": g})["g"]

        out = self._run(mesh, body, (P(("dp", "ep")),), P(("dp", "ep")), x)
        np.testing.assert_allclose(np.asarray(out), np.full((8, 1), 3.5))

    def test_all_gather_then_reduce_scatter_roundtrip(self):
        mesh = build_mesh(MeshSpec(tp=8))
        x = jnp.arange(16.0).reshape(16, 1)

        def body(x):
            g = all_gather_tp(x, axis=0)          # [16,1] per shard
            return reduce_scatter_tp(g, axis=0)   # back to [2,1], ×8

        out = self._run(mesh, body, (P("tp"),), P("tp"), x)
        np.testing.assert_allclose(np.asarray(out), np.asarray(x) * 8)

    def test_all_to_all_ep(self):
        mesh = build_mesh(MeshSpec(ep=4, dp=2))
        # [tokens=4, experts=4]: shard tokens, transpose to shard experts.
        x = jnp.arange(16.0).reshape(4, 4)

        def body(x):
            return all_to_all_ep(x, split_axis=1, concat_axis=0)

        out = self._run(mesh, body, (P("ep"),), P(None, "ep"), x)
        np.testing.assert_allclose(np.asarray(out), np.asarray(x))

    def test_ring_halo_exchange(self):
        mesh = build_mesh(MeshSpec(sp=4, dp=2))
        x = jnp.arange(16.0).reshape(16, 1)

        def body(x):
            prev, nxt = ring_halo_exchange(x, "sp", halo=1)
            return jnp.concatenate([prev, nxt], axis=0)

        out = self._run(mesh, body, (P("sp"),), P("sp"), x)
        out = np.asarray(out).reshape(4, 2)
        # shard i holds rows [4i..4i+3]; prev-halo = last row of shard i-1,
        # next-halo = first row of shard i+1 (ring wrap).
        for i in range(4):
            assert out[i, 0] == (4 * ((i - 1) % 4) + 3)
            assert out[i, 1] == (4 * ((i + 1) % 4))


class TestPipeline:
    def test_matches_sequential(self):
        n_stages = 4
        mesh = build_mesh(MeshSpec(pp=n_stages, dp=2))
        rng = np.random.default_rng(2)
        dim = 8
        w = jnp.asarray(rng.normal(size=(n_stages, dim, dim)) * 0.3)
        b = jnp.asarray(rng.normal(size=(n_stages, dim)) * 0.1)
        params = {"w": w, "b": b}
        x = jnp.asarray(rng.normal(size=(16, dim)))

        def stage_fn(p, x):
            return jnp.tanh(x @ p["w"] + p["b"])

        out = pipeline_apply(
            stage_fn, params, x, mesh=mesh, num_microbatches=4
        )
        expected = x
        for i in range(n_stages):
            expected = jnp.tanh(expected @ w[i] + b[i])
        np.testing.assert_allclose(np.asarray(out), np.asarray(expected), atol=1e-5)

    def test_grad_through_pipeline(self):
        n_stages = 2
        mesh = build_mesh(MeshSpec(pp=n_stages, dp=2, tp=2))
        rng = np.random.default_rng(3)
        dim = 4
        params = {"w": jnp.asarray(rng.normal(size=(n_stages, dim, dim)) * 0.3)}
        x = jnp.asarray(rng.normal(size=(8, dim)))

        def stage_fn(p, x):
            return jnp.tanh(x @ p["w"])

        def loss(params):
            return pipeline_apply(
                stage_fn, params, x, mesh=mesh, num_microbatches=2
            ).sum()

        g = jax.grad(loss)(params)

        def ref_loss(params):
            h = x
            for i in range(n_stages):
                h = jnp.tanh(h @ params["w"][i])
            return h.sum()

        g_ref = jax.grad(ref_loss)(params)
        np.testing.assert_allclose(
            np.asarray(g["w"]), np.asarray(g_ref["w"]), atol=1e-5
        )

    def test_bubble_tick_nan_aux_masked(self):
        """Bubble ticks run stage_fn on garbage (zero-initialized)
        activations; an aux that is non-finite there (log 0 → -inf) must
        not poison the accumulator — multiplicative masking would turn
        0 * -inf into NaN, selection masking must not."""
        n_stages = 2
        num_micro = 2
        mesh = build_mesh(MeshSpec(pp=n_stages, dp=4))
        rng = np.random.default_rng(5)
        dim = 4
        w = jnp.asarray(rng.normal(size=(n_stages, dim, dim)) * 0.3)
        # Inputs bounded away from zero so every VALID tick's aux is
        # finite; only garbage ticks see all-zero activations.
        x = jnp.asarray(np.abs(rng.normal(size=(8, dim))) + 1.0)

        def stage_fn(p, xin):
            y = jnp.tanh(xin @ p["w"]) + 2.0  # activations stay positive
            return y, {"logsum": jnp.log(jnp.abs(xin).sum())}

        out, aux = pipeline_apply(
            stage_fn, {"w": w}, x, mesh=mesh,
            num_microbatches=num_micro, stage_aux=True,
        )
        got = float(aux["logsum"])
        assert np.isfinite(got), "bubble-tick -inf leaked into the aux sum"
        # Sequential reference: Σ over (stage, microbatch) of the aux on
        # that stage's true input.
        x_mb = np.asarray(x).reshape(num_micro, -1, dim)
        expect = 0.0
        for u in range(num_micro):
            h = x_mb[u]
            for s in range(n_stages):
                expect += np.log(np.abs(h).sum())
                h = np.tanh(h @ np.asarray(w[s])) + 2.0
        np.testing.assert_allclose(got, expect, rtol=1e-5)

    def test_bubble_tick_nan_aux_masked_interleaved(self):
        """Same NaN-in-bubble regression for the interleaved (virtual
        stage) schedule, whose aux path masks by the chunk-tick window."""
        pp, virtual, num_micro = 2, 2, 2
        mesh = build_mesh(MeshSpec(pp=pp, dp=4))
        rng = np.random.default_rng(6)
        dim = 4
        # leaves [pp, virtual, ...]: element [d, c] = global stage c*pp+d
        w = jnp.asarray(rng.normal(size=(pp, virtual, dim, dim)) * 0.3)
        x = jnp.asarray(np.abs(rng.normal(size=(4, dim))) + 1.0)

        def stage_fn(p, xin):
            y = jnp.tanh(xin @ p["w"]) + 2.0
            return y, {"logsum": jnp.log(jnp.abs(xin).sum())}

        out, aux = pipeline_apply(
            stage_fn, {"w": w}, x, mesh=mesh, num_microbatches=num_micro,
            schedule="interleaved", virtual=virtual, stage_aux=True,
        )
        got = float(aux["logsum"])
        assert np.isfinite(got), "bubble-tick -inf leaked into the aux sum"
        x_mb = np.asarray(x).reshape(num_micro, -1, dim)
        expect = 0.0
        for u in range(num_micro):
            h = x_mb[u]
            for g in range(virtual * pp):  # global virtual stage order
                expect += np.log(np.abs(h).sum())
                h = np.tanh(h @ np.asarray(w[g % pp, g // pp])) + 2.0
        np.testing.assert_allclose(got, expect, rtol=1e-5)

    def test_rejects_bad_microbatch(self):
        mesh = build_mesh(MeshSpec(pp=2, dp=4))
        with pytest.raises(ValueError):
            pipeline_apply(
                lambda p, x: x, {"w": jnp.zeros((2, 1))},
                jnp.zeros((7, 4)), mesh=mesh, num_microbatches=2,
            )


def test_ring_cross_length_causal_skip_exact():
    """The causal ring-step skip must compare GLOBAL positions: with
    t_q != t_k a 'future' kv owner can still hold visible keys."""
    from tony_tpu.parallel.mesh import MeshSpec, build_mesh

    mesh = build_mesh(MeshSpec(sp=2, dp=2, tp=2))
    rng = np.random.default_rng(7)
    q = jnp.asarray(rng.normal(size=(2, 16, 2, 8)), dtype=jnp.float32)
    k = jnp.asarray(rng.normal(size=(2, 8, 2, 8)), dtype=jnp.float32)
    v = jnp.asarray(rng.normal(size=(2, 8, 2, 8)), dtype=jnp.float32)
    out = ring_attention(q, k, v, mesh, causal=True, block_k=4)
    # dense reference with plain global positions (ring convention)
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * (8 ** -0.5)
    q_pos = jnp.arange(16)[:, None]
    k_pos = jnp.arange(8)[None, :]
    s = jnp.where((q_pos >= k_pos)[None, None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    ref = jnp.einsum("bhqk,bkhd->bqhd", p, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)
