"""Model-layer tests on the virtual 8-device CPU mesh: every parallelism
axis is exercised by a real train step, and the sharded result is checked
against a single-device reference run (the strongest correctness statement a
sharding test can make)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from tony_tpu.models import (
    MnistConfig,
    TransformerConfig,
    forward,
    init_params,
    lm_loss,
    make_train_step,
)
from tony_tpu.models.train import make_classifier_step
from tony_tpu.parallel.mesh import MeshSpec, build_mesh

# jax < 0.5: the shard_map grad/transpose path re-runs the out-spec
# replication check even under check_vma/check_rep=False, and rejects the
# MoE pipeline's psum-replicated aux scalars with a _SpecError; the
# router-collapse numerics also differ under the old PRNG. The affected
# tests run on current jax.
OLD_JAX = tuple(int(p) for p in jax.__version__.split(".")[:2]) < (0, 5)
moe_pipeline_old_jax = pytest.mark.skipif(
    OLD_JAX,
    reason="jax < 0.5 shard_map transpose cannot express the MoE "
           "pipeline's replicated aux outputs (_SpecError)",
)

CFG = TransformerConfig(
    vocab_size=256,
    d_model=64,
    n_layers=4,
    n_heads=4,
    head_dim=16,
    d_ff=128,
    max_seq=64,
    dtype="float32",  # CPU tests compare across meshes; bf16 noise would mask bugs
    remat=False,
)


def _tokens(b=8, t=33, seed=0, vocab=None):
    rng = np.random.default_rng(seed)
    return jnp.asarray(
        rng.integers(0, vocab or CFG.vocab_size, (b, t)), jnp.int32
    )


def _single_device_loss(cfg, tokens, key):
    mesh1 = Mesh(np.array(jax.devices()[:1]).reshape(1, 1, 1, 1, 1),
                 ("dp", "pp", "ep", "sp", "tp"))
    params = jax.jit(lambda k: init_params(k, cfg))(key)
    with jax.sharding.set_mesh(mesh1):
        return float(jax.jit(
            lambda p, t: lm_loss(p, t, cfg, mesh1)
        )(params, tokens))


class TestForward:
    def test_logits_shape_and_finite(self):
        mesh = build_mesh(MeshSpec(dp=2, sp=2, tp=2))
        tokens = _tokens()[:, :-1]
        params = jax.jit(lambda k: init_params(k, CFG))(jax.random.key(0))
        with jax.sharding.set_mesh(mesh):
            logits = jax.jit(lambda p, t: forward(p, t, CFG, mesh))(
                params, tokens
            )
        assert logits.shape == (8, 32, CFG.vocab_size)
        assert bool(jnp.isfinite(logits).all())

    def test_sharded_loss_matches_single_device(self):
        tokens = _tokens()
        key = jax.random.key(1)
        want = _single_device_loss(CFG, tokens, key)
        mesh = build_mesh(MeshSpec(dp=2, sp=2, tp=2))
        params = jax.jit(lambda k: init_params(k, CFG))(key)
        with jax.sharding.set_mesh(mesh):
            got = float(jax.jit(
                lambda p, t: lm_loss(p, t, CFG, mesh)
            )(params, tokens))
        np.testing.assert_allclose(got, want, rtol=2e-4)


class TestTrainStep:
    def test_gspmd_step_all_axes(self):
        mesh = build_mesh(MeshSpec(dp=2, sp=2, tp=2))
        init_fn, step_fn = make_train_step(CFG, mesh, learning_rate=1e-3)
        with jax.sharding.set_mesh(mesh):
            state = init_fn(jax.random.key(0))
            tokens = _tokens()
            losses = []
            for i in range(3):
                state, metrics = step_fn(state, tokens)
                losses.append(float(metrics["loss"]))
        assert int(state.step) == 3
        assert losses[2] < losses[0]  # adamw on a fixed batch must descend

    def test_moe_step_with_expert_parallel(self):
        cfg = TransformerConfig(
            vocab_size=128, d_model=32, n_layers=2, n_heads=2, head_dim=16,
            d_ff=64, max_seq=64, n_experts=4, expert_top_k=2,
            dtype="float32", remat=False,
        )
        mesh = build_mesh(MeshSpec(dp=2, ep=2, tp=2))
        init_fn, step_fn = make_train_step(cfg, mesh, learning_rate=1e-3)
        with jax.sharding.set_mesh(mesh):
            state = init_fn(jax.random.key(0))
            tokens = _tokens(b=4, t=17, vocab=cfg.vocab_size)
            losses = []
            for _ in range(3):
                state, metrics = step_fn(state, tokens)
                losses.append(float(metrics["loss"]))
        assert all(np.isfinite(losses))
        assert losses[2] < losses[0]
        # Router metrics ride the step output on the MoE path.
        for k in ("moe_balance", "moe_zloss", "moe_drop_rate", "moe_entropy"):
            assert np.isfinite(float(metrics[k])), k

    @pytest.mark.skipif(
        OLD_JAX,
        reason="router-collapse initial entropy differs under the "
               "pre-0.5 jax PRNG",
    )
    def test_moe_balance_loss_recovers_biased_router(self):
        """Start from a router collapsed onto expert 0 (shrunk weights plus
        an expert-0 column aligned with the batch's activation directions):
        with the Switch balance loss the assignment re-spreads (entropy
        rises to ~ln E, drop rate goes to 0); with the coefficient at 0 the
        collapse persists. This is the failure mode the aux loss exists
        for — dropped tokens silently pass through the residual."""

        def run(balance_coef, steps=40):
            cfg = TransformerConfig(
                vocab_size=128, d_model=32, n_layers=2, n_heads=2,
                head_dim=16, d_ff=64, max_seq=64, n_experts=4,
                expert_top_k=1, dtype="float32", remat=False,
                moe_balance_coef=balance_coef,
            )
            mesh = build_mesh(MeshSpec(dp=2, ep=2, tp=2))
            init_fn, step_fn = make_train_step(cfg, mesh, learning_rate=1e-2)
            rng = np.random.default_rng(1)
            tokens = jnp.asarray(rng.integers(0, 128, (4, 17)), jnp.int32)
            with jax.sharding.set_mesh(mesh):
                state = init_fn(jax.random.key(0))
                embed = state.params["embed"]
                used = jnp.unique(tokens)
                direction = embed[used]
                direction = (
                    direction
                    / jnp.linalg.norm(direction, axis=-1, keepdims=True)
                ).sum(0)
                router = state.params["layers"]["router"] * 0.05
                router = router.at[:, :, 0].add(0.1 * direction)
                state = state._replace(
                    params={**state.params,
                            "layers": {**state.params["layers"],
                                       "router": router}},
                )
                hist = []
                for _ in range(steps):
                    state, metrics = step_fn(state, tokens)
                    hist.append({k: float(v) for k, v in metrics.items()})
            return hist

        with_aux = run(0.05)
        without = run(0.0)
        ln_e = float(np.log(4))
        # Both start collapsed: entropy well below uniform, heavy overflow.
        assert with_aux[0]["moe_entropy"] < 0.65 * ln_e
        assert with_aux[0]["moe_drop_rate"] > 0.3
        # The balance loss re-spreads routing; CE alone does not (top-1
        # combine weights are constant 1, so CE gives the router no signal).
        assert with_aux[-1]["moe_entropy"] > 0.9 * ln_e
        assert with_aux[-1]["moe_drop_rate"] < 0.05
        assert without[-1]["moe_entropy"] < 0.7 * ln_e
        assert without[-1]["moe_drop_rate"] > 0.3

    def test_pipeline_step_pp_tp_dp(self):
        mesh = build_mesh(MeshSpec(dp=2, pp=2, tp=2))
        init_fn, step_fn = make_train_step(
            CFG, mesh, learning_rate=1e-3, pipeline_microbatches=4
        )
        with jax.sharding.set_mesh(mesh):
            state = init_fn(jax.random.key(0))
            tokens = _tokens()
            losses = []
            for _ in range(3):
                state, metrics = step_fn(state, tokens)
                losses.append(float(metrics["loss"]))
        assert all(np.isfinite(losses))
        assert losses[2] < losses[0]

    def test_unrolled_layer_loop_matches_scan(self):
        """layer_scan_unroll >= n_layers takes the static Python-loop
        path (grads avoid scan's stacked-grad DUS); it must be the same
        math as the rolled scan — loss AND grads."""
        import dataclasses

        tokens = _tokens()
        params = jax.jit(lambda k: init_params(k, CFG))(jax.random.key(3))
        mesh = build_mesh(MeshSpec(dp=2, sp=2, tp=2))
        out = {}
        for unroll in (1, CFG.n_layers):
            cfg = dataclasses.replace(CFG, layer_scan_unroll=unroll)
            with jax.sharding.set_mesh(mesh):
                loss, grads = jax.jit(jax.value_and_grad(
                    lambda p, t, c=cfg: lm_loss(p, t, c, mesh)
                ))(params, tokens)
            out[unroll] = (float(loss), grads)
        np.testing.assert_allclose(out[1][0], out[CFG.n_layers][0],
                                   rtol=1e-6)
        for (path, a), (_, b) in zip(
            jax.tree_util.tree_flatten_with_path(out[1][1])[0],
            jax.tree_util.tree_flatten_with_path(out[CFG.n_layers][1])[0],
        ):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-7,
                err_msg=str(path),
            )

    def test_pipeline_loss_matches_gspmd(self):
        """Same params, same batch: the pp=2 manual trunk and the GSPMD
        trunk are the same math."""
        tokens = _tokens()
        key = jax.random.key(3)
        params = jax.jit(lambda k: init_params(k, CFG))(key)

        gmesh = build_mesh(MeshSpec(dp=2, sp=2, tp=2))
        with jax.sharding.set_mesh(gmesh):
            want = float(jax.jit(
                lambda p, t: lm_loss(p, t, CFG, gmesh)
            )(params, tokens))

        pmesh = build_mesh(MeshSpec(dp=2, pp=2, sp=2))
        with jax.sharding.set_mesh(pmesh):
            got = float(jax.jit(
                lambda p, t: lm_loss(p, t, CFG, pmesh, pipeline_microbatches=4)
            )(params, tokens))
        np.testing.assert_allclose(got, want, rtol=2e-4)

    def test_interleaved_schedule_matches_gpipe_loss_and_grads(self):
        """Megatron-style virtual stages (v=2) vs GPipe on the same pp=2
        mesh: identical loss AND identical gradients — the round-robin
        chunk placement and wrap-around output collection must be a pure
        re-scheduling of the same math."""
        tokens = _tokens()
        params = jax.jit(lambda k: init_params(k, CFG))(jax.random.key(3))
        pmesh = build_mesh(MeshSpec(dp=2, pp=2, tp=2))

        def loss_fn(schedule, virtual):
            def f(p, t):
                return lm_loss(p, t, CFG, pmesh, pipeline_microbatches=4,
                               pipeline_schedule=schedule,
                               pipeline_virtual=virtual)
            return f

        with jax.sharding.set_mesh(pmesh):
            lg, gg = jax.jit(jax.value_and_grad(loss_fn("gpipe", 1)))(
                params, tokens)
            li, gi = jax.jit(
                jax.value_and_grad(loss_fn("interleaved", 2))
            )(params, tokens)
        np.testing.assert_allclose(float(li), float(lg), rtol=2e-5)
        for (path, a), (_, b) in zip(
            jax.tree_util.tree_flatten_with_path(gg)[0],
            jax.tree_util.tree_flatten_with_path(gi)[0],
        ):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=5e-4, atol=1e-5,
                err_msg=str(path),
            )

    def test_interleaved_pp4_v4_matches_gpipe_loss_and_grads(self):
        """pp=4, virtual=4 (16 virtual stages over a 16-layer trunk): the
        index algebra in _pipeline_interleaved_local is exactly the kind
        that can pass at 2/2 and break at 4/4 (VERDICT r3 weak #7), so pin
        loss AND grads against GPipe on the same mesh at depth."""
        cfg16 = TransformerConfig(
            vocab_size=128, d_model=32, n_layers=16, n_heads=2, head_dim=16,
            d_ff=64, max_seq=64, dtype="float32", remat=False,
        )
        tokens = _tokens(b=8, t=17, vocab=128)
        params = jax.jit(lambda k: init_params(k, cfg16))(jax.random.key(5))
        pmesh = build_mesh(MeshSpec(pp=4, tp=2))

        def loss_fn(schedule, virtual):
            def f(p, t):
                return lm_loss(p, t, cfg16, pmesh, pipeline_microbatches=8,
                               pipeline_schedule=schedule,
                               pipeline_virtual=virtual)
            return f

        with jax.sharding.set_mesh(pmesh):
            lg, gg = jax.jit(jax.value_and_grad(loss_fn("gpipe", 1)))(
                params, tokens)
            l4, g4 = jax.jit(
                jax.value_and_grad(loss_fn("interleaved", 4))
            )(params, tokens)
            l2, _ = jax.jit(
                jax.value_and_grad(loss_fn("interleaved", 2))
            )(params, tokens)
        np.testing.assert_allclose(float(l4), float(lg), rtol=2e-5)
        np.testing.assert_allclose(float(l2), float(lg), rtol=2e-5)
        for (path, a), (_, b) in zip(
            jax.tree_util.tree_flatten_with_path(gg)[0],
            jax.tree_util.tree_flatten_with_path(g4)[0],
        ):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=5e-4, atol=1e-5,
                err_msg=str(path),
            )

    def test_interleaved_schedule_shrinks_bubble(self):
        """Tick accounting: at v virtual stages the idle bubble per device
        drops from (pp-1) full-stage ticks to (pp-1) chunk ticks — a ~v
        fold reduction of idle time (the schedule implementations derive
        their scan lengths from this same function)."""
        from tony_tpu.parallel.pipeline import schedule_info

        m, pp, layers = 8, 4, 16
        v = 2
        gp = schedule_info("gpipe", m, pp, layers)
        il = schedule_info("interleaved", m, pp, layers, virtual=v)
        # Idle time per device, in units of layer executions: GPipe idles
        # (pp-1) full ticks, interleaved pp chunk-ticks of 1/v the work —
        # a ((pp-1)/pp)*v-fold shrink (1.5x here).
        gp_idle = gp.bubble_fraction * gp.ticks * gp.tick_layers
        il_idle = il.bubble_fraction * il.ticks * il.tick_layers
        assert gp_idle == pytest.approx((pp - 1) * layers / pp)
        assert il_idle == pytest.approx(layers / v)
        assert il_idle < gp_idle / (((pp - 1) / pp) * v * 0.99)
        # Same useful work either way: m microbatches x all layers / pp —
        # exact in both schedules (the accounting must conserve work).
        assert gp.ticks * gp.tick_layers * (1 - gp.bubble_fraction) == (
            pytest.approx(m * layers / pp)
        )
        assert il.ticks * il.tick_layers * (1 - il.bubble_fraction) == (
            pytest.approx(m * layers / pp)
        )

    @moe_pipeline_old_jax
    def test_moe_pipeline_matches_gspmd_loss_and_grads(self):
        """MoE through the pipeline trunk (VERDICT r4 weak #1): pp=2×ep=2
        ×tp=2 manual-collective experts (resident E/ep slabs, all_to_all
        token exchange) produce the same total loss AND gradients as the
        GSPMD MoE trunk on a dp=2×ep=2×tp=2 mesh. Capacity factor = E so
        nothing drops — the two trunks then compute identical math."""
        cfg = TransformerConfig(
            vocab_size=128, d_model=32, n_layers=2, n_heads=2,
            head_dim=16, d_ff=64, max_seq=64, dtype="float32",
            remat=False, n_experts=4, expert_top_k=2, capacity_factor=4.0,
        )
        tokens = _tokens(b=8, t=17, vocab=128)
        params = jax.jit(lambda k: init_params(k, cfg))(jax.random.key(7))
        gmesh = build_mesh(MeshSpec(dp=2, ep=2, tp=2))
        pmesh = build_mesh(MeshSpec(pp=2, ep=2, tp=2))

        with jax.sharding.set_mesh(gmesh):
            lg, gg = jax.jit(jax.value_and_grad(
                lambda p, t: lm_loss(p, t, cfg, gmesh)
            ))(params, tokens)
        with jax.sharding.set_mesh(pmesh):
            lp_, gp_ = jax.jit(jax.value_and_grad(
                lambda p, t: lm_loss(p, t, cfg, pmesh,
                                     pipeline_microbatches=1)
            ))(params, tokens)
        np.testing.assert_allclose(float(lp_), float(lg), rtol=2e-5)
        for (path, a), (_, b) in zip(
            jax.tree_util.tree_flatten_with_path(gg)[0],
            jax.tree_util.tree_flatten_with_path(gp_)[0],
        ):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=5e-4, atol=1e-5,
                err_msg=str(path),
            )

    @moe_pipeline_old_jax
    def test_moe_pipeline_microbatched_aux_metrics(self):
        """Microbatched (m=2) MoE pipeline: aux losses accumulate across
        microbatches and average — the train step surfaces finite router
        metrics with zero drops at generous capacity, and the interleaved
        schedule's loss AND grads match GPipe's (same math, different
        scheduling — including the per-schedule aux accumulation)."""
        from tony_tpu.models import make_train_step

        cfg = TransformerConfig(
            vocab_size=128, d_model=32, n_layers=4, n_heads=2,
            head_dim=16, d_ff=64, max_seq=64, dtype="float32",
            remat=False, n_experts=4, expert_top_k=2, capacity_factor=4.0,
        )
        tokens = _tokens(b=8, t=17, vocab=128)
        pmesh = build_mesh(MeshSpec(pp=2, ep=2, tp=2))
        with jax.sharding.set_mesh(pmesh):
            init_fn, step_fn = make_train_step(
                cfg, pmesh, pipeline_microbatches=2
            )
            state = init_fn(jax.random.key(0))
            state, metrics = step_fn(state, tokens)
            lg, gg = jax.jit(jax.value_and_grad(
                lambda p, t: lm_loss(p, t, cfg, pmesh,
                                     pipeline_microbatches=2)
            ))(state.params, tokens)
            li, gi = jax.jit(jax.value_and_grad(
                lambda p, t: lm_loss(p, t, cfg, pmesh,
                                     pipeline_microbatches=2,
                                     pipeline_schedule="interleaved",
                                     pipeline_virtual=2)
            ))(state.params, tokens)
        for k in ("moe_balance", "moe_zloss", "moe_drop_rate",
                  "moe_entropy"):
            assert np.isfinite(float(metrics[k])), k
        assert float(metrics["moe_drop_rate"]) == 0.0
        assert float(metrics["moe_balance"]) >= 1.0 - 1e-5  # Switch minimum
        np.testing.assert_allclose(float(li), float(lg), rtol=2e-5)
        for (path, a), (_, b) in zip(
            jax.tree_util.tree_flatten_with_path(gg)[0],
            jax.tree_util.tree_flatten_with_path(gi)[0],
        ):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=5e-4, atol=1e-5,
                err_msg=str(path),
            )

    def test_moe_pipeline_rejects_indivisible_experts(self):
        cfg = TransformerConfig(n_experts=3, n_layers=2)
        mesh = build_mesh(MeshSpec(pp=2, ep=2, tp=2))
        params = jax.eval_shape(lambda k: init_params(k, cfg), jax.random.key(0))
        with pytest.raises(ValueError, match="divisible by ep"):
            from tony_tpu.models.transformer import forward_pipeline
            forward_pipeline(
                params, jnp.zeros((4, 8), jnp.int32), cfg, mesh,
                num_microbatches=2,
            )


class TestMnist:
    def test_mnist_cnn_learns(self):
        mesh = build_mesh(MeshSpec(dp=8))
        cfg = MnistConfig(arch="cnn", dtype="float32")
        init_fn, step_fn = make_classifier_step(cfg, mesh, learning_rate=2e-3)
        rng = np.random.default_rng(0)
        # Separable synthetic task: class = brightest quadrant band
        images = jnp.asarray(rng.normal(size=(64, 28, 28, 1)), jnp.float32)
        labels = jnp.asarray(
            (np.asarray(images).reshape(64, -1).mean(-1) > 0).astype(np.int32)
        )
        with jax.sharding.set_mesh(mesh):
            state = init_fn(jax.random.key(0))
            losses = []
            for _ in range(5):
                state, m = step_fn(state, images, labels)
                losses.append(float(m["loss"]))
        assert losses[-1] < losses[0]

    def test_steps_per_call_matches_sequential(self):
        """steps_per_call=3 (one on-device scan) must produce the same
        final params and metrics as 3 sequential single-step calls over
        the same batches — the fused loop is dispatch batching, not a
        different optimizer."""
        from tony_tpu.models import MnistConfig
        from tony_tpu.models.train import make_classifier_step
        from tony_tpu.parallel.mesh import MeshSpec, build_mesh

        mesh = build_mesh(MeshSpec(dp=8))
        cfg = MnistConfig(arch="mlp", dtype="float32")
        rng = np.random.default_rng(2)
        images = jnp.asarray(
            rng.normal(size=(3, 16, 28, 28, 1)), jnp.float32
        )
        labels = jnp.asarray(rng.integers(0, 10, (3, 16)), jnp.int32)

        init1, step1 = make_classifier_step(cfg, mesh, learning_rate=1e-3)
        init3, step3 = make_classifier_step(
            cfg, mesh, learning_rate=1e-3, steps_per_call=3
        )
        with jax.sharding.set_mesh(mesh):
            s1 = init1(jax.random.key(4))
            for i in range(3):
                s1, m1 = step1(s1, images[i], labels[i])
            s3 = init3(jax.random.key(4))
            s3, m3 = step3(s3, images, labels)
        assert int(s1.step) == int(s3.step) == 3
        np.testing.assert_allclose(
            float(m1["loss"]), float(m3["loss"]), rtol=1e-6
        )
        for a, b in zip(jax.tree.leaves(s1.params), jax.tree.leaves(s3.params)):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-7
            )

    def test_mnist_mlp_shapes(self):
        from tony_tpu.models import mnist_apply, mnist_init
        cfg = MnistConfig(arch="mlp", dtype="float32")
        params = mnist_init(jax.random.key(0), cfg)
        logits = mnist_apply(params, jnp.zeros((4, 784)), cfg)
        assert logits.shape == (4, 10)


class TestResNet:
    def _tiny(self):
        from tony_tpu.models import ResNetConfig

        return ResNetConfig(depth=18, width=8, n_classes=10, dtype="float32")

    def test_forward_shapes_and_dtype(self):
        from tony_tpu.models import resnet_apply, resnet_init

        cfg = self._tiny()
        params = resnet_init(jax.random.key(0), cfg)
        x = jnp.ones((2, 32, 32, 3))
        logits = resnet_apply(params, x, cfg)
        assert logits.shape == (2, 10) and logits.dtype == jnp.float32
        assert np.isfinite(np.asarray(logits)).all()

    def test_resnet50_param_count(self):
        from tony_tpu.models import ResNetConfig, resnet_init

        cfg = ResNetConfig(depth=50, width=64, n_classes=1000)
        params = resnet_init(jax.random.key(0), cfg)
        n = sum(x.size for x in jax.tree.leaves(params))
        # canonical ResNet-50 is ~25.6M; GroupNorm keeps the same
        # scale/bias counts as BN's affine params
        assert 24e6 < n < 27e6, n

    def test_group_norm_matches_two_pass_reference(self):
        """The single-accumulation GroupNorm (E[x²]−E[x]² with fp32
        accumulation — the 2.7× ResNet step win) must match the textbook
        two-pass mean/var formulation."""
        from tony_tpu.models.resnet import _group_norm

        rng = np.random.default_rng(0)
        x = jnp.asarray(
            rng.normal(size=(2, 8, 8, 32)) * 3 + 1.5, jnp.float32
        )
        gn = {"scale": jnp.asarray(rng.normal(size=(32,)), jnp.float32),
              "bias": jnp.asarray(rng.normal(size=(32,)), jnp.float32)}

        def reference(x, gn, groups, eps=1e-5):
            b, h, w, c = x.shape
            g = min(groups, c)
            xf = x.astype(jnp.float32).reshape(b, h, w, g, c // g)
            mean = xf.mean(axis=(1, 2, 4), keepdims=True)
            var = xf.var(axis=(1, 2, 4), keepdims=True)
            xf = (xf - mean) * jax.lax.rsqrt(var + eps)
            return (xf.reshape(b, h, w, c) * gn["scale"] + gn["bias"])

        np.testing.assert_allclose(
            np.asarray(_group_norm(x, gn, 8)),
            np.asarray(reference(x, gn, 8)),
            atol=2e-5, rtol=2e-5,
        )
        # bf16 inputs: fp32 accumulation keeps stats sane
        xb = x.astype(jnp.bfloat16)
        out = _group_norm(xb, gn, 8)
        assert out.dtype == jnp.bfloat16
        np.testing.assert_allclose(
            np.asarray(out).astype(np.float32),
            np.asarray(reference(x, gn, 8)),
            atol=0.15,  # bf16 quantization of in/out, not the stats
        )

    def test_unsupported_depth_rejected(self):
        from tony_tpu.models import ResNetConfig

        with pytest.raises(ValueError, match="unsupported depth"):
            ResNetConfig(depth=42).plan

    def test_loss_descends_data_parallel(self):
        from tony_tpu.models import make_image_classifier_step, resnet_apply, resnet_init
        from tony_tpu.parallel.mesh import MeshSpec, build_mesh

        cfg = self._tiny()
        mesh = build_mesh(MeshSpec(dp=8))
        init_fn, step_fn = make_image_classifier_step(
            lambda key: resnet_init(key, cfg),
            lambda params, images: resnet_apply(params, images, cfg),
            mesh,
            learning_rate=5e-3,
        )
        rng = np.random.default_rng(0)
        labels = jnp.asarray(rng.integers(0, 10, (16,)), jnp.int32)
        images = jnp.asarray(
            rng.normal(size=(16, 32, 32, 3))
            + np.asarray(labels)[:, None, None, None] * 0.3,
            jnp.float32,
        )
        with jax.sharding.set_mesh(mesh):
            state = init_fn(jax.random.key(1))
            first = None
            for _ in range(8):
                state, metrics = step_fn(state, images, labels)
                first = first if first is not None else float(metrics["loss"])
            last = float(metrics["loss"])
        assert np.isfinite(last) and last < first


class TestDecode:
    """KV-cache decoding pinned to the training forward — the cached path
    must produce the same distribution the trunk was trained with."""

    def _setup(self):
        from tony_tpu.models import TransformerConfig, init_params

        cfg = TransformerConfig(
            vocab_size=64, d_model=32, n_layers=2, n_heads=2, head_dim=16,
            d_ff=64, max_seq=64, dtype="float32", remat=False,
        )
        params = init_params(jax.random.key(0), cfg)
        return cfg, params

    @pytest.mark.parametrize("prefill", [False, True])
    def test_prefill_matches_training_forward(self, prefill):
        """Both the dense-scan path and the flash prefill fast path (what
        generate() actually runs) must match the training forward at the
        logits level, not just post-argmax."""
        from tony_tpu.models import advance, forward, init_cache

        cfg, params = self._setup()
        tokens = jnp.asarray(
            np.random.default_rng(0).integers(0, 64, (2, 12)), jnp.int32
        )
        cache = init_cache(cfg, 2, 32)
        logits, cache = advance(params, cache, tokens, cfg, prefill=prefill)
        from tony_tpu.parallel.mesh import MeshSpec, build_mesh

        mesh = build_mesh(MeshSpec(), devices=jax.devices()[:1])
        with jax.sharding.set_mesh(mesh):
            full = forward(params, tokens, cfg, mesh)[:, -1].astype(
                jnp.float32
            )
        np.testing.assert_allclose(
            np.asarray(logits), np.asarray(full), atol=2e-4
        )
        assert int(cache["length"]) == 12

    def test_stepwise_decode_matches_full_recompute(self):
        """Greedy generation with the cache must emit the same tokens as
        re-running the full forward on the growing context each step."""
        from tony_tpu.models import forward, generate

        cfg, params = self._setup()
        prompt = jnp.asarray(
            np.random.default_rng(1).integers(0, 64, (2, 6)), jnp.int32
        )
        got = generate(params, prompt, cfg, max_new_tokens=5)
        # reference: uncached greedy loop
        from tony_tpu.parallel.mesh import MeshSpec, build_mesh

        mesh = build_mesh(MeshSpec(), devices=jax.devices()[:1])
        ctx = prompt
        want = []
        with jax.sharding.set_mesh(mesh):
            for _ in range(5):
                logits = forward(params, ctx, cfg, mesh)[:, -1]
                tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                want.append(tok)
                ctx = jnp.concatenate([ctx, tok[:, None]], axis=1)
        want = jnp.stack(want, axis=1)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    def test_temperature_sampling_varies_with_key(self):
        from tony_tpu.models import generate

        cfg, params = self._setup()
        prompt = jnp.ones((1, 4), jnp.int32)
        a = generate(params, prompt, cfg, 8, temperature=1.0,
                     key=jax.random.key(1))
        b = generate(params, prompt, cfg, 8, temperature=1.0,
                     key=jax.random.key(2))
        assert a.shape == b.shape == (1, 8)
        assert not np.array_equal(np.asarray(a), np.asarray(b))

    def test_moe_decode_matches_training_forward(self):
        """MoE trunk (with GQA): cached greedy decode emits the same tokens
        as full-recompute argmax. capacity_factor is sized so training's
        dispatch drops nothing — decode's dense-mixture evaluation never
        drops (inference serves whatever the router picks), so parity
        requires a non-dropping training config."""
        from tony_tpu.models import (
            TransformerConfig, forward, generate, init_params,
        )
        from tony_tpu.parallel.mesh import MeshSpec, build_mesh

        cfg = TransformerConfig(
            vocab_size=64, d_model=32, n_layers=2, n_heads=4, head_dim=8,
            d_ff=64, max_seq=64, dtype="float32", remat=False,
            n_experts=4, expert_top_k=2, capacity_factor=4.0,
            n_kv_heads=2,
        )
        params = init_params(jax.random.key(7), cfg)
        prompt = jnp.asarray(
            np.random.default_rng(3).integers(0, 64, (2, 6)), jnp.int32
        )
        got = generate(params, prompt, cfg, max_new_tokens=5)
        mesh = build_mesh(MeshSpec(), devices=jax.devices()[:1])
        ctx = prompt
        want = []
        with jax.sharding.set_mesh(mesh):
            for _ in range(5):
                logits = forward(params, ctx, cfg, mesh)[:, -1]
                nxt = jnp.argmax(logits, -1).astype(jnp.int32)
                want.append(nxt)
                ctx = jnp.concatenate([ctx, nxt[:, None]], axis=1)
        np.testing.assert_array_equal(
            np.asarray(got), np.stack(want, axis=1)
        )

    @pytest.mark.parametrize("n_experts", [4, 16])
    def test_routed_moe_decode_token_exact_vs_dense(self, n_experts):
        """Top-k-only (gathered) expert evaluation vs the dense mixture:
        identical greedy tokens at E=4 and E=16 (VERDICT r3 weak #3). On
        v5e the dense mixture measured FASTER at every tested (B, E) so
        it stays the default; this parity pin is what lets either mode be
        chosen on perf grounds alone."""
        import dataclasses

        from tony_tpu.models import TransformerConfig, generate, init_params

        base = TransformerConfig(
            vocab_size=64, d_model=32, n_layers=2, n_heads=4, head_dim=8,
            d_ff=64, max_seq=64, dtype="float32", remat=False,
            n_experts=n_experts, expert_top_k=2, capacity_factor=4.0,
        )
        params = init_params(jax.random.key(11), base)
        prompt = jnp.asarray(
            np.random.default_rng(5).integers(0, 64, (3, 7)), jnp.int32
        )
        out = {}
        for mode in ("routed", "dense"):
            cfg = dataclasses.replace(base, moe_decode_mode=mode)
            out[mode] = np.asarray(
                generate(params, prompt, cfg, max_new_tokens=6)
            )
        np.testing.assert_array_equal(out["routed"], out["dense"])

    def test_decode_session_matches_generate_and_refreshes(self):
        from tony_tpu.models import DecodeSession, generate

        cfg, params = self._setup()
        prompt = jnp.asarray(
            np.random.default_rng(9).integers(0, cfg.vocab_size, (2, 5)),
            jnp.int32,
        )
        session = DecodeSession(params, cfg)
        want = generate(params, prompt, cfg, max_new_tokens=6)
        got = session.generate(prompt, max_new_tokens=6)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
        # fusion happened once: the session holds the fused layout
        assert "qkv" in session.params["layers"]
        # refresh picks up new weights
        params2 = jax.tree.map(lambda p: p * 1.5, params)
        session.refresh(params2)
        want2 = generate(params2, prompt, cfg, max_new_tokens=6)
        got2 = session.generate(prompt, max_new_tokens=6)
        np.testing.assert_array_equal(np.asarray(got2), np.asarray(want2))

    def test_overflow_and_key_guards(self):
        from tony_tpu.models import generate
        import pytest

        cfg, params = self._setup()
        prompt = jnp.ones((1, 60), jnp.int32)
        with pytest.raises(ValueError, match="max_seq"):
            generate(params, prompt, cfg, max_new_tokens=10)  # 70 > 64
        with pytest.raises(ValueError, match="PRNG key"):
            generate(params, jnp.ones((1, 4), jnp.int32), cfg, 4,
                     temperature=1.0)

    def test_prefill_on_nonempty_cache_rejected(self):
        from tony_tpu.models import advance, init_cache

        cfg, params = self._setup()
        cache = init_cache(cfg, 1, 32)
        _, cache = advance(params, cache, jnp.ones((1, 4), jnp.int32), cfg,
                           prefill=True)
        with pytest.raises(ValueError, match="empty cache"):
            advance(params, cache, jnp.ones((1, 4), jnp.int32), cfg,
                    prefill=True)

    def test_cumulative_cache_overflow_rejected_eagerly(self):
        from tony_tpu.models import advance, init_cache
        import pytest

        cfg, params = self._setup()
        cache = init_cache(cfg, 1, 16)
        _, cache = advance(params, cache,
                           jnp.ones((1, 10), jnp.int32), cfg)
        with pytest.raises(ValueError, match="cannot take"):
            advance(params, cache, jnp.ones((1, 10), jnp.int32), cfg)

    def test_gqa_trains_and_decodes_token_exact(self):
        """GQA config (4 q heads, 2 kv heads): the train step descends and
        cached greedy decode matches full-recompute argmax token-for-token
        — same pin as the MHA parity tests, over the shrunken cache."""
        from tony_tpu.models import (
            TransformerConfig, forward, generate, make_train_step,
        )
        from tony_tpu.parallel.mesh import MeshSpec, build_mesh

        cfg = TransformerConfig(
            vocab_size=64, d_model=32, n_layers=2, n_heads=4, head_dim=8,
            d_ff=64, max_seq=64, n_kv_heads=2, dtype="float32", remat=False,
        )
        mesh = build_mesh(MeshSpec(dp=2, sp=2, tp=2))
        init_fn, step_fn = make_train_step(cfg, mesh, learning_rate=1e-2)
        rng = np.random.default_rng(5)
        tokens = jnp.asarray(rng.integers(0, 64, (4, 33)), jnp.int32)
        with jax.sharding.set_mesh(mesh):
            state = init_fn(jax.random.key(2))
            losses = []
            for _ in range(5):
                state, metrics = step_fn(state, tokens)
                losses.append(float(metrics["loss"]))
        assert all(np.isfinite(losses)) and losses[-1] < losses[0]

        params = jax.device_get(state.params)
        prompt = tokens[:2, :8]
        got = generate(params, prompt, cfg, max_new_tokens=6)
        # Reference: argmax over the full training forward, re-fed greedily.
        ctx = prompt
        want = []
        # Trivial 1-device mesh for the reference loop: its growing seq
        # lengths and batch 2 divide neither the training mesh's sp nor dp.
        dmesh = build_mesh(MeshSpec(), devices=jax.devices()[:1])
        with jax.sharding.set_mesh(dmesh):
            for _ in range(6):
                logits = forward(params, ctx, cfg, dmesh)[:, -1]
                nxt = jnp.argmax(logits, -1).astype(jnp.int32)
                want.append(nxt)
                ctx = jnp.concatenate([ctx, nxt[:, None]], axis=1)
        np.testing.assert_array_equal(
            np.asarray(got), np.stack(want, axis=1)
        )

    def test_gqa_cache_is_smaller(self):
        from tony_tpu.models import TransformerConfig, init_cache

        mha = TransformerConfig(n_heads=8, head_dim=16, d_model=128)
        gqa = TransformerConfig(
            n_heads=8, head_dim=16, d_model=128, n_kv_heads=2
        )
        c_mha = init_cache(mha, 2, 32)
        c_gqa = init_cache(gqa, 2, 32)
        assert c_gqa["k"].size * 4 == c_mha["k"].size

    def test_top_k_and_top_p_sampling(self):
        """top_k=1 must equal greedy argmax regardless of temperature; a
        tight top_p keeps samples inside the nucleus; invalid combos are
        rejected eagerly."""
        from tony_tpu.models import generate

        cfg, params = self._setup()
        prompt = jnp.asarray(
            np.random.default_rng(4).integers(0, 64, (2, 6)), jnp.int32
        )
        greedy = generate(params, prompt, cfg, 6)
        k1 = generate(params, prompt, cfg, 6, temperature=1.0, top_k=1,
                      key=jax.random.key(9))
        np.testing.assert_array_equal(np.asarray(greedy), np.asarray(k1))

        # Tiny top_p: only the argmax survives the nucleus at any step
        # where one token dominates; with p→0 the threshold keeps exactly
        # the top token, so this must also equal greedy.
        p_small = generate(params, prompt, cfg, 6, temperature=1.0,
                           top_p=1e-6, key=jax.random.key(11))
        np.testing.assert_array_equal(
            np.asarray(greedy), np.asarray(p_small)
        )

        # A permissive nucleus still varies with the key (real sampling).
        a = generate(params, prompt, cfg, 8, temperature=1.0, top_p=0.95,
                     key=jax.random.key(1))
        b = generate(params, prompt, cfg, 8, temperature=1.0, top_p=0.95,
                     key=jax.random.key(2))
        assert not np.array_equal(np.asarray(a), np.asarray(b))

        with pytest.raises(ValueError, match="set a temperature"):
            generate(params, prompt, cfg, 4, top_k=5)
        with pytest.raises(ValueError, match="top_p"):
            generate(params, prompt, cfg, 4, temperature=1.0, top_p=0.0,
                     key=jax.random.key(0))

    def test_tensor_parallel_decode_matches_single_device(self):
        """generate under a tp×dp mesh with sharded params produces the
        same tokens as the single-device path — multi-chip inference
        (megatron head/vocab splits) falls out of GSPMD."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        from tony_tpu.models import (
            TransformerConfig, decode_weights, generate, init_params,
            param_roles,
        )
        from tony_tpu.models.train import _sharding_for_tree
        from tony_tpu.parallel.mesh import MeshSpec, build_mesh

        cfg = TransformerConfig(
            vocab_size=64, d_model=32, n_layers=2, n_heads=4, head_dim=8,
            d_ff=64, max_seq=64, dtype="float32", remat=False,
            n_kv_heads=2,
        )
        params = init_params(jax.random.key(5), cfg)
        prompt = jnp.asarray(
            np.random.default_rng(6).integers(0, 64, (2, 6)), jnp.int32
        )
        want = generate(params, prompt, cfg, max_new_tokens=6)

        mesh = build_mesh(MeshSpec(dp=2, tp=4))
        shardings = _sharding_for_tree(params, param_roles(cfg), mesh)
        sharded = jax.device_put(params, shardings)
        # The point of the test: weights really are tp-sharded.
        wq_spec = sharded["layers"]["wq"].sharding.spec
        assert wq_spec[2] == "tp", wq_spec  # heads axis megatron-split
        with jax.sharding.set_mesh(mesh):
            got = generate(sharded, prompt, cfg, max_new_tokens=6)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    def test_decode_session_sharded_serving_parity(self):
        """DecodeSession(mesh=...) is the serve-in-place API (r4's
        GSPMD TP-decode parity test promoted to surface): fused weights
        land tp-sharded, the KV cache shards batch-over-dp and
        kv-heads-over-tp, and the generated tokens exactly match the
        single-device session."""
        from tony_tpu.models import (
            DecodeSession, TransformerConfig, init_params,
        )
        from tony_tpu.parallel.mesh import MeshSpec, build_mesh

        cfg = TransformerConfig(
            vocab_size=64, d_model=32, n_layers=2, n_heads=4, head_dim=8,
            d_ff=64, max_seq=64, dtype="float32", remat=False,
            n_kv_heads=2,
        )
        params = init_params(jax.random.key(5), cfg)
        prompt = jnp.asarray(
            np.random.default_rng(6).integers(0, 64, (4, 6)), jnp.int32
        )
        want = DecodeSession(params, cfg).generate(prompt, max_new_tokens=6)

        mesh = build_mesh(MeshSpec(dp=4, tp=2))
        session = DecodeSession(params, cfg, mesh=mesh)
        spec = session.params["layers"]["qkv"].sharding.spec
        assert spec[2] == "tp", spec          # packed head axis split
        spec = session.params["layers"]["w_down"].sharding.spec
        assert spec[1] == "tp", spec          # ff axis split
        got = session.generate(prompt, max_new_tokens=6)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
        # refresh() keeps the serving shardings
        session.refresh(params)
        assert session.params["layers"]["qkv"].sharding.spec[2] == "tp"

    def test_decode_session_sharded_moe_parity(self):
        """Sharded serving of an MoE model: expert weights split over ep,
        ff over tp (decode_param_specs' expert branch) — tokens identical
        to the single-device session."""
        from tony_tpu.models import (
            DecodeSession, TransformerConfig, init_params,
        )
        from tony_tpu.parallel.mesh import MeshSpec, build_mesh

        cfg = TransformerConfig(
            vocab_size=64, d_model=32, n_layers=2, n_heads=4, head_dim=8,
            d_ff=64, max_seq=64, dtype="float32", remat=False,
            n_kv_heads=2, n_experts=4, expert_top_k=2,
        )
        params = init_params(jax.random.key(3), cfg)
        prompt = jnp.asarray(
            np.random.default_rng(2).integers(0, 64, (4, 5)), jnp.int32
        )
        want = DecodeSession(params, cfg).generate(prompt, max_new_tokens=5)
        mesh = build_mesh(MeshSpec(dp=2, ep=2, tp=2))
        session = DecodeSession(params, cfg, mesh=mesh)
        spec = session.params["layers"]["gate_up"].sharding.spec
        assert tuple(spec)[:2] == (None, "ep"), spec
        got = session.generate(prompt, max_new_tokens=5)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    def test_init_cache_sharded_under_mesh(self):
        """Inside a mesh context the KV cache is born sharded (batch over
        dp, kv heads over tp) — not left to GSPMD propagation; outside a
        mesh it is unconstrained. Non-divisible dims fall back to
        replicated."""
        from tony_tpu.models import TransformerConfig, init_cache
        from tony_tpu.parallel.mesh import MeshSpec, build_mesh

        cfg = TransformerConfig(
            vocab_size=64, d_model=32, n_layers=2, n_heads=4, head_dim=8,
            d_ff=64, max_seq=64, dtype="float32", remat=False,
            n_kv_heads=2,
        )
        mesh = build_mesh(MeshSpec(dp=4, tp=2))
        with jax.sharding.set_mesh(mesh):
            cache = jax.jit(
                lambda: init_cache(cfg, batch=8, max_len=32)
            )()
            assert tuple(cache["k"].sharding.spec)[:4] == (
                None, "dp", None, "tp"
            ), cache["k"].sharding.spec
            # batch=3: dp (4) doesn't divide -> replicated batch axis,
            # heads still sharded
            cache3 = jax.jit(
                lambda: init_cache(cfg, batch=3, max_len=32)
            )()
            assert tuple(cache3["k"].sharding.spec)[:4] == (
                None, None, None, "tp"
            ), cache3["k"].sharding.spec
        plain = init_cache(cfg, batch=8, max_len=32)
        assert plain["k"].sharding.is_fully_replicated or isinstance(
            plain["k"].sharding, jax.sharding.SingleDeviceSharding
        )

    def test_eos_masks_continuation(self):
        """Tokens after a sequence's first EOS come back as pad; the EOS
        itself survives; sequences that never emit EOS are untouched."""
        import numpy as _np

        from tony_tpu.models import generate

        cfg, params = self._setup()
        prompt = jnp.asarray(
            _np.random.default_rng(8).integers(0, 64, (2, 6)), jnp.int32
        )
        plain = _np.asarray(generate(params, prompt, cfg, 8))
        # Pick row 0's second token as the "EOS" so masking must trigger.
        eos = int(plain[0, 1])
        masked = _np.asarray(generate(
            params, prompt, cfg, 8, eos_token=eos, pad_token=63
        ))
        # Expected under the documented rule, derived row-by-row so both
        # the has-EOS and no-EOS properties are always exercised.
        def expect(row):
            row = row.copy()
            hits = _np.flatnonzero(row == eos)
            if hits.size:
                row[hits[0] + 1:] = 63
            return row

        for r in range(plain.shape[0]):
            _np.testing.assert_array_equal(masked[r], expect(plain[r]))

    def test_checked_overflow_caught_under_jit(self):
        """checked=True + checkify turns a traced-length cache overflow into
        a runtime error instead of a clamped, silently-corrupting update."""
        from jax.experimental import checkify

        from tony_tpu.models import advance, init_cache

        cfg, params = self._setup()

        @jax.jit
        def two_steps(params, tokens):
            cache = init_cache(cfg, 1, 16)
            err1, (_, cache) = checkify.checkify(
                lambda: advance(params, cache, tokens, cfg, checked=True)
            )()
            err2, _ = checkify.checkify(
                lambda: advance(params, cache, tokens, cfg, checked=True)
            )()
            return err1, err2

        err1, err2 = two_steps(params, jnp.ones((1, 10), jnp.int32))
        err1.throw()  # 10 <= 16: fine
        import pytest

        with pytest.raises(Exception, match="KV cache overflow"):
            err2.throw()  # 20 > 16


class TestEosIdGeneration:
    """generate(..., eos_id=): done-mask early exit + effective lengths
    (the serving-era EOS contract, distinct from legacy eos_token's
    post-hoc pad masking)."""

    def _setup(self):
        from tony_tpu.models import TransformerConfig, init_params

        cfg = TransformerConfig(
            vocab_size=64, d_model=32, n_layers=2, n_heads=2, head_dim=16,
            d_ff=64, max_seq=64, dtype="float32", remat=False,
        )
        return cfg, init_params(jax.random.key(0), cfg)

    def test_lengths_and_forced_tail_match_plain_greedy(self):
        from tony_tpu.models import generate

        cfg, params = self._setup()
        prompt = jnp.asarray(
            np.random.default_rng(8).integers(0, 64, (3, 6)), jnp.int32
        )
        plain = np.asarray(generate(params, prompt, cfg, 8))
        eos = int(plain[0, 1])  # row 0 stops at its 2nd token
        res = generate(params, prompt, cfg, 8, eos_id=eos)
        toks, lens = np.asarray(res.tokens), np.asarray(res.lengths)
        for b in range(3):
            hits = np.flatnonzero(plain[b] == eos)
            want_len = hits[0] + 1 if hits.size else 8
            assert lens[b] == want_len
            # Unfinished prefix matches the plain trajectory exactly
            # (positional key schedule), tail is forced to eos_id.
            np.testing.assert_array_equal(toks[b, :want_len],
                                          plain[b, :want_len])
            assert (toks[b, want_len:] == eos).all()

    def test_effective_length_one_when_first_token_is_eos(self):
        from tony_tpu.models import generate

        cfg, params = self._setup()
        prompt = jnp.asarray(
            np.random.default_rng(8).integers(0, 64, (2, 5)), jnp.int32
        )
        plain = np.asarray(generate(params, prompt, cfg, 4))
        res = generate(params, prompt, cfg, 4, eos_id=int(plain[1, 0]))
        assert int(np.asarray(res.lengths)[1]) == 1

    def test_eos_id_and_eos_token_mutually_exclusive(self):
        from tony_tpu.models import generate

        cfg, params = self._setup()
        with pytest.raises(ValueError, match="different contracts"):
            generate(params, jnp.ones((1, 4), jnp.int32), cfg, 4,
                     eos_id=3, eos_token=3)

    def test_temperature_rows_match_plain_path_until_eos(self):
        """The while_loop's positional key schedule: a sampling row that
        has NOT hit EOS draws exactly what the plain scan path draws at
        that step, even while other rows sit done."""
        from tony_tpu.models import generate

        cfg, params = self._setup()
        prompt = jnp.asarray(
            np.random.default_rng(5).integers(0, 64, (3, 6)), jnp.int32
        )
        key = jax.random.key(11)
        plain = np.asarray(generate(
            params, prompt, cfg, 8, temperature=0.9, key=key
        ))
        eos = int(plain[0, 2])
        res = generate(params, prompt, cfg, 8, temperature=0.9, key=key,
                       eos_id=eos)
        toks, lens = np.asarray(res.tokens), np.asarray(res.lengths)
        for b in range(3):
            hits = np.flatnonzero(plain[b] == eos)
            want_len = hits[0] + 1 if hits.size else 8
            assert lens[b] == want_len
            np.testing.assert_array_equal(toks[b, :want_len],
                                          plain[b, :want_len])


class TestDecodeSessionRefresh:
    """Satellite: DecodeSession.refresh + repeated generate — fused
    weights are reused (never re-fused), and the compile-cache
    instrumentation neither double-counts reused executables nor misses
    new signatures across a refresh."""

    def _setup(self):
        from tony_tpu.models import TransformerConfig, init_params

        cfg = TransformerConfig(
            vocab_size=64, d_model=32, n_layers=2, n_heads=2, head_dim=16,
            d_ff=64, max_seq=64, dtype="float32", remat=False,
        )
        return cfg, init_params(jax.random.key(0), cfg)

    def test_refresh_with_fused_layout_is_identity(self):
        from tony_tpu.models import DecodeSession

        cfg, params = self._setup()
        session = DecodeSession(params, cfg)
        fused = session.params
        assert "qkv" in fused["layers"]
        session.refresh(fused)  # already fused: adopted as-is, no re-fuse
        assert session.params is fused

    def test_repeated_generate_and_refresh_instrumentation(self):
        from tony_tpu.models import DecodeSession, generate
        from tony_tpu.observability.metrics import default_registry

        cfg, params = self._setup()
        reg = default_registry()

        def totals():
            snap = reg.snapshot()["counters"]
            return (snap.get("tony_compile_cache_hits_total", 0)
                    + snap.get("tony_compile_cache_misses_total", 0))

        session = DecodeSession(params, cfg)
        prompt = jnp.asarray(
            np.random.default_rng(1).integers(0, 64, (2, 5)), jnp.int32
        )
        base = totals()
        session.generate(prompt, max_new_tokens=4)
        assert totals() == base + 1  # first signature instruments once
        session.generate(prompt, max_new_tokens=4)
        assert totals() == base + 1  # cached executable: not re-counted

        # refresh() swaps weights only — same avals, same executable —
        # so the signature must stay marked compiled...
        params2 = jax.tree.map(lambda p: p * 1.5, params)
        session.refresh(params2)
        got = session.generate(prompt, max_new_tokens=4)
        assert totals() == base + 1
        # ...while still producing the refreshed weights' output.
        want = generate(params2, prompt, cfg, max_new_tokens=4)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

        # A genuinely new signature (different horizon) counts again.
        session.generate(prompt, max_new_tokens=6)
        assert totals() == base + 2
