"""Model-layer tests on the virtual 8-device CPU mesh: every parallelism
axis is exercised by a real train step, and the sharded result is checked
against a single-device reference run (the strongest correctness statement a
sharding test can make)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from tony_tpu.models import (
    MnistConfig,
    TransformerConfig,
    forward,
    init_params,
    lm_loss,
    make_train_step,
)
from tony_tpu.models.train import make_classifier_step
from tony_tpu.parallel.mesh import MeshSpec, build_mesh

CFG = TransformerConfig(
    vocab_size=256,
    d_model=64,
    n_layers=4,
    n_heads=4,
    head_dim=16,
    d_ff=128,
    max_seq=64,
    dtype="float32",  # CPU tests compare across meshes; bf16 noise would mask bugs
    remat=False,
)


def _tokens(b=8, t=33, seed=0, vocab=None):
    rng = np.random.default_rng(seed)
    return jnp.asarray(
        rng.integers(0, vocab or CFG.vocab_size, (b, t)), jnp.int32
    )


def _single_device_loss(cfg, tokens, key):
    mesh1 = Mesh(np.array(jax.devices()[:1]).reshape(1, 1, 1, 1, 1),
                 ("dp", "pp", "ep", "sp", "tp"))
    params = jax.jit(lambda k: init_params(k, cfg))(key)
    with jax.sharding.set_mesh(mesh1):
        return float(jax.jit(
            lambda p, t: lm_loss(p, t, cfg, mesh1)
        )(params, tokens))


class TestForward:
    def test_logits_shape_and_finite(self):
        mesh = build_mesh(MeshSpec(dp=2, sp=2, tp=2))
        tokens = _tokens()[:, :-1]
        params = jax.jit(lambda k: init_params(k, CFG))(jax.random.key(0))
        with jax.sharding.set_mesh(mesh):
            logits = jax.jit(lambda p, t: forward(p, t, CFG, mesh))(
                params, tokens
            )
        assert logits.shape == (8, 32, CFG.vocab_size)
        assert bool(jnp.isfinite(logits).all())

    def test_sharded_loss_matches_single_device(self):
        tokens = _tokens()
        key = jax.random.key(1)
        want = _single_device_loss(CFG, tokens, key)
        mesh = build_mesh(MeshSpec(dp=2, sp=2, tp=2))
        params = jax.jit(lambda k: init_params(k, CFG))(key)
        with jax.sharding.set_mesh(mesh):
            got = float(jax.jit(
                lambda p, t: lm_loss(p, t, CFG, mesh)
            )(params, tokens))
        np.testing.assert_allclose(got, want, rtol=2e-4)


class TestTrainStep:
    def test_gspmd_step_all_axes(self):
        mesh = build_mesh(MeshSpec(dp=2, sp=2, tp=2))
        init_fn, step_fn = make_train_step(CFG, mesh, learning_rate=1e-3)
        with jax.sharding.set_mesh(mesh):
            state = init_fn(jax.random.key(0))
            tokens = _tokens()
            losses = []
            for i in range(3):
                state, metrics = step_fn(state, tokens)
                losses.append(float(metrics["loss"]))
        assert int(state.step) == 3
        assert losses[2] < losses[0]  # adamw on a fixed batch must descend

    def test_moe_step_with_expert_parallel(self):
        cfg = TransformerConfig(
            vocab_size=128, d_model=32, n_layers=2, n_heads=2, head_dim=16,
            d_ff=64, max_seq=64, n_experts=4, expert_top_k=2,
            dtype="float32", remat=False,
        )
        mesh = build_mesh(MeshSpec(dp=2, ep=2, tp=2))
        init_fn, step_fn = make_train_step(cfg, mesh, learning_rate=1e-3)
        with jax.sharding.set_mesh(mesh):
            state = init_fn(jax.random.key(0))
            tokens = _tokens(b=4, t=17, vocab=cfg.vocab_size)
            losses = []
            for _ in range(3):
                state, metrics = step_fn(state, tokens)
                losses.append(float(metrics["loss"]))
        assert all(np.isfinite(losses))
        assert losses[2] < losses[0]

    def test_pipeline_step_pp_tp_dp(self):
        mesh = build_mesh(MeshSpec(dp=2, pp=2, tp=2))
        init_fn, step_fn = make_train_step(
            CFG, mesh, learning_rate=1e-3, pipeline_microbatches=4
        )
        with jax.sharding.set_mesh(mesh):
            state = init_fn(jax.random.key(0))
            tokens = _tokens()
            losses = []
            for _ in range(3):
                state, metrics = step_fn(state, tokens)
                losses.append(float(metrics["loss"]))
        assert all(np.isfinite(losses))
        assert losses[2] < losses[0]

    def test_pipeline_loss_matches_gspmd(self):
        """Same params, same batch: the pp=2 manual trunk and the GSPMD
        trunk are the same math."""
        tokens = _tokens()
        key = jax.random.key(3)
        params = jax.jit(lambda k: init_params(k, CFG))(key)

        gmesh = build_mesh(MeshSpec(dp=2, sp=2, tp=2))
        with jax.sharding.set_mesh(gmesh):
            want = float(jax.jit(
                lambda p, t: lm_loss(p, t, CFG, gmesh)
            )(params, tokens))

        pmesh = build_mesh(MeshSpec(dp=2, pp=2, sp=2))
        with jax.sharding.set_mesh(pmesh):
            got = float(jax.jit(
                lambda p, t: lm_loss(p, t, CFG, pmesh, pipeline_microbatches=4)
            )(params, tokens))
        np.testing.assert_allclose(got, want, rtol=2e-4)

    def test_moe_requires_gspmd_trunk(self):
        cfg = TransformerConfig(n_experts=4, n_layers=2)
        mesh = build_mesh(MeshSpec(pp=2, dp=4))
        params = jax.eval_shape(lambda k: init_params(k, cfg), jax.random.key(0))
        with pytest.raises(ValueError, match="GSPMD"):
            from tony_tpu.models.transformer import forward_pipeline
            forward_pipeline(
                params, jnp.zeros((4, 8), jnp.int32), cfg, mesh,
                num_microbatches=2,
            )


class TestMnist:
    def test_mnist_cnn_learns(self):
        mesh = build_mesh(MeshSpec(dp=8))
        cfg = MnistConfig(arch="cnn", dtype="float32")
        init_fn, step_fn = make_classifier_step(cfg, mesh, learning_rate=2e-3)
        rng = np.random.default_rng(0)
        # Separable synthetic task: class = brightest quadrant band
        images = jnp.asarray(rng.normal(size=(64, 28, 28, 1)), jnp.float32)
        labels = jnp.asarray(
            (np.asarray(images).reshape(64, -1).mean(-1) > 0).astype(np.int32)
        )
        with jax.sharding.set_mesh(mesh):
            state = init_fn(jax.random.key(0))
            losses = []
            for _ in range(5):
                state, m = step_fn(state, images, labels)
                losses.append(float(m["loss"]))
        assert losses[-1] < losses[0]

    def test_mnist_mlp_shapes(self):
        from tony_tpu.models import mnist_apply, mnist_init
        cfg = MnistConfig(arch="mlp", dtype="float32")
        params = mnist_init(jax.random.key(0), cfg)
        logits = mnist_apply(params, jnp.zeros((4, 784)), cfg)
        assert logits.shape == (4, 10)


class TestResNet:
    def _tiny(self):
        from tony_tpu.models import ResNetConfig

        return ResNetConfig(depth=18, width=8, n_classes=10, dtype="float32")

    def test_forward_shapes_and_dtype(self):
        from tony_tpu.models import resnet_apply, resnet_init

        cfg = self._tiny()
        params = resnet_init(jax.random.key(0), cfg)
        x = jnp.ones((2, 32, 32, 3))
        logits = resnet_apply(params, x, cfg)
        assert logits.shape == (2, 10) and logits.dtype == jnp.float32
        assert np.isfinite(np.asarray(logits)).all()

    def test_resnet50_param_count(self):
        from tony_tpu.models import ResNetConfig, resnet_init

        cfg = ResNetConfig(depth=50, width=64, n_classes=1000)
        params = resnet_init(jax.random.key(0), cfg)
        n = sum(x.size for x in jax.tree.leaves(params))
        # canonical ResNet-50 is ~25.6M; GroupNorm keeps the same
        # scale/bias counts as BN's affine params
        assert 24e6 < n < 27e6, n

    def test_unsupported_depth_rejected(self):
        from tony_tpu.models import ResNetConfig

        with pytest.raises(ValueError, match="unsupported depth"):
            ResNetConfig(depth=42).plan

    def test_loss_descends_data_parallel(self):
        from tony_tpu.models import make_image_classifier_step, resnet_apply, resnet_init
        from tony_tpu.parallel.mesh import MeshSpec, build_mesh

        cfg = self._tiny()
        mesh = build_mesh(MeshSpec(dp=8))
        init_fn, step_fn = make_image_classifier_step(
            lambda key: resnet_init(key, cfg),
            lambda params, images: resnet_apply(params, images, cfg),
            mesh,
            learning_rate=5e-3,
        )
        rng = np.random.default_rng(0)
        labels = jnp.asarray(rng.integers(0, 10, (16,)), jnp.int32)
        images = jnp.asarray(
            rng.normal(size=(16, 32, 32, 3))
            + np.asarray(labels)[:, None, None, None] * 0.3,
            jnp.float32,
        )
        with jax.sharding.set_mesh(mesh):
            state = init_fn(jax.random.key(1))
            first = None
            for _ in range(8):
                state, metrics = step_fn(state, images, labels)
                first = first if first is not None else float(metrics["loss"])
            last = float(metrics["loss"])
        assert np.isfinite(last) and last < first


class TestDecode:
    """KV-cache decoding pinned to the training forward — the cached path
    must produce the same distribution the trunk was trained with."""

    def _setup(self):
        from tony_tpu.models import TransformerConfig, init_params

        cfg = TransformerConfig(
            vocab_size=64, d_model=32, n_layers=2, n_heads=2, head_dim=16,
            d_ff=64, max_seq=64, dtype="float32", remat=False,
        )
        params = init_params(jax.random.key(0), cfg)
        return cfg, params

    def test_prefill_matches_training_forward(self):
        from tony_tpu.models import advance, forward, init_cache

        cfg, params = self._setup()
        tokens = jnp.asarray(
            np.random.default_rng(0).integers(0, 64, (2, 12)), jnp.int32
        )
        cache = init_cache(cfg, 2, 32)
        logits, cache = advance(params, cache, tokens, cfg)
        from tony_tpu.parallel.mesh import MeshSpec, build_mesh

        mesh = build_mesh(MeshSpec(), devices=jax.devices()[:1])
        with jax.sharding.set_mesh(mesh):
            full = forward(params, tokens, cfg, mesh)[:, -1].astype(
                jnp.float32
            )
        np.testing.assert_allclose(
            np.asarray(logits), np.asarray(full), atol=2e-4
        )
        assert int(cache["length"]) == 12

    def test_stepwise_decode_matches_full_recompute(self):
        """Greedy generation with the cache must emit the same tokens as
        re-running the full forward on the growing context each step."""
        from tony_tpu.models import forward, generate

        cfg, params = self._setup()
        prompt = jnp.asarray(
            np.random.default_rng(1).integers(0, 64, (2, 6)), jnp.int32
        )
        got = generate(params, prompt, cfg, max_new_tokens=5)
        # reference: uncached greedy loop
        from tony_tpu.parallel.mesh import MeshSpec, build_mesh

        mesh = build_mesh(MeshSpec(), devices=jax.devices()[:1])
        ctx = prompt
        want = []
        with jax.sharding.set_mesh(mesh):
            for _ in range(5):
                logits = forward(params, ctx, cfg, mesh)[:, -1]
                tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                want.append(tok)
                ctx = jnp.concatenate([ctx, tok[:, None]], axis=1)
        want = jnp.stack(want, axis=1)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    def test_temperature_sampling_varies_with_key(self):
        from tony_tpu.models import generate

        cfg, params = self._setup()
        prompt = jnp.ones((1, 4), jnp.int32)
        a = generate(params, prompt, cfg, 8, temperature=1.0,
                     key=jax.random.key(1))
        b = generate(params, prompt, cfg, 8, temperature=1.0,
                     key=jax.random.key(2))
        assert a.shape == b.shape == (1, 8)
        assert not np.array_equal(np.asarray(a), np.asarray(b))

    def test_moe_decode_rejected(self):
        from tony_tpu.models import TransformerConfig, advance, init_cache, init_params
        import pytest

        cfg = TransformerConfig(
            vocab_size=64, d_model=32, n_layers=2, n_heads=2, head_dim=16,
            d_ff=64, max_seq=32, dtype="float32", n_experts=4,
        )
        params = init_params(jax.random.key(0), cfg)
        with pytest.raises(NotImplementedError):
            advance(params, init_cache(cfg, 1, 8),
                    jnp.ones((1, 4), jnp.int32), cfg)

    def test_overflow_and_key_guards(self):
        from tony_tpu.models import generate
        import pytest

        cfg, params = self._setup()
        prompt = jnp.ones((1, 60), jnp.int32)
        with pytest.raises(ValueError, match="max_seq"):
            generate(params, prompt, cfg, max_new_tokens=10)  # 70 > 64
        with pytest.raises(ValueError, match="PRNG key"):
            generate(params, jnp.ones((1, 4), jnp.int32), cfg, 4,
                     temperature=1.0)

    def test_cumulative_cache_overflow_rejected_eagerly(self):
        from tony_tpu.models import advance, init_cache
        import pytest

        cfg, params = self._setup()
        cache = init_cache(cfg, 1, 16)
        _, cache = advance(params, cache,
                           jnp.ones((1, 10), jnp.int32), cfg)
        with pytest.raises(ValueError, match="cannot take"):
            advance(params, cache, jnp.ones((1, 10), jnp.int32), cfg)

    def test_checked_overflow_caught_under_jit(self):
        """checked=True + checkify turns a traced-length cache overflow into
        a runtime error instead of a clamped, silently-corrupting update."""
        from jax.experimental import checkify

        from tony_tpu.models import advance, init_cache

        cfg, params = self._setup()

        @jax.jit
        def two_steps(params, tokens):
            cache = init_cache(cfg, 1, 16)
            err1, (_, cache) = checkify.checkify(
                lambda: advance(params, cache, tokens, cfg, checked=True)
            )()
            err2, _ = checkify.checkify(
                lambda: advance(params, cache, tokens, cfg, checked=True)
            )()
            return err1, err2

        err1, err2 = two_steps(params, jnp.ones((1, 10), jnp.int32))
        err1.throw()  # 10 <= 16: fine
        import pytest

        with pytest.raises(Exception, match="KV cache overflow"):
            err2.throw()  # 20 > 16
