"""Multi-tenant scheduler + warm slice pool (tony_tpu/scheduler/):
queue ordering / quota units, pool lease-release-expiry units, staging
dedup, the leased (external-slice) backend mode, and mini-cluster e2e —
two sequential jobs sharing one warm slice, and a high-priority submit
preempting a low-priority job that later resumes from its checkpoint
step via TONY_RESUME_STEP."""

import json
import sys
import time
import urllib.request
from pathlib import Path

import pytest

from tony_tpu.conf import keys
from tony_tpu.conf.configuration import TonyConfiguration
from tony_tpu.mini import MiniTonyCluster
from tony_tpu.observability.metrics import MetricsRegistry
from tony_tpu.scheduler import (
    JobQueue,
    JobState,
    SchedJob,
    SchedulerDaemon,
    SlicePool,
    SliceState,
    TenantQuotas,
)
from tony_tpu.scheduler.pool import (
    BOOTSTRAP_MARKER,
    COLD_PROVISIONS_COUNTER,
    LEASE_EXPIRED_COUNTER,
    WARM_HITS_COUNTER,
)

FIXTURES = Path(__file__).resolve().parent / "fixtures"


def _job(job_id: str, priority: int = 0, tenant: str = "default") -> SchedJob:
    return SchedJob(job_id=job_id, conf=TonyConfiguration(), app_dir="/x",
                    priority=priority, tenant=tenant)


# ---------------------------------------------------------------------------
# Queue ordering + quotas
# ---------------------------------------------------------------------------
class TestJobQueue:
    def test_priority_order_fifo_within_band(self):
        q = JobQueue()
        q.submit(_job("a", priority=0))
        q.submit(_job("b", priority=5))
        q.submit(_job("c", priority=5))
        q.submit(_job("d", priority=1))
        order = [q.pop_next().job_id for _ in range(4)]
        assert order == ["b", "c", "d", "a"]

    def test_popped_job_is_launching(self):
        q = JobQueue()
        q.submit(_job("a"))
        assert q.pop_next().state is JobState.LAUNCHING
        assert q.pop_next() is None

    def test_requeue_keeps_original_seq(self):
        """A preempted job re-enters at the HEAD of its priority band —
        preemption defers it, it must not also send it to the back."""
        q = JobQueue()
        first = q.submit(_job("first", priority=1))
        q.submit(_job("second", priority=1))
        popped = q.pop_next()
        assert popped is first
        q.submit(_job("third", priority=1))
        q.requeue(first)  # preempted
        assert [j.job_id for j in q.queued()] == ["first", "second", "third"]

    def test_quota_skips_tenant_at_limit(self):
        q = JobQueue(TenantQuotas(default=1))
        q.submit(_job("a1", tenant="alice"))
        q.submit(_job("b1", tenant="bob"))
        # alice already runs one job: her queued job is skipped, bob pops.
        job = q.pop_next(running_per_tenant={"alice": 1})
        assert job.job_id == "b1"
        # both at quota: nothing eligible.
        assert q.pop_next(running_per_tenant={"alice": 1, "bob": 1}) is None
        # alice freed: her job pops.
        assert q.pop_next(running_per_tenant={}).job_id == "a1"

    def test_quota_overrides_and_parse(self):
        conf = TonyConfiguration()
        conf.set(keys.K_SCHED_TENANT_QUOTA, 1)
        conf.set(keys.K_SCHED_TENANT_QUOTAS, "alice=3, bob=0")
        quotas = TenantQuotas.from_conf(conf)
        assert quotas.limit("alice") == 3
        assert quotas.limit("carol") == 1
        assert quotas.admits("alice", 2)
        assert not quotas.admits("carol", 1)
        assert quotas.admits("bob", 99)  # 0 = unlimited

    def test_bad_quota_string_raises(self):
        conf = TonyConfiguration()
        conf.set(keys.K_SCHED_TENANT_QUOTAS, "alice=lots")
        with pytest.raises(ValueError, match="tenant=N"):
            TenantQuotas.from_conf(conf)

    def test_remove_queued(self):
        q = JobQueue()
        q.submit(_job("a"))
        assert q.remove("a").job_id == "a"
        assert q.remove("a") is None
        assert q.depth() == 0


# ---------------------------------------------------------------------------
# Slice pool
# ---------------------------------------------------------------------------
class _Clock:
    def __init__(self):
        self.now = 1_000_000

    def __call__(self):
        return self.now


def _pool(tmp_path, **kw) -> tuple[SlicePool, _Clock]:
    clock = _Clock()
    kw.setdefault("max_slices", 2)
    kw.setdefault("lease_timeout_ms", 1000)
    kw.setdefault("idle_timeout_ms", 0)
    pool = SlicePool(tmp_path / "slices", registry=MetricsRegistry(),
                     clock_ms=clock, **kw)
    return pool, clock


class TestSlicePool:
    def test_cold_then_warm_reuse(self, tmp_path):
        pool, _ = _pool(tmp_path)
        lease1 = pool.lease("local", "job1")
        assert not lease1.warm
        s = lease1.slice
        assert (s.workspace / BOOTSTRAP_MARKER).is_file()
        assert s.compile_cache_dir.is_dir()
        # The warm payload: whatever a job leaves in the workspace (venv
        # blobs, xla cache entries) survives release → next lease.
        (s.compile_cache_dir / "entry").write_text("compiled")
        pool.release(s.slice_id)
        lease2 = pool.lease("local", "job2")
        assert lease2.warm
        assert lease2.slice.slice_id == s.slice_id
        assert lease2.slice.jobs_served == 2
        assert (lease2.slice.compile_cache_dir / "entry").read_text() \
            == "compiled"
        snap = pool.registry.snapshot()["counters"]
        assert snap[WARM_HITS_COUNTER] == 1
        assert snap[COLD_PROVISIONS_COUNTER] == 1

    def test_profile_mismatch_provisions_new(self, tmp_path):
        pool, _ = _pool(tmp_path)
        a = pool.lease("v5litepod-16x1", "job1")
        pool.release(a.slice.slice_id)
        b = pool.lease("v5litepod-32x1", "job2")
        assert not b.warm
        assert b.slice.slice_id != a.slice.slice_id

    def test_capacity_cap_returns_none(self, tmp_path):
        pool, _ = _pool(tmp_path, max_slices=1)
        assert pool.lease("local", "job1") is not None
        assert pool.lease("local", "job2") is None

    def test_full_pool_evicts_idle_mismatched_profile(self, tmp_path):
        """A pool full of FREE slices of the WRONG profile must not
        starve a new-profile job: the LRU idle slice is evicted to make
        headroom. Leased slices are never evicted."""
        pool, _ = _pool(tmp_path, max_slices=1)
        a = pool.lease("profA", "job1")
        pool.release(a.slice.slice_id)
        b = pool.lease("profB", "job2")
        assert b is not None and not b.warm
        assert pool.get(a.slice.slice_id) is None
        assert not a.slice.workspace.exists()
        # Pool full of LEASED capacity: nothing evictable.
        assert pool.lease("profC", "job3") is None

    def test_lease_expiry_retires_slice(self, tmp_path):
        pool, clock = _pool(tmp_path, lease_timeout_ms=1000)
        s = pool.lease("local", "job1").slice
        clock.now += 500
        assert pool.expire_leases() == []
        clock.now += 501
        expired = pool.expire_leases()
        assert [e.slice_id for e in expired] == [s.slice_id]
        # Retired, torn down, and NOT warm-reusable.
        assert not s.workspace.exists()
        assert pool.get(s.slice_id) is None
        assert pool.registry.snapshot()["counters"][
            LEASE_EXPIRED_COUNTER] == 1

    def test_renew_extends_lease(self, tmp_path):
        pool, clock = _pool(tmp_path, lease_timeout_ms=1000)
        s = pool.lease("local", "job1").slice
        clock.now += 900
        pool.renew(s.slice_id)
        clock.now += 900
        assert pool.expire_leases() == []

    def test_unhealthy_release_retires(self, tmp_path):
        pool, _ = _pool(tmp_path)
        s = pool.lease("local", "job1").slice
        pool.release(s.slice_id, healthy=False)
        assert pool.get(s.slice_id) is None
        assert not s.workspace.exists()

    def test_idle_reap(self, tmp_path):
        pool, clock = _pool(tmp_path, idle_timeout_ms=5000)
        s = pool.lease("local", "job1").slice
        pool.release(s.slice_id)
        clock.now += 4000
        assert pool.reap_idle() == []
        clock.now += 1001
        assert [r.slice_id for r in pool.reap_idle()] == [s.slice_id]

    def test_expired_capacity_is_freed(self, tmp_path):
        pool, clock = _pool(tmp_path, max_slices=1, lease_timeout_ms=100)
        pool.lease("local", "job1")
        assert pool.lease("local", "job2") is None
        clock.now += 101
        pool.expire_leases()
        assert pool.lease("local", "job2") is not None


# ---------------------------------------------------------------------------
# Leased (external-slice) backend mode
# ---------------------------------------------------------------------------
class _LeaseFakeApi:
    """Minimal TpuApi fake: slices READY immediately, executors exit 0
    on their first status poll."""

    def __init__(self):
        self.created: dict[str, tuple[str, int]] = {}
        self.deleted: list[str] = []
        self.started: list[tuple[str, int]] = []

    def create_slice(self, name, accelerator_type, num_slices):
        self.created[name] = (accelerator_type, num_slices)

    def slice_state(self, name):
        return "READY"

    def start_executor(self, name, host_index, env):
        self.started.append((name, host_index))
        return {"name": name}

    def executor_status(self, handle):
        return 0

    def kill_executor(self, handle):
        pass

    def delete_slice(self, name):
        self.deleted.append(name)


def test_tpu_provisioner_speaks_daemon_profiles(tmp_path):
    """The pool's TPU seam end to end against a fake control plane: the
    daemon-format profile ('job=accelxN,...') provisions one slice
    group per job type, external_slices() yields the leased-backend
    mapping, and teardown deletes every group."""
    from tony_tpu.scheduler import TpuSliceProvisioner

    api = _LeaseFakeApi()
    prov = TpuSliceProvisioner(api, poll_interval_s=0.01)
    profile = "ps=v4-8x1,worker=v5litepod-16x2"
    assert TpuSliceProvisioner.parse_profile(profile) == {
        "ps": ("v4-8", 1), "worker": ("v5litepod-16", 2),
    }
    ws = tmp_path / "ws"
    prov.provision("slice-abc", profile, ws)
    assert api.created == {
        "slice-abc-ps": ("v4-8", 1),
        "slice-abc-worker": ("v5litepod-16", 2),
    }
    assert (ws / BOOTSTRAP_MARKER).is_file()
    from tony_tpu.scheduler.pool import PooledSlice

    pooled = PooledSlice("slice-abc", profile, ws)
    assert TpuSliceProvisioner.external_slices(pooled) == {
        "ps": "slice-abc-ps", "worker": "slice-abc-worker",
    }
    prov.teardown("slice-abc", profile, ws)
    assert sorted(api.deleted) == ["slice-abc-ps", "slice-abc-worker"]
    with pytest.raises(ValueError, match="job=accelerator_type"):
        TpuSliceProvisioner.parse_profile("local")


def test_tpu_backend_external_slices_not_created_or_deleted(tmp_path):
    from tony_tpu.coordinator.backend import TpuVmBackend, plan_slices
    from tony_tpu.coordinator.session import TonyTask

    api = _LeaseFakeApi()
    backend = TpuVmBackend(api, "app1",
                           external_slices={"worker": "pool-slice-7"})
    backend.prepare_slices({"worker": plan_slices(4, 4, "v5e")})
    task = TonyTask("worker", 0, 1)
    handle = backend.launch(task, {"E": "1"})
    # No create: the pool owns the slice; poll starts the executor on it.
    assert api.created == {}
    assert backend.poll(handle) is None
    assert api.started == [("pool-slice-7", 0)]
    assert backend.poll(handle) == 0
    backend.stop_all()
    assert api.deleted == []  # release, not teardown


# ---------------------------------------------------------------------------
# Content-hash staging dedup (client._stage)
# ---------------------------------------------------------------------------
def test_staging_dedup_second_submit_skips_copy(tmp_path):
    from tony_tpu.client.client import STAGING_DEDUP_COUNTER, TonyClient
    from tony_tpu.observability.metrics import default_registry

    venv = tmp_path / "env.zip"
    venv.write_bytes(b"PK\x05\x06" + bytes(18))  # minimal empty zip
    staging = tmp_path / "staging"

    def stage():
        client = TonyClient().init([
            "--python_venv", str(venv),
            "--conf", f"{keys.K_STAGING_LOCATION}={staging}",
        ])
        app_dir = client._stage()
        return client, app_dir

    before = default_registry().snapshot()["counters"].get(
        STAGING_DEDUP_COUNTER, 0)
    c1, app1 = stage()
    c2, app2 = stage()
    blob1 = Path(c1.conf.get_str(keys.K_PYTHON_VENV))
    blob2 = Path(c2.conf.get_str(keys.K_PYTHON_VENV))
    # One blob, content-addressed, shared by both frozen confs.
    assert blob1 == blob2
    assert blob1.is_file() and blob1.parent.parent.name == "blobs"
    assert len(list((staging / "blobs").rglob("*.zip"))) == 1
    # No per-app copy in either app dir.
    assert not (app1 / "env.zip").exists()
    assert not (app2 / "env.zip").exists()
    after = default_registry().snapshot()["counters"][STAGING_DEDUP_COUNTER]
    assert after == before + 1

    # A DIFFERENT venv gets its own blob (no false dedup).
    venv.write_bytes(b"PK\x05\x06" + bytes(17) + b"x")
    _, _ = stage()
    assert len(list((staging / "blobs").rglob("*.zip"))) == 2


def test_blob_store_prune_lru_spares_current_blob(tmp_path):
    import os
    import time as _time

    from tony_tpu.client.client import prune_blob_store, stage_blob

    blob_root = tmp_path / "blobs"
    blobs = []
    for i in range(3):
        src = tmp_path / f"v{i}.zip"
        src.write_bytes(bytes(100))
        # Distinct content => distinct blobs; distinct mtimes => LRU order.
        src.write_bytes(bytes(99) + bytes([i]))
        blob, _ = stage_blob(src, blob_root)
        os.utime(blob, (1000.0 + i, 1000.0 + i))
        blobs.append(blob)
    # Cap at 2 blobs' worth: the oldest goes, the excluded current blob
    # survives even if the cap is tighter than its size.
    assert prune_blob_store(blob_root, 200) == 1
    assert not blobs[0].exists() and blobs[1].exists() and blobs[2].exists()
    assert prune_blob_store(blob_root, 50, exclude=blobs[2]) == 1
    assert blobs[2].exists() and not blobs[1].exists()
    # A dedup hit refreshes the LRU stamp.
    src = tmp_path / "v2.zip"
    old = blobs[2].stat().st_mtime
    _time.sleep(0.01)
    _, hit = stage_blob(src, blob_root)
    assert hit and blobs[2].stat().st_mtime > old


# ---------------------------------------------------------------------------
# Daemon e2e on the mini cluster (jax-free fixtures)
# ---------------------------------------------------------------------------
@pytest.fixture()
def cluster(tmp_path):
    with MiniTonyCluster(tmp_path) as c:
        yield c


def _sched_conf(cluster, **kv):
    conf = cluster.base_conf()
    conf.set(keys.K_SCHED_TICK_MS, 50)
    for k, v in kv.items():
        conf.set(k, v)
    return conf


def _job_conf(cluster, fixture, **kv):
    conf = cluster.base_conf()
    conf.set(keys.K_EXECUTES, str(FIXTURES / fixture))
    conf.set(keys.K_PYTHON_BINARY, sys.executable)
    conf.set(keys.instances_key("worker"), 1)
    conf.set(keys.instances_key("ps"), 0)
    for k, v in kv.items():
        conf.set(k, v)
    return conf


def _events(daemon, kind):
    return [e for e in daemon.events.to_dicts() if e["kind"] == kind]


def test_two_sequential_jobs_share_warm_slice(cluster):
    """The warm-reuse acceptance shape, jax-free: job 2 skips
    provisioning (one cold provision total, warm hit counted, same
    slice serves both) and the published state file + events say so."""
    daemon = cluster.start_scheduler(
        _sched_conf(cluster, **{keys.K_SCHED_MAX_SLICES: 1}),
        serve_http=False,
    )
    j1 = daemon.submit(_job_conf(cluster, "exit_0.py"))
    assert daemon.wait_job(j1, 60) is JobState.SUCCEEDED
    j2 = daemon.submit(_job_conf(cluster, "exit_0.py"))
    assert daemon.wait_job(j2, 60) is JobState.SUCCEEDED

    snap = daemon.registry.snapshot()["counters"]
    assert snap[COLD_PROVISIONS_COUNTER] == 1  # provisioning skipped for j2
    assert snap[WARM_HITS_COUNTER] == 1
    launches = _events(daemon, "job_launched")
    assert [e["warm"] for e in launches] == [False, True]
    assert len({e["slice_id"] for e in launches}) == 1
    slices = daemon.pool.slices()
    assert len(slices) == 1 and slices[0].jobs_served == 2
    assert slices[0].state is SliceState.FREE

    # The state file is published just AFTER completion is signalled —
    # poll briefly for it to catch up.
    deadline = time.monotonic() + 5
    while True:
        state = json.loads(
            (daemon.base_dir / "scheduler-state.json").read_text()
        )
        if {j["state"] for j in state["jobs"]} == {"SUCCEEDED"}:
            break
        assert time.monotonic() < deadline, state
        time.sleep(0.05)
    assert state["queue_depth"] == 0


def test_failed_job_still_releases_slice_warm(cluster):
    daemon = cluster.start_scheduler(
        _sched_conf(cluster, **{keys.K_SCHED_MAX_SLICES: 1}),
        serve_http=False,
    )
    j1 = daemon.submit(_job_conf(cluster, "exit_1.py"))
    assert daemon.wait_job(j1, 60) is JobState.FAILED
    j2 = daemon.submit(_job_conf(cluster, "exit_0.py"))
    assert daemon.wait_job(j2, 60) is JobState.SUCCEEDED
    assert daemon.registry.snapshot()["counters"][WARM_HITS_COUNTER] == 1


def _fabricate_checkpoint(ckpt_dir: Path, step: int) -> None:
    """A complete CheckpointManager step (commit marker + the one
    process shard) the scheduler's resume probe will find."""
    d = ckpt_dir / f"step_{step}"
    d.mkdir(parents=True)
    (d / "metadata.json").write_text(
        json.dumps({"step": step, "num_processes": 1})
    )
    (d / "process_0.npz").write_bytes(b"shard")


def test_preemption_requeues_and_resumes_from_checkpoint(cluster, tmp_path):
    """High-priority submit preempts the running low-priority job; the
    victim requeues at the head of its band and its relaunch resumes
    from the probed checkpoint step (TONY_RESUME_STEP seeded for the
    FIRST session of the new coordinator)."""
    daemon = cluster.start_scheduler(
        _sched_conf(cluster, **{keys.K_SCHED_MAX_SLICES: 1}),
        serve_http=False,
    )
    marker = tmp_path / "marker.txt"
    ckpt = tmp_path / "ckpt"
    _fabricate_checkpoint(ckpt, 7)
    low = daemon.submit(_job_conf(
        cluster, "preemptible.py",
        **{keys.K_SHELL_ENV: f"MARKER_OUT={marker}",
           keys.K_SCHED_PRIORITY: 0,
           keys.K_CHECKPOINT_LOCATION: str(ckpt),
           # This test pins the requeue/resume mechanics; the fixture
           # never checkpoints, so live migration's flush wait would
           # only run out its deadline. The migration path has its own
           # e2e in test_checkpoint.py.
           keys.K_CKPT_MIGRATE_ON_PREEMPT: False},
    ))
    # Wait until the low-pri worker actually runs (its marker appears).
    deadline = time.monotonic() + 60
    while not marker.exists():
        assert time.monotonic() < deadline, "low-pri job never started"
        time.sleep(0.1)
    hi = daemon.submit(_job_conf(
        cluster, "exit_0.py", **{keys.K_SCHED_PRIORITY: 10},
    ))
    assert daemon.wait_job(hi, 90) is JobState.SUCCEEDED
    assert daemon.wait_job(low, 90) is JobState.SUCCEEDED

    job = daemon.job(low)
    assert job.preemptions == 1
    assert job.attempts == 2
    assert job.resume_step == 7
    preempt_events = _events(daemon, "job_preempted")
    assert len(preempt_events) == 1
    assert preempt_events[0]["resume_step"] == 7
    # The fixture saw no resume on attempt 1, step 7 on attempt 2.
    assert marker.read_text().splitlines() == ["resume=None", "resume=7"]
    assert daemon.registry.snapshot()["counters"][
        "tony_sched_preemptions_total"] == 1


def test_kill_queued_and_running_jobs(cluster, tmp_path):
    daemon = cluster.start_scheduler(
        _sched_conf(cluster, **{keys.K_SCHED_MAX_SLICES: 1}),
        serve_http=False,
    )
    marker = tmp_path / "m.txt"
    running = daemon.submit(_job_conf(
        cluster, "preemptible.py",
        **{keys.K_SHELL_ENV: f"MARKER_OUT={marker}"},
    ))
    deadline = time.monotonic() + 60
    while not marker.exists():
        assert time.monotonic() < deadline
        time.sleep(0.1)
    queued = daemon.submit(_job_conf(cluster, "exit_0.py"))
    assert daemon.kill(queued)
    assert daemon.job(queued).state is JobState.KILLED
    assert daemon.kill(running)
    assert daemon.wait_job(running, 60) is JobState.KILLED
    assert not daemon.kill(running)  # already terminal


# ---------------------------------------------------------------------------
# HTTP API + thin client + CLI + history panel
# ---------------------------------------------------------------------------
def test_scheduler_api_client_submit_and_cli_tables(cluster, capsys):
    """The whole thin-submit loop: TonyClient in scheduler mode stages
    and POSTs the app dir, monitors via the job API; `tony ps` and
    `tony queue` read the live API, then fall back to the state file
    once the daemon is gone."""
    daemon = cluster.start_scheduler(_sched_conf(cluster))
    addr = (daemon.base_dir / "scheduler.addr").read_text().strip()

    from tony_tpu.client.client import TonyClient

    client = TonyClient().init([
        "--executes", str(FIXTURES / "exit_0.py"),
        "--python_binary_path", sys.executable,
        "--conf", f"{keys.K_STAGING_LOCATION}={cluster.staging_dir}",
        "--conf", f"{keys.K_HISTORY_LOCATION}={cluster.history_dir}",
        "--conf", f"{keys.K_SCHED_ADDRESS}={addr}",
        "--conf", f"{keys.instances_key('ps')}=0",
    ])
    assert client.run() == 0
    assert client.job_id is not None
    job = daemon.job(client.job_id)
    assert job is not None and job.state is JobState.SUCCEEDED
    # The staged app dir (client-side) is what ran.
    assert Path(job.app_dir) == client.app_dir

    with urllib.request.urlopen(
        f"http://{addr}/api/state", timeout=5
    ) as resp:
        state = json.loads(resp.read())
    assert state["jobs"][0]["job_id"] == client.job_id
    with urllib.request.urlopen(f"http://{addr}/metrics", timeout=5) as r:
        prom = r.read().decode()
    assert "tony_sched_jobs_submitted_total 1" in prom

    from tony_tpu.client.cli import ps_cmd, queue_cmd

    assert ps_cmd(["--scheduler", addr]) == 0
    out = capsys.readouterr().out
    assert client.job_id in out and "SUCCEEDED" in out
    assert queue_cmd(["--scheduler", addr]) == 0
    out = capsys.readouterr().out
    assert "pool" in out

    # Daemon gone -> state-file fallback through --scheduler-dir.
    base_dir = str(daemon.base_dir)
    cluster.shutdown()
    assert ps_cmd(["--scheduler-dir", base_dir]) == 0
    out = capsys.readouterr().out
    assert "state-file" in out and client.job_id in out


def test_history_server_scheduler_panel(cluster):
    daemon = cluster.start_scheduler(_sched_conf(cluster),
                                     serve_http=False)
    j = daemon.submit(_job_conf(cluster, "exit_0.py"))
    assert daemon.wait_job(j, 60) is JobState.SUCCEEDED

    from tony_tpu.history.server import HistoryServer

    server = HistoryServer(str(cluster.history_dir),
                           scheduler_dir=str(daemon.base_dir))
    port = server.serve_background()
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/api/scheduler", timeout=5
        ) as resp:
            state = json.loads(resp.read())
        assert state["jobs"][0]["job_id"] == j
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/scheduler", timeout=5
        ) as resp:
            page = resp.read().decode()
        assert j in page and "Slice pool" in page
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/", timeout=5
        ) as resp:
            assert "/scheduler" in resp.read().decode()
    finally:
        server.stop()


# ---------------------------------------------------------------------------
# The full warm-reuse acceptance e2e (jax in executors: compile cache)
# ---------------------------------------------------------------------------
@pytest.mark.slow
def test_warm_pool_second_job_skips_provisioning_staging_and_compiles_warm(
    cluster, tmp_path,
):
    """Acceptance: two sequential identical jobs through the scheduler
    share a pooled slice; the second proves (a) provisioning skipped,
    (b) staging dedup hit for its venv archive, and (c) compile-cache
    hits > 0 with misses == 0 — the daemon pinned the slice's
    pool-owned cache dir into the frozen conf, the executor exported
    TONY_COMPILE_*, and runtime.initialize() wired jax."""
    import zipfile

    from tony_tpu.client.client import STAGING_DEDUP_COUNTER, TonyClient
    from tony_tpu.observability.metrics import default_registry

    daemon = cluster.start_scheduler(
        _sched_conf(cluster, **{keys.K_SCHED_MAX_SLICES: 1})
    )
    addr = (daemon.base_dir / "scheduler.addr").read_text().strip()
    probe_out = tmp_path / "probe.jsonl"
    venv = tmp_path / "env.zip"
    with zipfile.ZipFile(venv, "w") as z:
        z.writestr("payload.txt", "venv-shaped artifact, no bin/python")

    def submit() -> str:
        client = TonyClient().init([
            "--executes", str(FIXTURES / "compile_cache_probe.py"),
            "--python_binary_path", sys.executable,
            "--python_venv", str(venv),
            "--shell_env", f"PROBE_OUT={probe_out}",
            "--shell_env", "JAX_PLATFORMS=cpu",
            "--conf", f"{keys.K_STAGING_LOCATION}={cluster.staging_dir}",
            "--conf", f"{keys.K_SCHED_ADDRESS}={addr}",
            "--conf", f"{keys.instances_key('ps')}=0",
        ])
        assert client.submit() == 0
        return client.job_id

    dedup0 = default_registry().snapshot()["counters"].get(
        STAGING_DEDUP_COUNTER, 0)
    j1 = submit()
    assert daemon.wait_job(j1, 300) is JobState.SUCCEEDED
    j2 = submit()
    assert daemon.wait_job(j2, 300) is JobState.SUCCEEDED

    # (a) provisioning skipped: one cold provision, one warm hit.
    snap = daemon.registry.snapshot()["counters"]
    assert snap[COLD_PROVISIONS_COUNTER] == 1
    assert snap[WARM_HITS_COUNTER] == 1
    # (b) staging dedup: the second client submit found the venv blob.
    dedup1 = default_registry().snapshot()["counters"][
        STAGING_DEDUP_COUNTER]
    assert dedup1 == dedup0 + 1
    # (c) warm compiles: cold run all misses, warm run hits only.
    lines = [json.loads(line)
             for line in probe_out.read_text().splitlines()]
    assert len(lines) == 2
    cold, warm = lines
    assert cold["tony_compile_cache_misses_total"] == 2  # init + step
    assert cold.get("tony_compile_cache_hits_total", 0) == 0
    assert warm["tony_compile_cache_hits_total"] == 2
    assert warm.get("tony_compile_cache_misses_total", 0) == 0
