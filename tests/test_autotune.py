"""Measured autotuner (parallel/autotune.py): record persistence
degrade-to-miss semantics (corrupt / torn / concurrent / version-bump),
the shared search loop (default-first convention, trial budget, warm
reuse with zero trials), knob consumption (`set_tuned_blocks`,
`make_train_step` lookup, stepstats live feedback), the `tony.tune.*`
config-check rules (TONY-C002 enum, min-one budget, TONY-C011 scratch),
the int8 quantized KV cache's greedy parity bound, and the `tony tune`
CLI table."""

import dataclasses
import json
import os
from pathlib import Path

import jax
import numpy as np
import pytest

from tony_tpu.models import TransformerConfig
from tony_tpu.parallel import autotune
from tony_tpu.parallel import plan as plan_lib
from tony_tpu.parallel.mesh import MeshSpec, build_mesh

CFG = TransformerConfig(
    vocab_size=64, d_model=32, n_layers=2, n_heads=2, head_dim=16,
    d_ff=64, max_seq=96, dtype="float32", remat=False,
)


def _record_for(key: str, *, best=None, **extra) -> dict:
    rec = {
        "version": autotune._RECORD_VERSION,
        "key": key,
        "label": "t",
        "best": best if best is not None else {"block_q": 256},
        "best_ms": 1.0,
        "default_ms": 2.0,
        "trials": [{"knobs": {}, "ms": 2.0},
                   {"knobs": {"block_q": 256}, "ms": 1.0}],
    }
    rec.update(extra)
    return rec


# ---------------------------------------------------------------------------
# Record persistence: every failure mode degrades to a miss
# ---------------------------------------------------------------------------


class TestRecordPersistence:
    def test_round_trip(self, tmp_path):
        key = autotune.tune_key("t", config=CFG)
        autotune.save_record(_record_for(key), cache_dir=str(tmp_path))
        rec = autotune.load_record(key, cache_dir=str(tmp_path))
        assert rec is not None
        assert rec["best"] == {"block_q": 256}

    def test_absent_is_miss(self, tmp_path):
        key = autotune.tune_key("t", config=CFG)
        assert autotune.load_record(key, cache_dir=str(tmp_path)) is None

    def test_corrupt_json_is_miss(self, tmp_path):
        key = autotune.tune_key("t", config=CFG)
        autotune.save_record(_record_for(key), cache_dir=str(tmp_path))
        path = Path(autotune._record_path(key, str(tmp_path)))
        path.write_text("{ not json !!")
        assert autotune.load_record(key, cache_dir=str(tmp_path)) is None

    def test_torn_write_is_miss(self, tmp_path):
        key = autotune.tune_key("t", config=CFG)
        autotune.save_record(_record_for(key), cache_dir=str(tmp_path))
        path = Path(autotune._record_path(key, str(tmp_path)))
        path.write_text(path.read_text()[: len(path.read_text()) // 2])
        assert autotune.load_record(key, cache_dir=str(tmp_path)) is None

    def test_key_mismatch_is_miss(self, tmp_path):
        # A record dir moved wholesale across identities: the embedded
        # key disagrees with the filename's — never served.
        key = autotune.tune_key("t", config=CFG)
        autotune.save_record(
            _record_for("0" * len(key)), cache_dir=str(tmp_path)
        )
        os.replace(
            autotune._record_path("0" * len(key), str(tmp_path)),
            autotune._record_path(key, str(tmp_path)),
        )
        assert autotune.load_record(key, cache_dir=str(tmp_path)) is None

    def test_version_bump_is_miss(self, tmp_path):
        key = autotune.tune_key("t", config=CFG)
        autotune.save_record(
            _record_for(key, version=autotune._RECORD_VERSION + 1),
            cache_dir=str(tmp_path),
        )
        assert autotune.load_record(key, cache_dir=str(tmp_path)) is None

    def test_non_dict_best_is_miss(self, tmp_path):
        key = autotune.tune_key("t", config=CFG)
        autotune.save_record(
            _record_for(key, best="fast"), cache_dir=str(tmp_path)
        )
        assert autotune.load_record(key, cache_dir=str(tmp_path)) is None

    def test_jax_version_bump_changes_key(self):
        # The backend fingerprint rides the key, so a jax upgrade (or a
        # different device kind) is a MISS by construction — exactly how
        # plan-measurements.json invalidates.
        base = autotune.tune_key("t", config=CFG)
        bumped = autotune.tune_key(
            "t", config=CFG,
            backend=dict(plan_lib.backend_fingerprint(), jax="99.99.99"),
        )
        assert base != bumped

    def test_concurrent_writers_last_complete_record_wins(self, tmp_path):
        # Two searchers race: each lands a COMPLETE file via tmp+rename;
        # whatever survives is a valid record, and a dead writer's
        # leftover tmp never shadows it.
        key = autotune.tune_key("t", config=CFG)
        autotune.save_record(
            _record_for(key, best={"block_q": 256}), cache_dir=str(tmp_path)
        )
        autotune.save_record(
            _record_for(key, best={"block_q": 512}), cache_dir=str(tmp_path)
        )
        path = autotune._record_path(key, str(tmp_path))
        with open(f"{path}.tmp.99999", "w") as f:
            f.write('{"half": ')  # a crashed writer's torn tmp
        rec = autotune.load_record(key, cache_dir=str(tmp_path))
        assert rec is not None and rec["best"] == {"block_q": 512}
        assert all(
            r["best"] == {"block_q": 512}
            for r in autotune.list_records(str(tmp_path))
        )

    def test_unwritable_dir_degrades_silently(self, tmp_path, monkeypatch):
        blocked = tmp_path / "blocked"
        blocked.write_text("a file, not a dir")
        key = autotune.tune_key("t", config=CFG)
        autotune.save_record(_record_for(key), cache_dir=str(blocked))
        assert autotune.load_record(key, cache_dir=str(blocked)) is None


# ---------------------------------------------------------------------------
# The search loop
# ---------------------------------------------------------------------------


class TestSearch:
    def _measure(self, walls):
        calls = []

        def measure(knobs):
            calls.append(knobs)
            return walls[len(calls) - 1]

        return measure, calls

    def test_default_first_and_best_wins(self, tmp_path):
        cands = [autotune.Knobs(), autotune.Knobs(block_q=256),
                 autotune.Knobs(block_q=512)]
        measure, calls = self._measure([3.0, 1.0, 2.0])
        rec = autotune.search(
            "t", cands, measure, key="k1", cache_dir=str(tmp_path)
        )
        assert calls[0] == autotune.Knobs()
        assert rec["default_ms"] == 3.0
        assert rec["best_ms"] == 1.0
        assert rec["best"]["block_q"] == 256
        assert rec["trials_this_run"] == 3

    def test_trial_budget_caps_measurement(self, tmp_path):
        cands = [autotune.Knobs(block_q=b) for b in (128, 256, 512, 1024)]
        measure, calls = self._measure([4.0, 3.0, 2.0, 1.0])
        rec = autotune.search(
            "t", cands, measure, key="k2", trial_budget=2,
            cache_dir=str(tmp_path),
        )
        assert len(calls) == 2
        assert rec["best"]["block_q"] == 256

    def test_warm_reuse_zero_trials(self, tmp_path):
        cands = [autotune.Knobs(), autotune.Knobs(block_q=256)]
        measure, calls = self._measure([2.0, 1.0])
        autotune.search("t", cands, measure, key="k3",
                        cache_dir=str(tmp_path))
        rec = autotune.search(
            "t", cands, measure, key="k3", cache_dir=str(tmp_path)
        )
        assert rec["trials_this_run"] == 0
        assert len(calls) == 2  # nothing re-measured
        assert rec["best"]["block_q"] == 256

    def test_failed_and_nonfinite_trials_are_data(self, tmp_path):
        def measure(knobs):
            if knobs.block_q == 256:
                raise RuntimeError("pallas says no")
            if knobs.block_q == 512:
                return float("nan")
            return 5.0

        cands = [autotune.Knobs(), autotune.Knobs(block_q=256),
                 autotune.Knobs(block_q=512)]
        rec = autotune.search(
            "t", cands, measure, key="k4", cache_dir=str(tmp_path)
        )
        assert rec["best"] == dataclasses.asdict(autotune.Knobs()) | {
            "xla_flags": []
        }
        errors = [t for t in rec["trials"] if "error" in t]
        assert len(errors) == 2

    def test_all_failed_search_not_persisted(self, tmp_path):
        def measure(knobs):
            raise RuntimeError("no backend")

        rec = autotune.search(
            "t", [autotune.Knobs()], measure, key="k5",
            cache_dir=str(tmp_path),
        )
        assert rec["best_ms"] is None
        assert autotune.load_record("k5", cache_dir=str(tmp_path)) is None

    def test_note_step_time_improves_live_best(self, tmp_path):
        key = autotune.tune_key("lm_train_step", config=CFG)
        autotune.save_record(_record_for(key), cache_dir=str(tmp_path))
        autotune.note_step_time(
            "lm_train_step", config=CFG, step_ms=0.5,
            cache_dir=str(tmp_path),
        )
        rec = autotune.load_record(key, cache_dir=str(tmp_path))
        assert rec["live_best_ms"] == 0.5
        # A worse production step never regresses the record.
        autotune.note_step_time(
            "lm_train_step", config=CFG, step_ms=9.0,
            cache_dir=str(tmp_path),
        )
        rec = autotune.load_record(key, cache_dir=str(tmp_path))
        assert rec["live_best_ms"] == 0.5

    def test_flash_block_candidates_clamped_and_deduped(self):
        cands = autotune.flash_block_candidates(512)
        assert cands[0] == autotune.Knobs()
        sizes = {(k.block_q, k.block_k) for k in cands[1:]}
        assert all(q <= 512 and k <= 512 for q, k in sizes)
        assert len(sizes) == len(cands) - 1


# ---------------------------------------------------------------------------
# Consumption: tuned blocks, make_train_step, DecodeSession
# ---------------------------------------------------------------------------


class TestConsumption:
    def test_set_tuned_blocks_fills_defaults_only(self):
        from tony_tpu.ops import attention as attention_lib

        try:
            attention_lib.set_tuned_blocks(256, 128)
            bq, bk = attention_lib._default_blocks(2048, 2048, None, None)
            assert (bq, bk) == (256, 128)
            # Explicit arguments always win over the tuned pin.
            bq, bk = attention_lib._default_blocks(2048, 2048, 1024, None)
            assert (bq, bk) == (1024, 128)
            # The pin clamps to the sequence like the bucketed default.
            bq, bk = attention_lib._default_blocks(64, 64, None, None)
            assert (bq, bk) == (64, 64)
        finally:
            attention_lib.clear_tuned_blocks()
        assert attention_lib.tuned_blocks() == (None, None)

    def test_make_train_step_consumes_record(self, tmp_path, monkeypatch):
        from tony_tpu import constants
        from tony_tpu.models import make_train_step
        from tony_tpu.ops import attention as attention_lib

        monkeypatch.setenv(constants.TONY_TUNE_RECORD_DIR, str(tmp_path))
        mesh = build_mesh(MeshSpec(dp=1), devices=jax.devices()[:1])
        key = autotune.tune_key("lm_train_step", config=CFG, mesh=mesh)
        autotune.save_record(
            _record_for(key, best={"block_q": 256, "block_k": 128}),
            cache_dir=str(tmp_path),
        )
        try:
            make_train_step(CFG, mesh)
            assert attention_lib.tuned_blocks() == (256, 128)
        finally:
            attention_lib.clear_tuned_blocks()

    def test_make_train_step_disabled_ignores_record(
        self, tmp_path, monkeypatch
    ):
        from tony_tpu import constants
        from tony_tpu.models import make_train_step
        from tony_tpu.ops import attention as attention_lib

        monkeypatch.setenv(constants.TONY_TUNE_RECORD_DIR, str(tmp_path))
        monkeypatch.setenv(constants.TONY_TUNE_ENABLED, "false")
        mesh = build_mesh(MeshSpec(dp=1), devices=jax.devices()[:1])
        key = autotune.tune_key("lm_train_step", config=CFG, mesh=mesh)
        autotune.save_record(
            _record_for(key, best={"block_q": 256}), cache_dir=str(tmp_path)
        )
        try:
            make_train_step(CFG, mesh)
            assert attention_lib.tuned_blocks() == (None, None)
        finally:
            attention_lib.clear_tuned_blocks()

    def test_lookup_counts_hits_and_misses(self, tmp_path):
        from tony_tpu import observability

        reg = observability.default_registry()
        hits0 = reg.counter(autotune.TUNE_RECORD_HITS_COUNTER).value
        misses0 = reg.counter(autotune.TUNE_RECORD_MISSES_COUNTER).value
        assert autotune.lookup(
            "absent", config=CFG, cache_dir=str(tmp_path)
        ) is None
        key = autotune.tune_key("present", config=CFG)
        autotune.save_record(_record_for(key), cache_dir=str(tmp_path))
        knobs = autotune.lookup(
            "present", config=CFG, cache_dir=str(tmp_path)
        )
        assert knobs is not None and knobs.block_q == 256
        assert reg.counter(autotune.TUNE_RECORD_HITS_COUNTER).value \
            == hits0 + 1
        assert reg.counter(autotune.TUNE_RECORD_MISSES_COUNTER).value \
            == misses0 + 1

    def test_apply_xla_flags_appends_once(self, monkeypatch):
        monkeypatch.setenv("XLA_FLAGS", "--xla_existing=1")
        knobs = autotune.Knobs(xla_flags=("--xla_new_thing=true",))
        assert autotune.apply_xla_flags(knobs)
        assert os.environ["XLA_FLAGS"] == \
            "--xla_existing=1 --xla_new_thing=true"
        assert not autotune.apply_xla_flags(knobs)  # already present


# ---------------------------------------------------------------------------
# tony.tune.* config checks (TONY-C002 enum, min-one budget, TONY-C011)
# ---------------------------------------------------------------------------


class TestTuneConfigCheck:
    def _findings(self, rule_id, **overrides):
        from tony_tpu.analysis.config_check import check_config
        from tony_tpu.conf.configuration import TonyConfiguration

        conf = TonyConfiguration()
        for k, v in overrides.items():
            conf.set(k, v)
        return [f for f in check_config(conf) if f.rule_id == rule_id]

    def test_zero_trial_budget_rejected(self):
        from tony_tpu.conf import keys

        found = self._findings(
            "TONY-C002", **{keys.K_TUNE_TRIAL_BUDGET: "0"}
        )
        assert len(found) == 1

    def test_kv_quant_enum(self):
        from tony_tpu.conf import keys

        assert self._findings(
            "TONY-C002", **{keys.K_TUNE_KV_QUANT: "fp4"}
        )
        assert not self._findings(
            "TONY-C002", **{keys.K_TUNE_KV_QUANT: "int8"}
        )

    def test_scratch_record_dir_flagged(self):
        from tony_tpu.conf import keys

        found = self._findings(
            "TONY-C011", **{keys.K_TUNE_RECORD_DIR: "/tmp/tune"}
        )
        assert len(found) == 1
        assert "scratch" in found[0].message

    def test_durable_dir_and_disabled_pass(self):
        from tony_tpu.conf import keys

        assert not self._findings(
            "TONY-C011", **{keys.K_TUNE_RECORD_DIR: "/srv/tony-tune"}
        )
        assert not self._findings("TONY-C011", **{
            keys.K_TUNE_RECORD_DIR: "/tmp/tune",
            keys.K_TUNE_ENABLED: "false",
        })
        assert not self._findings("TONY-C011")  # empty = beside the cache


# ---------------------------------------------------------------------------
# int8 KV cache: layout + greedy parity bound
# ---------------------------------------------------------------------------


class TestInt8KV:
    def _tokens(self, kv_quant):
        from tony_tpu.models import init_params
        from tony_tpu.serving import ServingEngine

        params = init_params(jax.random.key(0), CFG)
        eng = ServingEngine(params, CFG, slots=2, max_len=96,
                            prefill_chunk=8, kv_quant=kv_quant)
        prompt = np.array([3, 7, 11, 19, 5], dtype=np.int32)
        req = eng.submit(prompt, max_new_tokens=24, temperature=0.0)
        for _ in range(400):
            if req.done():
                break
            eng.step()
        out = req.result(timeout=5)
        eng.close()
        return out["tokens"]

    def test_cache_layout_is_int8(self):
        from tony_tpu.models import init_params
        from tony_tpu.serving import ServingEngine
        from tony_tpu.serving.engine import QuantizedKV

        params = init_params(jax.random.key(0), CFG)
        eng = ServingEngine(params, CFG, slots=2, max_len=96,
                            prefill_chunk=8, kv_quant="int8")
        assert isinstance(eng._k, QuantizedKV)
        assert eng._k.data.dtype == np.int8
        assert eng._k.scale.dtype == np.float32
        assert eng._k.scale.shape == eng._k.data.shape[:-1] + (1,)
        assert eng.stats()["kv_quant"] == "int8"
        eng.close()

    def test_bad_mode_rejected(self):
        from tony_tpu.models import init_params
        from tony_tpu.serving import ServingEngine

        params = init_params(jax.random.key(0), CFG)
        with pytest.raises(ValueError, match="kv_quant"):
            ServingEngine(params, CFG, slots=2, kv_quant="fp4")

    def test_greedy_parity_bound(self):
        # The tolerance this repo pins: on a random-weight (worst-case:
        # near-uniform logits, tiny argmax margins) model, int8 greedy
        # decode must agree with the float cache on a meaningful prefix
        # and at least half the horizon. Measured on the seed model:
        # 16/24 identical with a 16-token agreeing prefix — the bound
        # leaves ~2x slack for backend drift but catches a broken
        # quantizer (which degenerates to ~chance agreement) instantly.
        a = self._tokens("none")
        b = self._tokens("int8")
        assert len(a) == len(b) == 24
        prefix = next(
            (i for i, (x, y) in enumerate(zip(a, b)) if x != y), len(a)
        )
        matches = sum(int(x == y) for x, y in zip(a, b))
        assert prefix >= 8, (a, b)
        assert matches >= len(a) // 2, (a, b)

    def test_quantize_roundtrip_error_bounded(self):
        import jax.numpy as jnp

        from tony_tpu.serving.engine import _materialize, _quantize

        x = jax.random.normal(jax.random.key(1), (4, 16, 2, 16),
                              jnp.float32)
        back = _materialize(_quantize(x), jnp.float32)
        err = float(jnp.max(jnp.abs(back - x)))
        amax = float(jnp.max(jnp.abs(x)))
        assert err <= amax / 127.0 + 1e-6


# ---------------------------------------------------------------------------
# `tony tune` CLI + history panel
# ---------------------------------------------------------------------------


class TestSurfaces:
    def test_tune_cli_table(self, tmp_path, capsys):
        from tony_tpu.client.cli import tune_cmd

        key = autotune.tune_key("lm_train_step", config=CFG)
        autotune.save_record(
            _record_for(key, label="lm_train_step", live_best_ms=0.9),
            cache_dir=str(tmp_path),
        )
        assert tune_cmd(["--record-dir", str(tmp_path)]) == 0
        out = capsys.readouterr().out
        assert "lm_train_step" in out
        assert "block_q" in out

    def test_tune_cli_json(self, tmp_path, capsys):
        from tony_tpu.client.cli import tune_cmd

        key = autotune.tune_key("t", config=CFG)
        autotune.save_record(_record_for(key), cache_dir=str(tmp_path))
        assert tune_cmd(["--record-dir", str(tmp_path), "--json"]) == 0
        doc = json.loads(capsys.readouterr().out)
        assert len(doc["records"]) == 1
        assert doc["records"][0]["best"] == {"block_q": 256}

    def test_history_autotune_section(self):
        from tony_tpu.history.server import HistoryHandler

        final = {"metrics": {"tasks": {"worker:0": {
            autotune.TUNE_RECORD_HITS_COUNTER: 2,
            autotune.TUNE_RECORD_MISSES_COUNTER: 0,
            autotune.TUNE_SEARCH_TRIALS_COUNTER: 5,
        }, "worker:1": {}}}}
        parts = HistoryHandler._autotune_section(
            None, final, lambda s: str(s)
        )
        html = "".join(parts)
        assert "Autotuning" in html
        assert "worker:0" in html
        assert "worker:1" not in html  # no tune activity, no row
        assert HistoryHandler._autotune_section(
            None, {"metrics": {"tasks": {}}}, str
        ) == []


# ---------------------------------------------------------------------------
# End-to-end search on a real (tiny) train step — heavy, slow-marked
# ---------------------------------------------------------------------------


@pytest.mark.slow
class TestEndToEnd:
    def test_tune_train_step_cold_then_warm(self, tmp_path):
        mesh = build_mesh(MeshSpec(dp=1), devices=jax.devices()[:1])
        cold = autotune.tune_train_step(
            CFG, mesh, global_batch=2, seq=32, trial_budget=2,
            cache_dir=str(tmp_path),
        )
        assert cold["trials_this_run"] >= 1
        assert cold["best_ms"] is not None
        assert cold["default_ms"] >= cold["best_ms"]
        warm = autotune.tune_train_step(
            CFG, mesh, global_batch=2, seq=32, trial_budget=2,
            cache_dir=str(tmp_path),
        )
        assert warm["trials_this_run"] == 0
        assert warm["best"] == cold["best"]
