"""Test bootstrap: force JAX onto a virtual 8-device CPU mesh so every
sharding/parallelism test runs without TPU hardware (the tony-mini idea from
the reference test strategy — SURVEY.md §4 — applied to devices), and arm
the runtime sync sanitizer so every e2e doubles as a race probe."""

import os

# Sync sanitizer ON for the whole tier-1 suite (opt-out with =0): every
# control-plane lock the suite exercises feeds the process-global
# lock-order graph, and the autouse fixture below fails the test during
# which an inversion was observed. setdefault BEFORE any tony_tpu
# import — the factories read the flag at lock-creation time.
os.environ.setdefault("TONY_SYNC_SANITIZER", "1")

# Jit sanitizer ON for the whole tier-1 suite (opt-out with =0): every
# instrument_jit dispatch the suite exercises is classified cold/hit/
# retrace in the process-global tracker, and every step region runs
# under a device-to-host transfer guard. The autouse fixture below
# fails the test during which an over-budget retrace or an implicit
# transfer was observed.
os.environ.setdefault("TONY_JIT_SANITIZER", "1")

# Forced (not setdefault): the ambient environment pins JAX_PLATFORMS to the
# real TPU and a sitecustomize imports jax at interpreter startup, so both
# the env var AND the already-imported jax config must be overridden before
# any backend initializes. Tests always run on the virtual CPU mesh;
# bench.py is the only entry point that targets the real chip.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

import jax

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    # Older jax (< 0.5) has no jax_num_cpu_devices option; the
    # xla_force_host_platform_device_count flag above covers it.
    pass

import pytest


@pytest.fixture(autouse=True)
def _sync_sanitizer_gate():
    """Fail the test during which the sanitizer observed a lock-order
    inversion in the PROCESS-GLOBAL tracker (tests seeding deliberate
    inversions use private ``SyncTracker`` instances, which this gate
    never reads). Long-hold violations are hygiene telemetry, not
    failures — CPU-contended CI must not flake on hold times."""
    from tony_tpu.analysis import sync_sanitizer as _sync

    if not _sync.enabled():
        yield
        return
    tracker = _sync.tracker()
    mark = tracker.mark()
    yield
    inversions = tracker.violations_since(
        mark, kind=_sync.LOCK_ORDER_INVERSION
    )
    if inversions:
        import json

        pytest.fail(
            "sync sanitizer observed lock-order inversion(s):\n"
            + json.dumps(inversions, indent=2),
            pytrace=False,
        )


@pytest.fixture(autouse=True)
def _jit_sanitizer_gate():
    """Fail the test during which the jit sanitizer observed an implicit
    device-to-host transfer inside a step region, or a retrace past the
    budget, in the PROCESS-GLOBAL tracker (tests seeding deliberate
    violations use private ``JitTracker`` instances, which this gate
    never reads). In-budget retraces are telemetry, not failures — a
    test legitimately calls the same wrapper with a handful of shapes."""
    from tony_tpu.analysis import jit_sanitizer as _jit

    if not _jit.enabled():
        yield
        return
    tracker = _jit.tracker()
    mark = tracker.mark()
    yield
    since = tracker.violations_since(mark)
    bad = [
        v for v in since
        if v.get("kind") == _jit.GUARDED_TRANSFER or v.get("over_budget")
    ]
    if bad:
        import json

        pytest.fail(
            "jit sanitizer observed dispatch violation(s):\n"
            + json.dumps(bad, indent=2),
            pytrace=False,
        )
