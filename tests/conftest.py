"""Test bootstrap: force JAX onto a virtual 8-device CPU mesh so every
sharding/parallelism test runs without TPU hardware (the tony-mini idea from
the reference test strategy — SURVEY.md §4 — applied to devices)."""

import os

# Forced (not setdefault): the ambient environment pins JAX_PLATFORMS to the
# real TPU and a sitecustomize imports jax at interpreter startup, so both
# the env var AND the already-imported jax config must be overridden before
# any backend initializes. Tests always run on the virtual CPU mesh;
# bench.py is the only entry point that targets the real chip.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

import jax

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    # Older jax (< 0.5) has no jax_num_cpu_devices option; the
    # xla_force_host_platform_device_count flag above covers it.
    pass
