"""Test bootstrap: force JAX onto a virtual 8-device CPU mesh so every
sharding/parallelism test runs without TPU hardware (the tony-mini idea from
the reference test strategy — SURVEY.md §4 — applied to devices)."""

import os

# Must be set before jax is imported anywhere in the test process.
os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")
