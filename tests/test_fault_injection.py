"""Fault-injection e2e matrix — the analogue of the reference's env-flag
fault tests (TestTonyE2E.java:86-117, 201-238), grown into a structured
chaos suite: the legacy ``TEST_*`` env vars still work as deprecated
aliases, and the ``tony.fault.plan`` tests drive the failure classifier,
backoff policy, and checkpoint-aware resume end to end (SURVEY §4)."""

import json
import sys
from pathlib import Path

import pytest

from tony_tpu.conf import keys
from tony_tpu.coordinator.session import SessionStatus
from tony_tpu.mini import MiniTonyCluster

FIXTURES = Path(__file__).resolve().parent / "fixtures"


@pytest.fixture()
def cluster(tmp_path):
    return MiniTonyCluster(tmp_path)


def _job(cluster, fixture, workers=1, **conf_extra):
    conf = cluster.base_conf()
    conf.set(keys.K_EXECUTES, str(FIXTURES / fixture))
    conf.set(keys.K_PYTHON_BINARY, sys.executable)
    conf.set(keys.instances_key("worker"), workers)
    for k, v in conf_extra.items():
        conf.set(k, v)
    return conf


def test_missed_heartbeats_fail_job(cluster, monkeypatch):
    # Executor skips 200 pings; expiry = interval × max-missed = 0.6s while
    # the user script sleeps — the liveness monitor must declare it dead
    # (TestTonyE2E.java:86-100).
    monkeypatch.setenv("TEST_TASK_EXECUTOR_NUM_HB_MISS", "200")
    conf = _job(cluster, "exit_0.py")
    conf.set(keys.K_EXECUTES, "-c 'import time; time.sleep(30)'")
    conf.set(keys.K_TASK_HEARTBEAT_INTERVAL_MS, 100)
    conf.set(keys.K_TASK_MAX_MISSED_HEARTBEATS, 6)
    status, coord = cluster.run_job(conf, timeout_s=60)
    assert status is SessionStatus.FAILED
    assert "missed too many heartbeats" in coord.session.diagnostics


def test_skewed_straggler_still_passes(cluster, monkeypatch):
    # worker:1 sleeps 1.5s before even registering; the gang barrier must
    # hold for it and the job still succeeds (TestTonyE2E.java:102-117).
    monkeypatch.setenv("TEST_TASK_EXECUTOR_SKEW", "worker#1#1500")
    status, _ = cluster.run_job(_job(cluster, "check_jax_env.py", workers=2))
    assert status is SessionStatus.SUCCEEDED


def test_worker_termination_fails_job(cluster, monkeypatch):
    # As soon as the chief registers, the coordinator SIGKILLs a non-chief
    # worker (preemption simulation); its nonzero exit must fail the session
    # (TestTonyE2E.java:226-238 via TonyApplicationMaster.java:1108-1119).
    monkeypatch.setenv("TEST_WORKER_TERMINATION", "1")
    conf = _job(cluster, "exit_0.py", workers=2)
    # keep tasks alive long enough for the kill to land mid-flight
    conf.set(keys.K_EXECUTES, "-c 'import time; time.sleep(10)'")
    status, coord = cluster.run_job(conf, timeout_s=60)
    assert status is SessionStatus.FAILED


def test_session_retry_recovers(cluster, tmp_path):
    # First attempt fails (marker file absent → fixture exits 1 and creates
    # it); with am.retry-count=1 the coordinator resets the session, bumps
    # the session id, and the rerun succeeds — the whole-session retry path
    # (TonyApplicationMaster.reset:526-542).
    marker = tmp_path / "attempt.marker"
    script = tmp_path / "flaky.py"
    script.write_text(
        # Only the WORKER is flaky: the job also carries a default ps
        # task running this same script, and the ps racing the worker
        # to the marker (creating it first, so attempt 1 "succeeds")
        # was a measured tier-1 flake on a loaded box.
        "import os, pathlib, sys\n"
        "if os.environ.get('JOB_NAME') != 'worker':\n"
        "    sys.exit(0)\n"
        f"m = pathlib.Path({str(marker)!r})\n"
        "if m.exists():\n"
        "    sys.exit(0)\n"
        "m.touch()\n"
        "sys.exit(1)\n"
    )
    conf = _job(cluster, "exit_0.py")
    conf.set(keys.K_EXECUTES, str(script))
    conf.set(keys.K_AM_RETRY_COUNT, 1)
    status, coord = cluster.run_job(conf, timeout_s=90)
    assert status is SessionStatus.SUCCEEDED
    assert coord.session.session_id == 2  # second attempt won


def test_retries_exhausted_still_fails(cluster):
    conf = _job(cluster, "exit_1.py")
    conf.set(keys.K_AM_RETRY_COUNT, 1)
    status, coord = cluster.run_job(conf, timeout_s=90)
    assert status is SessionStatus.FAILED
    assert coord.session.session_id == 2


def test_user_permanent_fails_fast_without_consuming_retries(cluster):
    """Chaos: worker:0 exits 1 BEFORE the rendezvous barrier (the fault
    plan's exit_executor — how a typo'd script path looks). The classifier
    must read the pre-registration nonzero exit as USER_PERMANENT and fail
    the job on session 1, with the full retry budget untouched — no slices
    burned re-running a deterministic user bug."""
    plan = {"seed": 3, "faults": [
        {"action": "exit_executor", "target": "worker:0",
         "at": "pre_register", "code": 1},
    ]}
    conf = _job(cluster, "exit_0.py")
    conf.set(keys.K_FAULT_PLAN, json.dumps(plan))
    conf.set(keys.K_AM_RETRY_COUNT, 3)
    status, coord = cluster.run_job(conf, timeout_s=60)
    assert status is SessionStatus.FAILED
    stats = json.loads(
        (coord.app_dir / "final-status.json").read_text()
    )["stats"]
    assert stats["sessions_run"] == 1  # fail-fast: no retries consumed
    (record,) = stats["retries"]
    assert record["category"] == "USER_PERMANENT"
    assert record["retried"] is False
    assert record["backoff_ms"] == 0
    assert "pre-rendezvous" in record["failure"]


def test_transient_exit_consumes_retry_budget(cluster):
    """Counterpoint: the same exit code AFTER rendezvous is TRANSIENT and
    does consume retries — the category, not the code, decides."""
    conf = _job(cluster, "exit_1.py")
    conf.set(keys.K_AM_RETRY_COUNT, 1)
    conf.set(keys.K_AM_RETRY_BACKOFF_BASE_MS, 50)
    status, coord = cluster.run_job(conf, timeout_s=90)
    assert status is SessionStatus.FAILED
    stats = json.loads(
        (coord.app_dir / "final-status.json").read_text()
    )["stats"]
    assert stats["sessions_run"] == 2
    assert [r["category"] for r in stats["retries"]] \
        == ["TRANSIENT", "TRANSIENT"]
    assert stats["retries"][0]["retried"] is True
    assert stats["retries"][0]["backoff_ms"] > 0
    assert stats["retries"][1]["retried"] is False


@pytest.mark.slow
def test_chaos_kill_worker_resumes_from_checkpoint(cluster, tmp_path):
    """THE acceptance chaos run: a fault plan SIGKILLs the non-chief worker
    mid-training (after its 15th heartbeat, by which point both workers
    have parked on a complete step-5 checkpoint — see
    fixtures/chaos_train.py). Asserts, deterministically under the plan
    seed: the session retries with the exact seeded backoff (observable in
    final-status.json stats), the retried session resumes from step 5
    rather than step 0, and the job finishes SUCCEEDED."""
    from tony_tpu.resilience import FailureCategory, RetryPolicy

    ckpt_dir = tmp_path / "chaos-ckpts"
    plan = {"seed": 7, "faults": [
        {"action": "kill_task", "target": "worker:1",
         "after_heartbeats": 15, "session": 1},
    ]}
    conf = _job(cluster, "chaos_train.py", workers=2)
    # No ps task: every task type runs the user command, and a ps would
    # checkpoint as process 0 of a 1-process job into the same directory —
    # colliding with worker:0's shards and lying about completeness.
    conf.set(keys.instances_key("ps"), 0)
    conf.set(keys.K_FAULT_PLAN, json.dumps(plan))
    conf.set(keys.K_CHECKPOINT_LOCATION, str(ckpt_dir))
    conf.set(keys.K_AM_RETRY_COUNT, 2)
    conf.set(keys.K_AM_RETRY_BACKOFF_BASE_MS, 300)
    conf.set(keys.K_AM_RETRY_BACKOFF_MAX_MS, 2000)
    status, coord = cluster.run_job(conf, timeout_s=240)
    assert status is SessionStatus.SUCCEEDED
    final = json.loads((coord.app_dir / "final-status.json").read_text())
    stats = final["stats"]
    assert stats["sessions_run"] == 2
    (record,) = stats["retries"]
    # SIGKILL'd mid-training → INFRA, with the exact deterministic backoff
    # the plan seed implies (jitter seed inherits the plan seed).
    assert record["category"] == "INFRA"
    assert record["retried"] is True
    assert record["resume_step"] == 5
    expected = RetryPolicy(
        budget=2, backoff_base_ms=300, backoff_max_ms=2000, seed=7,
    ).backoff_ms_for(1, FailureCategory.INFRA)
    assert record["backoff_ms"] == expected > 0
    # Training finished at the target, resuming — not recomputing — and
    # the chief's log proves the step-5 resume (chaos_train.py exits 1 on
    # any other resume point).
    assert stats["best_checkpoint_step"] == 10
    chief_log = (coord.app_dir / "logs" / "worker-0.log").read_text()
    assert "resumed from step 5" in chief_log


def test_final_status_carries_run_stats(cluster, tmp_path):
    """final-status.json is self-describing: session count, failed tasks,
    missed-heartbeat tasks, wall time (the reference declares metrics-core
    and never uses it — SURVEY 5.5)."""
    import json

    marker = tmp_path / "attempt.marker"
    script = tmp_path / "flaky.py"
    script.write_text(
        # Only the WORKER is flaky: the job also carries a default ps
        # task running this same script, and the ps racing the worker
        # to the marker (creating it first, so attempt 1 "succeeds")
        # was a measured tier-1 flake on a loaded box.
        "import os, pathlib, sys\n"
        "if os.environ.get('JOB_NAME') != 'worker':\n"
        "    sys.exit(0)\n"
        f"m = pathlib.Path({str(marker)!r})\n"
        "if m.exists():\n"
        "    sys.exit(0)\n"
        "m.touch()\n"
        "sys.exit(1)\n"
    )
    conf = _job(cluster, "exit_0.py")
    conf.set(keys.K_EXECUTES, str(script))
    conf.set(keys.K_AM_RETRY_COUNT, 1)
    status, coord = cluster.run_job(conf, timeout_s=90)
    assert status is SessionStatus.SUCCEEDED
    stats = json.loads(
        (coord.app_dir / "final-status.json").read_text()
    )["stats"]
    assert stats["sessions_run"] == 2
    assert stats["tasks_failed"] == 1
    assert stats["heartbeat_missed_tasks"] == []
    assert stats["wall_ms"] > 0
