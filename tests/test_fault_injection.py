"""Fault-injection e2e matrix — the analogue of the reference's env-flag
fault tests (TestTonyE2E.java:86-117, 201-238): deterministic failures
injected via env vars read at well-defined points (SURVEY §4)."""

import sys
from pathlib import Path

import pytest

from tony_tpu.conf import keys
from tony_tpu.coordinator.session import SessionStatus
from tony_tpu.mini import MiniTonyCluster

FIXTURES = Path(__file__).resolve().parent / "fixtures"


@pytest.fixture()
def cluster(tmp_path):
    return MiniTonyCluster(tmp_path)


def _job(cluster, fixture, workers=1, **conf_extra):
    conf = cluster.base_conf()
    conf.set(keys.K_EXECUTES, str(FIXTURES / fixture))
    conf.set(keys.K_PYTHON_BINARY, sys.executable)
    conf.set(keys.instances_key("worker"), workers)
    for k, v in conf_extra.items():
        conf.set(k, v)
    return conf


def test_missed_heartbeats_fail_job(cluster, monkeypatch):
    # Executor skips 200 pings; expiry = interval × max-missed = 0.6s while
    # the user script sleeps — the liveness monitor must declare it dead
    # (TestTonyE2E.java:86-100).
    monkeypatch.setenv("TEST_TASK_EXECUTOR_NUM_HB_MISS", "200")
    conf = _job(cluster, "exit_0.py")
    conf.set(keys.K_EXECUTES, "-c 'import time; time.sleep(30)'")
    conf.set(keys.K_TASK_HEARTBEAT_INTERVAL_MS, 100)
    conf.set(keys.K_TASK_MAX_MISSED_HEARTBEATS, 6)
    status, coord = cluster.run_job(conf, timeout_s=60)
    assert status is SessionStatus.FAILED
    assert "missed too many heartbeats" in coord.session.diagnostics


def test_skewed_straggler_still_passes(cluster, monkeypatch):
    # worker:1 sleeps 1.5s before even registering; the gang barrier must
    # hold for it and the job still succeeds (TestTonyE2E.java:102-117).
    monkeypatch.setenv("TEST_TASK_EXECUTOR_SKEW", "worker#1#1500")
    status, _ = cluster.run_job(_job(cluster, "check_jax_env.py", workers=2))
    assert status is SessionStatus.SUCCEEDED


def test_worker_termination_fails_job(cluster, monkeypatch):
    # As soon as the chief registers, the coordinator SIGKILLs a non-chief
    # worker (preemption simulation); its nonzero exit must fail the session
    # (TestTonyE2E.java:226-238 via TonyApplicationMaster.java:1108-1119).
    monkeypatch.setenv("TEST_WORKER_TERMINATION", "1")
    conf = _job(cluster, "exit_0.py", workers=2)
    # keep tasks alive long enough for the kill to land mid-flight
    conf.set(keys.K_EXECUTES, "-c 'import time; time.sleep(10)'")
    status, coord = cluster.run_job(conf, timeout_s=60)
    assert status is SessionStatus.FAILED


def test_session_retry_recovers(cluster, tmp_path):
    # First attempt fails (marker file absent → fixture exits 1 and creates
    # it); with am.retry-count=1 the coordinator resets the session, bumps
    # the session id, and the rerun succeeds — the whole-session retry path
    # (TonyApplicationMaster.reset:526-542).
    marker = tmp_path / "attempt.marker"
    script = tmp_path / "flaky.py"
    script.write_text(
        "import pathlib, sys\n"
        f"m = pathlib.Path({str(marker)!r})\n"
        "if m.exists():\n"
        "    sys.exit(0)\n"
        "m.touch()\n"
        "sys.exit(1)\n"
    )
    conf = _job(cluster, "exit_0.py")
    conf.set(keys.K_EXECUTES, str(script))
    conf.set(keys.K_AM_RETRY_COUNT, 1)
    status, coord = cluster.run_job(conf, timeout_s=90)
    assert status is SessionStatus.SUCCEEDED
    assert coord.session.session_id == 2  # second attempt won


def test_retries_exhausted_still_fails(cluster):
    conf = _job(cluster, "exit_1.py")
    conf.set(keys.K_AM_RETRY_COUNT, 1)
    status, coord = cluster.run_job(conf, timeout_s=90)
    assert status is SessionStatus.FAILED
    assert coord.session.session_id == 2


def test_final_status_carries_run_stats(cluster, tmp_path):
    """final-status.json is self-describing: session count, failed tasks,
    missed-heartbeat tasks, wall time (the reference declares metrics-core
    and never uses it — SURVEY 5.5)."""
    import json

    marker = tmp_path / "attempt.marker"
    script = tmp_path / "flaky.py"
    script.write_text(
        "import pathlib, sys\n"
        f"m = pathlib.Path({str(marker)!r})\n"
        "if m.exists():\n"
        "    sys.exit(0)\n"
        "m.touch()\n"
        "sys.exit(1)\n"
    )
    conf = _job(cluster, "exit_0.py")
    conf.set(keys.K_EXECUTES, str(script))
    conf.set(keys.K_AM_RETRY_COUNT, 1)
    status, coord = cluster.run_job(conf, timeout_s=90)
    assert status is SessionStatus.SUCCEEDED
    stats = json.loads(
        (coord.app_dir / "final-status.json").read_text()
    )["stats"]
    assert stats["sessions_run"] == 2
    assert stats["tasks_failed"] == 1
    assert stats["heartbeat_missed_tasks"] == []
    assert stats["wall_ms"] > 0
