"""Config-system tests, including the key⟷defaults-file parity test — the
analogue of the reference's TestTonyConfigurationFields.java:11-62 which
forces TonyConfigurationKeys and tony-default.xml to stay in sync in both
directions, including default values."""

import json
from pathlib import Path

import pytest

from tony_tpu import constants
from tony_tpu.conf import TonyConfiguration, keys, load_job_config

DEFAULTS_FILE = (
    Path(__file__).resolve().parents[1]
    / "tony_tpu" / "conf" / constants.TONY_DEFAULT_CONF
)


def _expected_defaults() -> dict:
    d = dict(keys.DEFAULTS)
    for job in ("worker", "ps"):
        d[keys.instances_key(job)] = keys.default_instances(job)
        d[keys.memory_key(job)] = keys.DEFAULT_MEMORY
        d[keys.vcores_key(job)] = keys.DEFAULT_VCORES
        d[keys.gpus_key(job)] = keys.DEFAULT_GPUS
        d[keys.tpus_key(job)] = keys.DEFAULT_TPUS
    return d


def test_config_parity():
    shipped = json.loads(DEFAULTS_FILE.read_text())
    expected = _expected_defaults()
    missing = set(expected) - set(shipped)
    extra = set(shipped) - set(expected)
    assert not missing, f"keys declared in keys.py but absent from defaults file: {missing}"
    assert not extra, f"keys in defaults file not declared in keys.py: {extra}"
    for k, v in expected.items():
        assert shipped[k] == v, f"default mismatch for {k}: {shipped[k]!r} != {v!r}"


def test_every_key_constant_has_a_default():
    key_consts = {
        v for n, v in vars(keys).items()
        if n.startswith("K_") and isinstance(v, str)
    }
    assert key_consts == set(keys.DEFAULTS), (
        "every K_* constant must have an entry in keys.DEFAULTS"
    )


def test_layering_order(tmp_path):
    job = tmp_path / "tony.json"
    job.write_text(json.dumps({keys.K_FRAMEWORK: "pytorch", "tony.worker.instances": 4}))
    conf = load_job_config(conf_file=str(job), overrides=["tony.worker.instances=8"])
    # default ⟵ job file ⟵ CLI override
    assert conf.get_str(keys.K_FRAMEWORK) == "pytorch"
    assert conf.get_int(keys.instances_key("worker")) == 8
    assert conf.get_str(keys.memory_key("worker")) == "2g"  # untouched default


def test_site_config_layer(tmp_path, monkeypatch):
    site_dir = tmp_path / "confdir"
    site_dir.mkdir()
    (site_dir / constants.TONY_SITE_CONF).write_text(
        json.dumps({keys.K_HISTORY_LOCATION: "/srv/hist"})
    )
    monkeypatch.setenv(constants.TONY_CONF_DIR_ENV, str(site_dir))
    conf = TonyConfiguration()
    assert conf.get_str(keys.K_HISTORY_LOCATION) == "/srv/hist"


def test_freeze_thaw(tmp_path):
    conf = TonyConfiguration()
    conf.set("tony.evaluator.instances", 2)
    final = tmp_path / constants.TONY_FINAL_CONF
    conf.write_final(final)
    thawed = TonyConfiguration.from_final(final)
    assert thawed.to_dict() == conf.to_dict()


def test_job_type_discovery():
    conf = TonyConfiguration()
    conf.set("tony.evaluator.instances", 1)
    conf.set("tony.chief2.instances", 1)
    assert set(conf.job_types()) >= {"worker", "ps", "evaluator", "chief2"}


def test_bool_parsing():
    conf = TonyConfiguration(load_defaults=False)
    conf.set("a", "true")
    conf.set("b", "0")
    conf.set("c", "junk")
    assert conf.get_bool("a") is True
    assert conf.get_bool("b") is False
    assert conf.get_bool("missing", True) is True
    with pytest.raises(ValueError):
        conf.get_bool("c")
