"""Per-job credentials + RPC method ACLs — the analogue of the reference's
token/ACL plumbing (TonyClient.getTokens:568-621, TFPolicyProvider.java:15-26,
TFClientSecurityInfo.java:24-50)."""

import sys
from pathlib import Path

import pytest

from tony_tpu import security
from tony_tpu.conf import keys
from tony_tpu.conf.configuration import TonyConfiguration
from tony_tpu.coordinator.session import SessionStatus
from tony_tpu.mini import MiniTonyCluster
from tony_tpu.rpc.client import ApplicationRpcClient, RpcError
from tony_tpu.rpc.protocol import ApplicationRpc
from tony_tpu.rpc.server import ApplicationRpcServer

FIXTURES = Path(__file__).resolve().parent / "fixtures"


class _Impl(ApplicationRpc):
    def get_task_urls(self):
        return []

    def get_cluster_spec(self):
        return {"worker": ["h:1"]}

    def register_worker_spec(self, worker, spec):
        return {"worker": [spec]}

    def register_tensorboard_url(self, spec, url):
        return None

    def register_execution_result(self, exit_code, job_name, job_index, session_id):
        return None

    def finish_application(self):
        return None

    def task_executor_heartbeat(self, task_id, session_id, metrics=None,
                                profile=None):
        return None

    def request_profile(self, duration_ms):
        return {"req_id": "prof-test"}

    def get_application_status(self):
        return {"state": "RUNNING"}


class TestTokens:
    def test_role_tokens_distinct_and_deterministic(self):
        s = security.generate_job_secret()
        assert security.role_token(s, "client") != security.role_token(s, "executor")
        assert security.role_token(s, "client") == security.role_token(s, "client")
        assert len(s) == 32  # 16 random bytes, hex

    def test_prepare_mints_fresh_secret_only_when_placeholder(self):
        conf = TonyConfiguration()
        conf.set(keys.K_SECURITY_ENABLED, True)
        assert conf.get_str(keys.K_SECRET_KEY) == "dev"  # shipped default
        security.prepare_job_security(conf)
        minted = conf.get_str(keys.K_SECRET_KEY)
        assert minted not in ("", "dev")

        conf2 = TonyConfiguration()
        conf2.set(keys.K_SECURITY_ENABLED, True)
        conf2.set(keys.K_SECRET_KEY, "externally-managed")
        security.prepare_job_security(conf2)
        assert conf2.get_str(keys.K_SECRET_KEY) == "externally-managed"

    def test_prepare_noop_when_security_off(self):
        conf = TonyConfiguration()
        security.prepare_job_security(conf)
        assert conf.get_str(keys.K_SECRET_KEY) == "dev"


class TestMethodAcl:
    @pytest.fixture()
    def server(self):
        s = ApplicationRpcServer(
            _Impl(), host="127.0.0.1", port_range=(26000, 27000),
            role_tokens=security.role_tokens("job-secret"),
        )
        s.start()
        yield s
        s.stop()

    def _client(self, server, role):
        return ApplicationRpcClient(
            "127.0.0.1", server.port,
            secret=security.role_token("job-secret", role),
        )

    def test_acl_covers_every_rpc_method(self):
        from tony_tpu.rpc.protocol import RPC_METHODS

        assert set(security.METHOD_ACL) == set(RPC_METHODS)

    def test_executor_role_cannot_finish_application(self, server):
        executor = self._client(server, security.EXECUTOR_ROLE)
        assert executor.register_worker_spec("worker:0", "h:1") is not None
        with pytest.raises(RpcError, match="not permitted"):
            executor.finish_application()
        executor.close()

    def test_client_role_cannot_join_rendezvous(self, server):
        client = self._client(server, security.CLIENT_ROLE)
        assert client.get_application_status()["state"] == "RUNNING"
        with pytest.raises(RpcError, match="not permitted"):
            client.register_worker_spec("worker:0", "h:1")
        client.close()

    def test_both_roles_may_read_cluster_spec(self, server):
        for role in (security.CLIENT_ROLE, security.EXECUTOR_ROLE):
            c = self._client(server, role)
            assert c.get_cluster_spec() == {"worker": ["h:1"]}
            c.close()

    def test_unknown_token_rejected(self, server):
        bad = ApplicationRpcClient("127.0.0.1", server.port, secret="nope")
        with pytest.raises(RpcError, match="authentication failed"):
            bad.get_cluster_spec()
        bad.close()


def test_secure_job_end_to_end(tmp_path):
    """Full stack with security on: executors authenticate with the
    executor role token and the job completes."""
    cluster = MiniTonyCluster(tmp_path)
    conf = cluster.base_conf()
    conf.set(keys.K_FRAMEWORK, "jax")
    conf.set(keys.K_EXECUTES, str(FIXTURES / "exit_0.py"))
    conf.set(keys.K_PYTHON_BINARY, sys.executable)
    conf.set(keys.instances_key("worker"), 1)
    conf.set(keys.instances_key("ps"), 0)
    conf.set(keys.K_SECURITY_ENABLED, True)
    secret = security.generate_job_secret()
    conf.set(keys.K_SECRET_KEY, secret)
    status, coord = cluster.run_job(conf)
    assert status is SessionStatus.SUCCEEDED, coord.session.diagnostics
    # Privilege separation: executors got a secret-STRIPPED conf (so they
    # cannot derive the client role token) plus their own role credential.
    import json

    stripped = json.loads(
        (coord.app_dir / "tony-executor.json").read_text()
    )
    assert stripped[keys.K_SECRET_KEY] == ""
    assert secret not in (coord.app_dir / "tony-executor.json").read_text()
