"""Dispatch-count regression pins for the two hottest paths the TONY-X
discipline protects: the lm_train steady-state step and the serving
decode window. Both must be retrace-free after their cold compile and
free of unannotated device-to-host transfers — the process-global jit
tracker (armed suite-wide by conftest) is the witness.

On the CPU backend jax's transfer guard cannot fire (arrays are
host-resident), so the transfer half of these pins is plumbing-level
here and bites on a real accelerator; the retrace half is fully real
on any backend."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tony_tpu.analysis import jit_sanitizer
from tony_tpu.models import TransformerConfig, init_params, make_train_step
from tony_tpu.parallel.mesh import MeshSpec, build_mesh

pytestmark = pytest.mark.skipif(
    not jit_sanitizer.enabled(),
    reason="jit sanitizer disarmed (TONY_JIT_SANITIZER=0)",
)


def _violations_during(mark):
    tr = jit_sanitizer.tracker()
    return tr.violations_since(mark)


class TestLmTrainSteadyState:
    def test_steady_state_step_is_retrace_free(self):
        cfg = TransformerConfig(
            vocab_size=64, d_model=32, n_layers=2, n_heads=2, head_dim=16,
            d_ff=64, max_seq=32, dtype="float32", remat=False,
        )
        mesh = build_mesh(MeshSpec(dp=2, sp=2, tp=2))
        init_fn, step_fn = make_train_step(cfg, mesh, learning_rate=1e-3)
        rng = np.random.default_rng(0)
        tokens = jnp.asarray(
            rng.integers(0, cfg.vocab_size, (4, 17)), jnp.int32
        )
        tr = jit_sanitizer.tracker()
        mark = tr.mark()
        with jax.sharding.set_mesh(mesh):
            state = init_fn(jax.random.key(0))
            # Cold compile on the first step, then steady state: every
            # later dispatch must classify as a pure cache hit.
            for _ in range(4):
                state, metrics = step_fn(state, tokens)
        assert int(state.step) == 4
        during = _violations_during(mark)
        assert during == [], (
            "lm_train step path dispatched dirty:\n"
            + "\n".join(str(v) for v in during)
        )

    def test_shape_change_is_the_seeded_counterexample(self):
        """The same harness MUST see a retrace when shapes drift —
        proves the clean run above is a real measurement, not a dead
        tracker. Seeded on a private tracker so the suite gate and the
        bench gate never see the deliberate violation."""
        from tony_tpu.parallel import plan as plan_lib

        tr = jit_sanitizer.JitTracker(budget=4)
        fn = jax.jit(lambda x: x * 2)
        key = "seeded-shape-drift"
        for batch in (4, 8):
            x = jnp.zeros((batch, 3))
            sig = "x".join(str(d) for d in x.shape)
            jit_sanitizer.note_dispatch(key, sig, tracker_=tr)
            fn(x)
        assert tr.retraces(key) == 1
        del plan_lib


class TestServingDecodeWindow:
    def test_decode_window_and_prefill_are_retrace_free(self):
        from tony_tpu.serving import ServingEngine

        cfg = TransformerConfig(
            vocab_size=64, d_model=32, n_layers=2, n_heads=2, head_dim=16,
            d_ff=64, max_seq=96, dtype="float32", remat=False,
        )
        params = init_params(jax.random.key(0), cfg)
        rng = np.random.default_rng(3)
        tr = jit_sanitizer.tracker()
        mark = tr.mark()
        eng = ServingEngine(
            params, cfg, slots=2, prefill_chunk=5, decode_window=4,
            prefill_batch=2,
        )
        with eng:
            reqs = [
                eng.submit(rng.integers(0, 64, n).astype(np.int32), 5)
                for n in (3, 7, 11)
            ]
            for r in reqs:
                r.result(timeout=120)
        # The padded prefill rounds and the fixed decode window pin
        # every dispatch to ONE signature per key: cold once, hits
        # forever — zero retraces in the whole serve.
        decode_key = eng._decode.plan_cache_key
        prefill_key = eng._prefill.plan_cache_key
        assert tr.retraces(decode_key) == 0
        assert tr.retraces(prefill_key) == 0
        during = _violations_during(mark)
        assert during == [], (
            "serving dispatch path dirty:\n"
            + "\n".join(str(v) for v in during)
        )
