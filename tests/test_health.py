"""Health-analytics subsystem tests: the streaming detectors
(straggler / stall / loss / jitter / io), the crash flight recorder,
the TONY-D postmortem rule catalogue + `tony doctor`, the TONY-E001
event-catalogue lint, events.jsonl hardening, aggregator behavior
under many tasks and clock skew, `tony events --follow`, and the
mini-cluster chaos e2e that drives the whole chain (injected fault →
health alert → blackbox → ranked diagnosis)."""

import json
import re
import sys
import threading
import time
import urllib.request
from pathlib import Path

import pytest

from tony_tpu import constants
from tony_tpu.analysis import postmortem
from tony_tpu.analysis.events_lint import check_event_catalogue
from tony_tpu.conf import keys
from tony_tpu.conf.configuration import TonyConfiguration
from tony_tpu.coordinator.app_master import TonyCoordinator
from tony_tpu.coordinator.backend import LocalProcessBackend
from tony_tpu.coordinator.session import SessionStatus
from tony_tpu.mini import MiniTonyCluster
from tony_tpu.observability import events as obs_events
from tony_tpu.observability import health as obs_health
from tony_tpu.observability.aggregator import (
    MetricsAggregator,
    ObservabilityHttpServer,
)
from tony_tpu.observability.flight import FlightRecorder, find_blackboxes
from tony_tpu.observability.health import HealthConfig, HealthMonitor

FIXTURES = Path(__file__).resolve().parent / "fixtures"


class FakeClock:
    def __init__(self, t=100.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, s):
        self.t += s


def _snap(gauges=None, counters=None, histograms=None):
    return {
        "ts_ms": int(time.time() * 1000),
        "gauges": gauges or {},
        "counters": counters or {},
        "histograms": histograms or {},
    }


# ---------------------------------------------------------------------------
# health.py — detectors
# ---------------------------------------------------------------------------
class TestMadScores:
    def test_outlier_scores_high_uniform_fleet(self):
        scores = obs_health.mad_scores(
            {"w0": 5.0, "w1": 5.0, "w2": 5.0, "w3": 80.0}
        )
        assert scores["w3"] > 10
        assert scores["w0"] < 3

    def test_fewer_than_three_tasks_score_zero(self):
        assert obs_health.mad_scores({"a": 1.0, "b": 100.0}) == {
            "a": 0.0, "b": 0.0,
        }


class TestHealthMonitor:
    def _monitor(self, clock, **overrides):
        cfg = HealthConfig(
            heartbeat_interval_ms=100, alert_cooldown_ms=10_000,
            **overrides,
        )
        alerts = []

        def emit(**kw):
            alerts.append(kw)

        return HealthMonitor(cfg, emit=emit, clock=clock), alerts

    def test_straggler_alert_names_slow_task_only(self):
        clock = FakeClock()
        mon, alerts = self._monitor(clock)
        for tid, st in (("w:0", 5.0), ("w:1", 5.0), ("w:2", 80.0)):
            mon.observe(tid, _snap(gauges={"step_time_ms": st}))
        assert [a["task"] for a in alerts
                if a["detector"] == "straggler"] == ["w:2"]
        scores = mon.straggler_scores()
        assert scores["w:2"] > 3.0
        # faster-than-median tasks never score as stragglers
        assert scores["w:0"] == 0.0

    def test_progress_stall_watchdog(self):
        clock = FakeClock()
        mon, alerts = self._monitor(clock, stall_timeout_ms=1000,
                                    heartbeat_jitter_factor=1000.0)
        mon.observe("w:0", _snap(counters={"train_steps_total": 5}))
        clock.advance(0.5)
        mon.observe("w:0", _snap(counters={"train_steps_total": 6}))
        clock.advance(1.5)  # no progress, past the timeout
        mon.observe("w:0", _snap(counters={"train_steps_total": 6}))
        assert [a["detector"] for a in alerts] == ["progress_stall"]
        assert mon.to_json()["tasks"]["w:0"]["stalled"] is True
        # progress clears the stall flag
        mon.observe("w:0", _snap(counters={"train_steps_total": 7}))
        assert mon.to_json()["tasks"]["w:0"]["stalled"] is False

    def test_loss_nan_and_spike(self):
        clock = FakeClock()
        mon, alerts = self._monitor(clock, loss_spike_factor=5.0)
        for loss in (1.0, 0.9, 0.8, 0.7):
            mon.observe("w:0", _snap(gauges={"loss": loss}))
        mon.observe("w:0", _snap(gauges={"loss": 50.0}))  # > 5× median
        mon.observe("w:1", _snap(gauges={"loss": float("nan")}))
        detectors = [a["detector"] for a in alerts]
        assert "loss_spike" in detectors
        assert "loss_nan" in detectors

    def test_heartbeat_jitter_uses_coordinator_clock(self):
        clock = FakeClock()
        mon, alerts = self._monitor(clock, heartbeat_jitter_factor=3.0)
        # Executor-claimed timestamps are irrelevant: only arrival gaps
        # on OUR clock count.
        mon.observe("w:0", None)
        clock.advance(0.1)
        mon.observe("w:0", None)  # 100ms gap: fine
        clock.advance(0.9)        # 900ms > 3 × 100ms interval
        mon.observe("w:0", None)
        assert [a["detector"] for a in alerts] == ["heartbeat_jitter"]
        assert alerts[0]["gap_ms"] == pytest.approx(900, abs=1)

    def test_io_stall_ratio(self):
        clock = FakeClock()
        mon, alerts = self._monitor(clock, io_stall_ratio=0.5,
                                    heartbeat_jitter_factor=1000.0)
        h = {"tony_io_queue_wait_ms": {"count": 1, "sum": 0.0,
                                       "buckets": []}}
        mon.observe("w:0", _snap(histograms=h))
        clock.advance(1.0)
        h2 = {"tony_io_queue_wait_ms": {"count": 5, "sum": 800.0,
                                        "buckets": []}}
        mon.observe("w:0", _snap(histograms=h2))  # 800ms wait / 1000ms wall
        assert [a["detector"] for a in alerts] == ["io_stall"]
        assert alerts[0]["stall_ratio"] == pytest.approx(0.8)

    def test_mfu_collapse_relative_to_own_median(self):
        clock = FakeClock()
        mon, alerts = self._monitor(clock, mfu_collapse_ratio=0.5,
                                    heartbeat_jitter_factor=1000.0)
        # 6 healthy samples build the rolling median; value is tiny on
        # purpose — the detector is relative, not an absolute bar.
        for _ in range(6):
            clock.advance(0.1)
            mon.observe("w:0", _snap(gauges={"tony_mfu": 0.01}))
        assert alerts == []
        clock.advance(0.1)
        mon.observe("w:0", _snap(gauges={"tony_mfu": 0.001}))  # 10× drop
        assert [a["detector"] for a in alerts] == ["mfu_collapse"]
        assert alerts[0]["task"] == "w:0"
        assert alerts[0]["mfu"] == pytest.approx(0.001)
        # a healthy dip (0.6×) never alerts
        mon2, alerts2 = self._monitor(clock, mfu_collapse_ratio=0.5,
                                      heartbeat_jitter_factor=1000.0)
        for _ in range(6):
            clock.advance(0.1)
            mon2.observe("w:0", _snap(gauges={"tony_mfu": 0.01}))
        clock.advance(0.1)
        mon2.observe("w:0", _snap(gauges={"tony_mfu": 0.006}))
        assert alerts2 == []

    def test_comms_bound_reads_phase_breakdown(self):
        clock = FakeClock()
        mon, alerts = self._monitor(clock, comms_bound_ratio=0.5,
                                    heartbeat_jitter_factor=1000.0)
        balanced = {
            'tony_step_phase_ms{phase="compute"}': 70.0,
            'tony_step_phase_ms{phase="collective"}': 20.0,
            'tony_step_phase_ms{phase="data_wait"}': 5.0,
            'tony_step_phase_ms{phase="h2d"}': 3.0,
            'tony_step_phase_ms{phase="host"}': 2.0,
        }
        mon.observe("w:0", _snap(gauges=balanced))
        assert alerts == []
        comms_bound = dict(balanced)
        comms_bound['tony_step_phase_ms{phase="collective"}'] = 200.0
        clock.advance(0.1)
        mon.observe("w:0", _snap(gauges=comms_bound))
        assert [a["detector"] for a in alerts] == ["comms_bound"]
        assert alerts[0]["share"] == pytest.approx(200.0 / 280.0, abs=0.01)

    def test_cooldown_suppresses_repeat_alerts(self):
        clock = FakeClock()
        mon, alerts = self._monitor(clock, heartbeat_jitter_factor=1.0)
        mon.observe("w:0", None)
        for _ in range(5):
            clock.advance(1.0)  # every gap is over the limit
            mon.observe("w:0", None)
        assert len(alerts) == 1  # cooldown (10s) swallows the repeats
        clock.advance(11.0)
        mon.observe("w:0", None)
        assert len(alerts) == 2

    def test_disabled_monitor_is_inert(self):
        clock = FakeClock()
        mon, alerts = self._monitor(clock, enabled=False)
        mon.observe("w:0", _snap(gauges={"loss": float("nan")}))
        assert alerts == [] and mon.to_json()["tasks"] == {}

    def test_reset_tasks_keeps_alert_history(self):
        clock = FakeClock()
        mon, alerts = self._monitor(clock)
        mon.observe("w:0", _snap(gauges={"loss": float("nan")}))
        assert len(mon.alerts()) == 1
        mon.reset_tasks()
        assert mon.to_json()["tasks"] == {}
        assert len(mon.alerts()) == 1  # history describes the job

    def test_alert_counter_and_emit_failure_tolerated(self):
        from tony_tpu.observability.metrics import MetricsRegistry

        reg = MetricsRegistry()

        def explode(**kw):
            raise OSError("sink gone")

        mon = HealthMonitor(HealthConfig(), emit=explode, registry=reg)
        mon.observe("w:0", _snap(gauges={"loss": float("nan")}))  # no raise
        assert reg.snapshot()["counters"][obs_health.ALERTS_COUNTER] == 1

    def test_from_conf(self):
        conf = TonyConfiguration()
        conf.set(keys.K_HEALTH_STRAGGLER_THRESHOLD, "2.5")
        conf.set(keys.K_HEALTH_ENABLED, "false")
        cfg = HealthConfig.from_conf(conf)
        assert cfg.straggler_threshold == 2.5
        assert cfg.enabled is False
        assert cfg.stall_timeout_ms == 60000  # default


# ---------------------------------------------------------------------------
# flight.py — crash flight recorder
# ---------------------------------------------------------------------------
class TestFlightRecorder:
    def test_rings_are_bounded(self):
        fr = FlightRecorder(proc="coordinator", limit=4)
        for i in range(10):
            fr.record_rpc("task_executor_heartbeat", task=f"w:{i}")
            fr.record_event({"kind": "task_scheduled", "i": i})
        snap = fr.snapshot()
        assert len(snap["rpcs"]) == 4 and len(snap["events"]) == 4
        assert snap["rpcs"][-1]["task"] == "w:9"  # newest survives

    def test_record_report_compacts_and_coerces(self):
        fr = FlightRecorder(proc="executor:w:0")
        fr.record_report("w:0", {
            "ts_ms": 7, "gauges": {"loss": 0.5, "step_time_ms": 5.0,
                                   "irrelevant": 1.0,
                                   "tokens_per_sec": "x" * 1000},
            "counters": {"train_steps_total": 3, "other_total": 9},
        })
        fr.record_report("w:0", None)  # bare ping: not recorded
        reports = fr.snapshot()["reports"]
        assert len(reports) == 1
        # user-supplied garbage is dropped at the trust boundary, not
        # copied into the ring (and every future blackbox dump)
        assert reports[0] == {"ts_ms": 7, "task": "w:0", "loss": 0.5,
                              "step_time_ms": 5.0,
                              "train_steps_total": 3}

    def test_dump_atomic_and_json_safe(self, tmp_path):
        fr = FlightRecorder(proc="coordinator")
        fr.record_report("w:0", {"gauges": {"loss": float("nan")},
                                 "ts_ms": 1})
        path = fr.dump(tmp_path, "task-failure",
                       name="coordinator-s1-task-failure",
                       extra={"session": 1})
        assert path is not None
        assert path.name == "blackbox-coordinator-s1-task-failure.json"
        doc = json.loads(path.read_text())  # strictly parseable (NaN→null)
        assert doc["reason"] == "task-failure"
        assert doc["session"] == 1
        assert doc["reports"][0]["loss"] is None
        assert not list(tmp_path.glob(".*tmp*"))  # no torn temp left

    def test_dump_sanitizes_names(self, tmp_path):
        fr = FlightRecorder(proc="executor:worker:1")
        path = fr.dump(tmp_path, "x", name="executor-worker:1/s1")
        assert path is not None and ":" not in path.name
        assert "/" not in path.name.replace(str(tmp_path), "")

    def test_find_blackboxes(self, tmp_path):
        (tmp_path / "blackbox-a.json").write_text("{}")
        (tmp_path / "logs").mkdir()
        (tmp_path / "logs" / "blackbox-b.json").write_text("{}")
        (tmp_path / "not-a-blackbox.json").write_text("{}")
        found = find_blackboxes(tmp_path, tmp_path / "logs",
                                tmp_path / "missing", None)
        assert [p.name for p in found] == ["blackbox-a.json",
                                           "blackbox-b.json"]


# ---------------------------------------------------------------------------
# aggregator under many tasks + clock skew (satellite), health wiring
# ---------------------------------------------------------------------------
class TestAggregatorScale:
    def test_many_tasks_bounded_memory(self):
        agg = MetricsAggregator(series_limit=16)
        for t in range(50):
            for i in range(40):
                agg.ingest(f"w:{t}", {
                    "ts_ms": i, "counters": {},
                    "gauges": {"loss": float(i), "lr": 0.1},
                    "histograms": {},
                })
        data = agg.to_json()
        assert len(data["tasks"]) == 50
        assert len(data["series"]) == 100  # 50 tasks × 2 gauges
        for points in data["series"].values():
            assert len(points) <= 16
        assert data["heartbeats"]["w:0"] == 40

    def test_skewed_clock_keeps_series_monotonic(self):
        """An executor whose wall clock steps backwards must not
        interleave out-of-order points into the per-task series."""
        agg = MetricsAggregator()
        for ts in (100, 50, 150, 150, 149, 200):
            agg.ingest("w:0", {
                "ts_ms": ts, "counters": {},
                "gauges": {"loss": float(ts)}, "histograms": {},
            })
        series = agg.to_json()["series"]["w:0:loss"]
        stamps = [ts for ts, _ in series]
        assert stamps == [100, 150, 200]
        assert stamps == sorted(stamps)

    def test_non_numeric_ts_falls_back_to_coordinator_clock(self):
        agg = MetricsAggregator()
        agg.ingest("w:0", {"ts_ms": "yesterday", "counters": {},
                           "gauges": {"loss": 1.0}, "histograms": {}})
        ((ts, _),) = agg.to_json()["series"]["w:0:loss"]
        assert isinstance(ts, int) and ts > 0

    def test_health_fed_and_rendered(self):
        mon = HealthMonitor(HealthConfig(heartbeat_interval_ms=100))
        agg = MetricsAggregator(health=mon)
        for tid, st in (("w:0", 5.0), ("w:1", 5.0), ("w:2", 80.0)):
            agg.ingest(tid, _snap(gauges={"step_time_ms": st}))
        text = agg.prometheus_text()
        assert '# TYPE tony_task_straggler_score gauge' in text
        m = re.search(
            r'tony_task_straggler_score\{task="w:2"\} ([0-9.]+)', text
        )
        assert m and float(m.group(1)) > 3.0
        assert 'tony_task_straggler_score{task="w:0"} 0' in text

    def test_http_health_and_events_cursor(self):
        mon = HealthMonitor(HealthConfig())
        agg = MetricsAggregator(health=mon)
        agg.ingest("w:0", _snap(gauges={"loss": float("nan")}))
        events = obs_events.EventLog()
        events.emit(obs_events.TASK_REGISTERED, task="w:0")
        events.emit(obs_events.TASK_FINISHED, task="w:0", exit_code=0)
        server = ObservabilityHttpServer(agg, events=events,
                                         host="127.0.0.1")
        port = server.serve_background()
        base = f"http://127.0.0.1:{port}"
        try:
            health = json.loads(
                urllib.request.urlopen(f"{base}/api/health").read()
            )
            assert health["alerts"][0]["detector"] == "loss_nan"
            assert "w:0" in health["tasks"]
            # cursorless: the plain list (back-compat)
            plain = json.loads(
                urllib.request.urlopen(f"{base}/api/events").read()
            )
            assert isinstance(plain, list) and len(plain) == 2
            # cursor form: suffix + resume point
            tail = json.loads(urllib.request.urlopen(
                f"{base}/api/events?cursor=1"
            ).read())
            assert tail["cursor"] == 2
            assert [e["kind"] for e in tail["events"]] == ["task_finished"]
        finally:
            server.stop()


# ---------------------------------------------------------------------------
# events.jsonl hardening (satellite)
# ---------------------------------------------------------------------------
class TestEventsHardening:
    def test_sink_appends_whole_lines(self, tmp_path):
        path = tmp_path / "events.jsonl"
        sink = obs_events.jsonl_file_sink(path)
        sink({"kind": "a"})
        sink({"kind": "b", "task": "w:0"})
        lines = path.read_text().splitlines()
        assert [json.loads(x)["kind"] for x in lines] == ["a", "b"]

    def test_reader_and_doctor_skip_torn_tail(self, tmp_path):
        """A coordinator SIGKILLed mid-append leaves a truncated last
        line; the history reader and `tony doctor` must surface the
        rest of the timeline instead of raising."""
        from tony_tpu.history.reader import job_events
        from tony_tpu.history.writer import setup_job_dir

        job_dir = setup_job_dir(str(tmp_path), "application_torn_1",
                                int(time.time() * 1000))
        good = [
            {"kind": "job_submitted", "ts_ms": 1},
            {"kind": "task_finished", "task": "w:0", "exit_code": -9,
             "ts_ms": 2},
        ]
        text = "".join(json.dumps(e) + "\n" for e in good)
        (Path(job_dir) / "events.jsonl").write_text(
            text + '{"kind": "final_sta'  # torn tail, no newline
        )
        events = job_events(str(tmp_path), "application_torn_1")
        assert [e["kind"] for e in events] == ["job_submitted",
                                               "task_finished"]
        findings = postmortem.diagnose(events=events)
        assert findings and findings[0].rule_id == "TONY-D001"
        assert findings[0].task == "w:0"


# ---------------------------------------------------------------------------
# postmortem rules (TONY-D catalogue)
# ---------------------------------------------------------------------------
class TestPostmortem:
    def test_signal_kill_ranks_first_and_quotes_evidence(self):
        events = [
            {"kind": "session_started", "session": 1},
            {"kind": "health_alert", "detector": "straggler",
             "task": "w:1", "reason": "step time 80.0ms vs fleet "
                                      "median 5.0ms (score 200.0)"},
            {"kind": "task_finished", "task": "w:1", "exit_code": -9},
        ]
        final = {
            "state": "FAILED",
            "tasks": [{"id": "w:1", "exit_code": -9}],
            "stats": {"retries": [{
                "session": 1, "failure": "task_exit w:1 exit=-9",
                "category": "INFRA", "retried": False,
            }]},
        }
        findings = postmortem.diagnose(events=events, final=final)
        top = findings[0]
        assert top.rule_id == "TONY-D001" and top.task == "w:1"
        assert "SIGKILL" in top.cause
        assert any("exit_code=-9" in e for e in top.evidence)
        # the straggler corroboration is present, ranked below
        rules = [f.rule_id for f in findings]
        assert "TONY-D003" in rules
        assert rules.index("TONY-D001") < rules.index("TONY-D003")
        # corroborated straggler (same task as the failure) scores higher
        straggler = next(f for f in findings if f.rule_id == "TONY-D003")
        assert straggler.score == 65

    def test_user_permanent_beats_signal(self):
        final = {
            "state": "FAILED",
            "tasks": [{"id": "w:0", "exit_code": 127}],
            "stats": {"retries": [{
                "failure": "task_exit w:0 exit=127",
                "category": "USER_PERMANENT",
                "reason": "deterministic user failure",
            }]},
        }
        findings = postmortem.diagnose(final=final)
        assert findings[0].rule_id == "TONY-D007"
        assert "command not found" in " ".join(f.cause for f in findings)

    def test_heartbeat_expiry(self):
        events = [
            {"kind": "heartbeat_missed", "task": "w:2", "session": 1},
            {"kind": "health_alert", "detector": "heartbeat_jitter",
             "task": "w:2", "reason": "heartbeat gap 900ms exceeds 300ms"},
        ]
        findings = postmortem.diagnose(events=events)
        assert findings[0].rule_id == "TONY-D002"
        assert findings[0].task == "w:2"
        assert any("900ms" in e for e in findings[0].evidence)

    def test_step_anatomy_rule_reads_alert_and_final_snapshot(self):
        events = [
            {"kind": "health_alert", "detector": "mfu_collapse",
             "task": "worker:0",
             "reason": "mfu 0.001 collapsed below 0.5× recent median"},
        ]
        final = {"state": "SUCCEEDED", "metrics": {"tasks": {"worker:0": {
            "counters": {},
            "gauges": {
                'tony_step_phase_ms{phase="data_wait"}': 150.0,
                'tony_step_phase_ms{phase="compute"}': 15.0,
                'tony_step_phase_ms{phase="h2d"}': 0.0,
                'tony_step_phase_ms{phase="collective"}': 0.0,
                'tony_step_phase_ms{phase="host"}': 0.5,
            },
        }}}}
        findings = postmortem.diagnose(events=events, final=final)
        d12 = [f for f in findings if f.rule_id == "TONY-D012"]
        assert len(d12) == 1 and d12[0].task == "worker:0"
        # the terminal record corroborates with the dominant phase
        assert any("dominant phase data_wait" in e for e in d12[0].evidence)

    def test_comms_bound_alert_diagnosed_without_final(self):
        events = [
            {"kind": "health_alert", "detector": "comms_bound",
             "task": "worker:1",
             "reason": "collective time is 71% of the step"},
        ]
        findings = postmortem.diagnose(events=events)
        d12 = [f for f in findings if f.rule_id == "TONY-D012"]
        assert len(d12) == 1 and d12[0].task == "worker:1"
        assert "communication-bound" in d12[0].cause

    def test_rendezvous_rule_tolerates_sessionless_events(self):
        """Hand-edited / older-version timelines may lack session ids;
        the doctor must degrade, not traceback."""
        findings = postmortem.diagnose(
            events=[{"kind": "session_started"},
                    {"kind": "task_scheduled", "task": "w:0"}],
            final={"state": "FAILED"},
        )
        assert all(f.rule_id != "TONY-D006" for f in findings)

    def test_rendezvous_timeout(self):
        events = [
            {"kind": "session_started", "session": 1},
            {"kind": "task_scheduled", "task": "w:0", "session": 1},
            {"kind": "task_scheduled", "task": "w:1", "session": 1},
            {"kind": "task_registered", "task": "w:0", "session": 1},
        ]
        final = {"state": "FAILED"}
        findings = postmortem.diagnose(events=events, final=final)
        top = next(f for f in findings if f.rule_id == "TONY-D006")
        assert "1 of 2 tasks registered" in top.cause
        assert top.task == "w:1"

    def test_preemption_suppresses_generic_signal_rule(self):
        final = {
            "state": "FAILED",
            "tasks": [{"id": "w:3", "exit_code": -9}],
            "stats": {"retries": [{
                "failure": "preemption w:3 exit=-9 "
                           "backend-reported preemption",
                "category": "INFRA",
            }]},
        }
        findings = postmortem.diagnose(final=final)
        rules = [f.rule_id for f in findings]
        assert rules[0] == "TONY-D008"
        assert "TONY-D001" not in rules  # not double-reported

    def test_lost_coordinator_reads_blackbox(self):
        final = {"state": "FAILED",
                 "tasks": [{"id": "w:0", "exit_code": 87}]}
        blackboxes = {"blackbox-executor-w-0-s1.json": {
            "reason": "lost-coordinator", "task": "w:0",
            "rpcs": [{"method": "task_executor_heartbeat", "ok": False}] * 5,
        }}
        findings = postmortem.diagnose(final=final, blackboxes=blackboxes)
        assert findings[0].rule_id == "TONY-D009"
        assert any("5 failed heartbeat send(s)" in e
                   for e in findings[0].evidence)

    def test_task_id_prefix_does_not_corroborate(self):
        """'worker:1' must not match inside 'worker:10' when attributing
        the first failure — the cascade victim must not outrank the
        root cause."""
        final = {
            "state": "FAILED",
            "tasks": [{"id": "worker:10", "exit_code": -9},
                      {"id": "worker:1", "exit_code": -15}],
            "stats": {"retries": [{
                "failure": "task_exit worker:10 exit=-9",
                "category": "INFRA",
            }]},
        }
        findings = postmortem.diagnose(final=final)
        d001 = {f.task: f.score for f in findings
                if f.rule_id == "TONY-D001"}
        assert d001["worker:10"] == 80   # the recorded first failure
        assert d001["worker:1"] == 55    # cascade SIGTERM, demoted
        assert findings[0].task == "worker:10"

    def test_large_plain_exit_is_not_a_signal(self):
        """sys.exit(255) (or any unnamed 128+N code) is a plain exit —
        TONY-D011, not a 'killed by signal 127' misdiagnosis; the shell
        convention is only trusted for nameable signals (137 = KILL)."""
        findings = postmortem.diagnose(final={
            "state": "FAILED",
            "tasks": [{"id": "w:0", "exit_code": 255}],
        })
        assert findings[0].rule_id == "TONY-D011"
        assert all(f.rule_id != "TONY-D001" for f in findings)
        findings = postmortem.diagnose(final={
            "state": "FAILED",
            "tasks": [{"id": "w:0", "exit_code": 137}],
        })
        assert findings[0].rule_id == "TONY-D001"
        assert "SIGKILL" in findings[0].cause

    def test_timeout_and_empty_inputs(self):
        final = {"state": "FAILED",
                 "diagnostics": "application timed out after 1000ms"}
        findings = postmortem.diagnose(final=final)
        assert findings[0].rule_id == "TONY-D010"
        assert postmortem.diagnose() == []
        report = postmortem.format_report("app_1", [])
        assert "no adverse findings" in report

    def test_health_view_feeds_io_and_loss_rules(self):
        health = {"alerts": [
            {"detector": "io_stall", "task": "w:0",
             "reason": "input pipeline stalled 80% of the last 1000ms"},
        ]}
        findings = postmortem.diagnose(health=health)
        assert findings[0].rule_id == "TONY-D004"


# ---------------------------------------------------------------------------
# TONY-E001 event-catalogue lint + TONY-M001 declared-name extension
# ---------------------------------------------------------------------------
class TestEventCatalogueLint:
    def test_unknown_literal_kind_flagged(self, tmp_path):
        bad = tmp_path / "bad.py"
        bad.write_text("log.emit('totally_bogus_kind', task='w:0')\n")
        findings = check_event_catalogue([bad])
        assert len(findings) == 1
        assert findings[0].rule_id == "TONY-E001"
        assert "totally_bogus_kind" in findings[0].message

    def test_known_constant_and_literal_pass(self, tmp_path):
        ok = tmp_path / "ok.py"
        ok.write_text(
            "from tony_tpu.observability import events as obs_events\n"
            "log.emit(obs_events.TASK_FINISHED, exit_code=0)\n"
            "log.emit('health_alert', detector='straggler')\n"
            "handler.emit(record)\n"  # dynamic arg: ignored
        )
        assert check_event_catalogue([ok]) == []

    def test_removed_constant_reference_flagged(self, tmp_path):
        stale = tmp_path / "stale.py"
        stale.write_text("self.events.emit(obs_events.NO_SUCH_KIND)\n")
        findings = check_event_catalogue([stale])
        assert findings and "NO_SUCH_KIND" in findings[0].message

    def test_undocumented_kind_flagged(self, tmp_path):
        docs = tmp_path / "DEPLOY.md"
        docs.write_text("only `job_submitted` documented here")
        findings = check_event_catalogue([], docs=docs)
        flagged = {f.message.split("'")[1] for f in findings}
        assert "health_alert" in flagged
        assert "job_submitted" not in flagged

    def test_declared_metric_constants_linted(self, tmp_path):
        from tony_tpu.analysis.metrics_lint import check_metric_names

        mod = tmp_path / "m.py"
        mod.write_text(
            'GOOD_COUNTER = "things_total"\n'
            'BAD_GAUGE = "Not-Snake"\n'
            'WRONG_COUNTER = "missing_suffix"\n'
            'UNRELATED = "Whatever This Is"\n'
            "def f():\n"
            '    local_GAUGE = "not a metric declaration"\n'
            "    return local_GAUGE\n"
        )
        findings = check_metric_names([mod])
        msgs = " ".join(f.message for f in findings)
        assert "Not-Snake" in msgs and "missing_suffix" in msgs
        assert "Whatever" not in msgs
        # function-local strings are not declarations, whatever their name
        assert "not a metric declaration" not in msgs
        assert len(findings) == 2

    def test_health_float_keys_reject_nonfinite_and_nonpositive(self):
        from tony_tpu.analysis.config_check import check_config

        conf = TonyConfiguration()
        conf.set(keys.K_HEALTH_STRAGGLER_THRESHOLD, "nan")
        conf.set(keys.K_HEALTH_IO_STALL_RATIO, "0")
        findings = check_config(conf)
        msgs = " ".join(f.message for f in findings
                        if f.rule_id == "TONY-C002")
        assert "straggler-threshold" in msgs and "finite" in msgs
        assert "io-stall-ratio" in msgs
        conf2 = TonyConfiguration()
        conf2.set(keys.K_HEALTH_STRAGGLER_THRESHOLD, "2.5")
        assert not [f for f in check_config(conf2)
                    if f.rule_id == "TONY-C002"]


# ---------------------------------------------------------------------------
# tools/profile_step.py writes through $TONY_METRICS_FILE (satellite)
# ---------------------------------------------------------------------------
def test_profile_step_registry_publishes_to_metrics_file(
    tmp_path, monkeypatch,
):
    sys.path.insert(0, str(Path(__file__).resolve().parents[1] / "tools"))
    try:
        import profile_step
    finally:
        sys.path.pop(0)
    out = tmp_path / "report.json"
    monkeypatch.setenv("TONY_METRICS_FILE", str(out))
    reg = profile_step.make_registry()
    reg.gauge("profile_device_total_ms").set(12.5)
    reg.flush()
    snap = json.loads(out.read_text())
    assert snap["gauges"]["profile_device_total_ms"] == 12.5
    # without the env the registry is purely in-memory
    monkeypatch.delenv("TONY_METRICS_FILE")
    reg2 = profile_step.make_registry()
    assert reg2._publish_path is None


# ---------------------------------------------------------------------------
# tony events --follow (cursor tail)
# ---------------------------------------------------------------------------
def test_events_follow_live_then_drains_staging(tmp_path, capsys):
    from tony_tpu.client import cli

    staging = tmp_path / "staging"
    app_dir = staging / "application_follow_1"
    app_dir.mkdir(parents=True)
    events = obs_events.EventLog(
        sink=obs_events.jsonl_file_sink(app_dir / "events.jsonl")
    )
    events.emit(obs_events.JOB_SUBMITTED, app_id="application_follow_1")
    events.emit(obs_events.SESSION_STARTED, session=1)
    server = ObservabilityHttpServer(
        MetricsAggregator(), events=events, host="127.0.0.1"
    )
    port = server.serve_background()
    (app_dir / "coordinator.http").write_text(f"127.0.0.1:{port}\n")
    try:
        rc = cli.main([
            "events", "application_follow_1", "--follow", "--max-polls",
            "1", "--staging-location", str(staging),
        ])
        out = capsys.readouterr().out
        assert rc == 0
        assert "job_submitted" in out and "session_started" in out
    finally:
        server.stop()
    # Coordinator gone: --follow drains the staging events.jsonl instead.
    events.emit(obs_events.FINAL_STATUS, state="SUCCEEDED")
    rc = cli.main([
        "events", "application_follow_1", "--follow",
        "--staging-location", str(staging),
    ])
    out = capsys.readouterr().out
    assert rc == 0
    assert "final_status" in out
    # --follow --json streams one parseable object per line
    rc = cli.main([
        "events", "application_follow_1", "--follow", "--json",
        "--staging-location", str(staging),
    ])
    out = capsys.readouterr().out
    assert rc == 0
    parsed = [json.loads(line) for line in out.splitlines() if line]
    assert [e["kind"] for e in parsed][-1] == "final_status"


# ---------------------------------------------------------------------------
# mini-cluster chaos e2e — the acceptance scenario
# ---------------------------------------------------------------------------
def test_health_chaos_e2e_straggler_kill_blackbox_doctor(tmp_path, capsys):
    """Seeded fault plan (delay_heartbeats + kill_task) against a 3-worker
    jax-free job where worker:1 also reports straggler step times:

    * a nonzero tony_task_straggler_score{task="worker:1"} appears on the
      live /metrics;
    * health_alert events land in events.jsonl (persisted to history);
    * blackbox-*.json dumps are persisted to history;
    * `tony doctor` names worker:1 / the injected kill in its top-ranked
      finding."""
    cluster = MiniTonyCluster(tmp_path)
    conf = cluster.base_conf()
    conf.set(keys.K_EXECUTES, str(FIXTURES / "health_train.py"))
    conf.set(keys.K_PYTHON_BINARY, sys.executable)
    conf.set(keys.instances_key("worker"), 3)
    conf.set(keys.instances_key("ps"), 0)
    conf.set(keys.K_TASK_HEARTBEAT_INTERVAL_MS, 150)
    conf.set(keys.K_HEALTH_HB_JITTER_FACTOR, 2.0)
    # Subprocess startup (executor spawn + user-process imports) can eat
    # 10+ seconds on this 1-core box; the fixture reports for ~28s and
    # the timed kill lands at 20s, leaving a wide live window in which
    # all three workers are reporting step times.
    conf.set(keys.K_SHELL_ENV,
             "STRAGGLER_TASK=worker:1,FIXTURE_STEPS=350,LINGER_S=2.0")
    conf.set(keys.K_FAULT_PLAN, json.dumps({
        "seed": 5,
        "faults": [
            {"action": "delay_heartbeats", "target": "worker:1",
             "ms": 500, "count": 4},
            {"action": "kill_task", "target": "worker:1",
             "after_ms": 20000},
        ],
    }))

    app_id = "application_mini_health1"
    app_dir = cluster.staging_dir / app_id
    app_dir.mkdir(parents=True)
    conf.write_final(app_dir / constants.TONY_FINAL_CONF)
    coordinator = TonyCoordinator(
        conf, app_dir, app_id=app_id,
        backend=LocalProcessBackend(app_dir / "logs"),
    )
    result = []
    t = threading.Thread(
        target=lambda: result.append(coordinator.run()), daemon=True
    )
    cluster._live.append(coordinator)
    t.start()
    try:
        # -- live: a nonzero straggler score for worker:1 on /metrics ----
        deadline = time.monotonic() + 90
        addr_file = app_dir / "coordinator.http"
        while not addr_file.is_file() and time.monotonic() < deadline:
            time.sleep(0.05)
        assert addr_file.is_file(), "coordinator.http never advertised"
        addr = addr_file.read_text().strip()
        score = 0.0
        pattern = re.compile(
            r'tony_task_straggler_score\{task="worker:1"\} ([0-9.eE+]+)'
        )
        while time.monotonic() < deadline:
            try:
                text = urllib.request.urlopen(
                    f"http://{addr}/metrics", timeout=5
                ).read().decode()
            except OSError:
                time.sleep(0.1)
                continue
            m = pattern.search(text)
            if m and float(m.group(1)) > 0:
                score = float(m.group(1))
                break
            time.sleep(0.1)
        assert score > 0, (
            "tony_task_straggler_score{task=\"worker:1\"} never went "
            "nonzero on the live /metrics"
        )
    finally:
        t.join(timeout=120)
    assert result and result[0] is SessionStatus.FAILED, (
        coordinator.session.diagnostics if coordinator.session else "no run"
    )

    # -- health_alert events persisted to history ------------------------
    event_files = list(cluster.history_dir.rglob("events.jsonl"))
    assert len(event_files) == 1
    events = obs_events.parse_jsonl(event_files[0].read_text())
    health_alerts = [e for e in events if e["kind"] == "health_alert"]
    assert any(a.get("task") == "worker:1"
               and a.get("detector") == "straggler"
               for a in health_alerts), health_alerts
    # the injected heartbeat delays register as jitter on the
    # coordinator's clock
    assert any(a.get("detector") == "heartbeat_jitter"
               and a.get("task") == "worker:1"
               for a in health_alerts), health_alerts

    # -- blackboxes persisted to history ---------------------------------
    history_blackboxes = [
        p for p in cluster.history_dir.rglob("blackbox-*.json")
    ]
    names = sorted(p.name for p in history_blackboxes)
    assert any("task-failure" in n for n in names), names
    assert any("final-status" in n for n in names), names
    doc = json.loads(next(
        p for p in history_blackboxes if "task-failure" in p.name
    ).read_text())
    assert doc["reason"] == "task-failure"
    # the ring captured heartbeat frames and per-step reports
    assert any(r.get("method") == "task_executor_heartbeat"
               for r in doc["rpcs"])
    assert any(r.get("task") == "worker:1" for r in doc["reports"])
    assert doc["health"]["alerts"], "blackbox carries the health state"

    # -- retry record carries the active health alerts -------------------
    final = json.loads((app_dir / "final-status.json").read_text())
    retries = final["stats"]["retries"]
    assert retries and retries[0]["health_alerts"], retries
    assert any(a["task"] == "worker:1"
               for a in retries[0]["health_alerts"])

    # -- tony doctor: top-ranked finding names the injected task ---------
    findings = postmortem.diagnose(
        events=events, final=final,
        blackboxes={p.name: json.loads(p.read_text())
                    for p in history_blackboxes},
    )
    assert findings, "doctor found nothing"
    top = findings[0]
    assert top.rule_id == "TONY-D001"
    assert top.task == "worker:1"
    assert "SIGKILL" in top.cause
    # straggler corroboration rides along, ranked below the kill
    assert any(f.rule_id == "TONY-D003" and f.task == "worker:1"
               for f in findings)

    from tony_tpu.client import cli

    rc = cli.main([
        "doctor", app_id, "--staging-location", str(cluster.staging_dir),
        "--history-location", str(cluster.history_dir),
    ])
    out = capsys.readouterr().out
    assert rc == 0
    first_finding = next(line for line in out.splitlines()
                         if line.startswith("#1"))
    assert "TONY-D001" in first_finding and "worker:1" in first_finding


def test_executor_blackbox_on_user_exit_e2e(tmp_path, capsys):
    """A user script that exits nonzero leaves an executor blackbox in
    the scratch dir; the coordinator persists it to history and the
    per-job Diagnosis panel renders the postmortem."""
    cluster = MiniTonyCluster(tmp_path)
    conf = cluster.base_conf()
    conf.set(keys.K_EXECUTES, str(FIXTURES / "exit_1.py"))
    conf.set(keys.K_PYTHON_BINARY, sys.executable)
    conf.set(keys.instances_key("worker"), 1)
    conf.set(keys.instances_key("ps"), 0)
    status, coord = cluster.run_job(conf)
    assert status is SessionStatus.FAILED
    logs_boxes = list((coord.app_dir / "logs").glob("blackbox-*.json"))
    assert len(logs_boxes) == 1
    doc = json.loads(logs_boxes[0].read_text())
    assert doc["reason"] == "user-exit-1"
    assert doc["proc"].startswith("executor:worker:0")
    # persisted to history alongside the coordinator's dumps
    hist_names = sorted(
        p.name for p in cluster.history_dir.rglob("blackbox-*.json")
    )
    assert logs_boxes[0].name in hist_names
    assert any("coordinator" in n for n in hist_names)

    # reader surfaces them; the history server renders a Diagnosis panel
    from tony_tpu.history.reader import job_blackboxes
    from tony_tpu.history.server import HistoryServer

    boxes = job_blackboxes(cluster.history_dir, coord.app_id)
    assert boxes and logs_boxes[0].name in boxes
    server = HistoryServer(str(cluster.history_dir), port=0)
    port = server.serve_background()
    try:
        page = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/job/{coord.app_id}", timeout=5
        ).read().decode()
        assert "Diagnosis" in page
    finally:
        server.stop()
