"""Byte-heavy data-plane tests: the parallel span readers, the rollover
batch assembly, the threaded ``device_prefetch`` pipeline's edge
semantics, the on-device uint8 decode contract, and the ``tony_io_*``
telemetry — the machinery behind the streamed-ResNet acceptance numbers
in ``bench_input_pipeline``."""

import time

import numpy as np
import pytest

from tony_tpu.io import (
    DevicePrefetcher,
    ShardedRecordReader,
    device_prefetch,
)
from tony_tpu.io.reader import _IoMetrics


def _write_tokens(path, n_rec, rl, dtype=np.uint16, seed=0):
    rng = np.random.default_rng(seed)
    data = rng.integers(
        0, np.iinfo(dtype).max, size=(n_rec, rl)
    ).astype(dtype)
    data.tofile(path)
    return data


# ---------------------------------------------------------------------------
# device_prefetch edge semantics (threaded pipeline)
# ---------------------------------------------------------------------------
class TestDevicePrefetchEdges:
    def test_producer_exception_surfaces_after_successes(self):
        """A source failure AFTER `depth` successful puts must reach the
        consumer at the position it occurred — not read as a clean end of
        stream once the earlier batches drain."""

        def src():
            for i in range(4):
                yield np.full((2,), i, np.int32)
            raise OSError("disk died mid-shard")

        it = device_prefetch(src(), depth=2)
        got = [np.asarray(it.__next__())[0] for _ in range(4)]
        assert got == [0, 1, 2, 3]
        with pytest.raises(OSError, match="disk died"):
            next(it)
        # sticky: a catch-and-retry consumer keeps failing loudly
        with pytest.raises(OSError, match="disk died"):
            next(it)
        it.close()

    def test_transfer_exception_surfaces_in_order(self):
        """A failed device put surfaces like a producer failure — via the
        future at its position in the stream."""
        calls = []

        def bad_put(b):
            calls.append(int(b[0]))
            if int(b[0]) == 2:
                raise RuntimeError("transfer rejected")
            return b

        src = (np.full((1,), i, np.int32) for i in range(5))
        it = DevicePrefetcher(src, depth=3, put_fn=bad_put)
        assert int(next(it)[0]) == 0
        assert int(next(it)[0]) == 1
        with pytest.raises(RuntimeError, match="transfer rejected"):
            next(it)
        with pytest.raises(RuntimeError, match="transfer rejected"):
            next(it)  # sticky
        it.close()

    def test_depth_one_degenerates_to_eager(self):
        """depth=1: the in-flight bound covers the yielded batch, so the
        source advances only when the consumer asks — no lookahead."""
        pulled = []

        def src():
            for i in range(3):
                pulled.append(i)
                yield np.full((1,), i, np.int32)

        it = device_prefetch(src(), depth=1)
        next(it)
        time.sleep(0.05)
        assert pulled == [0], pulled
        next(it)
        time.sleep(0.05)
        assert pulled == [0, 1], pulled
        it.close()

    def test_close_mid_iteration_does_not_deadlock(self):
        """close() with a full pipeline and an unbounded source must
        release the transfer thread promptly (the slot wait polls the
        stop event) — an abandoned fetcher would leak a thread per
        epoch."""

        def endless():
            i = 0
            while True:
                yield np.full((4,), i, np.int32)
                i += 1

        it = device_prefetch(endless(), depth=2)
        next(it)
        t0 = time.monotonic()
        it.close()
        assert time.monotonic() - t0 < 3
        it._thread.join(timeout=3)
        assert not it._thread.is_alive()

    def test_reader_close_unblocks_prefetcher(self, tmp_path):
        """Closing the reader mid-epoch must terminate the stream for a
        prefetcher blocked on its queue — the transfer thread sees
        end-of-stream instead of hanging, and close() stays prompt."""
        rl = 8
        p = tmp_path / "c.bin"
        _write_tokens(p, 2000, rl)
        reader = ShardedRecordReader(
            [str(p)], fmt="tokens", record_len=rl, dtype=np.uint16,
            batch_size=4, buffer_records=64,
        )
        it = device_prefetch(
            (b for b in reader), depth=2, transfer_workers=1
        )
        next(it)
        reader.close()
        t0 = time.monotonic()
        it.close()
        assert time.monotonic() - t0 < 3
        it._thread.join(timeout=3)
        assert not it._thread.is_alive()

    def test_context_manager_closes(self):
        with device_prefetch(
            (np.zeros(2, np.int32) for _ in range(100)), depth=2
        ) as it:
            next(it)
        assert not it._thread.is_alive()

    def test_next_after_close_terminates(self):
        """next() on a closed pipeline must raise StopIteration, not hang
        on the drained queue."""
        it = device_prefetch(
            (np.zeros(2, np.int32) for _ in range(10)), depth=2
        )
        next(it)
        it.close()
        with pytest.raises(StopIteration):
            next(it)

    def test_abandoned_prefetcher_thread_shuts_down(self):
        """A prefetcher dropped without close() must not pin its producer
        thread forever: the thread holds only a weakref, so collection of
        the abandoned object stops the loop."""
        import gc
        import weakref

        def src():
            i = 0
            while True:
                yield np.full((2,), i, np.int32)
                i += 1

        it = device_prefetch(src(), depth=2)
        next(it)
        thread = it._thread
        ref = weakref.ref(it)
        del it
        deadline = time.monotonic() + 10
        while thread.is_alive() and time.monotonic() < deadline:
            gc.collect()
            time.sleep(0.05)
        assert ref() is None
        assert not thread.is_alive()

    def test_exhausted_stream_keeps_raising_stopiteration(self):
        it = device_prefetch(iter([np.zeros(1, np.int32)]), depth=2)
        next(it)
        with pytest.raises(StopIteration):
            next(it)
        with pytest.raises(StopIteration):
            next(it)
        it.close()


# ---------------------------------------------------------------------------
# parallel span readers
# ---------------------------------------------------------------------------
class TestParallelReaders:
    @pytest.mark.parametrize("workers", [1, 4])
    def test_exactly_once_across_tasks(self, tmp_path, workers):
        rl, n_rec = 8, 103
        p = tmp_path / "t.bin"
        data = np.arange(rl * n_rec, dtype=np.uint16).reshape(n_rec, rl)
        data.tofile(p)
        seen = []
        for t in range(4):
            with ShardedRecordReader(
                [str(p)], t, 4, fmt="tokens", record_len=rl,
                dtype=np.uint16, batch_size=10, read_workers=workers,
            ) as r:
                for batch in r:
                    seen.extend(batch[:, 0].tolist())
        assert sorted(seen) == [i * rl for i in range(n_rec)]

    @pytest.mark.parametrize("workers", [1, 4])
    def test_order_is_stream_order(self, tmp_path, workers):
        """Parallel reads must come back in submission order — batch N is
        byte-identical to records [N*bs, (N+1)*bs) regardless of worker
        count or chunk size."""
        rl = 16
        p = tmp_path / "big.bin"
        data = _write_tokens(p, 1000, rl)
        with ShardedRecordReader(
            [str(p)], fmt="tokens", record_len=rl, dtype=np.uint16,
            batch_size=64, read_workers=workers, chunk_records=32,
        ) as r:
            got = np.concatenate([b for b in r])
        np.testing.assert_array_equal(got, data)

    def test_native_and_python_paths_identical_under_pool(
        self, tmp_path, monkeypatch
    ):
        """The tier-1 pin: with the worker pool active, the native pread
        kernel and the pure-Python preadv fallback produce byte-identical
        streams (satellite: CI floor for the new read path)."""
        from tony_tpu.io import native

        rl = 8
        p = tmp_path / "pin.bin"
        data = _write_tokens(p, 517, rl, seed=3)

        def read_all(force_py):
            if force_py:
                monkeypatch.setattr(native, "available", lambda: False)
            try:
                with ShardedRecordReader(
                    [str(p)], fmt="tokens", record_len=rl,
                    dtype=np.uint16, batch_size=50, read_workers=4,
                    chunk_records=16,
                ) as r:
                    return np.concatenate([b for b in r])
            finally:
                monkeypatch.undo()

        py = read_all(True)
        np.testing.assert_array_equal(py, data)
        if native.available():
            np.testing.assert_array_equal(read_all(False), py)

    def test_multi_file_parallel(self, tmp_path):
        rl = 4
        parts, expect = [], []
        for fi, n in enumerate([77, 3, 130]):
            p = tmp_path / f"part-{fi}.bin"
            expect.append(_write_tokens(p, n, rl, seed=fi))
            parts.append(str(p))
        with ShardedRecordReader(
            parts, fmt="tokens", record_len=rl, dtype=np.uint16,
            batch_size=32, read_workers=3, chunk_records=8,
        ) as r:
            got = np.concatenate([b for b in r])
        np.testing.assert_array_equal(got, np.concatenate(expect))

    def test_gs_ranged_reads_parallel_match_local(self, tmp_path):
        from tony_tpu.cloud import default_storage, set_default_storage
        from tony_tpu.cloud.gcs import FileObjectStorage

        set_default_storage(FileObjectStorage(tmp_path / "obj"))
        try:
            rl, n_rec = 8, 300
            local = tmp_path / "t.bin"
            data = _write_tokens(local, n_rec, rl)
            default_storage().put_bytes(
                "gs://corpus/t.bin", local.read_bytes()
            )
            with ShardedRecordReader(
                ["gs://corpus/t.bin"], fmt="tokens", record_len=rl,
                dtype=np.uint16, batch_size=37, read_workers=4,
                chunk_records=16,
            ) as r:
                got = np.concatenate([b for b in r])
            np.testing.assert_array_equal(got, data)
            # writable: the single-copy ranged-read fix must not hand
            # out read-only frombuffer views
            assert got.flags.writeable
        finally:
            set_default_storage(None)

    def test_illegal_explicit_knobs_rejected(self, tmp_path):
        p = tmp_path / "z.bin"
        _write_tokens(p, 4, 4)
        for kw in ({"chunk_records": 0}, {"read_workers": 0}):
            with pytest.raises(ValueError):
                ShardedRecordReader(
                    [str(p)], fmt="tokens", record_len=4,
                    dtype=np.uint16, batch_size=4, **kw,
                )

    def test_queue_bounded_in_bytes_for_byte_heavy_records(self, tmp_path):
        """Image-sized records must cap BOTH the per-chunk bytes and the
        total queue bytes — the buffer must not balloon to buffer_records
        worth of 147 KB rows."""
        rec = 224 * 224 * 3
        p = tmp_path / "img.bin"
        np.zeros((4, rec), np.uint8).tofile(p)
        with ShardedRecordReader(
            [str(p)], fmt="tokens", dtype=np.uint8, record_len=rec,
            batch_size=2,
        ) as r:
            chunk_bytes = r._chunk_rows * rec
            assert chunk_bytes <= r._CHUNK_BYTES_CAP
            assert r._queue.maxsize * chunk_bytes <= r._QUEUE_BYTES_CAP
            assert sum(len(b) for b in r) == 4

    def test_env_knobs_reach_reader(self, tmp_path, monkeypatch):
        monkeypatch.setenv("TONY_IO_CHUNK_RECORDS", "7")
        monkeypatch.setenv("TONY_IO_READ_WORKERS", "2")
        p = tmp_path / "e.bin"
        _write_tokens(p, 10, 4)
        with ShardedRecordReader(
            [str(p)], fmt="tokens", record_len=4, dtype=np.uint16,
            batch_size=4,
        ) as r:
            assert r.chunk_records == 7
            assert r.read_workers == 2
        with ShardedRecordReader(  # explicit args win over env
            [str(p)], fmt="tokens", record_len=4, dtype=np.uint16,
            batch_size=4, chunk_records=3, read_workers=5,
        ) as r:
            assert r.chunk_records == 3
            assert r.read_workers == 5


# ---------------------------------------------------------------------------
# rollover batch assembly
# ---------------------------------------------------------------------------
class TestRollingAssembly:
    @pytest.mark.parametrize("batch,chunk", [
        (100, 64),   # batches cross chunk boundaries
        (7, 16),     # several batches per chunk, misaligned
        (32, 32),    # aligned: every batch is a zero-copy view
        (256, 8),    # batch spans many chunks
    ])
    def test_batches_identical_to_records(self, tmp_path, batch, chunk):
        rl = 8
        p = tmp_path / "r.bin"
        data = _write_tokens(p, 403, rl, seed=batch)
        with ShardedRecordReader(
            [str(p)], fmt="tokens", record_len=rl, dtype=np.uint16,
            batch_size=batch, chunk_records=chunk,
        ) as r:
            batches = list(r)
        for b in batches[:-1]:
            assert b.shape == (batch, rl)
        got = np.concatenate(batches)
        np.testing.assert_array_equal(got, data)

    def test_zero_copy_batches_are_writable_and_independent(self, tmp_path):
        """Aligned batches are views into the span buffer; mutating one
        batch in place (masking) must not corrupt its neighbours."""
        rl = 4
        p = tmp_path / "w.bin"
        data = _write_tokens(p, 64, rl)
        with ShardedRecordReader(
            [str(p)], fmt="tokens", record_len=rl, dtype=np.uint16,
            batch_size=16, chunk_records=16,
        ) as r:
            first = r.next_batch()
            first *= 0  # consumer masks in place
            second = r.next_batch()
        np.testing.assert_array_equal(second, data[16:32])

    def test_tail_batch_short(self, tmp_path):
        p = tmp_path / "tail.bin"
        _write_tokens(p, 41, 4)
        with ShardedRecordReader(
            [str(p)], fmt="tokens", record_len=4, dtype=np.uint16,
            batch_size=16, chunk_records=8,
        ) as r:
            sizes = [len(b) for b in r]
        assert sizes == [16, 16, 9]


# ---------------------------------------------------------------------------
# on-device decode contract + end-to-end streamed training
# ---------------------------------------------------------------------------
class TestOnDeviceDecode:
    def test_resnet_decodes_uint8_like_prescaled_float(self):
        import jax.numpy as jnp

        from tony_tpu.models import ResNetConfig, resnet_apply, resnet_init
        import jax

        cfg = ResNetConfig(depth=18, width=8, n_classes=4, dtype="float32")
        params = resnet_init(jax.random.key(0), cfg)
        raw = np.random.default_rng(0).integers(
            0, 256, (2, 32, 32, 3), dtype=np.uint8
        )
        logits_u8 = resnet_apply(params, jnp.asarray(raw), cfg)
        logits_f32 = resnet_apply(
            params, jnp.asarray(raw, jnp.float32) / 255.0, cfg
        )
        np.testing.assert_allclose(
            np.asarray(logits_u8), np.asarray(logits_f32),
            rtol=1e-4, atol=1e-4,
        )

    def test_streamed_uint8_training_end_to_end(self, tmp_path):
        """The whole acceptance pipeline in miniature: uint8 records on
        disk → parallel reader → threaded device_prefetch (uint8 over
        H2D) → jitted step with on-device normalize — losses stay finite
        and every layer's telemetry fires."""
        import jax
        import jax.numpy as jnp

        from tony_tpu.models import (
            make_image_classifier_step, uint8_image_normalizer,
        )
        from tony_tpu.parallel.mesh import MeshSpec, build_mesh
        from jax.sharding import NamedSharding, PartitionSpec as P

        size, classes, batch = 8, 4, 16
        rec = size * size * 3
        p = tmp_path / "img.bin"
        rng = np.random.default_rng(0)
        rng.integers(0, 256, (8 * batch, rec), dtype=np.uint8).tofile(p)

        mesh = build_mesh(MeshSpec(), devices=jax.devices()[:1])

        def apply_fn(params, images):
            flat = images.reshape(images.shape[0], -1)
            return flat @ params["w"] + params["b"]

        init_fn, step_fn = make_image_classifier_step(
            lambda key: {
                "w": jax.random.normal(key, (rec, classes)) * 0.01,
                "b": jnp.zeros((classes,)),
            },
            apply_fn,
            mesh,
            preprocess=uint8_image_normalizer(mean=127.5, std=127.5),
        )
        labels = jnp.asarray(rng.integers(0, classes, (batch,)), jnp.int32)
        sharding = NamedSharding(mesh, P(("dp", "ep")))
        metrics = _IoMetrics.get()
        h2d0 = metrics.h2d_bytes.value
        read0 = metrics.bytes_read.value
        with jax.sharding.set_mesh(mesh):
            state = init_fn(jax.random.key(1))
            with ShardedRecordReader(
                [str(p)], fmt="tokens", dtype=np.uint8, record_len=rec,
                batch_size=batch, read_workers=2,
            ) as reader:
                def batches():
                    for b in reader:
                        if len(b) == batch:
                            yield b.reshape(batch, size, size, 3)

                with device_prefetch(batches(), sharding, depth=3) as it:
                    losses = []
                    for img in it:
                        assert img.dtype == jnp.uint8  # bytes over H2D
                        state, m = step_fn(state, img, labels)
                        losses.append(float(m["loss"]))
        assert len(losses) == 8
        assert all(np.isfinite(losses))
        assert metrics.bytes_read.value - read0 >= 8 * batch * rec
        assert metrics.h2d_bytes.value - h2d0 >= 8 * batch * rec

    def test_to_global_batch_skips_placed_arrays(self):
        """A batch the prefetcher already placed with the step's sharding
        must pass through _to_global_batch untouched — the second
        device_put per batch was half the H2D bill."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from tony_tpu.models.train import _to_global_batch
        from tony_tpu.parallel.mesh import MeshSpec, build_mesh

        mesh = build_mesh(MeshSpec(), devices=jax.devices()[:1])
        sharding = NamedSharding(mesh, P(("dp", "ep")))
        placed = jax.device_put(np.zeros((4, 3), np.float32), sharding)
        assert _to_global_batch(placed, sharding) is placed
        # numpy input still takes the put
        out = _to_global_batch(np.zeros((4, 3), np.float32), sharding)
        assert isinstance(out, jax.Array)


# ---------------------------------------------------------------------------
# telemetry
# ---------------------------------------------------------------------------
class TestIoTelemetry:
    def test_reader_metrics_registered_and_counted(self, tmp_path):
        from tony_tpu import observability

        names = observability.default_registry().names()
        p = tmp_path / "m.bin"
        _write_tokens(p, 100, 8)
        m = _IoMetrics.get()
        before = m.bytes_read.value
        with ShardedRecordReader(
            [str(p)], fmt="tokens", record_len=8, dtype=np.uint16,
            batch_size=10,
        ) as r:
            list(r)
        assert m.bytes_read.value - before == 100 * 16
        names = observability.default_registry().names()
        for required in (
            "tony_io_bytes_read_total", "tony_io_read_ms",
            "tony_io_assemble_ms", "tony_io_batch_wait_ms",
            "tony_io_prefetch_queue_depth", "tony_io_h2d_bytes_total",
            "tony_io_h2d_ms", "tony_io_queue_wait_ms",
            "tony_io_h2d_inflight_depth",
        ):
            assert required in names

    def test_metrics_render_to_prometheus(self):
        from tony_tpu import observability

        _IoMetrics.get()
        text = observability.default_registry().to_prometheus()
        assert "tony_io_bytes_read_total" in text
        assert "tony_io_h2d_ms_bucket" in text


# ---------------------------------------------------------------------------
# throughput floor (slow): the reader must sustain real record rates on
# the CPU fallback path — a regression that serializes the pool or
# reintroduces per-batch concatenation shows up here long before a TPU
# bench runs.
# ---------------------------------------------------------------------------
@pytest.mark.slow
class TestThroughputFloor:
    FLOOR_RECORDS_PER_SEC = 50_000

    def test_python_fallback_sustains_floor(self, tmp_path, monkeypatch):
        from tony_tpu.io import native

        monkeypatch.setattr(native, "available", lambda: False)
        rl, n_rec = 32, 200_000  # 12.8 MB corpus
        p = tmp_path / "floor.bin"
        rng = np.random.default_rng(0)
        rng.integers(0, 2**16, (n_rec, rl)).astype(np.uint16).tofile(p)
        with ShardedRecordReader(
            [str(p)], fmt="tokens", record_len=rl, dtype=np.uint16,
            batch_size=512, read_workers=4,
        ) as r:
            t0 = time.perf_counter()
            total = sum(len(b) for b in r)
            dt = time.perf_counter() - t0
        assert total == n_rec
        rate = total / dt
        assert rate >= self.FLOOR_RECORDS_PER_SEC, (
            f"python fallback read {rate:,.0f} records/s, floor is "
            f"{self.FLOOR_RECORDS_PER_SEC:,}"
        )
