"""TONY-X dispatch-discipline lint: each rule against its bad/good
fixture pair, waiver syntax, docs drift, and the single-module
preflight entry point."""

from pathlib import Path

from tony_tpu.analysis.dispatch import (
    ALL_RULES,
    RULE_DONATION,
    RULE_HOST_SYNC,
    RULE_JIT_IN_LOOP,
    RULE_KEY_REUSE,
    RULE_RETRACE,
    RULE_SHARDING,
    check_dispatch,
    check_rule_docs,
    lint_dispatch_source,
)

REPO = Path(__file__).resolve().parents[1]
FIX = REPO / "tests" / "fixtures" / "lint"


def run(name):
    return check_dispatch([FIX / name])


def rules(findings):
    return sorted({f.rule_id for f in findings})


class TestJitInLoop:
    def test_all_three_shapes_flagged(self):
        findings = [f for f in run("x001_bad.py")
                    if f.rule_id == RULE_JIT_IN_LOOP]
        # In-loop construction, immediate invocation, and the
        # construct-dispatch-once-discard local binding (anchored at
        # the dispatch site).
        assert len(findings) == 3
        joined = " | ".join(f.message for f in findings)
        assert "inside a loop" in joined
        assert "one expression" in joined
        assert "discarded" in joined

    def test_module_binding_and_closure_capture_clean(self):
        assert [f for f in run("x001_good.py")
                if f.rule_id == RULE_JIT_IN_LOOP] == []


class TestHostSync:
    def test_cast_branch_and_helper_propagation_flagged(self):
        findings = [f for f in run("x002_bad.py")
                    if f.rule_id == RULE_HOST_SYNC]
        assert len(findings) == 3
        joined = " | ".join(f.message for f in findings)
        assert "host cast" in joined
        assert "implicit bool()" in joined
        # Call-graph propagation: the helper that float()s its
        # argument flags the CALL SITE inside the step loop.
        assert "log_metrics" in joined

    def test_post_loop_fence_clean(self):
        assert [f for f in run("x002_good.py")
                if f.rule_id == RULE_HOST_SYNC] == []


class TestRetraceHazard:
    def test_loop_index_len_and_weak_float_flagged(self):
        findings = [f for f in run("x003_bad.py")
                    if f.rule_id == RULE_RETRACE]
        assert len(findings) == 3
        joined = " | ".join(f.message for f in findings)
        assert "loop index `i`" in joined
        assert "len(...)" in joined
        assert "weak-typed" in joined

    def test_static_argnums_clean(self):
        assert [f for f in run("x003_good.py")
                if f.rule_id == RULE_RETRACE] == []

    def test_data_iteration_is_not_a_loop_index(self):
        # ``for batch in batches`` yields data, not Python ints — the
        # X001 fixture dispatches its for-target and must not trip X003.
        assert [f for f in run("x001_bad.py")
                if f.rule_id == RULE_RETRACE] == []


class TestDonation:
    def test_read_after_donation_flagged(self):
        findings = [f for f in run("x004_bad.py")
                    if f.rule_id == RULE_DONATION]
        assert len(findings) == 1
        assert "donated" in findings[0].message

    def test_rebound_result_clean(self):
        assert [f for f in run("x004_good.py")
                if f.rule_id == RULE_DONATION] == []


class TestShardingDrift:
    def test_in_without_out_flagged(self):
        findings = [f for f in run("x005_bad.py")
                    if f.rule_id == RULE_SHARDING]
        assert len(findings) == 1
        assert "out_shardings" in findings[0].message

    def test_both_sides_clean(self):
        assert [f for f in run("x005_good.py")
                if f.rule_id == RULE_SHARDING] == []


class TestKeyReuse:
    def test_double_draw_and_loop_draw_flagged(self):
        findings = [f for f in run("x006_bad.py")
                    if f.rule_id == RULE_KEY_REUSE]
        assert len(findings) == 2
        joined = " | ".join(f.message for f in findings)
        assert "reused here" in joined
        assert "inside a loop" in joined

    def test_split_per_consumer_clean(self):
        assert [f for f in run("x006_good.py")
                if f.rule_id == RULE_KEY_REUSE] == []


class TestWaivers:
    def test_both_spellings_suppress(self):
        assert run("x_noqa_waived.py") == []

    def test_unwaived_copy_still_fires(self, tmp_path):
        src = (FIX / "x_noqa_waived.py").read_text()
        stripped = "\n".join(
            line.split("  # tony: noqa")[0] for line in src.splitlines()
        )
        (tmp_path / "m.py").write_text(stripped + "\n")
        assert rules(check_dispatch([tmp_path])) == sorted(
            [RULE_HOST_SYNC, RULE_JIT_IN_LOOP]
        )


class TestDocsDrift:
    def test_repo_docs_cover_every_rule(self):
        assert check_rule_docs(REPO / "docs" / "DEPLOY.md") == []

    def test_missing_rows_reported(self, tmp_path):
        doc = tmp_path / "DEPLOY.md"
        doc.write_text("only TONY-X001 is documented here\n")
        missing = check_rule_docs(doc)
        assert sorted(f.rule_id for f in missing) == sorted(
            r for r in ALL_RULES if r != "TONY-X001"
        )


class TestSingleModuleEntry:
    def test_preflight_source_entry(self):
        source = (FIX / "x001_bad.py").read_text()
        findings = lint_dispatch_source(source, filename="submitted.py")
        assert rules(findings) == [RULE_JIT_IN_LOOP]
        assert all(f.file == "submitted.py" for f in findings)

    def test_unparseable_source_is_script_lints_problem(self):
        assert lint_dispatch_source("def broken(:\n") == []


class TestCliDedup:
    def test_dispatch_flag_does_not_double_report(self, capsys):
        # Preflight lints each script's dispatch discipline AND
        # --dispatch sweeps the same path: the merged report must carry
        # each finding once.
        from tony_tpu.client.cli import lint as cli_lint

        rc = cli_lint(["--dispatch", str(FIX / "x004_bad.py")])
        out = capsys.readouterr().out
        assert rc == 1
        assert out.count("TONY-X004") == 1
        assert "1 error(s)" in out


class TestRepoIsClean:
    def test_zero_unwaived_findings_in_tree(self):
        roots = [REPO / "tony_tpu", REPO / "examples", REPO / "tools",
                 REPO / "bench.py"]
        findings = check_dispatch(roots, docs=REPO / "docs" / "DEPLOY.md")
        assert findings == [], "\n".join(f.render() for f in findings)
