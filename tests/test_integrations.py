"""Workflow integration + version stamping — the analogue of
``TestTensorFlowJob`` (tony-azkaban/src/test) and the VersionInfo seam."""

import json
import sys
from pathlib import Path

import pytest

from tony_tpu.conf import keys
from tony_tpu.conf.configuration import TonyConfiguration
from tony_tpu.integrations import props_to_argv, submit_from_props
from tony_tpu.version import collect_version_info, inject_version_info

FIXTURES = Path(__file__).resolve().parent / "fixtures"


class TestPropsMapping:
    def test_direct_args_and_worker_env(self, tmp_path):
        argv = props_to_argv(
            {
                "executes": "train.py",
                "src_dir": "src",
                "task_params": "--epochs 3",
                "worker_env.FOO": "1",
                "worker_env.BAR": "x y",
            },
            job_id="job1",
            working_dir=tmp_path,
        )
        assert argv[:2] == ["--executes=train.py", "--src_dir=src"]
        assert "--shell_env=BAR=x y" in argv
        assert "--shell_env=FOO=1" in argv

    def test_option_like_task_params_survive_argparse(self, tmp_path):
        """task_params='--fast' must parse (the --name=value form; bare
        ['--task_params', '--fast'] would SystemExit in argparse)."""
        from tony_tpu.client.client import build_arg_parser

        argv = props_to_argv(
            {"executes": "t.py", "task_params": "--fast"},
            job_id="j", working_dir=tmp_path,
        )
        args, rest = build_arg_parser().parse_known_args(argv)
        assert args.task_params == "--fast" and rest == []

    def test_tony_props_become_conf_file(self, tmp_path):
        argv = props_to_argv(
            {
                "executes": "t.py",
                "tony.worker.instances": "3",
                "tony.application.framework": "pytorch",
            },
            job_id="jobX",
            working_dir=tmp_path,
        )
        conf_arg = next(a for a in argv if a.startswith("--conf_file="))
        conf_file = Path(conf_arg.split("=", 1)[1])
        assert conf_file.parent.name == "_tony-conf-jobX"
        body = json.loads(conf_file.read_text())
        assert body["tony.worker.instances"] == "3"
        assert body["tony.application.framework"] == "pytorch"

    def test_unknown_submitter_rejected(self, tmp_path):
        with pytest.raises(ValueError, match="unknown submitter"):
            submit_from_props({}, "j", submitter="bogus",
                              working_dir=tmp_path)

    def test_round_trip_local_submission(self, tmp_path):
        """The done-criterion from VERDICT r1 item 10: a props dict maps to
        a successful local submission end-to-end."""
        rc = submit_from_props(
            {
                "executes": str(FIXTURES / "check_env.py"),
                "python_binary_path": sys.executable,
                "worker_env.USER_SHELL_VAR": "propagated",
                "tony.worker.instances": "1",
                "tony.ps.instances": "0",
                "tony.am.stop-grace": "0",
            },
            job_id="wf1",
            submitter="local",
            working_dir=tmp_path,
        )
        assert rc == 0


class TestVersionInfo:
    def test_collect_in_git_checkout(self):
        info = collect_version_info()
        assert len(info["revision"]) == 40  # this repo IS a git checkout
        assert info["branch"] and info["user"]
        assert info["version"] == "0.1.0"

    def test_injected_into_conf_and_frozen(self, tmp_path):
        conf = TonyConfiguration()
        inject_version_info(conf)
        assert len(conf.get_str(keys.K_VERSION_INFO_REVISION)) == 40
        # rides the frozen conf (what executors + history see)
        final = tmp_path / "tony-final.json"
        conf.write_final(final)
        frozen = json.loads(final.read_text())
        assert frozen[keys.K_VERSION_INFO_REVISION] == conf.get_str(
            keys.K_VERSION_INFO_REVISION
        )

    def test_client_stamps_on_init(self, tmp_path):
        from tony_tpu.client.client import TonyClient

        client = TonyClient().init(["--executes", "x.py"])
        assert len(client.conf.get_str(keys.K_VERSION_INFO_REVISION)) == 40
