"""Fleet observability rollup tests: the multi-resolution TSDB
(downsampling, retention, WAL/chunk persistence), snapshot/histogram
merging, the scraping collector's fold rules (restart-safe counter
deltas, gauge family folds, bucket-aligned histogram merge, staleness
eviction), SLO burn-rate evaluation with edge-triggered ``slo_burn``
events, journal size/age compaction bounds, history events truncation,
the TONY-M003 cardinality lint, and the multi-job mini-cluster e2e:
two tenants, one scheduler, one ``GET /metrics/fleet`` scrape."""

import json
import sys
import time
import urllib.request
from pathlib import Path

import pytest

from tony_tpu.conf import keys
from tony_tpu.history.reader import events_truncation
from tony_tpu.history.writer import truncate_events
from tony_tpu.observability import metrics as obs_metrics
from tony_tpu.observability.events import EventLog
from tony_tpu.observability.rollup import (
    FleetRollup,
    ROLLUP_EVICTIONS_COUNTER,
    ROLLUP_MERGE_CONFLICTS_COUNTER,
    ROLLUP_SCRAPE_FAILURES_COUNTER,
    SloObjective,
    Target,
    default_objectives,
)
from tony_tpu.observability.tsdb import TimeSeriesStore
from tony_tpu.scheduler.journal import SchedulerJournal

FIXTURES = Path(__file__).resolve().parent / "fixtures"

# A fixed epoch, aligned to the 600 s bucket width so single-minute
# batches land in one downsample bucket deterministically.
BASE_MS = 1_700_000_400_000


def _hist(count, total, buckets, maximum=None):
    snap = {"count": count, "sum": total, "buckets": buckets}
    if maximum is not None:
        snap["max"] = maximum
    return snap


# ---------------------------------------------------------------------------
# metrics.py merge primitives
# ---------------------------------------------------------------------------
class TestMergePrimitives:
    def test_merge_histograms_adds_aligned_parts(self):
        a = _hist(2, 30.0, [[10.0, 1], [100.0, 2]], maximum=25.0)
        b = _hist(3, 120.0, [[10.0, 0], [100.0, 3]], maximum=90.0)
        merged = obs_metrics.merge_histograms([a, b])
        assert merged["count"] == 5
        assert merged["sum"] == pytest.approx(150.0)
        assert merged["buckets"] == [[10.0, 1], [100.0, 5]]
        assert merged["max"] == 90.0
        # quantiles stay answerable on the merged snapshot
        q = obs_metrics.histogram_quantile(merged, 0.95)
        assert q is not None and 10.0 <= q <= 100.0

    def test_merge_histograms_rejects_mismatched_bounds(self):
        a = _hist(1, 5.0, [[10.0, 1], [100.0, 1]])
        b = _hist(1, 5.0, [[20.0, 1], [100.0, 1]])
        with pytest.raises(ValueError, match="mismatched histogram"):
            obs_metrics.merge_histograms([a, b])

    def test_merge_snapshots_counters_and_gauge_aggs(self):
        s1 = {"counters": {"x_total": 3}, "gauges": {"loss": 1.0},
              "histograms": {}}
        s2 = {"counters": {"x_total": 4}, "gauges": {"loss": 3.0},
              "histograms": {}}
        merged = obs_metrics.merge_snapshots([s1, s2], gauge_agg="avg")
        assert merged["counters"]["x_total"] == 7
        assert merged["gauges"]["loss"] == pytest.approx(2.0)
        assert obs_metrics.merge_snapshots(
            [s1, s2], gauge_agg="max"
        )["gauges"]["loss"] == 3.0


# ---------------------------------------------------------------------------
# tsdb.py
# ---------------------------------------------------------------------------
class TestTimeSeriesStore:
    def test_record_query_and_downsample(self):
        ts = TimeSeriesStore(None)
        # six points inside one minute
        for i, v in enumerate((1.0, 2.0, 3.0, 4.0, 5.0, 6.0)):
            ts.record_many(BASE_MS + i * 10_000, {"s": v})
        rows = ts.query("s", since_ms=BASE_MS - 1,
                        until_ms=BASE_MS + 60_000, step_s=60, agg="avg")
        assert len(rows) == 1
        assert rows[0][1] == pytest.approx(3.5)
        for agg, want in (("sum", 21.0), ("min", 1.0), ("max", 6.0),
                          ("last", 6.0), ("count", 6.0)):
            assert ts.query(
                "s", since_ms=BASE_MS - 1, until_ms=BASE_MS + 60_000,
                step_s=60, agg=agg,
            )[0][1] == pytest.approx(want)

    def test_unknown_agg_raises(self):
        with pytest.raises(ValueError, match="unknown agg"):
            TimeSeriesStore(None).query("s", agg="p95")

    def test_raw_retention_trims_but_buckets_survive(self):
        ts = TimeSeriesStore(None, retention_raw_s=120)
        for i in range(60):  # 10 minutes of 10 s points
            ts.record_many(BASE_MS + i * 10_000, {"s": float(i)})
        stats = ts.stats()
        # raw horizon is 2 minutes => at most ~13 raw points retained
        assert stats["raw_points"] <= 13
        # but the 1m buckets still cover the full 10 minutes
        rows = ts.query("s", since_ms=BASE_MS,
                        until_ms=BASE_MS + 600_000, step_s=60, agg="avg")
        assert len(rows) >= 9

    def test_resolution_pick_coarsens_past_raw_horizon(self):
        ts = TimeSeriesStore(None, retention_raw_s=60,
                             retention_1m_s=3600)
        assert ts._pick_resolution(BASE_MS, 60) == 0  # no data: age 0
        ts.record_many(BASE_MS + 7_200_000, {"s": 1.0})
        latest = BASE_MS + 7_200_000
        # inside the raw horizon: finest wins
        assert ts._pick_resolution(latest - 30_000, 30) == 0
        # past raw but inside the 1m horizon
        assert ts._pick_resolution(latest - 600_000, 60) == 60
        # a since 2 h back outlives both finer horizons
        assert ts._pick_resolution(BASE_MS, 600) == 600

    def test_persistence_checkpoint_and_wal_replay(self, tmp_path):
        d = tmp_path / "tsdb"
        ts = TimeSeriesStore(d)
        ts.record_many(BASE_MS, {"a": 1.0, "b": 2.0})
        ts.checkpoint()
        ts.record_many(BASE_MS + 60_000, {"a": 3.0})  # WAL only
        # a torn tail line must not poison the load
        with open(d / "tsdb-wal.jsonl", "a") as f:
            f.write('{"ts_ms": 999, "val')

        ts2 = TimeSeriesStore(d)
        assert ts2.names() == ["a", "b"]
        rows = ts2.query("a", since_ms=BASE_MS - 1,
                         until_ms=BASE_MS + 120_000, step_s=60, agg="last")
        assert [v for _, v in rows] == [1.0, 3.0]

    def test_wal_lines_before_watermark_not_doubled(self, tmp_path):
        d = tmp_path / "tsdb"
        ts = TimeSeriesStore(d)
        ts.record_many(BASE_MS, {"a": 1.0})
        ts.checkpoint()
        # simulate a crash between append and truncate: re-append the
        # already-folded line; the watermark must skip it on load
        with open(d / "tsdb-wal.jsonl", "a") as f:
            f.write(json.dumps({"ts_ms": BASE_MS, "values": {"a": 1.0}})
                    + "\n")
        ts2 = TimeSeriesStore(d)
        rows = ts2.query("a", since_ms=BASE_MS - 1, until_ms=BASE_MS + 1,
                         step_s=60, agg="count")
        assert rows[0][1] == 1.0

    def test_avg_over_window(self):
        ts = TimeSeriesStore(None)
        for i in range(10):
            ts.record_many(BASE_MS + i * 15_000, {"s": 0.5})
        assert ts.avg_over("s", 300,
                           until_ms=BASE_MS + 150_000) == pytest.approx(0.5)
        assert ts.avg_over("missing", 300, until_ms=BASE_MS) is None

    def test_non_finite_and_non_numeric_dropped(self):
        ts = TimeSeriesStore(None)
        n = ts.record_many(BASE_MS, {"ok": 1.0, "nan": float("nan"),
                                     "bad": "x"})
        assert n == 1 and ts.names() == ["ok"]


# ---------------------------------------------------------------------------
# the collector (fake fetch_json: no HTTP, no scheduler)
# ---------------------------------------------------------------------------
def _job_doc(steps=5.0, goodput=0.4, hb=3, ttft_hist=None):
    doc = {
        "coordinator": {
            "counters": {"train_steps_total": steps},
            "gauges": {"tony_goodput_ratio": goodput},
            "histograms": (
                {"tony_serving_ttft_ms": ttft_hist} if ttft_hist else {}
            ),
        },
        "heartbeats": {"worker:0": hb},
        "heartbeat_age_s": {"worker:0": 0.5},
        "tasks": {},
    }
    return doc


def _rollup(targets, docs, failing=(), **kw):
    """A FleetRollup whose discovery and scraping are injected: ``docs``
    maps target key -> /api/metrics document, ``failing`` keys raise."""
    def fetch(url, timeout_s):
        for t in targets():
            if url == f"http://{t.addr}/api/metrics":
                if t.key in failing:
                    raise OSError("connection refused")
                return docs[t.key]
        raise OSError("unknown target")

    kw.setdefault("tsdb", TimeSeriesStore(None))
    r = FleetRollup(None, fetch_json=fetch, **kw)
    r.discover_targets = lambda: targets()
    return r


class TestFleetRollupFold:
    def test_scope_fold_counters_gauges_tenants(self):
        t1 = Target("j1", "job", "h:1", tenant="alice")
        t2 = Target("j2", "job", "h:2", tenant="bob")
        sched = Target("scheduler", "scheduler", "h:9")
        docs = {
            "j1": _job_doc(steps=5.0, goodput=0.4),
            "j2": _job_doc(steps=7.0, goodput=0.8),
            "scheduler": {"counters": {"tony_sched_submits_total": 2.0},
                          "gauges": {}, "histograms": {}},
        }
        r = _rollup(lambda: [t1, t2, sched], docs)
        r.tick(now_ms=BASE_MS)
        snap = r.fleet_snapshot()
        c, g = snap["counters"], snap["gauges"]
        assert c['train_steps_total{scope="fleet"}'] == 12.0
        assert c['train_steps_total{scope="cluster"}'] == 12.0
        assert c['train_steps_total{scope="tenant",tenant="alice"}'] == 5.0
        assert c['train_steps_total{scope="tenant",tenant="bob"}'] == 7.0
        # the scheduler's own counters roll up to cluster scope only
        assert c['tony_sched_submits_total{scope="cluster"}'] == 2.0
        assert 'tony_sched_submits_total{scope="fleet"}' not in c
        # _ratio family folds by average at fleet scope
        assert g['tony_goodput_ratio{scope="fleet"}'] == pytest.approx(0.6)
        assert g['tony_goodput_ratio{scope="tenant",tenant="alice"}'] \
            == pytest.approx(0.4)
        # heartbeat part synthesized: counter sum + worst age
        assert c['tony_task_heartbeats_total{scope="fleet"}'] == 6.0
        assert g['tony_task_heartbeat_age_seconds{scope="fleet"}'] == 0.5

    def test_counter_deltas_are_restart_safe(self):
        docs = {"j1": _job_doc(steps=100.0)}
        t = Target("j1", "job", "h:1", tenant="alice")
        r = _rollup(lambda: [t], docs)
        r.tick(now_ms=BASE_MS)
        fleet = 'train_steps_total{scope="fleet"}'
        assert r.fleet_snapshot()["counters"][fleet] == 100.0
        # the job restarts: its counter resets to 10 — the fleet total
        # must not move backwards (delta clamped at zero)
        docs["j1"] = _job_doc(steps=10.0)
        r.tick(now_ms=BASE_MS + 15_000)
        assert r.fleet_snapshot()["counters"][fleet] == 100.0
        # and new progress folds in as a delta from the restart point
        docs["j1"] = _job_doc(steps=15.0)
        r.tick(now_ms=BASE_MS + 30_000)
        assert r.fleet_snapshot()["counters"][fleet] == 105.0

    def test_histogram_merge_and_quantile_series(self):
        h1 = _hist(8, 80.0, [[10.0, 4], [100.0, 8]])
        h2 = _hist(2, 150.0, [[10.0, 0], [100.0, 2]])
        docs = {"j1": _job_doc(ttft_hist=h1), "j2": _job_doc(ttft_hist=h2)}
        r = _rollup(lambda: [Target("j1", "job", "h:1", tenant="a"),
                             Target("j2", "job", "h:2", tenant="b")], docs)
        r.tick(now_ms=BASE_MS)
        merged = r.fleet_snapshot()["histograms"][
            'tony_serving_ttft_ms{scope="fleet"}'
        ]
        assert merged["count"] == 10 and merged["buckets"][1] == [100.0, 10]
        # quantile series recorded for the range API
        assert "tony_serving_ttft_ms:p95|fleet" in r.tsdb.names()
        points = r.tsdb.query("tony_serving_ttft_ms:p95|fleet",
                              since_ms=BASE_MS - 1, until_ms=BASE_MS + 1,
                              step_s=60, agg="last")
        assert points and 10.0 <= points[0][1] <= 100.0

    def test_mismatched_buckets_drop_series_loudly(self):
        h1 = _hist(1, 5.0, [[10.0, 1], [100.0, 1]])
        h2 = _hist(1, 5.0, [[25.0, 1], [100.0, 1]])
        docs = {"j1": _job_doc(ttft_hist=h1), "j2": _job_doc(ttft_hist=h2)}
        r = _rollup(lambda: [Target("j1", "job", "h:1"),
                             Target("j2", "job", "h:2")], docs)
        r.tick(now_ms=BASE_MS)
        snap = r.fleet_snapshot()
        assert 'tony_serving_ttft_ms{scope="fleet"}' \
            not in snap["histograms"]
        conflicts = r.registry.snapshot()["counters"][
            ROLLUP_MERGE_CONFLICTS_COUNTER
        ]
        assert conflicts >= 1

    def test_gone_target_evicts_gauges_but_keeps_counters(self):
        docs = {"j1": _job_doc(steps=5.0), "j2": _job_doc(steps=7.0)}
        live = [Target("j1", "job", "h:1", tenant="a"),
                Target("j2", "job", "h:2", tenant="b")]
        r = _rollup(lambda: list(live), docs)
        r.tick(now_ms=BASE_MS)
        assert len(r.summary()["targets"]) == 2
        del live[1]  # the scheduler stops listing j2
        r.tick(now_ms=BASE_MS + 15_000)
        snap = r.fleet_snapshot()
        # j2's gauges are gone from every scope...
        assert 'tony_goodput_ratio{scope="tenant",tenant="b"}' \
            not in snap["gauges"]
        # ...but its folded counter contribution survives
        assert snap["counters"]['train_steps_total{scope="fleet"}'] == 12.0
        assert snap["counters"][
            'train_steps_total{scope="tenant",tenant="b"}'
        ] == 7.0
        evictions = r.registry.snapshot()["counters"][
            ROLLUP_EVICTIONS_COUNTER
        ]
        assert evictions == 1

    def test_unreachable_target_ages_out_at_stale_after(self):
        docs = {"j1": _job_doc(goodput=0.4)}
        t = Target("j1", "job", "h:1", tenant="a")
        failing = set()
        r = _rollup(lambda: [t], docs, failing=failing,
                    stale_after_ms=30_000)
        r.tick(now_ms=BASE_MS)
        assert 'tony_goodput_ratio{scope="fleet"}' \
            in r.fleet_snapshot()["gauges"]
        failing.add("j1")  # still discovered, stops answering
        r.tick(now_ms=BASE_MS + 10_000)
        # within stale_after: last-good snapshot still serves
        assert 'tony_goodput_ratio{scope="fleet"}' \
            in r.fleet_snapshot()["gauges"]
        assert r.summary()["target_failures"]["j1"] == 1
        fails = r.registry.snapshot()["counters"][
            ROLLUP_SCRAPE_FAILURES_COUNTER + '{kind="job"}'
        ]
        assert fails == 1
        r.tick(now_ms=BASE_MS + 50_000)  # past stale_after_ms
        assert 'tony_goodput_ratio{scope="fleet"}' \
            not in r.fleet_snapshot()["gauges"]

    def test_prometheus_text_one_scrape(self):
        h = _hist(2, 30.0, [[10.0, 1], [100.0, 2]])
        docs = {"j1": _job_doc(goodput=0.4, ttft_hist=h)}
        r = _rollup(lambda: [Target("j1", "job", "h:1", tenant="a")], docs)
        r.tick(now_ms=BASE_MS)
        text = r.prometheus_text()
        assert 'tony_goodput_ratio{scope="fleet"}' in text
        assert 'tony_goodput_ratio{scope="tenant",tenant="a"}' in text
        assert 'tony_serving_ttft_ms_bucket{le="10"' in text
        assert "tony_rollup_targets 1" in text
        assert "# TYPE tony_task_heartbeats_total counter" in text

    def test_query_series_scopes(self):
        docs = {"j1": _job_doc(goodput=0.4)}
        r = _rollup(lambda: [Target("j1", "job", "h:1", tenant="a")], docs)
        for i in range(4):
            r.tick(now_ms=BASE_MS + i * 15_000)
        doc = r.query_series("tony_goodput_ratio", agg="avg", tenant="a",
                             since_s=600, step_s=60)
        assert doc["scope"] == "tenant:a"
        assert doc["points"] and doc["points"][0][1] == pytest.approx(0.4)
        assert r.query_series("tony_goodput_ratio")["scope"] == "fleet"


# ---------------------------------------------------------------------------
# SLO burn rates
# ---------------------------------------------------------------------------
class TestSloBurn:
    def _rollup_with_objective(self, kind="min", target=0.9):
        events = EventLog()
        r = _rollup(
            lambda: [], {}, events=events,
            objectives=[SloObjective("obj", "s|fleet", kind, target)],
            fast_window_s=60, slow_window_s=120, burn_threshold=1.0,
        )
        return r, events

    def test_breach_emits_edge_triggered_event(self):
        r, events = self._rollup_with_objective()
        # seed a breaching series: goodput 0.45 against a 0.9 floor
        for i in range(10):
            r.tsdb.record_many(BASE_MS + i * 15_000, {"s|fleet": 0.45})
        r.tick(now_ms=BASE_MS + 150_000)
        state = r.summary()["slo"]["obj"]
        assert state["breached"] is True
        assert state["burn_fast"] == pytest.approx(2.0)
        burns = [e for e in events.to_dicts() if e["kind"] == "slo_burn"]
        assert len(burns) == 1
        assert burns[0]["objective"] == "obj"
        assert burns[0]["burn_slow"] > 1.0
        # still breaching on the next tick: latched, no second event
        r.tsdb.record_many(BASE_MS + 165_000, {"s|fleet": 0.45})
        r.tick(now_ms=BASE_MS + 165_000)
        assert len([e for e in events.to_dicts()
                    if e["kind"] == "slo_burn"]) == 1
        # the burn gauge is live on the one-scrape page
        text = r.prometheus_text()
        assert _sample(
            text, 'tony_slo_burn_rate{objective="obj"}'
        ) == pytest.approx(2.0)

    def test_recovery_unlatches_and_rebreach_reemits(self):
        r, events = self._rollup_with_objective()
        now = BASE_MS
        for i in range(10):
            r.tsdb.record_many(now + i * 15_000, {"s|fleet": 0.45})
        now += 150_000
        r.tick(now_ms=now)
        # recover: both windows must clear before the latch resets
        for i in range(20):
            r.tsdb.record_many(now + (i + 1) * 15_000, {"s|fleet": 1.0})
        now += 20 * 15_000
        r.tick(now_ms=now)
        assert r.summary()["breached"] == []
        for i in range(20):
            r.tsdb.record_many(now + (i + 1) * 15_000, {"s|fleet": 0.3})
        now += 20 * 15_000
        r.tick(now_ms=now)
        assert len([e for e in events.to_dicts()
                    if e["kind"] == "slo_burn"]) == 2

    def test_fast_breach_alone_does_not_alert(self):
        r, events = self._rollup_with_objective()
        # a long healthy history, then one bad fast window: the slow
        # window holds the alert back (no flapping on blips)
        for i in range(8):
            r.tsdb.record_many(BASE_MS + i * 15_000, {"s|fleet": 1.0})
        r.tsdb.record_many(BASE_MS + 8 * 15_000, {"s|fleet": 0.2})
        r.tick(now_ms=BASE_MS + 8 * 15_000)
        state = r.summary()["slo"]["obj"]
        assert state["burn_fast"] > 1.0
        assert state["breached"] is False
        assert events.to_dicts() == []

    def test_max_kind_objective(self):
        events = EventLog()
        r = _rollup(
            lambda: [], {}, events=events,
            objectives=[SloObjective("ttft", "t:p95|fleet", "max", 100.0)],
            fast_window_s=60, slow_window_s=120,
        )
        for i in range(10):
            r.tsdb.record_many(BASE_MS + i * 15_000, {"t:p95|fleet": 250.0})
        r.tick(now_ms=BASE_MS + 150_000)
        state = r.summary()["slo"]["ttft"]
        assert state["burn_fast"] == pytest.approx(2.5)
        assert state["breached"] is True

    def test_empty_window_holds_gauges_and_latch(self):
        r, events = self._rollup_with_objective()
        r.tick(now_ms=BASE_MS)  # nothing recorded yet
        state = r.summary()["slo"]["obj"]
        assert state["fast"] is None and "breached" not in state
        assert events.to_dicts() == []

    def test_default_objectives_from_conf(self):
        from tony_tpu.conf.configuration import TonyConfiguration

        conf = TonyConfiguration()
        objs = {o.name: o for o in default_objectives(conf)}
        assert set(objs) == {"fleet_goodput_ratio", "serving_ttft_p95"}
        assert objs["fleet_goodput_ratio"].kind == "min"
        assert objs["serving_ttft_p95"].series \
            == "tony_serving_ttft_ms:p95|fleet"
        conf.set(keys.K_SLO_MFU_FLOOR, 0.3)
        assert "fleet_mfu_floor" in {
            o.name for o in default_objectives(conf)
        }
        conf.set(keys.K_SLO_ENABLED, False)
        assert default_objectives(conf) == []


# ---------------------------------------------------------------------------
# journal size/age compaction bounds (tony.scheduler.journal-max-*)
# ---------------------------------------------------------------------------
class TestJournalRetentionBounds:
    def test_needs_rotation_by_bytes(self, tmp_path):
        j = SchedulerJournal(tmp_path / "j.jsonl")
        j.append("job_queued", BASE_MS, job_id="a", blob="x" * 200)
        assert not j.needs_rotation(BASE_MS, max_bytes=10_000)
        assert j.needs_rotation(BASE_MS, max_bytes=64)
        assert not j.needs_rotation(BASE_MS)  # all bounds disabled

    def test_needs_rotation_by_age_and_reset_on_rotate(self, tmp_path):
        j = SchedulerJournal(tmp_path / "j.jsonl")
        s1 = j.append("job_queued", BASE_MS, job_id="a")
        now = BASE_MS + 3_600_000
        s2 = j.append("job_launched", now, job_id="a")
        assert j.oldest_age_ms(now) == 3_600_000
        assert j.needs_rotation(now, max_age_ms=1_800_000)
        # rotating away the old prefix clears the age trigger
        j.rotate(s1)
        assert j.oldest_age_ms(now) == 0
        assert not j.needs_rotation(now, max_age_ms=1_800_000)
        assert j.size_bytes() > 0 and s2 > s1

    def test_age_survives_reload(self, tmp_path):
        path = tmp_path / "j.jsonl"
        SchedulerJournal(path).append("job_queued", BASE_MS, job_id="a")
        j2 = SchedulerJournal(path)  # re-scan on boot
        assert j2.needs_rotation(BASE_MS + 100, max_age_ms=50)

    def test_record_count_bound_unchanged(self, tmp_path):
        j = SchedulerJournal(tmp_path / "j.jsonl")
        for i in range(5):
            j.append("job_queued", BASE_MS + i, job_id=f"j{i}")
        assert not j.needs_rotation(BASE_MS, max_records=5)
        assert j.needs_rotation(BASE_MS, max_records=4)


# ---------------------------------------------------------------------------
# history events truncation (tony.history.max-events)
# ---------------------------------------------------------------------------
class TestEventsTruncation:
    def _events(self, n):
        return [{"ts_ms": BASE_MS + i, "kind": f"k{i}"} for i in range(n)]

    def test_noop_at_or_under_cap(self):
        events = self._events(10)
        assert truncate_events(events, 10) is events
        assert truncate_events(events, 0) is events

    def test_drops_middle_keeps_edges_and_marks(self):
        events = self._events(100)
        out = truncate_events(events, 11)
        assert len(out) == 11
        assert out[0] == events[0]          # the submission edge
        assert out[-1] == events[-1]        # the death edge
        marker = events_truncation(out)
        assert marker == {"dropped": 90, "ts_ms": out[4]["ts_ms"]}

    def test_reader_returns_none_when_complete(self):
        assert events_truncation(self._events(5)) is None
        assert events_truncation(None) is None


# ---------------------------------------------------------------------------
# TONY-M003 cardinality lint
# ---------------------------------------------------------------------------
class TestCardinalityLint:
    def _findings(self, tmp_path, source):
        from tony_tpu.analysis.metrics_lint import check_label_cardinality

        p = tmp_path / "mod.py"
        p.write_text(source)
        return check_label_cardinality([p])

    def test_flags_per_occurrence_id_label(self, tmp_path):
        found = self._findings(tmp_path, (
            "def f(reg, request_id):\n"
            "    reg.counter('rpc_calls_total',"
            " labels={'request': request_id}).inc()\n"
        ))
        assert len(found) == 1
        assert found[0].rule_id == "TONY-M003"
        assert "request_id" in found[0].message

    def test_attribute_ids_flagged_too(self, tmp_path):
        found = self._findings(tmp_path, (
            "def f(reg, task):\n"
            "    reg.gauge('queue_depth',"
            " labels={'seq': task.seq_no}).set(1)\n"
        ))
        assert len(found) == 1

    def test_closed_set_labels_pass(self, tmp_path):
        found = self._findings(tmp_path, (
            "def f(reg, task_id, state):\n"
            "    reg.counter('x_total', labels={'state': 'RUNNING'}).inc()\n"
            "    reg.counter('x_total', labels={'task': task_id}).inc()\n"
            "    reg.gauge('depth', labels={'state': state}).set(1)\n"
        ))
        assert found == []

    def test_noqa_waives(self, tmp_path):
        found = self._findings(tmp_path, (
            "def f(reg, trace_id):\n"
            "    reg.counter('x_total',"
            " labels={'trace': trace_id}).inc()"
            "  # tony: noqa[TONY-M003]\n"
        ))
        assert found == []


# ---------------------------------------------------------------------------
# bench_rollup: the workload runs and its gates point the right way
# ---------------------------------------------------------------------------
def test_bench_rollup_smoke_and_gate_directions():
    import bench

    out = bench.bench_rollup(targets=4, tasks_per_target=2, ticks=3,
                             queries=10)
    for gated in ("scrape_fan_in_ms", "rollup_tick_ms", "query_p95_ms"):
        assert out[gated] >= 0
        assert bench.metric_direction(f"rollup.{gated}") == "lower"
    # shape numbers stay ungated: a store that grows is not a regression
    assert bench.metric_direction("rollup.series_bytes_on_disk") is None
    assert bench.metric_direction("rollup.series") is None
    assert out["series"] > 0


# ---------------------------------------------------------------------------
# mini-cluster e2e: two tenants, one scheduler, one scrape
# ---------------------------------------------------------------------------
def _poll(deadline_s, fn, what):
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        result = fn()
        if result:
            return result
        time.sleep(0.1)
    raise AssertionError(f"timed out waiting for {what}")


def _get(url):
    with urllib.request.urlopen(url, timeout=5) as resp:
        return resp.read().decode()


def _sample(text, needle):
    """The float value of the first exposition line starting needle."""
    for line in text.splitlines():
        if line.startswith(needle):
            return float(line.rsplit(" ", 1)[1])
    return None


def test_multi_job_rollup_e2e(tmp_path):
    """The acceptance scenario: two jax-free jobs under different
    tenants on one scheduler; a history server's rollup discovers both
    through scheduler state, and ONE ``GET /metrics/fleet`` shows the
    summed fleet counters plus per-tenant goodput. Killing a job evicts
    its gauges without perturbing the fleet counters, and a restarted
    TimeSeriesStore replays the persisted series."""
    from tony_tpu.history.server import HistoryServer
    from tony_tpu.mini import MiniTonyCluster

    with MiniTonyCluster(tmp_path) as cluster:
        sconf = cluster.base_conf()
        sconf.set(keys.K_SCHED_TICK_MS, 50)
        daemon = cluster.start_scheduler(sconf, serve_http=True)

        def job_conf():
            conf = cluster.base_conf()
            conf.set(keys.K_EXECUTES, str(FIXTURES / "report_metrics.py"))
            conf.set(keys.K_PYTHON_BINARY, sys.executable)
            conf.set(keys.instances_key("worker"), 1)
            conf.set(keys.instances_key("ps"), 0)
            conf.set(keys.K_TASK_HEARTBEAT_INTERVAL_MS, 150)
            conf.set(keys.K_SHELL_ENV, "LINGER_S=45.0")
            return conf

        j1 = daemon.submit(job_conf(), tenant="alice")
        j2 = daemon.submit(job_conf(), tenant="bob")

        tsdb_dir = tmp_path / "fleet-tsdb"
        rollup = FleetRollup(
            cluster.base_dir / "scheduler",
            tsdb=TimeSeriesStore(tsdb_dir),
            events=EventLog(),
            interval_ms=200,
            stale_after_ms=3_000,
        )
        server = HistoryServer(str(cluster.history_dir), port=0,
                               rollup=rollup)
        port = server.serve_background()
        base = f"http://127.0.0.1:{port}"
        try:
            # -- one scrape shows the whole fleet -------------------------
            def both_tenants_up():
                text = _get(f"{base}/metrics/fleet")
                ok = ('tony_goodput_ratio{scope="tenant",tenant="alice"}'
                      in text
                      and 'tony_goodput_ratio{scope="tenant",tenant="bob"}'
                      in text
                      and (_sample(
                          text, 'train_steps_total{scope="fleet"}'
                      ) or 0) >= 2)
                return text if ok else None

            text = _poll(90, both_tenants_up, "both tenants on one scrape")
            fleet_steps = _sample(text, 'train_steps_total{scope="fleet"}')
            alice = _sample(
                text, 'train_steps_total{scope="tenant",tenant="alice"}'
            )
            bob = _sample(
                text, 'train_steps_total{scope="tenant",tenant="bob"}'
            )
            assert fleet_steps == pytest.approx(alice + bob)
            assert 'tony_task_heartbeats_total{scope="fleet"}' in text
            assert "tony_rollup_targets" in text

            # -- the range API answers over HTTP --------------------------
            doc = json.loads(_get(
                f"{base}/api/query?name=train_steps_total&agg=last"
                f"&scope=fleet&since=600&step=60"
            ))
            assert doc["points"], doc
            summary = json.loads(_get(f"{base}/api/fleet/summary"))
            assert {t["key"] for t in summary["targets"]} >= {j1, j2}
            assert "SLO" in _get(f"{base}/fleet")

            # -- kill one job: gauges evict, counters survive -------------
            assert daemon.kill(j2)
            daemon.wait_job(j2, 60)

            def bob_evicted():
                t = _get(f"{base}/metrics/fleet")
                return t if ('tony_goodput_ratio{scope="tenant",'
                             'tenant="bob"}') not in t else None

            text = _poll(30, bob_evicted, "killed job's gauges to evict")
            after = _sample(text, 'train_steps_total{scope="fleet"}')
            assert after is not None and after >= fleet_steps
            assert _sample(
                text, 'train_steps_total{scope="tenant",tenant="bob"}'
            ) == bob
        finally:
            server.stop()
            daemon.kill(j1)
            daemon.wait_job(j1, 60)

    # -- the store survives the process -----------------------------------
    replayed = TimeSeriesStore(tsdb_dir)
    assert "train_steps_total|fleet" in replayed.names()
    until = replayed.latest_ms()
    rows = replayed.query("train_steps_total|fleet",
                          since_ms=until - 600_000, until_ms=until,
                          step_s=60, agg="last")
    assert rows and rows[-1][1] >= 2
