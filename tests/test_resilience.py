"""Unit tests for the failure-aware retry subsystem (resilience/): the
classifier's category table, backoff-schedule determinism, the
progress-aware retry budget, fault-plan parse/validation, the jax-free
checkpoint probe, liveness expiry + ping fencing, and the hardened
Heartbeater. All fast — the kill-and-resume chaos e2e lives in
tests/test_fault_injection.py behind the ``slow`` marker."""

import json
import time

import numpy as np
import pytest

from tony_tpu import constants
from tony_tpu.conf import keys
from tony_tpu.conf.configuration import TonyConfiguration
from tony_tpu.coordinator.liveness import LivenessMonitor
from tony_tpu.resilience import (
    FailureCategory,
    FailureEvent,
    FaultPlan,
    FaultPlanError,
    RetryPolicy,
    classify,
    latest_complete_step,
)
from tony_tpu.resilience import classifier as kinds
from tony_tpu.resilience.faults import (
    CheckpointFaults,
    FaultInjector,
    FaultSpec,
)


# ---------------------------------------------------------------------------
# Classifier
# ---------------------------------------------------------------------------
class TestClassifier:
    @pytest.mark.parametrize("event,expected", [
        # Substrate failures → INFRA.
        (FailureEvent(kinds.HEARTBEAT_EXPIRY, task_id="worker:1"),
         FailureCategory.INFRA),
        (FailureEvent(kinds.PREEMPTION, task_id="worker:0", exit_code=1),
         FailureCategory.INFRA),
        # Signal deaths → INFRA, both the subprocess (-9) and shell (137)
        # spellings, and even pre-rendezvous (a SIGKILL is external).
        (FailureEvent(kinds.TASK_EXIT, exit_code=-9),
         FailureCategory.INFRA),
        (FailureEvent(kinds.TASK_EXIT, exit_code=137),
         FailureCategory.INFRA),
        (FailureEvent(kinds.TASK_EXIT, exit_code=143, registered=False),
         FailureCategory.INFRA),
        (FailureEvent(kinds.TASK_EXIT,
                      exit_code=constants.EXIT_CODE_LOST_COORDINATOR),
         FailureCategory.INFRA),
        # Deterministic user errors → USER_PERMANENT.
        (FailureEvent(kinds.TASK_EXIT, exit_code=127),
         FailureCategory.USER_PERMANENT),
        (FailureEvent(kinds.TASK_EXIT, exit_code=126),
         FailureCategory.USER_PERMANENT),
        (FailureEvent(kinds.TASK_EXIT, exit_code=1, registered=False),
         FailureCategory.USER_PERMANENT),
        (FailureEvent(kinds.CONF_ERROR, detail="bad topology"),
         FailureCategory.USER_PERMANENT),
        # Could-work-on-rerun → TRANSIENT.
        (FailureEvent(kinds.TASK_EXIT, exit_code=1, registered=True),
         FailureCategory.TRANSIENT),
        (FailureEvent(kinds.TASK_EXIT, exit_code=124, registered=False),
         FailureCategory.TRANSIENT),  # timeout: ran, overran
        (FailureEvent(kinds.TASK_EXIT),  # unattributed default
         FailureCategory.TRANSIENT),
    ])
    def test_category_table(self, event, expected):
        assert classify(event) is expected

    def test_describe_mentions_the_facts(self):
        e = FailureEvent(kinds.TASK_EXIT, task_id="worker:1", exit_code=9,
                         registered=False)
        d = e.describe()
        assert "worker:1" in d and "exit=9" in d and "pre-rendezvous" in d


# ---------------------------------------------------------------------------
# Retry policy
# ---------------------------------------------------------------------------
class TestRetryPolicy:
    def test_backoff_deterministic_and_bounded(self):
        a = RetryPolicy(budget=5, backoff_base_ms=1000, seed=42)
        b = RetryPolicy(budget=5, backoff_base_ms=1000, seed=42)
        for attempt in (1, 2, 3, 4):
            x = a.backoff_ms_for(attempt, FailureCategory.TRANSIENT)
            assert x == b.backoff_ms_for(attempt, FailureCategory.TRANSIENT)
            base = 1000 * 2 ** (attempt - 1)
            assert base <= x < base * 1.5

    def test_backoff_grows_exponentially_and_caps(self):
        p = RetryPolicy(budget=9, backoff_base_ms=100,
                        backoff_max_ms=500, seed=1)
        # attempt 4 raw = 800 → capped at 500; jitter < 1.5 keeps it < 750
        assert p.backoff_ms_for(4, FailureCategory.TRANSIENT) < 750
        assert p.backoff_ms_for(4, FailureCategory.TRANSIENT) >= 500

    def test_different_seeds_decorrelate(self):
        vals = {
            RetryPolicy(budget=1, seed=s).backoff_ms_for(
                1, FailureCategory.TRANSIENT
            )
            for s in range(20)
        }
        assert len(vals) > 1  # retry storms must not stampede in lockstep

    def test_infra_backs_off_half_of_transient(self):
        p = RetryPolicy(budget=1, backoff_base_ms=1000, seed=7)
        t = p.backoff_ms_for(1, FailureCategory.TRANSIENT)
        i = p.backoff_ms_for(1, FailureCategory.INFRA)
        assert i == int(t * 0.5) or abs(i - t / 2) <= 1

    def test_user_permanent_never_retries(self):
        p = RetryPolicy(budget=100)
        d = p.decide(FailureCategory.USER_PERMANENT)
        assert not d.retry and p.remaining == 100

    def test_budget_consumed_and_exhausted(self):
        p = RetryPolicy(budget=2, backoff_base_ms=10)
        assert p.decide(FailureCategory.TRANSIENT).retry
        assert p.decide(FailureCategory.INFRA).retry
        d = p.decide(FailureCategory.TRANSIENT)
        assert not d.retry and "exhausted" in d.reason

    def test_progress_refreshes_budget(self):
        p = RetryPolicy(budget=1, backoff_base_ms=10)
        p.observe_progress(100)          # first observation: baseline
        assert p.decide(FailureCategory.INFRA).retry
        assert p.remaining == 0
        assert p.observe_progress(200)   # advanced → refresh
        assert p.remaining == 1
        assert p.decide(FailureCategory.INFRA).retry

    def test_no_progress_no_refresh(self):
        p = RetryPolicy(budget=1, backoff_base_ms=10)
        p.observe_progress(100)
        assert p.decide(FailureCategory.INFRA).retry
        assert not p.observe_progress(100)   # same step: no refresh
        assert not p.observe_progress(None)  # no checkpoint: no refresh
        assert not p.decide(FailureCategory.INFRA).retry


# ---------------------------------------------------------------------------
# Fault plan parse/validation
# ---------------------------------------------------------------------------
GOOD_PLAN = {
    "seed": 7,
    "faults": [
        {"action": "crash_coordinator", "phase": "schedule", "session": 1},
        {"action": "kill_task", "target": "worker:1", "at": "rendezvous"},
        {"action": "kill_task", "target": "any_non_chief",
         "at": "rendezvous"},
        {"action": "kill_task", "target": "worker:1", "after_heartbeats": 3},
        {"action": "kill_task", "target": "worker:1", "after_ms": 1500,
         "session": 1},
        {"action": "exit_executor", "target": "worker:0", "code": 1},
        {"action": "drop_heartbeats", "target": "worker:0", "count": 10},
        {"action": "delay_heartbeats", "target": "worker:0", "ms": 250,
         "count": 5},
        {"action": "blackout_rpc", "after_ms": 2000, "ms": 1500},
        {"action": "fail_checkpoint_write", "step": 10},
        {"action": "fail_checkpoint_write", "step": 12, "mode": "partial"},
        {"action": "delay_checkpoint_write", "ms": 200, "count": 3},
        {"action": "throttle_io", "target": "worker:0", "ms": 50,
         "after_batches": 4, "count": 100},
    ],
}


class TestFaultPlanParse:
    def test_good_plan_parses(self):
        plan = FaultPlan.parse(json.dumps(GOOD_PLAN))
        assert plan.seed == 7
        assert len(plan.specs) == 13
        by_action: dict[str, list] = {}
        for s in plan.specs:
            by_action.setdefault(s.action, []).append(s)
        assert by_action["exit_executor"][0].at == "pre_register"  # default
        fails = by_action["fail_checkpoint_write"]
        assert [s.mode for s in fails] == ["error", "partial"]
        assert by_action["delay_checkpoint_write"][0].ms == 200
        assert by_action["throttle_io"][0].after_batches == 4

    @pytest.mark.parametrize("mutate,complaint", [
        (lambda p: p.update(seed="x"), "seed must be an integer"),
        (lambda p: p.update(extra=1), "unknown top-level field"),
        (lambda p: p["faults"].append({"action": "explode"}),
         "unknown action"),
        (lambda p: p["faults"].append(
            {"action": "kill_task", "target": "worker:1", "at": "rendezvous",
             "bogus": 1}), "unknown field 'bogus'"),
        (lambda p: p["faults"].append({"action": "kill_task"}),
         "missing required field 'target'"),
        (lambda p: p["faults"].append(
            {"action": "kill_task", "target": "worker:1"}),
         "exactly one trigger"),
        (lambda p: p["faults"].append(
            {"action": "kill_task", "target": "worker:1",
             "at": "rendezvous", "after_ms": 5}), "exactly one trigger"),
        (lambda p: p["faults"].append(
            {"action": "kill_task", "target": "nocolon", "after_ms": 5}),
         "job:index"),
        (lambda p: p["faults"].append(
            {"action": "kill_task", "target": "any_non_chief",
             "after_ms": 5}), "only legal with at='rendezvous'"),
        (lambda p: p["faults"].append(
            {"action": "crash_coordinator", "phase": "nope"}),
         "phase must be one of"),
        (lambda p: p["faults"].append(
            {"action": "exit_executor", "target": "any_non_chief"}),
         "concrete 'job:index'"),
        (lambda p: p["faults"].append(
            {"action": "exit_executor", "target": "worker:0", "code": 0}),
         "must be nonzero"),
        (lambda p: p["faults"].append(
            {"action": "delay_heartbeats", "target": "worker:0"}),
         "missing required field 'ms'"),
        (lambda p: p["faults"].append(
            {"action": "fail_checkpoint_write", "step": -1}),
         "must be >= 0"),
        (lambda p: p["faults"].append(
            {"action": "drop_heartbeats", "target": "worker:0", "count": 0}),
         "must be >= 1"),
        (lambda p: p["faults"].append(
            {"action": "throttle_io", "target": "worker:0"}),
         "missing required field 'ms'"),
        (lambda p: p["faults"].append(
            {"action": "throttle_io", "target": "worker:0", "ms": 0}),
         "must be nonzero for throttle_io"),
        (lambda p: p["faults"].append(
            {"action": "throttle_io", "target": "any_non_chief", "ms": 5}),
         "concrete 'job:index'"),
        (lambda p: p["faults"].append(
            {"action": "fail_checkpoint_write", "step": 1,
             "mode": "sideways"}), "must be 'error' or 'partial'"),
        (lambda p: p["faults"].append(
            {"action": "delay_checkpoint_write"}),
         "missing required field 'ms'"),
        (lambda p: p["faults"].append(
            {"action": "delay_checkpoint_write", "ms": 0}),
         "must be nonzero for delay_checkpoint_write"),
        (lambda p: p["faults"].append(
            {"action": "delay_checkpoint_write", "target": "any_non_chief",
             "ms": 5}), "concrete 'job:index'"),
    ])
    def test_bad_plans_refused_with_pointed_errors(self, mutate, complaint):
        plan = json.loads(json.dumps(GOOD_PLAN))
        mutate(plan)
        with pytest.raises(FaultPlanError) as e:
            FaultPlan.parse(json.dumps(plan))
        assert complaint in str(e.value)

    def test_not_json_and_not_object(self):
        with pytest.raises(FaultPlanError, match="not valid JSON"):
            FaultPlan.parse("{nope")
        with pytest.raises(FaultPlanError, match="must be a JSON object"):
            FaultPlan.parse("[1,2]")

    def test_all_errors_reported_at_once(self):
        bad = {"faults": [{"action": "kill_task"},
                          {"action": "explode"}]}
        with pytest.raises(FaultPlanError) as e:
            FaultPlan.parse(json.dumps(bad))
        assert len(e.value.errors) >= 2

    def test_from_conf_inline_file_and_empty(self, tmp_path):
        conf = TonyConfiguration()
        assert FaultPlan.from_conf(conf, env={}) is None
        conf.set(keys.K_FAULT_PLAN, json.dumps(GOOD_PLAN))
        assert len(FaultPlan.from_conf(conf, env={}).specs) == 13
        path = tmp_path / "plan.json"
        path.write_text(json.dumps(GOOD_PLAN))
        conf.set(keys.K_FAULT_PLAN, str(path))
        assert FaultPlan.from_conf(conf, env={}).seed == 7
        conf.set(keys.K_FAULT_PLAN, str(tmp_path / "missing.json"))
        with pytest.raises(FaultPlanError, match="cannot read plan file"):
            FaultPlan.from_conf(conf, env={})

    def test_io_throttle_batch_semantics(self):
        """throttle_io fires per BATCH: nothing until after_batches have
        been served, then `ms` per batch for `count` batches, scoped to
        the target task and session."""
        from tony_tpu.resilience.faults import IoFaults

        plan = FaultPlan.parse(json.dumps({"faults": [
            {"action": "throttle_io", "target": "worker:0", "ms": 50,
             "after_batches": 2, "count": 3, "session": 1},
        ]}))
        sleeps = []
        io = IoFaults(plan, "worker:0", session=1, sleep=sleeps.append)
        for _ in range(8):
            io.maybe_throttle()
        # batches 3,4,5 throttled; the count then exhausts
        assert sleeps == [0.05, 0.05, 0.05]
        # wrong task / wrong session: inert
        for task, session in (("worker:1", 1), ("worker:0", 2)):
            other = []
            io2 = IoFaults(plan, task, session=session, sleep=other.append)
            for _ in range(8):
                io2.maybe_throttle()
            assert other == [] and not io2.active

    def test_legacy_env_aliases(self):
        conf = TonyConfiguration()
        plan = FaultPlan.from_conf(
            conf, env={constants.TEST_AM_CRASH: "1",
                       constants.TEST_WORKER_TERMINATION: "1"},
        )
        actions = sorted(s.action for s in plan.specs)
        assert actions == ["crash_coordinator", "kill_task"]


# ---------------------------------------------------------------------------
# Fault injector (coordinator-side semantics)
# ---------------------------------------------------------------------------
class TestFaultInjector:
    def _injector(self, *specs, seed=7):
        return FaultInjector(FaultPlan(seed=seed, specs=list(specs)))

    def test_disabled_without_plan(self):
        inj = FaultInjector(None)
        assert not inj.enabled
        assert inj.timed_kills(1, 1e9) == []
        assert not inj.heartbeat_kill("worker:0", 1)

    def test_concrete_rendezvous_kill_fires_once(self):
        inj = self._injector(FaultSpec(action="kill_task", target="worker:1",
                                       at="rendezvous"))
        assert inj.rendezvous_kills("worker:0", True, 1, ["worker:1"]) == []
        assert inj.rendezvous_kills("worker:1", False, 1, ["worker:1"]) \
            == ["worker:1"]
        # one-shot: re-registration does not re-fire
        assert inj.rendezvous_kills("worker:1", False, 1, ["worker:1"]) == []

    def test_any_non_chief_victim_is_seeded_deterministic(self):
        spec = FaultSpec(action="kill_task", target="any_non_chief",
                         at="rendezvous")
        pool = ["worker:1", "worker:2", "worker:3"]
        picks = {
            self._injector(spec, seed=5).rendezvous_kills(
                "worker:0", True, 1, pool
            )[0]
            for _ in range(5)
        }
        assert len(picks) == 1  # same seed → same victim, every run
        other = {
            self._injector(spec, seed=s).rendezvous_kills(
                "worker:0", True, 1, pool
            )[0]
            for s in range(10)
        }
        assert len(other) > 1   # different seeds spread the choice

    def test_session_scoping(self):
        inj = self._injector(FaultSpec(action="kill_task", target="worker:1",
                                       at="rendezvous", session=2))
        assert inj.rendezvous_kills("worker:1", False, 1, []) == []
        assert inj.rendezvous_kills("worker:1", False, 2, []) == ["worker:1"]

    def test_heartbeat_kill_counts_per_target(self):
        inj = self._injector(FaultSpec(action="kill_task", target="worker:1",
                                       after_heartbeats=3))
        assert not inj.heartbeat_kill("worker:1", 1)
        assert not inj.heartbeat_kill("worker:0", 1)  # other task: no count
        assert not inj.heartbeat_kill("worker:1", 1)
        assert inj.heartbeat_kill("worker:1", 1)
        assert not inj.heartbeat_kill("worker:1", 1)  # one-shot

    def test_heartbeat_counters_reset_per_session(self):
        inj = self._injector(
            FaultSpec(action="kill_task", target="worker:1",
                      after_heartbeats=2, count=2),
        )
        assert not inj.heartbeat_kill("worker:1", 1)
        inj.reset_session()
        assert not inj.heartbeat_kill("worker:1", 2)  # count restarted
        assert inj.heartbeat_kill("worker:1", 2)

    def test_timed_kills(self):
        inj = self._injector(FaultSpec(action="kill_task", target="worker:1",
                                       after_ms=500))
        assert inj.timed_kills(1, 499.0) == []
        assert inj.timed_kills(1, 500.0) == ["worker:1"]
        assert inj.timed_kills(1, 9999.0) == []  # one-shot

    def test_crash_coordinator_calls_exit(self, monkeypatch):
        import os

        calls = []
        monkeypatch.setattr(os, "_exit", lambda code: calls.append(code))
        inj = self._injector(FaultSpec(action="crash_coordinator",
                                       phase="monitor", session=1, code=3))
        inj.coordinator_phase("schedule", 1)
        assert calls == []
        inj.coordinator_phase("monitor", 2)  # wrong session
        assert calls == []
        inj.coordinator_phase("monitor", 1)
        assert calls == [3]


# ---------------------------------------------------------------------------
# Executor-side faults
# ---------------------------------------------------------------------------
class TestExecutorFaults:
    def test_resolution_scopes_by_task_and_session(self):
        plan = FaultPlan.parse(json.dumps({
            "faults": [
                {"action": "exit_executor", "target": "worker:1",
                 "session": 1, "code": 9},
                {"action": "drop_heartbeats", "target": "worker:1",
                 "count": 4},
                {"action": "delay_heartbeats", "target": "worker:0",
                 "ms": 100, "count": 2},
                {"action": "blackout_rpc", "ms": 500, "after_ms": 100},
            ],
        }))
        w1s1 = plan.for_executor("worker:1", 1)
        assert w1s1.pre_register_exit == 9
        assert w1s1.drop_heartbeats == 4
        assert w1s1.delay_heartbeats is None
        assert w1s1.rpc_blackout == (100, 500)
        w1s2 = plan.for_executor("worker:1", 2)
        assert w1s2.pre_register_exit is None  # session-scoped
        assert w1s2.drop_heartbeats == 4
        w0 = plan.for_executor("worker:0", 1)
        assert w0.pre_register_exit is None
        assert w0.delay_heartbeats == (2, 100)
        assert w0.rpc_blackout == (100, 500)  # untargeted: everyone

    def test_blackout_hook_window(self):
        plan = FaultPlan.parse(json.dumps({
            "faults": [{"action": "blackout_rpc", "ms": 100,
                        "after_ms": 50}],
        }))
        start = time.monotonic()
        hook = plan.for_executor("worker:0", 1).blackout_hook(start)
        hook()  # before the window: fine
        time.sleep(0.06)
        with pytest.raises(OSError, match="blackout"):
            hook()
        time.sleep(0.12)  # past the window
        hook()

    def test_checkpoint_faults_fire_counted(self):
        plan = FaultPlan.parse(json.dumps({
            "faults": [{"action": "fail_checkpoint_write", "step": 5}],
        }))
        cf = CheckpointFaults(plan, "worker:0")
        cf.maybe_fail_write(4)
        with pytest.raises(OSError, match="fault injection"):
            cf.maybe_fail_write(5)
        cf.maybe_fail_write(5)  # count=1: second write of step 5 succeeds

    def test_checkpoint_faults_respect_session(self):
        # A fault scoped to session 1 must NOT re-fire in the retried
        # session (a fresh process with fresh counters — the session id
        # is the only cross-process scoping there is).
        plan = FaultPlan.parse(json.dumps({
            "faults": [{"action": "fail_checkpoint_write", "step": 5,
                        "session": 1}],
        }))
        with pytest.raises(OSError):
            CheckpointFaults(plan, "worker:0", session=1).maybe_fail_write(5)
        CheckpointFaults(plan, "worker:0", session=2).maybe_fail_write(5)

    def test_checkpoint_faults_respect_target(self):
        plan = FaultPlan.parse(json.dumps({
            "faults": [{"action": "fail_checkpoint_write", "step": 5,
                        "target": "worker:1"}],
        }))
        CheckpointFaults(plan, "worker:0").maybe_fail_write(5)  # not us
        with pytest.raises(OSError):
            CheckpointFaults(plan, "worker:1").maybe_fail_write(5)


# ---------------------------------------------------------------------------
# Checkpoint progress probe (jax-free)
# ---------------------------------------------------------------------------
class TestProgressProbe:
    def _write_step(self, root, step, n_processes, *, torn=False,
                    bad_meta=False):
        d = root / f"step_{step}"
        d.mkdir(parents=True)
        for p in range(n_processes - (1 if torn else 0)):
            (d / f"process_{p}.npz").write_bytes(b"x")
        meta = (b"{not json" if bad_meta
                else json.dumps({"step": step,
                                 "num_processes": n_processes}).encode())
        (d / "metadata.json").write_bytes(meta)

    def test_missing_and_empty_dirs(self, tmp_path):
        assert latest_complete_step(tmp_path / "nope") is None
        assert latest_complete_step(tmp_path) is None

    def test_newest_complete_wins_over_torn(self, tmp_path):
        self._write_step(tmp_path, 3, 2)
        self._write_step(tmp_path, 7, 2)
        self._write_step(tmp_path, 9, 2, torn=True)     # missing a shard
        self._write_step(tmp_path, 11, 2, bad_meta=True)
        assert latest_complete_step(tmp_path) == 7

    def test_step_without_metadata_ignored(self, tmp_path):
        d = tmp_path / "step_4"
        d.mkdir()
        (d / "process_0.npz").write_bytes(b"x")
        assert latest_complete_step(tmp_path) is None

    def test_restore_resumable_pins_env_step(self, tmp_path, monkeypatch):
        from tony_tpu.checkpoint import CheckpointManager

        mgr = CheckpointManager(tmp_path, process_id=0, num_processes=1)
        template = {"step": np.array(0)}
        for s in (3, 7):
            mgr.save(s, {"step": np.array(s)}, blocking=True)
        # No env: newest complete, like plain restore.
        monkeypatch.delenv("TONY_RESUME_STEP", raising=False)
        assert int(mgr.restore_resumable(template)["step"]) == 7
        # Env pins the exact (older) step — stragglers may have finished
        # a newer one, but every process must resume the SAME step.
        monkeypatch.setenv("TONY_RESUME_STEP", "3")
        assert int(mgr.restore_resumable(template)["step"]) == 3
        # A vanished step and garbage both fall back to newest-complete.
        monkeypatch.setenv("TONY_RESUME_STEP", "5")
        assert int(mgr.restore_resumable(template)["step"]) == 7
        monkeypatch.setenv("TONY_RESUME_STEP", "junk")
        assert int(mgr.restore_resumable(template)["step"]) == 7

    def test_probe_agrees_with_checkpoint_manager(self, tmp_path):
        # The completeness rule's source of truth is CheckpointManager;
        # this pin keeps the jax-free re-implementation from drifting.
        from tony_tpu.checkpoint import CheckpointManager

        mgr = CheckpointManager(tmp_path, process_id=0, num_processes=1)
        state = {"step": np.array(3), "w": np.zeros(4)}
        mgr.save(3, state, blocking=True)
        mgr.save(7, {"step": np.array(7), "w": np.ones(4)}, blocking=True)
        assert mgr.latest_step() == 7
        assert latest_complete_step(tmp_path) == 7
        (tmp_path / "step_7" / "process_0.npz").unlink()  # tear it
        assert mgr.latest_step() == 3
        assert latest_complete_step(tmp_path) == 3


# ---------------------------------------------------------------------------
# Liveness: expiry timing + ping fencing
# ---------------------------------------------------------------------------
class TestLiveness:
    def test_expiry_fires_on_silence_not_on_pings(self):
        expired = []
        mon = LivenessMonitor(
            heartbeat_interval_ms=100, max_missed_heartbeats=3,
            on_expired=expired.append,
        )
        mon.start()
        try:
            mon.register("worker:0")
            # Ping for ~0.6s (well past the 0.3s expiry window): must stay
            # alive while pings flow.
            for _ in range(6):
                time.sleep(0.1)
                assert mon.receive_ping("worker:0")
            assert expired == []
            # Silence: expiry must fire within a generous bound.
            deadline = time.monotonic() + 5.0
            while not expired and time.monotonic() < deadline:
                time.sleep(0.05)
            assert expired == ["worker:0"]
        finally:
            mon.stop()

    def test_ping_from_unknown_task_is_fenced(self):
        mon = LivenessMonitor(100, 3, on_expired=lambda t: None)
        assert not mon.receive_ping("worker:9")       # never registered
        assert "worker:9" not in mon._last_seen

    def test_ping_after_expiry_does_not_reregister(self):
        expired = []
        mon = LivenessMonitor(
            heartbeat_interval_ms=50, max_missed_heartbeats=2,
            on_expired=expired.append,
        )
        mon.start()
        try:
            mon.register("worker:0")
            deadline = time.monotonic() + 5.0
            while not expired and time.monotonic() < deadline:
                time.sleep(0.05)
            assert expired == ["worker:0"]
            # The zombie pings again: it must NOT silently re-enter the
            # failed session's monitor.
            assert not mon.receive_ping("worker:0")
            assert "worker:0" not in mon._last_seen
        finally:
            mon.stop()

    def test_ping_after_unregister_is_fenced(self):
        mon = LivenessMonitor(100, 3, on_expired=lambda t: None)
        mon.register("worker:0")
        assert mon.receive_ping("worker:0")
        mon.unregister("worker:0")          # task completed
        assert not mon.receive_ping("worker:0")


# ---------------------------------------------------------------------------
# Heartbeater hardening
# ---------------------------------------------------------------------------
class _FlakyHeartbeatClient:
    def __init__(self, fail_first=0, fail_forever=False):
        self.fail_first = fail_first
        self.fail_forever = fail_forever
        self.sent = 0

    def task_executor_heartbeat(self, task_id, session_id):
        if self.fail_forever or self.fail_first > 0:
            self.fail_first -= 1
            raise ConnectionError("injected")
        self.sent += 1


class TestHeartbeater:
    def _beater(self, client, **kw):
        from tony_tpu.executor.task_executor import Heartbeater

        lost = []
        hb = Heartbeater(client, "worker:0", "1", interval_ms=10,
                         on_lost=lambda: lost.append(True), **kw)
        return hb, lost

    def test_transient_failures_survived(self):
        client = _FlakyHeartbeatClient(fail_first=3)
        hb, lost = self._beater(client, max_failures=5)
        hb.start()
        deadline = time.monotonic() + 5.0
        while client.sent < 3 and time.monotonic() < deadline:
            time.sleep(0.01)
        hb.stop()
        hb.join(timeout=2)
        assert client.sent >= 3      # recovered and kept pinging
        assert lost == []            # never declared the coordinator dead
        assert hb.consecutive_failures == 0

    def test_persistent_failure_triggers_on_lost(self):
        client = _FlakyHeartbeatClient(fail_forever=True)
        hb, lost = self._beater(client, max_failures=4)
        hb.start()
        hb.join(timeout=5)           # on_lost returns → thread exits
        assert lost == [True]
        assert hb.consecutive_failures == 4

    def test_drop_pings_fault_swallows_then_resumes(self):
        client = _FlakyHeartbeatClient()
        hb, lost = self._beater(client, drop_pings=3)
        hb.start()
        deadline = time.monotonic() + 5.0
        while client.sent < 2 and time.monotonic() < deadline:
            time.sleep(0.01)
        hb.stop()
        hb.join(timeout=2)
        assert client.sent >= 2 and lost == []
