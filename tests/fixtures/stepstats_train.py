"""Jax-free step-anatomy fixture: drives a REAL ``StepStats`` recorder
around a sleep-based "train step" and a batch iterator that honors the
fault plan's ``throttle_io`` entries (``io_faults_from_env``), so the
chaos e2e can flip the dominant phase to ``data_wait`` and collapse the
MFU deterministically without a jax compile in the loop.

Workload shape: tokens [B, T+1] like a real LM step; the config is
transformer-shaped so the analytic flops model sizes ``tony_mfu``.
``peak_flops`` is pinned so the MFU is a stable ratio of the step wall
whatever host runs the test: normal steps sleep ``FIXTURE_COMPUTE_S``,
throttled steps additionally wait out the fault plan's delay inside
``next()`` — exactly where a real starved input pipeline stalls.
"""
import os
import sys
import time

import numpy as np

from tony_tpu import observability
from tony_tpu.observability.stepstats import StepStats
from tony_tpu.resilience.faults import io_faults_from_env

if not os.environ.get("TONY_METRICS_FILE"):
    print("TONY_METRICS_FILE not exported", file=sys.stderr)
    sys.exit(4)

# Publish on every report: the e2e asserts on what rides the very next
# heartbeat, so the default write throttle only adds latency.
registry = observability.default_registry()
registry._publish_min_interval_s = 0.0


class Cfg:
    d_model = 64
    n_layers = 2
    vocab_size = 512
    n_heads = 4
    head_dim = 16
    n_kv_heads = 2
    d_ff = 256
    dtype = "float32"


stats = StepStats(
    cfg=Cfg(), registry=registry, peak_flops=1e12,
    enabled=True, calibrate=False,
)

faults = io_faults_from_env()


def batches():
    while True:
        if faults is not None:
            faults.maybe_throttle()
        yield np.zeros((4, 33), np.int32)  # [B, T+1] = batch 4, seq 32


wrapped = stats.wrap_batches(batches())

steps = int(os.environ.get("FIXTURE_STEPS", "90"))
compute_s = float(os.environ.get("FIXTURE_COMPUTE_S", "0.015"))

for step in range(1, steps + 1):
    batch = next(wrapped)
    stats.step_begin(batch.shape)
    time.sleep(compute_s)  # the "device" work
    stats.step_end(0.0005)
    registry.report(step=step, loss=1.0 / step)

time.sleep(float(os.environ.get("LINGER_S", "2.0")))
sys.exit(0)
