"""Proves a REAL cross-process collective through the full stack: the
executor-injected env -> tony_tpu.runtime.initialize() -> jax.distributed
(gloo over the CPU backend) -> pmap psum across every executor process.

This is the analogue of the reference running real gang-scheduled jobs
through its whole stack (TestTonyE2E.java:27-253), strengthened to assert
the *value* of an actual collective rather than just the env contract.
"""
import os
import sys

# The test environment pins JAX to the real TPU chip; executors must land on
# the CPU backend so two processes can share one machine.
os.environ["JAX_PLATFORMS"] = "cpu"
import jax

try:
    jax.config.update("jax_platforms", "cpu")
except RuntimeError:
    pass

import tony_tpu.runtime as rt

ctx = rt.initialize()
if not ctx.is_distributed:
    print("expected a distributed context (2+ processes)", file=sys.stderr)
    sys.exit(6)

import jax.numpy as jnp

local = jax.local_device_count()
n_global = jax.device_count()
if n_global != ctx.num_processes * local:
    print(
        f"global device count {n_global} != {ctx.num_processes} procs x "
        f"{local} local devices — jax.distributed did not connect",
        file=sys.stderr,
    )
    sys.exit(7)

# Each process contributes (process_id + 1) per local device; the psum must
# see every other process's value, proving real cross-process data movement.
x = jnp.full((local,), float(ctx.process_id + 1))
y = jax.pmap(lambda v: jax.lax.psum(v, "i"), axis_name="i")(x)
got = float(y[0])
want = float(local * sum(p + 1 for p in range(ctx.num_processes)))
print(
    f"process {ctx.process_id}/{ctx.num_processes}: psum={got} want={want} "
    f"(global devices={n_global})"
)
if got != want:
    print(f"psum mismatch: got {got}, want {want}", file=sys.stderr)
    sys.exit(8)
sys.exit(0)
