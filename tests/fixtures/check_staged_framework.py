"""Asserts the executor imports tony_tpu from the cluster submitter's staged
per-submission lib dir (``lib-<uuid>/tony_tpu``), not an ambient install —
the analogue of the reference resolving the submitted fat jar from
``.tony/<uuid>`` (ClusterSubmitter.java:59-63)."""
import sys

import tony_tpu

if "lib-" not in tony_tpu.__file__:
    print(f"tony_tpu resolved from {tony_tpu.__file__}, not a staged lib dir",
          file=sys.stderr)
    sys.exit(9)
sys.exit(0)
