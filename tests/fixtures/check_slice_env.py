"""Asserts the coordinator threaded its SlicePlan into the task env as
TONY_SLICE_TOPOLOGY, readable via tony_tpu.runtime.slice_topology()."""
import sys

import tony_tpu.runtime as rt

plan = rt.slice_topology()
if plan is None:
    print("TONY_SLICE_TOPOLOGY missing", file=sys.stderr)
    sys.exit(2)
for field in ("accelerator_type", "num_slices", "hosts_per_slice",
              "chips_per_slice"):
    if field not in plan:
        print(f"slice plan missing {field}: {plan}", file=sys.stderr)
        sys.exit(3)
if plan["accelerator_type"] != "v5litepod-4":
    print(f"unexpected accelerator: {plan}", file=sys.stderr)
    sys.exit(4)
sys.exit(0)
