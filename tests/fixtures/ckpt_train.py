"""Checkpoint/resume fixture: trains 10 steps with CheckpointManager,
crashing at step 5 on the first session; the retried session must restore
from the latest complete checkpoint (step > 0), finish training, and end
with state that proves no steps were lost or repeated."""
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
import jax.numpy as jnp

from tony_tpu.checkpoint import CheckpointManager

TOTAL_STEPS = 10
CRASH_AT = 5

session = os.environ.get("SESSION_ID", "1")
# NOT wrapped in Path(): gs:// URIs must survive verbatim (Path collapses
# the double slash).
mgr = CheckpointManager(os.environ["CKPT_DIR"])
template = {"step": jnp.zeros((), jnp.int32), "w": jnp.zeros((4,))}
restored = mgr.restore(template)
start = int(restored["step"]) if restored is not None else 0
state = restored if restored is not None else template
print(f"session {session}: starting from step {start}", flush=True)

if session != "1" and start == 0:
    print("retried session did not resume from a checkpoint", file=sys.stderr)
    sys.exit(7)

for step in range(start, TOTAL_STEPS):
    state = {
        "step": jnp.asarray(step + 1, jnp.int32),
        "w": state["w"] + 1.0,
    }
    mgr.save(step + 1, state, blocking=True)
    if step + 1 == CRASH_AT and session == "1":
        print("simulated crash mid-training", file=sys.stderr)
        sys.exit(1)

if float(state["w"][0]) != float(TOTAL_STEPS):
    print(f"lost or repeated steps: w={state['w']}", file=sys.stderr)
    sys.exit(8)
sys.exit(0)
