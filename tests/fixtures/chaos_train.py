"""Deterministic chaos-training fixture for the kill-and-resume e2e.

Trains a tiny numpy "model" through the real CheckpointManager, one
complete checkpoint per step. In session 1 every task parks (sleeps)
once it reaches PARK_AT — so the session can only end via the fault
plan's kill, making the surviving checkpoint step deterministic. A
retried session must resume from TONY_RESUME_STEP (asserted: resuming
from 0 or from a step != PARK_AT fails the run) and train to TARGET.
"""

import os
import sys
import time

import numpy as np

from tony_tpu.checkpoint import CheckpointManager

TARGET = 10
PARK_AT = 5


def main() -> int:
    ckpt_dir = os.environ["TONY_CHECKPOINT_DIR"]
    session = int(os.environ.get("SESSION_ID", "1"))
    process_id = int(os.environ.get("TASK_INDEX", "0"))
    num = int(os.environ.get("TASK_NUM", "1"))
    mgr = CheckpointManager(
        ckpt_dir, process_id=process_id, num_processes=num
    )
    state = {"step": np.array(0), "w": np.zeros(4)}
    resume_env = os.environ.get("TONY_RESUME_STEP")
    restored = mgr.restore_resumable(state)
    start = 0
    if restored is not None:
        state = restored
        start = int(state["step"])
        print(f"resumed from step {start}", flush=True)
    if session > 1:
        # The retried session must have been pointed at the parked
        # checkpoint — recomputing from scratch is the bug this fixture
        # exists to catch.
        if resume_env is None:
            print("retried session got no TONY_RESUME_STEP", file=sys.stderr)
            return 1
        if start != int(resume_env):
            print(f"resumed from {start}, expected {resume_env}",
                  file=sys.stderr)
            return 1
    for step in range(start + 1, TARGET + 1):
        state = {"step": np.array(step), "w": state["w"] + 1.0}
        mgr.save(step, state, blocking=True)
        print(f"step {step}", flush=True)
        if session == 1 and step >= PARK_AT:
            # Park: session 1 never finishes on its own; only the fault
            # plan's kill ends it, always with step PARK_AT complete.
            time.sleep(300)
    return 0


if __name__ == "__main__":
    sys.exit(main())
