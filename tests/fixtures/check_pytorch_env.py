"""Asserts the PyTorch runtime env contract (reference:
exit_0_check_pytorchenv.py): RANK / WORLD / INIT_METHOD present and sane."""
import os
import sys

for var in ("RANK", "WORLD", "WORLD_SIZE", "INIT_METHOD", "MASTER_ADDR", "MASTER_PORT"):
    if var not in os.environ:
        print(f"missing {var}", file=sys.stderr)
        sys.exit(2)

if not os.environ["INIT_METHOD"].startswith("tcp://"):
    print(f"bad INIT_METHOD {os.environ['INIT_METHOD']}", file=sys.stderr)
    sys.exit(3)

rank, world = int(os.environ["RANK"]), int(os.environ["WORLD"])
if not 0 <= rank < world:
    print(f"bad rank {rank} of {world}", file=sys.stderr)
    sys.exit(4)

sys.exit(0)
