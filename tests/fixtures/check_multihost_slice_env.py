"""Asserts per-slice identity when slices span MULTIPLE hosts — the
placement path VERDICT r3 weak #1 found untested: with hosts_per_slice>1,
task index i must land on slice i // hosts as in-slice process i % hosts.
Run with 4 workers x tpus=4 pinned to v4-16 => 2 slices of 2 hosts each."""
import os
import sys

import tony_tpu.runtime as rt

ctx = rt.task_context()
plan = rt.slice_topology()
if plan is None or plan["hosts_per_slice"] != 2 or plan["num_slices"] != 2:
    print(f"expected 2 slices x 2 hosts, got {plan}", file=sys.stderr)
    sys.exit(2)
want_slice, want_proc = divmod(ctx.task_index, 2)
if ctx.slice_index != want_slice or ctx.slice_process_id != want_proc:
    print(f"slice identity wrong: task {ctx.task_index} -> "
          f"slice {ctx.slice_index}/{ctx.slice_process_id}, want "
          f"{want_slice}/{want_proc}", file=sys.stderr)
    sys.exit(3)
if os.environ.get("MEGASCALE_SLICE_ID") != str(want_slice):
    print(f"MEGASCALE_SLICE_ID = "
          f"{os.environ.get('MEGASCALE_SLICE_ID')!r}, want {want_slice}",
          file=sys.stderr)
    sys.exit(4)
# One flat jax.distributed identity across both slices.
if ctx.num_processes != 4:
    print(f"num_processes = {ctx.num_processes}", file=sys.stderr)
    sys.exit(5)
sys.exit(0)
