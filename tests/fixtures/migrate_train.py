"""Fixture: a checkpointing trainer for the live-migration e2e.

Attempt 1 trains toward a far TARGET (it can only end by preemption),
saving every CKPT_EVERY steps AND whenever the coordinator's flush
order arrives (``mgr.flush_requested`` — the migration path under
test), reporting every step over the heartbeat piggyback so the
coordinator knows how far it got. The victim's last executed step is
continuously published to $LAST_STEP_OUT so the test can compare it to
the relaunch's resume step. A resumed attempt (TONY_RESUME_STEP set)
runs two more steps and exits 0.
"""

import os
import sys
import time

import numpy as np

from tony_tpu import observability
from tony_tpu.checkpoint import CheckpointManager

TARGET = int(os.environ.get("TARGET_STEPS", "500"))
EVERY = int(os.environ.get("CKPT_EVERY", "10"))
STEP_S = float(os.environ.get("STEP_S", "0.15"))


def main() -> int:
    mgr = CheckpointManager(
        os.environ["TONY_CHECKPOINT_DIR"],
        process_id=int(os.environ.get("TASK_INDEX", "0")),
        num_processes=int(os.environ.get("TASK_NUM", "1")),
    )
    state = {"step": np.array(0), "w": np.zeros(4)}
    restored = mgr.restore_resumable(state)
    start = 0
    if restored is not None:
        state = restored
        start = int(state["step"])
    print(f"starting at step {start}", flush=True)
    resumed = os.environ.get("TONY_RESUME_STEP") is not None
    last_out = os.environ.get("LAST_STEP_OUT")
    for step in range(start + 1, TARGET + 1):
        time.sleep(STEP_S)
        state = {"step": np.array(step), "w": state["w"] + 1.0}
        observability.report(step=step, loss=1.0 / step,
                            step_time_ms=STEP_S * 1000.0)
        if last_out:
            with open(last_out + ".tmp", "w") as f:
                f.write(str(step))
            os.replace(last_out + ".tmp", last_out)
        # Consume the flush order even on interval-save steps (same
        # pattern as examples/lm_train.py — a short-circuit `or` would
        # leave the order unserved and double-save one step later).
        flushed = mgr.flush_requested(step)
        if flushed or step % EVERY == 0:
            mgr.save(step, state)
        if resumed and step >= start + 2:
            mgr.save(step, state, blocking=True)
            print(f"resumed run done at step {step}", flush=True)
            return 0
    mgr.wait()
    return 0


if __name__ == "__main__":
    sys.exit(main())
