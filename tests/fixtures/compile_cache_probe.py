"""Fixture: prove the tony.compile.* wiring reaches the user process and
the plan-instrumented step records cache hits/misses. Initializes the
runtime (which configures the persistent cache from the executor's
TONY_COMPILE_* env), compiles one tiny classifier step, and appends this
session's compile counters to $PROBE_OUT — one JSON line per run, so a
re-submitted job appends a second line the test compares."""
import json
import os
import sys

import tony_tpu.runtime as rt

ctx = rt.initialize()

import jax  # noqa: E402  (after initialize: cache config must precede use)
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

if os.environ.get("TONY_COMPILE_CACHE_DIR", "") != \
        jax.config.jax_compilation_cache_dir:
    print("compile cache env not wired into jax config", file=sys.stderr)
    sys.exit(2)

from tony_tpu.models import MnistConfig  # noqa: E402
from tony_tpu.models.train import make_classifier_step  # noqa: E402
from tony_tpu.parallel.mesh import MeshSpec, build_mesh  # noqa: E402

mesh = build_mesh(MeshSpec(), devices=jax.devices()[:1])
init_fn, step_fn = make_classifier_step(
    MnistConfig(arch="mlp", dtype="float32"), mesh
)
rng = np.random.default_rng(0)
images = jnp.asarray(rng.normal(size=(8, 28, 28, 1)), jnp.float32)
labels = jnp.asarray(rng.integers(0, 10, (8,)), jnp.int32)
state = init_fn(jax.random.key(0))
state, metrics = step_fn(state, images, labels)
assert np.isfinite(float(metrics["loss"]))

from tony_tpu import observability  # noqa: E402

counters = observability.default_registry().snapshot()["counters"]
with open(os.environ["PROBE_OUT"], "a") as f:
    f.write(json.dumps(counters) + "\n")
