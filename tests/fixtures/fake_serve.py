"""Fixture: a jax-free stand-in for examples/lm_serve.py — the serving
task the fleet e2e tests launch as replica jobs. Speaks the replica
contract the router and daemon reconcile against:

* publishes ``serving-fake-<idx>.addr`` atomically under $TONY_LOG_DIR
  once bound (what ``discover_replica_addr`` globs for);
* ``GET /healthz`` -> the serving stats shape the router polls
  (active_slots / queue_depth / slots / draining / models / retired);
* ``POST /generate`` -> a deterministic token function of the prompt
  (stateless, so every replica agrees — the fleet-parity check);
* ``POST /shutdown`` -> drain and exit 0 (the graceful scale-down
  path: the replica job SUCCEEDs).

Env knobs: SERVE_SLEEP_MS delays each generate (in-flight failover
windows); SERVE_MODELS comma-lists the advertised models.
"""
import json
import os
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer


def fake_tokens(prompt, max_new_tokens, eos_id=None):
    base = sum(int(t) for t in prompt) % 1000
    out = []
    for i in range(int(max_new_tokens)):
        tok = (base * 31 + i * 7 + 1) % 97
        out.append(tok)
        if eos_id is not None and tok == eos_id:
            break
    return out


def main() -> int:
    shutdown = threading.Event()
    sleep_ms = int(os.environ.get("SERVE_SLEEP_MS", "0"))
    models = [m for m in os.environ.get("SERVE_MODELS",
                                        "default").split(",") if m]
    retired = [0]

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *args):
            pass

        def _reply(self, code, obj):
            body = json.dumps(obj).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            if self.path == "/healthz":
                self._reply(200, {
                    "active_slots": 0, "queue_depth": 0, "slots": 4,
                    "draining": False, "models": models,
                    "retired": retired[0],
                })
            else:
                self._reply(404, {"error": f"no route {self.path}"})

        def do_POST(self):
            n = int(self.headers.get("Content-Length", "0"))
            body = json.loads(self.rfile.read(n) or b"{}")
            if self.path == "/shutdown":
                self._reply(200, {"ok": True})
                shutdown.set()
            elif self.path == "/generate":
                if sleep_ms:
                    time.sleep(sleep_ms / 1000.0)
                tokens = fake_tokens(body.get("prompt", []),
                                     body.get("max_new_tokens", 0),
                                     body.get("eos_id"))
                retired[0] += 1
                self._reply(200, {
                    "id": body.get("request_id", "req"),
                    "tokens": tokens, "length": len(tokens),
                    "ttft_ms": 1.0, "wall_ms": 2.0,
                })
            else:
                self._reply(404, {"error": f"no route {self.path}"})

    httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
    port = httpd.server_address[1]
    threading.Thread(target=httpd.serve_forever, daemon=True).start()

    log_dir = os.environ.get("TONY_LOG_DIR", ".")
    idx = os.environ.get("TASK_INDEX", "0")
    addr_file = os.path.join(log_dir, f"serving-fake-{idx}.addr")
    tmp = f"{addr_file}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        f.write(f"127.0.0.1:{port}\n")
    os.replace(tmp, addr_file)
    print(f"fake serving on :{port}", flush=True)

    shutdown.wait(timeout=float(os.environ.get("SERVE_MAX_S", "600")))
    httpd.shutdown()
    httpd.server_close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
