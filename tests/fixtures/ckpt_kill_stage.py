"""Fixture: SIGKILL itself mid-persist at a chosen pipeline stage.

Commits steps 1..3 normally, then saves step 4 with a store wrapper
that SIGKILLs this process at exactly one commit boundary:

    shard    — inside the step-4 shard upload (tmp written, no rename
               on fs stores; the raw put on object stores)
    sidecar  — after the shard landed, before its commit sidecar
    marker   — after shard + sidecar, before process 0's step marker

Whatever the stage, the parent test must find step 3 the newest
complete step and step 4 unreadable — the torn-step-unreadability
contract of the commit-marker layout.

Usage: ckpt_kill_stage.py <dir> <stage>
"""

import os
import signal
import sys

import numpy as np

from tony_tpu.checkpoint import CheckpointManager
from tony_tpu.checkpoint import layout

KILL_STEP = 4


class _KillingStore:
    def __init__(self, inner, stage: str) -> None:
        self._inner = inner
        self._stage = stage

    def put_file(self, step, name, data):
        if step == KILL_STEP:
            if self._stage == "shard" and name == layout.shard_name(0):
                # Die INSIDE the upload: write the tmp file the fs
                # store would, then never rename it.
                step_dir = self._inner.directory / f"step_{step}"
                step_dir.mkdir(parents=True, exist_ok=True)
                (step_dir / f".tmp_{name}").write_bytes(data[:16])
                os.kill(os.getpid(), signal.SIGKILL)
            if self._stage == "sidecar" and name == layout.sidecar_name(0):
                os.kill(os.getpid(), signal.SIGKILL)
            if self._stage == "marker" and name == layout.MARKER:
                os.kill(os.getpid(), signal.SIGKILL)
        return self._inner.put_file(step, name, data)

    def __getattr__(self, attr):
        return getattr(self._inner, attr)


def main() -> int:
    directory, stage = sys.argv[1], sys.argv[2]
    mgr = CheckpointManager(directory, torn_gc_grace_s=3600.0)
    for step in (1, 2, 3):
        mgr.save(step, {"step": np.array(step),
                        "w": np.full(8, float(step))}, blocking=True)
    mgr._store = _KillingStore(mgr._store, stage)
    mgr.save(KILL_STEP, {"step": np.array(KILL_STEP),
                         "w": np.full(8, float(KILL_STEP))})
    mgr.wait()
    print("survived — the kill stage never fired", file=sys.stderr)
    return 1


if __name__ == "__main__":
    sys.exit(main())
