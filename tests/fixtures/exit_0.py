"""Trivial success fixture (reference: tony-core/src/test/resources/exit_0.py)."""
import sys

sys.exit(0)
