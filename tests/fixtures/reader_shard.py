"""Data-plane handoff fixture: each executor process builds a reader via
tony_tpu.runtime.sharded_reader (identity from the injected env) and writes
the record ids it read to TONY_LOG_DIR; the test asserts the shards form an
exact cover — every record read exactly once across the job."""
import json
import os
import sys

import tony_tpu.runtime as rt

ctx = rt.task_context()
# ";"-separated so multiple paths (incl. gs:// URIs, which embed ":") fit
# in one comma-separated shell-env assignment.
data = os.environ["READER_DATA"].split(";")
reader = rt.sharded_reader(data, fmt="jsonl", batch_size=4)
schema = json.loads(reader.schema_json())
if schema["format"] != "jsonl":
    print(f"bad schema: {schema}", file=sys.stderr)
    sys.exit(5)

ids = []
for batch in reader:
    ids.extend(rec["id"] for rec in batch)
reader.close()

out = os.path.join(os.environ["TONY_LOG_DIR"],
                   f"reader-shard-{ctx.process_id}.json")
with open(out, "w") as f:
    json.dump(ids, f)
print(f"process {ctx.process_id} read {len(ids)} records")
sys.exit(0)
