"""Data-plane handoff fixture: each executor process builds a reader via
tony_tpu.runtime.sharded_reader (identity from the injected env) and writes
the record ids it read to TONY_LOG_DIR; the test asserts the shards form an
exact cover — every record read exactly once across the job."""
import glob
import json
import os
import sys
import time

import tony_tpu.runtime as rt

ctx = rt.task_context()
# ";"-separated so multiple paths (incl. gs:// URIs, which embed ":") fit
# in one comma-separated shell-env assignment.
data = os.environ["READER_DATA"].split(";")
reader = rt.sharded_reader(data, fmt="jsonl", batch_size=4)
schema = json.loads(reader.schema_json())
if schema["format"] != "jsonl":
    print(f"bad schema: {schema}", file=sys.stderr)
    sys.exit(5)

ids = []
for batch in reader:
    ids.extend(rec["id"] for rec in batch)
reader.close()

out = os.path.join(os.environ["TONY_LOG_DIR"],
                   f"reader-shard-{ctx.process_id}.json")
tmp = out + ".tmp"
with open(tmp, "w") as f:
    json.dump(ids, f)
os.rename(tmp, out)
print(f"process {ctx.process_id} read {len(ids)} records")

# Chief success ends the SESSION (reference semantics) and teardown then
# kills stragglers — so every worker waits for the full shard set before
# exiting, or a slow peer's file could be lost mid-write under load.
deadline = time.time() + 60
while len(glob.glob(os.path.join(
        os.environ["TONY_LOG_DIR"], "reader-shard-*.json"))) < ctx.num_processes:
    if time.time() > deadline:
        print("timed out waiting for peer shards", file=sys.stderr)
        sys.exit(6)
    time.sleep(0.1)
sys.exit(0)
