"""Jax-free health-analytics fixture: every worker reports a steady
train loop through ``observability.report``, but the task named by
``STRAGGLER_TASK`` reports a step time far above the fleet's — the
coordinator's MAD-based straggler detector must flag exactly that task
while the job runs. Step count and cadence come from the env so chaos
tests can keep the job alive long enough for timed kills to land."""
import os
import sys
import time

from tony_tpu import observability

if not os.environ.get("TONY_METRICS_FILE"):
    print("TONY_METRICS_FILE not exported", file=sys.stderr)
    sys.exit(4)

# Publish on every report: the health e2e asserts on what rides the
# very next heartbeat, so the default write throttle only adds latency.
registry = observability.default_registry()
registry._publish_min_interval_s = 0.0

task = f"{os.environ['JOB_NAME']}:{os.environ['TASK_INDEX']}"
straggling = os.environ.get("STRAGGLER_TASK") == task
step_time_ms = 80.0 if straggling else 5.0
steps = int(os.environ.get("FIXTURE_STEPS", "40"))
cadence_s = float(os.environ.get("FIXTURE_CADENCE_S", "0.08"))

for step in range(1, steps + 1):
    registry.report(step=step, loss=1.0 / step, step_time_ms=step_time_ms)
    time.sleep(cadence_s)

time.sleep(float(os.environ.get("LINGER_S", "0.5")))
sys.exit(0)
