"""Jax-free self-healing chaos fixture.

Every worker runs a paced train loop reporting step/loss/step_time_ms
through ``observability.report`` (so the coordinator's MAD straggler
scorer and the ``kill_task after_steps`` trigger see real telemetry);
the CHIEF additionally writes one complete checkpoint per step through
the real ``CheckpointManager`` so the coordinator's resume probe is
exact. A ``degrade_task`` fault-plan entry makes any worker a
deterministic straggler (incarnation 0 only — an evicted-and-replaced
copy runs clean), and the process honors the healing env contract:

* ``TONY_RESUME_STEP`` — start there instead of step 0 (a resync'd
  survivor or a freshly launched replacement both resume);
* ``TONY_TASK_INCARNATION`` — echoed into the start line so tests can
  grep which copy ran;
* ``TONY_RESHARD_PLAN`` — printed (plan key + process count) so the
  elastic-shrink e2e can assert the survivors actually received the
  coordinator's replanned sharding.

Gang-finish barrier: real SPMD training is lock-step — the job is done
when the SLOWEST worker is done, because every step synchronizes on
collectives. These workers step independently, and the session's chief
semantics would otherwise end the job (and the straggler's drag) the
moment the clean chief finished. So each non-chief drops a
``done-s<session>-<dense index>`` marker in the shared log dir when it
reaches the target, and the chief exits only once every peer's marker
exists — a straggler stretches the job wall exactly like it would
stretch a synchronized train loop.
"""

import json
import os
import sys
import time

import numpy as np

from tony_tpu import observability
from tony_tpu.checkpoint import CheckpointManager
from tony_tpu.resilience.faults import step_faults_from_env

if not os.environ.get("TONY_METRICS_FILE"):
    print("TONY_METRICS_FILE not exported", file=sys.stderr)
    sys.exit(4)

# Publish on every report: the healing loop acts on what rides the very
# next heartbeat, so the default write throttle only adds latency.
registry = observability.default_registry()
registry._publish_min_interval_s = 0.0

job = os.environ.get("JOB_NAME", "worker")
task_index = int(os.environ.get("TASK_INDEX", "0"))
task_num = int(os.environ.get("TASK_NUM", "1"))
incarnation = int(os.environ.get("TONY_TASK_INCARNATION", "0") or 0)
target = int(os.environ.get("HEAL_TARGET", "30"))
cadence_s = float(os.environ.get("HEAL_CADENCE_S", "0.1"))
chief = job == "worker" and task_index == 0

ckpt_dir = os.environ.get("TONY_CHECKPOINT_DIR")
mgr = (
    CheckpointManager(ckpt_dir, process_id=0, num_processes=1)
    if chief and ckpt_dir else None
)

start = 0
resume_env = os.environ.get("TONY_RESUME_STEP")
if resume_env:
    start = int(resume_env)
elif mgr is not None:
    restored = mgr.restore_resumable({"step": np.array(0), "w": np.zeros(2)})
    if restored is not None:
        start = int(restored["step"])

print(
    f"heal-train start task={job}:{task_index} num={task_num} "
    f"incarnation={incarnation} start={start}",
    flush=True,
)
reshard = os.environ.get("TONY_RESHARD_PLAN")
if reshard:
    note = json.loads(reshard)
    print(
        f"reshard note: plan={note.get('plan')} "
        f"num_processes={note.get('num_processes')} "
        f"resume_step={note.get('resume_step')}",
        flush=True,
    )

faults = step_faults_from_env()
for step in range(start + 1, target + 1):
    t0 = time.perf_counter()
    time.sleep(cadence_s)
    if faults is not None:
        faults.maybe_degrade(step)
    wall_ms = (time.perf_counter() - t0) * 1000.0
    registry.report(step=step, loss=1.0 / step, step_time_ms=wall_ms)
    if mgr is not None:
        mgr.save(step, {"step": np.array(step), "w": np.zeros(2) + step},
                 blocking=True)
    print(f"step {step}", flush=True)

sync_dir = os.environ.get("HEAL_SYNC_DIR") or os.environ.get("TONY_LOG_DIR")
session = os.environ.get("SESSION_ID", "0")
if sync_dir:
    if not chief:
        marker = os.path.join(sync_dir, f"done-s{session}-{task_index}")
        with open(marker, "w") as f:
            f.write(str(target))
    else:
        # Lock-step finish: the chief (whose exit decides the session)
        # waits for every peer of THIS session's dense gang view.
        deadline = time.monotonic() + float(
            os.environ.get("HEAL_SYNC_TIMEOUT_S", "180")
        )
        want = [os.path.join(sync_dir, f"done-s{session}-{i}")
                for i in range(1, task_num)]
        while not all(os.path.exists(p) for p in want):
            if time.monotonic() > deadline:
                print(f"gang-finish barrier timed out waiting for "
                      f"{[p for p in want if not os.path.exists(p)]}",
                      file=sys.stderr, flush=True)
                sys.exit(3)
            time.sleep(0.1)

print(f"done at {target}", flush=True)
sys.exit(0)
