"""Fixture: the failover-chaos daemon — detached attempts, a warm-idle
slice, one RUNNING and one QUEUED job, then the parent SIGKILLs it.

Submits three jobs before starting the loop (so their journal records
are down deterministically), all against a 2-slice pool with a
per-tenant quota of 1:

    warm  — exit_0.py, tenant "w": runs, finishes, leaves a FREE slice
    run   — preemptible.py, tenant "t": sleeps $SLEEP_S holding a slice
            (detached: its coordinator survives the daemon's death)
    queue — exit_0.py, tenant "t": quota-blocked behind "run"

Prints the three job ids space-separated on stdout, starts the daemon,
and waits to be SIGKILLed. The parent watches scheduler-state.json for
the acceptance shape (warm SUCCEEDED+FREE slice, run RUNNING, queue
QUEUED), kills this process, and recovers with a fresh daemon.

Usage: sched_ha_chaos.py <base_dir> <marker_file> <sleep_s>
"""

import sys
import time
from pathlib import Path

from tony_tpu.conf import keys
from tony_tpu.conf.configuration import TonyConfiguration
from tony_tpu.scheduler.service import SchedulerDaemon

FIXTURES = Path(__file__).resolve().parent


def _conf(base: Path, **kv) -> TonyConfiguration:
    conf = TonyConfiguration()
    conf.set(keys.K_STAGING_LOCATION, str(base / "staging"))
    conf.set(keys.K_HISTORY_LOCATION, str(base / "history"))
    conf.set(keys.K_AM_STOP_GRACE_MS, 0)
    for k, v in kv.items():
        conf.set(k, v)
    return conf


def main() -> int:
    base = Path(sys.argv[1])
    marker, sleep_s = sys.argv[2], sys.argv[3]
    daemon = SchedulerDaemon(base / "sched", conf=_conf(
        base,
        **{keys.K_SCHED_TICK_MS: 50,
           keys.K_SCHED_MAX_SLICES: 2,
           keys.K_SCHED_DETACHED: True,
           keys.K_SCHED_TENANT_QUOTA: 1},
    ))

    def job(fixture: str, tenant: str, **kv) -> TonyConfiguration:
        c = _conf(base, **kv)
        c.set(keys.K_EXECUTES, str(FIXTURES / fixture))
        c.set(keys.K_PYTHON_BINARY, sys.executable)
        c.set(keys.instances_key("worker"), 1)
        c.set(keys.instances_key("ps"), 0)
        c.set(keys.K_SCHED_TENANT, tenant)
        return c

    ids = [
        daemon.submit(job("exit_0.py", "w")),
        daemon.submit(job(
            "preemptible.py", "t",
            **{keys.K_SHELL_ENV: f"MARKER_OUT={marker},SLEEP_S={sleep_s}"},
        )),
        daemon.submit(job("exit_0.py", "t")),
    ]
    print(" ".join(ids), flush=True)

    daemon.start(serve_http=False)
    time.sleep(600)  # the parent SIGKILLs us
    return 3


if __name__ == "__main__":
    sys.exit(main())
