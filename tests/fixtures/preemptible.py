"""Fixture: a preemptible worker for scheduler e2e tests. First run
(no TONY_RESUME_STEP) appends its resume state to $MARKER_OUT and
sleeps — the window the test preempts into; a resumed run (the
scheduler seeded TONY_RESUME_STEP from the probed checkpoint) records
the step and exits 0 immediately."""
import os
import sys
import time

with open(os.environ["MARKER_OUT"], "a") as f:
    f.write(f"resume={os.environ.get('TONY_RESUME_STEP')}\n")
if os.environ.get("TONY_RESUME_STEP") is None:
    time.sleep(float(os.environ.get("SLEEP_S", "60")))
sys.exit(0)
