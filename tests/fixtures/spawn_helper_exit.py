"""Teardown fixture: the worker spawns a long-lived helper in its process
group and exits 0 — the helper must NOT survive the job (the executor
reaps the whole user process group even after a clean script exit, like
YARN killing the container cgroup)."""
import json
import os
import subprocess
import sys

helper = subprocess.Popen(
    [sys.executable, "-c", "import time; time.sleep(3600)"]
)
out = os.path.join(
    os.environ["TONY_LOG_DIR"], f"helper-{os.environ['TASK_INDEX']}.json"
)
with open(out, "w") as f:
    json.dump({"helper": helper.pid}, f)
sys.exit(0)
