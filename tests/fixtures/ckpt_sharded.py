"""Multi-process sharded checkpoint fixture: 2 executor processes hold a
global array sharded across both (non-fully-addressable from each), save
per-process shards, then restore and verify — the path single-process unit
tests cannot reach."""
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
import jax

try:
    jax.config.update("jax_platforms", "cpu")
except RuntimeError:
    pass

import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import tony_tpu.runtime as rt
from tony_tpu.checkpoint import CheckpointManager

ctx = rt.initialize()
if not ctx.is_distributed:
    print("expected 2+ processes", file=sys.stderr)
    sys.exit(6)

from jax.experimental import multihost_utils

mesh = Mesh(np.array(jax.devices()), ("dp",))
sharding = NamedSharding(mesh, P("dp"))
n = jax.device_count() * 2  # 2 rows per device
local = jax.local_device_count() * 2
lo = ctx.process_id * local
local_data = np.arange(lo, lo + local, dtype=np.float32)
x = jax.make_array_from_process_local_data(sharding, local_data, (n,))
assert not x.is_fully_addressable, "fixture needs a cross-process array"

mgr = CheckpointManager(
    os.environ["CKPT_DIR"],
    process_id=ctx.process_id,
    num_processes=ctx.num_processes,
)
mgr.save(1, {"x": x}, blocking=True)
multihost_utils.sync_global_devices("ckpt-written")

restored = mgr.restore({"x": x})
if restored is None:
    print("restore returned None", file=sys.stderr)
    sys.exit(7)
y = restored["x"]
if y.sharding != x.sharding or y.shape != x.shape:
    print("sharding/shape mismatch after restore", file=sys.stderr)
    sys.exit(8)
for shard in y.addressable_shards:
    want = np.arange(n, dtype=np.float32)[shard.index]
    if not np.array_equal(np.asarray(shard.data), want):
        print(f"shard {shard.index} wrong: {shard.data} != {want}",
              file=sys.stderr)
        sys.exit(9)
print(f"process {ctx.process_id}: sharded checkpoint roundtrip OK")
sys.exit(0)
