"""Asserts the per-slice env contract for multi-slice jobs: the
coordinator stamps TONY_SLICE_INDEX/TONY_NUM_SLICES at launch, and the JAX
runtime adds the megascale/DCN variables at rendezvous. Run with 2 workers
x tpus=8 pinned to v5litepod-8 => 2 slices of 1 host each."""
import os
import sys

import tony_tpu.runtime as rt

ctx = rt.task_context()
plan = rt.slice_topology()
if plan is None or plan["num_slices"] != 2:
    print(f"expected a 2-slice plan, got {plan}", file=sys.stderr)
    sys.exit(2)
if ctx.num_slices != 2:
    print(f"ctx.num_slices = {ctx.num_slices}", file=sys.stderr)
    sys.exit(3)
# 1 host per slice: worker i is slice i, in-slice process 0.
if ctx.slice_index != ctx.task_index or ctx.slice_process_id != 0:
    print(f"slice identity wrong: task {ctx.task_index} -> "
          f"slice {ctx.slice_index}/{ctx.slice_process_id}", file=sys.stderr)
    sys.exit(4)
for var, want in [
    ("MEGASCALE_NUM_SLICES", "2"),
    ("MEGASCALE_SLICE_ID", str(ctx.task_index)),
]:
    if os.environ.get(var) != want:
        print(f"{var} = {os.environ.get(var)!r}, want {want!r}",
              file=sys.stderr)
        sys.exit(5)
if not os.environ.get("MEGASCALE_COORDINATOR_ADDRESS"):
    print("MEGASCALE_COORDINATOR_ADDRESS missing", file=sys.stderr)
    sys.exit(6)
# One flat jax.distributed identity across both slices.
if ctx.num_processes != 2:
    print(f"num_processes = {ctx.num_processes}", file=sys.stderr)
    sys.exit(7)
sys.exit(0)
