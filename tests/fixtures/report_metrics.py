"""Jax-free telemetry fixture: reports a few train-loop metrics through
``observability.report`` (auto-published to TONY_METRICS_FILE, where the
executor piggybacks them on its heartbeat), opens a user-process span
that joins the job trace, and lingers long enough for several heartbeats
to carry the snapshot."""
import os
import sys
import time

from tony_tpu import observability

if not os.environ.get("TONY_METRICS_FILE"):
    print("TONY_METRICS_FILE not exported", file=sys.stderr)
    sys.exit(4)
if not os.environ.get("TONY_TRACE_ID"):
    print("TONY_TRACE_ID not exported", file=sys.stderr)
    sys.exit(5)

# Force every report to publish: the e2e asserts on what rides the very
# next heartbeat, so the default write throttle would only add latency.
registry = observability.default_registry()
registry._publish_min_interval_s = 0.0

with observability.span("fixture_train"):
    for step in range(1, 6):
        registry.report(
            step=step, loss=1.0 / step, step_time_ms=5.0,
            tokens_per_sec=1000.0,
        )
        time.sleep(0.05)

# Linger so heartbeats (interval set tight by the test) carry the final
# snapshot before this task exits.
time.sleep(float(os.environ.get("LINGER_S", "2.0")))
sys.exit(0)
