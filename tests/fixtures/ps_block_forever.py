"""Teardown fixture: the ps task spawns a grandchild and then blocks
forever — the tf.distribute.Server.join() shape whose processes were found
orphaned on the build box (VERDICT r3 weak #6). It records its pids so the
test can assert the WHOLE process group is reaped when the session ends;
workers exit 0 immediately so the session SUCCEEDS while ps still runs."""
import json
import os
import subprocess
import sys
import time

pids_file = os.path.join(os.environ["TONY_LOG_DIR"], "ps-pids.json")
if os.environ["JOB_NAME"] == "ps":
    child = subprocess.Popen(
        [sys.executable, "-c", "import time; time.sleep(3600)"]
    )
    tmp = pids_file + ".tmp"
    with open(tmp, "w") as f:
        json.dump({"script": os.getpid(), "grandchild": child.pid}, f)
    os.rename(tmp, pids_file)
    time.sleep(3600)  # Server.join() analogue: never returns
else:
    # The worker gates session success on the ps having recorded its pids,
    # so the test never races the ps script's startup.
    deadline = time.time() + 60
    while not os.path.exists(pids_file):
        if time.time() > deadline:
            sys.exit(9)
        time.sleep(0.1)
sys.exit(0)
