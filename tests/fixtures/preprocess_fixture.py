"""Dual-role fixture for the preprocess AM mode (doPreprocessingJob,
TonyApplicationMaster.java:640-703): run as the preprocess job it emits a
``Model parameters:`` line; run as a task it asserts that line arrived in
the MODEL_PARAMS env. ``PREPROCESS_SHOULD_FAIL`` makes the preprocess run
exit nonzero (to test that scheduling is gated on preprocess success)."""
import os
import sys

if os.environ.get("PREPROCESSING_JOB") == "true":
    if os.environ.get("PREPROCESS_SHOULD_FAIL"):
        print("preprocess failing on purpose", file=sys.stderr)
        sys.exit(3)
    print("Model parameters: --lr 0.1 --layers 4")
    sys.exit(0)

if os.environ.get("MODEL_PARAMS") != "--lr 0.1 --layers 4":
    print(f"MODEL_PARAMS wrong: {os.environ.get('MODEL_PARAMS')!r}",
          file=sys.stderr)
    sys.exit(4)
sys.exit(0)
