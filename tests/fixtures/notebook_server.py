"""Notebook stand-in: serve one HTTP request on TB_PORT, then exit 0 (the
executor reserves the port and registers http://host:port as the tracking
URL; a real deployment runs jupyter --port=$TB_PORT here)."""

import os
from http.server import BaseHTTPRequestHandler, HTTPServer


class Handler(BaseHTTPRequestHandler):
    def do_GET(self):
        body = b"notebook-alive"
        self.send_response(200)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *args):
        pass


server = HTTPServer(("0.0.0.0", int(os.environ["TB_PORT"])), Handler)
server.timeout = 60
server.handle_request()
