"""Asserts the generic env contract + shell-env propagation (reference:
exit_0_check_env.py). Exits nonzero on any missing/bad variable."""
import os
import sys

for var in ("JOB_NAME", "TASK_INDEX", "TASK_NUM", "SESSION_ID"):
    if var not in os.environ:
        print(f"missing {var}", file=sys.stderr)
        sys.exit(2)

if os.environ.get("USER_SHELL_VAR") != "propagated":
    print("shell-env not propagated", file=sys.stderr)
    sys.exit(3)

sys.exit(0)
