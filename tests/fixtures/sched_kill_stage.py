"""Fixture: a scheduler daemon that SIGKILLs itself (``os._exit``, no
cleanup, no atexit) at a chosen journal/actuation boundary — the
control-plane half of the kill-at-every-transition contract. The
parent test recovers the base dir with a fresh daemon and asserts no
job was lost and none launched twice.

The submitted job is journaled BEFORE ``start()`` so the crash phase
is deterministic: the first tick after start hits the boundary.

    post-journal — the launch landed in the journal, no coordinator
                   exists yet (recovery must classify it dead and
                   requeue, not lose it or double-launch it)
    mid-tick     — lease expiries handled, pop loop not yet run (the
                   job is still QUEUED on disk)
    pre-publish  — transitions journaled, snapshot stale (recovery is
                   pure journal replay past an old watermark)

Usage: sched_kill_stage.py <base_dir> <phase> <job_script>
Prints the submitted job id on stdout, then starts the daemon and
waits to die. Exits 3 if the crash never fires.
"""

import json
import sys
import time
from pathlib import Path

from tony_tpu.conf import keys
from tony_tpu.conf.configuration import TonyConfiguration
from tony_tpu.scheduler.service import SchedulerDaemon


def main() -> int:
    base, phase, job_script = Path(sys.argv[1]), sys.argv[2], sys.argv[3]
    conf = TonyConfiguration()
    conf.set(keys.K_STAGING_LOCATION, str(base / "staging"))
    conf.set(keys.K_HISTORY_LOCATION, str(base / "history"))
    conf.set(keys.K_AM_STOP_GRACE_MS, 0)
    conf.set(keys.K_SCHED_TICK_MS, 50)
    conf.set(keys.K_SCHED_MAX_SLICES, 1)
    conf.set(keys.K_FAULT_PLAN, json.dumps(
        {"faults": [{"action": "crash_scheduler", "at": phase}]}
    ))
    daemon = SchedulerDaemon(base / "sched", conf=conf)

    job = TonyConfiguration()
    job.set(keys.K_STAGING_LOCATION, str(base / "staging"))
    job.set(keys.K_HISTORY_LOCATION, str(base / "history"))
    job.set(keys.K_AM_STOP_GRACE_MS, 0)
    job.set(keys.K_EXECUTES, job_script)
    job.set(keys.K_PYTHON_BINARY, sys.executable)
    job.set(keys.instances_key("worker"), 1)
    job.set(keys.instances_key("ps"), 0)
    # submit() before start(): the daemon grabs the leader seat on the
    # spot and journals the queued job; the loop (and its crash point)
    # has not run yet.
    print(daemon.submit(job), flush=True)

    daemon.start(serve_http=False)
    time.sleep(30)  # the tick thread os._exits the whole process
    return 3


if __name__ == "__main__":
    sys.exit(main())
