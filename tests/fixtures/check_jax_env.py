"""Asserts the JAX runtime env contract: coordinator address + process
identity, with chief:0 as process 0, and a parseable CLUSTER_SPEC."""
import json
import os
import sys

for var in (
    "JAX_COORDINATOR_ADDRESS",
    "TONY_COORDINATOR_ADDRESS",
    "TONY_NUM_PROCESSES",
    "TONY_PROCESS_ID",
    "CLUSTER_SPEC",
):
    if var not in os.environ:
        print(f"missing {var}", file=sys.stderr)
        sys.exit(2)

spec = json.loads(os.environ["CLUSTER_SPEC"])
n = sum(len(v) for v in spec.values())
pid = int(os.environ["TONY_PROCESS_ID"])
if int(os.environ["TONY_NUM_PROCESSES"]) != n or not 0 <= pid < n:
    print("inconsistent process identity", file=sys.stderr)
    sys.exit(3)

# chief (worker:0 by default) must be process 0 and own the coordinator port
if os.environ["JOB_NAME"] == "worker" and os.environ["TASK_INDEX"] == "0":
    if pid != 0:
        print(f"chief has process_id {pid}, want 0", file=sys.stderr)
        sys.exit(4)
    if os.environ["JAX_COORDINATOR_ADDRESS"] not in spec["worker"][0]:
        print("coordinator address is not chief's", file=sys.stderr)
        sys.exit(5)

sys.exit(0)
