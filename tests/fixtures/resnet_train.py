"""BASELINE config 5 fixture: gang-scheduled ResNet training with
fault-restart. Each worker trains the in-framework ResNet (tiny depth-18
shape for CI) with checkpointing; worker 0 crashes mid-run on the first
session, the retried session resumes from the latest checkpoint and
finishes."""
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
import jax

try:
    jax.config.update("jax_platforms", "cpu")
except RuntimeError:
    pass

import jax.numpy as jnp
import numpy as np

import tony_tpu.runtime as rt
from tony_tpu.checkpoint import CheckpointManager
from tony_tpu.models import (
    ResNetConfig,
    make_image_classifier_step,
    resnet_apply,
    resnet_init,
)
from tony_tpu.parallel.mesh import MeshSpec, build_mesh

TOTAL_STEPS = 6
CRASH_AT = 3

ctx = rt.initialize()
session = os.environ.get("SESSION_ID", "1")
cfg = ResNetConfig(depth=18, width=8, n_classes=10, dtype="float32")
mesh = build_mesh(MeshSpec.auto(jax.local_device_count()),
                  devices=jax.local_devices())
init_fn, step_fn = make_image_classifier_step(
    lambda key: resnet_init(key, cfg),
    lambda params, images: resnet_apply(params, images, cfg),
    mesh,
)

rng = np.random.default_rng(ctx.process_id)
images = jnp.asarray(rng.normal(size=(8, 32, 32, 3)), jnp.float32)
labels = jnp.asarray(rng.integers(0, 10, (8,)), jnp.int32)

mgr = CheckpointManager(
    os.path.join(os.environ["CKPT_DIR"], f"proc-{ctx.process_id}")
)
with jax.sharding.set_mesh(mesh):
    state = init_fn(jax.random.key(0))
    restored = mgr.restore(state)
    start = 0
    if restored is not None:
        state, start = restored, int(restored.step)
    print(f"[{ctx.process_id}] session {session}: start step {start}",
          flush=True)
    if session != "1" and start == 0:
        print("retried session did not resume", file=sys.stderr)
        sys.exit(7)
    if start >= TOTAL_STEPS:
        # This worker had already finished before the gang restart (only
        # the chief crashes; a fast non-chief can complete session 1).
        print(f"[{ctx.process_id}] already complete at step {start}",
              flush=True)
        sys.exit(0)
    for step in range(start, TOTAL_STEPS):
        state, metrics = step_fn(state, images, labels)
        mgr.save(int(state.step), state, blocking=True)
        if (
            step + 1 == CRASH_AT and session == "1"
            and ctx.process_id == 0
        ):
            print("simulated worker crash", file=sys.stderr)
            sys.exit(1)
    loss = float(metrics["loss"])
print(f"[{ctx.process_id}] final loss {loss:.4f}", flush=True)
sys.exit(0 if np.isfinite(loss) else 8)
