"""Trivial failure fixture (reference: tony-core/src/test/resources/exit_1.py)."""
import sys

sys.exit(1)
