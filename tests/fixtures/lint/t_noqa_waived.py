"""Waiver fixture: both rule-id spellings suppress TONY-T002."""
import threading
import time


class Publisher:
    def __init__(self):
        self._lock = threading.Lock()

    def short_form(self):
        with self._lock:
            time.sleep(1.0)  # tony: noqa[T002] — deliberate: fixture

    def long_form(self):
        with self._lock:
            time.sleep(1.0)  # tony: noqa[TONY-T002] — deliberate: fixture
