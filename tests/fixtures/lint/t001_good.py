"""TONY-T001 fixture: one global order, RLock re-entry."""
import threading


class AB:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()
        self._r = threading.RLock()

    def one(self):
        with self._a:
            with self._b:
                pass

    def two(self):
        with self._a:
            with self._b:
                pass

    def reentrant(self):
        with self._r:
            self.helper()

    def helper(self):
        with self._r:
            pass
