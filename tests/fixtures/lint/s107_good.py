"""Clean twin of s107: deterministic order via sorted()."""
import glob

import jax

files = sorted(glob.glob("data/*.jsonl"))
shard = files[0]
