"""TONY-T005 fixture: daemon flag present (kwarg or attr)."""
import threading


def start(fn):
    t = threading.Thread(target=fn, daemon=True)
    t.start()
    return t


def start_attr(fn):
    t = threading.Thread(target=fn)
    t.daemon = True
    t.start()
    return t
