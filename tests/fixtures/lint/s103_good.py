"""Clean twin of s103: every spec axis exists on the mesh."""
import numpy as np
import jax
from jax.sharding import Mesh, PartitionSpec as P

mesh = Mesh(np.array(jax.devices()).reshape(2, -1), ("data", "model"))
spec = P("data", "model")
