"""TONY-T003 fixture: every mutation under one lock."""
import threading


class Worker:
    def __init__(self, pool):
        self.count = 0
        self._lock = threading.Lock()
        threading.Thread(target=self._run, daemon=True).start()
        pool.submit(self._drain)

    def _run(self):
        with self._lock:
            self.count += 1

    def _drain(self):
        with self._lock:
            self.count = 0
