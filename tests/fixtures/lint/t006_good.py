"""TONY-T006 fixture: bounded join; str/path joins untouched."""
import os.path


def wait_for(t, parts):
    t.join(timeout=5)
    return os.path.join(*parts) + ",".join(parts)
