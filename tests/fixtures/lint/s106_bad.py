"""TONY-S106: multi-worker JAX job with no distributed init (expected
line 4 — the jax import anchors the whole-file finding)."""

import jax
import jax.numpy as jnp


def main():
    x = jnp.ones((8,))
    return jax.device_count() * x.sum()
