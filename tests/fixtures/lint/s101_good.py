"""Clean twin of s101: constant seed."""
import jax

key = jax.random.PRNGKey(42)
