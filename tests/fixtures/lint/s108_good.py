"""Clean twin of s108: no interactive calls."""
import jax


def main():
    return 0
