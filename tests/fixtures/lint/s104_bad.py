"""TONY-S104: blocking host sync inside a jitted step (expected line 8)."""
import jax


@jax.jit
def step(x):
    y = x * 2
    jax.device_get(y)
    return y
