"""Waiver fixture: both rule-id spellings suppress TONY-X findings."""
import jax

_step = jax.jit(lambda s: s + 1)


def per_call(x):
    return jax.jit(lambda v: v + 1)(x)  # tony: noqa[X001] — deliberate: fixture


def train(state, steps):
    for _ in range(steps):
        state = _step(state)
        loss = float(state)  # tony: noqa[TONY-X002] — deliberate: fixture
        del loss
    return state
