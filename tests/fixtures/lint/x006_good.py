"""TONY-X006 clean: split per consumer, split per iteration."""
import jax


def fresh_draws():
    key = jax.random.key(0)
    ka, kb = jax.random.split(key)
    a = jax.random.normal(ka, (4,))
    b = jax.random.uniform(kb, (4,))
    return a, b


def loop_draw(n):
    key = jax.random.key(0)
    out = []
    for _ in range(n):
        key, sub = jax.random.split(key)
        out.append(jax.random.normal(sub, (4,)))
    return out
