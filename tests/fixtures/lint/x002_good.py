"""TONY-X002 clean: the step loop stays on-device; the only readback
happens once, after the loop."""
import jax

_step = jax.jit(lambda s: s + 1)


def train(state, steps):
    for _ in range(steps):
        state = _step(state)
    return float(state)
