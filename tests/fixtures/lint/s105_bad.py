"""TONY-S105: reads TF_CONFIG while importing jax (expected line 7)."""
import json
import os

import jax

cluster = json.loads(os.environ.get("TF_CONFIG", "{}"))
