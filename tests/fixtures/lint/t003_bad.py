"""TONY-T003 fixture: two thread entrypoints, no common lock."""
import threading


class Worker:
    def __init__(self, pool):
        self.count = 0
        threading.Thread(target=self._run, daemon=True).start()
        pool.submit(self._drain)

    def _run(self):
        self.count += 1

    def _drain(self):
        self.count = 0
