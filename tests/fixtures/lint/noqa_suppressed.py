"""Inline suppression fixtures: line 8 suppresses its rule by id, line 9
suppresses everything on the line, line 10 suppresses the WRONG id and
must still be flagged."""
import time

import jax

k1 = jax.random.PRNGKey(int(time.time()))  # tony: noqa[TONY-S101]
k2 = jax.random.PRNGKey(int(time.time()))  # tony: noqa
k3 = jax.random.PRNGKey(int(time.time()))  # tony: noqa[TONY-S102]
