"""TONY-T002 fixture: snapshot under the lock, I/O outside."""
import json
import pathlib
import threading
import time


class Publisher:
    def __init__(self):
        self._lock = threading.Lock()
        self._state = {}

    def publish(self, path):
        with self._lock:
            snapshot = dict(self._state)
        pathlib.Path(path).write_text(json.dumps(snapshot))

    def backoff(self):
        time.sleep(1.0)
