"""TONY-X005 fixture: in_shardings declared without out_shardings —
outputs fall back to GSPMD's guess."""
import jax


def build(spec):
    return jax.jit(lambda x: x * 2, in_shardings=(spec,))
