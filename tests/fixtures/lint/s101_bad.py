"""TONY-S101: PRNG key from a host-divergent source (expected line 7)."""
import time

import jax

seed = 42
key = jax.random.PRNGKey(int(time.time()))
