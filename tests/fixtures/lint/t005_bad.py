"""TONY-T005 fixture: non-daemon background thread."""
import threading


def start(fn):
    t = threading.Thread(target=fn)
    t.start()
    return t
