"""Clean twin of s102: side effect outside the jitted function."""
import jax


@jax.jit
def step(x):
    return x * 2


def run(x):
    y = step(x)
    print("step value", y)
    return y
