"""TONY-T006 fixture: join without a timeout."""
import threading


def wait_for(t: threading.Thread):
    t.join()
