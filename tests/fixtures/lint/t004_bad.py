"""TONY-T004 fixture: guarded attr, bare check-then-act."""
import threading


class Cache:
    def __init__(self):
        self._lock = threading.Lock()
        self._value = None

    def set(self, value):
        with self._lock:
            self._value = value

    def ensure(self):
        if self._value is None:
            self._value = object()
        return self._value
