"""TONY-X003 fixture: retrace hazards — loop index and len() into
non-static positions, weak float literal riding in a container."""
import jax

_f = jax.jit(lambda x, n: x * n)


def loop_index(xs):
    out = []
    for i in range(8):
        out.append(_f(xs, i))
    return out


def length(xs):
    return _f(xs, len(xs))


def weak_float(xs):
    return _f(xs, {"scale": 0.5})
