"""TONY-X002 fixture: host round-trips inside an instrumented step
loop — direct cast, implicit bool branch, and a helper that syncs its
argument (call-graph propagation)."""
import jax

_step = jax.jit(lambda s: s + 1)


def train(state, steps):
    for _ in range(steps):
        state = _step(state)
        loss = float(state)
        if state > 0:
            print(loss)
    return state


def log_metrics(metrics):
    return float(metrics)


def train_with_helper(state, steps):
    for _ in range(steps):
        state = _step(state)
        log_metrics(state)
    return state
