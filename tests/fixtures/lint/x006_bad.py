"""TONY-X006 fixture: PRNG key consumed twice, and consumed in a loop
without a per-iteration split."""
import jax


def double_draw():
    key = jax.random.key(0)
    a = jax.random.normal(key, (4,))
    b = jax.random.uniform(key, (4,))
    return a, b


def loop_draw(n):
    key = jax.random.key(0)
    out = []
    for _ in range(n):
        out.append(jax.random.normal(key, (4,)))
    return out
