"""TONY-S107: unsorted directory listing shards data (expected line 6)."""
import glob

import jax

files = glob.glob("data/*.jsonl")
shard = files[0]
