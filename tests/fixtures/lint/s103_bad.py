"""TONY-S103: PartitionSpec axis absent from the module's Mesh
(expected line 9)."""
import numpy as np
import jax
from jax.sharding import Mesh, PartitionSpec as P

mesh = Mesh(np.array(jax.devices()).reshape(2, -1), ("data", "model"))
good_spec = P("data", "model")
bad_spec = P("data", "modle")
