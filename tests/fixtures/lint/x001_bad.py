"""TONY-X001 fixture: jit constructed per-iteration / per-call."""
import jax


def per_iteration(xs):
    out = []
    for x in xs:
        f = jax.jit(lambda v: v * 2)
        out.append(f(x))
    return out


def immediate(x):
    return jax.jit(lambda v: v + 1)(x)


def once_and_discard(x):
    g = jax.jit(lambda v: v - 1)
    return g(x)
