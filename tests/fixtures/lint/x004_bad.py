"""TONY-X004 fixture: a donated buffer is read after the call that may
have aliased its pages."""
import jax

_update = jax.jit(lambda s: s + 1, donate_argnums=(0,))


def step(state):
    new = _update(state)
    total = state.sum()
    return new, total
