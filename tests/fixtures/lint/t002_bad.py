"""TONY-T002 fixture: blocking work inside critical sections."""
import json
import pathlib
import threading
import time


class Publisher:
    def __init__(self):
        self._lock = threading.Lock()
        self._state = {}

    def publish(self, path):
        with self._lock:
            pathlib.Path(path).write_text(json.dumps(self._state))

    def backoff(self):
        with self._lock:
            time.sleep(1.0)

    def indirect(self):
        with self._lock:
            self._slow()

    def _slow(self):
        time.sleep(0.5)
