"""Clean twin of s106: initializes the distributed runtime."""
import jax

import tony_tpu.runtime as rt


def main():
    ctx = rt.initialize()
    return jax.device_count()
