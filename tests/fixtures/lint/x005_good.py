"""TONY-X005 clean: both sides of the boundary pinned from the plan."""
import jax


def build(spec):
    return jax.jit(
        lambda x: x * 2, in_shardings=(spec,), out_shardings=(spec,)
    )
