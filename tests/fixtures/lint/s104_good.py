"""Clean twin of s104: synchronization happens outside the step."""
import jax


@jax.jit
def step(x):
    return x * 2


def run(x):
    y = step(x)
    return jax.device_get(y)
