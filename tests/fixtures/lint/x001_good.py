"""TONY-X001 clean: construct once, reuse across the loop, and a
closure capture (reused across calls of the returned step fn)."""
import jax

_double = jax.jit(lambda v: v * 2)


def steps(xs):
    out = []
    for x in xs:
        out.append(_double(x))
    return out


def make_step():
    jitted = jax.jit(lambda v: v + 1)

    def step(x):
        return jitted(x)

    return step
