"""Clean twin of s105: distributed identity via the runtime."""
import jax

import tony_tpu.runtime as rt

ctx = rt.initialize()
