"""TONY-S102: print inside a jitted function (expected line 8)."""
import jax


@jax.jit
def step(x):
    y = x * 2
    print("step value", y)
    return y
