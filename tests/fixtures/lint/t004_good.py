"""TONY-T004 fixture: the test-and-set holds the lock."""
import threading


class Cache:
    def __init__(self):
        self._lock = threading.Lock()
        self._value = None

    def set(self, value):
        with self._lock:
            self._value = value

    def ensure(self):
        with self._lock:
            if self._value is None:
                self._value = object()
            return self._value
