"""TONY-X004 clean: the donated name is rebound to the call's result,
so nothing reads the stale buffer."""
import jax

_update = jax.jit(lambda s: s + 1, donate_argnums=(0,))


def step(state):
    state = _update(state)
    total = state.sum()
    return state, total
