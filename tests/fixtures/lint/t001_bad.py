"""TONY-T001 fixture: lock-order cycle + self-deadlock."""
import threading


class AB:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()

    def forward(self):
        with self._a:
            with self._b:
                pass

    def reverse(self):
        with self._b:
            with self._a:
                pass

    def outer(self):
        with self._a:
            self.helper()

    def helper(self):
        with self._a:
            pass
