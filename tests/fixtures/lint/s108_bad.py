"""TONY-S108: interactive blocker in a submitted script (expected line 6)."""
import jax


def main():
    answer = input("continue? ")
    return answer
