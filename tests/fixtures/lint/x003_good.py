"""TONY-X003 clean: the varying scalar position is declared static, so
each distinct value is a legitimate (cached) specialization."""
import jax

_f = jax.jit(lambda x, n: x * n, static_argnums=(1,))


def loop_index(xs):
    out = []
    for i in range(8):
        out.append(_f(xs, i))
    return out


def fixed_scalar(xs):
    return _f(xs, 4)
