"""Continuous-batching serving engine tests.

The load-bearing pin is GREEDY PARITY: any request pushed through the
slot engine — whatever slot it lands in, however its prompt was
chunked, whoever shared its decode iterations — must produce
token-for-token the same output as a single-request ``generate`` call.
That one property proves admission, chunked prefill, per-slot
positions/masks, the wpos parking contract, EOS retirement, and slot
reuse all at once, so the e2e tests below assert it under staggered
mixed-length concurrent load rather than in isolation.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.request
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tony_tpu.models import TransformerConfig, generate, init_params
from tony_tpu.observability.metrics import MetricsRegistry
from tony_tpu.serving import ServingEngine, ServingQueueFull
from tony_tpu.serving.scheduler import _chunk_plan


def _tiny_setup(n_experts: int = 0):
    cfg = TransformerConfig(
        vocab_size=64, d_model=32, n_layers=2, n_heads=2, head_dim=16,
        d_ff=64, max_seq=96, dtype="float32", remat=False,
        n_experts=n_experts, expert_top_k=2 if n_experts else 0,
    )
    params = init_params(jax.random.key(0), cfg)
    return cfg, params


class TestChunkPlan:
    def test_short_prompt_single_padded_chunk(self):
        assert _chunk_plan(3, 8) == [(0, 3)]

    def test_exact_multiple(self):
        assert _chunk_plan(16, 8) == [(0, 8), (8, 8)]

    def test_remainder_overlapped_final_chunk(self):
        # 20 = 2 full chunks + an overlapped final chunk at 12: every
        # chunk fully valid, overlap rewrites identical K/V.
        assert _chunk_plan(20, 8) == [(0, 8), (8, 8), (12, 8)]


class TestSubmitValidation:
    def test_rejects_bad_requests(self):
        cfg, params = _tiny_setup()
        eng = ServingEngine(params, cfg, slots=2, max_len=32)
        with pytest.raises(ValueError, match="empty prompt"):
            eng.submit([], 4)
        with pytest.raises(ValueError, match="max_new_tokens"):
            eng.submit([1, 2], 0)
        with pytest.raises(ValueError, match="KV capacity"):
            eng.submit(list(range(30)), 8)  # 30 + 8 > 32
        with pytest.raises(ValueError, match="temperature"):
            eng.submit([1, 2], 4, temperature=-1.0)

    def test_queue_backpressure_sheds(self):
        cfg, params = _tiny_setup()
        eng = ServingEngine(params, cfg, slots=1, max_queue=2)
        for _ in range(2):
            eng.submit([1, 2], 2)
        with pytest.raises(ServingQueueFull):
            eng.submit([1, 2], 2)

    def test_rejects_oversized_max_len(self):
        cfg, params = _tiny_setup()
        with pytest.raises(ValueError, match="max_seq"):
            ServingEngine(params, cfg, max_len=cfg.max_seq + 1)


class TestEngineParity:
    """The acceptance e2e: >= 8 staggered mixed-length requests through
    admission -> chunked prefill -> EOS retirement -> slot reuse, each
    matching its single-request greedy ``generate`` reference."""

    @pytest.mark.parametrize("window,prefill_batch", [(1, 1), (4, 3)])
    def test_staggered_mixed_length_requests_match_references(
        self, window, prefill_batch
    ):
        cfg, params = _tiny_setup()
        rng = np.random.default_rng(7)
        lens = (3, 7, 12, 20, 5, 11, 17, 9, 6, 14)
        budgets = (6, 8, 9, 4, 12, 3, 8, 6, 10, 5)
        prompts = [rng.integers(0, 64, n).astype(np.int32) for n in lens]
        # Half the requests get a real EOS mid-stream, derived from
        # their plain greedy continuation, so retirement-before-budget
        # is actually exercised; the rest run to their token budget.
        eos_ids: list[int | None] = []
        for i, (p, n) in enumerate(zip(prompts, budgets)):
            if i % 2 == 0 and n >= 4:
                plain = np.asarray(
                    generate(params, jnp.asarray(p)[None], cfg, n)
                )[0]
                eos_ids.append(int(plain[n // 2]))
            else:
                eos_ids.append(None)

        registry = MetricsRegistry()
        eng = ServingEngine(
            params, cfg, slots=3, prefill_chunk=5, decode_window=window,
            prefill_batch=prefill_batch, registry=registry,
        )
        assert eng.slots < len(prompts)  # slot reuse is forced
        with eng:  # engine loop thread runs; submissions are staggered
            reqs = []
            for i, (p, n, e) in enumerate(zip(prompts, budgets, eos_ids)):
                reqs.append(eng.submit(p, n, eos_id=e))
                if i % 3 == 2:
                    time.sleep(0.05)  # arrivals overlap in-flight decode
            results = [r.result(timeout=120) for r in reqs]

        for p, n, e, res in zip(prompts, budgets, eos_ids, results):
            if e is None:
                want = np.asarray(
                    generate(params, jnp.asarray(p)[None], cfg, n)
                )[0]
                assert res["length"] == n
            else:
                ref = generate(params, jnp.asarray(p)[None], cfg, n,
                               eos_id=e)
                want_len = int(np.asarray(ref.lengths)[0])
                want = np.asarray(ref.tokens)[0][:want_len]
                assert res["length"] == want_len
            np.testing.assert_array_equal(np.asarray(res["tokens"]), want)

        # Every slot was reused and everything retired.
        stats = eng.stats()
        assert stats["retired"] == len(prompts)
        assert stats["active_slots"] == 0 and stats["queue_depth"] == 0

        # Serving telemetry flowed through the registry.
        snap = registry.snapshot()
        assert snap["counters"]["tony_serving_requests_total"] == len(
            prompts
        )
        assert snap["counters"]["tony_serving_retired_total"] == len(
            prompts
        )
        assert snap["counters"]["tony_serving_generated_tokens_total"] > 0
        assert snap["histograms"]["tony_serving_ttft_ms"]["count"] == len(
            prompts
        )
        assert snap["histograms"]["tony_serving_inter_token_ms"][
            "count"
        ] > 0
        assert "tony_serving_queue_depth" in snap["gauges"]
        assert "tony_serving_active_slots" in snap["gauges"]
        assert "tony_serving_tokens_per_sec" in snap["gauges"]

    def test_moe_trunk_parity(self):
        cfg, params = _tiny_setup(n_experts=2)
        rng = np.random.default_rng(3)
        prompts = [rng.integers(0, 64, n).astype(np.int32)
                   for n in (4, 9, 13)]
        eng = ServingEngine(params, cfg, slots=2, prefill_chunk=4)
        reqs = [eng.submit(p, 5) for p in prompts]
        for _ in range(500):
            if all(r.done() for r in reqs):
                break
            eng.step()
        for p, r in zip(prompts, reqs):
            want = np.asarray(
                generate(params, jnp.asarray(p)[None], cfg, 5)
            )[0]
            np.testing.assert_array_equal(
                np.asarray(r.result(1)["tokens"]), want
            )

    def test_temperature_request_runs_and_differs_from_greedy(self):
        cfg, params = _tiny_setup()
        prompt = np.arange(8, dtype=np.int32)
        eng = ServingEngine(params, cfg, slots=2, seed=5)
        hot = eng.submit(prompt, 16, temperature=1.5)
        cold = eng.submit(prompt, 16)
        for _ in range(500):
            if hot.done() and cold.done():
                break
            eng.step()
        greedy = np.asarray(
            generate(params, jnp.asarray(prompt)[None], cfg, 16)
        )[0]
        np.testing.assert_array_equal(
            np.asarray(cold.result(1)["tokens"]), greedy
        )
        # Sampling at temperature 1.5 over 16 draws flipping no token
        # vs greedy would be astronomically unlikely.
        assert not np.array_equal(
            np.asarray(hot.result(1)["tokens"]), greedy
        )

    def test_compile_instrumentation_counts_engine_executables(self):
        from tony_tpu.observability.metrics import default_registry

        cfg, params = _tiny_setup()
        reg = default_registry()

        def totals():
            snap = reg.snapshot()["counters"]
            return (snap.get("tony_compile_cache_hits_total", 0)
                    + snap.get("tony_compile_cache_misses_total", 0))

        eng = ServingEngine(params, cfg, slots=2, prefill_chunk=4)
        before = totals()
        r = eng.submit(np.arange(6, dtype=np.int32), 3)
        for _ in range(200):
            if r.done():
                break
            eng.step()
        r.result(1)
        # Exactly two instrumented first-compiles: the prefill batch and
        # the decode window.
        assert totals() == before + 2


class TestServingHTTP:
    def test_generate_healthz_shutdown(self):
        from tony_tpu.serving.http import ServingServer

        cfg, params = _tiny_setup()
        eng = ServingEngine(params, cfg, slots=2).start()
        server = ServingServer(eng, port=0)
        port = server.start()
        try:
            prompt = list(range(1, 7))
            body = json.dumps({
                "prompt": prompt, "max_new_tokens": 5,
            }).encode()
            with urllib.request.urlopen(urllib.request.Request(
                f"http://127.0.0.1:{port}/generate", data=body,
                headers={"Content-Type": "application/json"},
            ), timeout=120) as resp:
                out = json.loads(resp.read())
            want = np.asarray(generate(
                params, jnp.asarray(prompt, jnp.int32)[None], cfg, 5
            ))[0]
            np.testing.assert_array_equal(np.asarray(out["tokens"]), want)
            assert out["length"] == 5 and out["wall_ms"] >= 0

            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=10
            ) as resp:
                health = json.loads(resp.read())
            assert health["slots"] == 2 and health["retired"] == 1

            # Malformed body -> 400, not a wedged connection.
            bad = urllib.request.Request(
                f"http://127.0.0.1:{port}/generate", data=b"{}",
            )
            with pytest.raises(urllib.error.HTTPError) as err:
                urllib.request.urlopen(bad, timeout=10)
            assert err.value.code == 400

            with urllib.request.urlopen(urllib.request.Request(
                f"http://127.0.0.1:{port}/shutdown", data=b"",
            ), timeout=10) as resp:
                assert json.loads(resp.read())["ok"] is True
            assert server.wait_shutdown(timeout=10)
        finally:
            server.stop()
            eng.close()

    def test_close_fails_pending_requests(self):
        cfg, params = _tiny_setup()
        eng = ServingEngine(params, cfg, slots=1)
        req = eng.submit([1, 2, 3], 4)  # never stepped
        eng.close()
        with pytest.raises(RuntimeError, match="shut down"):
            req.result(timeout=1)


class TestProxyCounters:
    """Satellite: tony.proxy.connect-timeout + byte counters."""

    def test_tunnel_counts_bytes_by_direction(self):
        import socket
        import socketserver

        class Echo(socketserver.ThreadingTCPServer):
            allow_reuse_address = True

        class Handler(socketserver.StreamRequestHandler):
            def handle(self):
                data = self.rfile.read(5)
                self.wfile.write(data.upper())

        upstream = Echo(("127.0.0.1", 0), Handler)
        threading.Thread(target=upstream.serve_forever,
                         daemon=True).start()
        registry = MetricsRegistry()
        from tony_tpu.proxy import ProxyServer

        proxy = ProxyServer(
            "127.0.0.1", upstream.server_address[1], 0,
            connect_timeout_s=2.0, registry=registry,
        )
        port = proxy.start()
        try:
            with socket.create_connection(("127.0.0.1", port),
                                          timeout=10) as sock:
                sock.sendall(b"hello")
                assert sock.recv(5) == b"HELLO"
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                counters = registry.snapshot()["counters"]
                up = counters.get(
                    'tony_proxy_bytes_total{direction="up"}', 0)
                down = counters.get(
                    'tony_proxy_bytes_total{direction="down"}', 0)
                if up >= 5 and down >= 5:
                    break
                time.sleep(0.05)
            assert up == 5 and down == 5
        finally:
            proxy.stop()
            upstream.shutdown()
            upstream.server_close()

    def test_connect_timeout_is_configurable(self):
        from tony_tpu.proxy import ProxyServer

        proxy = ProxyServer("127.0.0.1", 1, 0, connect_deadline_s=0.0,
                            connect_timeout_s=0.05,
                            registry=MetricsRegistry())
        t0 = time.monotonic()
        assert proxy._connect_upstream() is None
        assert time.monotonic() - t0 < 5.0  # old hardcoded floor

    def test_conf_key_registered_and_validated(self):
        from tony_tpu.analysis.config_check import check_config
        from tony_tpu.conf import keys
        from tony_tpu.conf.configuration import TonyConfiguration

        assert keys.DEFAULTS[keys.K_PROXY_CONNECT_TIMEOUT_MS] == 5000
        conf = TonyConfiguration()
        conf.set(keys.K_PROXY_CONNECT_TIMEOUT_MS, 0)
        assert any(
            f.rule_id == "TONY-C002" and "connect-timeout" in f.message
            for f in check_config(conf)
        )

    def test_serving_keys_validated(self):
        from tony_tpu.analysis.config_check import check_config
        from tony_tpu.conf import keys
        from tony_tpu.conf.configuration import TonyConfiguration

        for key in (keys.K_SERVING_SLOTS, keys.K_SERVING_PREFILL_CHUNK,
                    keys.K_SERVING_DECODE_WINDOW,
                    keys.K_SERVING_MAX_QUEUE):
            conf = TonyConfiguration()
            conf.set(key, 0)
            assert any(f.rule_id == "TONY-C002" for f in check_config(conf)), key
        conf = TonyConfiguration()
        conf.set(keys.K_SERVING_PORT, 0)  # 0 = ephemeral is legal
        assert not [f for f in check_config(conf) if f.rule_id == "TONY-C002"]


class TestBenchServingGate:
    """The bench_serving sub-metrics flatten into gated names and the
    seeded cpu baseline catches a serving-throughput collapse."""

    _LINE = {
        "metric": "x",
        "extras": {"device": "cpu", "serving": {
            "wall_tokens_per_sec": 1341, "sustained_tokens_per_sec": 1577,
            "generate_wall_tokens_per_sec": 4530,
            "generate_wall_speedup": 0.35,
            "single_shot_wall_tokens_per_sec": 942,
            "single_shot_speedup": 1.67,
            "inter_token_p50_ms": 4.5, "inter_token_p95_ms": 13.6,
            "ttft_p50_ms": 440.0, "ttft_p95_ms": 1791.0,
            "generated_tokens": 3000, "slots": 16, "n_requests": 128,
            "prefill_chunk": 32, "decode_window": 8, "out_mean": 32.0,
            "d_model": 128,
        }},
    }

    def _bench(self):
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "bench", Path(__file__).resolve().parent.parent / "bench.py"
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    def test_seeded_cpu_gate_passes_and_catches_collapse(self):
        bench = self._bench()
        current = bench.collect_submetrics(self._LINE)
        assert current["serving.single_shot_speedup"] == 1.67
        assert "serving.slots" not in current  # shape params ungated
        # The cpu table also gates other workload families (scheduler);
        # this synthetic line is serving-only, so gate that subset — a
        # REAL bench line carries every family and gates them all.
        baseline = {
            k: v for k, v in bench.load_baselines().get("cpu", {}).items()
            if k.startswith("serving.")
        }
        assert baseline, "cpu serving baselines must be seeded"
        assert not bench.check_regressions(current, baseline)
        collapsed = dict(current)
        collapsed["serving.single_shot_speedup"] = 0.5
        collapsed["serving.sustained_tokens_per_sec"] = 300.0
        problems = bench.check_regressions(collapsed, baseline)
        assert any("single_shot_speedup" in p for p in problems)
        assert any("sustained_tokens_per_sec" in p for p in problems)


@pytest.mark.slow
class TestMiniClusterServing:
    """The full wire: a `serving` task type submitted to the mini
    cluster runs examples/lm_serve.py (checkpointless smoke weights),
    the test tunnels to it through ProxyServer exactly as a gateway
    would, drives generate requests end to end, and the job SUCCEEDs
    after /shutdown — with the tunnel's byte counters ticking."""

    def test_serving_task_through_proxy(self, tmp_path):
        import sys

        from tony_tpu.conf import keys
        from tony_tpu.coordinator.session import SessionStatus
        from tony_tpu.mini import MiniTonyCluster
        from tony_tpu.proxy import ProxyServer

        repo = Path(__file__).resolve().parent.parent
        addr_file = tmp_path / "serving.addr"
        with MiniTonyCluster(tmp_path / "cluster") as cluster:
            conf = cluster.base_conf()
            conf.set(keys.K_FRAMEWORK, "jax")
            conf.set(keys.K_EXECUTES,
                     str(repo / "examples" / "lm_serve.py"))
            conf.set(keys.K_PYTHON_BINARY, sys.executable)
            conf.set(keys.instances_key("worker"), 0)
            conf.set(keys.instances_key("ps"), 0)
            conf.set(keys.instances_key("serving"), 1)
            conf.set(keys.K_CHIEF_NAME, "serving")
            conf.set(keys.K_SERVING_SLOTS, 2)
            conf.set(keys.K_SERVING_PREFILL_CHUNK, 8)
            conf.set(keys.K_SERVING_DECODE_WINDOW, 2)
            conf.set(keys.K_TASK_PARAMS,
                     f"--max-seq 96 --seed 0 --addr-file {addr_file}")
            job = cluster.start_job(conf)
            proxy = None
            try:
                deadline = time.monotonic() + 180
                while not addr_file.exists():
                    assert job.running(), "serving job died before binding"
                    assert time.monotonic() < deadline, "no addr published"
                    time.sleep(0.25)
                host, _, port = addr_file.read_text().strip().rpartition(
                    ":")
                registry = MetricsRegistry()
                proxy = ProxyServer(host, int(port), 0,
                                    connect_timeout_s=conf.get_int(
                                        keys.K_PROXY_CONNECT_TIMEOUT_MS,
                                        5000) / 1000.0,
                                    registry=registry)
                local = proxy.start()
                base = f"http://127.0.0.1:{local}"

                prompt = [1, 5, 9, 2]
                body = json.dumps(
                    {"prompt": prompt, "max_new_tokens": 8}).encode()
                with urllib.request.urlopen(urllib.request.Request(
                    f"{base}/generate", data=body,
                ), timeout=180) as resp:
                    out = json.loads(resp.read())
                assert out["length"] == 8

                # Reference: the fixture serves fresh weights from
                # seed 0 with lm_train's default model flags — rebuild
                # the identical config/params here and pin parity
                # through the whole proxy -> engine wire.
                import argparse

                sys.path.insert(0, str(repo / "examples"))
                try:
                    import lm_train
                finally:
                    sys.path.pop(0)
                p = argparse.ArgumentParser()
                lm_train.add_model_args(p)
                cfg = lm_train.model_config_from_args(
                    p.parse_args([]), max_seq=96
                )
                params = init_params(jax.random.key(0), cfg)
                want = np.asarray(generate(
                    params, jnp.asarray(prompt, jnp.int32)[None], cfg, 8
                ))[0]
                np.testing.assert_array_equal(
                    np.asarray(out["tokens"]), want
                )

                with urllib.request.urlopen(f"{base}/healthz",
                                            timeout=30) as resp:
                    health = json.loads(resp.read())
                assert health["slots"] == 2 and health["retired"] >= 1

                counters = registry.snapshot()["counters"]
                assert counters['tony_proxy_bytes_total{direction="up"}'] > 0
                assert counters[
                    'tony_proxy_bytes_total{direction="down"}'] > 0

                with urllib.request.urlopen(urllib.request.Request(
                    f"{base}/shutdown", data=b"",
                ), timeout=30):
                    pass
                status = job.wait(timeout_s=120)
                assert status is SessionStatus.SUCCEEDED
            finally:
                if proxy is not None:
                    proxy.stop()


class TestDrain:
    def test_drain_completes_inflight_then_blocks_admission(self):
        cfg, params = _tiny_setup()
        eng = ServingEngine(params, cfg, slots=2)
        with eng:
            reqs = [eng.submit(np.arange(1, 6, dtype=np.int32), 6)
                    for _ in range(4)]
            assert eng.drain(timeout=60.0)
            for r in reqs:
                assert r.done() and r.error is None
                assert r.result(1)["length"] == 6
            with pytest.raises(RuntimeError, match="draining"):
                eng.submit([1, 2], 2)
