"""Continuous-batching serving engine tests.

The load-bearing pin is GREEDY PARITY: any request pushed through the
slot engine — whatever slot it lands in, however its prompt was
chunked, whoever shared its decode iterations — must produce
token-for-token the same output as a single-request ``generate`` call.
That one property proves admission, chunked prefill, per-slot
positions/masks, the wpos parking contract, EOS retirement, and slot
reuse all at once, so the e2e tests below assert it under staggered
mixed-length concurrent load rather than in isolation.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.request
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tony_tpu.models import TransformerConfig, generate, init_params
from tony_tpu.observability.metrics import MetricsRegistry
from tony_tpu.serving import ServingEngine, ServingQueueFull
from tony_tpu.serving.scheduler import _chunk_plan


def _tiny_setup(n_experts: int = 0):
    cfg = TransformerConfig(
        vocab_size=64, d_model=32, n_layers=2, n_heads=2, head_dim=16,
        d_ff=64, max_seq=96, dtype="float32", remat=False,
        n_experts=n_experts, expert_top_k=2 if n_experts else 0,
    )
    params = init_params(jax.random.key(0), cfg)
    return cfg, params


class TestChunkPlan:
    def test_short_prompt_single_padded_chunk(self):
        assert _chunk_plan(3, 8) == [(0, 3)]

    def test_exact_multiple(self):
        assert _chunk_plan(16, 8) == [(0, 8), (8, 8)]

    def test_remainder_overlapped_final_chunk(self):
        # 20 = 2 full chunks + an overlapped final chunk at 12: every
        # chunk fully valid, overlap rewrites identical K/V.
        assert _chunk_plan(20, 8) == [(0, 8), (8, 8), (12, 8)]


class TestSubmitValidation:
    def test_rejects_bad_requests(self):
        cfg, params = _tiny_setup()
        eng = ServingEngine(params, cfg, slots=2, max_len=32)
        with pytest.raises(ValueError, match="empty prompt"):
            eng.submit([], 4)
        with pytest.raises(ValueError, match="max_new_tokens"):
            eng.submit([1, 2], 0)
        with pytest.raises(ValueError, match="KV capacity"):
            eng.submit(list(range(30)), 8)  # 30 + 8 > 32
        with pytest.raises(ValueError, match="temperature"):
            eng.submit([1, 2], 4, temperature=-1.0)

    def test_queue_backpressure_sheds(self):
        cfg, params = _tiny_setup()
        eng = ServingEngine(params, cfg, slots=1, max_queue=2)
        for _ in range(2):
            eng.submit([1, 2], 2)
        with pytest.raises(ServingQueueFull):
            eng.submit([1, 2], 2)

    def test_rejects_oversized_max_len(self):
        cfg, params = _tiny_setup()
        with pytest.raises(ValueError, match="max_seq"):
            ServingEngine(params, cfg, max_len=cfg.max_seq + 1)


class TestEngineParity:
    """The acceptance e2e: >= 8 staggered mixed-length requests through
    admission -> chunked prefill -> EOS retirement -> slot reuse, each
    matching its single-request greedy ``generate`` reference."""

    @pytest.mark.parametrize("window,prefill_batch", [(1, 1), (4, 3)])
    def test_staggered_mixed_length_requests_match_references(
        self, window, prefill_batch
    ):
        cfg, params = _tiny_setup()
        rng = np.random.default_rng(7)
        lens = (3, 7, 12, 20, 5, 11, 17, 9, 6, 14)
        budgets = (6, 8, 9, 4, 12, 3, 8, 6, 10, 5)
        prompts = [rng.integers(0, 64, n).astype(np.int32) for n in lens]
        # Half the requests get a real EOS mid-stream, derived from
        # their plain greedy continuation, so retirement-before-budget
        # is actually exercised; the rest run to their token budget.
        eos_ids: list[int | None] = []
        for i, (p, n) in enumerate(zip(prompts, budgets)):
            if i % 2 == 0 and n >= 4:
                plain = np.asarray(
                    generate(params, jnp.asarray(p)[None], cfg, n)
                )[0]
                eos_ids.append(int(plain[n // 2]))
            else:
                eos_ids.append(None)

        registry = MetricsRegistry()
        eng = ServingEngine(
            params, cfg, slots=3, prefill_chunk=5, decode_window=window,
            prefill_batch=prefill_batch, registry=registry,
        )
        assert eng.slots < len(prompts)  # slot reuse is forced
        with eng:  # engine loop thread runs; submissions are staggered
            reqs = []
            for i, (p, n, e) in enumerate(zip(prompts, budgets, eos_ids)):
                reqs.append(eng.submit(p, n, eos_id=e))
                if i % 3 == 2:
                    time.sleep(0.05)  # arrivals overlap in-flight decode
            results = [r.result(timeout=120) for r in reqs]

        for p, n, e, res in zip(prompts, budgets, eos_ids, results):
            if e is None:
                want = np.asarray(
                    generate(params, jnp.asarray(p)[None], cfg, n)
                )[0]
                assert res["length"] == n
            else:
                ref = generate(params, jnp.asarray(p)[None], cfg, n,
                               eos_id=e)
                want_len = int(np.asarray(ref.lengths)[0])
                want = np.asarray(ref.tokens)[0][:want_len]
                assert res["length"] == want_len
            np.testing.assert_array_equal(np.asarray(res["tokens"]), want)

        # Every slot was reused and everything retired.
        stats = eng.stats()
        assert stats["retired"] == len(prompts)
        assert stats["active_slots"] == 0 and stats["queue_depth"] == 0

        # Serving telemetry flowed through the registry.
        snap = registry.snapshot()
        assert snap["counters"]["tony_serving_requests_total"] == len(
            prompts
        )
        assert snap["counters"]["tony_serving_retired_total"] == len(
            prompts
        )
        assert snap["counters"]["tony_serving_generated_tokens_total"] > 0
        assert snap["histograms"]["tony_serving_ttft_ms"]["count"] == len(
            prompts
        )
        assert snap["histograms"]["tony_serving_inter_token_ms"][
            "count"
        ] > 0
        assert "tony_serving_queue_depth" in snap["gauges"]
        assert "tony_serving_active_slots" in snap["gauges"]
        assert "tony_serving_tokens_per_sec" in snap["gauges"]

    def test_moe_trunk_parity(self):
        cfg, params = _tiny_setup(n_experts=2)
        rng = np.random.default_rng(3)
        prompts = [rng.integers(0, 64, n).astype(np.int32)
                   for n in (4, 9, 13)]
        eng = ServingEngine(params, cfg, slots=2, prefill_chunk=4)
        reqs = [eng.submit(p, 5) for p in prompts]
        for _ in range(500):
            if all(r.done() for r in reqs):
                break
            eng.step()
        for p, r in zip(prompts, reqs):
            want = np.asarray(
                generate(params, jnp.asarray(p)[None], cfg, 5)
            )[0]
            np.testing.assert_array_equal(
                np.asarray(r.result(1)["tokens"]), want
            )

    def test_temperature_request_runs_and_differs_from_greedy(self):
        cfg, params = _tiny_setup()
        prompt = np.arange(8, dtype=np.int32)
        eng = ServingEngine(params, cfg, slots=2, seed=5)
        hot = eng.submit(prompt, 16, temperature=1.5)
        cold = eng.submit(prompt, 16)
        for _ in range(500):
            if hot.done() and cold.done():
                break
            eng.step()
        greedy = np.asarray(
            generate(params, jnp.asarray(prompt)[None], cfg, 16)
        )[0]
        np.testing.assert_array_equal(
            np.asarray(cold.result(1)["tokens"]), greedy
        )
        # Sampling at temperature 1.5 over 16 draws flipping no token
        # vs greedy would be astronomically unlikely.
        assert not np.array_equal(
            np.asarray(hot.result(1)["tokens"]), greedy
        )

    def test_compile_instrumentation_counts_engine_executables(self):
        from tony_tpu.observability.metrics import default_registry

        cfg, params = _tiny_setup()
        reg = default_registry()

        def totals():
            snap = reg.snapshot()["counters"]
            return (snap.get("tony_compile_cache_hits_total", 0)
                    + snap.get("tony_compile_cache_misses_total", 0))

        eng = ServingEngine(params, cfg, slots=2, prefill_chunk=4)
        before = totals()
        r = eng.submit(np.arange(6, dtype=np.int32), 3)
        for _ in range(200):
            if r.done():
                break
            eng.step()
        r.result(1)
        # Exactly two instrumented first-compiles: the prefill batch and
        # the decode window.
        assert totals() == before + 2


class TestServingHTTP:
    def test_generate_healthz_shutdown(self):
        from tony_tpu.serving.http import ServingServer

        cfg, params = _tiny_setup()
        eng = ServingEngine(params, cfg, slots=2).start()
        server = ServingServer(eng, port=0)
        port = server.start()
        try:
            prompt = list(range(1, 7))
            body = json.dumps({
                "prompt": prompt, "max_new_tokens": 5,
            }).encode()
            with urllib.request.urlopen(urllib.request.Request(
                f"http://127.0.0.1:{port}/generate", data=body,
                headers={"Content-Type": "application/json"},
            ), timeout=120) as resp:
                out = json.loads(resp.read())
            want = np.asarray(generate(
                params, jnp.asarray(prompt, jnp.int32)[None], cfg, 5
            ))[0]
            np.testing.assert_array_equal(np.asarray(out["tokens"]), want)
            assert out["length"] == 5 and out["wall_ms"] >= 0

            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=10
            ) as resp:
                health = json.loads(resp.read())
            assert health["slots"] == 2 and health["retired"] == 1

            # Malformed body -> 400, not a wedged connection.
            bad = urllib.request.Request(
                f"http://127.0.0.1:{port}/generate", data=b"{}",
            )
            with pytest.raises(urllib.error.HTTPError) as err:
                urllib.request.urlopen(bad, timeout=10)
            assert err.value.code == 400

            with urllib.request.urlopen(urllib.request.Request(
                f"http://127.0.0.1:{port}/shutdown", data=b"",
            ), timeout=10) as resp:
                assert json.loads(resp.read())["ok"] is True
            assert server.wait_shutdown(timeout=10)
        finally:
            server.stop()
            eng.close()

    def test_close_fails_pending_requests(self):
        cfg, params = _tiny_setup()
        eng = ServingEngine(params, cfg, slots=1)
        req = eng.submit([1, 2, 3], 4)  # never stepped
        eng.close()
        with pytest.raises(RuntimeError, match="shut down"):
            req.result(timeout=1)


class TestProxyCounters:
    """Satellite: tony.proxy.connect-timeout + byte counters."""

    def test_tunnel_counts_bytes_by_direction(self):
        import socket
        import socketserver

        class Echo(socketserver.ThreadingTCPServer):
            allow_reuse_address = True

        class Handler(socketserver.StreamRequestHandler):
            def handle(self):
                data = self.rfile.read(5)
                self.wfile.write(data.upper())

        upstream = Echo(("127.0.0.1", 0), Handler)
        threading.Thread(target=upstream.serve_forever,
                         daemon=True).start()
        registry = MetricsRegistry()
        from tony_tpu.proxy import ProxyServer

        proxy = ProxyServer(
            "127.0.0.1", upstream.server_address[1], 0,
            connect_timeout_s=2.0, registry=registry,
        )
        port = proxy.start()
        try:
            with socket.create_connection(("127.0.0.1", port),
                                          timeout=10) as sock:
                sock.sendall(b"hello")
                assert sock.recv(5) == b"HELLO"
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                counters = registry.snapshot()["counters"]
                up = counters.get(
                    'tony_proxy_bytes_total{direction="up"}', 0)
                down = counters.get(
                    'tony_proxy_bytes_total{direction="down"}', 0)
                if up >= 5 and down >= 5:
                    break
                time.sleep(0.05)
            assert up == 5 and down == 5
        finally:
            proxy.stop()
            upstream.shutdown()
            upstream.server_close()

    def test_connect_timeout_is_configurable(self):
        from tony_tpu.proxy import ProxyServer

        proxy = ProxyServer("127.0.0.1", 1, 0, connect_deadline_s=0.0,
                            connect_timeout_s=0.05,
                            registry=MetricsRegistry())
        t0 = time.monotonic()
        assert proxy._connect_upstream() is None
        assert time.monotonic() - t0 < 5.0  # old hardcoded floor

    def test_conf_key_registered_and_validated(self):
        from tony_tpu.analysis.config_check import check_config
        from tony_tpu.conf import keys
        from tony_tpu.conf.configuration import TonyConfiguration

        assert keys.DEFAULTS[keys.K_PROXY_CONNECT_TIMEOUT_MS] == 5000
        conf = TonyConfiguration()
        conf.set(keys.K_PROXY_CONNECT_TIMEOUT_MS, 0)
        assert any(
            f.rule_id == "TONY-C002" and "connect-timeout" in f.message
            for f in check_config(conf)
        )

    def test_serving_keys_validated(self):
        from tony_tpu.analysis.config_check import check_config
        from tony_tpu.conf import keys
        from tony_tpu.conf.configuration import TonyConfiguration

        for key in (keys.K_SERVING_SLOTS, keys.K_SERVING_PREFILL_CHUNK,
                    keys.K_SERVING_DECODE_WINDOW,
                    keys.K_SERVING_MAX_QUEUE):
            conf = TonyConfiguration()
            conf.set(key, 0)
            assert any(f.rule_id == "TONY-C002" for f in check_config(conf)), key
        conf = TonyConfiguration()
        conf.set(keys.K_SERVING_PORT, 0)  # 0 = ephemeral is legal
        assert not [f for f in check_config(conf) if f.rule_id == "TONY-C002"]


class TestBenchServingGate:
    """The bench_serving sub-metrics flatten into gated names and the
    seeded cpu baseline catches a serving-throughput collapse."""

    _LINE = {
        "metric": "x",
        "extras": {"device": "cpu", "serving": {
            "wall_tokens_per_sec": 1341, "sustained_tokens_per_sec": 1577,
            "generate_wall_tokens_per_sec": 4530,
            "generate_wall_speedup": 0.35,
            "single_shot_wall_tokens_per_sec": 942,
            "single_shot_speedup": 1.67,
            "inter_token_p50_ms": 4.5, "inter_token_p95_ms": 13.6,
            "ttft_p50_ms": 440.0, "ttft_p95_ms": 1791.0,
            "generated_tokens": 3000, "slots": 16, "n_requests": 128,
            "prefill_chunk": 32, "decode_window": 8, "out_mean": 32.0,
            "d_model": 128,
        }},
    }

    def _bench(self):
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "bench", Path(__file__).resolve().parent.parent / "bench.py"
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    def test_seeded_cpu_gate_passes_and_catches_collapse(self):
        bench = self._bench()
        current = bench.collect_submetrics(self._LINE)
        assert current["serving.single_shot_speedup"] == 1.67
        assert "serving.slots" not in current  # shape params ungated
        # The cpu table also gates other workload families (scheduler);
        # this synthetic line is serving-only, so gate that subset — a
        # REAL bench line carries every family and gates them all.
        baseline = {
            k: v for k, v in bench.load_baselines().get("cpu", {}).items()
            if k.startswith("serving.")
        }
        assert baseline, "cpu serving baselines must be seeded"
        assert not bench.check_regressions(current, baseline)
        collapsed = dict(current)
        collapsed["serving.single_shot_speedup"] = 0.5
        collapsed["serving.sustained_tokens_per_sec"] = 300.0
        problems = bench.check_regressions(collapsed, baseline)
        assert any("single_shot_speedup" in p for p in problems)
        assert any("sustained_tokens_per_sec" in p for p in problems)


@pytest.mark.slow
class TestMiniClusterServing:
    """The full wire: a `serving` task type submitted to the mini
    cluster runs examples/lm_serve.py (checkpointless smoke weights),
    the test tunnels to it through ProxyServer exactly as a gateway
    would, drives generate requests end to end, and the job SUCCEEDs
    after /shutdown — with the tunnel's byte counters ticking."""

    def test_serving_task_through_proxy(self, tmp_path):
        import sys

        from tony_tpu.conf import keys
        from tony_tpu.coordinator.session import SessionStatus
        from tony_tpu.mini import MiniTonyCluster
        from tony_tpu.proxy import ProxyServer

        repo = Path(__file__).resolve().parent.parent
        addr_file = tmp_path / "serving.addr"
        with MiniTonyCluster(tmp_path / "cluster") as cluster:
            conf = cluster.base_conf()
            conf.set(keys.K_FRAMEWORK, "jax")
            conf.set(keys.K_EXECUTES,
                     str(repo / "examples" / "lm_serve.py"))
            conf.set(keys.K_PYTHON_BINARY, sys.executable)
            conf.set(keys.instances_key("worker"), 0)
            conf.set(keys.instances_key("ps"), 0)
            conf.set(keys.instances_key("serving"), 1)
            conf.set(keys.K_CHIEF_NAME, "serving")
            conf.set(keys.K_SERVING_SLOTS, 2)
            conf.set(keys.K_SERVING_PREFILL_CHUNK, 8)
            conf.set(keys.K_SERVING_DECODE_WINDOW, 2)
            conf.set(keys.K_TASK_PARAMS,
                     f"--max-seq 96 --seed 0 --addr-file {addr_file}")
            job = cluster.start_job(conf)
            proxy = None
            try:
                deadline = time.monotonic() + 180
                while not addr_file.exists():
                    assert job.running(), "serving job died before binding"
                    assert time.monotonic() < deadline, "no addr published"
                    time.sleep(0.25)
                host, _, port = addr_file.read_text().strip().rpartition(
                    ":")
                registry = MetricsRegistry()
                proxy = ProxyServer(host, int(port), 0,
                                    connect_timeout_s=conf.get_int(
                                        keys.K_PROXY_CONNECT_TIMEOUT_MS,
                                        5000) / 1000.0,
                                    registry=registry)
                local = proxy.start()
                base = f"http://127.0.0.1:{local}"

                prompt = [1, 5, 9, 2]
                body = json.dumps(
                    {"prompt": prompt, "max_new_tokens": 8}).encode()
                with urllib.request.urlopen(urllib.request.Request(
                    f"{base}/generate", data=body,
                ), timeout=180) as resp:
                    out = json.loads(resp.read())
                assert out["length"] == 8

                # Reference: the fixture serves fresh weights from
                # seed 0 with lm_train's default model flags — rebuild
                # the identical config/params here and pin parity
                # through the whole proxy -> engine wire.
                import argparse

                sys.path.insert(0, str(repo / "examples"))
                try:
                    import lm_train
                finally:
                    sys.path.pop(0)
                p = argparse.ArgumentParser()
                lm_train.add_model_args(p)
                cfg = lm_train.model_config_from_args(
                    p.parse_args([]), max_seq=96
                )
                params = init_params(jax.random.key(0), cfg)
                want = np.asarray(generate(
                    params, jnp.asarray(prompt, jnp.int32)[None], cfg, 8
                ))[0]
                np.testing.assert_array_equal(
                    np.asarray(out["tokens"]), want
                )

                with urllib.request.urlopen(f"{base}/healthz",
                                            timeout=30) as resp:
                    health = json.loads(resp.read())
                assert health["slots"] == 2 and health["retired"] >= 1

                counters = registry.snapshot()["counters"]
                assert counters['tony_proxy_bytes_total{direction="up"}'] > 0
                assert counters[
                    'tony_proxy_bytes_total{direction="down"}'] > 0

                with urllib.request.urlopen(urllib.request.Request(
                    f"{base}/shutdown", data=b"",
                ), timeout=30):
                    pass
                status = job.wait(timeout_s=120)
                assert status is SessionStatus.SUCCEEDED
            finally:
                if proxy is not None:
                    proxy.stop()


class TestDrain:
    def test_drain_completes_inflight_then_blocks_admission(self):
        cfg, params = _tiny_setup()
        eng = ServingEngine(params, cfg, slots=2)
        with eng:
            reqs = [eng.submit(np.arange(1, 6, dtype=np.int32), 6)
                    for _ in range(4)]
            assert eng.drain(timeout=60.0)
            for r in reqs:
                assert r.done() and r.error is None
                assert r.result(1)["length"] == 6
            with pytest.raises(RuntimeError, match="draining"):
                eng.submit([1, 2], 2)


class TestServingFleetSatellites:
    """PR-18 serving-side satellites: 429 + Retry-After shed signal,
    fleet-facing /healthz fields, gauge zeroing on drain/close, model
    multiplexing parity + LRU residency, and prefill/decode
    disaggregation parity over the HTTP wire format."""

    def test_http_429_retry_after_and_healthz_fleet_fields(self):
        from tony_tpu.serving.http import ServingServer

        cfg, params = _tiny_setup()
        # Engine deliberately NOT started: the queue can't drain, so
        # filling it is deterministic.
        eng = ServingEngine(params, cfg, slots=1, max_queue=1)
        eng.submit([1, 2, 3], 4)  # queue now at max_queue
        server = ServingServer(eng, port=0,
                               extra_health={"role": "prefill"})
        port = server.start()
        try:
            body = json.dumps({"prompt": [1, 2], "max_new_tokens": 2})
            with pytest.raises(urllib.error.HTTPError) as err:
                urllib.request.urlopen(urllib.request.Request(
                    f"http://127.0.0.1:{port}/generate",
                    data=body.encode(),
                ), timeout=10)
            # Shed is distinguishable from failure: 429 + Retry-After,
            # which the fleet router uses to retry another replica.
            assert err.value.code == 429
            assert err.value.headers["Retry-After"] == "1"

            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=10
            ) as resp:
                health = json.loads(resp.read())
            # The fields the router/autoscaler read, plus the merged
            # extra_health role the fleet layer advertises.
            assert health["active_slots"] == 0
            assert health["queue_depth"] == 1
            assert health["draining"] is False
            assert health["models"] == ["default"]
            assert health["role"] == "prefill"
        finally:
            server.stop()
            eng.close()

    def test_gauges_zeroed_on_drain_and_close(self):
        registry = MetricsRegistry()
        cfg, params = _tiny_setup()
        eng = ServingEngine(params, cfg, slots=2, registry=registry)
        with eng:
            reqs = [eng.submit([1, 2, 3, 4], 5) for _ in range(3)]
            assert eng.drain(timeout=60.0)
            for r in reqs:
                assert r.result(1)["length"] == 5
            # A drained replica must publish zero load — stale gauges
            # would keep attracting router traffic and block the
            # autoscaler's scale-down forever.
            for name in ("tony_serving_queue_depth",
                         "tony_serving_active_slots",
                         "tony_serving_tokens_per_sec"):
                assert registry.gauge(name).value == 0

        # close() without a drain (requests still queued) zeroes too.
        reg2 = MetricsRegistry()
        eng2 = ServingEngine(params, cfg, slots=1, registry=reg2)
        eng2.submit([1, 2], 3)  # never started, never stepped
        eng2.close()
        for name in ("tony_serving_queue_depth",
                     "tony_serving_active_slots",
                     "tony_serving_tokens_per_sec"):
            assert reg2.gauge(name).value == 0

    def test_multiplexing_parity_and_lru_residency(self):
        cfg, params_a = _tiny_setup()
        params_b = init_params(jax.random.key(1), cfg)
        params_c = init_params(jax.random.key(2), cfg)
        loads = {"b": 0, "c": 0}

        def load_b():
            loads["b"] += 1
            return params_b

        def load_c():
            loads["c"] += 1
            return params_c

        prompt = np.arange(1, 8, dtype=np.int32)
        want = {
            name: np.asarray(generate(
                p, jnp.asarray(prompt)[None], cfg, 6
            ))[0]
            for name, p in (("default", params_a), ("b", params_b),
                            ("c", params_c))
        }

        # max_resident_models=2: "default" (ctor weights, no loader —
        # pinned) + one loader-backed model; serving the other must
        # evict its sibling and re-fuse it on the next swap.
        eng = ServingEngine(params_a, cfg, slots=2,
                            max_resident_models=2)
        eng.add_model("b", loader=load_b)
        eng.add_model("c", loader=load_c)
        with eng:
            assert eng.stats()["models"] == ["b", "c", "default"]
            for name in ("b", "c", "default", "b"):
                got = eng.submit(prompt, 6, model=name).result(
                    timeout=120)
                np.testing.assert_array_equal(
                    np.asarray(got["tokens"]), want[name],
                    err_msg=f"model {name!r} diverged from its "
                            f"single-request generate reference",
                )
            # Serving "c" evicted "b" (LRU past the residency bound),
            # so the second "b" request re-fused from its loader.
            assert loads["b"] == 2 and loads["c"] == 1
            assert len(eng._resident) <= 2

    def test_disaggregation_parity_over_http_wire(self):
        from tony_tpu.serving.http import (ServingServer, decode_kv,
                                           encode_kv)

        cfg, params = _tiny_setup()
        prompt = list(range(2, 11))
        total_new = 6
        want = np.asarray(generate(
            params, jnp.asarray(prompt, jnp.int32)[None], cfg, total_new
        ))[0]

        # encode/decode roundtrip is exact for float32 KV.
        rng = np.random.default_rng(3)
        kk = rng.standard_normal((2, 4, 2, 16)).astype(np.float32)
        vv = rng.standard_normal((2, 4, 2, 16)).astype(np.float32)
        rk, rv = decode_kv(encode_kv(kk, vv))
        np.testing.assert_array_equal(rk, kk)
        np.testing.assert_array_equal(rv, vv)

        pre_eng = ServingEngine(params, cfg, slots=2).start()
        dec_eng = ServingEngine(params, cfg, slots=2).start()
        pre_srv = ServingServer(pre_eng, port=0)
        dec_srv = ServingServer(dec_eng, port=0)
        pre_port = pre_srv.start()
        dec_port = dec_srv.start()

        def _post(port, path, obj):
            body = json.dumps(obj).encode()
            with urllib.request.urlopen(urllib.request.Request(
                f"http://127.0.0.1:{port}{path}", data=body,
                headers={"Content-Type": "application/json"},
            ), timeout=120) as resp:
                return json.loads(resp.read())

        try:
            # Prefill replica: chunked prefill + first token + exported
            # KV rows; the slot frees instead of decoding.
            pre = _post(pre_port, "/prefill", {
                "prompt": prompt, "max_new_tokens": total_new,
            })
            assert pre["last_token"] == int(want[0])
            assert pre["pos"] == len(prompt)
            assert pre["kv"]["shape"][1] == len(prompt)
            assert pre_eng.stats()["active_slots"] == 0

            # Decode replica: inject the shipped rows, decode the rest.
            dec = _post(dec_port, "/inject", {
                "kv": pre["kv"], "last_token": pre["last_token"],
                "pos": pre["pos"],
                "max_new_tokens": total_new - 1,
            })
            got = [pre["last_token"]] + list(dec["tokens"])
            np.testing.assert_array_equal(
                np.asarray(got), want,
                err_msg="disaggregated prefill->inject diverged from "
                        "single-engine generate",
            )
        finally:
            pre_srv.stop()
            dec_srv.stop()
            pre_eng.close()
            dec_eng.close()


class TestBenchFleetGate:
    """bench_serving_fleet sub-metrics flatten into gated names and the
    seeded cpu baselines catch a fleet-throughput collapse, a TTFT
    blow-up, and a dead (or slow) autoscaler."""

    _LINE = {
        "metric": "x",
        "extras": {"device": "cpu", "serving_fleet": {
            "fleet_wall_tokens_per_sec": 1459,
            "fleet_sustained_tokens_per_sec": 1912,
            "ttft_p50_ms": 167.8, "ttft_p95_ms": 318.9,
            "autoscale_reaction_ms": 15.5,
            "replicas_peak": 3, "scale_ups": 2, "requests_ok": 80,
            "requests_failed": 0, "generated_tokens": 1280,
            "slots": 4, "max_replicas": 3, "d_model": 128,
            # _safe stamps this whenever the jit sanitizer is armed
            # (always, under bench --check); baselined at absolute 0.
            "retraces_total": 0,
        }},
    }

    def test_seeded_cpu_gate_passes_and_catches_collapse(self):
        bench = TestBenchServingGate()._bench()
        current = bench.collect_submetrics(self._LINE)
        # Directionality: throughput gates higher-is-better, reaction
        # and TTFT lower-is-better, shape params ungated.
        assert bench.metric_direction(
            "serving_fleet.autoscale_reaction_ms") == "lower"
        assert bench.metric_direction(
            "serving_fleet.fleet_sustained_tokens_per_sec") == "higher"
        assert "serving_fleet.replicas_peak" not in current
        baseline = {
            k: v for k, v in bench.load_baselines().get("cpu", {}).items()
            if k.startswith("serving_fleet.")
        }
        assert baseline, "cpu serving_fleet baselines must be seeded"
        assert not bench.check_regressions(current, baseline)

        collapsed = dict(current)
        collapsed["serving_fleet.fleet_sustained_tokens_per_sec"] = 100.0
        # The no-scale-up sentinel (9e9) must fail the reaction gate.
        collapsed["serving_fleet.autoscale_reaction_ms"] = 9e9
        problems = bench.check_regressions(collapsed, baseline)
        assert any("fleet_sustained_tokens_per_sec" in p
                   for p in problems)
        assert any("autoscale_reaction_ms" in p for p in problems)
