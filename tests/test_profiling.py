"""Profiler seam: the jax.profiler integration behind the reserved
TB/profiler ports (SURVEY §5.1; TaskExecutor.java:121-124 analogue)."""

import os

import jax
import jax.numpy as jnp

from tony_tpu import constants, profiling


def _work():
    x = jnp.ones((64, 64))
    return float(jnp.sum(jax.jit(lambda a: a @ a)(x)))


def test_trace_writes_capture(tmp_path):
    with profiling.trace(str(tmp_path)):
        _work()
    # jax writes plugins/profile/<run>/*.xplane.pb under the trace dir
    captured = [p for p in tmp_path.rglob("*") if p.is_file()]
    assert captured, "trace produced no files"


def test_trace_defaults_to_tony_log_dir(tmp_path, monkeypatch):
    monkeypatch.setenv(constants.TONY_LOG_DIR, str(tmp_path))
    assert profiling.default_trace_dir() == str(tmp_path / "profile")
    with profiling.trace():
        _work()
    assert list((tmp_path / "profile").rglob("*.pb"))


def test_step_profiler_window(tmp_path):
    prof = profiling.StepProfiler(start=2, num=2, log_dir=str(tmp_path))
    for step in range(6):
        prof.before_step(step)
        _work()
        prof.after_step(step)
    assert not prof._active
    assert list(tmp_path.rglob("*.pb"))


def test_step_profiler_close_mid_window(tmp_path):
    prof = profiling.StepProfiler(start=0, num=100, log_dir=str(tmp_path))
    prof.before_step(0)
    _work()
    prof.close()
    assert not prof._active


def test_maybe_start_profiler_server_no_env(monkeypatch):
    monkeypatch.delenv(constants.PROFILER_PORT, raising=False)
    assert profiling.maybe_start_profiler_server() is None
