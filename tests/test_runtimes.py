"""Unit tests for the framework runtime env builders (the TaskExecutor
switch analogue, TaskExecutor.java:128-151)."""

import json

import pytest

from tony_tpu.conf import TonyConfiguration
from tony_tpu.executor.runtimes import get_runtime

SPEC = {"worker": ["h0:5000", "h1:5001"], "ps": ["h2:5002"]}


def _conf():
    return TonyConfiguration()


def test_tensorflow_env():
    env = get_runtime("tensorflow").build_env(SPEC, "worker", 1, _conf())
    tf = json.loads(env["TF_CONFIG"])
    assert tf["cluster"] == SPEC
    assert tf["task"] == {"type": "worker", "index": 1}
    assert json.loads(env["CLUSTER_SPEC"]) == SPEC


def test_pytorch_env():
    env = get_runtime("pytorch").build_env(SPEC, "ps", 0, _conf())
    assert env["INIT_METHOD"] == "tcp://h0:5000"
    assert env["MASTER_ADDR"] == "h0"
    assert env["MASTER_PORT"] == "5000"
    assert env["WORLD"] == env["WORLD_SIZE"] == "3"
    # flat order: worker (chief job) first, then ps → ps:0 has rank 2
    assert env["RANK"] == "2"


def test_jax_env_chief_is_process_zero():
    rt = get_runtime("jax")
    chief_env = rt.build_env(SPEC, "worker", 0, _conf())
    assert chief_env["TONY_PROCESS_ID"] == "0"
    assert chief_env["JAX_COORDINATOR_ADDRESS"] == "h0:5000"
    assert chief_env["TONY_NUM_PROCESSES"] == "3"
    ps_env = rt.build_env(SPEC, "ps", 0, _conf())
    assert ps_env["TONY_PROCESS_ID"] == "2"
    assert ps_env["JAX_COORDINATOR_ADDRESS"] == "h0:5000"


def test_jax_env_multislice_megascale(monkeypatch):
    """With the coordinator's slice identity in the executor env, the JAX
    runtime injects the megascale/DCN variables (slice id, slice count,
    coordinator host) alongside the flat jax.distributed identity —
    VERDICT r2 item 2's per-slice env contract."""
    monkeypatch.setenv("TONY_SLICE_INDEX", "1")
    monkeypatch.setenv("TONY_SLICE_PROCESS_ID", "0")
    monkeypatch.setenv("TONY_NUM_SLICES", "2")
    rt = get_runtime("jax")
    env = rt.build_env(SPEC, "worker", 1, _conf())
    assert env["MEGASCALE_COORDINATOR_ADDRESS"] == "h0"
    assert env["MEGASCALE_NUM_SLICES"] == "2"
    assert env["MEGASCALE_SLICE_ID"] == "1"
    assert env["TONY_SLICE_INDEX"] == "1"
    # jax.distributed still spans all processes with ONE coordinator.
    assert env["JAX_COORDINATOR_ADDRESS"] == "h0:5000"
    assert env["TONY_NUM_PROCESSES"] == "3"


def test_jax_env_single_slice_has_no_megascale():
    env = get_runtime("jax").build_env(SPEC, "worker", 0, _conf())
    assert "MEGASCALE_SLICE_ID" not in env


def test_unknown_framework():
    with pytest.raises(ValueError, match="unknown framework"):
        get_runtime("mxnet")
