"""End-to-end tests on the mini cluster — the analogue of the reference's
``TestTonyE2E.java`` (11 scenarios on a 3-NM MiniYARNCluster): a real
coordinator with a real RPC server launching real executor subprocesses that
run Python fixture scripts asserting the env contract."""

import sys
from pathlib import Path

import pytest

from tony_tpu.conf import keys
from tony_tpu.coordinator.session import SessionStatus
from tony_tpu.history.writer import JobMetadata
from tony_tpu.mini import MiniTonyCluster

FIXTURES = Path(__file__).resolve().parent / "fixtures"


@pytest.fixture()
def cluster(tmp_path):
    return MiniTonyCluster(tmp_path)


def _job(cluster, fixture, workers=1, ps=0, framework="jax", **extra):
    conf = cluster.base_conf()
    conf.set(keys.K_FRAMEWORK, framework)
    conf.set(keys.K_EXECUTES, str(FIXTURES / fixture))
    conf.set(keys.K_PYTHON_BINARY, sys.executable)
    conf.set(keys.instances_key("worker"), workers)
    conf.set(keys.instances_key("ps"), ps)
    for k, v in extra.items():
        conf.set(k, v)
    return conf


def test_single_worker_succeeds(cluster):
    status, _ = cluster.run_job(_job(cluster, "exit_0.py"))
    assert status is SessionStatus.SUCCEEDED


def test_failing_worker_fails_job(cluster):
    status, coord = cluster.run_job(_job(cluster, "exit_1.py"))
    assert status is SessionStatus.FAILED
    assert "worker:0" in coord.session.diagnostics


def test_env_contract_and_shell_env(cluster):
    conf = _job(cluster, "check_env.py", workers=2)
    conf.set(keys.K_SHELL_ENV, "USER_SHELL_VAR=propagated")
    status, _ = cluster.run_job(conf)
    assert status is SessionStatus.SUCCEEDED


def test_jax_runtime_env(cluster):
    status, _ = cluster.run_job(_job(cluster, "check_jax_env.py", workers=2, ps=1))
    assert status is SessionStatus.SUCCEEDED


def test_pytorch_runtime_env(cluster):
    status, _ = cluster.run_job(
        _job(cluster, "check_pytorch_env.py", workers=2, framework="pytorch")
    )
    assert status is SessionStatus.SUCCEEDED


def test_gang_barrier_with_ps(cluster):
    # ps + 2 workers: everyone must pass the barrier; chief success ends the
    # job while ps (running exit_0 too, but untracked) cannot block it.
    status, coord = cluster.run_job(_job(cluster, "exit_0.py", workers=2, ps=1))
    assert status is SessionStatus.SUCCEEDED
    spec = coord.session.cluster_spec()
    assert spec is not None and len(spec["worker"]) == 2 and len(spec["ps"]) == 1


def test_slice_topology_reaches_user_script(cluster):
    """tony.worker.tpus=4 -> coordinator plans a v5litepod-4 slice and the
    user script reads it via tony_tpu.runtime.slice_topology()."""
    conf = _job(cluster, "check_slice_env.py")
    conf.set(keys.tpus_key("worker"), 4)
    status, coord = cluster.run_job(conf)
    assert status is SessionStatus.SUCCEEDED, coord.session.diagnostics
    assert coord.slice_plans["worker"].accelerator_type == "v5litepod-4"


def test_multislice_identity_reaches_user_script(cluster):
    """2 workers x tpus=8 pinned to v5litepod-8 => a 2-slice plan; each
    executor must see its slice index, in-slice process id, and the
    megascale/DCN env, while jax.distributed stays one flat process list
    (VERDICT r2 item 2: multi-slice must be driveable end to end)."""
    conf = _job(cluster, "check_multislice_env.py", workers=2)
    conf.set(keys.tpus_key("worker"), 8)
    conf.set(keys.K_TPU_ACCELERATOR_TYPE, "v5litepod-8")
    status, coord = cluster.run_job(conf)
    assert status is SessionStatus.SUCCEEDED, coord.session.diagnostics
    plan = coord.slice_plans["worker"]
    assert plan.num_slices == 2 and plan.hosts_per_slice == 1


def test_multihost_slice_identity_reaches_user_script(cluster):
    """4 workers x tpus=4 pinned to v4-16 (a 2-host slice shape) => 2
    slices x 2 hosts; each executor must see slice index task//2 and
    in-slice process id task%2 — the hosts_per_slice>1 placement path
    (VERDICT r3 weak #1: previously only 1-host-per-slice was e2e'd)."""
    conf = _job(cluster, "check_multihost_slice_env.py", workers=4)
    conf.set(keys.tpus_key("worker"), 4)
    conf.set(keys.K_TPU_ACCELERATOR_TYPE, "v4-16")
    status, coord = cluster.run_job(conf)
    assert status is SessionStatus.SUCCEEDED, coord.session.diagnostics
    plan = coord.slice_plans["worker"]
    assert plan.num_slices == 2 and plan.hosts_per_slice == 2


def test_sharded_reader_handoff_exactly_once(cluster, tmp_path):
    """Data-plane handoff (the py4j analogue): two executor processes each
    build a reader via tony_tpu.runtime.sharded_reader; together their
    shards must cover every record exactly once."""
    import json as _json

    data = tmp_path / "corpus.jsonl"
    data.write_text("".join(
        _json.dumps({"id": i, "text": "x" * (i % 7)}) + "\n"
        for i in range(57)
    ))
    conf = _job(cluster, "reader_shard.py", workers=2)
    conf.set(keys.K_SHELL_ENV, f"READER_DATA={data}")
    status, coord = cluster.run_job(conf)
    assert status is SessionStatus.SUCCEEDED, coord.session.diagnostics
    shards = []
    for p in sorted((coord.app_dir / "logs").glob("reader-shard-*.json")):
        shards.append(_json.loads(p.read_text()))
    assert len(shards) == 2 and all(shards)
    combined = sorted(i for s in shards for i in s)
    assert combined == list(range(57))  # exact cover, nothing twice


def test_sharded_reader_over_gs_uris(cluster, tmp_path):
    """The remote-storage data plane end to end (VERDICT r3 missing #1):
    executors stream a gs:// corpus via ranged reads — no staging, the way
    the reference's reader opens HDFS directly
    (HdfsAvroFileSplitReader.java:347-416). TONY_GCS_EMULATOR_DIR (the
    MiniDFS analogue) maps the bucket onto a local dir in every executor
    subprocess."""
    import json as _json

    from tony_tpu.cloud.gcs import FileObjectStorage

    store = FileObjectStorage(tmp_path / "objects")
    store.put_bytes("gs://corpus/part-0.jsonl", "".join(
        _json.dumps({"id": i, "text": "x" * (i % 7)}) + "\n"
        for i in range(39)
    ).encode())
    store.put_bytes("gs://corpus/part-1.jsonl", "".join(
        _json.dumps({"id": i, "text": "y" * (i % 5)}) + "\n"
        for i in range(39, 57)
    ).encode())
    conf = _job(cluster, "reader_shard.py", workers=2)
    conf.set(
        keys.K_SHELL_ENV,
        "READER_DATA=gs://corpus/part-0.jsonl;gs://corpus/part-1.jsonl,"
        f"TONY_GCS_EMULATOR_DIR={tmp_path / 'objects'}",
    )
    status, coord = cluster.run_job(conf)
    assert status is SessionStatus.SUCCEEDED, coord.session.diagnostics
    shards = []
    for p in sorted((coord.app_dir / "logs").glob("reader-shard-*.json")):
        shards.append(_json.loads(p.read_text()))
    assert len(shards) == 2 and all(shards)
    combined = sorted(i for s in shards for i in s)
    assert combined == list(range(57))


def test_cross_process_psum(cluster):
    """A REAL jax.distributed collective through the full stack: 2 executor
    subprocesses each call tony_tpu.runtime.initialize() and run a pmap psum
    whose value proves cross-process data movement (VERDICT r1 item 2)."""
    status, coord = cluster.run_job(
        _job(cluster, "jax_psum.py", workers=2), timeout_s=300
    )
    assert status is SessionStatus.SUCCEEDED, coord.session.diagnostics


def test_succeeded_session_reaps_blocked_ps_processes(cluster):
    """A SUCCEEDED session must leave ZERO job processes behind — including
    an untracked ps whose user script blocks forever in Server.join() and
    the grandchildren it spawned (VERDICT r3 weak #6: such orphans were
    found on the build box). The reference kills whole containers on
    reset/stop (TonyApplicationMaster.java:526-542, 621-637); here the
    TERM->reap handshake between backend.kill and the executor's death
    handlers is the equivalent."""
    import json as _json
    import os as _os
    import time as _time

    status, coord = cluster.run_job(
        _job(cluster, "ps_block_forever.py", workers=1, ps=1)
    )
    assert status is SessionStatus.SUCCEEDED, coord.session.diagnostics
    pids = _json.loads(
        (coord.app_dir / "logs" / "ps-pids.json").read_text()
    )
    deadline = _time.time() + 30  # generous: 1-CPU box under suite load
    still_alive = dict(pids)
    while still_alive and _time.time() < deadline:
        for name, pid in list(still_alive.items()):
            try:
                _os.kill(pid, 0)
            except ProcessLookupError:
                del still_alive[name]
        _time.sleep(0.2)
    assert not still_alive, f"orphaned job processes: {still_alive}"


def test_exited_script_cannot_orphan_helpers(cluster):
    """A worker that spawns a background helper and exits 0: the helper
    (same user process group) must be reaped even though the direct child
    exited cleanly — group teardown, not child teardown."""
    import json as _json
    import os as _os
    import time as _time

    status, coord = cluster.run_job(_job(cluster, "spawn_helper_exit.py"))
    assert status is SessionStatus.SUCCEEDED, coord.session.diagnostics
    helper = _json.loads(
        (coord.app_dir / "logs" / "helper-0.json").read_text()
    )["helper"]
    deadline = _time.time() + 30
    while _time.time() < deadline:
        try:
            _os.kill(helper, 0)
        except ProcessLookupError:
            break
        _time.sleep(0.2)
    else:
        raise AssertionError(f"helper {helper} survived the job")


def test_backend_escalation_reaps_user_group_via_pgid_file(tmp_path):
    """The SIGKILL escalation path cannot rely on the executor's handlers
    (SIGKILL runs none): the backend must reap the user process group from
    the pgid file the executor advertised at spawn."""
    import os as _os
    import signal as _signal
    import subprocess as _subprocess
    import time as _time

    from tony_tpu.coordinator.backend import LocalProcessBackend, _ProcHandle

    backend = LocalProcessBackend(tmp_path / "logs")
    # a fake "user process" in its own session, advertised via pgid file
    user = _subprocess.Popen(
        [sys.executable, "-c", "import time; time.sleep(3600)"],
        start_new_session=True,
    )
    (tmp_path / "logs" / ".worker-0.userpgid").write_text(str(user.pid))
    # a fake "wedged executor" that ignores SIGTERM; it prints once the
    # handler is installed so the TERM below cannot race the install
    wedged = _subprocess.Popen(
        [sys.executable, "-u", "-c",
         "import signal, time; signal.signal(signal.SIGTERM, "
         "signal.SIG_IGN); print('ready', flush=True); time.sleep(3600)"],
        start_new_session=True, stdout=_subprocess.PIPE,
    )
    assert wedged.stdout is not None and wedged.stdout.readline().strip() == b"ready"
    backend.KILL_GRACE_S = 1.0
    try:
        backend.kill(_ProcHandle(wedged, "worker:0"))
        assert wedged.poll() is not None  # escalated to SIGKILL
        deadline = _time.time() + 10
        while user.poll() is None and _time.time() < deadline:
            _time.sleep(0.1)
        assert user.poll() is not None, "user group survived escalation"
    finally:
        for p in (user, wedged):
            if p.poll() is None:
                _os.killpg(p.pid, _signal.SIGKILL)


def test_history_written(cluster):
    status, coord = cluster.run_job(_job(cluster, "exit_0.py"))
    assert status is SessionStatus.SUCCEEDED
    jhists = list(cluster.history_dir.rglob("*.jhist"))
    assert len(jhists) == 1
    meta = JobMetadata.parse_jhist_name(jhists[0].name)
    assert meta.status == "SUCCEEDED" and meta.app_id == coord.app_id
    assert (jhists[0].parent / "config.json").is_file()


def test_task_urls_point_at_logs(cluster):
    status, coord = cluster.run_job(_job(cluster, "exit_0.py", workers=2))
    urls = coord.session.task_urls()
    assert [u.index for u in urls] == [0, 1]
    assert all(u.url.startswith("file://") for u in urls)


def test_single_node_mode_succeeds(cluster):
    """K_IS_SINGLE_NODE: the user command runs inside the coordinator, no
    executors launch (doPreprocessingJob + early exit, reference :483-497)."""
    conf = _job(cluster, "exit_0.py", workers=2)
    conf.set(keys.K_IS_SINGLE_NODE, True)
    status, coord = cluster.run_job(conf)
    assert status is SessionStatus.SUCCEEDED
    # no executor ever launched (their logs would exist otherwise)
    logs = list((coord.app_dir / "logs").glob("worker-*.log"))
    assert logs == []
    assert list((coord.app_dir / "logs").glob("preprocess-*.log"))


def test_single_node_failure_never_retries(cluster):
    conf = _job(cluster, "exit_1.py")
    conf.set(keys.K_IS_SINGLE_NODE, True)
    conf.set(keys.K_AM_RETRY_COUNT, 3)
    status, coord = cluster.run_job(conf)
    assert status is SessionStatus.FAILED
    assert coord.session.session_id == 1  # reference :365: no single-node retry


def test_preprocess_gates_and_forwards_model_params(cluster):
    """K_ENABLE_PREPROCESS: same script runs first in the coordinator
    (emitting 'Model parameters: ...'), then as tasks that must see
    MODEL_PARAMS (reference :684-701)."""
    conf = _job(cluster, "preprocess_fixture.py", workers=2)
    conf.set(keys.K_ENABLE_PREPROCESS, True)
    status, coord = cluster.run_job(conf)
    assert status is SessionStatus.SUCCEEDED, coord.session.diagnostics


def test_preprocess_failure_blocks_scheduling(cluster):
    conf = _job(cluster, "preprocess_fixture.py", workers=2)
    conf.set(keys.K_ENABLE_PREPROCESS, True)
    conf.set(keys.K_SHELL_ENV, "PREPROCESS_SHOULD_FAIL=1")
    status, coord = cluster.run_job(conf)
    assert status is SessionStatus.FAILED
    assert "preprocess job exited with 3" in coord.session.diagnostics
    assert list((coord.app_dir / "logs").glob("worker-*.log")) == []


def test_application_timeout(cluster):
    conf = _job(cluster, "exit_0.py")
    # make the worker hang forever via a sleep command instead of the fixture
    conf.set(keys.K_EXECUTES, "-c 'import time; time.sleep(600)'")
    conf.set(keys.K_APPLICATION_TIMEOUT, 2000)
    status, coord = cluster.run_job(conf, timeout_s=60)
    assert status is SessionStatus.FAILED
    assert "timed out" in coord.session.diagnostics
