"""Serving fleets (tony_tpu/fleet/): autoscaler decision units with an
injectable clock, fleet-state/journal-fold units, router routing +
failover against fake in-process replicas, the daemon fleet lifecycle
e2e on the mini cluster (create → route → scale down → replica-death
replacement), crash recovery re-adopting a fleet without a double
launch, and the `tony fleet ps` live → state-file → history fallback
order."""

import importlib.util
import json
import sys
import threading
import time
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from pathlib import Path

import pytest

from tony_tpu.conf import keys
from tony_tpu.fleet.autoscale import (
    AutoscalePolicy,
    Autoscaler,
    FleetSignals,
)
from tony_tpu.fleet.manager import (
    FleetSpec,
    FleetState,
    discover_replica_addr,
)
from tony_tpu.fleet.router import FleetRouter
from tony_tpu.mini import MiniTonyCluster
from tony_tpu.scheduler import JobState, SchedulerDaemon, SchedulerJournal
from tony_tpu.scheduler import journal as wal

FIXTURES = Path(__file__).resolve().parent / "fixtures"

_spec = importlib.util.spec_from_file_location(
    "fake_serve", FIXTURES / "fake_serve.py"
)
fake_serve = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(fake_serve)


def _wait(cond, timeout_s=90.0, msg="condition never held"):
    deadline = time.monotonic() + timeout_s
    while not cond():
        assert time.monotonic() < deadline, msg
        time.sleep(0.05)


# ---------------------------------------------------------------------------
# Autoscaler units (injectable clock)
# ---------------------------------------------------------------------------
class _Clock:
    def __init__(self):
        self.now = 1_000_000

    def __call__(self):
        return self.now


def _scaler(**pol):
    clock = _Clock()
    return Autoscaler(policy=AutoscalePolicy(**pol), clock_ms=clock), clock


class TestAutoscaler:
    def test_scale_up_needs_sustained_overload_then_cooldown(self):
        a, clock = _scaler(max_replicas=4, scale_up_queue_depth=4,
                           hysteresis_ticks=2, cooldown_ms=15000)
        hot = FleetSignals(ready_replicas=1, queue_depth=9)
        assert a.tick(hot, 1) is None          # tick 1: not sustained yet
        d = a.tick(hot, 1)                     # tick 2: sustained -> +1
        assert d is not None and d.target == 2 and not d.cold_wake
        # Inside the cooldown nothing fires, however hot.
        clock.now += 14_000
        assert a.tick(hot, 2) is None and a.tick(hot, 2) is None
        # Cooldown over: the still-saturated hysteresis fires at once.
        clock.now += 2_000
        assert a.tick(hot, 2).target == 3

    def test_one_cool_tick_resets_hysteresis(self):
        a, _ = _scaler(hysteresis_ticks=2, cooldown_ms=0)
        hot = FleetSignals(ready_replicas=1, queue_depth=9)
        calm = FleetSignals(ready_replicas=1, queue_depth=1,
                            active_slots=4, total_slots=4)
        assert a.tick(hot, 1) is None
        assert a.tick(calm, 1) is None         # blip over: counter resets
        assert a.tick(hot, 1) is None          # needs 2 fresh hot ticks
        assert a.tick(hot, 1).target == 2

    def test_ttft_breach_scales_up(self):
        a, _ = _scaler(ttft_target_ms=500.0, hysteresis_ticks=1,
                       cooldown_ms=0)
        slow = FleetSignals(ready_replicas=2, queue_depth=0,
                            p95_ttft_ms=900.0)
        d = a.tick(slow, 2)
        assert d is not None and d.target == 3 and "ttft" in d.reason

    def test_scale_down_after_sustained_idle_to_min(self):
        a, clock = _scaler(min_replicas=0, scale_down_idle_ms=30000,
                           scale_down_util=0.25, cooldown_ms=0)
        idle = FleetSignals(ready_replicas=2, queue_depth=0,
                            active_slots=0, total_slots=8)
        assert a.tick(idle, 2) is None
        clock.now += 29_000
        assert a.tick(idle, 2) is None
        # A busy blip restarts the idle clock entirely.
        a.tick(FleetSignals(ready_replicas=2, queue_depth=3,
                            active_slots=8, total_slots=8), 2)
        clock.now += 29_000
        assert a.tick(idle, 2) is None
        clock.now += 31_000
        assert a.tick(idle, 2).target == 1
        # ...all the way to zero (scale-to-zero releases the slices).
        clock.now += 31_000
        a.tick(idle, 1)
        clock.now += 31_000
        assert a.tick(FleetSignals(ready_replicas=1, queue_depth=0,
                                   active_slots=0, total_slots=4),
                      1).target == 0

    def test_cold_wake_bypasses_hysteresis_and_cooldown(self):
        a, _ = _scaler(min_replicas=0, hysteresis_ticks=5,
                       cooldown_ms=10 ** 9)
        a._last_action_ms = a.clock_ms()  # mid-cooldown
        d = a.tick(FleetSignals(wake_requested=True), 0)
        assert d is not None and d.cold_wake and d.target == 1
        # Queued work visible at zero replicas also wakes.
        d2 = a.tick(FleetSignals(queue_depth=1), 0)
        assert d2 is not None and d2.cold_wake

    def test_bounds_violations_actuate_immediately(self):
        a, _ = _scaler(min_replicas=1, max_replicas=3)
        assert a.tick(FleetSignals(), 5).target == 3
        assert a.tick(FleetSignals(), 0).target == 1


# ---------------------------------------------------------------------------
# Fleet state + journal fold units
# ---------------------------------------------------------------------------
class TestFleetState:
    def test_next_rid_fills_gaps(self):
        st = FleetState(spec=FleetSpec(name="f", template_dir="/t"))
        assert st.next_rid() == "r0"
        st.replicas = {"r0": "j0", "r2": "j2"}
        assert st.next_rid() == "r1"

    def test_replica_role_split_is_deterministic(self):
        spec = FleetSpec(name="f", template_dir="/t", disaggregated=True,
                         prefill_replicas=1)
        st = FleetState(spec=spec)
        assert st.replica_role("r0") == "prefill"
        assert st.replica_role("r1") == "decode"
        st.spec.disaggregated = False
        assert st.replica_role("r0") == "both"

    def test_spec_and_state_roundtrip(self):
        spec = FleetSpec(name="f", template_dir="/t", desired=2,
                         min_replicas=0, max_replicas=5,
                         disaggregated=True, prefill_replicas=2,
                         router_port=7070)
        st = FleetState(spec=spec, desired=2, replicas={"r0": "j0"})
        back = FleetState.from_json(json.loads(json.dumps(st.to_json())))
        assert back.spec == spec
        assert back.desired == 2 and back.replicas == {"r0": "j0"}


def _rec(seq, kind, **fields):
    return {"seq": seq, "ts_ms": seq, "kind": kind, **fields}


class TestFleetJournalFold:
    def test_fleet_lifecycle_folds(self):
        spec = FleetSpec(name="f1", template_dir="/t", desired=1)
        out = wal.replay(None, [
            _rec(1, wal.J_FLEET_CREATED, fleet="f1",
                 spec=spec.to_json(), desired=1),
            _rec(2, wal.J_REPLICA_LAUNCHED, fleet="f1", replica_id="r0",
                 job_id="job_a", role="both"),
            _rec(3, wal.J_FLEET_SCALED, fleet="f1", to=2,
                 reason="operator", **{"from": 1}),
            _rec(4, wal.J_REPLICA_LAUNCHED, fleet="f1", replica_id="r1",
                 job_id="job_b", role="both"),
            _rec(5, wal.J_REPLICA_RETIRED, fleet="f1", replica_id="r0",
                 job_id="job_a", reason="scale_down"),
        ])
        f = out["fleets"]["f1"]
        assert f["desired"] == 2
        assert f["replicas"] == {"r1": "job_b"}
        assert f["spec"]["name"] == "f1"

    def test_snapshot_fleets_parse_and_tail_overrides(self):
        snapshot = {"journal_seq": 2, "fleets": {
            "f1": {"spec": FleetSpec(name="f1",
                                     template_dir="/t").to_json(),
                   "desired": 2, "replicas": {"r0": "job_a"}},
            "broken": {"desired": 3},  # no spec: dropped, not a crash
        }}
        out = wal.replay(snapshot, [
            _rec(3, wal.J_REPLICA_RETIRED, fleet="f1", replica_id="r0",
                 job_id="job_a", reason="recovery"),
            _rec(4, wal.J_FLEET_SCALED, fleet="f1", to=1,
                 reason="autoscaler", **{"from": 2}),
        ])
        assert set(out["fleets"]) == {"f1"}
        assert out["fleets"]["f1"]["desired"] == 1
        assert out["fleets"]["f1"]["replicas"] == {}


# ---------------------------------------------------------------------------
# Router units against fake in-process replicas
# ---------------------------------------------------------------------------
class _FakeReplica:
    """One in-process serving replica with a switchable failure mode:
    ``ok`` serves, ``die`` drops the connection mid-request (the
    in-flight-death window), ``shed`` answers 429."""

    def __init__(self, models=("default",), queue_depth=0):
        self.models = list(models)
        self.queue_depth = queue_depth
        self.mode = "ok"
        self.hits = 0
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):
                pass

            def _reply(self, code, obj, headers=None):
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                self._reply(200, {
                    "active_slots": 0,
                    "queue_depth": outer.queue_depth,
                    "slots": 4, "draining": False,
                    "models": outer.models,
                })

            def do_POST(self):
                n = int(self.headers.get("Content-Length", "0"))
                body = json.loads(self.rfile.read(n) or b"{}")
                if outer.mode == "die":
                    # In-flight death: request accepted, never answered.
                    self.close_connection = True
                    self.connection.close()
                    return
                if outer.mode == "shed":
                    self._reply(429, {"error": "serving queue full"},
                                {"Retry-After": "1"})
                    return
                outer.hits += 1
                tokens = fake_serve.fake_tokens(
                    body.get("prompt", []),
                    body.get("max_new_tokens", 0), body.get("eos_id"),
                )
                self._reply(200, {"id": "req", "tokens": tokens,
                                  "length": len(tokens),
                                  "served_by": id(outer)})

        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.addr = f"127.0.0.1:{self.httpd.server_address[1]}"
        threading.Thread(target=self.httpd.serve_forever,
                         daemon=True).start()

    def stop(self):
        self.httpd.shutdown()
        self.httpd.server_close()


@pytest.fixture()
def router():
    r = FleetRouter(health_interval_s=3600, wake_timeout_s=0.5,
                    retries=2)
    reps = []

    def add(rid, rep, role="both"):
        reps.append(rep)
        r.add_replica(rid, rep.addr, role=role)
        return rep

    r.start()
    yield r, add
    r.stop()
    for rep in reps:
        rep.stop()


class TestRouter:
    def test_least_queue_depth_and_per_model_routing(self, router):
        r, add = router
        busy = add("r0", _FakeReplica(queue_depth=7))
        idle = add("r1", _FakeReplica(queue_depth=0))
        code, raw, _ = r.route_generate({"prompt": [1], "max_new_tokens": 2})
        assert code == 200 and idle.hits == 1 and busy.hits == 0
        # Per-model routing overrides load: only r0 hosts "m2".
        busy.models = ["default", "m2"]
        r.poll_once()
        code, raw, _ = r.route_generate(
            {"prompt": [1], "max_new_tokens": 2, "model": "m2"}
        )
        assert code == 200 and busy.hits == 1
        sig = r.signals()
        assert sig.ready_replicas == 2 and sig.total_slots == 8

    def test_draining_replica_stops_receiving_new_work(self, router):
        r, add = router
        a = add("r0", _FakeReplica())
        b = add("r1", _FakeReplica())
        r.drain_replica("r0")
        assert r.status()["ready_rids"] == ["r1"]
        for _ in range(3):
            code, _, _ = r.route_generate(
                {"prompt": [2], "max_new_tokens": 1}
            )
            assert code == 200
        assert a.hits == 0 and b.hits == 3

    def test_inflight_replica_death_retries_on_survivor(self, router):
        """The failover satellite: a replica dying with the request in
        flight costs a bounded retry against a survivor, not a client
        error — and the dead replica leaves the rotation."""
        r, add = router
        dead = add("r0", _FakeReplica(queue_depth=0))
        live = add("r1", _FakeReplica(queue_depth=5))
        dead.mode = "die"  # picked first (lower queue depth), then dies
        body = {"prompt": [3, 4], "max_new_tokens": 4}
        code, raw, _ = r.route_generate(body)
        assert code == 200
        assert json.loads(raw)["tokens"] == fake_serve.fake_tokens(
            [3, 4], 4
        )
        assert live.hits == 1
        snap = r.registry.snapshot()["counters"]
        assert snap["tony_fleet_router_retries_total"] == 1
        # Out of rotation: subsequent (and queued) requests land on the
        # survivor directly, no repeat retry.
        assert r.status()["ready_rids"] == ["r1"]
        code, _, _ = r.route_generate(body)
        assert code == 200 and live.hits == 2
        assert r.registry.snapshot()["counters"][
            "tony_fleet_router_retries_total"] == 1

    def test_429_retries_elsewhere_then_surfaces_with_retry_after(
        self, router,
    ):
        r, add = router
        shedding = add("r0", _FakeReplica(queue_depth=0))
        other = add("r1", _FakeReplica(queue_depth=5))
        shedding.mode = "shed"
        code, _, _ = r.route_generate({"prompt": [5], "max_new_tokens": 1})
        assert code == 200 and other.hits == 1   # shed here, admit there
        other.mode = "shed"
        code, raw, headers = r.route_generate(
            {"prompt": [5], "max_new_tokens": 1}
        )
        assert code == 429 and headers.get("Retry-After") == "1"
        assert r.registry.snapshot()["counters"][
            "tony_fleet_router_shed_total"] == 1

    def test_cold_wake_raised_for_empty_fleet(self):
        woke = threading.Event()
        r = FleetRouter(wake_timeout_s=0.3, on_cold_wake=woke.set)
        r.start()
        try:
            code, _, _ = r.route_generate(
                {"prompt": [1], "max_new_tokens": 1}
            )
            assert code == 503          # nothing came up within the hold
            assert woke.is_set()
            assert r.signals().wake_requested
            assert r.consume_wake() is True
            assert r.consume_wake() is False
        finally:
            r.stop()


# ---------------------------------------------------------------------------
# Daemon fleet lifecycle e2e (mini cluster, jax-free fake replicas)
# ---------------------------------------------------------------------------
@pytest.fixture()
def cluster(tmp_path):
    with MiniTonyCluster(tmp_path) as c:
        yield c


def _sched_conf(cluster, **kv):
    conf = cluster.base_conf()
    conf.set(keys.K_SCHED_TICK_MS, 50)
    for k, v in kv.items():
        conf.set(k, v)
    return conf


def _fleet_template(cluster, **kv):
    conf = cluster.base_conf()
    conf.set(keys.K_EXECUTES, str(FIXTURES / "fake_serve.py"))
    conf.set(keys.K_PYTHON_BINARY, sys.executable)
    conf.set(keys.instances_key("worker"), 1)
    conf.set(keys.instances_key("ps"), 0)
    conf.set(keys.K_FLEET_AUTOSCALE, False)
    for k, v in kv.items():
        conf.set(k, v)
    return conf


def _journal_kinds(daemon, kind, fleet=None):
    return [r for r in SchedulerJournal.load(
        daemon.base_dir / wal.JOURNAL_FILE
    ) if r["kind"] == kind and (fleet is None or r.get("fleet") == fleet)]


def _ready(daemon, name):
    doc = daemon.fleet_json(name) or {}
    return (doc.get("router") or {}).get("ready", 0)


def test_fleet_create_route_scale_down_and_replace(cluster):
    """The fleet lifecycle acceptance, jax-free: create launches the
    replicas as pool jobs, the router serves once their endpoints bind,
    an operator scale-down retires the highest rid gracefully (its job
    SUCCEEDs via /shutdown), and a killed replica's record folds out
    with a journaled replacement launch."""
    daemon = cluster.start_scheduler(
        _sched_conf(cluster, **{keys.K_SCHED_MAX_SLICES: 3}),
    )
    doc = daemon.create_fleet(
        "lmfleet",
        _fleet_template(cluster, **{keys.K_FLEET_MAX_REPLICAS: 3}),
        replicas=2,
    )
    assert doc["desired"] == 2
    _wait(lambda: _ready(daemon, "lmfleet") == 2, 90,
          "replicas never entered rotation")

    # Route through the router's own HTTP port: deterministic fake
    # tokens prove a replica actually served it.
    router_addr = daemon.fleet_json("lmfleet")["router"]["addr"]
    body = json.dumps({"prompt": [1, 2, 3],
                       "max_new_tokens": 5}).encode()
    req = urllib.request.Request(
        f"http://{router_addr}/generate", data=body,
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=10) as resp:
        out = json.loads(resp.read())
    assert out["tokens"] == fake_serve.fake_tokens([1, 2, 3], 5)

    # Operator scale-down: r1 (highest rid) retires GRACEFULLY — the
    # /shutdown path drains and the job SUCCEEDs, not KILLED.
    r1_job = daemon.fleet_json("lmfleet")["replicas"]["r1"]
    daemon.scale_fleet("lmfleet", 1)
    _wait(lambda: set(daemon.fleet_json("lmfleet")["replicas"]) == {"r0"},
          60, "scale-down never retired r1")
    assert daemon.wait_job(r1_job, 60) is JobState.SUCCEEDED
    retired = _journal_kinds(daemon, wal.J_REPLICA_RETIRED, "lmfleet")
    assert [r["replica_id"] for r in retired] == ["r1"]
    assert retired[0]["reason"] == "scale_down"

    # Replica death: kill r0's job — reconcile folds the dead record
    # out and journals a replacement launch (same rid, fresh job).
    r0_job = daemon.fleet_json("lmfleet")["replicas"]["r0"]
    daemon.kill(r0_job)
    _wait(lambda: daemon.fleet_json("lmfleet")["replicas"].get("r0")
          not in (None, r0_job), 60, "replacement never launched")
    _wait(lambda: _ready(daemon, "lmfleet") == 1, 90,
          "replacement never entered rotation")
    launches = _journal_kinds(daemon, wal.J_REPLICA_LAUNCHED, "lmfleet")
    assert len(launches) == 3       # r0, r1, r0-replacement
    assert len({r["job_id"] for r in launches}) == 3
    assert [r["replica_id"]
            for r in _journal_kinds(daemon, wal.J_REPLICA_RETIRED,
                                    "lmfleet")] == ["r1", "r0"]

    # The fleet shows up on the scheduler API.
    api = f"127.0.0.1:{daemon.http_server.port}"
    with urllib.request.urlopen(f"http://{api}/api/fleets",
                                timeout=5) as resp:
        fleets = json.loads(resp.read())["fleets"]
    assert fleets["lmfleet"]["desired"] == 1
    events = [e["kind"] for e in daemon.events.to_dicts()]
    for kind in ("fleet_created", "fleet_scaled", "replica_launched",
                 "replica_retired"):
        assert kind in events


def test_recovery_readopts_fleet_without_double_launch(cluster):
    """Crash-recovery acceptance: a daemon dying with a live detached
    replica re-adopts the fleet from the journal — same rid -> job_id
    binding, the surviving replica re-enters rotation, and no second
    replica_launched record ever lands."""
    base = cluster.base_dir / "sched"
    conf = _sched_conf(cluster, **{keys.K_SCHED_MAX_SLICES: 2,
                                   keys.K_SCHED_DETACHED: True})
    d1 = SchedulerDaemon(base, conf=conf).start(serve_http=False)
    d1.create_fleet("f1", _fleet_template(cluster), replicas=1)
    _wait(lambda: _ready(d1, "f1") == 1, 90, "replica never ready")
    r0_job = d1.fleet_json("f1")["replicas"]["r0"]

    # SIGKILL-shaped crash: loop stopped dead, flock dropped, no clean
    # shutdown — the detached replica keeps serving.
    d1._stop.set()
    d1._wake.set()
    if d1._thread is not None:
        d1._thread.join(timeout=30)
    d1.election.abandon()

    d2 = SchedulerDaemon(base, conf=conf).start(serve_http=False)
    try:
        recovered = [e for e in d2.events.to_dicts()
                     if e["kind"] == "scheduler_recovered"]
        assert len(recovered) == 1 and recovered[0]["fleets"] == 1
        assert d2.fleet_json("f1")["replicas"] == {"r0": r0_job}
        assert d2.job(r0_job).state is JobState.RUNNING
        _wait(lambda: _ready(d2, "f1") == 1, 90,
              "recovered replica never re-entered rotation")
        # The WHOLE journal (both lives) holds exactly one launch.
        launches = _journal_kinds(d2, wal.J_REPLICA_LAUNCHED, "f1")
        assert len(launches) == 1 and launches[0]["job_id"] == r0_job
        # And the recovered router still routes.
        addr = d2.fleet_json("f1")["router"]["addr"]
        req = urllib.request.Request(
            f"http://{addr}/generate",
            data=json.dumps({"prompt": [9], "max_new_tokens": 3}).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=10) as resp:
            assert json.loads(resp.read())["tokens"] == \
                fake_serve.fake_tokens([9], 3)
    finally:
        d2.shutdown()


def test_replica_addr_discovery(tmp_path):
    assert discover_replica_addr(tmp_path / "missing") is None
    app = tmp_path / "app"
    (app / "logs").mkdir(parents=True)
    assert discover_replica_addr(app) is None
    (app / "logs" / "serving-fake-0.addr").write_text("127.0.0.1:7001\n")
    assert discover_replica_addr(app) == "127.0.0.1:7001"


# ---------------------------------------------------------------------------
# CLI: `tony fleet ps` fallback order (live -> state-file -> history)
# ---------------------------------------------------------------------------
def test_fleet_ps_fallback_order(cluster, capsys):
    """Pins the documented fallback chain: the live API while the
    daemon runs, the atomically-published scheduler-state.json once it
    is gone, and the job history as the last resort."""
    from tony_tpu.client.cli import fleet_cmd

    daemon = cluster.start_scheduler(
        # Zero slots: the replica job stays QUEUED — cheap and stable.
        _sched_conf(cluster, **{keys.K_SCHED_MAX_SLICES: 0}),
    )
    daemon.create_fleet("psfleet", _fleet_template(cluster), replicas=1)
    base_dir = str(daemon.base_dir)
    state_file = daemon.base_dir / "scheduler-state.json"
    _wait(lambda: state_file.is_file()
          and "psfleet" in state_file.read_text(), 30,
          "fleet never published to the state file")

    # 1) live API.
    assert fleet_cmd(["ps", "--scheduler-dir", base_dir]) == 0
    out = capsys.readouterr().out
    assert "(live)" in out and "psfleet" in out and "r0" in out

    # 2) daemon gone -> state file.
    cluster.shutdown()
    assert fleet_cmd(["ps", "--scheduler-dir", base_dir]) == 0
    out = capsys.readouterr().out
    assert "(state-file)" in out and "psfleet" in out

    # 3) no state file either -> job history.
    state_file.unlink()
    (Path(base_dir) / "scheduler.addr").unlink(missing_ok=True)
    assert fleet_cmd([
        "ps", "--scheduler-dir", base_dir,
        "--history-location", str(cluster.history_dir),
    ]) == 0
    out = capsys.readouterr().out
    assert "history fallback" in out

    # status (unlike ps) stops at the state-file rung.
    assert fleet_cmd(["status", "--scheduler-dir", base_dir]) == 1


# ---------------------------------------------------------------------------
# Slow e2e: a REAL lm_serve fleet through the daemon
# ---------------------------------------------------------------------------
@pytest.mark.slow
def test_real_lm_serve_fleet_token_parity(cluster):
    """The heavyweight acceptance: 3 examples/lm_serve.py replicas
    (fresh seed-0 weights, real jax engines) launched as fleet jobs,
    routed through the fleet router's HTTP front door under concurrent
    load — every response token-for-token equal to a single-request
    ``generate`` on locally rebuilt identical weights, with the load
    actually spread across replicas."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tony_tpu.models import generate, init_params

    repo = Path(__file__).resolve().parent.parent
    daemon = cluster.start_scheduler(
        _sched_conf(cluster, **{keys.K_SCHED_MAX_SLICES: 3}),
    )
    template = _fleet_template(cluster, **{
        keys.K_EXECUTES: str(repo / "examples" / "lm_serve.py"),
        keys.K_FRAMEWORK: "jax",
        keys.K_FLEET_MAX_REPLICAS: 3,
        keys.K_TASK_PARAMS: ("--max-seq 96 --seed 0 --slots 2 "
                             "--prefill-chunk 8 --decode-window 2"),
    })
    daemon.create_fleet("jaxfleet", template, replicas=3)
    _wait(lambda: _ready(daemon, "jaxfleet") == 3, 300,
          "lm_serve replicas never entered rotation")
    router_addr = daemon.fleet_json("jaxfleet")["router"]["addr"]

    # The reference: identical fresh weights (lm_train default model
    # flags at max_seq 96, seed 0) through single-request generate.
    import argparse

    sys.path.insert(0, str(repo / "examples"))
    try:
        import lm_train
    finally:
        sys.path.pop(0)
    p = argparse.ArgumentParser()
    lm_train.add_model_args(p)
    cfg = lm_train.model_config_from_args(p.parse_args([]), max_seq=96)
    params = init_params(jax.random.key(0), cfg)

    rng = np.random.default_rng(11)
    prompts = [rng.integers(1, cfg.vocab_size,
                            int(n)).astype(np.int32).tolist()
               for n in (4, 7, 5, 9, 6, 8)]
    wants = [np.asarray(generate(
        params, jnp.asarray(pr, jnp.int32)[None], cfg, 6
    ))[0] for pr in prompts]

    outs: list = [None] * len(prompts)

    def _client(i):
        body = json.dumps({"prompt": prompts[i],
                           "max_new_tokens": 6}).encode()
        req = urllib.request.Request(
            f"http://{router_addr}/generate", data=body,
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=300) as resp:
            outs[i] = json.loads(resp.read())

    # Concurrent clients so least-queue-depth routing actually spreads
    # (sequential idle-fleet requests would all tie-break to one rid).
    threads = [threading.Thread(target=_client, args=(i,))
               for i in range(len(prompts))]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=300)

    for i, want in enumerate(wants):
        assert outs[i] is not None, f"request {i} never completed"
        np.testing.assert_array_equal(
            np.asarray(outs[i]["tokens"]), want,
            err_msg=f"fleet response {i} diverged from single-request "
                    f"generate",
        )

    # Load spread: with 6 concurrent requests against 3 two-slot
    # replicas, at least two replicas must have retired work.
    served = 0
    for rep in daemon.fleet_json("jaxfleet")["router"]["replicas"]:
        with urllib.request.urlopen(
            f"http://{rep['addr']}/healthz", timeout=30
        ) as resp:
            health = json.loads(resp.read())
        assert health["role"] == "both"  # lm_serve default extra_health
        served += int(health["retired"] > 0)
    assert served >= 2, "all requests landed on one replica"
