"""Self-healing actuation tests (coordinator/healing.py): the policy
config, infra-exit classification, the session's gang-patch surgery
(incarnation fencing + generation-gated barrier), liveness/aggregator
incarnation fencing, MAD straggler scoring under gang-size change, the
``degrade_task`` / ``kill_task after_steps`` chaos actions, the goodput
ledger's ``healing`` category, the HealingController state machine
against a fake coordinator, doctor rule TONY-D013 — plus the two slow
chaos acceptance e2e runs (evict-and-replace beating the non-healing
baseline on wall AND wasted chip-seconds; elastic shrink to n−1 under a
planner-chosen sharding)."""

import json
import re
import sys
import time
from pathlib import Path
from types import SimpleNamespace

import pytest

from tony_tpu import constants
from tony_tpu.analysis import postmortem
from tony_tpu.conf import keys
from tony_tpu.conf.configuration import TonyConfiguration
from tony_tpu.coordinator.healing import (
    HealConfig,
    HealingController,
    choose_shrink_plan,
    is_infra_exit,
)
from tony_tpu.coordinator.liveness import LivenessMonitor
from tony_tpu.coordinator.session import SessionStatus, TaskStatus, TonySession
from tony_tpu.mini import MiniTonyCluster
from tony_tpu.observability import events as obs_events
from tony_tpu.observability.aggregator import MetricsAggregator
from tony_tpu.observability.goodput import CATEGORIES, GoodputLedger
from tony_tpu.observability.health import HealthConfig, HealthMonitor
from tony_tpu.observability.metrics import MetricsRegistry
from tony_tpu.resilience.faults import (
    DEGRADE_TASK,
    FaultInjector,
    FaultPlan,
    FaultPlanError,
    StepFaults,
)

FIXTURES = Path(__file__).resolve().parent / "fixtures"


class FakeClock:
    def __init__(self, t=100.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, s):
        self.t += s


def _conf(workers=3):
    conf = TonyConfiguration()
    conf.set(keys.instances_key("worker"), workers)
    conf.set(keys.instances_key("ps"), 0)
    return conf


def _session(workers=3, register=True):
    session = TonySession(_conf(workers), session_id=1)
    session.status = SessionStatus.RUNNING
    if register:
        for i in range(workers):
            session.register_task(f"worker:{i}", f"h{i}:500{i}")
            session.get_task_by_id(f"worker:{i}").handle = object()
    return session


def _snap(gauges=None, counters=None, histograms=None):
    return {
        "ts_ms": int(time.time() * 1000),
        "gauges": gauges or {},
        "counters": counters or {},
        "histograms": histograms or {},
    }


# ---------------------------------------------------------------------------
# Policy config + infra-exit classification
# ---------------------------------------------------------------------------
class TestHealConfig:
    def test_defaults_disabled(self):
        cfg = HealConfig.from_conf(TonyConfiguration())
        assert cfg.enabled is False
        assert cfg.max_evictions == 2
        assert cfg.min_shrink_fraction == 0.5
        assert cfg.speculative is False

    def test_reads_conf_keys(self):
        conf = TonyConfiguration()
        conf.set(keys.K_HEAL_ENABLED, "true")
        conf.set(keys.K_HEAL_CONFIRM_WINDOW_MS, 500)
        conf.set(keys.K_HEAL_MAX_EVICTIONS, 7)
        conf.set(keys.K_HEAL_MIN_SHRINK_FRACTION, 0.25)
        conf.set(keys.K_HEAL_SPECULATIVE, "true")
        conf.set(keys.K_HEALTH_STRAGGLER_THRESHOLD, 2.5)
        cfg = HealConfig.from_conf(conf)
        assert cfg.enabled and cfg.speculative
        assert cfg.confirm_window_ms == 500
        assert cfg.max_evictions == 7
        assert cfg.min_shrink_fraction == 0.25
        assert cfg.straggler_threshold == 2.5

    def test_every_heal_key_has_registered_default(self):
        for key in (keys.K_HEAL_ENABLED, keys.K_HEAL_CONFIRM_WINDOW_MS,
                    keys.K_HEAL_MAX_EVICTIONS,
                    keys.K_HEAL_MIN_SHRINK_FRACTION,
                    keys.K_HEAL_SPECULATIVE,
                    keys.K_HEAL_SPECULATIVE_DELAY_MS):
            assert key in keys.DEFAULTS, key


class TestIsInfraExit:
    @pytest.mark.parametrize("code,reason,expected", [
        (-9, None, True),            # Popen signal death
        (-15, None, True),
        (137, None, True),           # 128+SIGKILL shell convention
        (143, None, True),           # 128+SIGTERM
        (0, "preempted", True),      # backend-reported preemption
        (1, None, False),            # plain user bug
        (2, None, False),
        (126, None, False),          # not executable
        (127, None, False),          # not found
        (255, None, False),          # 255-128=127 is not a nameable signal
    ])
    def test_table(self, code, reason, expected):
        assert is_infra_exit(code, reason) is expected


class TestChooseShrinkPlan:
    def test_pins_dp_to_survivor_devices(self):
        plan = choose_shrink_plan(2)
        assert plan is not None
        assert plan.mesh_spec.dp == 2
        assert plan.key() == "dp2.pp1.ep1.sp1.tp1"

    def test_single_device_still_plans(self):
        plan = choose_shrink_plan(1)
        assert plan is not None and plan.mesh_spec.dp == 1


# ---------------------------------------------------------------------------
# Session gang patches: incarnation fencing + generation-gated barrier
# ---------------------------------------------------------------------------
class TestSessionGangPatch:
    def test_evict_reopens_registration_under_bumped_incarnation(self):
        session = _session()
        task = session.evict_task("worker:1")
        assert task.incarnation == 1
        assert task.host_port is None
        assert task.status is TaskStatus.SCHEDULED

    def test_stale_incarnation_registration_dropped(self):
        session = _session()
        session.evict_task("worker:1")
        # the zombie copy (incarnation 0) re-dials in: dropped
        assert not session.register_task("worker:1", "zombie:1", 0)
        assert session.get_task_by_id("worker:1").host_port is None
        # the replacement (incarnation 1) takes the identity
        assert session.register_task("worker:1", "new:1", 1)
        assert session.get_task_by_id("worker:1").host_port == "new:1"

    def test_higher_incarnation_adopted_first_to_register_wins(self):
        # speculation: the task never registered; the backup copy
        # (incarnation 1) dials in first and takes the identity
        session = _session(register=False)
        assert session.register_task("worker:2", "backup:9", 1)
        task = session.get_task_by_id("worker:2")
        assert task.incarnation == 1
        assert task.host_port == "backup:9"
        # the original (incarnation 0) is now the zombie
        assert not session.register_task("worker:2", "orig:9", 0)
        assert task.host_port == "backup:9"

    def test_begin_patch_witholds_spec_until_everyone_reregisters(self):
        session = _session()
        assert session.cluster_spec() is not None
        generation = session.begin_patch()
        assert generation == 1
        assert session.cluster_spec() is None  # barrier re-armed
        # survivors re-register one by one; spec returns only when ALL
        # live tasks have confirmed the new generation
        for i in range(3):
            assert session.cluster_spec() is None
            assert session.register_task(f"worker:{i}", f"h{i}:500{i}")
        spec = session.cluster_spec()
        assert spec == {"worker": ["h0:5000", "h1:5001", "h2:5002"]}

    def test_remove_task_renumbers_dense_but_keeps_ids(self):
        session = _session()
        removed = session.remove_task("worker:1")
        assert removed is not None and removed.id == "worker:1"
        assert [t.id for t in session.removed] == ["worker:1"]
        # survivors keep their ORIGINAL ids/indices...
        assert session.get_task_by_id("worker:2") is not None
        assert session.get_task("worker", 2).id == "worker:2"
        assert session.get_task("worker", 1) is None
        # ...but the runtime view is dense
        assert session.runtime_assignment("worker:0") == (0, 2)
        assert session.runtime_assignment("worker:2") == (1, 2)
        session.begin_patch()
        for tid, hp in (("worker:0", "h0:5000"), ("worker:2", "h2:5002")):
            session.register_task(tid, hp)
        assert session.cluster_spec() == {"worker": ["h0:5000", "h2:5002"]}

    def test_cannot_remove_last_task(self):
        session = _session(workers=1)
        assert session.remove_task("worker:0") is None

    def test_generation_echo_fences_superseded_confirms(self):
        # a survivor's registration confirms the generation it was told
        # about; if a second patch folded in mid-flight, the stale echo
        # must NOT read as confirming the newer patch
        session = _session()
        session.begin_patch()   # gen 1 (eviction)
        session.begin_patch()   # gen 2 (folded shrink renumber)
        assert session.register_task("worker:0", "h0:5000", 0,
                                     generation=1)
        assert session.get_task_by_id("worker:0").generation == 1
        for i in (1, 2):
            session.register_task(f"worker:{i}", f"h{i}:500{i}", 0,
                                  generation=2)
        assert session.cluster_spec() is None  # worker:0 still owes gen 2
        session.register_task("worker:0", "h0:5000", 0, generation=2)
        assert session.cluster_spec() is not None
        # an echo AHEAD of the gang (can't legitimately happen) clamps
        session.begin_patch()   # gen 3
        session.register_task("worker:0", "h0:5000", 0, generation=99)
        assert session.get_task_by_id("worker:0").generation == 3

    def test_settled_identity_rejects_late_loser_registration(self):
        # the original copy won the speculation race (REGISTERED at
        # incarnation 0); the dying backup's in-flight registration
        # (incarnation 1) must not hijack the settled identity — it
        # would overwrite the live address and fence the winner out
        session = _session()
        assert not session.register_task("worker:2", "loser:9", 1)
        task = session.get_task_by_id("worker:2")
        assert task.incarnation == 0
        assert task.host_port == "h2:5002"
        # the winner's own traffic still passes the fence
        assert session.register_task("worker:2", "h2:5002", 0) is False
        assert task.host_port == "h2:5002"

    def test_completed_task_exempt_from_patched_barrier(self):
        # a worker that already FINISHED can never re-register into a
        # patched generation — it must not park the barrier forever
        session = _session()
        session.on_task_completed("worker", 2, 0)
        session.begin_patch()
        for i in range(2):
            session.register_task(f"worker:{i}", f"h{i}:500{i}")
        spec = session.cluster_spec()
        assert spec == {"worker": ["h0:5000", "h1:5001", "h2:5002"]}


class TestLivenessIncarnationFence:
    def _monitor(self):
        return LivenessMonitor(
            heartbeat_interval_ms=100, max_missed_heartbeats=5,
            on_expired=lambda tid: None,
        )

    def test_stale_incarnation_ping_fenced(self):
        mon = self._monitor()
        mon.register("worker:1", incarnation=1)
        assert not mon.receive_ping("worker:1", incarnation=0)
        assert mon.receive_ping("worker:1", incarnation=1)

    def test_default_incarnation_compatible(self):
        mon = self._monitor()
        mon.register("worker:0")
        assert mon.receive_ping("worker:0")

    def test_unregister_clears_incarnation(self):
        mon = self._monitor()
        mon.register("worker:1", incarnation=3)
        mon.unregister("worker:1")
        assert not mon.receive_ping("worker:1", incarnation=3)


class TestAggregatorIncarnationReset:
    def test_reset_task_drops_series_and_latest(self):
        agg = MetricsAggregator()
        agg.ingest("worker:1", _snap(gauges={"step_time_ms": 80.0},
                                     counters={"train_steps_total": 9}))
        agg.ingest("worker:2", _snap(gauges={"step_time_ms": 5.0}))
        agg.reset_task("worker:1")
        assert "worker:1" not in agg.to_json()["tasks"]
        assert "worker:2" in agg.to_json()["tasks"]
        # the replacement's first snapshot starts a fresh series
        agg.ingest("worker:1", _snap(gauges={"step_time_ms": 5.0}))
        assert agg.to_json()["tasks"]["worker:1"]["gauges"][
            "step_time_ms"] == 5.0

    def test_latest_counter_feeds_step_triggered_faults(self):
        agg = MetricsAggregator()
        agg.ingest("worker:0", _snap(counters={"train_steps_total": 4}))
        agg.ingest("worker:1", _snap(counters={"train_steps_total": 7}))
        agg.ingest("worker:2", _snap(gauges={"loss": 1.0}))
        assert agg.latest_counter("train_steps_total") == {
            "worker:0": 4.0, "worker:1": 7.0,
        }


# ---------------------------------------------------------------------------
# MAD straggler scoring under gang-size change (satellite)
# ---------------------------------------------------------------------------
class TestHealthGangChange:
    def _monitor(self, clock, **overrides):
        overrides.setdefault("heartbeat_jitter_factor", 1000.0)
        cfg = HealthConfig(
            heartbeat_interval_ms=100, alert_cooldown_ms=10_000,
            **overrides,
        )
        alerts = []
        return HealthMonitor(cfg, emit=lambda **kw: alerts.append(kw),
                             clock=clock), alerts

    def test_score_stable_when_nonoutlier_removed(self):
        clock = FakeClock()
        mon, _ = self._monitor(clock)
        for tid, st in (("w:0", 5.0), ("w:1", 5.0), ("w:2", 5.0),
                        ("w:3", 80.0)):
            mon.observe(tid, _snap(gauges={"step_time_ms": st}))
        before = mon.straggler_scores()["w:3"]
        mon.remove_task("w:1")  # elastic shrink takes a healthy task
        for tid, st in (("w:0", 5.0), ("w:2", 5.0), ("w:3", 80.0)):
            mon.observe(tid, _snap(gauges={"step_time_ms": st}))
        after = mon.straggler_scores()
        assert "w:1" not in after
        assert after["w:3"] > 3.0, "outlier must survive the n→n−1 rescore"
        assert after["w:3"] == pytest.approx(before, rel=0.5)

    def test_removing_the_outlier_clears_the_fleet(self):
        clock = FakeClock()
        mon, _ = self._monitor(clock)
        for tid, st in (("w:0", 5.0), ("w:1", 5.0), ("w:2", 5.0),
                        ("w:3", 80.0)):
            mon.observe(tid, _snap(gauges={"step_time_ms": st}))
        mon.remove_task("w:3")
        for tid, st in (("w:0", 5.0), ("w:1", 5.0), ("w:2", 5.0)):
            mon.observe(tid, _snap(gauges={"step_time_ms": st}))
        assert all(s == 0.0 for s in mon.straggler_scores().values())

    def test_replacement_rejoin_resets_cooldown(self):
        clock = FakeClock()
        mon, alerts = self._monitor(clock)
        for tid, st in (("w:0", 5.0), ("w:1", 5.0), ("w:2", 80.0)):
            mon.observe(tid, _snap(gauges={"step_time_ms": st}))
        assert [a["task"] for a in alerts
                if a["detector"] == "straggler"] == ["w:2"]
        # still inside the 10s cooldown: the same task cannot re-alert
        mon.observe("w:2", _snap(gauges={"step_time_ms": 90.0}))
        assert len([a for a in alerts if a["detector"] == "straggler"]) == 1
        # eviction removes the task; its REPLACEMENT (same id, new
        # machine) rejoins and its first genuine anomaly must not be
        # swallowed by the evicted copy's cooldown window
        mon.reset_task("w:2")
        for tid, st in (("w:0", 5.0), ("w:1", 5.0), ("w:2", 85.0)):
            mon.observe(tid, _snap(gauges={"step_time_ms": st}))
        assert len([a for a in alerts if a["detector"] == "straggler"]) == 2

    def test_no_self_alert_storm_mid_patch(self):
        clock = FakeClock()
        mon, alerts = self._monitor(clock, stall_timeout_ms=1000)
        for tid in ("w:0", "w:1", "w:2"):
            mon.observe(tid, _snap(gauges={"step_time_ms": 5.0},
                                   counters={"train_steps_total": 50}))
        mon.begin_patch()
        # mid-patch the survivors' user processes are parked on purpose:
        # stale step walls + frozen counters must not read as a fleet
        # incident, however long the surgery takes
        clock.advance(30.0)
        for tid, st in (("w:0", 5.0), ("w:1", 5.0), ("w:2", 400.0)):
            mon.observe(tid, _snap(gauges={"step_time_ms": st},
                                   counters={"train_steps_total": 50}))
        assert [a for a in alerts
                if a["detector"] in ("straggler", "progress_stall")] == []
        mon.end_patch()
        # post-patch the restarted processes' counters BEGIN BELOW the
        # stale totals — a rebaseline, not a stall, not a straggler
        for tid in ("w:0", "w:1", "w:2"):
            mon.observe(tid, _snap(gauges={"step_time_ms": 5.0},
                                   counters={"train_steps_total": 2}))
        clock.advance(0.5)
        for tid in ("w:0", "w:1", "w:2"):
            mon.observe(tid, _snap(gauges={"step_time_ms": 5.0},
                                   counters={"train_steps_total": 3}))
        assert [a for a in alerts
                if a["detector"] in ("straggler", "progress_stall")] == []

    def test_end_patch_clears_stored_straggler_scores(self):
        # straggler_scores() feeds the confirm window every tick: a
        # stale pre-patch score must not survive the re-baseline and
        # confirm-evict a healthy restarted survivor
        clock = FakeClock()
        mon, _ = self._monitor(clock)
        for tid, st in (("w:0", 5.0), ("w:1", 5.0), ("w:2", 80.0)):
            mon.observe(tid, _snap(gauges={"step_time_ms": st}))
        assert mon.straggler_scores()["w:2"] > 3.0
        mon.begin_patch()
        mon.end_patch()
        assert all(s == 0.0 for s in mon.straggler_scores().values())

    def test_patch_depth_nests(self):
        clock = FakeClock()
        mon, alerts = self._monitor(clock)
        mon.begin_patch()
        mon.end_patch()
        for tid, st in (("w:0", 5.0), ("w:1", 5.0), ("w:2", 80.0)):
            mon.observe(tid, _snap(gauges={"step_time_ms": st}))
        assert [a["task"] for a in alerts
                if a["detector"] == "straggler"] == ["w:2"]


# ---------------------------------------------------------------------------
# Fault actions: degrade_task + kill_task after_steps (satellite)
# ---------------------------------------------------------------------------
class TestDegradeAndStepKillFaults:
    def test_parse_degrade_task(self):
        plan = FaultPlan.parse(json.dumps({"faults": [
            {"action": "degrade_task", "target": "worker:2", "ms": 400,
             "after_steps": 2, "count": 100},
        ]}))
        (spec,) = plan.specs
        assert spec.action == DEGRADE_TASK
        assert spec.after_steps == 2 and spec.ms == 400

    def test_degrade_requires_nonzero_ms(self):
        with pytest.raises(FaultPlanError, match="ms must be nonzero"):
            FaultPlan.parse(json.dumps({"faults": [
                {"action": "degrade_task", "target": "worker:1", "ms": 0},
            ]}))

    def test_degrade_after_steps_zero_means_from_first_step(self):
        plan = FaultPlan.parse(json.dumps({"faults": [
            {"action": "degrade_task", "target": "worker:1", "ms": 10,
             "after_steps": 0},
        ]}))
        assert plan.specs[0].after_steps == 0

    def test_degrade_rejects_any_non_chief(self):
        with pytest.raises(FaultPlanError, match="concrete"):
            FaultPlan.parse(json.dumps({"faults": [
                {"action": "degrade_task", "target": "any_non_chief",
                 "ms": 10},
            ]}))

    def test_kill_after_steps_parses_and_is_exclusive(self):
        plan = FaultPlan.parse(json.dumps({"faults": [
            {"action": "kill_task", "target": "worker:1", "after_steps": 5},
        ]}))
        assert plan.specs[0].after_steps == 5
        with pytest.raises(FaultPlanError, match="exactly one trigger"):
            FaultPlan.parse(json.dumps({"faults": [
                {"action": "kill_task", "target": "worker:1",
                 "after_steps": 5, "after_ms": 100},
            ]}))

    def test_kill_after_steps_zero_rejected(self):
        # train_steps_total starts advancing at 1: a 0 trigger would
        # never fire (degrade_task's 0 floor is deliberate, see parse)
        with pytest.raises(FaultPlanError, match="after_steps"):
            FaultPlan.parse(json.dumps({"faults": [
                {"action": "kill_task", "target": "worker:1",
                 "after_steps": 0},
            ]}))

    def test_step_kills_fire_once_at_threshold(self):
        plan = FaultPlan.parse(json.dumps({"faults": [
            {"action": "kill_task", "target": "worker:1", "after_steps": 5},
        ]}))
        inj = FaultInjector(plan)
        assert inj.step_kills(1, {"worker:1": 3.0}) == []
        assert inj.step_kills(1, {"worker:2": 50.0}) == []  # wrong task
        assert inj.step_kills(1, {"worker:1": 5.0}) == ["worker:1"]
        assert inj.step_kills(1, {"worker:1": 6.0}) == []  # one-shot

    def test_step_faults_sleep_window(self):
        plan = FaultPlan.parse(json.dumps({"faults": [
            {"action": "degrade_task", "target": "worker:1", "ms": 50,
             "after_steps": 2, "count": 3},
        ]}))
        sleeps = []
        faults = StepFaults(plan, "worker:1", sleep=sleeps.append)
        for step in range(1, 8):
            faults.maybe_degrade(step)
        # steps 3,4,5 degraded (after_steps=2, count=3), then exhausted
        assert sleeps == [0.05, 0.05, 0.05]

    def test_step_faults_scope(self):
        plan = FaultPlan.parse(json.dumps({"faults": [
            {"action": "degrade_task", "target": "worker:1", "ms": 50},
        ]}))
        assert not StepFaults(plan, "worker:2").active  # other task
        assert not StepFaults(plan, "worker:1", incarnation=1).active
        assert StepFaults(plan, "worker:1").active

    def test_step_faults_from_env_respects_incarnation(self, monkeypatch):
        from tony_tpu.resilience import faults as faults_mod

        plan = json.dumps({"faults": [
            {"action": "degrade_task", "target": "worker:1", "ms": 50},
        ]})
        monkeypatch.setenv(constants.TONY_FAULT_PLAN, plan)
        monkeypatch.setenv(constants.JOB_NAME, "worker")
        monkeypatch.setenv(constants.TASK_INDEX, "1")
        monkeypatch.setenv(constants.TONY_TASK_INCARNATION, "1")
        # both process-lifetime caches must reset: the plan parse
        # (_env_plan, shared with io/checkpoint faults) and this
        # consumer's own singleton
        monkeypatch.setattr(faults_mod, "_env_plan", None)
        monkeypatch.setattr(faults_mod, "_step_faults", False)
        assert faults_mod.step_faults_from_env() is None
        # the original incarnation 0 IS degraded
        monkeypatch.setenv(constants.TONY_TASK_INCARNATION, "0")
        monkeypatch.setattr(faults_mod, "_env_plan", None)
        monkeypatch.setattr(faults_mod, "_step_faults", False)
        assert faults_mod.step_faults_from_env() is not None
        monkeypatch.setattr(faults_mod, "_env_plan", None)
        monkeypatch.setattr(faults_mod, "_step_faults", False)


# ---------------------------------------------------------------------------
# Goodput: the dedicated healing category
# ---------------------------------------------------------------------------
class TestGoodputHealingCategory:
    def _healed_run(self):
        return [
            {"ts_ms": 0, "kind": "job_submitted"},
            {"ts_ms": 1_000, "kind": "job_staged"},
            {"ts_ms": 2_000, "kind": "session_started", "session": 1},
            {"ts_ms": 2_500, "kind": "task_scheduled", "task": "worker:0"},
            {"ts_ms": 3_000, "kind": "task_registered", "task": "worker:0"},
            {"ts_ms": 5_000, "kind": "rendezvous_released"},
            {"ts_ms": 6_000, "kind": "train_progress", "task": "worker:0",
             "steps": 1},
            {"ts_ms": 10_000, "kind": "task_evicted", "task": "worker:1"},
            # mid-patch plumbing must STAY healing, not flip the phase —
            # including the survivors' re-registrations into the patched
            # generation and the replacement's own registration
            {"ts_ms": 10_200, "kind": "task_registered", "task": "worker:0"},
            {"ts_ms": 10_500, "kind": "task_scheduled", "task": "worker:1"},
            {"ts_ms": 10_800, "kind": "task_registered", "task": "worker:1"},
            {"ts_ms": 11_000, "kind": "task_replaced", "task": "worker:1"},
            {"ts_ms": 11_500, "kind": "rendezvous_released"},
            {"ts_ms": 13_000, "kind": "train_progress", "task": "worker:0",
             "steps": 9},
            {"ts_ms": 16_000, "kind": "session_finished", "session": 1,
             "status": "SUCCEEDED"},
            {"ts_ms": 17_000, "kind": "final_status", "state": "SUCCEEDED"},
        ]

    def test_healing_category_registered(self):
        assert "healing" in CATEGORIES

    def test_eviction_to_first_progress_is_healing(self):
        j = GoodputLedger.from_events(self._healed_run()).to_json()
        assert j["categories"]["healing"] == pytest.approx(3.0)
        assert j["categories"]["productive"] == pytest.approx(7.0)
        assert j["categories"]["wasted_by_failure"] == pytest.approx(0.0)
        assert sum(j["categories"].values()) == pytest.approx(17.0)

    def test_elastic_reshard_bills_healing_too(self):
        evs = self._healed_run()
        evs[7] = {"ts_ms": 10_000, "kind": "elastic_reshard",
                  "task": "worker:1", "survivors": 2}
        assert evs[7]["kind"] == "elastic_reshard"
        # no replacement (or its launch/registration) on the shrink path
        evs = [e for e in evs
               if not (e["kind"] in ("task_replaced", "task_scheduled")
                       and e["ts_ms"] > 10_000)
               and not (e["kind"] == "task_registered"
                        and e.get("task") == "worker:1")]
        j = GoodputLedger.from_events(evs).to_json()
        assert j["categories"]["healing"] == pytest.approx(3.0)
        assert sum(j["categories"].values()) == pytest.approx(17.0)

    def test_heal_events_registered_kinds(self):
        for kind in (obs_events.TASK_EVICTED, obs_events.TASK_REPLACED,
                     obs_events.ELASTIC_RESHARD,
                     obs_events.SPECULATIVE_LAUNCHED):
            assert kind in obs_events.KNOWN_KINDS


# ---------------------------------------------------------------------------
# HealingController against a fake coordinator
# ---------------------------------------------------------------------------
class FakeBackend:
    def __init__(self):
        self.launched = []  # (task_id, env, handle)
        self.hard_killed = []
        self.reasons = {}

    def launch(self, task, env):
        handle = SimpleNamespace(task_id=task.id)
        self.launched.append((task.id, dict(env), handle))
        return handle

    def kill(self, handle):
        self.hard_killed.append(handle)

    def kill_hard(self, handle):
        self.hard_killed.append(handle)

    def exit_reason(self, handle):
        return self.reasons.get(id(handle))


class FakeHealth:
    def __init__(self):
        self.scores = {}
        self.patch_calls = []
        self.reset_tasks = []
        self.removed_tasks = []

    def straggler_scores(self):
        return dict(self.scores)

    def begin_patch(self):
        self.patch_calls.append("begin")

    def end_patch(self):
        self.patch_calls.append("end")

    def reset_task(self, tid):
        self.reset_tasks.append(tid)

    def remove_task(self, tid):
        self.removed_tasks.append(tid)


class FakeCoordinator:
    def __init__(self, workers=3):
        self.session = _session(workers)
        self.backend = FakeBackend()
        self.metrics = MetricsRegistry()
        self.events = SimpleNamespace(
            emitted=[],
            emit=lambda kind, **kw: self.events.emitted.append(
                {"kind": kind, **kw}
            ),
        )
        self.health = FakeHealth()
        self.liveness = SimpleNamespace(
            unregistered=[],
            unregister=lambda tid: self.liveness.unregistered.append(tid),
        )
        self.aggregator = SimpleNamespace(
            reset=[],
            reset_task=lambda tid: self.aggregator.reset.append(tid),
        )
        self.slice_plans = {}
        self.spare_pool = None
        self.spare_profile = None
        self.app_id = "application_test"
        self._released = True
        self._resume_step = None
        self.failed_silent = []
        self.checkpoint_step = 7
        self.wakes = 0

    def rendezvous_released(self):
        return self._released

    def reset_rendezvous(self):
        self._released = False

    def wake_monitor(self):
        self.wakes += 1

    def probe_checkpoint_step(self):
        return self.checkpoint_step

    def set_resume_step(self, step):
        if step is not None:
            self._resume_step = step

    def task_launch_env(self, task):
        env = {"TASK": task.id}
        if task.incarnation:
            env[constants.TONY_TASK_INCARNATION] = str(task.incarnation)
        if self._resume_step is not None:
            env[constants.TONY_RESUME_STEP] = str(self._resume_step)
        return env

    def fail_task_silent(self, task_id):
        self.failed_silent.append(task_id)


def _controller(coordinator, clock=None, **cfg):
    cfg.setdefault("enabled", True)
    return HealingController(
        coordinator, HealConfig(**cfg), clock=clock or FakeClock(),
    )


class TestEvictAndReplace:
    def test_full_surgery(self):
        c = FakeCoordinator()
        hc = _controller(c)
        task = c.session.get_task_by_id("worker:1")
        old_handle = task.handle
        assert hc.evict_and_replace(task, cause="straggler confirmed",
                                    score=9.0)
        # the straggler's container is put down hard, the barrier is
        # re-armed, and the replacement launches under incarnation 1
        # with the checkpoint resume step in its env
        assert c.backend.hard_killed == [old_handle]
        assert not c.rendezvous_released()
        assert c._resume_step == 7
        (tid, env, handle) = c.backend.launched[-1]
        assert tid == "worker:1"
        assert env[constants.TONY_TASK_INCARNATION] == "1"
        assert env[constants.TONY_RESUME_STEP] == "7"
        assert task.handle is handle
        assert c.liveness.unregistered == ["worker:1"]
        assert c.aggregator.reset == ["worker:1"]
        assert c.health.reset_tasks == ["worker:1"]
        assert c.health.patch_calls == ["begin"]
        kinds = [e["kind"] for e in c.events.emitted]
        assert kinds == [obs_events.TASK_EVICTED, obs_events.TASK_SCHEDULED]
        evicted = c.events.emitted[0]
        assert evicted["task"] == "worker:1"
        assert evicted["score"] == 9.0
        assert evicted["resume_step"] == 7

    def test_replacement_registration_completes_the_patch(self):
        c = FakeCoordinator()
        hc = _controller(c)
        task = c.session.get_task_by_id("worker:1")
        assert hc.evict_and_replace(task, cause="x")
        # survivors owe a resync (stale generation); the replacement
        # does not (it has never registered into this generation)
        cmd = hc.command_for("worker:0")
        assert cmd["resync"]["generation"] == 1
        assert cmd["resync"]["task_index"] == 0
        assert cmd["resync"]["task_num"] == 3
        assert cmd["resync"]["resume_step"] == 7
        # everyone re-registers; the coordinator's release hook fires
        for i in range(3):
            c.session.register_task(
                f"worker:{i}", f"h{i}:1", 1 if i == 1 else 0,
            )
        assert c.session.cluster_spec() is not None
        hc.on_task_registered(c.session.get_task_by_id("worker:1"))
        hc.on_rendezvous_released()
        assert c.health.patch_calls == ["begin", "end"]
        assert hc.stats()["replacements"] == 1
        replaced = [e for e in c.events.emitted
                    if e["kind"] == obs_events.TASK_REPLACED]
        assert len(replaced) == 1 and replaced[0]["incarnation"] == 1
        # post-patch: no more resync orders
        assert hc.command_for("worker:0") is None

    def test_failed_relaunch_falls_back_to_shrink(self):
        # the documented "no substrate to relaunch on" path: a launch
        # exception mid-patch must not escape the monitor thread — it
        # folds into an elastic shrink of the same patch
        c = FakeCoordinator()
        c.backend.launch = lambda task, env: (_ for _ in ()).throw(
            OSError("no substrate")
        )
        hc = _controller(c)
        task = c.session.get_task_by_id("worker:2")
        assert hc.on_task_exit(task, task.handle, -9)
        assert hc.stats()["reshards"] == 1
        assert [t.id for t in c.session.removed] == ["worker:2"]
        assert c.failed_silent == []

    def test_failed_relaunch_of_chief_fails_the_session(self):
        c = FakeCoordinator()
        c.backend.launch = lambda task, env: (_ for _ in ()).throw(
            OSError("no substrate")
        )
        hc = _controller(c)
        chief = c.session.get_task_by_id("worker:0")
        # consumed (the verdict is delivered via fail_task_silent — the
        # chief cannot be shrunk away)
        assert hc.on_task_exit(chief, chief.handle, -9)
        assert c.failed_silent == ["worker:0"]

    def test_failed_speculative_launch_is_non_fatal(self):
        clock = FakeClock()
        c = FakeCoordinator()
        session = TonySession(_conf(3), session_id=1)
        session.status = SessionStatus.RUNNING
        for i in range(3):
            session.get_task_by_id(f"worker:{i}").handle = object()
        for i in range(2):
            session.register_task(f"worker:{i}", f"h{i}:1")
        c.session = session
        c._released = False
        c.backend.launch = lambda task, env: (_ for _ in ()).throw(
            OSError("no substrate")
        )
        hc = _controller(c, clock=clock, speculative=True,
                         speculative_delay_ms=0)
        clock.advance(1.0)
        hc.tick()  # must not raise
        assert hc.stats()["speculative_launches"] == 0

    def test_budget_exhausted_declines(self):
        c = FakeCoordinator()
        hc = _controller(c, max_evictions=0)
        task = c.session.get_task_by_id("worker:1")
        assert not hc.evict_and_replace(task, cause="x")
        assert c.backend.launched == []

    def test_disabled_controller_is_inert(self):
        c = FakeCoordinator()
        hc = _controller(c, enabled=False)
        task = c.session.get_task_by_id("worker:1")
        task.handle, dead = object(), task.handle
        assert not hc.on_task_exit(task, task.handle, -9)
        assert not hc.note_heartbeat_expiry("worker:1")
        hc.tick()  # no-op, no crash
        assert c.events.emitted == []


class TestOnTaskExit:
    def test_expected_exit_consumed_once(self):
        c = FakeCoordinator()
        hc = _controller(c)
        task = c.session.get_task_by_id("worker:1")
        old = task.handle
        hc.evict_and_replace(task, cause="x", score=1.0)
        # the evicted copy's death must not read as a session failure
        assert hc.on_task_exit(task, old, -9)

    def test_infra_exit_heals(self):
        c = FakeCoordinator()
        hc = _controller(c)
        task = c.session.get_task_by_id("worker:2")
        assert hc.on_task_exit(task, task.handle, -9)
        assert hc.stats()["evictions"] == 1
        (tid, env, _) = c.backend.launched[-1]
        assert tid == "worker:2"

    def test_user_bug_exit_declined(self):
        c = FakeCoordinator()
        hc = _controller(c)
        task = c.session.get_task_by_id("worker:2")
        assert not hc.on_task_exit(task, task.handle, 1)
        assert c.backend.launched == []

    def test_preempted_reason_heals_even_exit_zero(self):
        c = FakeCoordinator()
        hc = _controller(c)
        task = c.session.get_task_by_id("worker:2")
        c.backend.reasons[id(task.handle)] = "preempted"
        assert hc.on_task_exit(task, task.handle, 0)
        assert hc.stats()["evictions"] == 1

    def test_pre_barrier_death_stays_on_retry_path(self):
        c = FakeCoordinator()
        c._released = False
        hc = _controller(c)
        task = c.session.get_task_by_id("worker:2")
        assert not hc.on_task_exit(task, task.handle, -9)

    def test_mid_patch_loss_folds_into_active_surgery(self):
        """The serialization contract: a second infra loss while a patch
        is in flight is QUEUED (not dropped to session retry), then
        folded into the armed patch on the next tick — the barrier then
        waits for both replacements."""
        c = FakeCoordinator()
        hc = _controller(c, max_evictions=4)
        straggler = c.session.get_task_by_id("worker:1")
        hc.evict_and_replace(straggler, cause="straggler confirmed")
        victim = c.session.get_task_by_id("worker:2")
        dead = victim.handle
        assert hc.on_task_exit(victim, dead, -9)  # queued, consumed
        assert hc.stats()["evictions"] == 1  # not yet healed
        # the dead handle re-polls the same code every monitor pass;
        # the queue must not grow
        assert hc.on_task_exit(victim, dead, -9)
        hc.tick()
        assert hc.stats()["evictions"] == 2
        launched = [t for t, _, _ in c.backend.launched]
        assert launched == ["worker:1", "worker:2"]
        # ONE patch episode: detectors suspended once, resumed once
        assert c.health.patch_calls == ["begin"]
        for i in range(3):
            c.session.register_task(
                f"worker:{i}", f"h{i}:1", 1 if i in (1, 2) else 0,
            )
        assert c.session.cluster_spec() is not None
        hc.on_rendezvous_released()
        assert c.health.patch_calls == ["begin", "end"]

    def test_mid_patch_loss_shrinks_when_budget_spent(self):
        c = FakeCoordinator()
        hc = _controller(c, max_evictions=1, min_shrink_fraction=0.5)
        straggler = c.session.get_task_by_id("worker:1")
        hc.evict_and_replace(straggler, cause="straggler confirmed")
        victim = c.session.get_task_by_id("worker:2")
        assert hc.on_task_exit(victim, victim.handle, -9)
        hc.tick()
        assert hc.stats()["reshards"] == 1
        assert [t.id for t in c.session.removed] == ["worker:2"]
        # the fold bumped the generation AGAIN: survivors that already
        # re-registered must resync once more with the dense indices
        assert c.session.gang_generation == 2


class TestElasticShrink:
    def test_shrink_emits_replanned_note(self):
        c = FakeCoordinator()
        hc = _controller(c, max_evictions=0)
        task = c.session.get_task_by_id("worker:2")
        dead = task.handle
        assert hc.on_task_exit(task, dead, -9)
        assert hc.stats()["reshards"] == 1
        assert [t.id for t in c.session.removed] == ["worker:2"]
        (event,) = [e for e in c.events.emitted
                    if e["kind"] == obs_events.ELASTIC_RESHARD]
        assert event["survivors"] == 2
        assert event["plan"] == "dp2.pp1.ep1.sp1.tp1"
        assert event["resume_step"] == 7
        # survivors' resync orders carry the reshard note + dense view
        cmd = hc.command_for("worker:1")
        note = json.loads(cmd["resync"]["reshard"])
        assert note["num_processes"] == 2
        assert note["plan"] == "dp2.pp1.ep1.sp1.tp1"
        assert cmd["resync"]["task_index"] == 1
        assert cmd["resync"]["task_num"] == 2

    def test_chief_is_never_shrunk_away(self):
        c = FakeCoordinator()
        hc = _controller(c, max_evictions=0)
        chief = c.session.get_task_by_id("worker:0")
        assert not hc.on_task_exit(chief, chief.handle, -9)
        assert c.session.removed == []

    def test_min_shrink_fraction_floors_the_gang(self):
        c = FakeCoordinator(workers=2)
        hc = _controller(c, max_evictions=0, min_shrink_fraction=0.9)
        task = c.session.get_task_by_id("worker:1")
        # 1/2 survivors < 0.9 floor: the loss goes to session retry
        assert not hc.on_task_exit(task, task.handle, -9)

    def test_heartbeat_expiry_queues_then_heals(self):
        c = FakeCoordinator()
        hc = _controller(c, max_evictions=0)
        assert hc.note_heartbeat_expiry("worker:1")
        assert c.wakes == 1
        hc.tick()
        assert hc.stats()["reshards"] == 1
        # the silent container is reaped before the survivors re-gang
        assert len(c.backend.hard_killed) == 1

    def test_heartbeat_expiry_declined_fails_task(self):
        c = FakeCoordinator()
        hc = _controller(c, max_evictions=0, min_shrink_fraction=1.0)
        assert hc.note_heartbeat_expiry("worker:1")
        hc.tick()
        # healing could not absorb it: the deferred liveness verdict
        # lands as the session-level failure it would have been
        assert c.failed_silent == ["worker:1"]


class TestStragglerConfirmWindow:
    def test_confirm_window_gates_eviction(self):
        clock = FakeClock()
        c = FakeCoordinator()
        hc = _controller(c, clock=clock, confirm_window_ms=2000,
                         straggler_threshold=3.0)
        c.health.scores = {"worker:1": 8.0}
        hc.tick()  # score crossed: confirmation window opens
        assert hc.stats()["evictions"] == 0
        clock.advance(1.0)
        hc.tick()  # 1s < 2s window
        assert hc.stats()["evictions"] == 0
        clock.advance(1.5)
        hc.tick()  # window elapsed: evict
        assert hc.stats()["evictions"] == 1
        (event,) = [e for e in c.events.emitted
                    if e["kind"] == obs_events.TASK_EVICTED]
        assert event["cause"] == "straggler confirmed"
        assert event["score"] == 8.0

    def test_score_recovery_clears_confirmation(self):
        clock = FakeClock()
        c = FakeCoordinator()
        hc = _controller(c, clock=clock, confirm_window_ms=2000)
        c.health.scores = {"worker:1": 8.0}
        hc.tick()
        clock.advance(1.0)
        c.health.scores = {"worker:1": 0.5}  # recovered
        hc.tick()
        clock.advance(2.0)
        c.health.scores = {"worker:1": 8.0}  # crossed again: fresh window
        hc.tick()
        assert hc.stats()["evictions"] == 0

    def test_session_restart_resets_confirmations_not_budget(self):
        clock = FakeClock()
        c = FakeCoordinator()
        hc = _controller(c, clock=clock, confirm_window_ms=0,
                         max_evictions=1)
        c.health.scores = {"worker:1": 8.0}
        hc.tick()
        assert hc.stats()["evictions"] == 1
        hc.on_session_start()
        c.health.scores = {"worker:2": 8.0}
        clock.advance(10.0)
        hc.tick()
        # the per-job budget survives the session restart
        assert hc.stats()["evictions"] == 1


class TestSpeculativeReexecution:
    def _stalled_gang(self, c):
        """2 of 3 registered; worker:2 launched but never registered."""
        session = TonySession(_conf(3), session_id=1)
        session.status = SessionStatus.RUNNING
        for i in range(3):
            session.get_task_by_id(f"worker:{i}").handle = object()
        for i in range(2):
            session.register_task(f"worker:{i}", f"h{i}:1")
        c.session = session
        c._released = False
        return session

    def _speculated(self):
        clock = FakeClock()
        c = FakeCoordinator()
        session = self._stalled_gang(c)
        hc = _controller(c, clock=clock, speculative=True,
                         speculative_delay_ms=5000)
        hc.tick()
        assert c.backend.launched == []  # inside the delay
        clock.advance(6.0)
        hc.tick()
        (tid, env, backup) = c.backend.launched[-1]
        assert tid == "worker:2"
        assert env[constants.TONY_TASK_INCARNATION] == "1"
        assert hc.stats()["speculative_launches"] == 1
        (event,) = [e for e in c.events.emitted
                    if e["kind"] == obs_events.SPECULATIVE_LAUNCHED]
        assert event["incarnation"] == 1
        hc.tick()
        assert len(c.backend.launched) == 1  # no duplicate backups
        return c, hc, session, backup

    def test_backup_launches_after_delay(self):
        self._speculated()

    def test_backup_wins_race(self):
        c, hc, session, backup = self._speculated()
        original = session.get_task_by_id("worker:2").handle
        assert session.register_task("worker:2", "backup:9", 1)
        task = session.get_task_by_id("worker:2")
        hc.on_task_registered(task)
        assert task.handle is backup
        assert c.backend.hard_killed == [original]
        # the loser's exit is expected, not a failure
        assert hc.on_task_exit(task, original, -9)

    def test_original_wins_race(self):
        c, hc, session, backup = self._speculated()
        original = session.get_task_by_id("worker:2").handle
        assert session.register_task("worker:2", "orig:9", 0)
        task = session.get_task_by_id("worker:2")
        hc.on_task_registered(task)
        assert task.handle is original
        assert c.backend.hard_killed == [backup]

    def test_speculation_needs_majority_registered(self):
        clock = FakeClock()
        c = FakeCoordinator()
        session = TonySession(_conf(3), session_id=1)
        session.status = SessionStatus.RUNNING
        for i in range(3):
            session.get_task_by_id(f"worker:{i}").handle = object()
        session.register_task("worker:0", "h0:1")  # 1 of 3 < majority
        c.session = session
        c._released = False
        hc = _controller(c, clock=clock, speculative=True,
                         speculative_delay_ms=0)
        clock.advance(1.0)
        hc.tick()
        assert c.backend.launched == []


# ---------------------------------------------------------------------------
# Doctor: TONY-D013
# ---------------------------------------------------------------------------
class TestDoctorD013:
    def test_evicted_and_replaced_informational_on_success(self):
        events = [
            {"ts_ms": 1, "kind": "task_evicted", "task": "worker:1",
             "cause": "straggler confirmed", "resume_step": 7},
            {"ts_ms": 2, "kind": "task_replaced", "task": "worker:1",
             "incarnation": 1},
        ]
        final = {"state": "SUCCEEDED",
                 "healing": {"evictions": 1, "replacements": 1}}
        findings = postmortem.diagnose(events=events, final=final)
        (f,) = [x for x in findings if x.rule_id == "TONY-D013"]
        assert f.task == "worker:1"
        assert "replaced in-session" in f.cause
        assert "resumed from step 7" in f.cause

    def test_elastic_reshape_names_plan_and_survivors(self):
        events = [
            {"ts_ms": 1, "kind": "elastic_reshard", "task": "worker:2",
             "cause": "signal", "survivors": 2,
             "plan": "dp2.pp1.ep1.sp1.tp1", "resume_step": 4},
        ]
        findings = postmortem.diagnose(
            events=events, final={"state": "SUCCEEDED"},
        )
        (f,) = [x for x in findings if x.rule_id == "TONY-D013"]
        assert "elastically reshaped" in f.cause
        assert "2 survivor(s)" in f.cause
        assert "dp2.pp1.ep1.sp1.tp1" in f.cause

    def test_final_status_fallback_when_events_pruned(self):
        final = {"state": "FAILED",
                 "healing": {"evictions": 2, "replacements": 1,
                             "reshards": 0}}
        findings = postmortem.diagnose(events=[], final=final)
        (f,) = [x for x in findings if x.rule_id == "TONY-D013"]
        assert "2 eviction(s)" in f.cause

    def test_failed_job_ranks_surgery_higher(self):
        events = [
            {"ts_ms": 1, "kind": "task_evicted", "task": "worker:1",
             "cause": "signal"},
        ]
        ok = postmortem.diagnose(events=events,
                                 final={"state": "SUCCEEDED"})
        bad = postmortem.diagnose(events=events, final={"state": "FAILED"})
        score_ok = next(f.score for f in ok if f.rule_id == "TONY-D013")
        score_bad = next(f.score for f in bad if f.rule_id == "TONY-D013")
        assert score_bad > score_ok


# ---------------------------------------------------------------------------
# Chaos acceptance e2e (slow)
# ---------------------------------------------------------------------------
def _heal_job_conf(cluster, ckpt_dir, heal_enabled, tmp_marker=None):
    conf = cluster.base_conf()
    conf.set(keys.K_EXECUTES, str(FIXTURES / "heal_train.py"))
    conf.set(keys.K_PYTHON_BINARY, sys.executable)
    conf.set(keys.instances_key("worker"), 3)
    conf.set(keys.instances_key("ps"), 0)
    conf.set(keys.K_TASK_HEARTBEAT_INTERVAL_MS, 150)
    conf.set(keys.K_CHECKPOINT_LOCATION, str(ckpt_dir))
    conf.set(keys.K_SHELL_ENV, "HEAL_TARGET=40,HEAL_CADENCE_S=0.25")
    conf.set(keys.K_HEALTH_STRAGGLER_THRESHOLD, 2.5)
    # the baseline must survive the injected kill via the PR-2 whole-
    # session retry path (that IS the comparison)
    conf.set(keys.K_AM_RETRY_COUNT, 2)
    conf.set(keys.K_AM_RETRY_BACKOFF_BASE_MS, 200)
    conf.set(keys.K_AM_RETRY_BACKOFF_MAX_MS, 1000)
    conf.set(keys.K_HEAL_ENABLED, "true" if heal_enabled else "false")
    conf.set(keys.K_HEAL_CONFIRM_WINDOW_MS, 2000)
    conf.set(keys.K_HEAL_MAX_EVICTIONS, 2)
    return conf


@pytest.mark.slow
def test_chaos_heal_evict_and_replace_beats_non_healing_baseline(tmp_path):
    """THE acceptance chaos run. One seeded plan makes worker:1 a
    deterministic mid-training straggler (degrade_task) and kills
    worker:2 once its reported steps cross 4 (kill_task after_steps — a
    mid-training hardware loss). With healing ON the job must SUCCEED in
    ONE session (both anomalies evicted-and-replaced in-session, the
    replacement incarnations running clean), beat the healing-disabled
    baseline's wall, and show strictly less wasted_by_failure + stalled
    chip time on the goodput ledger than the baseline (which pays a
    whole-session restart for the kill and drags the straggler to the
    end)."""
    plan = json.dumps({"seed": 11, "faults": [
        {"action": "degrade_task", "target": "worker:1", "ms": 800,
         "after_steps": 2, "count": 1000},
        # after_steps 6, not lower: the chief must have committed its
        # first checkpoint(s) before the kill lands, or the replacement
        # legitimately starts at 0 and the resume assertion below races
        # (the chief's early steps carry blocking saves and can lag the
        # victim's by a second-plus on a loaded box)
        {"action": "kill_task", "target": "worker:2", "after_steps": 6,
         "session": 1},
    ]})

    walls, ledgers = {}, {}
    for mode, heal in (("healed", True), ("baseline", False)):
        cluster = MiniTonyCluster(tmp_path / mode)
        ckpt = tmp_path / f"ckpt-{mode}"
        conf = _heal_job_conf(cluster, ckpt, heal_enabled=heal)
        conf.set(keys.K_FAULT_PLAN, plan)
        with cluster:
            status, coord = cluster.run_job(conf, timeout_s=420)
        assert status is SessionStatus.SUCCEEDED, (
            f"{mode}: {coord.session.diagnostics if coord.session else '?'}"
        )
        final = json.loads(
            (coord.app_dir / "final-status.json").read_text()
        )
        walls[mode] = final["stats"]["wall_ms"]
        ledgers[mode] = final["goodput"]["categories"]

        events = obs_events.parse_jsonl(
            (coord.app_dir / "events.jsonl").read_text()
        )
        by_kind = {}
        for e in events:
            by_kind.setdefault(e["kind"], []).append(e)
        if not heal:
            assert final["stats"]["sessions_run"] == 2, (
                "baseline must pay the whole-session restart"
            )
            assert "task_evicted" not in by_kind
            continue

        # -- healed run: both anomalies fixed inside ONE session --------
        assert final["stats"]["sessions_run"] == 1, (
            "healing must never fall back to a whole-session restart"
        )
        assert final["healing"]["evictions"] == 2
        assert final["healing"]["replacements"] == 2
        evicted = {e["task"] for e in by_kind["task_evicted"]}
        assert evicted == {"worker:1", "worker:2"}
        assert {e["task"] for e in by_kind["task_replaced"]} == evicted
        straggler_evts = [e for e in by_kind["task_evicted"]
                          if e["task"] == "worker:1"]
        assert straggler_evts[0]["cause"] == "straggler confirmed"
        # replacements ran as incarnation 1 and the straggler's
        # replacement ran CLEAN (degrade_task is incarnation-0 scoped),
        # resuming from a checkpoint instead of step 0
        for victim in ("worker-1", "worker-2"):
            log_text = (coord.app_dir / "logs" / f"{victim}.log").read_text()
            m = re.search(r"incarnation=1 start=(\d+)", log_text)
            assert m, f"{victim} replacement never started: {log_text[-2000:]}"
            assert int(m.group(1)) > 0, "replacement must resume, not recompute"
        # the healing episodes are ledger-visible
        assert ledgers["healed"]["healing"] > 0
        # doctor reads the surgery off the artifacts
        findings = postmortem.diagnose(events=events, final=final)
        d013 = [f for f in findings if f.rule_id == "TONY-D013"]
        assert {f.task for f in d013} == {"worker:1", "worker:2"}

    assert walls["healed"] < walls["baseline"], walls
    healed_waste = (ledgers["healed"]["wasted_by_failure"]
                    + ledgers["healed"]["stalled"])
    baseline_waste = (ledgers["baseline"]["wasted_by_failure"]
                      + ledgers["baseline"]["stalled"])
    assert healed_waste < baseline_waste, (ledgers["healed"],
                                           ledgers["baseline"])
    assert ledgers["baseline"]["wasted_by_failure"] > 0


@pytest.mark.slow
def test_chaos_elastic_shrink_to_n_minus_1(tmp_path):
    """The no-spare path: worker:1 dies mid-training with the eviction
    budget at 0 — the gang must continue on n−1 under a planner-chosen
    sharding (dp pinned to the surviving devices), the survivors must
    receive the reshard note + dense runtime view + checkpoint resume
    step, and the job must SUCCEED in one session with the removed task
    in its terminal record."""
    cluster = MiniTonyCluster(tmp_path)
    ckpt = tmp_path / "ckpt"
    conf = _heal_job_conf(cluster, ckpt, heal_enabled=True)
    conf.set(keys.K_HEAL_MAX_EVICTIONS, 0)  # "no spare": never replace
    conf.set(keys.K_FAULT_PLAN, json.dumps({"seed": 13, "faults": [
        {"action": "kill_task", "target": "worker:1", "after_steps": 6,
         "session": 1},
    ]}))
    with cluster:
        status, coord = cluster.run_job(conf, timeout_s=300)
    assert status is SessionStatus.SUCCEEDED, (
        coord.session.diagnostics if coord.session else "?"
    )
    final = json.loads((coord.app_dir / "final-status.json").read_text())
    assert final["stats"]["sessions_run"] == 1
    assert final["healing"]["reshards"] == 1
    assert final["healing"]["evictions"] == 0
    assert final["healing"]["removed_tasks"] == ["worker:1"]
    removed_rows = [t for t in final["tasks"] if t.get("removed")]
    assert [t["id"] for t in removed_rows] == ["worker:1"]

    events = obs_events.parse_jsonl(
        (coord.app_dir / "events.jsonl").read_text()
    )
    (reshard,) = [e for e in events if e["kind"] == "elastic_reshard"]
    assert reshard["task"] == "worker:1"
    assert reshard["survivors"] == 2
    assert reshard["plan"] == "dp2.pp1.ep1.sp1.tp1"
    assert reshard["resume_step"] is not None

    # the surviving non-chief (original id worker:2) restarted its user
    # process against the DENSE 2-process view, received the replanned
    # sharding note, and resumed from the checkpoint step
    survivor_log = (coord.app_dir / "logs" / "worker-2.log").read_text()
    assert "reshard note: plan=dp2.pp1.ep1.sp1.tp1 num_processes=2" \
        in survivor_log
    m = re.search(r"task=worker:1 num=2 incarnation=0 start=(\d+)",
                  survivor_log)
    assert m, f"survivor never resynced: {survivor_log[-2000:]}"
    assert int(m.group(1)) > 0
    chief_log = (coord.app_dir / "logs" / "worker-0.log").read_text()
    m = re.search(r"task=worker:0 num=2 incarnation=0 start=(\d+)",
                  chief_log)
    assert m and int(m.group(1)) > 0

    # ledger + doctor read the reshape
    assert final["goodput"]["categories"]["healing"] > 0
    findings = postmortem.diagnose(events=events, final=final)
    (f,) = [x for x in findings if x.rule_id == "TONY-D013"]
    assert "elastically reshaped" in f.cause
