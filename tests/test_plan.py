"""Plan layer (parallel/plan.py), persistent compile cache, and the
bench regression gate: cache keying (config/mesh/jax-version
sensitivity, corrupt-dir degradation), hit/miss metrics across
processes, planner candidate legality + measured refinement, the
TONY-C010 scratch-cache lint, and `bench.py --check` compare logic on
fixture JSON."""

import json
import os
import subprocess
import sys
from pathlib import Path

import jax
import numpy as np
import pytest

from tony_tpu.models import TransformerConfig
from tony_tpu.parallel import plan as plan_lib
from tony_tpu.parallel.mesh import MeshSpec, build_mesh

REPO = Path(__file__).resolve().parents[1]
FIXTURES = Path(__file__).resolve().parent / "fixtures" / "bench"

CFG = TransformerConfig(
    vocab_size=64, d_model=32, n_layers=4, n_heads=4, head_dim=8,
    d_ff=64, max_seq=64, dtype="float32", n_kv_heads=2,
)


# ---------------------------------------------------------------------------
# Cache keying
# ---------------------------------------------------------------------------


class TestPlanCacheKey:
    def test_identical_inputs_identical_key(self):
        mesh = build_mesh(MeshSpec(dp=4, tp=2), devices=jax.devices())
        a = plan_lib.plan_cache_key("step", config=CFG, mesh=mesh)
        b = plan_lib.plan_cache_key("step", config=CFG, mesh=mesh)
        assert a == b

    def test_model_config_invalidates(self):
        other = TransformerConfig(
            vocab_size=64, d_model=32, n_layers=2, n_heads=4, head_dim=8,
            d_ff=64, max_seq=64, dtype="float32", n_kv_heads=2,
        )
        assert plan_lib.plan_cache_key("step", config=CFG) != \
            plan_lib.plan_cache_key("step", config=other)

    def test_mesh_topology_invalidates(self):
        devs = jax.devices()
        m1 = build_mesh(MeshSpec(dp=4, tp=2), devices=devs)
        m2 = build_mesh(MeshSpec(dp=2, sp=2, tp=2), devices=devs)
        assert plan_lib.plan_cache_key("step", config=CFG, mesh=m1) != \
            plan_lib.plan_cache_key("step", config=CFG, mesh=m2)

    def test_jax_version_invalidates(self):
        base = plan_lib.backend_fingerprint()
        bumped = dict(base, jax="99.99.99")
        assert plan_lib.plan_cache_key("step", config=CFG, backend=base) != \
            plan_lib.plan_cache_key("step", config=CFG, backend=bumped)

    def test_label_and_plan_knobs_invalidate(self):
        p1 = plan_lib.Plan(MeshSpec(pp=2, tp=2, dp=2), microbatches=2)
        p2 = plan_lib.Plan(MeshSpec(pp=2, tp=2, dp=2), microbatches=4)
        assert plan_lib.plan_cache_key("a", plan=p1) != \
            plan_lib.plan_cache_key("b", plan=p1)
        assert plan_lib.plan_cache_key("a", plan=p1) != \
            plan_lib.plan_cache_key("a", plan=p2)


class TestCompileCache:
    def test_commit_then_seen(self, tmp_path):
        cache = plan_lib.CompileCache(str(tmp_path))
        key = "k" * 64
        assert not cache.seen(key)
        cache.commit(key, {"label": "step"})
        assert cache.seen(key)
        # A fresh instance over the same dir (≈ a new process) sees it.
        assert plan_lib.CompileCache(str(tmp_path)).seen(key)

    def test_corrupt_marker_degrades_to_miss(self, tmp_path):
        cache = plan_lib.CompileCache(str(tmp_path))
        key = "c" * 64
        cache.commit(key)
        marker = tmp_path / plan_lib._KEY_INDEX_DIR / f"{key}.json"
        marker.write_text("{torn json")
        assert not cache.seen(key)
        # mismatched content (wrong key recorded inside) is also a miss
        marker.write_text(json.dumps({"key": "someone-else"}))
        assert not cache.seen(key)

    def test_unwritable_index_never_crashes(self, tmp_path):
        # A FILE squatting the index path: commit and seen both degrade.
        (tmp_path / plan_lib._KEY_INDEX_DIR).write_text("not a dir")
        cache = plan_lib.CompileCache(str(tmp_path))
        cache.commit("x" * 64)  # must not raise
        assert not cache.seen("x" * 64)

    def test_disabled_cache(self):
        cache = plan_lib.CompileCache(None)
        assert not cache.enabled
        cache.commit("y" * 64)
        assert not cache.seen("y" * 64)

    def test_instrument_jit_counts_miss_then_hit(self, tmp_path):
        from tony_tpu import observability

        reg = observability.default_registry()
        cache = plan_lib.CompileCache(str(tmp_path))
        hits = reg.counter("tony_compile_cache_hits_total")
        misses = reg.counter("tony_compile_cache_misses_total")
        h0, m0 = hits.value, misses.value

        calls = []
        fn = plan_lib.instrument_jit(
            lambda x: calls.append(x) or x + 1, "base-key", cache=cache
        )
        assert fn(1) == 2 and fn(2) == 3
        assert (hits.value, misses.value) == (h0, m0 + 1)
        # "Second submit": a fresh wrapper over the same cache and the
        # same base key + argument signature classifies as a hit.
        fn2 = plan_lib.instrument_jit(
            lambda x: x + 1, "base-key", cache=cache
        )
        assert fn2(1) == 2  # same base key AND argument signature
        assert (hits.value, misses.value) == (h0 + 1, m0 + 1)
        # ... but a different argument SHAPE is a different executable.
        fn3 = plan_lib.instrument_jit(
            lambda x: x, "base-key", cache=cache
        )
        fn3(np.zeros((2, 3)))
        assert (hits.value, misses.value) == (h0 + 1, m0 + 2)


# ---------------------------------------------------------------------------
# Planner
# ---------------------------------------------------------------------------


class TestPlanner:
    def test_candidates_legal(self):
        plans = plan_lib.candidate_plans(CFG, 8, global_batch=16, seq=16)
        assert plans
        for p in plans:
            s = p.mesh_spec
            assert p.num_devices == 8
            assert CFG.n_heads % s.tp == 0 and CFG.n_kv_heads % s.tp == 0
            assert s.ep == 1  # no experts in CFG
            assert CFG.n_layers % s.pp == 0
            assert (p.microbatches is not None) == (s.pp > 1)
            if s.sp > 1:
                assert 16 % s.sp == 0

    def test_require_pins_axes(self):
        plans = plan_lib.candidate_plans(
            CFG, 8, require={"pp": 2, "tp": 2, "microbatches": 2}
        )
        assert plans
        for p in plans:
            assert p.mesh_spec.pp == 2 and p.mesh_spec.tp == 2
            assert p.microbatches == 2

    def test_ep_needs_experts(self):
        moe = TransformerConfig(
            vocab_size=64, d_model=32, n_layers=4, n_heads=4, head_dim=8,
            d_ff=64, max_seq=64, dtype="float32", n_experts=4,
        )
        assert any(
            p.mesh_spec.ep > 1
            for p in plan_lib.candidate_plans(moe, 8, seq=16)
        )
        assert plan_lib.candidate_plans(CFG, 8, seq=16, require={"ep": 2}) \
            == []

    def test_plan_for_impossible_raises(self):
        with pytest.raises(ValueError):
            plan_lib.plan_for(CFG, 8, require={"tp": 3})

    def test_measured_refinement_overrides_estimate(self, tmp_path):
        d = str(tmp_path)
        cands = plan_lib.candidate_plans(CFG, 8, seq=16)
        analytic = plan_lib.plan_for(CFG, 8, seq=16, cache_dir=d)
        # Declare some OTHER candidate measured-fastest; the pick must
        # follow the measurement, not the estimate.
        other = next(p for p in cands if p.key() != analytic.key())
        plan_lib.record_step_time(analytic, CFG, 500.0, seq=16,
                                  cache_dir=d)
        plan_lib.record_step_time(other, CFG, 1.0, seq=16, cache_dir=d)
        assert plan_lib.plan_for(CFG, 8, seq=16, cache_dir=d).key() == \
            other.key()
        # best-of: a worse later observation does not overwrite
        plan_lib.record_step_time(other, CFG, 900.0, seq=16, cache_dir=d)
        table = plan_lib.load_measurements(cache_dir=d)
        bucket = plan_lib._model_bucket(CFG, 8, None, 16)
        assert table[bucket][other.key()] == 1.0
        # a different work bucket (other batch/seq) must not see these
        assert plan_lib._model_bucket(CFG, 8, 64, 16) != bucket

    def test_corrupt_measurements_degrade_to_analytic(self, tmp_path):
        d = str(tmp_path)
        (tmp_path / plan_lib._MEASUREMENTS_FILE).write_text("{nope")
        assert plan_lib.load_measurements(cache_dir=d) == {}
        assert plan_lib.plan_for(CFG, 8, seq=16, cache_dir=d)  # no crash

    def test_pipeline_cost_includes_bubble(self):
        gspmd = plan_lib.Plan(MeshSpec(dp=8))
        pp_few = plan_lib.Plan(MeshSpec(dp=1, pp=8), microbatches=8)
        pp_many = plan_lib.Plan(MeshSpec(dp=1, pp=8), microbatches=32)
        big = TransformerConfig(
            vocab_size=32000, d_model=1024, n_layers=8, n_heads=16,
            head_dim=64, d_ff=4096, max_seq=2048,
        )
        c = lambda p: plan_lib.estimate_cost(p, big, global_batch=64,
                                             seq=2048)
        assert c(pp_many) < c(pp_few)   # more microbatches, less bubble
        assert c(gspmd) < c(pp_few)     # dp beats a bubbly pipeline here


# ---------------------------------------------------------------------------
# Plan → train step plumbing
# ---------------------------------------------------------------------------


class TestEstimatePhases:
    def test_decomposition_sums_to_estimate_cost(self):
        for spec in (MeshSpec(dp=8), MeshSpec(dp=2, tp=2, sp=2),
                     MeshSpec(dp=4, tp=2)):
            p = plan_lib.Plan(spec)
            est = plan_lib.estimate_phases(p, CFG, global_batch=16, seq=16)
            assert est["compute"] > 0 and est["collective"] >= 0
            assert plan_lib.estimate_cost(
                p, CFG, global_batch=16, seq=16
            ) == pytest.approx(est["compute"] + est["collective"])

    def test_comm_bytes_per_axis(self):
        p = plan_lib.Plan(MeshSpec(dp=2, tp=2, sp=2))
        est = plan_lib.estimate_phases(p, CFG, global_batch=16, seq=16)
        # every active axis > 1 moves bytes; inactive axes are absent
        assert set(est["comm_bytes"]) == {"dp", "tp", "sp"}
        assert all(v > 0 for v in est["comm_bytes"].values())
        single = plan_lib.estimate_phases(
            plan_lib.Plan(MeshSpec()), CFG, global_batch=16, seq=16
        )
        assert single["comm_bytes"] == {} and single["collective"] == 0.0

    def test_illegal_pipeline_reads_infinite_compute(self):
        p = plan_lib.Plan(MeshSpec(pp=2))  # no microbatches
        est = plan_lib.estimate_phases(p, CFG, global_batch=16, seq=16)
        assert est["compute"] == float("inf")
        assert plan_lib.estimate_cost(p, CFG) == float("inf")

    def test_plan_from_mesh_maps_axis_sizes(self):
        mesh = build_mesh(MeshSpec(dp=4, tp=2), devices=jax.devices())
        p = plan_lib.plan_from_mesh(mesh, num_slices=1)
        assert p.mesh_spec.dp == 4 and p.mesh_spec.tp == 2
        assert p.num_devices == 8

    def test_calibration_residuals_normalized(self, tmp_path):
        d = str(tmp_path)
        plans = plan_lib.candidate_plans(CFG, 8, global_batch=16, seq=16)
        # perfectly-calibrated measurements: measured == estimate × 2
        for p in plans[:3]:
            plan_lib.record_step_time(
                p, CFG,
                2.0 * plan_lib.estimate_cost(p, CFG, global_batch=16,
                                             seq=16),
                global_batch=16, seq=16, cache_dir=d,
            )
        res = plan_lib.calibration_residuals(
            CFG, 8, global_batch=16, seq=16, cache_dir=d
        )
        assert len(res) == 3
        # all ratios equal ⇒ every residual is exactly 1.0 after the
        # bucket-mean normalization (the shared ×2 scale divides out)
        for v in res.values():
            assert v == pytest.approx(1.0)
        # an empty bucket yields no residuals, never a crash
        assert plan_lib.calibration_residuals(
            CFG, 8, global_batch=99, seq=16, cache_dir=d
        ) == {}


class TestPlanTrainStep:
    def test_plan_supplies_mesh_and_trunk(self):
        import jax.numpy as jnp

        from tony_tpu.models import make_train_step

        plan = plan_lib.plan_for(CFG, len(jax.devices()),
                                 require={"pp": 1, "tp": 2}, seq=16)
        assert plan.trunk == "gspmd"
        init_fn, step_fn = make_train_step(CFG, plan=plan)
        tokens = jnp.asarray(
            np.random.default_rng(0).integers(0, CFG.vocab_size, (8, 17)),
            jnp.int32,
        )
        with jax.sharding.set_mesh(plan.build_mesh()):
            state = init_fn(jax.random.key(0))
            state, metrics = step_fn(state, tokens)
            assert np.isfinite(float(metrics["loss"]))

    def test_mesh_or_plan_required(self):
        from tony_tpu.models import make_train_step

        with pytest.raises(ValueError):
            make_train_step(CFG)


# ---------------------------------------------------------------------------
# TONY-C010: compile cache on non-persistent scratch
# ---------------------------------------------------------------------------


class TestScratchCacheLint:
    def _findings(self, **overrides):
        from tony_tpu.analysis.config_check import check_config
        from tony_tpu.conf.configuration import TonyConfiguration

        conf = TonyConfiguration()
        for k, v in overrides.items():
            conf.set(k, v)
        return [f for f in check_config(conf) if f.rule_id == "TONY-C010"]

    def test_tmp_cache_dir_flagged(self):
        from tony_tpu.conf import keys

        found = self._findings(**{keys.K_COMPILE_CACHE_DIR: "/tmp/xla"})
        assert len(found) == 1
        assert "non-persistent scratch" in found[0].message

    def test_durable_dir_and_disabled_pass(self):
        from tony_tpu.conf import keys

        assert not self._findings(
            **{keys.K_COMPILE_CACHE_DIR: "/home/me/.cache/xla"}
        )
        assert not self._findings(**{
            keys.K_COMPILE_CACHE_DIR: "/tmp/xla",
            keys.K_COMPILE_CACHE_ENABLED: "false",
        })
        assert not self._findings()  # empty dir = durable default


# ---------------------------------------------------------------------------
# bench.py --check regression gate (fixture JSON, no benches run)
# ---------------------------------------------------------------------------


def _bench():
    sys.path.insert(0, str(REPO))
    try:
        import bench
    finally:
        sys.path.pop(0)
    return bench


class TestBenchGate:
    def test_collect_gates_metrics_not_parameters(self):
        bench = _bench()
        line = json.loads((FIXTURES / "line_ok.json").read_text())
        got = bench.collect_submetrics(line)
        assert got["mnist_train_steps_per_sec_per_chip"] == 2400.0
        assert got["transformer.mfu"] == 0.53
        assert got["flash_attention_2k.speedup"] == 2.1
        assert "transformer.batch" not in got       # parameter, ungated
        assert "transformer.seq" not in got
        # errored extras contribute nothing (→ "missing" downstream)
        assert not any(k.startswith("moe.") for k in got)

    def test_check_passes_on_baseline_itself(self):
        bench = _bench()
        base = bench.load_baselines(str(FIXTURES / "baseline.json"))
        metrics = base["TPU v5 lite"]
        assert bench.check_regressions(dict(metrics), metrics) == []

    def test_check_catches_drop_rise_and_missing(self):
        bench = _bench()
        base = {"a.tokens_per_sec_per_chip": 1000.0, "a.step_ms": 10.0,
                "b.mfu": 0.6}
        cur = {"a.tokens_per_sec_per_chip": 850.0, "a.step_ms": 11.5}
        problems = bench.check_regressions(cur, base)
        assert len(problems) == 3
        assert any("below baseline" in p for p in problems)
        assert any("above baseline" in p for p in problems)
        assert any("missing" in p for p in problems)
        # within tolerance: no findings
        ok = {"a.tokens_per_sec_per_chip": 950.0, "a.step_ms": 10.5,
              "b.mfu": 0.58}
        assert bench.check_regressions(ok, base) == []

    def test_pct_metrics_get_absolute_slack(self):
        bench = _bench()
        base = {"io.overhead_pct": 1.3}
        # 3x the baseline but only +2.6 points: noise, not a regression
        assert bench.check_regressions({"io.overhead_pct": 3.9}, base) == []
        assert bench.check_regressions({"io.overhead_pct": 9.0}, base)

    def test_zero_baseline_retrace_counter_gates_absolutely(self):
        """`retraces_total` is lower-is-better, and its zero baseline is
        absolute: ONE steady-state recompile fails --check (no threshold
        to scale against). Zero-baseline higher-direction metrics keep
        passing free — a drop from zero is unscalable noise."""
        bench = _bench()
        assert bench.metric_direction("transformer.retraces_total") == \
            "lower"
        base = {"transformer.retraces_total": 0.0}
        assert bench.check_regressions(
            {"transformer.retraces_total": 0.0}, base
        ) == []
        problems = bench.check_regressions(
            {"transformer.retraces_total": 2.0}, base
        )
        assert len(problems) == 1 and "zero baseline" in problems[0]
        assert bench.check_regressions({"x.mfu": 0.5}, {"x.mfu": 0.0}) == []

    def test_retrace_baselines_seeded_for_hot_paths(self):
        bench = _bench()
        table = bench.load_baselines().get("TPU v5 lite", {})
        for wl in ("transformer", "serving", "decode_gqa"):
            assert table.get(f"{wl}.retraces_total") == 0

    def test_main_check_exit_codes(self, tmp_path):
        bench = _bench()
        baseline = str(FIXTURES / "baseline.json")
        assert bench.main(["--check", "--baseline", baseline,
                           "--input", str(FIXTURES / "line_ok.json")]) == 0
        assert bench.main(["--check", "--baseline", baseline,
                           "--input",
                           str(FIXTURES / "line_regressed.json")]) == 1
        # Unknown platform: ungated, not a regression.
        other = tmp_path / "line_other.json"
        line = json.loads((FIXTURES / "line_ok.json").read_text())
        line["extras"]["device"] = "TPU v9"
        other.write_text(json.dumps(line))
        assert bench.main(["--check", "--baseline", baseline,
                           "--input", str(other)]) == 0

    def test_update_baseline_roundtrip(self, tmp_path):
        bench = _bench()
        target = tmp_path / "BASELINE.json"
        target.write_text(json.dumps({"north_star": "keep-me"}))
        line_path = str(FIXTURES / "line_ok.json")
        assert bench.main(["--update-baseline", "--baseline", str(target),
                           "--input", line_path]) == 0
        doc = json.loads(target.read_text())
        assert doc["north_star"] == "keep-me"  # other keys untouched
        assert "TPU v5 lite" in doc[bench.BASELINE_KEY]
        assert bench.main(["--check", "--baseline", str(target),
                           "--input", line_path]) == 0

    def test_shipped_baseline_has_tpu_entries(self):
        bench = _bench()
        shipped = bench.load_baselines()
        assert "TPU v5 lite" in shipped
        assert shipped["TPU v5 lite"]["mnist_train_steps_per_sec_per_chip"] \
            > 0


# ---------------------------------------------------------------------------
# Persistent-cache e2e: a second identical run skips compilation
# ---------------------------------------------------------------------------

_PROBE = r"""
import json, os, sys, time
import jax
import jax.numpy as jnp
import numpy as np

from tony_tpu.parallel.plan import configure_compile_cache
cache_dir = configure_compile_cache()
assert cache_dir == os.environ["TONY_COMPILE_CACHE_DIR"], cache_dir

from tony_tpu.models import MnistConfig
from tony_tpu.models.train import make_classifier_step
from tony_tpu.parallel.mesh import MeshSpec, build_mesh

mesh = build_mesh(MeshSpec(), devices=jax.devices()[:1])
cfg = MnistConfig(arch="cnn", dtype="float32")
init_fn, step_fn = make_classifier_step(cfg, mesh)
rng = np.random.default_rng(0)
images = jnp.asarray(rng.normal(size=(16, 28, 28, 1)), jnp.float32)
labels = jnp.asarray(rng.integers(0, 10, (16,)), jnp.int32)
t0 = time.perf_counter()
with jax.sharding.set_mesh(mesh):
    state = init_fn(jax.random.key(0))
    state, m = step_fn(state, images, labels)
    assert np.isfinite(float(m["loss"]))
wall = time.perf_counter() - t0

from tony_tpu import observability
snap = observability.default_registry().snapshot()
print("PROBE" + json.dumps({
    "counters": snap["counters"],
    "compile_ms": snap["histograms"]["tony_compile_ms"]["sum"],
    "wall_s": wall,
}))
"""


def _run_probe(cache_dir: Path) -> dict:
    env = {k: v for k, v in os.environ.items()
           if not k.startswith(("TONY_", "XLA_"))}
    env.update({
        "JAX_PLATFORMS": "cpu",
        "TONY_COMPILE_CACHE_DIR": str(cache_dir),
        "PYTHONPATH": str(REPO),
    })
    out = subprocess.run(
        [sys.executable, "-c", _PROBE], env=env, capture_output=True,
        text=True, timeout=240,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    line = next(l for l in out.stdout.splitlines() if l.startswith("PROBE"))
    return json.loads(line[len("PROBE"):])


@pytest.mark.slow
def test_resubmitted_job_hits_compile_cache_through_cluster(tmp_path):
    """The full wiring, end to end: ``tony.compile.cache-dir`` in the job
    conf → client-style frozen conf → executor TONY_COMPILE_* env →
    ``runtime.initialize()`` configuring jax in the user process. A
    second submit of the IDENTICAL job records cache hits and zero
    misses for the step function."""
    from tony_tpu.conf import keys
    from tony_tpu.mini import MiniTonyCluster

    cluster = MiniTonyCluster(tmp_path)
    probe_out = tmp_path / "probe.jsonl"
    cache_dir = tmp_path / "xla-cache"

    def submit():
        conf = cluster.base_conf()
        conf.set(keys.K_FRAMEWORK, "jax")
        conf.set(keys.K_EXECUTES,
                 str(Path(__file__).resolve().parent / "fixtures" /
                     "compile_cache_probe.py"))
        conf.set(keys.K_PYTHON_BINARY, sys.executable)
        conf.set(keys.instances_key("worker"), 1)
        conf.set(keys.instances_key("ps"), 0)
        conf.set(keys.K_COMPILE_CACHE_DIR, str(cache_dir))
        conf.set(keys.K_SHELL_ENV, f"PROBE_OUT={probe_out}")
        status, coord = cluster.run_job(conf)
        assert status.name == "SUCCEEDED", coord.session.diagnostics

    submit()
    submit()
    lines = [json.loads(l) for l in probe_out.read_text().splitlines()]
    assert len(lines) == 2
    cold, warm = lines
    assert cold["tony_compile_cache_misses_total"] == 2  # init + step
    assert cold.get("tony_compile_cache_hits_total", 0) == 0
    assert warm["tony_compile_cache_hits_total"] == 2
    assert warm.get("tony_compile_cache_misses_total", 0) == 0


def test_second_identical_run_hits_compile_cache(tmp_path):
    """The retry/resume/re-submit acceptance path, minus the cluster: two
    fresh processes compile the identical program against one
    ``tony.compile.cache-dir``. The first is all misses; the second
    records cache hits and ZERO misses for the step function, and its
    measured compile+first-step wall drops (the XLA persistent cache
    serves the executable)."""
    cache = tmp_path / "xla-cache"
    cold = _run_probe(cache)
    warm = _run_probe(cache)

    assert cold["counters"]["tony_compile_cache_misses_total"] == 2
    assert cold["counters"].get("tony_compile_cache_hits_total", 0) == 0
    assert warm["counters"]["tony_compile_cache_hits_total"] == 2
    assert warm["counters"].get("tony_compile_cache_misses_total", 0) == 0
    # Wall-time reduction: generous margin (CPU boxes share the machine
    # with the suite), but a served cache must beat a cold XLA compile.
    assert warm["wall_s"] < cold["wall_s"]
    assert warm["compile_ms"] < cold["compile_ms"]
